#!/usr/bin/env python
"""Flagship benchmark: ResNet-50 training throughput (images/sec) on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline anchor: the reference's best published ResNet-50 training number,
81.69 images/sec (train bs64, MKL-DNN, 2x Xeon 6148 — see BASELINE.md §4;
the reference publishes no GPU ResNet-50 number). vs_baseline = value/81.69.

BENCH_MODE=lstm benchmarks the reference's RNN config instead (IMDB text
classification, embedding128 -> 2x[fc + peephole LSTM h512] -> fc2, seqlen
100 padded, bs64 — reference benchmark/README.md:100-120,
benchmark/paddle/rnn/rnn.py): JSON line reports ms/batch against the
published 184 ms/batch on K40m.
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 81.69
# Batch sweep on the tunneled v5e (pure-JAX ceiling probe, tools/
# jax_resnet_ref.py, r3): bs256 2573 img/s / bs384 2544 / bs512 2508 /
# bs640 2389 / bs768 2322 / bs1024 135 (host-spill collapse). Smaller
# batches win: per-step HBM pressure drops and the step stays wholly
# resident. bs256 is the throughput-optimal point.
BATCH = int(os.environ.get("BENCH_BATCH", "256"))
STEPS = int(os.environ.get("BENCH_STEPS", "20"))
# the tunneled TPU terminal runs the first ~20 executions of a fresh
# executable slow (program caching); warm past that to measure steady state
WARMUP = int(os.environ.get("BENCH_WARMUP", "25"))
AMP = os.environ.get("BENCH_AMP", "1") == "1"
AMP_LEVEL = os.environ.get("BENCH_AMP_LEVEL", "O2")
# ResNet-50 @224: ~4.09 GFLOP forward per image (counting FMA as 2 FLOPs);
# a training step costs ~3x forward (fwd + input grad + weight grad).
TRAIN_FLOPS_PER_IMG = 3 * 4.09e9
# per-chip bf16 peak for MFU reporting (v5e ~197 TF/s, v4 ~275, v5p ~459);
# override with BENCH_PEAK_TFLOPS for other chips. NOTE (r3 measured): the
# tunneled chip in this environment sustains ~32 TF/s bf16 on pure in-graph
# matmul chains (tools/jax_resnet_ref.py probes; high run-to-run variance,
# 2x bf16-over-f32 confirms full MXU datapath engagement) — the framework's
# step and a hand-rolled pure-JAX step both saturate that sustained rate,
# so MFU against the nominal 197 TF/s peak tops out near 0.16 here
# regardless of program quality.
PEAK_TFLOPS = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))


def main_lstm():
    """2xLSTM+fc h512 bs64 seqlen100 (reference benchmark/paddle/rnn/rnn.py:
    embedding 128, simple_lstm = fc(4h)+lstmemory with peepholes, Adam)."""
    import paddle_tpu as fluid

    import jax

    vocab, emb_dim, hid = 30000, 128, int(os.environ.get("BENCH_HIDDEN",
                                                         "512"))
    bsz = int(os.environ.get("BENCH_LSTM_BATCH", "64"))
    seqlen = 100
    steps = int(os.environ.get("BENCH_STEPS", "30"))
    warmup = int(os.environ.get("BENCH_WARMUP", "25"))
    baseline_ms = 184.0   # K40m, BASELINE.md §3

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(input=data, size=[vocab, emb_dim])
        h = emb
        for _ in range(2):
            proj = fluid.layers.fc(input=h, size=hid * 4,
                                    num_flatten_dims=2)
            h, _c = fluid.layers.dynamic_lstm(input=proj, size=hid * 4,
                                              use_peepholes=True)
        last = fluid.layers.sequence_last_step(h)
        logits = fluid.layers.fc(input=last, size=2, act="softmax")
        cost = fluid.layers.cross_entropy(input=logits, label=label)
        avg_cost = fluid.layers.mean(cost)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(
            avg_cost, startup_program=startup)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)

    rng = np.random.default_rng(0)
    # fixed-length (pad_seq=True in the reference run): dense [B, T] ids
    ids = rng.integers(0, vocab, (bsz, seqlen)).astype(np.int32)
    labs = rng.integers(0, 2, (bsz, 1)).astype(np.int32)
    feed = {"words": jax.device_put(ids, exe.device),
            "label": jax.device_put(labs, exe.device)}

    for _ in range(max(warmup, 1)):
        loss, = exe.run(main_prog, feed=feed, fetch_list=[avg_cost],
                        return_numpy=False)
    float(np.asarray(loss).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, = exe.run(main_prog, feed=feed, fetch_list=[avg_cost],
                        return_numpy=False)
    final_loss = float(np.asarray(loss).ravel()[0])
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    ms_batch = dt / steps * 1000
    # fwd FLOPs/batch: input projections (emb->4H, H->4H) + recurrent gemm
    # (H->4H per step) for both layers; train step ~ 3x forward
    gemm = (emb_dim * 4 * hid + hid * 4 * hid    # layer1 proj + recur
            + hid * 4 * hid + hid * 4 * hid)     # layer2 proj + recur
    fwd_flops = 2 * bsz * seqlen * gemm
    mfu = 3 * fwd_flops / (dt / steps) / (PEAK_TFLOPS * 1e12)
    print(json.dumps({
        "metric": "lstm2_h512_train_ms_per_batch",
        "value": round(ms_batch, 2),
        "unit": "ms/batch",
        "vs_baseline": round(baseline_ms / ms_batch, 3),
        "batch": bsz, "seqlen": seqlen, "hidden": hid,
        "mfu": round(mfu, 4),
    }))


def main():
    import paddle_tpu as fluid
    from paddle_tpu import models

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        img = fluid.layers.data(name="img", shape=[3, 224, 224],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        avg_cost, _, _ = models.build_image_classifier(
            models.resnet50, img, label, class_dim=1000)
        opt = fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        if AMP:
            # bf16 matmul/conv compute on the MXU, fp32 master weights;
            # O2 keeps activations bf16 end-to-end (halves HBM traffic)
            opt = fluid.amp.decorate(opt, level=AMP_LEVEL)
        opt.minimize(avg_cost, startup_program=startup)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)

    rng = np.random.default_rng(0)
    import jax
    if os.environ.get("BENCH_STAGED", "0") == "1":
        # stage one batch in HBM (compute-only throughput, the old mode)
        x = rng.standard_normal((BATCH, 3, 224, 224), dtype=np.float32)
        y = rng.integers(0, 1000, (BATCH, 1)).astype(np.int64)
        feed = {"img": jax.device_put(x, exe.device),
                "label": jax.device_put(y, exe.device)}
        feeds = iter(lambda: feed, None)
    else:
        # input pipeline: batches flow through the DoubleBufferedFeeder
        # (reader/pipeline.py; reference create_double_buffer_reader_op.cc).
        # By default the rotating batches are pre-staged in HBM once: on this
        # tunneled single-chip environment host->HBM bandwidth collapses to
        # ~70 MB/s while the chip computes (measured; 1.4 GB/s idle), so
        # per-step host uploads would benchmark the tunnel, not the chip.
        # BENCH_HOST_PIPELINE=1 switches to true per-step host uploads for
        # real TPU hosts; the overlap path itself is correctness-tested in
        # tests/test_input_pipeline.py.
        from paddle_tpu.reader.pipeline import DoubleBufferedFeeder
        host_uploads = os.environ.get("BENCH_HOST_PIPELINE", "0") == "1"
        n_bufs = 3 if host_uploads else 2
        host = [(rng.standard_normal((BATCH, 3, 224, 224), dtype=np.float32),
                 rng.integers(0, 1000, (BATCH, 1)).astype(np.int32))
                for _ in range(n_bufs)]
        if not host_uploads:
            host = [(jax.device_put(x, exe.device),
                     jax.device_put(y, exe.device)) for x, y in host]

        def reader():
            i = 0
            while True:
                x, y = host[i % len(host)]
                yield {"img": x, "label": y}
                i += 1

        feeds = iter(DoubleBufferedFeeder(
            reader, device=exe.device if host_uploads else None, capacity=1))

    for _ in range(max(WARMUP, 1)):
        loss, = exe.run(main_prog, feed=next(feeds), fetch_list=[avg_cost],
                        return_numpy=False)
    float(np.asarray(loss).ravel()[0])  # sync

    # return_numpy=False keeps the fetched loss on-device: steps enqueue
    # back to back with no per-step host sync; one sync at the end.
    t0 = time.perf_counter()
    for _ in range(STEPS):
        loss, = exe.run(main_prog, feed=next(feeds), fetch_list=[avg_cost],
                        return_numpy=False)
    final_loss = float(np.asarray(loss).ravel()[0])  # sync on the last step
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    img_s = BATCH * STEPS / dt
    mfu = img_s * TRAIN_FLOPS_PER_IMG / (PEAK_TFLOPS * 1e12)
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "batch": BATCH,
        "amp": AMP,
        "amp_level": AMP_LEVEL if AMP else None,
        "mfu": round(mfu, 4),
    }))


if __name__ == "__main__":
    if os.environ.get("BENCH_MODE", "resnet") == "lstm":
        sys.exit(main_lstm())
    sys.exit(main())
