#!/usr/bin/env python
"""Flagship benchmark: ResNet-50 training throughput (images/sec) on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline anchor: the reference's best published ResNet-50 training number,
81.69 images/sec (train bs64, MKL-DNN, 2x Xeon 6148 — see BASELINE.md §4;
the reference publishes no GPU ResNet-50 number). vs_baseline = value/81.69.
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 81.69
BATCH = int(os.environ.get("BENCH_BATCH", "768"))
STEPS = int(os.environ.get("BENCH_STEPS", "20"))
# the tunneled TPU terminal runs the first ~20 executions of a fresh
# executable slow (program caching); warm past that to measure steady state
WARMUP = int(os.environ.get("BENCH_WARMUP", "25"))
AMP = os.environ.get("BENCH_AMP", "1") == "1"
AMP_LEVEL = os.environ.get("BENCH_AMP_LEVEL", "O2")
# ResNet-50 @224: ~4.09 GFLOP forward per image (counting FMA as 2 FLOPs);
# a training step costs ~3x forward (fwd + input grad + weight grad).
TRAIN_FLOPS_PER_IMG = 3 * 4.09e9
# per-chip bf16 peak for MFU reporting (v5e ~197 TF/s, v4 ~275, v5p ~459);
# override with BENCH_PEAK_TFLOPS for other chips.
PEAK_TFLOPS = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))


def main():
    import paddle_tpu as fluid
    from paddle_tpu import models

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        img = fluid.layers.data(name="img", shape=[3, 224, 224],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        avg_cost, _, _ = models.build_image_classifier(
            models.resnet50, img, label, class_dim=1000)
        opt = fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        if AMP:
            # bf16 matmul/conv compute on the MXU, fp32 master weights;
            # O2 keeps activations bf16 end-to-end (halves HBM traffic)
            opt = fluid.amp.decorate(opt, level=AMP_LEVEL)
        opt.minimize(avg_cost, startup_program=startup)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)

    rng = np.random.default_rng(0)
    import jax
    if os.environ.get("BENCH_STAGED", "0") == "1":
        # stage one batch in HBM (compute-only throughput, the old mode)
        x = rng.standard_normal((BATCH, 3, 224, 224), dtype=np.float32)
        y = rng.integers(0, 1000, (BATCH, 1)).astype(np.int64)
        feed = {"img": jax.device_put(x, exe.device),
                "label": jax.device_put(y, exe.device)}
        feeds = iter(lambda: feed, None)
    else:
        # input pipeline: batches flow through the DoubleBufferedFeeder
        # (reader/pipeline.py; reference create_double_buffer_reader_op.cc).
        # By default the rotating batches are pre-staged in HBM once: on this
        # tunneled single-chip environment host->HBM bandwidth collapses to
        # ~70 MB/s while the chip computes (measured; 1.4 GB/s idle), so
        # per-step host uploads would benchmark the tunnel, not the chip.
        # BENCH_HOST_PIPELINE=1 switches to true per-step host uploads for
        # real TPU hosts; the overlap path itself is correctness-tested in
        # tests/test_input_pipeline.py.
        from paddle_tpu.reader.pipeline import DoubleBufferedFeeder
        host_uploads = os.environ.get("BENCH_HOST_PIPELINE", "0") == "1"
        n_bufs = 3 if host_uploads else 2
        host = [(rng.standard_normal((BATCH, 3, 224, 224), dtype=np.float32),
                 rng.integers(0, 1000, (BATCH, 1)).astype(np.int32))
                for _ in range(n_bufs)]
        if not host_uploads:
            host = [(jax.device_put(x, exe.device),
                     jax.device_put(y, exe.device)) for x, y in host]

        def reader():
            i = 0
            while True:
                x, y = host[i % len(host)]
                yield {"img": x, "label": y}
                i += 1

        feeds = iter(DoubleBufferedFeeder(
            reader, device=exe.device if host_uploads else None, capacity=1))

    for _ in range(max(WARMUP, 1)):
        loss, = exe.run(main_prog, feed=next(feeds), fetch_list=[avg_cost],
                        return_numpy=False)
    float(np.asarray(loss).ravel()[0])  # sync

    # return_numpy=False keeps the fetched loss on-device: steps enqueue
    # back to back with no per-step host sync; one sync at the end.
    t0 = time.perf_counter()
    for _ in range(STEPS):
        loss, = exe.run(main_prog, feed=next(feeds), fetch_list=[avg_cost],
                        return_numpy=False)
    final_loss = float(np.asarray(loss).ravel()[0])  # sync on the last step
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    img_s = BATCH * STEPS / dt
    mfu = img_s * TRAIN_FLOPS_PER_IMG / (PEAK_TFLOPS * 1e12)
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "batch": BATCH,
        "amp": AMP,
        "amp_level": AMP_LEVEL if AMP else None,
        "mfu": round(mfu, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
