#!/usr/bin/env python
"""Benchmarks vs the reference's published table (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

BENCH_MODE selects the config family:
  resnet (default)   ResNet-50 train bs256 AMP-O2, vs 81.69 img/s
                     (reference's best published ResNet-50 train,
                     MKL-DNN 2x Xeon 6148, BASELINE.md §4)
  alexnet            AlexNet train, vs 626.53 img/s (§4 bs256)
  googlenet          GoogleNet train, vs 250.46 img/s (§4 bs64)
  vgg19              VGG-19 train, vs 28.46 img/s (§4 bs64)
  resnet_infer       ResNet-50 inference bs16, vs 217.69 img/s (§4)
  alexnet_infer      AlexNet inference bs16, vs 850.51 img/s (§4)
  googlenet_infer    GoogleNet inference bs16, vs 600.94 img/s (§4)
  vgg19_infer        VGG-19 inference bs16, vs 96.75 img/s (§4)
  lstm               2xLSTM+fc h512 bs64 seqlen100 IMDB config, ms/batch
                     vs 184 ms/batch (K40m, §3; benchmark/paddle/rnn/rnn.py)
  attention          flash-attention (Pallas, fwd+bwd) vs XLA einsum
                     attention at T=4096 causal — the long-context kernel
                     the 2018 reference has no counterpart for;
                     vs_baseline is the speedup over the XLA path
  smallnet           SmallNet (CIFAR-quick) train, vs 8122 img/s (§1 bs512)
  transformer        transformer-LM train step with use_flash attention
                     (models/transformer.py), tokens/sec + MFU
"""

import json
import os
import sys
import time

import numpy as np

BATCH = os.environ.get("BENCH_BATCH")
STEPS = int(os.environ.get("BENCH_STEPS", "20"))
# the tunneled TPU terminal runs the first ~20 executions of a fresh
# executable slow (program caching); warm past that to measure steady state
WARMUP = int(os.environ.get("BENCH_WARMUP", "25"))
AMP = os.environ.get("BENCH_AMP", "1") == "1"
AMP_LEVEL = os.environ.get("BENCH_AMP_LEVEL", "O2")
# per-chip bf16 peak for MFU reporting (v5e ~197 TF/s, v4 ~275, v5p ~459);
# override with BENCH_PEAK_TFLOPS for other chips. NOTE (r3 measured): the
# tunneled chip in this environment sustains ~32 TF/s bf16 on pure in-graph
# matmul chains (tools/jax_resnet_ref.py probes; high run-to-run variance,
# 2x bf16-over-f32 confirms full MXU datapath engagement) — the framework's
# step and a hand-rolled pure-JAX step both saturate that sustained rate,
# so MFU against the nominal 197 TF/s peak tops out near 0.16 here
# regardless of program quality.
PEAK_TFLOPS = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))

# Per-family config. flops = forward GFLOPs/image at 224x224 (mul+add as 2);
# training step ~ 3x forward (fwd + input grad + weight grad). Baselines are
# the reference's best published number for the family (BASELINE.md §4;
# img/s, higher is better). train_bs: batch sweep on the tunneled v5e found
# bs256 throughput-optimal for ResNet-50 (r3, tools/jax_resnet_ref.py);
# VGG-19's larger activations favor a smaller batch.
CNN = {
    "resnet": dict(builder="resnet50", fwd_flops=4.09e9, train_bs=256,
                   train_base=81.69, infer_base=217.69, lr=0.1),
    # nets without batch norm diverge to NaN at lr=0.1 within the warmup
    # steps (the assert on the final loss is the guard); throughput is
    # lr-independent, so run them at a stable rate
    "alexnet": dict(builder="alexnet", fwd_flops=1.43e9, train_bs=256,
                    train_base=626.53, infer_base=850.51, lr=0.01),
    "googlenet": dict(builder="googlenet", fwd_flops=3.0e9, train_bs=256,
                      train_base=250.46, infer_base=600.94, lr=0.005),
    "vgg19": dict(builder="vgg19", fwd_flops=39.0e9, train_bs=128,
                  train_base=28.46, infer_base=96.75, lr=0.005),
    # SmallNet = CIFAR-quick (BASELINE.md §1: 63.039 ms/batch at bs512 on
    # K40m = 8122 img/s best published; no §4 inference row — reuse the
    # train anchor)
    "smallnet": dict(builder="smallnet_mnist_cifar", fwd_flops=2.05e7,
                     train_bs=512, train_base=8122.0, infer_base=8122.0,
                     lr=0.01, img=32, classes=10),
}
INFER_BS = 16  # the reference's §4 inference batch


def _feeds(exe, batch, shapes_dtypes, rng):
    """Rotating pre-staged HBM batches through the DoubleBufferedFeeder
    (reader/pipeline.py; reference create_double_buffer_reader_op.cc).
    Pre-staged by default: on this tunneled single-chip environment
    host->HBM bandwidth collapses to ~70 MB/s while the chip computes
    (measured r2; 1.4 GB/s idle), so per-step host uploads would benchmark
    the tunnel, not the chip. BENCH_HOST_PIPELINE=1 switches to true
    per-step host uploads for real TPU hosts; the overlap path itself is
    correctness-tested in tests/test_input_pipeline.py."""
    import jax
    from paddle_tpu.reader.pipeline import DoubleBufferedFeeder

    host_uploads = os.environ.get("BENCH_HOST_PIPELINE", "0") == "1"
    n_bufs = 3 if host_uploads else 2

    def make_batch():
        out = {}
        for name, shape, dtype in shapes_dtypes:
            if dtype == "img":
                out[name] = rng.standard_normal((batch,) + shape,
                                                dtype=np.float32)
            else:
                out[name] = rng.integers(0, dtype, (batch,) + shape,
                                         ).astype(np.int32)
        return out

    host = [make_batch() for _ in range(n_bufs)]
    if not host_uploads:
        host = [{k: jax.device_put(v, exe.device) for k, v in b.items()}
                for b in host]

    def reader():
        i = 0
        while True:
            yield host[i % len(host)]
            i += 1

    return iter(DoubleBufferedFeeder(
        reader, device=exe.device if host_uploads else None, capacity=1))


def _timed_loop(run_step, warmup, steps):
    """Warm, then time `steps` back-to-back enqueues with one final sync.
    run_step() must return an on-device scalar (return_numpy=False)."""
    for _ in range(max(warmup, 1)):
        out = run_step()
    float(np.asarray(out).ravel()[0])  # sync
    t0 = time.perf_counter()
    for _ in range(steps):
        out = run_step()
    final = float(np.asarray(out).ravel()[0])  # sync on the last step
    dt = time.perf_counter() - t0
    assert np.isfinite(final)
    return dt


def main_cnn(family, train=True):
    import paddle_tpu as fluid
    from paddle_tpu import models

    cfg = CNN[family]
    builder = getattr(models, cfg["builder"])
    batch = int(BATCH) if BATCH else (cfg["train_bs"] if train else INFER_BS)
    side = cfg.get("img", 224)
    classes = cfg.get("classes", 1000)

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        img = fluid.layers.data(name="img", shape=[3, side, side],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        if train:
            avg_cost, _, _ = models.build_image_classifier(
                builder, img, label, class_dim=classes)
            opt = fluid.optimizer.Momentum(learning_rate=cfg["lr"],
                                           momentum=0.9)
            if AMP:
                # bf16 matmul/conv compute on the MXU, fp32 master weights;
                # O2 keeps activations bf16 end-to-end (halves HBM traffic)
                opt = fluid.amp.decorate(opt, level=AMP_LEVEL)
            opt.minimize(avg_cost, startup_program=startup)
            fetch = avg_cost
        else:
            logits = builder(img, class_dim=classes, is_test=True)
            predict = fluid.layers.softmax(logits)
            # a scalar fetch keeps the timed loop sync-free; argmax-sum is
            # data-dependent so XLA cannot dead-code the network
            fetch = fluid.layers.reduce_sum(
                fluid.layers.reduce_max(predict, dim=-1))

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)

    rng = np.random.default_rng(0)
    shapes = [("img", (3, side, side), "img")]
    if train:
        shapes.append(("label", (1,), classes))  # infer programs take no label
    feeds = _feeds(exe, batch, shapes, rng)

    def step():
        out, = exe.run(main_prog, feed=next(feeds), fetch_list=[fetch],
                       return_numpy=False)
        return out

    dt = _timed_loop(step, WARMUP, STEPS)
    img_s = batch * STEPS / dt
    flops_per_img = (3 if train else 1) * cfg["fwd_flops"]
    mfu = img_s * flops_per_img / (PEAK_TFLOPS * 1e12)
    base = cfg["train_base"] if train else cfg["infer_base"]
    job = "train" if train else "infer"
    print(json.dumps({
        "metric": f"{cfg['builder']}_{job}_images_per_sec",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / base, 3),
        "batch": batch,
        "amp": AMP if train else False,
        "amp_level": (AMP_LEVEL if AMP else None) if train else None,
        "mfu": round(mfu, 4),
    }))


def main_lstm():
    """2xLSTM+fc h512 bs64 seqlen100 (reference benchmark/paddle/rnn/rnn.py:
    embedding 128, simple_lstm = fc(4h)+lstmemory with peepholes, Adam)."""
    import paddle_tpu as fluid

    import jax

    vocab, emb_dim, hid = 30000, 128, int(os.environ.get("BENCH_HIDDEN",
                                                         "512"))
    bsz = int(os.environ.get("BENCH_LSTM_BATCH", "64"))
    seqlen = 100
    steps, warmup = STEPS, WARMUP
    baseline_ms = 184.0   # K40m, BASELINE.md §3

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(input=data, size=[vocab, emb_dim])
        h = emb
        for _ in range(2):
            proj = fluid.layers.fc(input=h, size=hid * 4,
                                    num_flatten_dims=2)
            h, _c = fluid.layers.dynamic_lstm(input=proj, size=hid * 4,
                                              use_peepholes=True)
        last = fluid.layers.sequence_last_step(h)
        logits = fluid.layers.fc(input=last, size=2, act="softmax")
        cost = fluid.layers.cross_entropy(input=logits, label=label)
        avg_cost = fluid.layers.mean(cost)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(
            avg_cost, startup_program=startup)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)

    rng = np.random.default_rng(0)
    # fixed-length (pad_seq=True in the reference run): dense [B, T] ids
    ids = rng.integers(0, vocab, (bsz, seqlen)).astype(np.int32)
    labs = rng.integers(0, 2, (bsz, 1)).astype(np.int32)
    feed = {"words": jax.device_put(ids, exe.device),
            "label": jax.device_put(labs, exe.device)}

    def step():
        loss, = exe.run(main_prog, feed=feed, fetch_list=[avg_cost],
                        return_numpy=False)
        return loss

    dt = _timed_loop(step, warmup, steps)
    ms_batch = dt / steps * 1000
    # fwd FLOPs/batch: input projections (emb->4H, H->4H) + recurrent gemm
    # (H->4H per step) for both layers; train step ~ 3x forward
    gemm = (emb_dim * 4 * hid + hid * 4 * hid    # layer1 proj + recur
            + hid * 4 * hid + hid * 4 * hid)     # layer2 proj + recur
    fwd_flops = 2 * bsz * seqlen * gemm
    mfu = 3 * fwd_flops / (dt / steps) / (PEAK_TFLOPS * 1e12)
    print(json.dumps({
        "metric": "lstm2_h512_train_ms_per_batch",
        "value": round(ms_batch, 2),
        "unit": "ms/batch",
        "vs_baseline": round(baseline_ms / ms_batch, 3),
        "batch": bsz, "seqlen": seqlen, "hidden": hid,
        "mfu": round(mfu, 4),
    }))


def main_attention():
    """Pallas flash attention (fwd+bwd, O(T) memory) vs the XLA einsum
    reference at T=4096 causal — the kernel behind fused_attention
    (use_flash=True) and the in-shard blocks of ring attention. The 2018
    reference has no attention op at all (SURVEY.md §2.5 last row), so
    vs_baseline is the measured speedup over the XLA attention path on the
    same chip: >1 means the Pallas kernels beat the compiler."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_attention import flash_attention
    from paddle_tpu.parallel.ring_attention import attention_reference

    b = int(os.environ.get("BENCH_ATTN_BATCH", "1"))
    t = int(os.environ.get("BENCH_ATTN_SEQLEN", "4096"))
    h, d = 8, 64
    steps, warmup = STEPS, WARMUP
    rng = np.random.default_rng(1)
    q, k, v = [jax.device_put(rng.standard_normal((b, t, h, d))
                              .astype(np.float32)) for _ in range(3)]

    def make(fn):
        return jax.jit(jax.grad(
            lambda a, bb, c: jnp.sum(fn(a, bb, c) ** 2), argnums=(0, 1, 2)))

    def time_once(g, n):
        # fetch a scalar from the result for the sync: on the tunneled
        # terminal block_until_ready returns before execution completes
        # (measured r3), so only a value readback is a trustworthy fence
        r = g(q, k, v)
        float(np.asarray(r[0]).ravel()[0])
        t0 = time.perf_counter()
        for _ in range(n):
            r = g(q, k, v)
        float(np.asarray(r[0]).ravel()[0])
        return (time.perf_counter() - t0) / n

    g_flash = make(lambda a, bb, c: flash_attention(a, bb, c, True))
    g_xla = make(lambda a, bb, c: attention_reference(a, bb, c, causal=True))
    # BENCH_ATTN_XLA=0 skips the einsum side entirely — at long T its
    # [T, T] residuals exhaust HBM, which is exactly flash's point
    run_xla = os.environ.get("BENCH_ATTN_XLA", "1") == "1"
    for g in ((g_flash, g_xla) if run_xla else (g_flash,)):
        for _ in range(warmup):          # warm past the program cache
            r = g(q, k, v)
        float(np.asarray(r[0]).ravel()[0])
    # the tunneled chip drifts run-to-run (r3: high variance); alternate
    # measurement rounds and take each side's best so drift hits both
    flash_ts, xla_ts = [], []
    for _ in range(3):
        flash_ts.append(time_once(g_flash, steps))
        if run_xla:
            xla_ts.append(time_once(g_xla, steps))
    flash_s = min(flash_ts)
    xla_s = min(xla_ts) if run_xla else None
    print(json.dumps({
        "metric": f"flash_attention_fwd_bwd_ms_T{t}_causal",
        "value": round(flash_s * 1e3, 3),
        "unit": "ms/step",
        "vs_baseline": round(xla_s / flash_s, 3) if run_xla else None,
        "xla_reference_ms": round(xla_s * 1e3, 3) if run_xla else None,
        "shape": [b, t, h, d],
    }))


def main_transformer():
    """Transformer-LM training step (models/transformer.py) with flash
    attention: tokens/sec + MFU. No reference counterpart (2018);
    vs_baseline is the ratio against the same model on the XLA einsum
    attention path (use_flash=False). Measured honestly: the standalone
    flash kernels beat the einsum (1.5-1.6x fwd+bwd at these shapes); in
    the whole-program jit the einsum path is still modestly faster at
    benchmark sizes (~1.2x — the custom call limits cross-op fusion) —
    flash's end-to-end value is MEMORY (O(T) residuals; T=16k+ trains
    where the einsum path's [T,T] residuals cannot)."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import models

    bsz = int(BATCH) if BATCH else 8
    seqlen = int(os.environ.get("BENCH_SEQLEN", "2048"))
    n_layer = int(os.environ.get("BENCH_LAYERS", "4"))
    d_model = int(os.environ.get("BENCH_DMODEL", "512"))
    n_head = d_model // 64
    vocab = 8192
    steps, warmup = STEPS, WARMUP

    def build_and_time(use_flash):
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            tok = fluid.layers.data(name="tok", shape=[-1, seqlen],
                                    dtype="int64", append_batch_size=False)
            lab = fluid.layers.data(name="lab", shape=[-1, seqlen],
                                    dtype="int64", append_batch_size=False)
            loss = models.transformer_lm(
                tok, lab, vocab_size=vocab, d_model=d_model,
                n_head=n_head, n_layer=n_layer, use_flash=use_flash)
            opt = fluid.optimizer.Adam(learning_rate=1e-4)
            if AMP:
                opt = fluid.amp.decorate(opt, level=AMP_LEVEL)
            opt.minimize(loss, startup_program=startup)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, vocab, (bsz, seqlen)).astype(np.int32)
        labs = rng.integers(0, vocab, (bsz, seqlen)).astype(np.int32)
        feed = {"tok": jax.device_put(ids, exe.device),
                "lab": jax.device_put(labs, exe.device)}

        def step():
            out, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                           return_numpy=False)
            return out

        return _timed_loop(step, warmup, steps)

    dt = build_and_time(True)
    dt_xla = build_and_time(False)
    tok_s = bsz * seqlen * steps / dt
    # fwd FLOPs/token: 2*(attn qkvo 4*d^2 + mlp 8*d^2) + attention scores
    # 2*2*T*d per token; train ~ 3x fwd
    flops_tok = n_layer * (2 * 12 * d_model ** 2
                           + 4 * seqlen * d_model) + 2 * vocab * d_model
    mfu = 3 * tok_s * flops_tok / (PEAK_TFLOPS * 1e12)
    print(json.dumps({
        "metric": "transformer_lm_train_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(dt_xla / dt, 3),
        "xla_attention_tokens_per_sec": round(bsz * seqlen * steps / dt_xla,
                                              1),
        "batch": bsz, "seqlen": seqlen, "layers": n_layer,
        "d_model": d_model, "amp": AMP, "mfu": round(mfu, 4),
    }))


def main():
    mode = os.environ.get("BENCH_MODE", "resnet")
    if mode == "lstm":
        return main_lstm()
    if mode == "attention":
        return main_attention()
    if mode == "transformer":
        return main_transformer()
    family, _, job = mode.partition("_")
    if family not in CNN or job not in ("", "infer"):
        raise SystemExit(f"unknown BENCH_MODE={mode}")
    return main_cnn(family, train=(job != "infer"))


if __name__ == "__main__":
    sys.exit(main())
