#!/usr/bin/env python
"""Benchmarks vs the reference's published table (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

BENCH_MODE selects the config family:
  resnet (default)   ResNet-50 train bs256 AMP-O2, vs 81.69 img/s
                     (reference's best published ResNet-50 train,
                     MKL-DNN 2x Xeon 6148, BASELINE.md §4)
  alexnet            AlexNet train, vs 626.53 img/s (§4 bs256)
  googlenet          GoogleNet train, vs 250.46 img/s (§4 bs64)
  vgg19              VGG-19 train, vs 28.46 img/s (§4 bs64)
  resnet_infer       ResNet-50 inference bs16, vs 217.69 img/s (§4)
  alexnet_infer      AlexNet inference bs16, vs 850.51 img/s (§4)
  googlenet_infer    GoogleNet inference bs16, vs 600.94 img/s (§4)
  vgg19_infer        VGG-19 inference bs16, vs 96.75 img/s (§4)
  lstm               2xLSTM+fc h512 bs64 seqlen100 IMDB config, ms/batch
                     vs 184 ms/batch (K40m, §3; benchmark/paddle/rnn/rnn.py)
  attention          flash-attention (Pallas, fwd+bwd) vs XLA einsum
                     attention at T=4096 causal — the long-context kernel
                     the 2018 reference has no counterpart for;
                     vs_baseline is the speedup over the XLA path
  smallnet           SmallNet (CIFAR-quick) train, vs 8122 img/s (§1 bs512)
  transformer        transformer-LM train step with use_flash attention
                     (models/transformer.py), tokens/sec + MFU
  ring_attention     transformer-LM T=32k train step, flash ring over an
                     'sp' mesh of all visible devices; vs the r4 1.58 s/step
                     regression anchor
  embedding          criteo-DLRM-style sparse embedding train step: a
                     [BENCH_EMB_ROWS x BENCH_EMB_DIM] table fsdp-sharded
                     over all visible devices, SelectedRows gradients and
                     Adam scatter-apply end-to-end; rows_touched_per_sec
                     plus per-shard HBM table bytes (ISSUE 10)

`--steps-per-call K` (or BENCH_STEPS_PER_CALL) drives the CNN families
through Executor.run_steps — K device steps per Python dispatch via one
lax.scan window — and every JSON line carries `steps_per_call` plus a
`python_overhead_per_step_ms` probe so the dispatch-overhead win is
measurable against the K=1 baseline. TPU-hosts only for conv families:
XLA:CPU compiles GRADIENT convolutions inside loop bodies with the naive
expander instead of the Eigen path (~60x, measured: a conv train step in
a scan runs 28s vs 0.47s for 8 top-level steps), so on a CPU host the
knob only shows its win on conv-free configs.

Resilience (VERDICT r4 #1): every mode retries transient tunnel/compile
failures (bounded, BENCH_RETRIES), keeps completed timing chunks, and the
top level ALWAYS prints the JSON line — on persistent failure with
value=null plus an `errors` log, so the driver's parse never comes back
empty. Every mode also reports the session's sustained-TF/s roofline and
MFU against both nominal peak and that roofline (BENCH_ROOFLINE=0 skips).
"""

import json
import os
import sys
import time

import numpy as np

BATCH = os.environ.get("BENCH_BATCH")
# bounded retry budget for transient tunnel/compile failures (r4 lost its
# official number to a single `remote_compile: response body closed`)
RETRIES = int(os.environ.get("BENCH_RETRIES", "4"))
STEPS = int(os.environ.get("BENCH_STEPS", "20"))
# the tunneled TPU terminal runs the first ~20 executions of a fresh
# executable slow (program caching); warm past that to measure steady state
WARMUP = int(os.environ.get("BENCH_WARMUP", "25"))
AMP = os.environ.get("BENCH_AMP", "1") == "1"
# fused multi-step loop (Executor.run_steps): K device steps per Python
# dispatch. `--steps-per-call K` on the command line or the env var; 1 =
# the classic per-step path; `auto` measures dispatch overhead + HBM
# headroom on the compiled step and lets overlap.choose_steps_per_call
# pick K (ISSUE 9). Every JSON line reports the resolved value so
# BENCH_r* capture the dispatch-overhead trend.


def _parse_steps_per_call(v):
    v = str(v).strip().lower()
    if v == "auto":
        return "auto"
    return int(v)


STEPS_PER_CALL = _parse_steps_per_call(
    os.environ.get("BENCH_STEPS_PER_CALL", "1"))
# O2 = bf16 end-to-end; O3 = O2 + int8/fp8 quantized matmul/conv compute
# (quant.py). An O3 line carries quant_hits/quant_fallbacks; the serving
# family quantizes with BENCH_QUANT=int8|fp8 (ServingEngine(quantize=)).
AMP_LEVEL = os.environ.get("BENCH_AMP_LEVEL", "O2")
# per-chip bf16 peak for MFU reporting (v5e ~197 TF/s, v4 ~275, v5p ~459);
# override with BENCH_PEAK_TFLOPS for other chips. The in-session
# _roofline_cached probe measures what the chip+tunnel actually sustains
# (r5: ~104-108 TF/s bf16 — the r3 "~32 TF/s ceiling" was a probe
# artifact) and every mode reports mfu_vs_sustained against it; ResNet's
# ~30-32 TF/s step equals a hand-rolled pure-JAX step in the same session
# (tools/jax_resnet_ref.py), locating the rest in XLA's conv codegen.
PEAK_TFLOPS = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))

# Per-family config. flops = forward GFLOPs/image at 224x224 (mul+add as 2);
# training step ~ 3x forward (fwd + input grad + weight grad). Baselines are
# the reference's best published number for the family (BASELINE.md §4;
# img/s, higher is better). train_bs: batch sweep on the tunneled v5e found
# bs256 throughput-optimal for ResNet-50 (r3, tools/jax_resnet_ref.py);
# VGG-19's larger activations favor a smaller batch.
CNN = {
    "resnet": dict(builder="resnet50", fwd_flops=4.09e9, train_bs=256,
                   train_base=81.69, infer_base=217.69, lr=0.1),
    # nets without batch norm diverge to NaN at lr=0.1 within the warmup
    # steps (the assert on the final loss is the guard); throughput is
    # lr-independent, so run them at a stable rate
    "alexnet": dict(builder="alexnet", fwd_flops=1.43e9, train_bs=256,
                    train_base=626.53, infer_base=850.51, lr=0.01),
    "googlenet": dict(builder="googlenet", fwd_flops=3.0e9, train_bs=256,
                      train_base=250.46, infer_base=600.94, lr=0.005),
    "vgg19": dict(builder="vgg19", fwd_flops=39.0e9, train_bs=128,
                  train_base=28.46, infer_base=96.75, lr=0.005),
    # SmallNet = CIFAR-quick (BASELINE.md §1: 63.039 ms/batch at bs512 on
    # K40m = 8122 img/s best published; no §4 inference row — reuse the
    # train anchor)
    "smallnet": dict(builder="smallnet_mnist_cifar", fwd_flops=2.05e7,
                     train_bs=512, train_base=8122.0, infer_base=8122.0,
                     lr=0.01, img=32, classes=10),
}
INFER_BS = 16  # the reference's §4 inference batch


def _make_batch(batch, shapes_dtypes, rng):
    out = {}
    for name, shape, dtype in shapes_dtypes:
        if dtype == "img":
            out[name] = rng.standard_normal((batch,) + shape,
                                            dtype=np.float32)
        else:
            out[name] = rng.integers(0, dtype, (batch,) + shape,
                                     ).astype(np.int32)
    return out


def _feeds(exe, batch, shapes_dtypes, rng):
    """Rotating pre-staged HBM batches through the DoubleBufferedFeeder
    (reader/pipeline.py; reference create_double_buffer_reader_op.cc).
    Pre-staged by default: on this tunneled single-chip environment
    host->HBM bandwidth collapses to ~70 MB/s while the chip computes
    (measured r2; 1.4 GB/s idle), so per-step host uploads would benchmark
    the tunnel, not the chip. BENCH_HOST_PIPELINE=1 switches to true
    per-step host uploads for real TPU hosts; the overlap path itself is
    correctness-tested in tests/test_input_pipeline.py."""
    import jax
    from paddle_tpu.reader.pipeline import DoubleBufferedFeeder

    host_uploads = os.environ.get("BENCH_HOST_PIPELINE", "0") == "1"
    n_bufs = 3 if host_uploads else 2

    def make_batch():
        return _make_batch(batch, shapes_dtypes, rng)

    host = [make_batch() for _ in range(n_bufs)]
    if not host_uploads:
        host = [{k: jax.device_put(v, exe.device) for k, v in b.items()}
                for b in host]

    def reader():
        i = 0
        while True:
            yield host[i % len(host)]
            i += 1

    return iter(DoubleBufferedFeeder(
        reader, device=exe.device if host_uploads else None, capacity=1))


def _windows(exe, batch, shapes_dtypes, rng, k):
    """[K, B, ...] stacked windows for Executor.run_steps. Pre-staged in
    HBM and rotated by default (same tunnel rationale as _feeds);
    BENCH_HOST_PIPELINE=1 instead pulls each window through
    DoubleBufferedFeeder.next_window — per-batch host conversion overlapped
    with device compute, ONE stacked device_put per window."""
    import jax
    from paddle_tpu.reader.pipeline import DoubleBufferedFeeder

    if os.environ.get("BENCH_HOST_PIPELINE", "0") == "1":
        def reader():
            while True:
                yield _make_batch(batch, shapes_dtypes, rng)

        feeder = DoubleBufferedFeeder(reader, device=None, capacity=2)

        def gen():
            while True:
                yield feeder.next_window(k, device=exe.device)
        return gen()

    windows = []
    for _ in range(2):
        batches = [_make_batch(batch, shapes_dtypes, rng) for _ in range(k)]
        windows.append({
            name: jax.device_put(np.stack([b[name] for b in batches]),
                                 exe.device)
            for name, _, _ in shapes_dtypes})

    def gen():
        i = 0
        while True:
            yield windows[i % len(windows)]
            i += 1
    return gen()


def _dispatch_overhead_ms(run_step, k, n=10):
    """Host-side Python cost of driving ONE device step: time n
    enqueue-only calls (no host sync between them — async dispatch means
    the host returns as soon as the work is queued) and divide by the n*k
    device steps they drive. This is the number run_steps exists to
    shrink: the same model at --steps-per-call 8 should read ~8x lower.
    Never allowed to kill the bench line."""
    try:
        out = run_step()
        float(np.asarray(out).ravel()[0])            # drain the pipeline
        t0 = time.perf_counter()
        for _ in range(n):
            out = run_step()
        dt = time.perf_counter() - t0
        float(np.asarray(out).ravel()[0])            # leave it drained
        return round(dt / (n * k) * 1e3, 4)
    except Exception as e:  # noqa: BLE001 - metric is best-effort
        sys.stderr.write(f"dispatch-overhead probe failed: {e}\n")
        return None


def _dynamics_overhead_fraction(run_step, n=12, reps=3, warm=16):
    """Measured cost of the training-dynamics observatory's fused
    on-device reduction (dynamics.py), as a fraction of step time:
    per-step wall with dynamics on vs off, alternating `reps` A/B rounds
    and keeping each arm's MINIMUM (the same noise discipline as
    bench_diff's better-of-N). Flipping dynamics.override changes the
    executor's jit cache token, so the arms are distinct executables —
    and fresh XLA executables run slow for their first ~20 calls (same
    effect the roofline probe warms through), so each arm drains `warm`
    steps before its first timed round; without that the off-arm
    inherits the main loop's warmth and the comparison reads pure
    warmup as overhead. Best-effort — never kills the bench line. The
    acceptance bar is < 0.02 (ISSUE 19)."""
    try:
        from paddle_tpu import dynamics as dynamics_mod

        warmed = set()

        def _arm(enabled):
            with dynamics_mod.override(enabled):
                out = run_step()
                float(np.asarray(out).ravel()[0])    # compile + drain
                if enabled not in warmed:
                    warmed.add(enabled)
                    for _ in range(warm):
                        out = run_step()
                    float(np.asarray(out).ravel()[0])
                t0 = time.perf_counter()
                for _ in range(n):
                    out = run_step()
                float(np.asarray(out).ravel()[0])
                return (time.perf_counter() - t0) / n

        offs, ons = [], []
        for _ in range(reps):
            offs.append(_arm(False))
            ons.append(_arm(True))
        t_off, t_on = min(offs), min(ons)
        return round(max(t_on - t_off, 0.0) / t_off, 4)
    except Exception as e:  # noqa: BLE001 - metric is best-effort
        sys.stderr.write(f"dynamics-overhead probe failed: {e}\n")
        return None


def _auto_steps_per_call(exe, prog, run_step, feed, fetch):
    """`--steps-per-call auto`: measure the per-dispatch Python overhead
    and per-step device time on the already-compiled K=1 path, bound the
    window by the HBM headroom left over the K=1 footprint (HeadroomModel
    over the stacked feed window's linear growth), and let
    overlap.choose_steps_per_call pick K. Any probe failure degrades to
    whatever signals remain — the choice must never kill the bench."""
    from paddle_tpu.parallel import overlap as overlap_mod

    step_ms = None
    try:
        out = run_step()
        float(np.asarray(out).ravel()[0])        # compile + drain
        n = 5
        t0 = time.perf_counter()
        for _ in range(n):
            out = run_step()
        float(np.asarray(out).ravel()[0])
        step_ms = (time.perf_counter() - t0) / n * 1e3
    except Exception as e:  # noqa: BLE001 - probe is best-effort
        sys.stderr.write(f"auto steps-per-call timing probe failed: {e}\n")
    overhead_ms = _dispatch_overhead_ms(run_step, 1)
    peak = budget = feed_bytes = None
    try:
        from paddle_tpu import memory as memory_mod
        rec = exe.static_memory_analysis(prog, feed=feed,
                                         fetch_list=[fetch])
        peak = rec.total_bytes
        budget = memory_mod.default_budget(exe.device)
        feed_bytes = int(sum(np.asarray(v).nbytes for v in feed.values()))
    except Exception as e:  # noqa: BLE001 - probe is best-effort
        sys.stderr.write(f"auto steps-per-call memory probe failed: {e}\n")
    k = overlap_mod.choose_steps_per_call(
        python_overhead_ms=overhead_ms, step_time_ms=step_ms,
        feed_bytes_per_step=feed_bytes, peak_bytes=peak,
        budget_bytes=budget)
    sys.stderr.write(
        f"steps-per-call auto -> {k} (dispatch {overhead_ms}ms/step, "
        f"step {None if step_ms is None else round(step_ms, 3)}ms, "
        f"feed {feed_bytes}B, peak {peak}B, budget {budget}B)\n")
    return k


_TRANSIENT_MARKERS = (
    "INTERNAL", "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
    "remote_compile", "response body closed", "Connection reset",
    "Connection closed", "connection", "Broken pipe", "Socket closed",
    "timed out", "Timeout", "EOF", "RESOURCE_EXHAUSTED",
)


def _is_transient(e):
    """Transient infra failure (tunnel hiccup, remote-compile drop) vs a
    real bug. Assertion failures (NaN loss guards) are never transient;
    runtime-flavored errors and anything matching the marker list are —
    retries are bounded, so over-matching costs seconds, under-matching
    costs the round its official number (VERDICT r4 weak #1)."""
    if isinstance(e, (AssertionError, KeyboardInterrupt, SystemExit,
                      TypeError, NameError, AttributeError)):
        return False
    s = f"{type(e).__name__}: {e}"
    return ("RuntimeError" in type(e).__name__
            or any(m in s for m in _TRANSIENT_MARKERS))


class BenchError(RuntimeError):
    """Persistent failure after the retry budget; carries the error log."""

    def __init__(self, errors):
        super().__init__(errors[-1] if errors else "bench failed")
        self.errors = list(errors)


def _retrying(phase, fn, errors):
    """Call fn(), retrying transient failures up to BENCH_RETRIES times
    with linear backoff; every failure is logged into `errors`. Raises the
    original exception on a non-transient error or budget exhaustion."""
    attempts = RETRIES + 1
    for a in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - classified below
            errors.append(f"{phase}: {type(e).__name__}: {e}"[:300])
            if a == attempts - 1 or not _is_transient(e):
                raise
            time.sleep(min(2.0 * (a + 1), 10.0))
    return None


def _timed_loop(run_step, warmup, steps, errors=None):
    """Warm, then time back-to-back enqueues in chunks with one sync per
    chunk. run_step() must return an on-device scalar (return_numpy=False).

    Resilient (VERDICT r4 #1): every phase retries transient failures up to
    BENCH_RETRIES times — re-invoking run_step() re-triggers compilation,
    which is where the r4 tunnel drop hit — and completed timing chunks are
    kept, so one late hiccup still yields a number from the steps that did
    run. Non-transient failures (the NaN-loss assertion guard) always
    propagate — a diverged run must never be reported as a partial success.
    Returns (dt_seconds, steps_timed); appends messages to `errors`.
    Chunking (default 2) barely perturbs the measurement: enqueues still
    pipeline within a chunk and the per-chunk sync is one scalar readback.
    """
    errors = errors if errors is not None else []

    def _warm():
        out = None
        for _ in range(max(warmup, 1)):
            out = run_step()
        float(np.asarray(out).ravel()[0])  # sync

    try:
        _retrying("warmup", _warm, errors)
    except Exception as e:
        if not _is_transient(e):
            raise
        raise BenchError(errors) from e

    # First attempt times the WHOLE loop with ONE final sync — the mid-
    # loop syncs of a chunked measurement cost a tunnel round-trip each
    # and inflated fast-step families ~2x (measured r5: lstm 6 -> 14
    # ms/batch). Chunking only kicks in on RETRY attempts, where a flaky
    # session keeps the completed chunks as a partial result.
    chunks_env = os.environ.get("BENCH_CHUNKS")
    dt, done = 0.0, 0
    for a in range(RETRIES + 1):
        chunks = int(chunks_env) if chunks_env else (1 if a == 0 else 4)
        per = max(1, (steps - done) // max(chunks, 1))
        try:
            while done < steps:
                n = min(per, steps - done)
                t0 = time.perf_counter()
                out = None
                for _ in range(n):
                    out = run_step()
                final = float(np.asarray(out).ravel()[0])  # sync
                dt += time.perf_counter() - t0
                assert np.isfinite(final), f"non-finite fetch {final}"
                done += n
            return dt, done
        except Exception as e:  # noqa: BLE001 - classified below
            errors.append(f"timed: {type(e).__name__}: {e}"[:300])
            if not _is_transient(e):
                raise  # real bug (e.g. NaN): never report a partial number
            if a == RETRIES:
                if done:
                    break  # partial result from completed chunks
                raise BenchError(errors) from e
            time.sleep(min(2.0 * (a + 1), 10.0))
    return dt, done


_ROOFLINE = None


def _roofline_cached():
    """Same-session sustained bf16 matmul TF/s (VERDICT r4 #3).

    A jitted lax.scan of data-dependent [n,n] bf16 matmuls (each depends on
    the previous, so the chain cannot be elided or reordered) with a scalar
    readback as the fence — `block_until_ready` does not actually block on
    the tunneled terminal (measured r3). Best-of-3 rounds of back-to-back
    calls, because the tunnel drifts run-to-run. The result is the honest
    MFU denominator: nominal peak (197 TF/s v5e) is the datasheet; what the
    session's chip+tunnel actually sustains is what a program can use."""
    global _ROOFLINE
    if _ROOFLINE is not None:
        return _ROOFLINE or None
    if os.environ.get("BENCH_ROOFLINE", "1") != "1":
        _ROOFLINE = False
        return None
    try:
        import jax
        import jax.numpy as jnp
        from jax import lax

        n = int(os.environ.get("BENCH_ROOFLINE_N", "4096"))
        iters, calls = 16, 10
        rng = np.random.default_rng(0)
        scale = 1.0 / np.sqrt(n)  # variance-preserving: no bf16 overflow
        w = jnp.asarray(rng.standard_normal((n, n)) * scale, jnp.bfloat16)
        x = jnp.asarray(rng.standard_normal((n, n)) * scale, jnp.bfloat16)

        @jax.jit
        def chain(x, w):
            y, _ = lax.scan(lambda c, _: (c @ w, None), x, None,
                            length=iters)
            return (y[0, 0]).astype(jnp.float32)

        for _ in range(25):  # fresh executables run slow ~20 times here
            out = chain(x, w)
        float(out)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(calls):
                out = chain(x, w)
            float(out)  # device runs are ordered: last sync fences all
            best = min(best, time.perf_counter() - t0)
        tflops = 2.0 * iters * n ** 3 * calls / best / 1e12
        _ROOFLINE = {"tflops": round(tflops, 2), "n": n}
    except Exception as e:  # noqa: BLE001 - probe must never kill the bench
        _ROOFLINE = False
        sys.stderr.write(f"roofline probe failed: {e}\n")
        return None
    return _ROOFLINE


_CARRIED_ERRORS = []  # errors from a failed whole-family attempt (main())

# step thunk of the family currently being measured; each main_* sets it so
# _emit can attach per-op roofline attribution to the JSON line. Cleared
# after every emit (a failed family must not reuse the previous one's step).
_PERF_STEP = [None]

# program of the family currently being measured (same lifecycle as
# _PERF_STEP): _emit runs the static verifier over it so every bench
# line carries analyze_errors/analyze_warnings (ISSUE 12) — a perf
# regression can be cross-read against new analyzer findings
_ANALYZE_PROG = [None]


def _analyze_fields():
    """analyze_errors / analyze_warnings for the JSON line. The analysis
    is abstract (no tracing, no device), so it adds milliseconds;
    BENCH_ANALYZE=0 skips it and any failure degrades to no fields."""
    prog = _ANALYZE_PROG[0]
    if prog is None or os.environ.get("BENCH_ANALYZE", "1") != "1":
        return {}
    try:
        from paddle_tpu.analysis import analyze_program

        counts = analyze_program(prog).counts()
        return {"analyze_errors": counts.get("error", 0),
                "analyze_warnings": counts.get("warning", 0)}
    except Exception as e:  # noqa: BLE001 - advisory, never kills the line
        sys.stderr.write(f"static analysis skipped: {e}\n")
        return {}


def _perf_fields(probe=None):
    """`top_ops` / `bound` / `device_duty_cycle` for the JSON line (ISSUE 6:
    every bench line carries the evidence the MFU campaign needs): runs the
    family's step 3 more times under a silent traced session and joins the
    roofline report. BENCH_PERF=0 skips it; any failure degrades to no
    extra fields — the bench line itself must never die here."""
    step = _PERF_STEP[0]
    if step is None or os.environ.get("BENCH_PERF", "1") != "1":
        return {}
    try:
        from paddle_tpu import roofline

        if probe:
            # reuse the session's sustained-matmul measurement instead of
            # probing twice (the ridge only needs the HBM probe on top)
            roofline._PROBES.setdefault("sustained_tflops", probe["tflops"])
        report = roofline.capture(step, steps=3)
        if not report:
            return {}
        out = {"top_ops": roofline.top_ops(report),
               "device_duty_cycle": report.get("device_duty_cycle")}
        hc = report.get("hlo_counts")
        if hc:
            # per-step kernel-count trend: fusion wins show up as fewer
            # HLO instructions/fusions at the same img/s (ISSUE 7)
            out["hlo_instructions"] = hc["instructions"]
            out["hlo_fusions"] = hc["fusions"]
        attributed = [r for r in report["rows"]
                      if r["bound"] != "unattributed"]
        out["bound"] = (attributed[0]["bound"] if attributed
                        else "unattributed")
        # per-kernel scoreboard (ISSUE 11): measured vs roofline-minimum
        # device time per op+shape, plus how much of the conv-family time
        # the Pallas kernels served — the evidence columns the kernel
        # phase of the MFU campaign is judged by
        ke = report.get("kernel_efficiency")
        if ke:
            out["kernel_efficiency"] = ke[:5]
        if report.get("pallas_kernel_coverage") is not None:
            out["pallas_kernel_coverage"] = round(
                report["pallas_kernel_coverage"], 4)
        if report.get("input_bound") is not None:
            out["input_bound"] = report["input_bound"]
            if report.get("input_bound_remedy"):
                out["input_bound_remedy"] = report["input_bound_remedy"]
        try:
            # fleet fields (ISSUE 8): per-kind bus bandwidth, cross-host
            # step skew (1.0 single-host) and the goodput fraction
            from paddle_tpu import fleet
            bus = fleet.busbw_by_kind(report.get("collectives"))
            if bus:
                out["busbw"] = bus
            # overlap fields (ISSUE 9): collective time NOT hidden by
            # compute, and the hidden fraction — the tentpole's own metric
            es = fleet.exposed_summary(report.get("collectives"))
            if es:
                out.update(es)
            snap = fleet.fleet_snapshot()
            out["fleet_skew"] = round(snap["step_skew"], 4)
            gp = fleet.goodput_report()
            if gp:
                out["goodput"] = round(gp["goodput_fraction"], 4)
        except Exception:  # noqa: BLE001 - fleet fields are best-effort
            pass
        return out
    except Exception as e:  # noqa: BLE001 - attribution is best-effort
        sys.stderr.write(f"perf attribution failed: {e}\n")
        return {}


def _emit(payload, errors=()):
    """Print the ONE JSON line the driver parses. Attaches the retry error
    log and the session roofline (sustained TF/s + MFU against it) so a
    partial or degraded run is visible but still parseable."""
    # families that resolved `auto` set the chosen K explicitly; the rest
    # (LoD families can't window) effectively ran the per-step path
    payload.setdefault("steps_per_call",
                       STEPS_PER_CALL if isinstance(STEPS_PER_CALL, int)
                       else 1)
    allerr = _CARRIED_ERRORS + list(errors)
    if allerr:
        payload["errors"] = allerr
    # never run the device probe on the persistent-failure path: a wedged
    # tunnel hangs rather than raises, and the guaranteed JSON line must
    # still come out
    probe = None if payload.get("value") is None else _roofline_cached()
    if probe:
        payload["sustained_tflops"] = probe["tflops"]
        mfu = payload.get("mfu")
        if mfu is not None and probe["tflops"] > 0:
            payload["mfu_nominal"] = mfu
            payload["mfu_vs_sustained"] = round(
                mfu * PEAK_TFLOPS / probe["tflops"], 4)
    try:  # memory alongside images/sec; must never kill the bench line
        from paddle_tpu import memory as memory_mod
        mem = memory_mod.bench_summary()
        if mem:
            payload.setdefault("peak_hbm_bytes", mem["peak_hbm_bytes"])
            payload.setdefault("hbm_utilization", mem["hbm_utilization"])
    except Exception:
        pass
    if payload.get("value") is not None:
        payload.update(_perf_fields(probe))
    payload.update(_analyze_fields())
    try:  # quantization scoreboard (ISSUE 20): only on runs that could
        # quantize (O3 training or quantized serving), so older families'
        # lines keep their schema. quant_fallbacks is the acceptance
        # gate — a benched family must hit zero.
        from paddle_tpu import telemetry as _tel
        qh = _tel.read_series("quant_kernel_total")
        qf = _tel.read_series("quant_fallback_total")
        if AMP_LEVEL == "O3" or os.environ.get("BENCH_QUANT") or qh or qf:
            payload.setdefault("quant_hits", int(sum(qh.values())))
            payload.setdefault("quant_fallbacks", int(sum(qf.values())))
    except Exception:
        pass
    _PERF_STEP[0] = None
    _ANALYZE_PROG[0] = None
    print(json.dumps(payload))
    sys.stdout.flush()
    _append_history(payload)


def _append_history(payload):
    """Append the emitted line to the standing BENCH_HISTORY.jsonl ledger
    (ISSUE 17 satellite) — the series `tools/bench_diff.py --history`
    gates the BENCH_r* campaign against. Ledger metadata (git sha,
    timestamp) is passed in via BENCH_GIT_SHA/BENCH_TS by the driver, not
    computed here — the bench process stays subprocess-free. BENCH_HISTORY
    names the file (default: BENCH_HISTORY.jsonl next to bench.py);
    0/off/none disables. Never kills the bench line."""
    raw = os.environ.get("BENCH_HISTORY", "").strip()
    if raw.lower() in ("0", "off", "none", "no", "false"):
        return
    path = raw or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.jsonl")
    mode = os.environ.get("BENCH_MODE", "resnet")
    record = {"ts": float(os.environ.get("BENCH_TS") or time.time()),
              "git_sha": os.environ.get("BENCH_GIT_SHA") or None,
              "mode": mode, "family": mode.partition("_")[0]}
    record.update(payload)
    try:
        with open(path, "a") as f:
            f.write(json.dumps(record, default=str) + "\n")
    except OSError:
        pass


def main_cnn(family, train=True):
    import paddle_tpu as fluid
    from paddle_tpu import models

    cfg = CNN[family]
    builder = getattr(models, cfg["builder"])
    batch = int(BATCH) if BATCH else (cfg["train_bs"] if train else INFER_BS)
    side = cfg.get("img", 224)
    classes = cfg.get("classes", 1000)

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        img = fluid.layers.data(name="img", shape=[3, side, side],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        if train:
            avg_cost, _, _ = models.build_image_classifier(
                builder, img, label, class_dim=classes)
            opt = fluid.optimizer.Momentum(learning_rate=cfg["lr"],
                                           momentum=0.9)
            if AMP:
                # bf16 matmul/conv compute on the MXU, fp32 master weights;
                # O2 keeps activations bf16 end-to-end (halves HBM traffic)
                opt = fluid.amp.decorate(opt, level=AMP_LEVEL)
            opt.minimize(avg_cost, startup_program=startup)
            fetch = avg_cost
        else:
            logits = builder(img, class_dim=classes, is_test=True)
            predict = fluid.layers.softmax(logits)
            # a scalar fetch keeps the timed loop sync-free; argmax-sum is
            # data-dependent so XLA cannot dead-code the network
            fetch = fluid.layers.reduce_sum(
                fluid.layers.reduce_max(predict, dim=-1))

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)

    rng = np.random.default_rng(0)
    shapes = [("img", (3, side, side), "img")]
    if train:
        shapes.append(("label", (1,), classes))  # infer programs take no label
    k = STEPS_PER_CALL
    if k == "auto":
        probe_feeds = _feeds(exe, batch, shapes, rng)

        def step1():
            out, = exe.run(main_prog, feed=next(probe_feeds),
                           fetch_list=[fetch], return_numpy=False)
            return out

        k = _auto_steps_per_call(exe, main_prog, step1, next(probe_feeds),
                                 fetch)
    if k > 1:
        windows = _windows(exe, batch, shapes, rng, k)

        def step():
            out, = exe.run_steps(main_prog, feed_window=next(windows),
                                 steps=k, fetch_list=[fetch],
                                 fetch_mode="last", return_numpy=False)
            return out

        # STEPS/WARMUP stay denominated in device steps; the loop counts
        # CALLS, each driving k steps through one lax.scan dispatch
        calls, warm = max(1, STEPS // k), max(1, -(-WARMUP // k))
    else:
        feeds = _feeds(exe, batch, shapes, rng)

        def step():
            out, = exe.run(main_prog, feed=next(feeds), fetch_list=[fetch],
                           return_numpy=False)
            return out

        calls, warm = STEPS, WARMUP

    _PERF_STEP[0] = step
    _ANALYZE_PROG[0] = main_prog
    errors = []
    dt, done = _timed_loop(step, warm, calls, errors)
    done *= k
    overhead_ms = _dispatch_overhead_ms(step, k)
    img_s = batch * done / dt
    flops_per_img = (3 if train else 1) * cfg["fwd_flops"]
    mfu = img_s * flops_per_img / (PEAK_TFLOPS * 1e12)
    base = cfg["train_base"] if train else cfg["infer_base"]
    job = "train" if train else "infer"
    _emit({
        "metric": f"{cfg['builder']}_{job}_images_per_sec",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / base, 3),
        "batch": batch,
        "amp": AMP if train else False,
        "amp_level": (AMP_LEVEL if AMP else None) if train else None,
        "steps_timed": done,
        "steps_per_call": k,
        "steps_per_call_mode": ("auto" if STEPS_PER_CALL == "auto"
                                else "fixed"),
        "python_overhead_per_step_ms": overhead_ms,
        "mfu": round(mfu, 4),
    }, errors)


def main_fc():
    """Conv-free 3-layer MLP classifier (784-1024-1024-10, Momentum): the
    portable attribution family. No convolutions means no XLA:CPU
    grad-conv cliff inside scan bodies, so `--families fc` runs the full
    timed-loop + roofline-attribution path on any host — the CI smoke for
    the bench-side perf fields (ISSUE 6 acceptance)."""
    import paddle_tpu as fluid

    bsz = int(BATCH) if BATCH else 256
    hid = int(os.environ.get("BENCH_FC_HIDDEN", "1024"))
    classes = 10

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data(name="x", shape=[784], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=hid, act="relu")
        h = fluid.layers.fc(input=h, size=hid, act="relu")
        logits = fluid.layers.fc(input=h, size=classes, act="softmax")
        cost = fluid.layers.cross_entropy(input=logits, label=label)
        avg_cost = fluid.layers.mean(cost)
        opt = fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9)
        if AMP:
            opt = fluid.amp.decorate(opt, level=AMP_LEVEL)
        opt.minimize(avg_cost, startup_program=startup)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)

    rng = np.random.default_rng(0)
    shapes = [("x", (784,), "img"), ("label", (1,), classes)]
    k = STEPS_PER_CALL
    if k == "auto":
        probe_feeds = _feeds(exe, bsz, shapes, rng)

        def step1():
            out, = exe.run(main_prog, feed=next(probe_feeds),
                           fetch_list=[avg_cost], return_numpy=False)
            return out

        k = _auto_steps_per_call(exe, main_prog, step1, next(probe_feeds),
                                 avg_cost)
    if k > 1:
        windows = _windows(exe, bsz, shapes, rng, k)

        def step():
            out, = exe.run_steps(main_prog, feed_window=next(windows),
                                 steps=k, fetch_list=[avg_cost],
                                 fetch_mode="last", return_numpy=False)
            return out

        calls, warm = max(1, STEPS // k), max(1, -(-WARMUP // k))
    else:
        feeds = _feeds(exe, bsz, shapes, rng)

        def step():
            out, = exe.run(main_prog, feed=next(feeds),
                           fetch_list=[avg_cost], return_numpy=False)
            return out

        calls, warm = STEPS, WARMUP

    _PERF_STEP[0] = step
    _ANALYZE_PROG[0] = main_prog
    errors = []
    dt, done = _timed_loop(step, warm, calls, errors)
    done *= k
    ex_s = bsz * done / dt
    fwd_flops = 2 * (784 * hid + hid * hid + hid * classes)
    mfu = 3 * ex_s * fwd_flops / (PEAK_TFLOPS * 1e12)
    _emit({
        "metric": "fc_mlp_train_examples_per_sec",
        "value": round(ex_s, 1),
        "unit": "examples/sec",
        "vs_baseline": None,   # no reference-published MLP anchor
        "batch": bsz, "hidden": hid, "amp": AMP,
        "amp_level": AMP_LEVEL if AMP else None,
        "steps_timed": done,
        "steps_per_call": k,
        "steps_per_call_mode": ("auto" if STEPS_PER_CALL == "auto"
                                else "fixed"),
        "python_overhead_per_step_ms": _dispatch_overhead_ms(step, k),
        "dynamics_overhead_fraction": _dynamics_overhead_fraction(step),
        "mfu": round(mfu, 4),
    }, errors)


def main_lstm():
    """2xLSTM+fc h512 bs64 seqlen100 (reference benchmark/paddle/rnn/rnn.py:
    embedding 128, simple_lstm = fc(4h)+lstmemory with peepholes, Adam)."""
    import paddle_tpu as fluid

    import jax

    vocab, emb_dim, hid = 30000, 128, int(os.environ.get("BENCH_HIDDEN",
                                                         "512"))
    bsz = int(os.environ.get("BENCH_LSTM_BATCH", "64"))
    seqlen = 100
    steps, warmup = STEPS, WARMUP
    baseline_ms = 184.0   # K40m, BASELINE.md §3

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(input=data, size=[vocab, emb_dim])
        h = emb
        for _ in range(2):
            proj = fluid.layers.fc(input=h, size=hid * 4,
                                    num_flatten_dims=2)
            h, _c = fluid.layers.dynamic_lstm(input=proj, size=hid * 4,
                                              use_peepholes=True)
        last = fluid.layers.sequence_last_step(h)
        logits = fluid.layers.fc(input=last, size=2, act="softmax")
        cost = fluid.layers.cross_entropy(input=logits, label=label)
        avg_cost = fluid.layers.mean(cost)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(
            avg_cost, startup_program=startup)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)

    rng = np.random.default_rng(0)
    # fixed-length (pad_seq=True in the reference run): dense [B, T] ids
    ids = rng.integers(0, vocab, (bsz, seqlen)).astype(np.int32)
    labs = rng.integers(0, 2, (bsz, 1)).astype(np.int32)
    feed = {"words": jax.device_put(ids, exe.device),
            "label": jax.device_put(labs, exe.device)}

    def step():
        loss, = exe.run(main_prog, feed=feed, fetch_list=[avg_cost],
                        return_numpy=False)
        return loss

    _PERF_STEP[0] = step
    _ANALYZE_PROG[0] = main_prog
    errors = []
    dt, done = _timed_loop(step, warmup, steps, errors)
    ms_batch = dt / done * 1000
    # fwd FLOPs/batch: input projections (emb->4H, H->4H) + recurrent gemm
    # (H->4H per step) for both layers; train step ~ 3x forward
    gemm = (emb_dim * 4 * hid + hid * 4 * hid    # layer1 proj + recur
            + hid * 4 * hid + hid * 4 * hid)     # layer2 proj + recur
    fwd_flops = 2 * bsz * seqlen * gemm
    mfu = 3 * fwd_flops / (dt / done) / (PEAK_TFLOPS * 1e12)
    _emit({
        "metric": "lstm2_h512_train_ms_per_batch",
        "value": round(ms_batch, 2),
        "unit": "ms/batch",
        "vs_baseline": round(baseline_ms / ms_batch, 3),
        "batch": bsz, "seqlen": seqlen, "hidden": hid,
        "steps_timed": done,
        "mfu": round(mfu, 4),
    }, errors)


def main_attention():
    """Pallas flash attention (fwd+bwd, O(T) memory) vs the XLA einsum
    reference at T=4096 causal — the kernel behind fused_attention
    (use_flash=True) and the in-shard blocks of ring attention. The 2018
    reference has no attention op at all (SURVEY.md §2.5 last row), so
    vs_baseline is the measured speedup over the XLA attention path on the
    same chip: >1 means the Pallas kernels beat the compiler."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_attention import flash_attention
    from paddle_tpu.parallel.ring_attention import attention_reference

    b = int(os.environ.get("BENCH_ATTN_BATCH", "1"))
    t = int(os.environ.get("BENCH_ATTN_SEQLEN", "4096"))
    h, d = 8, 64
    steps, warmup = STEPS, WARMUP
    rng = np.random.default_rng(1)
    q, k, v = [jax.device_put(rng.standard_normal((b, t, h, d))
                              .astype(np.float32)) for _ in range(3)]

    def make(fn):
        return jax.jit(jax.grad(
            lambda a, bb, c: jnp.sum(fn(a, bb, c) ** 2), argnums=(0, 1, 2)))

    def time_once(g, n):
        # fetch a scalar from the result for the sync: on the tunneled
        # terminal block_until_ready returns before execution completes
        # (measured r3), so only a value readback is a trustworthy fence
        r = g(q, k, v)
        float(np.asarray(r[0]).ravel()[0])
        t0 = time.perf_counter()
        for _ in range(n):
            r = g(q, k, v)
        float(np.asarray(r[0]).ravel()[0])
        return (time.perf_counter() - t0) / n

    g_flash = make(lambda a, bb, c: flash_attention(a, bb, c, True))
    g_xla = make(lambda a, bb, c: attention_reference(a, bb, c, causal=True))
    # raw-jax family: no executor suppliers, so attribution degrades to
    # duty cycle + unattributed rows — still worth carrying on the line
    _PERF_STEP[0] = lambda: float(
        np.asarray(g_flash(q, k, v)[0]).ravel()[0])
    # BENCH_ATTN_XLA=0 skips the einsum side entirely — at long T its
    # [T, T] residuals exhaust HBM, which is exactly flash's point
    run_xla = os.environ.get("BENCH_ATTN_XLA", "1") == "1"
    errors = []

    def _retry(phase, fn):
        return _retrying(phase, fn, errors)

    def _warm(g):
        r = None
        for _ in range(warmup):          # warm past the program cache
            r = g(q, k, v)
        float(np.asarray(r[0]).ravel()[0])

    for g in ((g_flash, g_xla) if run_xla else (g_flash,)):
        _retry("warmup", lambda g=g: _warm(g))
    # the tunneled chip drifts run-to-run (r3: high variance); alternate
    # measurement rounds and take each side's best so drift hits both
    flash_ts, xla_ts = [], []
    for _ in range(3):
        flash_ts.append(_retry("flash", lambda: time_once(g_flash, steps)))
        if run_xla:
            xla_ts.append(_retry("xla", lambda: time_once(g_xla, steps)))
    flash_s = min(flash_ts)
    xla_s = min(xla_ts) if run_xla else None
    _emit({
        "metric": f"flash_attention_fwd_bwd_ms_T{t}_causal",
        "value": round(flash_s * 1e3, 3),
        "unit": "ms/step",
        "vs_baseline": round(xla_s / flash_s, 3) if run_xla else None,
        "xla_reference_ms": round(xla_s * 1e3, 3) if run_xla else None,
        "shape": [b, t, h, d],
    }, errors)


def _transformer_flops_per_token(n_layer, d_model, seqlen, vocab):
    """Forward FLOPs/token: per layer 2*(attn qkvo 4*d^2 + mlp 8*d^2) +
    attention scores 2*2*T*d, plus the vocab projection."""
    return n_layer * (2 * 12 * d_model ** 2
                      + 4 * seqlen * d_model) + 2 * vocab * d_model


def main_transformer():
    """Transformer-LM training step (models/transformer.py) with flash
    attention: tokens/sec + MFU. No reference counterpart (2018);
    vs_baseline is the ratio against the same model on the XLA einsum
    attention path (use_flash=False). With the r5-tuned 512/1024 tiles
    flash WINS end-to-end from T=2048 up (measured on v5e: 1.14x at
    T=2048, 1.32x at 4096, 1.65x at 8192) on top of its O(T) memory;
    below 2048 the einsum path fuses better and auto-selection keeps it
    (ops/nn_ops._flash_auto_threshold)."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import models

    bsz = int(BATCH) if BATCH else 8
    seqlen = int(os.environ.get("BENCH_SEQLEN", "2048"))
    n_layer = int(os.environ.get("BENCH_LAYERS", "4"))
    d_model = int(os.environ.get("BENCH_DMODEL", "512"))
    n_head = d_model // 64
    vocab = 8192
    steps, warmup = STEPS, WARMUP

    def build_and_time(use_flash):
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            tok = fluid.layers.data(name="tok", shape=[-1, seqlen],
                                    dtype="int64", append_batch_size=False)
            lab = fluid.layers.data(name="lab", shape=[-1, seqlen],
                                    dtype="int64", append_batch_size=False)
            loss = models.transformer_lm(
                tok, lab, vocab_size=vocab, d_model=d_model,
                n_head=n_head, n_layer=n_layer, use_flash=use_flash)
            opt = fluid.optimizer.Adam(learning_rate=1e-4)
            if AMP:
                opt = fluid.amp.decorate(opt, level=AMP_LEVEL)
            opt.minimize(loss, startup_program=startup)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, vocab, (bsz, seqlen)).astype(np.int32)
        labs = rng.integers(0, vocab, (bsz, seqlen)).astype(np.int32)
        feed = {"tok": jax.device_put(ids, exe.device),
                "lab": jax.device_put(labs, exe.device)}

        def step():
            out, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                           return_numpy=False)
            return out

        if use_flash:
            _PERF_STEP[0] = step
            _ANALYZE_PROG[0] = main_prog
        dt, done = _timed_loop(step, warmup, steps, errors)
        return dt / done  # seconds per step

    errors = []
    sps = build_and_time(True)
    sps_xla = build_and_time(False)
    tok_s = bsz * seqlen / sps
    flops_tok = _transformer_flops_per_token(n_layer, d_model, seqlen, vocab)
    mfu = 3 * tok_s * flops_tok / (PEAK_TFLOPS * 1e12)  # train ~ 3x fwd
    _emit({
        "metric": "transformer_lm_train_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(sps_xla / sps, 3),
        "xla_attention_tokens_per_sec": round(bsz * seqlen / sps_xla, 1),
        "batch": bsz, "seqlen": seqlen, "layers": n_layer,
        "d_model": d_model, "amp": AMP, "mfu": round(mfu, 4),
    }, errors)


def main_ring_attention():
    """Long-context flagship (VERDICT r4 #7): transformer-LM train step at
    T=32k with sequence_parallel=True — ring attention over an 'sp' mesh
    spanning every visible device (1 on the tunneled chip: the ring
    degenerates to the flash kernels + shard_map, which is exactly the
    single-chip long-context path; 8 on a CPU host mesh). The einsum
    path cannot run here at all: its [T, T] residuals are ~4 GB/head.
    vs_baseline guards the r4 regression number, 1.58 s/step."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import models
    from jax.sharding import Mesh

    bsz = int(BATCH) if BATCH else 1
    seqlen = int(os.environ.get("BENCH_SEQLEN", "32768"))
    n_layer = int(os.environ.get("BENCH_LAYERS", "4"))
    d_model = int(os.environ.get("BENCH_DMODEL", "512"))
    n_head = d_model // 64
    vocab = 8192
    baseline_s = 1.58            # r4 single-chip T=32k step (round4-state)
    # steps are ~1.5s each: a lighter default than the global 20/25
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    warmup = int(os.environ.get("BENCH_WARMUP", "8"))

    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(len(devs)), ("sp",))

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        tok = fluid.layers.data(name="tok", shape=[-1, seqlen],
                                dtype="int64", append_batch_size=False)
        lab = fluid.layers.data(name="lab", shape=[-1, seqlen],
                                dtype="int64", append_batch_size=False)
        loss = models.transformer_lm(
            tok, lab, vocab_size=vocab, d_model=d_model, n_head=n_head,
            n_layer=n_layer, use_flash=True, sequence_parallel=True)
        opt = fluid.optimizer.Adam(learning_rate=1e-4)
        if AMP:
            opt = fluid.amp.decorate(opt, level=AMP_LEVEL)
        opt.minimize(loss, startup_program=startup)
    main_prog._mesh = mesh

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (bsz, seqlen)).astype(np.int32)
    labs = rng.integers(0, vocab, (bsz, seqlen)).astype(np.int32)
    feed = {"tok": jax.device_put(ids, exe.device),
            "lab": jax.device_put(labs, exe.device)}

    def step():
        out, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                       return_numpy=False)
        return out

    _PERF_STEP[0] = step
    _ANALYZE_PROG[0] = main_prog
    errors = []
    dt, done = _timed_loop(step, warmup, steps, errors)
    s_step = dt / done
    tok_s = bsz * seqlen / s_step
    flops_tok = _transformer_flops_per_token(n_layer, d_model, seqlen, vocab)
    mfu = 3 * tok_s * flops_tok / (PEAK_TFLOPS * 1e12)
    _emit({
        "metric": f"ring_attention_transformer_T{seqlen}_sec_per_step",
        "value": round(s_step, 3),
        "unit": "sec/step",
        "vs_baseline": round(baseline_s / s_step, 3),
        "tokens_per_sec": round(tok_s, 1),
        "batch": bsz, "seqlen": seqlen, "layers": n_layer,
        "d_model": d_model, "sp_devices": len(devs), "amp": AMP,
        "steps_timed": done, "mfu": round(mfu, 4),
    }, errors)


def main_embedding():
    """Criteo-DLRM-style sparse embedding family (ISSUE 10 + 14): one
    shared [ROWS, DIM] table looked up by SLOTS categorical features per
    example, trained with Adam through the SelectedRows scatter-apply
    path (no dense [ROWS, DIM] gradient or moment update ever
    materializes). The JSON line reports rows_touched_per_sec — the
    sparse-path throughput unit: ids presented to the table per second —
    next to the table geometry, whether scatter-apply was live, the
    densify-fallback count (must stay 0), and HBM table/opt-state bytes.
    No AMP: the table and its moments stay f32.

    Default config: table row-sharded over an fsdp mesh of every visible
    device (the cache columns emit null). BENCH_EMB_BUDGET=<MB> instead
    runs the beyond-HBM hot-row cache (ISSUE 14): the table stays
    UNSHARDED (cache and row-sharding are mutually exclusive per table),
    only a budget-sized slab is device-resident, ids draw from a zipf
    law (skew BENCH_EMB_ZIPF, default 1.3 — the criteo-like regime where
    a small hot set covers most lookups), training runs fused
    BENCH_EMB_WINDOW-step windows through DoubleBufferedFeeder with the
    NEXT window's rows prefetched behind the in-flight window's compute,
    and three more columns report steady-state (post-warmup) cache
    behavior: cache_hit_rate, prefetch_overlap_fraction, and
    flush_bytes_per_step. A rows>budget table trains fine — that is the
    point — and densify_fallbacks must still be 0: the cache feeds the
    same scatter-apply kernels, just slab-indexed."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import telemetry
    from paddle_tpu.ops import sparse_ops
    from paddle_tpu.parallel import emb_cache as emb_cache_mod
    from paddle_tpu.parallel import embedding as emb_mod
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.reader.pipeline import DoubleBufferedFeeder

    bsz = int(BATCH) if BATCH else 256
    rows = int(os.environ.get("BENCH_EMB_ROWS", "1000000"))
    dim = int(os.environ.get("BENCH_EMB_DIM", "64"))
    slots = int(os.environ.get("BENCH_EMB_SLOTS", "26"))
    budget_mb = os.environ.get("BENCH_EMB_BUDGET")   # MB; enables cache
    zipf_a = float(os.environ.get("BENCH_EMB_ZIPF", "1.3"))
    k_window = int(os.environ.get("BENCH_EMB_WINDOW", "8"))
    devs = jax.devices()

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        ids = fluid.layers.data(name="ids", shape=[slots], dtype="int64")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(
            ids, size=[rows, dim], is_sparse=True,
            param_attr=fluid.ParamAttr(name="emb_table"))
        flat = fluid.layers.reshape(emb, shape=[-1, slots * dim])
        h = fluid.layers.fc(input=flat, size=256, act="relu")
        h = fluid.layers.fc(input=h, size=64, act="relu")
        logits = fluid.layers.fc(input=h, size=2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(
            loss, startup_program=startup)
    if budget_mb is None:
        main_prog._mesh = make_mesh((len(devs),), ("fsdp",))
        emb_mod.shard_table(main_prog, "emb_table", "fsdp")

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    rng = np.random.default_rng(0)
    lab_np = rng.integers(0, 2, (bsz, 1)).astype(np.int64)

    def draw_ids():
        if zipf_a > 1.0:
            z = rng.zipf(zipf_a, (bsz, slots)).astype(np.int64) - 1
            return np.minimum(z, rows - 1)
        return rng.integers(0, rows, (bsz, slots)).astype(np.int64)

    cache = None
    errors = []
    if budget_mb is not None:
        cache = emb_cache_mod.enable(
            main_prog, budget_bytes=int(float(budget_mb) * (1 << 20)))
        if cache is None:
            raise RuntimeError(
                f"BENCH_EMB_BUDGET={budget_mb}MB covers the whole "
                f"{rows}x{dim} table (or PADDLE_TPU_EMB_CACHE=0) — "
                f"nothing beyond-HBM to measure")
        sparse_names = cache.feed_id_names()

        def batches():
            while True:
                yield {"ids": draw_ids(), "label": lab_np}

        feeder = DoubleBufferedFeeder(batches, window_prefetch=2)
        pending = {"win": None, "handle": None}
        calls = [0]
        steady = {}        # stats snapshot at the warmup->timed boundary

        def step():
            # overlapped driver: dispatch window i, pull + prefetch
            # window i+1 while i computes, then block on i's loss
            if pending["win"] is None:
                pending["win"], _ = feeder.next_window(
                    k_window, device=exe.device, sparse_slots=sparse_names)
            out = exe.run_steps(
                main_prog, feed_window=pending["win"], fetch_list=[loss],
                fetch_mode="last", return_numpy=False)
            nwin, nuniq = feeder.next_window(
                k_window, device=exe.device, sparse_slots=sparse_names)
            handle = cache.prefetch(nuniq)
            val = out[0]
            np.asarray(val)            # block: compute hides the prefetch
            handle.wait()
            pending["win"] = nwin
            calls[0] += 1
            if calls[0] == max(WARMUP, 1):    # steady-state boundary
                steady.update(cache.stats(), calls=calls[0])
            return val

        rows_per_call = bsz * slots * k_window
    else:
        ids_np = draw_ids()
        feed = {"ids": jax.device_put(ids_np),
                "label": jax.device_put(lab_np)}

        def step():
            out, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                           return_numpy=False)
            return out

        rows_per_call = bsz * slots

    _PERF_STEP[0] = step
    _ANALYZE_PROG[0] = main_prog
    dt, done = _timed_loop(step, WARMUP, STEPS, errors)
    s_call = dt / done

    cache_hit_rate = overlap_frac = flush_per_step = None
    if cache is not None:
        s = cache.stats()
        base = steady or {"hits": 0, "misses": 0, "flush_bytes": 0,
                          "calls": 0}
        d_hit = s["hits"] - base["hits"]
        d_miss = s["misses"] - base["misses"]
        d_steps = max((calls[0] - base.get("calls", 0)) * k_window, 1)
        cache_hit_rate = round(d_hit / max(d_hit + d_miss, 1), 4)
        overlap_frac = round(s["overlap_fraction"], 4)
        flush_per_step = round(
            (s["flush_bytes"] - base["flush_bytes"]) / d_steps, 1)

    per = emb_mod.per_shard_table_bytes(main_prog)
    t = per["tables"].get("emb_table") if per.get("tables") else None
    densify = telemetry.read_series("sparse_densify_fallback_total")
    cache_spec = (next(iter(cache.tables().values()))
                  if cache is not None else None)
    _emit({
        "metric": "embedding_rows_touched_per_sec",
        "value": round(rows_per_call / s_call, 1),
        "unit": "rows/sec",
        "vs_baseline": None,   # no reference-published criteo anchor
        "examples_per_sec": round(
            bsz * (k_window if cache is not None else 1) / s_call, 1),
        "batch": bsz, "table_rows": rows, "emb_dim": dim, "slots": slots,
        "zipf_skew": zipf_a if zipf_a > 1.0 else None,
        "sparse_apply": sparse_ops.sparse_apply_enabled(),
        "fsdp_devices": len(devs) if budget_mb is None else None,
        "table_bytes": t["bytes"] if t else rows * dim * 4,
        "table_bytes_per_shard": t["per_shard_bytes"] if t else None,
        "opt_state_bytes_per_shard":
            t["opt_state_per_shard_bytes"] if t else None,
        "cache_rows": cache_spec.cache_rows if cache_spec else None,
        "cache_hit_rate": cache_hit_rate,
        "prefetch_overlap_fraction": overlap_frac,
        "flush_bytes_per_step": flush_per_step,
        "densify_fallbacks": sum(densify.values()),
        "steps_timed": done,
    }, errors)
    if cache is not None:
        # only AFTER _emit: _perf_fields re-runs step() for roofline
        # attribution, and step() pulls from the feeder — stopping it
        # earlier deadlocks that capture on next_window
        feeder.stop()


def main_serving():
    """Inference serving family (ISSUE 13): ServingEngine (AOT per-bucket
    executables) + DynamicBatcher under concurrent client threads, a
    normal phase at N clients then a 2x overload phase against the
    bounded queue. The JSON line is the serving trajectory's unit record:
    p50_ms/p99_ms (end-to-end request latency), qps, shed_fraction,
    bucket_hits (which ladder rungs actually ran), and goodput_fraction
    under overload — reject-not-collapse means the overload phase should
    show shed_fraction > 0 with accepted requests still completing,
    rather than p99 exploding. BENCH_SERVE_MODEL picks fc (default),
    dlrm (fsdp-sharded sparse table; densify must stay 0 at serve time),
    or transformer (token-level latency)."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import executor as executor_mod, models, telemetry
    from paddle_tpu.serving import DynamicBatcher, ServingEngine, run_load

    model = os.environ.get("BENCH_SERVE_MODEL", "fc")
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", "4"))
    requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "16"))
    max_batch = int(os.environ.get("BENCH_SERVE_MAX_BATCH", "16"))
    delay_ms = float(os.environ.get("BENCH_SERVE_DELAY_MS", "3.0"))
    queue_depth = int(os.environ.get("BENCH_SERVE_QUEUE_DEPTH", "32"))

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        if model == "dlrm":
            rows, dim, slots = 100000, 32, 26
            ids = fluid.layers.data(name="ids", shape=[slots],
                                    dtype="int64")
            emb = fluid.layers.embedding(
                ids, size=[rows, dim], is_sparse=True,
                param_attr=fluid.ParamAttr(name="emb_table"))
            flat = fluid.layers.reshape(emb, shape=[-1, slots * dim])
            h = fluid.layers.fc(input=flat, size=256, act="relu")
            h = fluid.layers.fc(input=h, size=64, act="relu")
            out = fluid.layers.softmax(fluid.layers.fc(input=h, size=2))
            feeds, fetches = ["ids"], [out.name]
        elif model == "transformer":
            seqlen, vocab = 128, 1024
            tok = fluid.layers.data(name="tok", shape=[-1, seqlen],
                                    dtype="int64",
                                    append_batch_size=False)
            lab = fluid.layers.data(name="lab", shape=[-1, seqlen],
                                    dtype="int64",
                                    append_batch_size=False)
            _loss, logits = models.transformer_lm(
                tok, lab, vocab_size=vocab, d_model=128, n_head=2,
                n_layer=2, is_test=True, return_logits=True)
            feeds, fetches = ["tok"], [logits.name]
        else:
            x = fluid.layers.data(name="x", shape=[64], dtype="float32")
            h = fluid.layers.fc(input=x, size=256, act="relu")
            h = fluid.layers.fc(input=h, size=64, act="relu")
            out = fluid.layers.fc(input=h, size=8)
            feeds, fetches = ["x"], [out.name]
    if model == "dlrm":
        from paddle_tpu.parallel import embedding as emb_mod
        from paddle_tpu.parallel.mesh import make_mesh
        main_prog._mesh = make_mesh((len(jax.devices()),), ("fsdp",))
        emb_mod.shard_table(main_prog, "emb_table", "fsdp")

    scope = executor_mod.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    with executor_mod.scope_guard(scope):
        exe.run(startup)
    quantize = os.environ.get("BENCH_QUANT", "").strip() or None
    if quantize and quantize.lower() in ("0", "off", "none", "f32"):
        quantize = None
    engine = ServingEngine(main_prog, feed_names=feeds,
                           fetch_names=fetches, scope=scope,
                           max_batch=max_batch, quantize=quantize)
    rng = np.random.default_rng(0)
    rows_choices = [1, 2, 3, max(1, max_batch // 4)]

    def rand_feed(n):
        feed = {}
        for name, (shape, dtype) in engine._feed_meta.items():
            dims = (n,) + tuple(8 if d == -1 else d for d in shape[1:])
            if np.issubdtype(dtype, np.integer):
                feed[name] = rng.integers(0, 8, dims).astype(dtype)
            else:
                feed[name] = rng.standard_normal(dims).astype(dtype)
        return feed

    def make_feed(ci, ri):
        return rand_feed(rows_choices[(ci + ri) % len(rows_choices)])

    errors = []
    batcher = DynamicBatcher(engine, max_delay_ms=delay_ms,
                             max_queue_depth=queue_depth).start()
    try:
        # bucket warm-up outside the timed phases: compile, don't measure
        for n in sorted({engine.bucket_for(r) for r in rows_choices}):
            engine.run_batch(rand_feed(n))
        normal = run_load(batcher, make_feed, clients=clients,
                          requests_per_client=requests, label="normal")
        overload = run_load(batcher, make_feed, clients=2 * clients,
                            requests_per_client=requests,
                            deadline_ms=max(delay_ms * 8, 50.0),
                            label="overload")
    finally:
        batcher.stop()
    densify = telemetry.read_series("sparse_densify_fallback_total")
    slo_report = batcher.slo_monitor.report()
    _emit({
        "metric": "serving_p50_ms",
        "value": normal["p50_ms"],
        "unit": "ms",
        "vs_baseline": None,
        "p50_ms": normal["p50_ms"], "p99_ms": normal["p99_ms"],
        "qps": round(normal["qps"], 1),
        "shed_fraction": normal["shed_fraction"],
        "bucket_hits": normal["bucket_hits"],
        "goodput_fraction": normal["goodput_fraction"],
        "timeouts": normal["timeouts"] + overload["timeouts"],
        "overload": {k: overload[k] for k in
                     ("p50_ms", "p99_ms", "qps", "shed_fraction",
                      "bucket_hits", "goodput_fraction")},
        "slo_burn_fast": slo_report["windows"]["fast"]["burn_rate"],
        "slo_burn_slow": slo_report["windows"]["slow"]["burn_rate"],
        "model": model, "clients": clients, "max_batch": max_batch,
        "quant": quantize,
        "compile_cache": {"hits": engine.cache_hits,
                          "misses": engine.cache_misses},
        "densify_fallbacks": sum(densify.values()),
    }, errors)
    engine.close()


def _dispatch(mode):
    if mode == "fc":
        return main_fc()
    if mode == "lstm":
        return main_lstm()
    if mode == "attention":
        return main_attention()
    if mode == "transformer":
        return main_transformer()
    if mode == "ring_attention":
        return main_ring_attention()
    if mode == "embedding":
        return main_embedding()
    if mode == "serving":
        return main_serving()
    family, _, job = mode.partition("_")
    if family not in CNN or job not in ("", "infer"):
        raise SystemExit(f"unknown BENCH_MODE={mode}")
    return main_cnn(family, train=(job != "infer"))


def main():
    """Run the selected family; NEVER exit without printing the JSON line.

    A transient failure gets one whole-family rebuild (fresh Program,
    fresh Executor, fresh jit — the only state a wedged tunnel can hold);
    a persistent one emits value=null plus the error log so the driver's
    `parsed` is non-null and carries the diagnosis (VERDICT r4 weak #1)."""
    mode = os.environ.get("BENCH_MODE", "resnet")
    for attempt in range(2):
        log = []
        try:
            return _dispatch(mode)
        except SystemExit:
            raise
        except Exception as e:  # noqa: BLE001 - reported, never swallowed
            if isinstance(e, BenchError):
                log.extend(e.errors)
            log.append(f"attempt{attempt}: {type(e).__name__}: {e}"[:300])
            if attempt == 0 and _is_transient(e):
                # carry the failed attempt's log into whatever the rebuilt
                # family emits: a run that needed a rebuild must say so
                _CARRIED_ERRORS.extend(log)
                time.sleep(5.0)
                continue
            _emit({"metric": mode, "value": None, "unit": None,
                   "vs_baseline": None}, log)
            return 1


if __name__ == "__main__":
    args = sys.argv[1:]
    if "--steps-per-call" in args:
        STEPS_PER_CALL = _parse_steps_per_call(
            args[args.index("--steps-per-call") + 1])
    if "--families" in args:
        # run several families back-to-back, one JSON line each
        # (e.g. `bench.py --families fc,resnet,lstm`); exit code is the
        # worst of the runs
        rc = 0
        for fam in args[args.index("--families") + 1].split(","):
            fam = fam.strip()
            if not fam:
                continue
            os.environ["BENCH_MODE"] = fam
            _CARRIED_ERRORS.clear()
            rc = max(rc, main() or 0)
        sys.exit(rc)
    sys.exit(main())
