"""v2 layer DSL (reference: python/paddle/v2/layer.py + trainer_config_
helpers/layers.py wrappers). Each call builds fluid IR in the default
program; the returned Variables ARE the v2 "Layer" handles (the reference
wrapped config-proto nodes; here the IR is the config).

Coverage follows the layers a reference v2 script actually touches: data /
fc / embedding / conv / pool / batch_norm / recurrent (true vanilla
recurrence, lstmemory, grumemory and the simple_* fronts in networks.py) /
sequence pooling + slicing / projections + mixed (full_matrix, table,
identity, dotmul, scaling, trans, conv) / matrix-elementwise layers
(rotate, norms, distances, outer/linear/bilinear products) / misc
(maxid, clip, pad, resize, prelu, gated_unit, scale_shift, FM) / costs +
similarity heads. Unknown-kwarg policy (ADVICE r3/r4): parameter-affecting
kwargs (param_attr/bias_attr/name, initial_std/initial_mean as
initializers) are FORWARDED, per-parameter optimizer kwargs warn, layout-
only ones the TPU build doesn't need are accepted and ignored by name,
anything else raises so silent config drift cannot happen."""

from __future__ import annotations

from .. import layers as fluid_layers
from ..param_attr import ParamAttr
from .activation import _Act
from .pooling import pool_name

# kwargs that configured the legacy C++ engine's layout/devices and have
# no TPU meaning; accepted (and discarded) by every wrapper for source
# compatibility with reference configs
_IGNORED_KW = {"layer_attr", "device", "drop_rate", "error_clipping_threshold",
               "is_static"}
# kwargs that DO affect the reference model (per-parameter LR/momentum,
# sparse update path): accepted but warned about, never silently dropped
# (ADVICE r4)
_WARN_KW = {"learning_rate", "momentum", "sparse_update"}
# kwargs mapped onto the fluid initializer (ADVICE r4: these set parameter
# init in the reference, not layout)
_INIT_KW = {"initial_std", "initial_mean"}


def _split_kw(kw, where, init_ok=False):
    """init_ok=True marks wrappers that fold initial_std/initial_mean into
    their param attr via _attr_with_init; everywhere else those kwargs
    warn — they affect the reference model and must never vanish
    silently (ADVICE/review r5)."""
    import warnings
    ignored = {k: kw.pop(k) for k in list(kw)
               if k in _IGNORED_KW or k in _INIT_KW}
    if not init_ok and (_INIT_KW & set(ignored)):
        warnings.warn(
            f"{where}: initial_std/initial_mean are not applied by this "
            "wrapper — pass a param_attr with an initializer instead",
            stacklevel=3)
    for k in list(kw):
        if k in _WARN_KW:
            warnings.warn(
                f"{where}: kwarg '{k}' (per-parameter optimizer setting) "
                "is not applied on this build — set it on the optimizer "
                "instead", stacklevel=3)
            kw.pop(k)
    if kw:
        raise TypeError(f"{where}: unsupported kwargs {sorted(kw)} "
                        "(would silently change the model)")
    return ignored


def _attr_with_init(param_attr, ignored):
    """Fold initial_std/initial_mean (reference: parameter init config)
    into the fluid ParamAttr as a NormalInitializer, unless the attr
    already carries an initializer (ADVICE r4)."""
    if not (_INIT_KW & set(ignored)):
        return _as_attr(param_attr)
    from ..initializer import NormalInitializer
    init = NormalInitializer(loc=float(ignored.get("initial_mean", 0.0)),
                             scale=float(ignored.get("initial_std", 1.0)))
    attr = _as_attr(param_attr)
    if attr is None:
        return ParamAttr(initializer=init)
    if getattr(attr, "initializer", None) is None:
        import copy
        attr = copy.copy(attr)       # never mutate a (possibly shared) attr
        attr.initializer = init
    return attr


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, _Act) or isinstance(act, type) and issubclass(act, _Act):
        return act.name
    return act


def _as_attr(attr):
    """v2 parameter_attribute -> fluid ParamAttr (name passthrough)."""
    if attr is None or isinstance(attr, ParamAttr):
        return attr
    if isinstance(attr, str):
        return ParamAttr(name=attr)
    if isinstance(attr, dict):
        return ParamAttr(**attr)
    return attr


def data(name, type):
    """Input declaration (reference v2/layer data); type is a
    data_type.InputType."""
    if type.is_int:
        return fluid_layers.data(name=name, shape=[1], dtype="int64",
                                 lod_level=type.seq)
    return fluid_layers.data(name=name, shape=[type.dim], dtype="float32",
                             lod_level=type.seq)


def fc(input, size, act=None, param_attr=None, bias_attr=None, name=None,
       num_flatten_dims=1, **kw):
    """Fully connected (reference fc_layer). param_attr/bias_attr/name are
    forwarded — v2 code names parameters for sharing and decode-time reuse
    (ADVICE r3: silently dropping them broke that)."""
    ignored = _split_kw(kw, "fc", init_ok=True)
    return _register_named(name, fluid_layers.fc(
        input=input, size=size, act=_act_name(act),
        param_attr=_attr_with_init(param_attr, ignored),
        bias_attr=_as_attr(bias_attr), name=name,
        num_flatten_dims=num_flatten_dims))


def embedding(input, size, param_attr=None, **kw):
    """size = embedding dim (reference embedding_layer); the vocab extent
    comes from the data layer's integer_value range."""
    vocab = kw.pop("vocab_size", None)
    if vocab is None:
        vocab = kw.pop("input_range", None)
    ignored = _split_kw(kw, "embedding", init_ok=True)
    if vocab is None:
        raise ValueError("embedding needs vocab_size= (the reference reads "
                         "it from the data layer's integer_value range)")
    return fluid_layers.embedding(input=input, size=[vocab, size],
                                  param_attr=_attr_with_init(param_attr,
                                                             ignored))


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=0, act=None, param_attr=None, bias_attr=None, **kw):
    """Image convolution (reference img_conv_layer)."""
    ignored = _split_kw(kw, "img_conv", init_ok=True)
    return fluid_layers.conv2d(input=input, num_filters=num_filters,
                               filter_size=filter_size, stride=stride,
                               padding=padding, act=_act_name(act),
                               param_attr=_attr_with_init(param_attr,
                                                          ignored),
                               bias_attr=_as_attr(bias_attr))


def img_pool(input, pool_size, stride=1, padding=0, pool_type="max", **kw):
    """Spatial pooling (reference img_pool_layer)."""
    _split_kw(kw, "img_pool")
    return fluid_layers.pool2d(input=input, pool_size=pool_size,
                               pool_type=pool_name(pool_type),
                               pool_stride=stride, pool_padding=padding)


def batch_norm(input, act=None, is_test=False, param_attr=None,
               bias_attr=None, **kw):
    """Batch normalization (reference batch_norm_layer)."""
    _split_kw(kw, "batch_norm")
    return fluid_layers.batch_norm(input=input, act=_act_name(act),
                                   is_test=is_test,
                                   param_attr=_as_attr(param_attr),
                                   bias_attr=_as_attr(bias_attr))


def dropout(input, dropout_rate, **kw):
    """(reference dropout_layer)."""
    _split_kw(kw, "dropout")
    return fluid_layers.dropout(input, dropout_prob=dropout_rate)


def lstmemory(input, size=None, reverse=False, act=None, **kw):
    """LSTM over a projected sequence (reference lstmemory: input must
    already be the 4x-gate projection; `size` is the HIDDEN width, so the
    input must be 4*size wide — fluid dynamic_lstm's size param is the
    4x-gate width). Returns the hidden sequence."""
    _split_kw(kw, "lstmemory")
    hidden = size or input.shape[-1] // 4
    if input.shape[-1] != 4 * hidden:
        raise ValueError(
            f"lstmemory(size={hidden}) needs a 4*size={4 * hidden}-wide "
            f"gate projection as input, got width {input.shape[-1]} "
            "(reference lstmemory contract)")
    h, _c = fluid_layers.dynamic_lstm(input=input, size=4 * hidden,
                                      is_reverse=reverse)
    return h


def grumemory(input, size=None, reverse=False, act=None, **kw):
    """GRU over a projected sequence (reference grumemory; input is the
    3x-gate projection). Returns the hidden sequence."""
    _split_kw(kw, "grumemory")
    size = size or input.shape[-1] // 3
    return fluid_layers.dynamic_gru(input=input, size=size,
                                    is_reverse=reverse)


def simple_lstm(input, size, **kw):
    """fc projection + LSTM (reference trainer_config_helpers simple_lstm =
    mixed+lstmemory); returns the hidden sequence."""
    _split_kw(kw, "simple_lstm")
    proj = fluid_layers.fc(input=input, size=size * 4, num_flatten_dims=2)
    h, _c = fluid_layers.dynamic_lstm(input=proj, size=size * 4)
    return h


def recurrent(input, act=None, reverse=False, bias_attr=None,
              param_attr=None, **kw):
    """Simple (vanilla) recurrent layer (reference recurrent_layer,
    trainer_config_helpers/layers.py:3988): h_t = act(x_t + W·h_{t-1} + b)
    with the reference's Tanh default — the input is already the
    projection, so the only parameters are W [size, size] and the bias,
    matching the reference's parameter count (ADVICE r4: the previous
    GRU-based stand-in silently changed architecture). Built on the same
    DynamicRNN machinery as recurrent_group. reverse=True keeps the
    (documented) GRU fallback — DynamicRNN scans forward only — and warns.
    """
    ignored = _split_kw(kw, "recurrent", init_ok=True)
    size = input.shape[-1]
    # None = reference default (tanh); an explicit Linear/identity act
    # maps to name None and must stay identity, not become tanh
    act = "tanh" if act is None else _act_name(act)
    if reverse:
        import warnings
        warnings.warn(
            "recurrent(reverse=True) runs a reverse dynamic_gru stand-in "
            "(different parameterization than the reference's simple "
            "recurrence); feed a reversed sequence for exact semantics",
            stacklevel=2)
        proj = fluid_layers.fc(input=input, size=size * 3,
                               num_flatten_dims=2)
        return fluid_layers.dynamic_gru(
            input=proj, size=size, is_reverse=True,
            param_attr=_attr_with_init(param_attr, ignored),
            bias_attr=_as_attr(bias_attr))
    rnn = fluid_layers.DynamicRNN()
    with rnn.block():
        x_t = rnn.step_input(input)
        prev = rnn.memory(shape=[size])
        wh = fluid_layers.fc(input=prev, size=size,
                             param_attr=_attr_with_init(param_attr,
                                                        ignored),
                             bias_attr=_as_attr(bias_attr))
        h = fluid_layers.elementwise_add(x_t, wh, act=act)
        rnn.update_memory(prev, h)
        rnn.output(h)
    return rnn()


# --- recurrent group ---------------------------------------------------------

class _RecurrentCtx:
    def __init__(self, rnn):
        self.rnn = rnn
        self.named = {}          # layers created with name= inside the step
        self.memories = []       # (name, mem_var)


_RG_STACK = []


def _register_named(name, var):
    """Step layers created with name= become memory-update targets
    (reference recurrent_group / beam_search wire memory(name=N) to the
    step layer named N)."""
    if name is not None:
        if _RG_STACK:
            _RG_STACK[-1].named[name] = var
        elif _BEAM_STACK:
            # mirror memory()'s dispatch: layers named inside a NESTED
            # recurrent_group belong to that group, never to the
            # enclosing beam loop (their vars live in the rg sub-block)
            _BEAM_STACK[-1].named[name] = var
    return var


def memory(name, size=None, boot_layer=None, **kw):
    """Previous-step value of the step layer called `name` (reference
    memory layer). Only meaningful inside recurrent_group's step; boots
    from boot_layer when given, else zeros of [size]."""
    _split_kw(kw, "memory")
    if _BEAM_STACK and not _RG_STACK:
        return _beam_memory(name, boot_layer)
    if not _RG_STACK:
        raise ValueError("memory() must be called inside a "
                         "recurrent_group or beam_search step function")
    ctx = _RG_STACK[-1]
    if boot_layer is not None:
        mem = ctx.rnn.memory(init=boot_layer)
    else:
        if size is None:
            raise ValueError("memory() needs size= (or boot_layer=)")
        mem = ctx.rnn.memory(shape=[size])
    ctx.memories.append((name, mem))
    return mem


class GeneratedInput:
    """Decode-time input marker (reference GeneratedInput,
    trainer_config_helpers/layers.py): inside beam_search the previous
    step's selected words feed an embedding lookup of `embedding_size`
    over a `size`-word vocabulary; `embedding_name` shares the trained
    embedding table."""

    def __init__(self, size, embedding_name=None, embedding_size=None,
                 embedding_param_attr=None):
        if embedding_size is None:
            raise ValueError("GeneratedInput needs embedding_size=")
        attr = _as_attr(embedding_param_attr)
        if attr is None:
            if embedding_name is None:
                # the reference makes embedding_name a required arg
                # (layers.py GeneratedInput) so decode always shares the
                # TRAINED table — an auto-named fresh parameter would
                # generate through random weights with no error
                raise ValueError(
                    "GeneratedInput needs embedding_name= (the trained "
                    "embedding table to decode with) or an explicit "
                    "embedding_param_attr")
            attr = ParamAttr(name=embedding_name)
        self.size = size
        self.embedding_size = embedding_size
        self.param_attr = attr


class _BeamCtx:
    def __init__(self, program, parent_idx, beam_size):
        self.program = program
        self.parent_idx = parent_idx
        self.beam_size = beam_size
        self.memories = []       # (name, pre_var)
        self.named = {}


_BEAM_STACK = []


def _beam_memory(name, boot_layer):
    """memory() inside beam_search's step: the carry var and its boot
    expansion are built in the PARENT block (before the While op is
    appended), the step reads it per iteration, and the wrapper reorders
    + reassigns it by beam parent after each selection."""
    from ..framework.framework import in_block

    if boot_layer is None:
        raise ValueError("beam_search memory() needs boot_layer= (the "
                         "decoder's initial state)")
    ctx = _BEAM_STACK[-1]
    with in_block(ctx.program, ctx.parent_idx):
        lanes = fluid_layers.expand(
            fluid_layers.unsqueeze(boot_layer, axes=[1]),
            expand_times=[1, ctx.beam_size, 1])      # [B, K, D]
        pre = fluid_layers.assign(lanes)
    ctx.memories.append((name, pre))
    return pre


def beam_search(step, input, bos_id, eos_id, beam_size, max_length=500,
                name=None, num_results_per_sample=None):
    """Beam-search generation (reference v2 beam_search over
    RecurrentGradientMachine's generation mode,
    RecurrentGradientMachine.h:73-150; here lowered onto the fluid beam
    ops — beam_search_op.cc / beam_search_decode_op.cc — over dense
    [B, K] beam lanes, the same convention the book decoder and the C
    API's beam program use).

    `input`: one GeneratedInput (the word feedback loop) plus any
    StaticInputs/plain vars passed through to `step` unchanged, IN THE
    LIST'S ORDER — the generated embedding [B, K, emb] is substituted at
    the GeneratedInput's position, exactly like the reference's
    __real_step__ insertion, so a reference-ordered step signature works
    unmodified. `step(...)` returns the per-lane word PROBABILITIES
    [B, K, vocab]; inside it, memory(name=N, boot_layer=init) carries
    decoder state across steps — create its update with name=N, and the
    wrapper reorders it by each step's surviving parent lanes.
    Returns (sentences, scores) from beam_search_decode, lanes sliced to
    num_results_per_sample (default beam_size)."""
    from ..framework.framework import default_main_program

    inputs = input if isinstance(input, (list, tuple)) else [input]
    # resolve markers ONCE, preserving positions: GeneratedInput slots
    # stay as markers (substituted with the embedding each iteration),
    # StaticInputs unwrap to their variables
    resolved = [x if isinstance(x, GeneratedInput)
                else (x.input if isinstance(x, StaticInput) else x)
                for x in inputs]
    gen_pos = [i for i, x in enumerate(resolved)
               if isinstance(x, GeneratedInput)]
    statics = [x for x in resolved if not isinstance(x, GeneratedInput)]
    if len(gen_pos) != 1:
        raise ValueError("beam_search needs exactly one GeneratedInput")
    if not statics:
        raise ValueError("beam_search needs at least one non-generated "
                         "input as the batch anchor (the reference "
                         "passes the encoded source as StaticInput)")
    gen = resolved[gen_pos[0]]
    anchor = statics[0]
    if getattr(anchor, "lod_level", 0):
        raise ValueError(
            "beam_search: the first non-generated input is the BATCH "
            "anchor and must be one row per sample, but it is a "
            "SEQUENCE (lod_level>0) — its token count would silently "
            "become the beam batch. Pool it (sequence_last_step/pooling)"
            " first, like the reference's decoder boot state")
    k = beam_size
    n_results = k if num_results_per_sample is None \
        else num_results_per_sample
    if not 1 <= n_results <= k:
        raise ValueError(
            f"num_results_per_sample must be in [1, beam_size={k}], got "
            f"{n_results}")

    import numpy as _np
    counter = fluid_layers.fill_constant(shape=[1], dtype="int64", value=0)
    max_len = fluid_layers.fill_constant(shape=[1], dtype="int64",
                                         value=max_length)
    init_ids = fluid_layers.fill_constant_batch_size_like(
        input=anchor, shape=[-1, k], dtype="int64", value=bos_id)
    lane_penalty = fluid_layers.assign(
        _np.concatenate([[0.0], _np.full(k - 1, -1e9)])
        .astype(_np.float32))
    init_scores = fluid_layers.elementwise_add(
        fluid_layers.fill_constant_batch_size_like(
            input=anchor, shape=[-1, k], dtype="float32", value=0.0),
        lane_penalty, axis=1)

    cap = max_length + 1
    ids_arr = fluid_layers.array_write(init_ids, counter, capacity=cap)
    parents_arr = fluid_layers.array_write(
        fluid_layers.cast(init_ids, "int32"), counter, capacity=cap)
    scores_arr = fluid_layers.array_write(init_scores, counter,
                                          capacity=cap)
    pre_ids = fluid_layers.assign(init_ids)
    pre_scores = fluid_layers.assign(init_scores)

    prog = default_main_program()
    ctx = _BeamCtx(prog, prog.current_block_idx, k)
    cond = fluid_layers.less_than(x=counter, y=max_len)
    w = fluid_layers.While(cond=cond, max_iters=max_length + 1)
    with w.block():
        _BEAM_STACK.append(ctx)
        try:
            tok_emb = fluid_layers.reshape(
                fluid_layers.embedding(
                    input=pre_ids, size=[gen.size, gen.embedding_size],
                    param_attr=gen.param_attr),
                [-1, k, gen.embedding_size])         # [B, K, emb] — the
            # reshape pins the lane axis: embedding squeezes trailing
            # singleton id dims, which would collapse K=1 lanes
            step_args = list(resolved)
            step_args[gen_pos[0]] = tok_emb          # reference order
            probs = step(*step_args)
        finally:
            _BEAM_STACK.pop()
        logp = fluid_layers.log(
            fluid_layers.clip(probs, min=1e-12, max=1.0))
        sel_ids, sel_scores, parent = fluid_layers.beam_search(
            pre_ids=pre_ids, pre_scores=pre_scores, scores=logp,
            beam_size=k, end_id=eos_id)
        fluid_layers.increment(counter, value=1, in_place=True)
        fluid_layers.array_write(sel_ids, counter, array=ids_arr)
        fluid_layers.array_write(parent, counter, array=parents_arr)
        fluid_layers.array_write(sel_scores, counter, array=scores_arr)
        fluid_layers.assign(sel_ids, pre_ids)
        fluid_layers.assign(sel_scores, pre_scores)
        if ctx.memories:
            # surviving lanes carry their PARENT's state: gather lanes
            # with a one-hot matmul (dense-lane equivalent of the
            # reference's memory frame reorder)
            onehot = fluid_layers.reshape(
                fluid_layers.cast(
                    fluid_layers.one_hot(
                        fluid_layers.cast(parent, "int64"), k),
                    "float32"),
                [-1, k, k])   # pin [B,K,K]: one_hot squeezes K=1 lanes
            for name_m, pre in ctx.memories:
                tgt = ctx.named.get(name_m)
                if tgt is None:
                    raise ValueError(
                        f"beam_search: memory('{name_m}') has no step "
                        f"layer named '{name_m}' to carry — create its "
                        "update with name=")
                fluid_layers.assign(fluid_layers.matmul(onehot, tgt),
                                    pre)
        # stop early once EVERY lane has emitted eos (the reference
        # generation mode stops when all sequences finish): cond =
        # (counter < max_len) AND any(sel_ids != eos). Composed from
        # arithmetic ops — |ids - eos| sums to 0 only when all-finished.
        not_done = fluid_layers.less_than(
            x=fluid_layers.fill_constant(shape=[1], dtype="float32",
                                         value=0.5),
            y=fluid_layers.reduce_sum(
                fluid_layers.abs(fluid_layers.cast(
                    fluid_layers.elementwise_sub(
                        sel_ids,
                        fluid_layers.fill_constant(
                            shape=[1], dtype="int64", value=eos_id)),
                    "float32")), keep_dim=True))
        in_budget = fluid_layers.less_than(x=counter, y=max_len)
        fluid_layers.assign(
            fluid_layers.cast(
                fluid_layers.elementwise_mul(
                    fluid_layers.cast(in_budget, "float32"),
                    fluid_layers.cast(not_done, "float32")), "bool"),
            cond)

    sentences, final_scores = fluid_layers.beam_search_decode(
        ids_arr, parents_arr, scores=scores_arr, end_id=eos_id)
    if n_results < k:
        sentences = fluid_layers.split(
            sentences, [n_results, k - n_results], dim=1)[0]
        final_scores = fluid_layers.split(
            final_scores, [n_results, k - n_results], dim=1)[0]
    return sentences, final_scores


class StaticInput:
    """Non-sequence input to recurrent_group: the SAME variable is visible
    at every step (reference StaticInput — the seq2seq demos pass the
    encoded source this way). The sub-block reads parent-block variables
    directly, so this is a pass-through marker."""

    def __init__(self, input, is_seq=False, size=None):
        if is_seq:
            raise NotImplementedError(
                "StaticInput(is_seq=True): pass sequence inputs to "
                "recurrent_group directly instead")
        if getattr(input, "lod_level", 0):
            raise ValueError(
                "StaticInput got a SEQUENCE variable (lod_level>0) — a "
                "static input is one vector per batch row; pass sequences "
                "to recurrent_group directly (or pool them first)")
        self.input = input


def recurrent_group(step, input, reverse=False, **kw):
    """Custom recurrence over sequence input(s) (reference
    recurrent_group, the v2 surface of RecurrentGradientMachine;
    reference gserver/gradientmachines/RecurrentGradientMachine.h:32).
    `step` receives per-step slices of each sequence input; inside it,
    memory(name=N, ...) reads the previous step's layer named N — create
    that layer with name=N (fc/addto/... forward name into the group's
    registry). Lowered onto fluid DynamicRNN -> lax.scan.

    Supported subset: sequence inputs (plain Variables), StaticInput
    (same variable every step), zero- or layer-booted memories, single
    or multiple step outputs. GeneratedInput (decode-time) stays on the
    fluid DynamicRNN/beam_search surface."""
    _split_kw(kw, "recurrent_group")
    if reverse:
        # pure argument check: raise BEFORE any graph construction
        raise NotImplementedError(
            "recurrent_group(reverse=True): feed a reversed sequence or "
            "use lstmemory/grumemory(reverse=True)")
    inputs = input if isinstance(input, (list, tuple)) else [input]
    if all(isinstance(x, StaticInput) for x in inputs):
        raise ValueError("recurrent_group needs at least one sequence "
                         "input (only StaticInputs given)")
    rnn = fluid_layers.DynamicRNN()
    ctx = _RecurrentCtx(rnn)
    with rnn.block():
        _RG_STACK.append(ctx)
        try:
            step_ins = [x.input if isinstance(x, StaticInput)
                        else rnn.step_input(x) for x in inputs]
            out = step(*step_ins)
        finally:
            _RG_STACK.pop()
        for name, mem in ctx.memories:
            tgt = ctx.named.get(name)
            if tgt is None:
                raise ValueError(
                    f"recurrent_group: memory('{name}') has no step "
                    f"layer named '{name}' to carry from — create it "
                    f"with name='{name}'")
            rnn.update_memory(mem, tgt)
        outs = out if isinstance(out, (list, tuple)) else [out]
        rnn.output(*outs)
    return rnn()


# --- sequence ops ------------------------------------------------------------

def last_seq(input):
    return fluid_layers.sequence_last_step(input)


def first_seq(input):
    return fluid_layers.sequence_first_step(input)


class AggregateLevel:
    """(reference trainer_config_helpers/layers.py:300) pooling scope
    marker: TO_NO_SEQUENCE aggregates each whole (sub)sequence to one
    row; TO_SEQUENCE (nested input) aggregates each inner sequence."""
    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


class ExpandLevel:
    """(reference layers.py ExpandLevel) expand() scope marker."""
    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE
    FROM_TIMESTEP = FROM_NO_SEQUENCE


def pooling(input, pooling_type="max",
            agg_level=AggregateLevel.TO_NO_SEQUENCE, **kw):
    """Sequence pooling with a pooling-type marker (reference
    pooling_layer + v2/pooling.py). Only whole-sequence aggregation
    (TO_NO_SEQUENCE, the default) is provided here; the nested
    TO_SEQUENCE form raises with a pointer to the fluid fold/unfold
    surface, which handles lod_level=2 explicitly."""
    _split_kw(kw, "pooling")
    if agg_level == AggregateLevel.TO_SEQUENCE:
        raise ValueError(
            "pooling(agg_level=TO_SEQUENCE) needs a NESTED sequence "
            "input; pool the lod_level=2 var with "
            "fluid.layers.sequence_pool after sequence_unfold instead")
    return fluid_layers.sequence_pool(input, pool_name(pooling_type))


def max_pooling(input):
    return fluid_layers.sequence_pool(input, "max")


def sum_pooling(input):
    return fluid_layers.sequence_pool(input, "sum")


def avg_pooling(input):
    return fluid_layers.sequence_pool(input, "average")


def expand(input, expand_as,
           expand_level=ExpandLevel.FROM_NO_SEQUENCE, **kw):
    """Broadcast per-sequence values across steps (reference
    expand_layer; expand_level accepted for config parity — both levels
    lower to sequence_expand against the target's layout)."""
    _split_kw(kw, "expand")
    return fluid_layers.sequence_expand(input, expand_as)


def seq_concat(a, b, **kw):
    """Concatenate two sequences in TIME — output length is
    len(a) + len(b) per sequence (reference seq_concat_layer; lowers to
    the fluid sequence_concat op)."""
    _split_kw(kw, "seq_concat")
    return fluid_layers.sequence_concat([a, b])


# --- combinators -------------------------------------------------------------

def concat(input, **kw):
    _split_kw(kw, "concat")
    return fluid_layers.concat(input, axis=1)


def addto(input, act=None, bias_attr=None, name=None, **kw):
    """Elementwise sum of N inputs (reference addto_layer)."""
    _split_kw(kw, "addto")
    inputs = input if isinstance(input, (list, tuple)) else [input]
    out = inputs[0]
    for x in inputs[1:]:
        out = fluid_layers.elementwise_add(out, x)
    act = _act_name(act)
    if act:
        out = getattr(fluid_layers, act)(out)
    return _register_named(name, out)


def dotmul_operator(a, b, scale=1.0):
    """Elementwise product (reference dotmul_operator)."""
    out = fluid_layers.elementwise_mul(a, b)
    if scale != 1.0:
        out = fluid_layers.scale(out, scale=float(scale))
    return out


def cos_sim(a, b, scale=1.0, **kw):
    """Cosine similarity head (reference cos_sim; recommender_system's
    matching score)."""
    _split_kw(kw, "cos_sim")
    out = fluid_layers.cos_sim(a, b)
    if scale != 1.0:
        out = fluid_layers.scale(out, scale=float(scale))
    return out


def slope_intercept(input, slope=1.0, intercept=0.0):
    """y = slope*x + intercept (reference slope_intercept_layer)."""
    return fluid_layers.scale(input, scale=float(slope),
                              bias=float(intercept))


def trans(input, **kw):
    """2-D transpose (reference trans_layer)."""
    _split_kw(kw, "trans")
    return fluid_layers.transpose(input, perm=[1, 0])


def img_cmrnorm(input, size=5, scale=0.0128, power=0.75, **kw):
    """Cross-map response normalization (reference img_cmrnorm_layer;
    AlexNet's LRN). The reference config parser divides the user scale by
    size before it reaches the kernel, so lrn's alpha = scale/size; the
    reference default scale is 0.0128."""
    _split_kw(kw, "img_cmrnorm")
    return fluid_layers.lrn(input, n=size, alpha=scale / size, beta=power)


def maxout(input, groups, **kw):
    """(reference maxout_layer)."""
    _split_kw(kw, "maxout")
    return fluid_layers.maxout(input, groups=groups)


def _check_crf_size(input, size, where):
    if size is not None and int(input.shape[-1]) != int(size):
        raise ValueError(
            f"{where}: size={size} but the feature layer is "
            f"{input.shape[-1]} wide — the reference crf_layer's size IS "
            "the tag count, so these must match")


def crf(input, label, size=None, param_attr=None, **kw):
    """Linear-chain CRF training cost (reference crf_layer; size, when
    given, must equal the feature width = tag count)."""
    _split_kw(kw, "crf")
    _check_crf_size(input, size, "crf")
    return fluid_layers.linear_chain_crf(input=input, label=label,
                                         param_attr=_as_attr(param_attr))


def crf_decoding(input, size=None, label=None, param_attr=None, **kw):
    """Viterbi decode with the CRF's learned transitions (reference
    crf_decoding_layer). param_attr must NAME the transition parameter
    the paired crf() created — decoding reads an existing parameter."""
    _split_kw(kw, "crf_decoding")
    _check_crf_size(input, size, "crf_decoding")
    if param_attr is None:
        raise ValueError(
            "crf_decoding needs param_attr naming the transition "
            "parameter shared with crf() (e.g. param_attr='crf_w' on "
            "both) — there is no default transition parameter to read")
    return fluid_layers.crf_decoding(input=input,
                                     param_attr=_as_attr(param_attr),
                                     label=label)


def ctc(input, label, size=None, norm_by_times=False, **kw):
    """CTC loss over a logit sequence (reference ctc_layer: size = real
    classes + 1, and the blank is the LAST category index — warp_ctc's
    blank-0 convention is the sibling warp_ctc_layer, not this one)."""
    _split_kw(kw, "ctc")
    width = int(input.shape[-1])
    if size is not None and int(size) != width:
        raise ValueError(
            f"ctc: size={size} but the input layer is {width} wide — "
            "size must be num_classes + 1 (the blank)")
    return fluid_layers.warpctc(input=input, label=label, blank=width - 1,
                                norm_by_times=norm_by_times)


def warp_ctc(input, label, size=None, blank=0, norm_by_times=False, **kw):
    """CTC with a selectable blank index (reference warp_ctc_layer:
    blank defaults to 0)."""
    _split_kw(kw, "warp_ctc")
    return fluid_layers.warpctc(input=input, label=label, blank=blank,
                                norm_by_times=norm_by_times)


def nce(input, label, num_classes, num_neg_samples=10, param_attr=None,
        bias_attr=None, **kw):
    """Noise-contrastive estimation head (reference nce_layer)."""
    _split_kw(kw, "nce")
    return fluid_layers.nce(input=input, label=label,
                            num_total_classes=num_classes,
                            num_neg_samples=num_neg_samples,
                            param_attr=_as_attr(param_attr),
                            bias_attr=_as_attr(bias_attr))


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             **kw):
    """Hierarchical sigmoid head (reference hsigmoid_layer)."""
    _split_kw(kw, "hsigmoid")
    return fluid_layers.hsigmoid(input=input, label=label,
                                 num_classes=num_classes,
                                 param_attr=_as_attr(param_attr),
                                 bias_attr=_as_attr(bias_attr))


def bilinear_interp(input, out_size_x, out_size_y, **kw):
    """Bilinear upsampling (reference bilinear_interp_layer)."""
    _split_kw(kw, "bilinear_interp")
    return fluid_layers.bilinear_interp(input, out_h=out_size_y,
                                        out_w=out_size_x)


def roi_pool(input, rois, pooled_width, pooled_height, spatial_scale=1.0,
             **kw):
    """(reference roi_pool_layer)."""
    _split_kw(kw, "roi_pool")
    return fluid_layers.roi_pool(input=input, rois=rois,
                                 pooled_height=pooled_height,
                                 pooled_width=pooled_width,
                                 spatial_scale=spatial_scale)


def interpolation(input, weight, **kw):
    """w*a + (1-w)*b with a per-row weight in [0,1] (reference
    interpolation_layer: input = [a, b], weight [N, 1])."""
    _split_kw(kw, "interpolation")
    a, b = input
    wa = fluid_layers.elementwise_mul(a, weight, axis=0)
    inv = fluid_layers.scale(weight, scale=-1.0, bias=1.0)   # 1 - w
    wb = fluid_layers.elementwise_mul(b, inv, axis=0)
    return fluid_layers.elementwise_add(wa, wb)


def power(input, weight, **kw):
    """x ** w elementwise with a per-row exponent (reference
    power_layer)."""
    _split_kw(kw, "power")
    return fluid_layers.elementwise_pow(input, weight, axis=0)


def scaling(input, weight, **kw):
    """Per-row scalar multiply (reference scaling_layer: weight [N, 1])."""
    _split_kw(kw, "scaling")
    return fluid_layers.elementwise_mul(input, weight, axis=0)


def repeat(input, num_repeats, **kw):
    """Tile features num_repeats times along the feature axis (reference
    repeat_layer)."""
    _split_kw(kw, "repeat")
    return fluid_layers.concat([input] * num_repeats, axis=-1)


def seq_reshape(input, reshape_size, **kw):
    """Reshape a sequence's step width (reference seq_reshape_layer)."""
    _split_kw(kw, "seq_reshape")
    return fluid_layers.sequence_reshape(input, new_dim=reshape_size)


def sampling_id(input, **kw):
    """Sample an id from each row's probability distribution (reference
    sampling_id_layer). Deterministic argmax fallback is NOT used — draws
    ride the program's PRNG stream via the uniform_random op. The count
    is clamped to num_classes-1: f32 cumsum can land slightly below 1.0
    (or rows may not sum to 1), and a draw above it would otherwise index
    one past the last class."""
    _split_kw(kw, "sampling_id")
    num_classes = int(input.shape[-1])
    u = fluid_layers.uniform_random_batch_size_like(input, shape=[-1, 1],
                                                    min=0.0, max=1.0)
    cum = fluid_layers.cumsum(input, axis=-1)
    hit = fluid_layers.cast(
        fluid_layers.less_than(cum, u), "float32")
    idx = fluid_layers.clip(fluid_layers.reduce_sum(hit, dim=-1),
                            0.0, float(num_classes - 1))
    return fluid_layers.cast(idx, "int64")


# --- costs -------------------------------------------------------------------

# --- projections + mixed -----------------------------------------------------
# The reference's mixed_layer sums "projections" (trainer_config_helpers/
# layers.py mixed_layer + *_projection). Here each projection applies
# immediately and returns a Variable; mixed() sums them (+ bias, + act) —
# the functional equivalent of the reference's `with mixed_layer() as m:
# m += proj` accumulation form.

def full_matrix_projection(input, size, param_attr=None, **kw):
    """W·x, no bias (reference full_matrix_projection)."""
    ignored = _split_kw(kw, "full_matrix_projection", init_ok=True)
    return fluid_layers.fc(input=input, size=size,
                           param_attr=_attr_with_init(param_attr, ignored),
                           bias_attr=False)


def trans_full_matrix_projection(input, size, param_attr=None, **kw):
    """W^T·x — the weight is created as [size, in] and used transposed so
    it can be SHARED with a forward projection (reference
    trans_full_matrix_projection)."""
    ignored = _split_kw(kw, "trans_full_matrix_projection", init_ok=True)
    attr = _attr_with_init(param_attr, ignored)
    in_dim = input.shape[-1]
    w = fluid_layers.create_parameter(shape=[size, in_dim],
                                      dtype=input.dtype,
                                      attr=attr)
    return fluid_layers.matmul(input, w, transpose_y=True)


def table_projection(input, size, param_attr=None, **kw):
    """Embedding-table lookup of integer ids (reference table_projection).
    Needs vocab_size= like embedding()."""
    vocab = kw.pop("vocab_size", None)
    ignored = _split_kw(kw, "table_projection", init_ok=True)
    if vocab is None:
        raise ValueError("table_projection needs vocab_size=")
    return fluid_layers.embedding(input=input, size=[vocab, size],
                                  param_attr=_attr_with_init(param_attr,
                                                             ignored))


def identity_projection(input, offset=None, size=None, **kw):
    """Pass-through, or a column slice [offset, offset+size) (reference
    identity_projection)."""
    _split_kw(kw, "identity_projection")
    if offset is None:
        return input
    if size is None:
        size = input.shape[-1] - offset
    total = input.shape[-1]
    sections = [s for s in (offset, size, total - offset - size) if s > 0]
    if len(sections) == 1:
        return input
    outs = fluid_layers.split(input, sections, dim=-1)
    return outs[1 if offset > 0 else 0]


def dotmul_projection(input, param_attr=None, **kw):
    """x ∘ w with a learned per-feature weight row (reference
    dotmul_projection)."""
    ignored = _split_kw(kw, "dotmul_projection", init_ok=True)
    w = fluid_layers.create_parameter(
        shape=[input.shape[-1]], dtype=input.dtype,
        attr=_attr_with_init(param_attr, ignored))
    return fluid_layers.elementwise_mul(input, w)


def scaling_projection(input, param_attr=None, **kw):
    """w·x with ONE learned scalar (reference scaling_projection)."""
    ignored = _split_kw(kw, "scaling_projection", init_ok=True)
    w = fluid_layers.create_parameter(
        shape=[1], dtype=input.dtype,
        attr=_attr_with_init(param_attr, ignored))
    return fluid_layers.elementwise_mul(input, w)


def context_projection(input, context_len, context_start=None,
                       padding_attr=False, **kw):
    """Window of neighboring timesteps concatenated on the feature axis
    (reference context_projection, trainer_config_helpers: default start
    centers the window at -(L-1)/2; boundaries zero-pad). Trainable
    boundary padding (padding_attr) is not carried — zero padding is the
    reference default."""
    _split_kw(kw, "context_projection")
    if padding_attr not in (False, None):
        import warnings
        warnings.warn("context_projection: trainable boundary padding is "
                      "not supported on this build; using zero padding",
                      stacklevel=2)
    return fluid_layers.context_project(input, context_len,
                                        context_start)


def gru_step(input, output_mem, size=None, act=None, gate_act=None,
             param_attr=None, bias_attr=None, name=None, **kw):
    """One GRU step for recurrent_group (reference gru_step_layer):
    `input` is the [B, 3H] x-projection, `output_mem` the previous hidden
    [B, H]; returns the new hidden (create with name= to pair with
    memory())."""
    ignored = _split_kw(kw, "gru_step", init_ok=True)
    size = size or output_mem.shape[-1]
    x, mem = input, output_mem
    lanes = None
    if len(x.shape) == 3:
        # beam_search lanes [B, K, *]: gru_unit computes on 2-D rows, so
        # flatten the lane axis through the step and restore it after
        if len(mem.shape) != 3:
            raise ValueError(
                "gru_step: 3-D (lane-shaped) input needs a 3-D "
                f"output_mem, got {tuple(mem.shape)} — expand the "
                "memory over the lanes (beam memory() does this)")
        lanes = mem.shape[1]
        x = fluid_layers.reshape(x, [-1, x.shape[-1]])
        mem = fluid_layers.reshape(mem, [-1, size])
    h, _reset, _gate = fluid_layers.gru_unit(
        x, mem, size * 3,
        param_attr=_attr_with_init(param_attr, ignored),
        bias_attr=_as_attr(bias_attr),
        activation=_act_name(act) or "tanh",
        gate_activation=_act_name(gate_act) or "sigmoid")
    if lanes is not None:
        h = fluid_layers.reshape(h, [-1, lanes, size])
    return _register_named(name, h)


gru_step_naive = gru_step   # reference exports both (same math here)


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, param_attr=None, **kw):
    """Convolution as a projection: no bias, no activation (reference
    conv_projection; bias/act come from the enclosing mixed())."""
    ignored = _split_kw(kw, "conv_projection", init_ok=True)
    return fluid_layers.conv2d(input=input, num_filters=num_filters,
                               filter_size=filter_size, stride=stride,
                               padding=padding, act=None,
                               param_attr=_attr_with_init(param_attr,
                                                          ignored),
                               bias_attr=False)


def conv_operator(img, filter=None, filter_size=None, num_filters=None,
                  num_channels=None, stride=1, padding=0, **kw):
    """Convolution inside mixed() (reference conv_operator: like
    conv_projection but positioned as a two-input operator). The reference
    convolves `img` with the `filter` layer's OUTPUT; here parameters are
    created internally like every projection, so a caller-supplied filter
    would be silently replaced by fresh weights — raise instead, per this
    module's raise-on-silent-drift policy."""
    if filter is not None:
        raise ValueError(
            "conv_operator: a `filter` input layer is not supported — the "
            "TPU port creates the convolution parameters internally "
            "(conv_projection semantics), so the supplied filter would be "
            "silently ignored and fresh weights trained in its place. "
            "Pass filter=None and use param_attr to control the weights.")
    _split_kw(kw, "conv_operator")
    return conv_projection(img, filter_size=filter_size,
                           num_filters=num_filters,
                           num_channels=num_channels, stride=stride,
                           padding=padding)


def slice_projection(input, slices, **kw):
    """Column slices concatenated (reference slice_projection:
    slices = [(start, end), ...])."""
    _split_kw(kw, "slice_projection")
    parts = [identity_projection(input, offset=s, size=e - s)
             for s, e in slices]
    if len(parts) == 1:
        return parts[0]
    return fluid_layers.concat(parts, axis=-1)


def img_conv3d(input, filter_size, num_filters, num_channels=None,
               stride=1, padding=0, act=None, param_attr=None,
               bias_attr=None, **kw):
    """Volumetric convolution (reference img_conv3d_layer over fluid
    conv3d)."""
    ignored = _split_kw(kw, "img_conv3d", init_ok=True)
    return fluid_layers.conv3d(input=input, num_filters=num_filters,
                               filter_size=filter_size, stride=stride,
                               padding=padding, act=_act_name(act),
                               param_attr=_attr_with_init(param_attr,
                                                          ignored),
                               bias_attr=_as_attr(bias_attr))


def img_pool3d(input, pool_size, stride=1, padding=0, pool_type="max",
               **kw):
    """Volumetric pooling (reference img_pool3d_layer over fluid
    pool3d)."""
    _split_kw(kw, "img_pool3d")
    return fluid_layers.pool3d(input=input, pool_size=pool_size,
                               pool_type=pool_name(pool_type),
                               pool_stride=stride, pool_padding=padding)


def priorbox(input, image, min_size, max_size=None, aspect_ratio=None,
             variance=None, **kw):
    """SSD prior boxes (reference priorbox_layer over the fluid
    detection stack's prior_box)."""
    _split_kw(kw, "priorbox")
    from ..layers import detection as det
    boxes, variances = det.prior_box(
        input, image, min_sizes=list(min_size),
        max_sizes=list(max_size) if max_size else None,
        aspect_ratios=list(aspect_ratio) if aspect_ratio else None,
        variance=list(variance) if variance else None)
    return boxes, variances


def mixed(size=None, input=None, act=None, bias_attr=None, name=None, **kw):
    """Sum of projections + bias + activation (reference mixed_layer).
    Functional form only: pass the applied projections as `input`
    (each *_projection here returns a Variable already)."""
    _split_kw(kw, "mixed")
    if not input:
        raise ValueError("mixed() needs input=[projection(...), ...]")
    inputs = input if isinstance(input, (list, tuple)) else [input]
    shape = inputs[0].shape
    if size is not None and shape is not None and all(
            d is not None and d > 0 for d in shape[1:]):
        # reference size = FLATTENED output width (conv projections emit
        # [N, C, H, W] whose size is C*H*W)
        width = 1
        for d in shape[1:]:
            width *= d
        if width != size:
            raise ValueError(
                f"mixed(size={size}) disagrees with its projections' "
                f"width {width} — the reference treats size as the "
                "output width, so this would silently change the model")
    out = inputs[0]
    for x in inputs[1:]:
        out = fluid_layers.elementwise_add(out, x)
    if bias_attr:
        from ..initializer import ConstantInitializer
        b = fluid_layers.create_parameter(
            shape=[out.shape[-1]], dtype=out.dtype,
            attr=_as_attr(bias_attr) if bias_attr is not True else None,
            default_initializer=ConstantInitializer(0.0))
        out = fluid_layers.elementwise_add(out, b)
    act = _act_name(act)
    if act is not None:
        out = getattr(fluid_layers, act)(out)
    return _register_named(name, out)


# --- matrix / elementwise layers ---------------------------------------------

def rotate(input, height, width, **kw):
    """Rotate each flattened [C, height, width] row 90° counter-clockwise
    (reference rotate_layer / gserver RotateLayer.cpp): out[c, W-1-w, h] =
    in[c, h, w], emitted as flattened [C*W*H]."""
    _split_kw(kw, "rotate")
    total = input.shape[-1]
    c = total // (height * width)
    x = fluid_layers.reshape(input, [-1, c, height, width])
    x = fluid_layers.transpose(x, [0, 1, 3, 2])        # [N, C, W, H]
    x = fluid_layers.reverse(x, axis=2)                # flip the W axis
    return fluid_layers.reshape(x, [-1, total])


def sum_to_one_norm(input, **kw):
    """Row-normalize so each row sums to 1 (reference
    sum_to_one_norm_layer)."""
    _split_kw(kw, "sum_to_one_norm")
    s = fluid_layers.reduce_sum(input, dim=-1, keep_dim=True)
    return fluid_layers.elementwise_div(input, s)


def row_l2_norm(input, **kw):
    """Row-normalize to unit L2 norm (reference row_l2_norm_layer)."""
    _split_kw(kw, "row_l2_norm")
    sq = fluid_layers.reduce_sum(
        fluid_layers.elementwise_mul(input, input), dim=-1, keep_dim=True)
    return fluid_layers.elementwise_div(input, fluid_layers.sqrt(sq))


def l2_distance(a, b, **kw):
    """Row-wise euclidean distance [N, 1] (reference l2_distance_layer)."""
    _split_kw(kw, "l2_distance")
    d = fluid_layers.elementwise_sub(a, b)
    sq = fluid_layers.reduce_sum(fluid_layers.elementwise_mul(d, d),
                                 dim=-1, keep_dim=True)
    return fluid_layers.sqrt(sq)


def dot_prod(a, b, **kw):
    """Row-wise dot product [N, 1] (reference dot_prod_layer)."""
    _split_kw(kw, "dot_prod")
    return fluid_layers.reduce_sum(fluid_layers.elementwise_mul(a, b),
                                   dim=-1, keep_dim=True)


def out_prod(a, b, **kw):
    """Row-wise outer product flattened to [N, da*db] (reference
    out_prod_layer)."""
    _split_kw(kw, "out_prod")
    da, db = a.shape[-1], b.shape[-1]
    prod = fluid_layers.matmul(fluid_layers.reshape(a, [-1, da, 1]),
                               fluid_layers.reshape(b, [-1, 1, db]))
    return fluid_layers.reshape(prod, [-1, da * db])


def linear_comb(weights, vectors, size, **kw):
    """out = sum_m w[:, m] * v[:, m, :], vectors given flattened
    [N, M*size] (reference linear_comb_layer / convex_comb_layer)."""
    _split_kw(kw, "linear_comb")
    m = vectors.shape[-1] // size
    v = fluid_layers.reshape(vectors, [-1, m, size])
    w = fluid_layers.reshape(weights, [-1, m, 1])
    return fluid_layers.reduce_sum(fluid_layers.elementwise_mul(v, w),
                                   dim=1)


convex_comb = linear_comb


def tensor(a, b, size, act=None, param_attr=None, bias_attr=None, **kw):
    """Bilinear tensor product: out_k = a^T W_k b for k < size (reference
    tensor_layer). W is stored [da, size*db]."""
    ignored = _split_kw(kw, "tensor", init_ok=True)
    da, db = a.shape[-1], b.shape[-1]
    w = fluid_layers.create_parameter(
        shape=[da, size * db], dtype=a.dtype,
        attr=_attr_with_init(param_attr, ignored))
    aw = fluid_layers.reshape(fluid_layers.matmul(a, w), [-1, size, db])
    out = fluid_layers.reduce_sum(
        fluid_layers.elementwise_mul(
            aw, fluid_layers.reshape(b, [-1, 1, db])), dim=2)
    if bias_attr:
        from ..initializer import ConstantInitializer
        bias = fluid_layers.create_parameter(
            shape=[size], dtype=a.dtype,
            attr=_as_attr(bias_attr) if bias_attr is not True else None,
            default_initializer=ConstantInitializer(0.0))
        out = fluid_layers.elementwise_add(out, bias)
    act = _act_name(act)
    if act is not None:
        out = getattr(fluid_layers, act)(out)
    return out


# --- misc layers -------------------------------------------------------------

def maxid(input, **kw):
    """Row argmax as int64 [N, 1] (reference maxid_layer)."""
    _split_kw(kw, "maxid")
    return fluid_layers.reshape(fluid_layers.argmax(input, axis=-1),
                                [-1, 1])


def clip(input, min, max, **kw):  # noqa: A002 - reference argument names
    """Elementwise clip (reference clip_layer)."""
    _split_kw(kw, "clip")
    return fluid_layers.clip(input, min=min, max=max)


def resize(input, size, **kw):
    """Reshape rows to [N*?, size] (reference resize_layer)."""
    _split_kw(kw, "resize")
    return fluid_layers.reshape(input, [-1, size])


def pad(input, pad_c=None, pad_h=None, pad_w=None, **kw):
    """Zero-pad a [N, C, H, W] image on channel/height/width (reference
    pad_layer)."""
    _split_kw(kw, "pad")
    pc = pad_c or [0, 0]
    ph = pad_h or [0, 0]
    pw = pad_w or [0, 0]
    return fluid_layers.pad(input, [0, 0] + list(pc) + list(ph) + list(pw))


def scale_shift(input, param_attr=None, bias_attr=None, **kw):
    """w·x + b with ONE learned scale and shift (reference
    scale_shift_layer)."""
    ignored = _split_kw(kw, "scale_shift", init_ok=True)
    w = fluid_layers.create_parameter(
        shape=[1], dtype=input.dtype,
        attr=_attr_with_init(param_attr, ignored))
    out = fluid_layers.elementwise_mul(input, w)
    from ..initializer import ConstantInitializer
    b = fluid_layers.create_parameter(
        shape=[1], dtype=input.dtype,
        attr=_as_attr(bias_attr),
        default_initializer=ConstantInitializer(0.0))
    return fluid_layers.elementwise_add(out, b)


def prelu(input, param_attr=None, **kw):
    """Parametric ReLU (reference prelu_layer)."""
    ignored = _split_kw(kw, "prelu", init_ok=True)
    return fluid_layers.prelu(input, mode="all",
                              param_attr=_attr_with_init(param_attr,
                                                         ignored))


def gated_unit(input, size, act=None, gate_param_attr=None,
               inproj_param_attr=None, **kw):
    """act(fc(x)) ∘ sigmoid(fc_gate(x)) (reference gated_unit_layer)."""
    ignored = _split_kw(kw, "gated_unit", init_ok=True)
    u = fluid_layers.fc(input=input, size=size, act=_act_name(act),
                        param_attr=_attr_with_init(inproj_param_attr,
                                                   ignored))
    g = fluid_layers.fc(input=input, size=size, act="sigmoid",
                        param_attr=_as_attr(gate_param_attr))
    return fluid_layers.elementwise_mul(u, g)


def factorization_machine(input, factor_size, param_attr=None, **kw):
    """Second-order FM interactions [N, 1]:
    0.5 * sum_f ((x·V)_f^2 - (x^2·V^2)_f) (reference
    factorization_machine)."""
    ignored = _split_kw(kw, "factorization_machine", init_ok=True)
    v = fluid_layers.create_parameter(
        shape=[input.shape[-1], factor_size], dtype=input.dtype,
        attr=_attr_with_init(param_attr, ignored))
    xv = fluid_layers.matmul(input, v)                        # [N, F]
    x2v2 = fluid_layers.matmul(
        fluid_layers.elementwise_mul(input, input),
        fluid_layers.elementwise_mul(v, v))                   # [N, F]
    diff = fluid_layers.elementwise_sub(
        fluid_layers.elementwise_mul(xv, xv), x2v2)
    return fluid_layers.scale(
        fluid_layers.reduce_sum(diff, dim=-1, keep_dim=True), scale=0.5)


def square_error_cost(input, label):
    return fluid_layers.mean(
        fluid_layers.square_error_cost(input=input, label=label))


mse_cost = square_error_cost


def classification_cost(input, label):
    """softmax + cross entropy on logits-or-probs: the v2 layer applied
    softmax itself, so `input` here is the pre-softmax fc output."""
    return fluid_layers.mean(fluid_layers.softmax_with_cross_entropy(
        logits=input, label=label))


def cross_entropy_cost(input, label):
    return fluid_layers.mean(
        fluid_layers.cross_entropy(input=input, label=label))


def rank_cost(left, right, label, **kw):
    """Pairwise RankNet cost (reference rank_cost): P = sigmoid(sl - sr),
    cross-entropied against the pair label (mq2007 pairwise training)."""
    _split_kw(kw, "rank_cost")
    diff = fluid_layers.elementwise_sub(left, right)
    return fluid_layers.mean(
        fluid_layers.sigmoid_cross_entropy_with_logits(x=diff, label=label))


def huber_regression_cost(input, label, delta=1.0, **kw):
    """Huber loss with knee at |d| = delta: 0.5 d^2 inside,
    delta*|d| - 0.5*delta^2 outside. Via the scaling identity
    huber(d, delta) = delta^2 * huber(d/delta, 1), where huber(., 1) is
    exactly smooth_l1 at sigma=1."""
    _split_kw(kw, "huber_regression_cost")
    delta = float(delta)
    unit = fluid_layers.smooth_l1(
        x=fluid_layers.scale(input, scale=1.0 / delta),
        y=fluid_layers.scale(label, scale=1.0 / delta), sigma=1.0)
    return fluid_layers.scale(fluid_layers.mean(unit), scale=delta * delta)


# --- second wrapper tranche (r5): remaining trainer_config_helpers tail --

def multiplex(input, index=None, **kw):
    """Row-wise select among N same-shaped inputs by per-row index
    (reference multiplex_layer: input[0] is the index column when index
    is not given separately, matching the legacy calling convention)."""
    _split_kw(kw, "multiplex")
    if index is None:
        index, *inputs = input
    else:
        inputs = list(input)
    return fluid_layers.multiplex(inputs, index)


def row_conv(input, context_len, act=None, param_attr=None, **kw):
    """Lookahead row convolution over a sequence (reference
    row_conv_layer; DeepSpeech2's streaming-friendly context).
    context_len COUNTS the current step (reference contract: the filter
    is [context_len, D]), so the fluid op's future_context_size is
    context_len - 1."""
    ignored = _split_kw(kw, "row_conv", init_ok=True)
    return fluid_layers.row_conv(input,
                                 future_context_size=context_len - 1,
                                 param_attr=_attr_with_init(param_attr,
                                                            ignored),
                                 act=_act_name(act))


def spp(input, pyramid_height=3, pool_type="max", **kw):
    """Spatial pyramid pooling over [N, C, H, W] (reference spp_layer)."""
    _split_kw(kw, "spp")
    return fluid_layers.spp(input, pyramid_height=pyramid_height,
                            pool_type=pool_name(pool_type))


def block_expand(input, block_x=1, block_y=1, stride_x=1, stride_y=1,
                 padding_x=0, padding_y=0, **kw):
    """im2col the [N, C, H, W] feature map into a sequence of flattened
    blocks (reference block_expand_layer — OCR's conv-to-sequence
    bridge; fluid grew the same op as im2sequence)."""
    _split_kw(kw, "block_expand")
    return fluid_layers.im2sequence(
        input, filter_size=[block_y, block_x],
        stride=[stride_y, stride_x],
        padding=[padding_y, padding_x])


def conv_shift(a, b, **kw):
    """Circular correlation of each row of a with the (odd-width) kernel
    row b (reference conv_shift_layer; NTM addressing)."""
    _split_kw(kw, "conv_shift")
    return fluid_layers.conv_shift(a, b)


def seq_slice(input, starts=None, ends=None, **kw):
    """Per-sequence slice [starts, ends) (reference seq_slice_layer:
    `ends` are END POSITIONS; the fluid op takes lengths, so lower as
    length = ends - starts). starts=None slices from 0; ends=None slices
    to each sequence's end (lengths recovered from the sequence mask)."""
    _split_kw(kw, "seq_slice")
    if starts is None and ends is None:
        return input
    if ends is None:
        seq_lens = fluid_layers.reduce_sum(
            fluid_layers.sequence_mask(input), dim=-1, keep_dim=True)
        ends = fluid_layers.cast(seq_lens, "int64")
    if starts is None:
        starts = fluid_layers.scale(ends, scale=0.0)  # zeros, same shape
    length = fluid_layers.elementwise_sub(ends, starts)
    return fluid_layers.sequence_slice(input, offset=starts,
                                       length=length)


def sub_seq(input, offsets, sizes, **kw):
    """Sub-sequence extraction (reference sub_seq_layer) — same lowering
    as seq_slice."""
    _split_kw(kw, "sub_seq")
    return fluid_layers.sequence_slice(input, offset=offsets, length=sizes)


def kmax_seq_score(input, beam_size=1, **kw):
    """Top-k score INDICES within each sequence (reference
    kmax_seq_score_layer: input is a [T, 1] score sequence; emits the k
    best positions per sequence). Padding steps are pushed to -1e30 via
    the sequence mask so they can never rank."""
    _split_kw(kw, "kmax_seq_score")
    flat = fluid_layers.reshape(input, [0, -1])          # [B, T]
    mask = fluid_layers.sequence_mask(input)             # [B, T] 1/0
    neg = fluid_layers.scale(
        fluid_layers.scale(mask, scale=-1.0, bias=1.0), scale=-1e30)
    masked = fluid_layers.elementwise_add(
        fluid_layers.elementwise_mul(flat, mask), neg)
    _vals, idx = fluid_layers.topk(masked, k=beam_size)
    return idx


def get_output(input, arg_name=None, **kw):
    """(reference get_output_layer) Layers here return their outputs
    directly (tuples for multi-output layers), so this is selection on an
    already-materialized tuple — kept for config compatibility."""
    _split_kw(kw, "get_output")
    if isinstance(input, (list, tuple)):
        if isinstance(arg_name, int):
            return input[arg_name]
        return input[0] if arg_name in (None, "out", "output") else input[1]
    return input


def cross_entropy_with_selfnorm(input, label, softmax_selfnorm_alpha=0.1,
                                **kw):
    """CE plus alpha * log(Z)^2 where Z is each row's probability mass —
    pushes the (already-softmaxed) rows toward self-normalization
    (reference cross_entropy_with_selfnorm)."""
    _split_kw(kw, "cross_entropy_with_selfnorm")
    ce = fluid_layers.cross_entropy(input=input, label=label)
    z = fluid_layers.reduce_sum(input, dim=-1, keep_dim=True)
    logz = fluid_layers.log(fluid_layers.clip(z, min=1e-12, max=1e12))
    penalty = fluid_layers.elementwise_mul(logz, logz)
    return fluid_layers.mean(
        fluid_layers.elementwise_add(
            ce, fluid_layers.scale(penalty,
                                   scale=float(softmax_selfnorm_alpha))))


def _two_pow_minus_one(x):
    """2^x - 1 (the NDCG gain) via exp(x ln 2)."""
    import math
    return fluid_layers.scale(
        fluid_layers.exp(fluid_layers.scale(x, scale=math.log(2.0))),
        bias=-1.0)


def _gt_mask(a, b):
    """float 1.0 where a > b (strict), via sign((a-b)) clamped to {0,1}:
    sign is -1/0/+1, so relu(sign) is exactly the strict-greater mask."""
    return fluid_layers.relu(
        fluid_layers.sign(fluid_layers.elementwise_sub(a, b)))


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, **kw):
    """LambdaRank listwise cost (reference lambda_cost_layer): pairwise
    logistic losses weighted by the |ΔNDCG@k| of swapping each pair in
    the ranking the predicted scores induce. input = predicted scores
    [N, L], score = relevance labels [N, L] (dense per-list rows; the
    reference consumed one LoD sequence per list). max_sort_size is
    accepted for signature parity — the dense form ranks the whole
    list."""
    import math

    import numpy as _np

    _split_kw(kw, "lambda_cost")
    pred, rel = input, score
    length = int(pred.shape[-1])
    k = min(NDCG_num, length)

    # ideal DCG@k per list: top-k relevances against 1/log2(rank+2)
    rel_sorted, _ = fluid_layers.topk(rel, k=k)
    discounts = fluid_layers.assign(
        (1.0 / _np.log2(_np.arange(2, k + 2))).astype(_np.float32))
    idcg = fluid_layers.reduce_sum(
        fluid_layers.elementwise_mul(_two_pow_minus_one(rel_sorted),
                                     discounts), dim=-1, keep_dim=True)
    idcg = fluid_layers.clip(idcg, min=1e-6, max=1e12)   # all-zero lists

    # predicted 0-based descending rank: rank_i = #{j : s_j > s_i}
    s_i = fluid_layers.unsqueeze(pred, axes=[2])         # [N, L, 1]
    s_j = fluid_layers.unsqueeze(pred, axes=[1])         # [N, 1, L]
    rank = fluid_layers.reduce_sum(_gt_mask(s_j, s_i), dim=-1)  # [N, L]

    # NDCG@k discount at each item's predicted rank (0 past position k)
    log2rank = fluid_layers.scale(
        fluid_layers.log(fluid_layers.scale(rank, bias=2.0)),
        scale=1.0 / math.log(2.0))
    inside_k = _gt_mask(fluid_layers.scale(rank, scale=0.0,
                                           bias=float(k)), rank)
    disc = fluid_layers.elementwise_div(inside_k, log2rank)  # [N, L]

    # |ΔNDCG| of swapping i and j
    gain = _two_pow_minus_one(rel)                       # [N, L]
    dg = fluid_layers.elementwise_sub(
        fluid_layers.unsqueeze(gain, axes=[2]),
        fluid_layers.unsqueeze(gain, axes=[1]))
    dd = fluid_layers.elementwise_sub(
        fluid_layers.unsqueeze(disc, axes=[2]),
        fluid_layers.unsqueeze(disc, axes=[1]))
    delta = fluid_layers.elementwise_div(
        fluid_layers.abs(fluid_layers.elementwise_mul(dg, dd)),
        fluid_layers.unsqueeze(idcg, axes=[2]))          # [N, L, L]

    # pairwise logistic loss log(1 + e^-(s_i - s_j)) for rel_i > rel_j,
    # in the overflow-safe softplus form relu(-d) + log(1 + e^-|d|)
    diff = fluid_layers.elementwise_sub(s_i, s_j)
    loglo = fluid_layers.elementwise_add(
        fluid_layers.relu(fluid_layers.scale(diff, scale=-1.0)),
        fluid_layers.log(fluid_layers.scale(
            fluid_layers.exp(fluid_layers.scale(fluid_layers.abs(diff),
                                                scale=-1.0)), bias=1.0)))
    pair_mask = _gt_mask(fluid_layers.unsqueeze(rel, axes=[2]),
                         fluid_layers.unsqueeze(rel, axes=[1]))
    weighted = fluid_layers.elementwise_mul(
        fluid_layers.elementwise_mul(loglo, delta), pair_mask)
    return fluid_layers.mean(fluid_layers.reduce_sum(
        fluid_layers.reduce_sum(weighted, dim=-1), dim=-1))


def crop(input, shape=None, offset=None, axis=2, **kw):
    """Crop to `shape` starting at `offset` (reference crop_layer; axis
    gives the first cropped dimension, earlier dims keep their extent —
    the fluid crop op takes full-rank shape/offsets, so fill the leading
    dims from the input)."""
    _split_kw(kw, "crop")
    if shape is None:
        raise ValueError(
            "crop() needs an explicit shape= (the reference's "
            "infer-from-second-input form is not supported; pass the "
            "target extents of the cropped dims)")
    in_shape = list(input.shape)
    full_shape = list(in_shape[:axis]) + list(shape)
    full_offset = [0] * axis + list(offset if offset is not None
                                    else [0] * len(shape))
    # leading batch extent may be dynamic (-1): the crop op keeps dims
    # whose target equals the input extent
    return fluid_layers.crop(input, shape=full_shape,
                             offsets=full_offset)


def switch_order(input, order, **kw):
    """Permute non-batch axes, e.g. [N, C, H, W] -> [N, H, W, C]
    (reference switch_order_layer's channel/spatial reorder; `order`
    lists the non-batch source axes 1-based from the input, reference
    reshape spec collapsed to its permutation)."""
    _split_kw(kw, "switch_order")
    perm = [0] + [int(a) for a in order]
    return fluid_layers.transpose(input, perm)


def printer(input, message=None, summarize=-1, **kw):
    """Execution-time tensor logging pass-through (reference
    printer_layer / print_layer over print_op.cc; fires each step, under
    jit via jax.debug.print)."""
    _split_kw(kw, "printer")
    return fluid_layers.Print(input, message=message, summarize=summarize)


print_ = printer   # reference exports both printer_layer and print_layer


def sum_cost(input, **kw):
    """Sum of every element of the input (reference sum_cost)."""
    _split_kw(kw, "sum_cost")
    return fluid_layers.reduce_sum(input)


def smooth_l1_cost(input, label, **kw):
    """Mean smooth-L1 between prediction and target rows (reference
    smooth_l1_cost)."""
    _split_kw(kw, "smooth_l1_cost")
    return fluid_layers.mean(fluid_layers.smooth_l1(x=input, y=label))


def multi_binary_label_cross_entropy(input, label, **kw):
    """Multi-label binary cross entropy on PROBABILITIES (the reference
    layer sits after a sigmoid activation): mean over rows of
    -sum_k [y log p + (1-y) log(1-p)] (reference
    multi_binary_label_cross_entropy)."""
    _split_kw(kw, "multi_binary_label_cross_entropy")
    eps = 1e-7
    p = fluid_layers.clip(input, min=eps, max=1.0 - eps)
    one_m_p = fluid_layers.scale(
        fluid_layers.scale(p, scale=-1.0), bias=1.0)
    one_m_y = fluid_layers.scale(
        fluid_layers.scale(label, scale=-1.0), bias=1.0)
    ce = fluid_layers.elementwise_add(
        fluid_layers.elementwise_mul(label, fluid_layers.log(p)),
        fluid_layers.elementwise_mul(one_m_y, fluid_layers.log(one_m_p)))
    return fluid_layers.scale(
        fluid_layers.mean(fluid_layers.reduce_sum(ce, dim=-1)), scale=-1.0)


def huber_classification_cost(input, label, **kw):
    """Smoothed hinge (reference huber_classification_cost): with
    y' = 2y-1 and z = y'·f, loss = 0 for z >= 1, (1-z)^2 for |z| < 1,
    -4z for z <= -1. Written as clip(1-z, 0, 2)^2 + 4·relu(-1-z), which
    matches all three regions continuously."""
    _split_kw(kw, "huber_classification_cost")
    y_signed = fluid_layers.scale(label, scale=2.0, bias=-1.0)
    z = fluid_layers.elementwise_mul(input, y_signed)
    one_m_z = fluid_layers.scale(z, scale=-1.0, bias=1.0)
    quad = fluid_layers.clip(one_m_z, min=0.0, max=2.0)
    lin = fluid_layers.relu(fluid_layers.scale(z, scale=-1.0, bias=-1.0))
    loss = fluid_layers.elementwise_add(
        fluid_layers.elementwise_mul(quad, quad),
        fluid_layers.scale(lin, scale=4.0))
    return fluid_layers.mean(loss)
