"""v2 layer DSL (reference: python/paddle/v2/layer.py + trainer_config_
helpers/layers.py wrappers). Each call builds fluid IR in the default
program; the returned Variables ARE the v2 "Layer" handles (the reference
wrapped config-proto nodes; here the IR is the config)."""

from __future__ import annotations

from .. import layers as fluid_layers
from .activation import _Act


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, _Act) or isinstance(act, type) and issubclass(act, _Act):
        return act.name
    return act


def data(name, type):
    """Input declaration (reference v2/layer data); type is a
    data_type.InputType."""
    if type.is_int:
        return fluid_layers.data(name=name, shape=[1], dtype="int64",
                                 lod_level=type.seq)
    return fluid_layers.data(name=name, shape=[type.dim], dtype="float32",
                             lod_level=type.seq)


def fc(input, size, act=None, **kw):
    return fluid_layers.fc(input=input, size=size, act=_act_name(act))


def embedding(input, size, **kw):
    """size = embedding dim (reference embedding_layer); the vocab extent
    comes from the data layer's integer_value range."""
    vocab = kw.pop("vocab_size", None)
    if vocab is None:
        vocab = kw.pop("input_range", None)
    if vocab is None:
        raise ValueError("embedding needs vocab_size= (the reference reads "
                         "it from the data layer's integer_value range)")
    return fluid_layers.embedding(input=input, size=[vocab, size])


def simple_lstm(input, size, **kw):
    """fc projection + LSTM (reference trainer_config_helpers simple_lstm =
    mixed+lstmemory); returns the hidden sequence."""
    proj = fluid_layers.fc(input=input, size=size * 4, num_flatten_dims=2)
    h, _c = fluid_layers.dynamic_lstm(input=proj, size=size * 4)
    return h


def last_seq(input):
    return fluid_layers.sequence_last_step(input)


def first_seq(input):
    return fluid_layers.sequence_first_step(input)


def max_pooling(input):
    return fluid_layers.sequence_pool(input, "max")


def sum_pooling(input):
    return fluid_layers.sequence_pool(input, "sum")


def concat(input):
    return fluid_layers.concat(input, axis=1)


def square_error_cost(input, label):
    return fluid_layers.mean(
        fluid_layers.square_error_cost(input=input, label=label))


def classification_cost(input, label):
    """softmax + cross entropy on logits-or-probs: the v2 layer applied
    softmax itself, so `input` here is the pre-softmax fc output."""
    return fluid_layers.mean(fluid_layers.softmax_with_cross_entropy(
        logits=input, label=label))


def cross_entropy_cost(input, label):
    return fluid_layers.mean(
        fluid_layers.cross_entropy(input=input, label=label))
