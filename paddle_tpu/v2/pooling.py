"""v2 pooling-type markers (reference: python/paddle/v2/pooling.py re-
exporting trainer_config_helpers/poolings.py classes). Passed as the
`pooling_type=` argument of v2 sequence pooling / networks wrappers; each
carries the fluid sequence_pool name it lowers to."""

from __future__ import annotations

__all__ = ["Max", "Avg", "Sum", "SqrtN", "CudnnMax", "CudnnAvg"]


class BasePoolingType:
    name = None

    def __init__(self):
        pass


class Max(BasePoolingType):
    name = "max"


class Avg(BasePoolingType):
    name = "average"


class Sum(BasePoolingType):
    name = "sum"


class SqrtN(BasePoolingType):
    """Sum scaled by 1/sqrt(len) (reference SqrtN for sequence bow)."""
    name = "sqrt"


# cudnn variants are spatial-pool markers in the reference; on TPU they
# alias the plain types (XLA owns the pooling implementation)
class CudnnMax(Max):
    pass


class CudnnAvg(Avg):
    pass


def pool_name(p) -> str:
    """Accept a class, an instance, or a plain string."""
    if isinstance(p, str):
        return p
    if isinstance(p, type) and issubclass(p, BasePoolingType):
        return p.name
    if isinstance(p, BasePoolingType):
        return p.name
    raise TypeError(f"not a pooling type: {p!r}")
