"""v2 optimizers (reference: python/paddle/v2/optimizer.py) delegating to
the fluid optimizer classes."""

from .. import optimizer as fluid_opt


class Optimizer:
    def __init__(self, fluid_optimizer):
        self.fluid_optimizer = fluid_optimizer


def Momentum(momentum=0.9, learning_rate=1e-3, **kw):
    return Optimizer(fluid_opt.Momentum(learning_rate=learning_rate,
                                        momentum=momentum))


def Adam(learning_rate=1e-3, beta1=0.9, beta2=0.999, **kw):
    return Optimizer(fluid_opt.Adam(learning_rate=learning_rate,
                                    beta1=beta1, beta2=beta2))


def SGD(learning_rate=1e-3, **kw):
    return Optimizer(fluid_opt.SGD(learning_rate=learning_rate))


def AdaGrad(learning_rate=1e-3, **kw):
    return Optimizer(fluid_opt.Adagrad(learning_rate=learning_rate))


def RMSProp(learning_rate=1e-3, **kw):
    return Optimizer(fluid_opt.RMSProp(learning_rate=learning_rate))
