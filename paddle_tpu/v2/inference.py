"""v2 inference (reference: python/paddle/v2/inference.py infer())."""

from __future__ import annotations

import numpy as np

from .. import DataFeeder, Executor, TPUPlace, io as fluid_io
from .. import executor as executor_mod
from ..framework.framework import default_startup_program


def infer(output_layer, parameters, input, feeding=None):
    """Run the topology up to output_layer with the given parameters over
    per-sample input tuples (reference inference.py:infer)."""
    program = output_layer.block.program
    infer_prog = fluid_io.get_inference_program([output_layer], program)
    block = infer_prog.global_block()
    exe = Executor(TPUPlace(0))
    scope = parameters._scope
    if scope is None:
        scope = executor_mod.Scope()
        parameters._scope = scope
        with executor_mod.scope_guard(scope):
            exe.run(default_startup_program())
    feeding = feeding or {}
    order = sorted(feeding, key=feeding.get)
    feed_vars = [block.var(n) for n in order]
    batch = [tuple(sample[feeding[n]] for n in order) for sample in input]
    feeder = DataFeeder(place=exe.place, feed_list=feed_vars)
    with executor_mod.scope_guard(scope):
        out, = exe.run(infer_prog, feed=feeder.feed(batch),
                       fetch_list=[output_layer.name])
    return np.asarray(out)
