"""Ploter: live train/test curve plotting (reference:
python/paddle/v2/plot/plot.py). Collects (step, value) series per title;
plot(path) renders a matplotlib figure to the file when matplotlib is
available; pathless plot() prints text sparklines (the headless Agg
backend cannot open a window). DISABLE_PLOT=True turns plot() into a
no-op like the reference; the data side keeps working either way."""

from __future__ import annotations

import os

__all__ = ["Ploter", "PlotData"]


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values, width=60):
    vals = values[-width:]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(_SPARK[int((v - lo) / span * (len(_SPARK) - 1))]
                   for v in vals)


class Ploter:
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {title: PlotData() for title in args}
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT")
        self.plt = None
        if not self.__plot_is_disabled__():
            try:
                import matplotlib
                matplotlib.use("Agg")          # headless-safe backend
                import matplotlib.pyplot as plt
                self.plt = plt
            except Exception:                  # text fallback below
                self.plt = None

    def __plot_is_disabled__(self):
        return self.__disable_plot__ == "True"

    def append(self, title, step, value):
        assert isinstance(title, str)
        assert title in self.__plot_data__
        self.__plot_data__[title].append(step, float(value))

    def plot(self, path=None):
        if self.__plot_is_disabled__():
            return
        titles = [t for t in self.__args__
                  if self.__plot_data__[t].step]
        if self.plt is not None and path is not None:
            for title in titles:
                data = self.__plot_data__[title]
                self.plt.plot(data.step, data.value)
            self.plt.legend(titles, loc="upper left")
            self.plt.savefig(path)
            self.plt.gcf().clear()
            return
        # pathless (terminal) display, or no matplotlib: text sparklines —
        # the Agg backend can't show a window, so the data must reach the
        # user some other way
        lines = []
        for title in titles:
            data = self.__plot_data__[title]
            lines.append(f"{title}: {_sparkline(data.value)} "
                         f"(last {data.value[-1]:.6g} "
                         f"@ step {data.step[-1]})")
        text = "\n".join(lines)
        if path is not None:
            with open(path, "w") as f:
                f.write(text + "\n")
        else:
            print(text)

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
