"""v2 input type declarations (reference: python/paddle/trainer/
PyDataProvider2.py dense_vector :?, integer_value :226,
integer_value_sequence :236; re-exported as paddle.v2.data_type)."""


class InputType:
    def __init__(self, dim, seq=0, is_int=False):
        self.dim = dim
        self.seq = seq          # 0 = no sequence, 1 = sequence
        self.is_int = is_int


def dense_vector(dim):
    return InputType(dim)


def dense_vector_sequence(dim):
    return InputType(dim, seq=1)


def integer_value(value_range):
    return InputType(value_range, is_int=True)


def integer_value_sequence(value_range):
    return InputType(value_range, seq=1, is_int=True)
