"""v2-style user API facade (reference: python/paddle/v2 — layer.py/
topology.py graph building, trainer.py:37 SGD event loop, parameters.py
numpy get/set + tar serialization, event.py callbacks, inference.py).

The reference v2 stack compiled its own ModelConfig proto and drove the
legacy C++ GradientMachine through SWIG; here the same USER SURFACE builds
fluid Programs underneath — one stack, two API skins, exactly how the
reference's book examples moved from v2 to fluid without retraining users.
"""

from . import (activation, data_type, evaluator, event, image, layer,
               networks, optimizer, parameters, plot, pooling)
from .inference import infer
from .trainer import SGD

# the aliases every reference v2 script leans on:
#   paddle.init(use_gpu=False, trainer_count=1)
#   paddle.batch(paddle.reader.shuffle(paddle.dataset.mnist.train(), ...))
from .. import dataset, reader
from ..minibatch import batch

_init_kwargs = {}


def init(**kwargs):
    """Runtime bring-up (reference paddle.init -> swig initPaddle +
    gflags). The XLA stack needs no explicit initialization — device
    selection happens at Executor construction, and TPUPlace falls back
    to CPU when no accelerator exists — so this records the flags for
    introspection and validates the ones with no analogue here."""
    _init_kwargs.update(kwargs)
    tc = int(kwargs.get("trainer_count", 1) or 1)
    if tc > 1:
        import warnings
        warnings.warn(
            "paddle.init(trainer_count>1): the v2 multi-thread trainer "
            "is subsumed by SPMD sharding — tag the program with a mesh "
            "(see paddle_tpu.parallel) instead; running single-replica.",
            stacklevel=2)


__all__ = ["activation", "data_type", "evaluator", "event", "image",
           "layer", "networks", "optimizer", "parameters", "plot",
           "pooling", "infer", "SGD", "dataset", "reader", "batch", "init"]
