"""v2-style user API facade (reference: python/paddle/v2 — layer.py/
topology.py graph building, trainer.py:37 SGD event loop, parameters.py
numpy get/set + tar serialization, event.py callbacks, inference.py).

The reference v2 stack compiled its own ModelConfig proto and drove the
legacy C++ GradientMachine through SWIG; here the same USER SURFACE builds
fluid Programs underneath — one stack, two API skins, exactly how the
reference's book examples moved from v2 to fluid without retraining users.
"""

from . import (activation, data_type, evaluator, event, image, layer,
               networks, optimizer, parameters, pooling)
from .inference import infer
from .trainer import SGD

# the aliases every reference v2 script leans on:
#   paddle.batch(paddle.reader.shuffle(paddle.dataset.mnist.train(), ...))
from .. import dataset, reader
from ..minibatch import batch

__all__ = ["activation", "data_type", "evaluator", "event", "image",
           "layer", "networks", "optimizer", "parameters", "pooling",
           "infer", "SGD", "dataset", "reader", "batch"]
