"""v2 activation objects (reference: python/paddle/trainer_config_helpers/
activations.py): Relu()/Tanh()/... map onto the fluid act strings."""


class _Act:
    name = None


class Linear(_Act):
    name = None


class Relu(_Act):
    name = "relu"


class Tanh(_Act):
    name = "tanh"


class Sigmoid(_Act):
    name = "sigmoid"


class Softmax(_Act):
    name = "softmax"
