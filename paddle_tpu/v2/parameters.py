"""v2 Parameters: numpy get/set + tar serialization (reference:
python/paddle/v2/parameters.py — __getitem__/__setitem__ over the
GradientMachine's buffers, to_tar/from_tar per-param files)."""

from __future__ import annotations

import io
import tarfile

import numpy as np


class Parameters:
    def __init__(self, program):
        self._program = program
        self._scope = None      # shared with trainer.SGD / inference.infer

    # --- topology ----------------------------------------------------------
    def names(self):
        return sorted(p.name for p in
                      self._program.global_block().all_parameters())

    def _bound(self):
        """Scope holding the parameter values; created lazily by running
        the startup program (so the reference's save-in-one-process /
        from_tar-then-infer-in-another flow works without a trainer)."""
        if self._scope is None:
            from .. import Executor, TPUPlace
            from .. import executor as executor_mod
            from ..framework.framework import default_startup_program
            self._scope = executor_mod.Scope()
            with executor_mod.scope_guard(self._scope):
                Executor(TPUPlace(0)).run(default_startup_program())
        return self._scope

    def __getitem__(self, name):
        return np.asarray(self._bound().find_var(name))

    def __setitem__(self, name, value):
        self._bound().set_var(name, np.asarray(value, np.float32))

    def keys(self):
        return self.names()

    # --- serialization (reference to_tar/from_tar) -------------------------
    def to_tar(self, f):
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name in self.names():
                buf = io.BytesIO()
                np.save(buf, self[name])
                data = buf.getvalue()
                info = tarfile.TarInfo(name=name)
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))

    def from_tar(self, f):
        with tarfile.open(fileobj=f, mode="r") as tar:
            for member in tar.getmembers():
                arr = np.load(io.BytesIO(tar.extractfile(member).read()))
                self[member.name] = arr


def create(cost):
    """Parameters of the topology that produces `cost` (reference
    parameters.create)."""
    return Parameters(cost.block.program)
