"""v2 composite networks (reference: python/paddle/v2/networks.py exposing
trainer_config_helpers/networks.py — simple_img_conv_pool, img_conv_group,
vgg_16_network, sequence_conv_pool, simple_lstm, bidirectional_lstm,
simple_gru). Thin v2-flavored fronts over the fluid nets/layers tier, so a
reference v2 script's network calls translate one-to-one."""

from __future__ import annotations

from .. import layers as fluid_layers
from .. import nets as fluid_nets
from .activation import _Act
from .pooling import pool_name

__all__ = ["simple_img_conv_pool", "img_conv_group", "vgg_16_network",
           "sequence_conv_pool", "simple_lstm", "bidirectional_lstm",
           "simple_gru", "bidirectional_gru", "simple_attention"]


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, _Act) or (isinstance(act, type)
                                 and issubclass(act, _Act)):
        return act.name
    return act


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, pool_type="max", **kw):
    """conv2d + pool2d (reference networks.py simple_img_conv_pool; the
    recognize_digits conv config uses exactly this)."""
    return fluid_nets.simple_img_conv_pool(
        input=input, num_filters=num_filters, filter_size=filter_size,
        pool_size=pool_size, pool_stride=pool_stride,
        act=_act_name(act), pool_type=pool_name(pool_type))


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, conv_with_batchnorm=False,
                   pool_stride=1, pool_type="max", **kw):
    """N convs (+optional BN) then one pool — the VGG block (reference
    networks.py img_conv_group)."""
    return fluid_nets.img_conv_group(
        input=input, conv_num_filter=conv_num_filter, pool_size=pool_size,
        conv_padding=conv_padding, conv_filter_size=conv_filter_size,
        conv_act=_act_name(conv_act),
        conv_with_batchnorm=conv_with_batchnorm,
        pool_stride=pool_stride, pool_type=pool_name(pool_type))


def vgg_16_network(input_image, num_channels=3, num_classes=1000):
    """The classic VGG-16 stack (reference networks.py vgg_16_network);
    returns softmax probabilities like the reference config did."""
    from ..models import vgg16
    logits = vgg16(input_image, class_dim=num_classes)
    return fluid_layers.softmax(logits)


def sequence_conv_pool(input, context_len, hidden_size, act=None,
                       pool_type="max", **kw):
    """Context-window conv over a sequence + pooling (reference
    networks.py sequence_conv_pool; text_conv configs)."""
    return fluid_nets.sequence_conv_pool(
        input=input, num_filters=hidden_size, filter_size=context_len,
        act=_act_name(act) or "tanh", pool_type=pool_name(pool_type))


def simple_lstm(input, size, act=None, **kw):
    """fc(4*size) projection + LSTM; returns the hidden sequence
    (reference networks.py simple_lstm = mixed + lstmemory)."""
    proj = fluid_layers.fc(input=input, size=size * 4, num_flatten_dims=2)
    h, _c = fluid_layers.dynamic_lstm(input=proj, size=size * 4)
    return h


def bidirectional_lstm(input, size, return_unmerged=False, **kw):
    """Forward + backward LSTM over the sequence, concatenated on the
    feature axis (reference networks.py bidirectional_lstm)."""
    fw_proj = fluid_layers.fc(input=input, size=size * 4, num_flatten_dims=2)
    fw, _ = fluid_layers.dynamic_lstm(input=fw_proj, size=size * 4)
    bw_proj = fluid_layers.fc(input=input, size=size * 4, num_flatten_dims=2)
    bw, _ = fluid_layers.dynamic_lstm(input=bw_proj, size=size * 4,
                                      is_reverse=True)
    if return_unmerged:
        return fw, bw
    return fluid_layers.concat([fw, bw], axis=-1)


def simple_gru(input, size, act=None, **kw):
    """fc(3*size) projection + GRU; returns the hidden sequence
    (reference networks.py simple_gru)."""
    proj = fluid_layers.fc(input=input, size=size * 3, num_flatten_dims=2)
    return fluid_layers.dynamic_gru(input=proj, size=size)


def bidirectional_gru(input, size, return_unmerged=False, **kw):
    """Forward + backward GRU over the sequence, concatenated on the
    feature axis (reference networks.py bidirectional_gru)."""
    from .layer import _split_kw
    _split_kw(kw, "bidirectional_gru")
    fw_proj = fluid_layers.fc(input=input, size=size * 3,
                              num_flatten_dims=2)
    fw = fluid_layers.dynamic_gru(input=fw_proj, size=size)
    bw_proj = fluid_layers.fc(input=input, size=size * 3,
                              num_flatten_dims=2)
    bw = fluid_layers.dynamic_gru(input=bw_proj, size=size,
                                  is_reverse=True)
    if return_unmerged:
        return fw, bw
    return fluid_layers.concat([fw, bw], axis=-1)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     **kw):
    """Additive (Bahdanau) attention context vector (reference
    networks.py:1400 simple_attention): score_j = v·tanh(W·s + U·h_j)
    with U·h_j precomputed as encoded_proj; softmax over the sequence;
    context = sum_j a_j h_j. decoder_state is per-batch-row [N, H]; the
    encoded inputs are sequences."""
    from .layer import _as_attr as _attr
    from .layer import _split_kw
    _split_kw(kw, "simple_attention")

    proj_size = encoded_proj.shape[-1]
    transform = fluid_layers.fc(input=decoder_state, size=proj_size,
                                bias_attr=False,
                                param_attr=_attr(transform_param_attr))
    expanded = fluid_layers.sequence_expand(x=transform,
                                            y=encoded_sequence)
    combined = fluid_layers.tanh(
        fluid_layers.elementwise_add(expanded, encoded_proj))
    score = fluid_layers.fc(input=combined, size=1, bias_attr=False,
                            num_flatten_dims=2,
                            param_attr=_attr(softmax_param_attr))
    weights = fluid_layers.sequence_softmax(score)       # [B, T, 1]
    scaled = fluid_layers.elementwise_mul(encoded_sequence, weights)
    return fluid_layers.sequence_pool(scaled, "sum")
