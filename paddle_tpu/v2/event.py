"""Training events (reference: python/paddle/v2/event.py:58-101)."""


class BeginPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class BeginIteration:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration:
    def __init__(self, pass_id, batch_id, cost, metrics=None):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        self.metrics = metrics or {}


class TestResult:
    """Result of SGD.test (reference event.py TestResult: sample-weighted
    mean cost over the test stream)."""

    def __init__(self, cost, num_samples):
        self.cost = cost
        self.num_samples = num_samples
