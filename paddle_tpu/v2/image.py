"""v2 image augmentation (reference: python/paddle/v2/image.py — cv2-based
load/resize/crop/flip/transform helpers feeding the image pipelines).

Pure-numpy reimplementation: this environment (and many TPU hosts) has no
cv2, and none of these transforms need it — bilinear resize is a gather +
lerp, crops are slices. Images are HWC uint8/float arrays; `simple_transform`
mirrors the reference's train/test pipeline contract (resize short edge →
center/random crop → optional flip → CHW float → optional mean subtract).
"""

from __future__ import annotations

import numpy as np

__all__ = ["resize_short", "to_chw", "center_crop", "random_crop",
           "left_right_flip", "simple_transform", "load_and_transform",
           "batch_images"]


def _resize_bilinear(im: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize of an HWC (or HW) array without cv2."""
    im2d = im[:, :, None] if im.ndim == 2 else im
    ih, iw, c = im2d.shape
    if (ih, iw) == (h, w):
        out = im2d
    else:
        # sample positions in source coordinates (align_corners=False)
        ys = (np.arange(h) + 0.5) * ih / h - 0.5
        xs = (np.arange(w) + 0.5) * iw / w - 0.5
        y0 = np.clip(np.floor(ys).astype(int), 0, ih - 1)
        x0 = np.clip(np.floor(xs).astype(int), 0, iw - 1)
        y1 = np.clip(y0 + 1, 0, ih - 1)
        x1 = np.clip(x0 + 1, 0, iw - 1)
        wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
        wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
        f = im2d.astype(np.float32)
        top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
        bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
        out = top * (1 - wy) + bot * wy
        if np.issubdtype(im.dtype, np.integer):
            out = np.clip(np.rint(out), 0, 255).astype(im.dtype)
        else:
            out = out.astype(im.dtype)
    return out[:, :, 0] if im.ndim == 2 else out


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Resize so the SHORT edge equals `size`, keeping aspect ratio
    (reference image.py resize_short)."""
    h, w = im.shape[:2]
    if h < w:
        return _resize_bilinear(im, size, int(round(w * size / h)))
    return _resize_bilinear(im, int(round(h * size / w)), size)


def to_chw(im: np.ndarray, order=(2, 0, 1)) -> np.ndarray:
    """HWC -> CHW (reference to_chw)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im: np.ndarray, size: int, is_color=True) -> np.ndarray:
    h, w = im.shape[:2]
    h0, w0 = (h - size) // 2, (w - size) // 2
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im: np.ndarray, size: int, is_color=True,
                rng: np.random.RandomState = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    h0 = rng.randint(0, h - size + 1)
    w0 = rng.randint(0, w - size + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im: np.ndarray, is_color=True) -> np.ndarray:
    return im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color=True, mean=None,
                     rng: np.random.RandomState = None) -> np.ndarray:
    """The reference's canonical pipeline (image.py simple_transform):
    resize short edge, then random crop + coin-flip mirror when training /
    center crop when testing, HWC->CHW float32, optional mean subtraction
    (scalar, per-channel, or full-element mean array)."""
    rng = rng or np.random
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if rng.randint(0, 2) == 1:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 2:
        im = im[:, :, None]
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, dtype=np.float32)
        if mean.ndim == 1:
            mean = mean[:, None, None]      # per-channel
        im -= mean
    return im


def load_and_transform(filename: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color=True, mean=None):
    """File loader + simple_transform. Supports .npy arrays natively; PNG
    and JPEG decode requires PIL if available (cv2-free)."""
    if filename.endswith(".npy"):
        im = np.load(filename)
    else:
        try:
            from PIL import Image  # optional; not a hard dependency
        except ImportError as e:
            raise RuntimeError(
                "image decode needs PIL (or pre-decoded .npy arrays); "
                "cv2 is deliberately not a dependency") from e
        im = np.asarray(Image.open(filename))
    return simple_transform(im, resize_size, crop_size, is_train,
                            is_color=is_color, mean=mean)


def batch_images(images) -> np.ndarray:
    """Stack a list of CHW images into an NCHW batch."""
    return np.stack([np.asarray(im) for im in images], axis=0)
