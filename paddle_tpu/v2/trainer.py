"""v2 SGD trainer event loop (reference: python/paddle/v2/trainer.py:37
SGD, :137 train — reader + topology + update_equation, event_handler
callbacks per iteration/pass)."""

from __future__ import annotations

import numpy as np

from .. import DataFeeder, Executor, TPUPlace
from .. import executor as executor_mod
from ..framework.framework import default_startup_program
from . import event as v2_event
from .optimizer import Optimizer
from .parameters import Parameters


class SGD:
    """cost + parameters + update_equation -> .train(reader, ...)
    (reference trainer.py SGD; the name is historical — any v2 optimizer
    is accepted)."""

    def __init__(self, cost, parameters, update_equation, extra_layers=None,
                 is_local=True, **kw):
        if not isinstance(parameters, Parameters):
            raise TypeError("parameters should be parameters")
        if not isinstance(update_equation, Optimizer):
            raise TypeError("update equation parameter must be "
                            "paddle_tpu.v2.optimizer.Optimizer")
        self.__cost__ = cost
        self.__parameters__ = parameters
        self.__program__ = cost.block.program
        self.__test_program__ = None   # built lazily by test(), cached
        update_equation.fluid_optimizer.minimize(cost)
        self.__exe__ = Executor(TPUPlace(0))
        if parameters._scope is not None:
            # parameters pre-bound (e.g. from_tar before training): keep
            # their values across the startup run, which re-initializes
            # every parameter (reference SGD keeps the Parameters buffers)
            self.__scope__ = parameters._scope
            preloaded = {n: parameters[n].copy()
                         for n in parameters.names()
                         if self.__scope__.find_var(n) is not None}
        else:
            self.__scope__ = executor_mod.Scope()
            parameters._scope = self.__scope__
            preloaded = {}
        with executor_mod.scope_guard(self.__scope__):
            self.__exe__.run(default_startup_program())
        for n, val in preloaded.items():
            parameters[n] = val

    def _feeding_setup(self, feeding, who):
        """(feeder, reorder) shared by train/test — feeding maps
        data-layer name -> sample tuple position."""
        if not feeding:
            raise ValueError(f"v2 SGD.{who} needs feeding="
                             "{name: position}")
        block = self.__program__.global_block()
        order = sorted(feeding, key=feeding.get)
        feed_vars = [block.var(n) for n in order]
        feeder = DataFeeder(place=self.__exe__.place, feed_list=feed_vars)

        def reorder(batch):
            return [tuple(sample[feeding[n]] for n in order)
                    for sample in batch]

        return feeder, reorder

    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        """reader yields per-sample tuples; feeding maps data-layer name ->
        tuple position (reference trainer.py:137)."""
        event_handler = event_handler or (lambda e: None)
        feeder, reorder = self._feeding_setup(feeding, "train")
        with executor_mod.scope_guard(self.__scope__):
            for pass_id in range(num_passes):
                event_handler(v2_event.BeginPass(pass_id))
                for batch_id, batch in enumerate(reader()):
                    batch = reorder(batch)
                    event_handler(v2_event.BeginIteration(pass_id, batch_id))
                    cost_v, = self.__exe__.run(
                        self.__program__, feed=feeder.feed(batch),
                        fetch_list=[self.__cost__])
                    event_handler(v2_event.EndIteration(
                        pass_id, batch_id, float(np.ravel(cost_v)[0])))
                event_handler(v2_event.EndPass(pass_id))

    def test(self, reader, feeding=None):
        """Forward-only evaluation over a batch reader; returns a
        TestResult with the sample-weighted mean cost (reference
        trainer.py:217 test — PASS_TEST forward, summed costs)."""
        feeder, reorder = self._feeding_setup(feeding, "test")
        if self.__test_program__ is None:
            # strip + prune + clone(for_test=True): evaluation must not
            # apply dropout masks, use batch-norm batch statistics, or
            # write anything back; cached so repeated test() calls reuse
            # one compiled program (the executor cache keys on identity)
            from .. import io as io_mod
            self.__test_program__ = io_mod.get_inference_program(
                [self.__cost__], self.__program__)
        total_cost, num_samples = 0.0, 0
        with executor_mod.scope_guard(self.__scope__):
            for batch in reader():
                batch = reorder(batch)
                cost_v, = self.__exe__.run(
                    self.__test_program__, feed=feeder.feed(batch),
                    fetch_list=[self.__cost__])
                total_cost += float(np.ravel(cost_v)[0]) * len(batch)
                num_samples += len(batch)
        if num_samples == 0:
            raise ValueError(
                "SGD.test consumed no samples — is the reader a one-shot "
                "generator that was already exhausted? Pass a factory "
                "yielding fresh batches per call.")
        return v2_event.TestResult(cost=total_cost / num_samples,
                                   num_samples=num_samples)

    def save_parameter_to_tar(self, f):
        self.__parameters__.to_tar(f)
