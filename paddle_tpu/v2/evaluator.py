"""v2 evaluator shims (reference: python/paddle/v2/evaluator.py exposing
trainer_config_helpers/evaluators.py — classification_error_evaluator,
auc_evaluator, ... wired into the topology). Here each call appends the
corresponding fluid metric op to the default program and returns the
metric Variable — fetch it alongside the cost to monitor it, which is
exactly how the v2 trainer surfaced evaluator values in events."""

from __future__ import annotations

from .. import layers as fluid_layers

__all__ = ["classification_error", "auc"]


def classification_error(input, label, name=None):
    """Fraction misclassified = 1 - accuracy (reference
    classification_error_evaluator). `input` is the prediction
    (post-softmax or logits; argmax is rank-invariant)."""
    acc = fluid_layers.accuracy(input=input, label=label)
    one = fluid_layers.fill_constant(shape=[1], dtype=acc.dtype, value=1.0)
    return fluid_layers.elementwise_sub(one, acc)


def auc(input, label, name=None):
    """Area under ROC (reference auc_evaluator; fluid auc op)."""
    return fluid_layers.auc(input=input, label=label)
