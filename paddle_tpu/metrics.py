"""Stateful Python-side metric aggregation (reference:
python/paddle/fluid/evaluator.py + metrics). Accumulates numpy values across
minibatches; graph-side per-batch metrics come from layers.accuracy/auc."""

from __future__ import annotations

import numpy as np

__all__ = ["Accuracy", "ChunkEvaluator", "EditDistance", "CompositeMetric",
           "DetectionMAP"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no minibatch accumulated")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Chunk F1 aggregation (reference evaluator.py:111 ChunkEvaluator)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        def _sc(x):
            return int(np.asarray(x).reshape(-1)[0])
        self.num_infer_chunks += _sc(num_infer_chunks)
        self.num_label_chunks += _sc(num_label_chunks)
        self.num_correct_chunks += _sc(num_correct_chunks)

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(distances.sum())
        self.seq_num += int(np.asarray(seq_num).reshape(-1)[0])
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data accumulated")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, *args):
        for m, a in zip(self._metrics, args):
            m.update(*a)

    def eval(self):
        return [m.eval() for m in self._metrics]


class DetectionMAP(MetricBase):
    """Accumulative detection mAP across minibatches (reference
    evaluator.py:254 DetectionMAP). The reference threads growing
    pos-count/true-pos/false-pos state tensors through a stateful
    detection_map op; state tensors grow per batch, which XLA's static
    shapes reject, so the TPU-native evaluator accumulates the raw
    detections/ground-truths host-side and computes the running mAP with
    the same kernel the in-graph per-batch metric uses
    (ops/detection_ops.py detection_map_np).

    update(dets, det_counts, gts, gt_counts): padded [B,D,6]/[B,G,6]
    batches + per-sample valid counts (the fetched form of the
    detection_map op's inputs). eval() -> accumulative mAP over every
    batch seen since reset().
    """

    def __init__(self, class_num=None, background_label=0,
                 overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral", name=None):
        super().__init__(name)
        self.background_label = background_label
        self.overlap_threshold = overlap_threshold
        self.evaluate_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        self._label_pos = {}
        self._tp = {}
        self._fp = {}
        self._n_updates = 0

    def update(self, dets, det_counts, gts, gt_counts):
        # incremental per-class contribution merge: per-image (score, tp/fp)
        # pairs are independent, so a running mAP over N batches costs O(N)
        # instead of recomputing over the full history each eval()
        from .ops.detection_ops import detection_tp_fp
        lp, tp, fp = detection_tp_fp(
            np.asarray(dets, np.float32), np.asarray(det_counts, np.int64),
            np.asarray(gts, np.float32), np.asarray(gt_counts, np.int64),
            self.overlap_threshold, self.evaluate_difficult)
        for k, v in lp.items():
            self._label_pos[k] = self._label_pos.get(k, 0) + v
        for k, v in tp.items():
            self._tp.setdefault(k, []).extend(v)
        for k, v in fp.items():
            self._fp.setdefault(k, []).extend(v)
        self._n_updates += 1

    def eval(self):
        if not self._n_updates:
            raise ValueError("DetectionMAP.eval() before any update()")
        from .ops.detection_ops import map_from_tp_fp
        return float(map_from_tp_fp(
            self._label_pos, self._tp, self._fp, self.ap_version,
            self.background_label))
