"""Stateful Python-side metric aggregation (reference:
python/paddle/fluid/evaluator.py + metrics). Accumulates numpy values across
minibatches; graph-side per-batch metrics come from layers.accuracy/auc."""

from __future__ import annotations

import numpy as np

__all__ = ["Accuracy", "ChunkEvaluator", "EditDistance", "CompositeMetric"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no minibatch accumulated")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Chunk F1 aggregation (reference evaluator.py:111 ChunkEvaluator)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        def _sc(x):
            return int(np.asarray(x).reshape(-1)[0])
        self.num_infer_chunks += _sc(num_infer_chunks)
        self.num_label_chunks += _sc(num_label_chunks)
        self.num_correct_chunks += _sc(num_correct_chunks)

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(distances.sum())
        self.seq_num += int(np.asarray(seq_num).reshape(-1)[0])
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data accumulated")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, *args):
        for m, a in zip(self._metrics, args):
            m.update(*a)

    def eval(self):
        return [m.eval() for m in self._metrics]
