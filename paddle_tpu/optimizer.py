"""Optimizer classes emitting optimizer ops into the program
(reference: python/paddle/fluid/optimizer.py:34 Optimizer, :250 SGD,
:276 Momentum, :320 Adagrad, :361 Adam, :466 Adamax, :550 DecayedAdagrad,
:594 Adadelta, :676 RMSProp, :811 ModelAverage)."""

from __future__ import annotations

from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from .backward import append_backward
from .framework import unique_name
from .framework.framework import (Parameter, Program, Variable,
                                  default_main_program,
                                  default_startup_program, program_guard)
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops
from .clip import append_gradient_clip_ops, error_clip_callback

__all__ = [
    "SGD", "Momentum", "Adagrad", "Adam", "Adamax", "DecayedAdagrad",
    "Adadelta", "RMSProp", "Ftrl", "SGDOptimizer", "MomentumOptimizer",
    "AdagradOptimizer", "AdamOptimizer", "AdamaxOptimizer",
    "DecayedAdagradOptimizer", "AdadeltaOptimizer", "RMSPropOptimizer",
    "FtrlOptimizer", "Optimizer", "ModelAverage",
]


class Optimizer:
    def __init__(self, learning_rate, regularization=None):
        assert learning_rate is not None
        self.regularization = regularization
        self._learning_rate = learning_rate
        self._learning_rate_var: Optional[Variable] = None
        # {accumulator name: {parameter name: accumulator var}}
        self._accumulators: Dict[str, Dict[str, Variable]] = defaultdict(dict)
        self.helper: Optional[LayerHelper] = None

    # --- learning rate ------------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_var = self._learning_rate
            return
        if self._learning_rate_var is None:
            from .layers.tensor import create_global_var
            self._learning_rate_var = create_global_var(
                name=unique_name.generate("learning_rate"), shape=[1],
                value=float(self._learning_rate), dtype="float32",
                persistable=True)

    def _global_learning_rate(self):
        return self._learning_rate_var

    def _create_param_lr(self, param_and_grad):
        param_lr = param_and_grad[0].optimize_attr.get("learning_rate", 1.0)
        base = self._global_learning_rate()
        if param_lr == 1.0:
            return base
        from .layers.nn import scale as scale_layer
        return scale_layer(base, scale=float(param_lr))

    # --- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        assert self.helper is not None
        shape = list(shape or param.shape)
        var = self.helper.create_global_variable(
            name=unique_name.generate(f"{param.name}_{name}"),
            persistable=True, dtype=dtype or param.dtype, shape=shape)
        self.helper.set_variable_initializer(
            var, ConstantInitializer(float(fill_value)))
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # --- hooks --------------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block):
        pass

    # --- driver -------------------------------------------------------------
    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        program = loss.block.program
        global_block = program.global_block()
        n_before = len(global_block.ops)
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        self._create_accumulators(global_block,
                                  [p for p, g in parameters_and_grads
                                   if g is not None])
        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            if param_and_grad[0].trainable:
                optimize_ops.append(
                    self._append_optimize_op(global_block, param_and_grad))
        self._finish_update(global_block)
        # role tag (reference OpRole::kOptimize): everything this pass
        # appended — update ops, lr-schedule ops, accumulator bumps — is
        # stripped by inference slicing, so a parameter's in-place ParamOut
        # can never drag the training tail into a pruned inference program
        for op in global_block.ops[n_before:]:
            op.desc.attrs.setdefault("op_role", "optimize")
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None) -> Tuple[List, List]:
        """append_backward + regularization + clip + optimizer ops
        (reference optimizer.py Optimizer.minimize)."""
        params_grads = append_backward(loss, parameter_list, no_grad_set,
                                       [error_clip_callback])
        params_grads = sorted(params_grads, key=lambda pg: pg[0].name)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        optimize_ops = self._create_optimization_pass(params_grads, loss,
                                                      startup_program)
        from . import telemetry
        telemetry.counter(
            "optimizer_minimize_total",
            "training graphs built (minimize calls), by optimizer type",
            labels=("optimizer",)).labels(
                optimizer=getattr(self, "type", type(self).__name__)).inc()
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type="sgd",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]]})


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity = self._get_accumulator(self._velocity_acc_str,
                                         param_and_grad[0])
        return block.append_op(
            type="momentum",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov})


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type="adagrad",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._beta1_pow = None
        self._beta2_pow = None

    def _create_accumulators(self, block, parameters):
        assert self.helper is not None
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)
        if self._beta1_pow is None:
            self._beta1_pow = self.helper.create_global_variable(
                name=unique_name.generate("beta1_pow_acc"), persistable=True,
                dtype="float32", shape=[1])
            self.helper.set_variable_initializer(
                self._beta1_pow, ConstantInitializer(self._beta1))
            self._beta2_pow = self.helper.create_global_variable(
                name=unique_name.generate("beta2_pow_acc"), persistable=True,
                dtype="float32", shape=[1])
            self.helper.set_variable_initializer(
                self._beta2_pow, ConstantInitializer(self._beta2))

    def _append_optimize_op(self, block, param_and_grad):
        m1 = self._get_accumulator(self._moment1_acc_str, param_and_grad[0])
        m2 = self._get_accumulator(self._moment2_acc_str, param_and_grad[0])
        return block.append_op(
            type="adam",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [self._beta1_pow],
                    "Beta2Pow": [self._beta2_pow],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "Moment1Out": [m1],
                     "Moment2Out": [m2]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})

    def _finish_update(self, block):
        """Advance beta^t accumulators (reference optimizer.py Adam
        _finish_update appends scale ops)."""
        block.append_op(type="scale", inputs={"X": [self._beta1_pow]},
                        outputs={"Out": [self._beta1_pow]},
                        attrs={"scale": self._beta1})
        block.append_op(type="scale", inputs={"X": [self._beta2_pow]},
                        outputs={"Out": [self._beta2_pow]},
                        attrs={"scale": self._beta2})


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._beta1_pow = None

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)
        if self._beta1_pow is None:
            self._beta1_pow = self.helper.create_global_variable(
                name=unique_name.generate("beta1_pow_acc"), persistable=True,
                dtype="float32", shape=[1])
            self.helper.set_variable_initializer(
                self._beta1_pow, ConstantInitializer(self._beta1))

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        inf_norm = self._get_accumulator(self._inf_norm_acc_str,
                                         param_and_grad[0])
        return block.append_op(
            type="adamax",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment": [moment], "InfNorm": [inf_norm],
                    "Beta1Pow": [self._beta1_pow],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment],
                     "InfNormOut": [inf_norm]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})

    def _finish_update(self, block):
        block.append_op(type="scale", inputs={"X": [self._beta1_pow]},
                        outputs={"Out": [self._beta1_pow]},
                        attrs={"scale": self._beta1})


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        ag = self._get_accumulator(self._avg_squared_grad_acc_str,
                                   param_and_grad[0])
        au = self._get_accumulator(self._avg_squared_update_acc_str,
                                   param_and_grad[0])
        return block.append_op(
            type="adadelta",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "AvgSquaredGrad": [ag], "AvgSquaredUpdate": [au],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "AvgSquaredGradOut": [ag], "AvgSquaredUpdateOut": [au]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "rmsprop"
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        mom = self._get_accumulator(self._momentum_acc_str, param_and_grad[0])
        ms = self._get_accumulator(self._mean_square_acc_str,
                                   param_and_grad[0])
        return block.append_op(
            type="rmsprop",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment": [mom], "MeanSquare": [ms],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [mom],
                     "MeanSquareOut": [ms]},
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum})


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        sq = self._get_accumulator(self._squared_acc_str, param_and_grad[0])
        lin = self._get_accumulator(self._linear_acc_str, param_and_grad[0])
        return block.append_op(
            type="ftrl",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "SquaredAccumulator": [sq], "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power})


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer


class ModelAverage(Optimizer):
    """Maintain running parameter averages and swap them in for evaluation
    (reference optimizer.py:811 ModelAverage, average_accumulates_op.cc).

    Appends an average_accumulates op per parameter to the main program;
    `apply()` is a context manager that replaces each parameter with
    (sum_1 + sum_2 + sum_3) / (num_accumulates + old_num_accumulates) and
    restores the trained values on exit (or via `restore()`)."""

    def __init__(self, average_window_rate, params_grads=None,
                 min_average_window=10000, max_average_window=10000,
                 **kwargs):
        super().__init__(0.0, **kwargs)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = params_grads or [
            (p, None) for p in
            default_main_program().global_block().all_parameters()]
        self.helper = LayerHelper(self.__class__.__name__)
        self._avg_params = []
        for param, _ in self.params_grads:
            self._append_average_accumulate_op(param)
            self._avg_params.append(param)

    def _append_average_accumulate_op(self, param):
        sum_1 = self._add_accumulator("sum_1", param)
        sum_2 = self._add_accumulator("sum_2", param)
        sum_3 = self._add_accumulator("sum_3", param)
        num_acc = self._add_accumulator("num_accumulates", param,
                                        dtype="int32", shape=[1])
        old_num = self._add_accumulator("old_num_accumulates", param,
                                        dtype="int32", shape=[1])
        num_upd = self._add_accumulator("num_updates", param,
                                        dtype="int32", shape=[1])
        default_main_program().global_block().append_op(
            type="average_accumulates",
            inputs={"param": [param], "in_sum_1": [sum_1],
                    "in_sum_2": [sum_2], "in_sum_3": [sum_3],
                    "in_num_accumulates": [num_acc],
                    "in_old_num_accumulates": [old_num],
                    "in_num_updates": [num_upd]},
            outputs={"out_sum_1": [sum_1], "out_sum_2": [sum_2],
                     "out_sum_3": [sum_3],
                     "out_num_accumulates": [num_acc],
                     "out_old_num_accumulates": [old_num],
                     "out_num_updates": [num_upd]},
            attrs={"average_window": float(self.average_window),
                   "min_average_window": int(self.min_average_window),
                   "max_average_window": int(self.max_average_window)})

    def _swap_program(self, restore):
        from .framework.framework import Program, program_guard
        from .layers import tensor as tl
        from .layers import nn as nl
        prog = Program()
        with program_guard(prog, Program()):
            for param, _ in self.params_grads:
                block = prog.global_block()
                p = block.create_var(name=param.name, shape=param.shape,
                                     dtype=param.dtype, persistable=True)
                backup = block.create_var(
                    name=param.name + "@MODEL_AVG_BACKUP",
                    shape=param.shape, dtype=param.dtype, persistable=True)
                if restore:
                    tl.assign(backup, output=p)
                    continue
                s1 = self._ref(block, self._get_accumulator("sum_1", param))
                s2 = self._ref(block, self._get_accumulator("sum_2", param))
                s3 = self._ref(block, self._get_accumulator("sum_3", param))
                na = self._ref(block,
                               self._get_accumulator("num_accumulates", param))
                on = self._ref(block, self._get_accumulator(
                    "old_num_accumulates", param))
                tl.assign(p, output=backup)
                total = nl.elementwise_add(nl.elementwise_add(s1, s2), s3)
                cnt = tl.cast(nl.elementwise_add(na, on), "float32")
                cnt = nl.elementwise_max(
                    cnt, tl.fill_constant(shape=[1], dtype="float32",
                                          value=1.0))
                avg = nl.elementwise_div(total, cnt, axis=0)
                tl.assign(avg, output=p)
        return prog

    @staticmethod
    def _ref(block, var):
        return block.create_var(name=var.name, shape=var.shape,
                                dtype=var.dtype, persistable=True)

    @contextmanager
    def apply(self, executor, need_restore=True):
        """Swap averaged parameter values in (reference optimizer.py:885)."""
        executor.run(self._swap_program(restore=False))
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor):
        executor.run(self._swap_program(restore=True))
