"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle-Fluid's
capabilities (reference: /root/reference, see SURVEY.md).

The public surface mirrors `paddle.fluid` (reference python/paddle/fluid/
__init__.py) so reference training scripts port by changing the import:
Program/Block IR + layers DSL, append_backward autodiff, an Executor that
compiles program blocks to XLA, TPUPlace alongside CPUPlace (CUDAPlace is a
source-compat alias), optimizers-as-ops, save/load, readers and datasets.
"""

from .framework import (Program, Block, Variable, Parameter, program_guard,
                        default_main_program, default_startup_program,
                        switch_main_program, switch_startup_program,
                        unique_name)
from .executor import (CPUPlace, CUDAPlace, TPUPlace, Executor, LoDTensor,
                       Scope, global_scope, scope_guard)
from .backward import append_backward, calc_gradient
from . import ops
from . import layers
from . import initializer
from .initializer import (Constant, ConstantInitializer, Normal,
                          NormalInitializer, Uniform, UniformInitializer,
                          Xavier, XavierInitializer, MSRA, MSRAInitializer)
from . import optimizer
from .optimizer import (SGD, SGDOptimizer, Momentum, MomentumOptimizer,
                        Adagrad, AdagradOptimizer, Adam, AdamOptimizer,
                        Adamax, AdamaxOptimizer, DecayedAdagrad,
                        DecayedAdagradOptimizer, Adadelta, AdadeltaOptimizer,
                        RMSProp, RMSPropOptimizer, Ftrl, FtrlOptimizer)
from .param_attr import ParamAttr
from . import regularizer
from . import clip
from .data_feeder import DataFeeder
from . import io
from . import nets
from . import models
from . import reader
from . import dataset
from .minibatch import batch
from . import parallel
from . import debugger
from . import profiler
from . import amp
from . import compat
from . import metrics
from . import average
from . import errors
from . import v2
from . import flags
from . import concurrency
from .concurrency import (make_channel, channel_send, channel_recv,
                          channel_close, Go, Select)
from . import telemetry
from . import tracing
from . import serving
from . import inspector
from . import roofline
from . import obs_server
obs_server.maybe_start_from_env()
from . import sentinel
sentinel.maybe_start_from_env()
from .parallel import transpiler
from .parallel.transpiler import DistributeTranspiler

__version__ = "0.1.0"
