"""Quantized MXU compute: the third AMP level ("O3").

The reference framework dispatches kernels by OpKernelType place/dtype/
library (reference: framework/op_kernel_type.h) — fp32 vs fp16 vs MKLDNN
int8 builds of the same op. On TPU the analogous axis is the MXU input
type: bf16 (AMP O1/O2) and, one level down, int8 / fp8 — the MXU runs
int8 dots at 2x the bf16 rate, and serving qps-per-chip comes from
exactly that. `amp.decorate(..., level="O3")` tags the program with a
quant mode ("int8" default, PADDLE_TPU_QUANT_MODE=fp8 to switch) and the
matmul/conv lowerings route eligible compute through this module:

  * weights are quantized symmetrically per output channel
    (scale = max|w| / 127 per column / per Co), activations per row,
    dynamically at each call — no calibration pass;
  * the integer dot accumulates in int32 (`preferred_element_type`) and
    dequantizes by the outer product of the two scale vectors, so the
    stored output is the same bf16 the O2 path would produce;
  * the whole quantized op is a `jax.custom_vjp`: backward is the plain
    bf16 matmul/conv math (straight-through estimator). `jnp.round` has
    a zero gradient a.e. and integer dots are not differentiable, so
    letting the generic vjp grad path retrace the quantized forward
    would silently produce zero weight gradients;
  * eligibility is a trace-time gate (`ineligible_matmul` /
    `ineligible_conv`) with counted per-reason fallbacks
    (quant_fallback_total{op,reason}), mirroring pallas_conv's
    pallas_fallback_total discipline — including a quantization
    error-bound check against PADDLE_TPU_QUANT_TOL;
  * serving (`ServingEngine(quantize="int8")`) pre-quantizes persistable
    weights ONCE at admission (`prequantize`, with a measured per-weight
    parity gate on the dequantization error) and bakes the int8 tensors
    into the AOT bucket executables as constants; activations still
    scale per call.

Gate-off story: with PADDLE_TPU_QUANT=0 every gate returns "disabled",
the lowerings take their plain O2 route, and O3 numerics equal O2
bitwise — the same contract as PADDLE_TPU_PALLAS_CONV=0.

Error model for the trace-time bound: symmetric uniform quantization
adds relative noise of RMS step/sqrt(12) per operand element (int8:
1/(127*sqrt(12)) ~ 0.23%; fp8 e4m3, 3 mantissa bits: 2^-3/sqrt(12) ~
3.6%). Quantization noise on a K-term dot product is zero-mean and
independent per term, so the *relative* RMS error of the output stays
~sqrt(eps_x^2 + eps_w^2) independent of K. Ops whose estimate exceeds
PADDLE_TPU_QUANT_TOL (default 0.06 — passes int8 and fp8; tighten to
force the "error_bound" fallback) fall back to bf16.
"""

from __future__ import annotations

import contextlib
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = [
    "FALLBACK_REASONS", "QUANT", "QUANT_OPS", "count_fallback",
    "count_hit", "error_estimate", "fp8_supported", "gate_for_op",
    "ineligible_conv", "ineligible_matmul", "prequantize",
    "prequantized", "qconv2d",
    "qmatmul", "quantize_channelwise", "suppress_counters",
    "weight_qparams",
]

QUANT = os.environ.get("PADDLE_TPU_QUANT", "1") == "1"
QUANT_TOL = float(os.environ.get("PADDLE_TPU_QUANT_TOL", "0.06"))

_LANE = 128

# Every reason the gates can return (pinned by check_quant_table — a
# reason produced but not listed here would ship an unlabelled fallback
# counter, exactly the pallas FALLBACK_REASONS contract).
FALLBACK_REASONS = frozenset(
    {"disabled", "mode", "rank", "dtype", "shape", "kernel",
     "error_bound"})

# RMS relative quantization noise per operand element (module
# docstring); bf16 operands arrive already rounded, so these are the
# *additional* noise of the int8/fp8 step.
_EPS_RMS = {"int8": 1.0 / (127.0 * math.sqrt(12.0)),
            "fp8": 2.0 ** -3 / math.sqrt(12.0)}

# int8 full-scale / fp8 e4m3 max-normal
_QMAX = {"int8": 127.0, "fp8": 448.0}

_FLOAT_IN = (jnp.bfloat16, jnp.float32)

# Registered op types that route through this module, and the quantized
# entry point each uses. check_quant_table pins it against ops/registry
# and the gate/lowering sources — an op listed here whose lowering never
# consults the gate (or vice versa) silently loses quantization, so the
# lint fails instead.
QUANT_OPS = {
    "mul": "qmatmul",
    "matmul": "qmatmul",
    "conv2d": "qconv2d",
    "depthwise_conv2d": "qconv2d",   # groups gate: always falls back
}


def cache_token(program):
    """The quant part of the executor's compile-cache key: everything
    that changes how lowerings route, beyond the program itself."""
    return (getattr(program, "_quant_mode", None), QUANT, QUANT_TOL)


_FP8_OK = None


def fp8_supported() -> bool:
    """Whether the current backend executes float8_e4m3fn dots — probed
    once per process with a tiny real dot (an eval_shape would not catch
    a backend that traces but cannot compile fp8)."""
    global _FP8_OK
    if _FP8_OK is None:
        try:
            a = jnp.ones((8, 8), jnp.float8_e4m3fn)
            out = jax.jit(lambda u, v: lax.dot_general(
                u, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))(a, a)
            jax.block_until_ready(out)
            _FP8_OK = True
        except Exception:  # noqa: BLE001 - any failure means "no fp8"
            _FP8_OK = False
    return _FP8_OK


def error_estimate(k: int, mode: str) -> float:
    """Trace-time relative-RMS error estimate for a quantized K-deep
    matmul/conv contraction (module docstring's model): both operands
    carry one quantization step of noise."""
    eps = _EPS_RMS.get(mode, 1.0)
    del k  # zero-mean noise: relative output error is depth-independent
    return math.sqrt(2.0) * eps


# --- trace-time gates ---------------------------------------------------

def ineligible_matmul(x, y, mode="int8"):
    """None when the quantized matmul applies to x [M, K] @ y [K, N],
    else the fallback reason. Operands are post-mxu_cast (bf16 under
    O3). Shared by the lowering, the preflight dry-run and the serving
    admission pass, so it must stay a pure shape/dtype predicate."""
    if not QUANT:
        return "disabled"
    if mode not in _QMAX:
        return "mode"
    if mode == "fp8" and not fp8_supported():
        return "mode"
    if getattr(x, "ndim", 0) != 2 or getattr(y, "ndim", 0) != 2:
        return "rank"
    if getattr(x, "dtype", None) not in _FLOAT_IN or \
            getattr(y, "dtype", None) not in _FLOAT_IN:
        return "dtype"
    k = x.shape[1]
    if k < 32 or k % 8:
        # too shallow to amortize the quantize/dequantize sweeps, or
        # misaligned for the int8 MXU tile (32 sublanes)
        return "shape"
    if error_estimate(k, mode) > QUANT_TOL:
        return "error_bound"
    return None


def ineligible_conv(x, w, strides, paddings, dilations, groups=1,
                    mode="int8"):
    """None when the quantized conv applies (NHWC x, OIHW w, both
    post-mxu_cast), else the reason. The int8 conv runs on the Pallas
    kernel suite, so pallas_conv.ineligible is a hard prerequisite —
    the explicit conv2d_grad lowering and the vjp fallback must keep
    agreeing with the forward route (same contract as the bf16 path)."""
    if not QUANT:
        return "disabled"
    if mode not in _QMAX:
        return "mode"
    if mode == "fp8":
        return "mode"    # the Pallas quant conv kernel is int8-only
    from .ops import pallas_conv
    if pallas_conv.ineligible(x, w, strides, paddings, dilations,
                              groups) is not None:
        return "kernel"
    co, ci, kh, kw = w.shape
    if error_estimate(ci * kh * kw, mode) > QUANT_TOL:
        return "error_bound"
    return None


def _pair2(v):
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1])) if len(v) > 1 else (int(v[0]),) * 2
    return (int(v), int(v))


def gate_for_op(op_type, ins, attrs, mode, nhwc=False):
    """Dry-run the lowering-time eligibility gate for ONE op instance on
    aval-like inputs (.shape/.dtype suffice — jax.ShapeDtypeStruct or
    real arrays). `ins` maps slot name -> list of values shaped the way
    the lowering receives them; `attrs` is the op's attr dict. For convs
    `nhwc` says Input is already channels-minor (the layout convention
    tags it so mid-stack); with nhwc=False the user-visible NCHW shape
    is rotated first, mirroring _conv2d's transpose.

    Shared by the roofline cost model (int8 peak factor) and the
    preflight quant pass so their verdicts replay the executor's actual
    routing without tracing. Returns None (would quantize) or the
    fallback reason string."""
    def _aval(shape, dtype):
        return jax.ShapeDtypeStruct(tuple(int(d) for d in shape), dtype)

    assert op_type in QUANT_OPS, op_type
    if op_type in ("conv2d", "depthwise_conv2d"):
        x, w = ins["Input"][0], ins["Filter"][0]
        if not nhwc and getattr(x, "ndim", 0) == 4:
            s = x.shape
            x = _aval((s[0], s[2], s[3], s[1]), x.dtype)
        return ineligible_conv(
            x, w, _pair2(attrs.get("strides", [1, 1])),
            _pair2(attrs.get("paddings", [0, 0])),
            _pair2(attrs.get("dilations", [1, 1])),
            attrs.get("groups", 1) or 1, mode)
    x, y = ins["X"][0], ins["Y"][0]
    if op_type == "mul":
        def _flat(v, n):
            shp = tuple(int(d) for d in v.shape)
            rows = int(np.prod(shp[:n])) if n else 1
            cols = int(np.prod(shp[n:])) if n < len(shp) else 1
            return _aval((rows, cols), v.dtype)
        x = _flat(x, int(attrs.get("x_num_col_dims", 1)))
        y = _flat(y, int(attrs.get("y_num_col_dims", 1)))
    else:  # matmul: gate sees post-transpose operands
        if attrs.get("transpose_X", False) and getattr(x, "ndim", 0) > 1:
            s = x.shape
            x = _aval(s[:-2] + (s[-1], s[-2]), x.dtype)
        if attrs.get("transpose_Y", False) and getattr(y, "ndim", 0) > 1:
            s = y.shape
            y = _aval(s[:-2] + (s[-1], s[-2]), y.dtype)
    return ineligible_matmul(x, y, mode)


# --- counters -----------------------------------------------------------

_SUPPRESS_COUNTERS = False


@contextlib.contextmanager
def suppress_counters():
    """Silence count_hit/count_fallback on this thread of lowering:
    generic_grad_lower's vjp re-traces forward lowerings, which would
    book a second quant_fallback_total/quant_kernel_total sample for an
    op that already counted itself on the forward trace."""
    global _SUPPRESS_COUNTERS
    prev = _SUPPRESS_COUNTERS
    _SUPPRESS_COUNTERS = True
    try:
        yield
    finally:
        _SUPPRESS_COUNTERS = prev


def count_fallback(op: str, reason: str):
    if _SUPPRESS_COUNTERS:
        return
    from . import telemetry
    telemetry.counter(
        "quant_fallback_total",
        "O3 lowerings that fell back from the quantized path to bf16, "
        "by op and gating reason",
        labels=("op", "reason")).labels(op=op, reason=reason).inc()


def count_hit(op: str):
    if _SUPPRESS_COUNTERS:
        return
    from . import telemetry
    telemetry.counter(
        "quant_kernel_total",
        "lowerings served by the quantized (int8/fp8) path, by op",
        labels=("op",)).labels(op=op).inc()


# --- quantize helpers ---------------------------------------------------

def quantize_channelwise(x, axis: int, mode: str = "int8"):
    """Symmetric per-channel quantization: reduce max|x| over every dim
    EXCEPT `axis`, scale to the mode's full range, round. Returns
    (q, scale) with scale shaped like x reduced to size 1 everywhere but
    `axis` — so `q * scale` (or the int32 accumulator times the scale
    product) dequantizes by broadcast."""
    x32 = x.astype(jnp.float32)
    red = tuple(d for d in range(x32.ndim) if d != axis % x32.ndim)
    amax = jnp.max(jnp.abs(x32), axis=red, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / _QMAX[mode]
    if mode == "fp8":
        q = (x32 / scale).astype(jnp.float8_e4m3fn)
    else:
        q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


def weight_qparams(w: np.ndarray, axis: int, mode: str = "int8"):
    """Host-side quantize_channelwise for serving admission: numpy in,
    (q, scale, rel_rms_err) out. The error term is the measured parity
    number the admission gate checks against QUANT_TOL — a real
    dequantize-and-compare, not the analytic estimate."""
    w32 = np.asarray(w, np.float32)
    red = tuple(d for d in range(w32.ndim) if d != axis % w32.ndim)
    amax = np.max(np.abs(w32), axis=red, keepdims=True)
    scale = np.maximum(amax, 1e-12) / _QMAX[mode]
    if mode == "fp8":
        q = (w32 / scale).astype(jnp.float8_e4m3fn)
    else:
        q = np.clip(np.rint(w32 / scale), -127, 127).astype(np.int8)
    deq = q.astype(np.float32) * scale
    denom = float(np.sqrt(np.mean(w32 * w32))) or 1.0
    err = float(np.sqrt(np.mean((deq - w32) ** 2))) / denom
    return q, scale.astype(np.float32), err


# --- quantized compute --------------------------------------------------

def _int_dot(xq, yq, mode):
    acc_t = jnp.float32 if mode == "fp8" else jnp.int32
    return lax.dot_general(xq, yq, (((1,), (0,)), ((), ())),
                           preferred_element_type=acc_t)


def _qmm_fwd_impl(x, y, mode, pre):
    xq, sx = quantize_channelwise(x, axis=0, mode=mode)   # [M,1] rows
    if pre is None:
        yq, sy = quantize_channelwise(y, axis=1, mode=mode)  # [1,N] cols
    else:
        yq, sy = jnp.asarray(pre[0]), jnp.asarray(pre[1])
    acc = _int_dot(xq, yq, mode).astype(jnp.float32)
    return (acc * (sx * sy)).astype(x.dtype)


def _make_qmm(mode: str, pre):
    @jax.custom_vjp
    def qmm(x, y):
        return _qmm_fwd_impl(x, y, mode, pre)

    def fwd(x, y):
        return qmm(x, y), (x, y)

    def bwd(res, g):
        # straight-through: the bf16 matmul vjp, exactly what the O2
        # path's generic grad would compute
        x, y = res
        gx = jnp.matmul(g, jnp.swapaxes(y, -1, -2)).astype(x.dtype)
        gy = jnp.matmul(jnp.swapaxes(x, -1, -2), g).astype(y.dtype)
        return gx, gy

    qmm.defvjp(fwd, bwd)
    return qmm


def qmatmul(x, y, mode: str = "int8", pre=None):
    """Quantized x [M, K] @ y [K, N] -> [M, N] in x.dtype. Per-row
    activation scales, per-column weight scales, int32 (fp8: f32)
    accumulation, straight-through bf16 backward. `pre` optionally
    supplies admission-time (q, scale) for y (ServingEngine) — y itself
    still flows in for the (never-taken at serve time) backward."""
    return _make_qmm(mode, pre)(x, y)


def _qconv_fwd_impl(x, w, strides, paddings, dilations, pre):
    from .ops import pallas_conv
    # conv activations scale per-tensor: the MXU contraction mixes every
    # input channel and tap, so only a scalar scale factors out of the
    # int32 accumulator
    x32 = x.astype(jnp.float32)
    sx = jnp.maximum(jnp.max(jnp.abs(x32)), 1e-12) / _QMAX["int8"]
    xq = jnp.clip(jnp.round(x32 / sx), -127, 127).astype(jnp.int8)
    if pre is None:
        wq, sw = quantize_channelwise(w, axis=0, mode="int8")  # per-Co
    else:
        wq, sw = jnp.asarray(pre[0]), jnp.asarray(pre[1])
    dq = (sx * sw.reshape(-1)).astype(jnp.float32)             # [Co]
    return pallas_conv.conv2d_q8(xq, wq, strides, paddings, dilations,
                                 dq, out_dtype=x.dtype)


def _make_qconv(strides, paddings, dilations, pre):
    @jax.custom_vjp
    def qconv(x, w):
        return _qconv_fwd_impl(x, w, strides, paddings, dilations, pre)

    def fwd(x, w):
        return qconv(x, w), (x, w)

    def bwd(res, g):
        # straight-through via the bf16 reference conv's vjp. The
        # explicit conv2d_grad lowering normally shortcuts this with the
        # Pallas grad kernels; this path exists for direct jax.grad
        # through the lowering (preflight probes, fused windows).
        x, w = res
        s, p, d = strides, paddings, dilations

        def ref(xv, wv):
            return lax.conv_general_dilated(
                xv, jnp.transpose(wv, (2, 3, 1, 0)),
                window_strides=s, padding=[(p[0], p[0]), (p[1], p[1])],
                rhs_dilation=d,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        _, vjp = jax.vjp(ref, x, w)
        gx, gw = vjp(g.astype(x.dtype))
        return gx.astype(x.dtype), gw.astype(w.dtype)

    qconv.defvjp(fwd, bwd)
    return qconv


def qconv2d(x, w, strides, paddings, dilations, mode: str = "int8",
            pre=None):
    """Quantized NHWC conv (x [N,H,W,Ci], w [Co,Ci,KH,KW]) through the
    Pallas int8 kernel: per-tensor activation scale, per-Co weight
    scales, int32 VMEM accumulation, dequantized on the output row while
    it is still in VMEM. Caller must have passed ineligible_conv."""
    del mode  # the conv kernel is int8-only (gate returns "mode" on fp8)
    return _make_qconv(tuple(strides), tuple(paddings), tuple(dilations),
                       pre)(x, w)


# --- serving admission --------------------------------------------------

# weight slot per quantizable op type: the persistable operand the
# engine pre-quantizes (activations are per-call by definition)
_WEIGHT_SLOTS = {"mul": "Y", "matmul": "Y", "conv2d": "Filter",
                 "depthwise_conv2d": "Filter"}


def prequantized(ctx, name: str):
    """The admission-time (q, scale) for weight var `name`, or None —
    read by the matmul/conv lowerings during the serving trace."""
    cache = getattr(ctx.program, "_quant_weights", None)
    return cache.get(name) if cache else None


def prequantize(program, scope, mode: str = "int8") -> dict:
    """Quantize every eligible persistable weight of `program` once,
    host-side, and stash the (q, scale) pairs on the program for the
    serving trace to bake into the AOT bucket executables as constants.

    Per-weight parity gate: the measured relative RMS dequantization
    error must stay within QUANT_TOL, or the weight is left dynamic
    (counted as quant_fallback_total{op,reason="error_bound"}). Returns
    {"quantized": [names], "skipped": {name: reason}} for the engine's
    admission report."""
    cache = {}
    skipped = {}
    block = program.global_block()
    for op_ in block.ops:
        slot = _WEIGHT_SLOTS.get(op_.type)
        if slot is None:
            continue
        names = op_.desc.inputs.get(slot, [])
        if not names:
            continue
        name = names[0]
        if name in cache or name in skipped:
            continue
        if op_.type == "matmul" and op_.attr("transpose_Y", False):
            skipped[name] = "shape"   # cache stores [K, N] orientation
            continue
        var = block.desc.vars.get(name)
        if var is None or not var.persistable:
            continue
        w = scope.find_var(name)
        if w is None:
            skipped[name] = "shape"
            continue
        w = np.asarray(w)
        if w.dtype not in (np.float32, np.dtype(jnp.bfloat16)):
            skipped[name] = "dtype"
            count_fallback(op_.type, "dtype")
            continue
        axis = 0 if slot == "Filter" else -1
        use_mode = "int8" if slot == "Filter" else mode
        q, scale, err = weight_qparams(w, axis, use_mode)
        if err > QUANT_TOL:
            skipped[name] = "error_bound"
            count_fallback(op_.type, "error_bound")
            continue
        cache[name] = (q, scale)
    program._quant_weights = cache
    return {"quantized": sorted(cache), "skipped": skipped}
