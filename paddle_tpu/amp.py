"""Automatic mixed precision (bf16 compute, fp32 master weights).

The TPU-native replacement for the reference's fp16 support
(reference: paddle/fluid/platform/float16.h:64 — an fp16 storage type with
per-kernel CUDA intrinsics). On TPU the low-precision matmul/conv input type
is bfloat16 (the MXU's native format), and because bf16 keeps float32's
exponent range, no loss scaling is required. The policy here is the standard
one:

  * matmul/conv operands are cast to bf16 at the op (ops/common.py
    mxu_cast), accumulating in fp32 (`preferred_element_type`);
  * everything else — parameters ("fp32 master weights"), batch-norm
    statistics, losses, optimizer state and updates — stays float32;
  * gradients w.r.t. weights come back fp32 automatically: the cast is part
    of the traced forward, so its vjp casts cotangents back to fp32.

Usage:
    fluid.amp.enable(program)               # or decorate(optimizer)
    ...build/run as usual...
"""

from __future__ import annotations

import os
from typing import Optional

from .framework.framework import Program, default_main_program

__all__ = ["enable", "disable", "decorate"]


def enable(program: Optional[Program] = None, dtype: str = "bfloat16",
           level: str = "O1"):
    """Tag `program` (default: the default main program) so MXU-bound ops
    compute in `dtype`. Takes effect on the next Executor.run — the compile
    cache is keyed on the policy.

    level="O1": matmul/conv compute in bf16, outputs restored to f32.
    level="O2": activations stay bf16 end-to-end (halves HBM traffic);
    norm statistics, losses, master weights and optimizer state stay f32.
    level="O3": O2 plus quantized MXU compute — eligible matmul/conv
    lowerings route through paddle_tpu/quant.py (int8 by default,
    PADDLE_TPU_QUANT_MODE=fp8 to switch) with per-channel dynamic
    scaling and counted per-reason fallbacks; PADDLE_TPU_QUANT=0 gates
    the routing off entirely, restoring O2 numerics bitwise.
    """
    assert level in ("O1", "O2", "O3"), level
    program = program or default_main_program()
    program._amp_dtype = dtype
    program._amp_level = level
    program._quant_mode = (
        os.environ.get("PADDLE_TPU_QUANT_MODE", "int8")
        if level == "O3" else None)
    return program


def disable(program: Optional[Program] = None):
    program = program or default_main_program()
    program._amp_dtype = None
    program._quant_mode = None
    return program


class _DecoratedOptimizer:
    """Source-compat shim mirroring later Paddle's
    `fluid.contrib.mixed_precision.decorate(optimizer)`: minimize() enables
    the bf16 policy on the program it builds into."""

    def __init__(self, optimizer, dtype: str = "bfloat16",
                 level: str = "O1"):
        self._opt = optimizer
        self._dtype = dtype
        self._level = level

    def minimize(self, loss, startup_program=None, **kw):
        enable(loss.block.program, self._dtype, self._level)
        return self._opt.minimize(loss, startup_program=startup_program, **kw)

    def __getattr__(self, name):
        return getattr(self._opt, name)


def decorate(optimizer, dtype: str = "bfloat16", level: str = "O1"):
    return _DecoratedOptimizer(optimizer, dtype, level)
