"""`paddle train`-style command line (reference:
paddle/trainer/TrainerMain.cpp:32-64 — jobs train/test/time driven by
--config; paddle/scripts/submit_local.sh.in:3-13 the `paddle` wrapper).

Usage:
    python -m paddle_tpu train --config=conf.py [--epochs N] [--save-dir D]
                               [--checkpoint-dir C] [--resume]
    python -m paddle_tpu time  --config=conf.py [--steps N]
    python -m paddle_tpu infer --model-dir=D --input=batch.npz
    python -m paddle_tpu telemetry [--log step.jsonl [--tail N]]
                                   [--prometheus] [--reduce]
    python -m paddle_tpu obs [--port P] [--steps N] [--hold]
    python -m paddle_tpu version

The config file is a Python module (the reference's --config was a Python
DSL file too, parsed by config_parser.py) defining:

    def build():
        ...build programs, apply an optimizer...
        return {"main_program": main, "startup_program": startup,
                "feed_order": ["x", "y"], "loss": loss_var,
                # optional: "fetch": [vars], "feed_targets": [vars]}

    def train_reader():   # yields per-sample tuples matching feed_order
        ...
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
import time as time_mod


def _load_config(path):
    spec = importlib.util.spec_from_file_location("paddle_tpu_config", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    if not hasattr(mod, "build"):
        raise SystemExit(f"config '{path}' must define build()")
    return mod


def _feeder(fluid, cfg, spec):
    feed_targets = spec.get("feed_targets")
    if feed_targets is None:
        block = spec["main_program"].global_block()
        feed_targets = [block.var(n) for n in spec["feed_order"]]
    return fluid.DataFeeder(feed_list=feed_targets, place=fluid.TPUPlace(0))


def cmd_train(args):
    import paddle_tpu as fluid
    from paddle_tpu.parallel import multihost

    cfg = _load_config(args.config)
    spec = cfg.build()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(spec["startup_program"])

    start_epoch = 0
    if args.checkpoint_dir and args.resume:
        meta = multihost.load_checkpoint(exe, args.checkpoint_dir,
                                         main_program=spec["main_program"])
        if meta:
            start_epoch = meta["step"] + 1
            print(f"resumed from checkpoint epoch {meta['step']}")

    feeder = _feeder(fluid, cfg, spec)
    import paddle_tpu.minibatch as minibatch
    batched = minibatch.batch(cfg.train_reader, batch_size=args.batch_size)

    loss_name = spec["loss"].name
    for epoch in range(start_epoch, args.epochs):
        t0 = time_mod.perf_counter()
        last = None
        n = 0
        for data in batched():
            last, = exe.run(spec["main_program"], feed=feeder.feed(data),
                            fetch_list=[loss_name])
            n += 1
        dt = time_mod.perf_counter() - t0
        import numpy as np
        print(f"epoch {epoch}: loss={float(np.asarray(last).ravel()[0]):.6f}"
              f" ({n} steps, {dt:.1f}s)")
        if args.checkpoint_dir:
            multihost.save_checkpoint(exe, args.checkpoint_dir, epoch,
                                      main_program=spec["main_program"])
    if args.save_dir:
        fetch = spec.get("fetch") or [spec["loss"]]
        fluid.io.save_inference_model(args.save_dir, spec["feed_order"],
                                      fetch, exe,
                                      main_program=spec["main_program"])
        print(f"saved inference model to {args.save_dir}")
    return 0


def cmd_time(args):
    """--job=time parity (reference TrainerBenchmark.cpp): steps/sec over
    synthetic repeats of the first batch."""
    import numpy as np
    import paddle_tpu as fluid
    import paddle_tpu.minibatch as minibatch

    cfg = _load_config(args.config)
    spec = cfg.build()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(spec["startup_program"])
    feeder = _feeder(fluid, cfg, spec)
    batched = minibatch.batch(cfg.train_reader, batch_size=args.batch_size)
    data = next(iter(batched()))
    feed = feeder.feed(data)
    loss_name = spec["loss"].name
    for _ in range(3):
        exe.run(spec["main_program"], feed=feed, fetch_list=[loss_name])
    t0 = time_mod.perf_counter()
    for _ in range(args.steps):
        out, = exe.run(spec["main_program"], feed=feed,
                       fetch_list=[loss_name], return_numpy=False)
    float(np.asarray(out).ravel()[0])
    dt = time_mod.perf_counter() - t0
    print(f"{args.steps} steps in {dt:.2f}s -> {args.steps / dt:.2f} steps/s")
    return 0


def cmd_checkgrad(args):
    """--job=checkgrad parity (reference TrainerMain.cpp:36): numeric
    central-difference gradients of the config's loss w.r.t. every
    parameter, compared against the analytic grads the IR backward pass
    emits. Optimizer-role ops are stripped so repeated loss evaluations
    never mutate the parameters."""
    import numpy as np
    import paddle_tpu as fluid
    import paddle_tpu.minibatch as minibatch
    from paddle_tpu import executor as executor_mod
    from paddle_tpu.framework.framework import grad_var_name

    from paddle_tpu.io import _strip_training_ops

    cfg = _load_config(args.config)
    spec = cfg.build()
    main = spec["main_program"]
    block = main.global_block()
    # forward + backward (no optimizer updates) for the analytic grads;
    # forward-only for the many numeric loss evaluations — the executor
    # compiles whole programs regardless of fetch list, so evaluating the
    # loss on the fwd+bwd program would recompute every gradient 2*samples
    # times per parameter
    check = main.clone()
    cb = check.global_block()
    cb.desc.ops = [d for d in cb.desc.ops
                   if d.attrs.get("op_role") != "optimize"]
    cb._sync_ops()
    fwd_only = _strip_training_ops(main)

    params = sorted(p.name for p in block.all_parameters())
    grads = [grad_var_name(p) for p in params]
    missing = [g for g in grads if not cb.has_var(g)]
    if missing:
        raise SystemExit(
            f"checkgrad needs analytic grads in the program; missing "
            f"{missing} (did build() call minimize()?)")

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(spec["startup_program"])
    feeder = _feeder(fluid, cfg, spec)
    batched = minibatch.batch(cfg.train_reader, batch_size=args.batch_size)
    feed = feeder.feed(next(iter(batched())))
    loss_name = spec["loss"].name
    scope = executor_mod.global_scope()

    def run_loss():
        # pin the PRNG stream: the executor advances __rng_counter__ every
        # run, so without this a config with random ops (dropout,
        # uniform_random) would draw different noise per evaluation and
        # the central difference would measure noise, not gradient
        scope.set_var("__rng_counter__", 0)
        out, = exe.run(fwd_only, feed=feed, fetch_list=[loss_name])
        return float(np.ravel(out)[0])

    scope.set_var("__rng_counter__", 0)
    outs = exe.run(check, feed=feed, fetch_list=[loss_name] + grads)
    analytic = {p: np.asarray(g) for p, g in zip(params, outs[1:])}

    rng = np.random.RandomState(0)
    delta, worst, failed = args.delta, 0.0, []
    for p in params:
        w = np.array(scope.find_var(p), np.float64)
        flat = w.reshape(-1)
        k = min(args.samples, flat.size)
        idxs = rng.choice(flat.size, size=k, replace=False)
        for i in idxs:
            orig = flat[i]
            flat[i] = orig + delta
            scope.set_var(p, w.astype(np.float32))
            lp = run_loss()
            flat[i] = orig - delta
            scope.set_var(p, w.astype(np.float32))
            lm = run_loss()
            flat[i] = orig
            scope.set_var(p, w.astype(np.float32))
            num = (lp - lm) / (2 * delta)
            ana = float(analytic[p].reshape(-1)[i])
            err = abs(num - ana) / max(abs(num), abs(ana), 1.0)
            worst = max(worst, err)
            if err > args.rtol:
                failed.append((p, int(i), num, ana, err))
        print(f"checkgrad {p}: {k} elements ok "
              f"(max rel err so far {worst:.2e})")
    if failed:
        for p, i, num, ana, err in failed:
            print(f"FAIL {p}[{i}]: numeric {num:.6g} vs analytic "
                  f"{ana:.6g} (rel err {err:.2e})")
        return 1
    print(f"checkgrad PASSED: {len(params)} parameters, "
          f"max rel err {worst:.2e}")
    return 0


def cmd_infer(args):
    import numpy as np
    import paddle_tpu as fluid

    exe = fluid.Executor(fluid.TPUPlace(0))
    prog, feed_names, fetch_targets = fluid.io.load_inference_model(
        args.model_dir, exe)
    data = np.load(args.input)
    feed = {n: data[n] for n in feed_names}
    outs = exe.run(prog, feed=feed, fetch_list=fetch_targets)
    for name, val in zip([v.name for v in fetch_targets], outs):
        arr = np.asarray(val)
        print(f"{name} shape={list(arr.shape)}")
        np.savetxt(sys.stdout, arr.reshape(arr.shape[0], -1), fmt="%.6f")
    return 0


def cmd_telemetry(args):
    """Pretty-print a telemetry snapshot or tail/summarize a JSONL step log
    (the scrape-less half of the ISSUE's observability story: the same data
    prometheus_text() exports, readable from a shell)."""
    import json

    from paddle_tpu import telemetry

    if args.log:
        recs = telemetry.read_step_log(args.log)
        if args.tail:
            for r in recs[-args.tail:]:
                print(json.dumps(r, sort_keys=True))
            return 0
        by_kind = {}
        for r in recs:
            by_kind.setdefault(r.get("kind", "?"), []).append(r)
        print(f"{args.log}: {len(recs)} events")
        for kind in sorted(by_kind):
            rs = by_kind[kind]
            secs = [r["seconds"] for r in rs if "seconds" in r]
            line = f"  {kind:12s} {len(rs):6d}"
            if secs:
                line += (f"  total {sum(secs):.3f}s"
                         f"  mean {sum(secs) / len(secs) * 1e3:.2f}ms"
                         f"  max {max(secs) * 1e3:.2f}ms")
            print(line)
        misses = by_kind.get("cache_miss", [])
        if misses:
            sig = misses[-1].get("signature")
            print(f"  last retrace signature: {sig}")
        return 0

    snap = telemetry.snapshot(reduce=args.reduce)
    if args.prometheus:
        print(telemetry.prometheus_text(snap), end="")
        return 0
    scope = "fleet" if args.reduce else f"host {snap.get('host', 0)}"
    print(f"telemetry snapshot ({scope})")
    for kind in ("counters", "gauges"):
        series = snap.get(kind, {})
        if not series:
            continue
        print(f"{kind}:")
        for name in sorted(series):
            for lk in sorted(series[name]):
                label = f"{{{lk}}}" if lk else ""
                print(f"  {name}{label} = {_fmt_num(series[name][lk])}")
    hists = snap.get("histograms", {})
    if hists:
        print("histograms:")
        for name in sorted(hists):
            for lk in sorted(hists[name]):
                h = hists[name][lk]
                label = f"{{{lk}}}" if lk else ""
                n = h["count"]
                mean = h["sum"] / n if n else 0.0
                print(f"  {name}{label}: count={n:g} sum={h['sum']:.4f}s "
                      f"mean={mean * 1e3:.3f}ms")
    return 0


def cmd_memory(args):
    """HBM observability console (memory.py): static per-program footprint
    (Compiled.memory_analysis + the peak-liveness walk), live accounting
    after a real step, donation audit, and the what-if headroom estimate
    ("will batch B fit?") — on the built-in smoke programs, a --config
    model, or a crash report's memory section."""
    import json

    from paddle_tpu import inspector, memory, telemetry

    if args.report:
        report = inspector.read_crash_report(args.report)
        section = {"memory": report.get("memory"),
                   "error": report.get("error")}
        if args.json:
            print(json.dumps(section, indent=2, sort_keys=True))
        else:
            print(inspector.format_crash_report(report))
        return 0

    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import executor as executor_mod

    budget = int(args.budget_gb * (1 << 30)) if args.budget_gb else None
    out = []

    def probe(label, main, loss, feed_fn, data_fn):
        exe = fluid.Executor(fluid.TPUPlace(0))
        entry = {"program": label, "batch": args.batch}
        measure = lambda b: exe.static_memory_analysis(
            main, feed=feed_fn(b), fetch_list=[loss])
        rec = measure(args.batch)
        entry["static"] = rec.to_dict()
        if data_fn is not None:
            run_b = min(args.batch, 8)
            exe.run(main, feed=data_fn(run_b), fetch_list=[loss])
            entry["live"] = memory.tracker().last
        if args.what_if:
            entry["what_if"] = memory.what_if(
                measure, batches=(max(args.batch // 4, 1), args.batch),
                budget_bytes=budget)
        out.append(entry)

    with executor_mod.scope_guard(executor_mod.Scope()):
        if args.config:
            import paddle_tpu.minibatch as minibatch
            cfg = _load_config(args.config)
            spec = cfg.build()
            exe0 = fluid.Executor(fluid.TPUPlace(0))
            exe0.run(spec["startup_program"])
            feeder = _feeder(fluid, cfg, spec)
            batched = minibatch.batch(cfg.train_reader,
                                      batch_size=args.batch)
            feed = feeder.feed(next(iter(batched())))
            arrs = {n: np.asarray(v.array() if hasattr(v, "array") else v)
                    for n, v in feed.items()}

            def feed_fn(b):
                import jax
                return {n: jax.ShapeDtypeStruct((b,) + a.shape[1:], a.dtype)
                        for n, a in arrs.items()}

            probe(os.path.basename(args.config), spec["main_program"],
                  spec["loss"], feed_fn, lambda b: feed)
        else:
            for name in args.smoke.split(","):
                spec = memory.build_smoke(name.strip())
                exe0 = fluid.Executor(fluid.TPUPlace(0))
                exe0.run(spec["startup"])
                probe(spec["label"], spec["main"], spec["loss"],
                      spec["feed_fn"], spec["data_fn"])

    if args.json:
        print(json.dumps({"programs": out,
                          "report": memory.memory_report()},
                         indent=2, sort_keys=True, default=str))
        return 0

    fmt = memory._fmt_bytes
    status = 0
    for entry in out:
        s = entry["static"]
        print(f"== {entry['program']} (batch {entry['batch']}) ==")
        print(f"static: args={fmt(s['argument_bytes'])} "
              f"out={fmt(s['output_bytes'])} temp={fmt(s['temp_bytes'])} "
              f"alias={fmt(s['alias_bytes'])} "
              f"code={fmt(s['generated_code_bytes'])} "
              f"total={fmt(s['total_bytes'])}")
        if s.get("donated_bytes"):
            print(f"donation: donated={fmt(s['donated_bytes'])} "
                  f"aliased={fmt(s['alias_bytes'])} "
                  f"lost={fmt(s['donation_lost_bytes'])}")
        peak = s.get("peak") or {}
        if peak:
            print(f"liveness walk: peak={fmt(peak['peak_bytes'])} at "
                  f"instruction {peak['peak_pos']}/{peak['n_instructions']}"
                  f" ({peak['live_at_peak']} buffers live)")
            for row in peak.get("top") or []:
                print(f"  {fmt(row['bytes']):>12s}  {row['instruction']}"
                      f"  <- {row['op']}")
        live = entry.get("live")
        if live:
            print(f"live after 1 step: in_use={fmt(live['bytes_in_use'])} "
                  f"peak={fmt(live['peak_bytes'])} "
                  f"(source={live['source']})"
                  + ("".join(f" {k}={fmt(v)}"
                             for k, v in (live.get("classes") or {}).items())))
        wi = entry.get("what_if")
        if wi:
            line = (f"what-if (budget {fmt(wi['budget_bytes'])}): "
                    f"max_batch={wi['max_batch']}")
            if "rel_err" in wi:
                ok = wi["rel_err"] <= 0.15
                status = status or (0 if ok else 1)
                line += (f", validated at b={wi['validate_batch']}: "
                         f"predicted={fmt(wi['predicted_bytes'])} "
                         f"measured={fmt(wi['measured_bytes'])} "
                         f"rel_err={wi['rel_err'] * 100:.1f}% "
                         f"(within 15%: {'yes' if ok else 'NO'})")
            print(line)
    if args.prometheus:
        print(telemetry.prometheus_text(), end="")
    return status


def cmd_inspect(args):
    """Read back a flight-recorder crash report (inspector.py): the JSON a
    crashed run leaves behind, rendered as the post-mortem a human wants —
    error + attributed origin + last recorded steps."""
    import json

    from paddle_tpu import inspector

    report = inspector.read_crash_report(args.dump)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0
    print(inspector.format_crash_report(report, show_program=args.program))
    return 0


def _fmt_num(v: float) -> str:
    return f"{int(v)}" if float(v).is_integer() else f"{v:.6g}"


def _analyze_programs(args):
    """-> [(label, program, feeds, fetches)] from --config / --example /
    --smoke (exactly one)."""
    import paddle_tpu as fluid  # noqa: F401 - registers ops/layers

    if args.config:
        cfg = _load_config(args.config)
        spec = cfg.build()
        fetches = [spec["loss"].name] if spec.get("loss") is not None else []
        for v in spec.get("fetch") or []:
            n = v if isinstance(v, str) else v.name
            if n not in fetches:
                fetches.append(n)
        return [(os.path.basename(args.config), spec["main_program"],
                 list(spec.get("feed_order") or []), fetches)]
    if args.example:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        name = args.example
        path = name if os.path.exists(name) else os.path.join(
            root, "examples", "fluid", f"train_{name}.py")
        if not os.path.exists(path):
            raise SystemExit(f"no such example: {args.example} "
                             f"(looked for {path})")
        spec_ = importlib.util.spec_from_file_location("paddle_tpu_example",
                                                       path)
        mod = importlib.util.module_from_spec(spec_)
        spec_.loader.exec_module(mod)
        if not hasattr(mod, "build_programs"):
            raise SystemExit(f"example '{path}' has no build_programs()")
        built = mod.build_programs()
        return [(os.path.basename(path), built["main"],
                 list(built.get("feeds") or []),
                 list(built.get("fetches") or []))]
    from . import memory
    out = []
    for name in (args.smoke or "fit_a_line").split(","):
        b = memory.build_smoke(name.strip())
        feeds = sorted(k for k, _ in b["feed_fn"](1).items()) \
            if callable(b.get("feed_fn")) else []
        out.append((b.get("label", name), b["main"], feeds,
                    [b["loss"].name]))
    return out


def cmd_analyze(args):
    """Static verification of a program: `python -m paddle_tpu analyze
    --example fit_a_line` / `--config conf.py --strict` / `--smoke resnet
    --json`. Exit 1 under --strict when error-severity diagnostics exist.
    `analyze --threads` runs the thread-safety lockset lint over the
    paddle_tpu source tree instead (exit 1 on any error finding)."""
    import json

    from .analysis import analyze_program

    if args.threads:
        from .analysis.threads import analyze_threads
        report = analyze_threads()
        if args.json:
            print(json.dumps(report.to_dict(), indent=2))
        else:
            print(report.format(show_info=not args.no_info))
        return 0 if report.ok else 1

    rc = 0
    payloads = []
    for label, program, feeds, fetches in _analyze_programs(args):
        report = analyze_program(program, feeds=feeds or None,
                                 fetches=fetches or None)
        if args.json:
            payloads.append({"program": label, **report.to_dict()})
        else:
            print(f"== {label} ==")
            print(report.format(show_info=not args.no_info))
        if args.strict and not report.ok:
            rc = 1
    if args.json:
        print(json.dumps(payloads if len(payloads) > 1 else payloads[0],
                         indent=2))
    return rc


def _serve_engine(args):
    """-> (engine, label) from --model-dir / --example / --smoke. Examples
    must export infer_feeds/infer_fetches from build_programs() (the
    serving surface the two flagship examples ship); --smoke builds a tiny
    in-process fc scorer so the command works on a bare checkout."""
    import numpy as np  # noqa: F401
    import paddle_tpu as fluid
    from paddle_tpu import executor as executor_mod
    from paddle_tpu.serving import ServingEngine

    if args.model_dir:
        return ServingEngine(args.model_dir, max_batch=args.max_batch), \
            args.model_dir
    if args.example:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        name = args.example
        path = name if os.path.exists(name) else os.path.join(
            root, "examples", "fluid", f"train_{name}.py")
        if not os.path.exists(path):
            raise SystemExit(f"no such example: {args.example} "
                             f"(looked for {path})")
        spec_ = importlib.util.spec_from_file_location(
            "paddle_tpu_serve_example", path)
        mod = importlib.util.module_from_spec(spec_)
        spec_.loader.exec_module(mod)
        built = mod.build_programs()
        if not built.get("infer_feeds") or not built.get("infer_fetches"):
            raise SystemExit(
                f"example '{path}' exports no serving surface "
                f"(build_programs() must return infer_feeds/infer_fetches)")
        scope = executor_mod.Scope()
        exe = fluid.Executor(fluid.TPUPlace(0))
        with executor_mod.scope_guard(scope):
            exe.run(built["startup"])
        return ServingEngine(built["main"],
                             feed_names=built["infer_feeds"],
                             fetch_names=built["infer_fetches"],
                             scope=scope, max_batch=args.max_batch), \
            os.path.basename(path)
    # --smoke: x[16] -> fc(32, relu) -> fc(4): compiles in well under a
    # second per bucket, exercises the whole ladder/batcher/shed stack
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        h = fluid.layers.fc(input=x, size=32, act="relu")
        pred = fluid.layers.fc(input=h, size=4)
    scope = executor_mod.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    with executor_mod.scope_guard(scope):
        exe.run(startup)
    return ServingEngine(main, feed_names=["x"], fetch_names=[pred.name],
                         scope=scope, max_batch=args.max_batch), "smoke"


def _serve_random_feed(engine, rng, rows):
    """Feed generator off the engine's declared feed geometry: ints draw
    from a small id range (valid for any vocab/table), floats from N(0,1);
    -1 inner dims (rare) default to 8."""
    import numpy as np
    feed = {}
    for name, (shape, dtype) in engine._feed_meta.items():
        dims = (rows,) + tuple(8 if d == -1 else d for d in shape[1:])
        if np.issubdtype(dtype, np.integer):
            feed[name] = rng.integers(0, 8, dims).astype(dtype)
        else:
            feed[name] = rng.standard_normal(dims).astype(dtype)
    return feed


def cmd_serve(args):
    """Concurrent-client serving benchmark: `python -m paddle_tpu serve
    --smoke` (or --example criteo_dlrm / --model-dir DIR). Spins up the
    ServingEngine + DynamicBatcher, drives a normal phase at N clients and
    an overload phase at 2N against the bounded queue, and prints one JSON
    line per phase with p50_ms/p99_ms/qps/shed_fraction/bucket_hits/
    goodput_fraction, plus an engine/batcher summary line."""
    import json

    import numpy as np
    from paddle_tpu.serving import DynamicBatcher, run_load

    engine, label = _serve_engine(args)
    rng = np.random.default_rng(0)
    rows_choices = [1, 2, 3, max(1, args.max_batch // 4)]

    def make_feed(ci, ri):
        rows = rows_choices[(ci + ri) % len(rows_choices)]
        return _serve_random_feed(engine, rng, rows)

    batcher = DynamicBatcher(engine, max_delay_ms=args.max_delay_ms,
                             max_queue_depth=args.max_queue_depth).start()
    try:
        for phase, clients in (("normal", args.clients),
                               ("overload", 2 * args.clients)):
            payload = run_load(batcher, make_feed, clients=clients,
                               requests_per_client=args.requests,
                               deadline_ms=args.deadline_ms, label=phase)
            payload["model"] = label
            print(json.dumps(payload, sort_keys=True))
    finally:
        batcher.stop()
        summary = {"model": label, "engine": engine.stats(),
                   "batcher": batcher.stats()}
        print(json.dumps(summary, sort_keys=True))
        engine.close()
    return 0


def cmd_obs(args):
    """Live observability plane smoke: start the scrapeable HTTP server
    (obs_server.py), enable request/step tracing, run a small training
    loop so the endpoints have live data, then self-scrape /metrics,
    /healthz and /spans over real HTTP and print one JSON summary line.
    With --hold the server keeps running after the loop (Ctrl-C exits) so
    an external Prometheus/curl can scrape a long-lived process."""
    import http.client
    import json

    import paddle_tpu as fluid
    from paddle_tpu import executor as executor_mod
    from paddle_tpu import memory, obs_server, tracing

    if not args.no_trace:
        tracing.enable()
    srv = obs_server.start(port=args.port)
    print(f"obs: serving http://127.0.0.1:{srv.port} "
          f"(/metrics /healthz /spans /report)", file=sys.stderr)

    with executor_mod.scope_guard(executor_mod.Scope()):
        spec = memory.build_smoke(args.smoke)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(spec["startup"])
        feed = spec["data_fn"](args.batch)
        for _ in range(args.steps):
            exe.run(spec["main"], feed=feed, fetch_list=[spec["loss"]])
            if args.interval_ms:
                time_mod.sleep(args.interval_ms / 1000.0)

    def get(route):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        try:
            conn.request("GET", route)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    st_metrics, metrics_body = get("/metrics")
    _st_health, health_body = get("/healthz")
    st_spans, spans_body = get("/spans?n=8")
    st_dyn, dyn_body = get("/dynamics?n=4")
    dyn = json.loads(dyn_body)
    summary = {
        "port": srv.port,
        "steps": args.steps,
        "metrics": {"status": st_metrics, "bytes": len(metrics_body)},
        "healthz": json.loads(health_body),
        "spans": {"status": st_spans,
                  "returned": len(json.loads(spans_body)["spans"]),
                  "buffered": len(tracing.recent_spans())},
        "dynamics": {"status": st_dyn, "enabled": dyn.get("enabled"),
                     "samples": dyn.get("samples_recorded"),
                     "programs": len(dyn.get("programs") or {})},
    }
    if args.export_trace:
        n = tracing.export_chrome_trace(args.export_trace)
        summary["chrome_trace"] = {"path": args.export_trace,
                                   "events": n}
    print(json.dumps(summary, sort_keys=True, default=str))
    if args.hold:
        print("obs: holding — Ctrl-C to exit", file=sys.stderr)
        try:
            while True:
                time_mod.sleep(1.0)
        except KeyboardInterrupt:
            pass
    obs_server.stop()
    return 0 if st_metrics == 200 and st_spans == 200 \
        and st_dyn == 200 else 1


def cmd_sentinel(args):
    """Run-sentinel drill: start the supervisor (sentinel.py), inject a
    planted step-time regression, a loss spike, and a short hang
    (--smoke), then print the alert ledger and one JSON summary line.
    Without --smoke, starts the sentinel and holds, supervising whatever
    the process's telemetry shows (Ctrl-C exits)."""
    import json

    from paddle_tpu import sentinel as sentinel_mod

    sent = sentinel_mod.start(
        report_path=args.report,
        interval_s=args.interval) if sentinel_mod.active() is None \
        else sentinel_mod.active()

    if not args.smoke:
        print("sentinel: supervising — Ctrl-C to exit", file=sys.stderr)
        try:
            while True:
                time_mod.sleep(1.0)
        except KeyboardInterrupt:
            pass
        return 0

    # 1) anomaly drill: healthy baselines, then a planted step-time
    #    regression and a loss spike — each must raise exactly one alert
    for i in range(16):
        sent.feed("step_time_regression", 0.1 + 0.001 * (i % 3))
        sentinel_mod.observe_loss(2.5 + 0.01 * (i % 3))
        sent.feed("loss_spike", 2.5 + 0.01 * (i % 3))
    a1 = sent.feed("step_time_regression", 0.35)
    a2 = sent.feed("loss_spike", 30.0)

    # 2) hang drill: a dispatch that sleeps past its deadline, then
    #    recovers — watchdog must fire AND clear
    drill = sent.inject_stall(0.8, budget_s=0.3)
    hang = None
    deadline = time_mod.time() + 5.0
    while hang is None and time_mod.time() < deadline:
        hang = sent.hang_state()
        time_mod.sleep(0.05)
    drill.join(timeout=5.0)
    recovered = sent.hang_state() is None

    for a in sent.alerts():
        print(f"[alert] {a['rule']} severity={a['severity']} "
              f"value={a['value']:.4g} z={a['zscore']:.1f} "
              f"x{a['count']}", file=sys.stderr)
    if hang is not None:
        print(f"[hang] program={hang['program']} "
              f"report={hang['report_path']} "
              f"recovered={recovered}", file=sys.stderr)

    summary = {
        "alerts": len(sent.alerts()),
        "rules_fired": sorted({a["rule"] for a in sent.alerts()}),
        "hang": {"fired": hang is not None,
                 "report": hang.get("report_path") if hang else None,
                 "recovered": recovered},
    }
    print(json.dumps(summary, sort_keys=True, default=str))
    ok = (a1 is not None and a2 is not None
          and hang is not None and recovered)
    return 0 if ok else 1


def cmd_dynamics(args):
    """Training-dynamics observatory (dynamics.py).

    --smoke trains a small program with a PLANTED dead layer (an fc whose
    output is multiplied by 0.0, so its grads are exactly zero) and a
    PLANTED update spike (the feed magnitude jumps late in the run, the
    moral equivalent of an LR spike), polling the run sentinel each step
    and serving /dynamics over real HTTP. Exits 0 iff the dead-layer
    verdict fires, the dynamics_update_ratio_spike sentinel alert fires,
    and /dynamics serves the series. --json prints the full observatory
    payload; --watch reprints the verdict table every --interval s."""
    import json

    from paddle_tpu import dynamics as dynamics_mod

    if args.json and not args.smoke:
        print(json.dumps(dynamics_mod.payload(recent=args.recent),
                         sort_keys=True, default=str))
        return 0
    if args.watch and not args.smoke:
        try:
            while True:
                p = dynamics_mod.payload(recent=1)
                verd = p.get("verdicts") or []
                print(f"dynamics: {p['samples_recorded']} samples, "
                      f"{len(verd)} non-ok verdict(s)", file=sys.stderr)
                for v in verd:
                    print(f"  {v['program']}/{v['series']}: {v['code']}",
                          file=sys.stderr)
                time_mod.sleep(args.interval)
        except KeyboardInterrupt:
            return 0

    import http.client

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import executor as executor_mod
    from paddle_tpu import obs_server
    from paddle_tpu import sentinel as sentinel_mod
    from paddle_tpu.framework import unique_name

    with unique_name.guard():
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            live = fluid.layers.fc(input=x, size=8, act="relu")
            dead = fluid.layers.fc(input=x, size=8, act="relu")
            # the planted dead layer: x0.0 kills its gradient exactly
            h = live + fluid.layers.scale(dead, scale=0.0)
            pred = fluid.layers.fc(input=h, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(
                loss, startup_program=startup)

    sent = sentinel_mod.active() or sentinel_mod.start(interval_s=3600.0)
    srv = obs_server.start(port=args.port)
    print(f"dynamics: serving http://127.0.0.1:{srv.port}/dynamics",
          file=sys.stderr)

    rng = np.random.RandomState(7)
    spike_at = args.steps - 4
    with dynamics_mod.override(True, 1), \
            executor_mod.scope_guard(executor_mod.Scope()):
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        for i in range(args.steps):
            xb = rng.randn(args.batch, 8).astype(np.float32)
            if i >= spike_at:
                xb = xb * 8.0       # the planted update spike
            yb = rng.randn(args.batch, 1).astype(np.float32)
            exe.run(main_prog, feed={"x": xb, "y": yb},
                    fetch_list=[loss])
            sent.poll()

    verd = dynamics_mod.verdicts()
    dead_fired = any(v["code"] == "dead-layer" for v in verd)
    rules_fired = sorted({a["rule"] for a in sent.alerts()
                          if a["rule"].startswith("dynamics_")})
    spike_fired = "dynamics_update_ratio_spike" in rules_fired

    def get(route):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        try:
            conn.request("GET", route)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    st_dyn, dyn_body = get("/dynamics?n=4")
    served = json.loads(dyn_body) if st_dyn == 200 else {}
    http_ok = st_dyn == 200 and bool(served.get("programs"))

    for v in verd:
        print(f"[verdict] {v['program']}/{v['series']} [{v['role']}]: "
              f"{v['code']}", file=sys.stderr)
    for a in sent.alerts():
        if a["rule"].startswith("dynamics_"):
            print(f"[alert] {a['rule']} severity={a['severity']} "
                  f"value={a['value']:.4g} z={a['zscore']:.1f}",
                  file=sys.stderr)

    summary = {
        "steps": args.steps,
        "dead_layer_verdict": dead_fired,
        "update_ratio_alert": spike_fired,
        "dynamics_rules_fired": rules_fired,
        "verdicts": [f"{v['program']}/{v['series']}:{v['code']}"
                     for v in verd],
        "http": {"status": st_dyn,
                 "programs": len(served.get("programs") or {}),
                 "samples": served.get("samples_recorded")},
    }
    if args.json:
        summary["payload"] = dynamics_mod.payload(recent=args.recent)
    print(json.dumps(summary, sort_keys=True, default=str))
    obs_server.stop()
    return 0 if dead_fired and spike_fired and http_ok else 1


def cmd_version(_args):
    import paddle_tpu
    import jax
    print(f"paddle_tpu {getattr(paddle_tpu, '__version__', '0.2.0')} "
          f"(jax {jax.__version__}, "
          f"devices: {[d.platform for d in jax.local_devices()]})")
    return 0


def cmd_perf(args):
    """Roofline performance report (roofline.py): run a smoke program (or
    read an existing trace dir) and print the per-op attribution table —
    device time, analytic FLOPs/bytes, achieved TF/s, arithmetic
    intensity, and the compute/memory/unattributed bound verdict — plus
    the step-time waterfall and MFU/duty-cycle summary."""
    import json

    from paddle_tpu import roofline

    probe = not args.no_probe
    if args.trace_dir:
        report = roofline.collect_report(args.trace_dir, (), probe=probe)
    else:
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod, memory

        with executor_mod.scope_guard(executor_mod.Scope()):
            spec = memory.build_smoke(args.smoke or "fit_a_line")
            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(spec["startup"])
            feed = spec["data_fn"](args.batch)

            def run():
                return exe.run(spec["main"], feed=feed,
                               fetch_list=[spec["loss"]])

            run()   # warm compile OUTSIDE the trace: attribute steps,
                    # not the one-off XLA compile
            report = roofline.capture(run, steps=args.steps, probe=probe)

    if report is None:
        print("perf: no report (trace empty or capture failed)")
        return 1
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True, default=str)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True, default=str))
    else:
        for line in roofline.format_report(report):
            print(line)
    return 0


def cmd_fleet(args):
    """Fleet observability report (fleet.py): per-collective bandwidth
    attribution (kind, call site, bytes, busbw, % of link roofline,
    exposed ms), the goodput ledger, and the cross-host skew line. With
    --smoke the smoke program runs on a dp mesh over every local device
    (forcing 4 host devices on CPU) so the trace actually contains
    collectives; with --trace-dir an existing trace is attributed."""
    import json
    import os

    probe = not args.no_probe
    if args.trace_dir:
        from paddle_tpu import fleet
        result = {
            "collectives": fleet.collective_table(args.trace_dir, (),
                                                  probe=probe),
            "goodput": fleet.goodput_report(),
            "snapshot": None,
        }
    else:
        # more than one device makes the smoke's dp mesh real — must be
        # set before first backend touch, harmless when already decided
        if "xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=4").strip()

        import numpy as np
        import jax

        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod, fleet, memory

        with executor_mod.scope_guard(executor_mod.Scope()):
            spec = memory.build_smoke(args.smoke or "fit_a_line")
            ndev = max(jax.local_device_count(), 1)
            spec["main"]._mesh = jax.sharding.Mesh(
                np.array(jax.local_devices()), ("dp",))
            batch = max(args.batch, ndev)
            batch -= batch % ndev     # dp-shardable batch
            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(spec["startup"])
            feed = spec["data_fn"](batch)

            def run():
                return exe.run(spec["main"], feed=feed,
                               fetch_list=[spec["loss"]])

            run()   # warm compile OUTSIDE the trace
            result = fleet.capture(run, steps=args.steps, probe=probe)

    if result is None:
        print("fleet: no report (trace empty or capture failed)")
        return 1
    if args.report:
        with open(args.report, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True, default=str)
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True, default=str))
        return 0

    from paddle_tpu import fleet
    colls = result.get("collectives")
    if colls and colls.get("rows"):
        print(f"{'Collective':20s} {'Call site':22s} {'MB':>9s} "
              f"{'busbw GB/s':>11s} {'% link':>7s} {'Exposed(ms)':>12s}")
        for r in colls["rows"]:
            bus = ("{:11.2f}".format(r["busbw_gbps"])
                   if r.get("busbw_gbps") is not None else
                   "          -")
            pct = ("{:6.1%}".format(r["pct_link"])
                   if r.get("pct_link") is not None else "     -")
            print("[coll] {:13s} {:22s} {:9.2f} {} {} {:12.3f}".format(
                r["kind"], r["site"], r["bytes"] / 1e6, bus, pct,
                r["exposed_ms"]))
        if colls.get("ici_gbps"):
            print("[coll] link roofline {:.1f} GB/s ({} participants)"
                  .format(colls["ici_gbps"],
                          colls.get("participants") or "?"))
    else:
        print("[coll] no collective events in the trace")
    for line in fleet.format_goodput(result.get("goodput")):
        print(line)
    snap = result.get("snapshot")
    if snap:
        print(fleet.format_fleet(snap))
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu",
        description="TPU-native trainer CLI (reference `paddle train`)")
    sub = parser.add_subparsers(dest="job", required=True)

    p_train = sub.add_parser("train", help="train a --config model")
    p_train.add_argument("--config", required=True)
    p_train.add_argument("--epochs", type=int, default=1)
    p_train.add_argument("--batch-size", type=int, default=32)
    p_train.add_argument("--save-dir", default=None)
    p_train.add_argument("--checkpoint-dir", default=None)
    p_train.add_argument("--resume", action="store_true")
    p_train.set_defaults(fn=cmd_train)

    p_time = sub.add_parser("time", help="steps/sec benchmark of a config")
    p_time.add_argument("--config", required=True)
    p_time.add_argument("--steps", type=int, default=20)
    p_time.add_argument("--batch-size", type=int, default=32)
    p_time.set_defaults(fn=cmd_time)

    p_cg = sub.add_parser(
        "checkgrad", help="numeric-vs-analytic gradient check of a config")
    p_cg.add_argument("--config", required=True)
    p_cg.add_argument("--batch-size", type=int, default=8)
    p_cg.add_argument("--delta", type=float, default=5e-3)
    p_cg.add_argument("--samples", type=int, default=4,
                      help="elements checked per parameter")
    p_cg.add_argument("--rtol", type=float, default=5e-2)
    p_cg.set_defaults(fn=cmd_checkgrad)

    p_infer = sub.add_parser("infer", help="run a saved inference model")
    p_infer.add_argument("--model-dir", required=True)
    p_infer.add_argument("--input", required=True,
                         help=".npz with one array per feed name")
    p_infer.set_defaults(fn=cmd_infer)

    p_tel = sub.add_parser(
        "telemetry", help="print a metrics snapshot or tail a step log")
    p_tel.add_argument("--log", default=None,
                       help="JSONL step log to summarize (see "
                            "telemetry.enable_step_log / PADDLE_TPU_STEP_LOG)")
    p_tel.add_argument("--tail", type=int, default=0,
                       help="with --log: print the last N raw events")
    p_tel.add_argument("--prometheus", action="store_true",
                       help="emit Prometheus text exposition format")
    p_tel.add_argument("--reduce", action="store_true",
                       help="allreduce the snapshot across hosts first")
    p_tel.set_defaults(fn=cmd_telemetry)

    p_ins = sub.add_parser(
        "inspect", help="read a flight-recorder crash report")
    p_ins.add_argument("dump", help="crash-report JSON written by the "
                                    "inspector flight recorder")
    p_ins.add_argument("--json", action="store_true",
                       help="print the raw report JSON instead of a summary")
    p_ins.add_argument("--program", action="store_true",
                       help="include the recorded program dump")
    p_ins.set_defaults(fn=cmd_inspect)

    p_mem = sub.add_parser(
        "memory", help="HBM footprint: static analysis, live accounting, "
                       "what-if headroom")
    p_mem.add_argument("--smoke", default="fit_a_line,resnet",
                       help="comma list of built-in smoke programs "
                            "(fit_a_line, resnet)")
    p_mem.add_argument("--config", default=None,
                       help="measure a --config model instead of the smokes")
    p_mem.add_argument("--batch", type=int, default=32,
                       help="base batch size for the static analysis")
    p_mem.add_argument("--what-if", action="store_true",
                       help="fit the headroom model and predict the max "
                            "batch under --budget-gb (exit 1 if the "
                            "validated prediction is off by more than 15%%)")
    p_mem.add_argument("--budget-gb", type=float, default=0,
                       help="HBM budget in GiB for --what-if (default: "
                            "device bytes_limit, else 16)")
    p_mem.add_argument("--report", default=None,
                       help="print the memory/OOM section of a crash report "
                            "instead of measuring")
    p_mem.add_argument("--json", action="store_true",
                       help="emit JSON instead of the human summary")
    p_mem.add_argument("--prometheus", action="store_true",
                       help="append the Prometheus exposition (hbm_*/"
                            "memory_* gauges) after the summary")
    p_mem.set_defaults(fn=cmd_memory)

    p_perf = sub.add_parser(
        "perf", help="roofline report: per-op FLOPs/bytes attribution, "
                     "bound verdicts, waterfall, MFU")
    p_perf.add_argument("--smoke", nargs="?", const="fit_a_line",
                        default=None,
                        help="run a built-in smoke program under a traced "
                             "session (fit_a_line or resnet; default "
                             "fit_a_line)")
    p_perf.add_argument("--trace-dir",
                        help="attribute an existing jax.profiler trace dir "
                             "instead of running anything")
    p_perf.add_argument("--steps", type=int, default=3,
                        help="traced steps for --smoke (default 3)")
    p_perf.add_argument("--batch", type=int, default=16,
                        help="smoke-program batch size (default 16)")
    p_perf.add_argument("--json", action="store_true",
                        help="print the full report as JSON")
    p_perf.add_argument("--report", metavar="PATH",
                        help="also write the JSON report to PATH")
    p_perf.add_argument("--no-probe", action="store_true",
                        help="skip the matmul/HBM roofline probes")
    p_perf.set_defaults(fn=cmd_perf)

    p_fleet = sub.add_parser(
        "fleet", help="fleet observability: per-collective busbw "
                      "attribution, goodput ledger, cross-host skew")
    p_fleet.add_argument("--smoke", nargs="?", const="fit_a_line",
                         default=None,
                         help="run a built-in smoke program on a dp mesh "
                              "under a traced session (fit_a_line or "
                              "resnet; default fit_a_line)")
    p_fleet.add_argument("--trace-dir",
                         help="attribute an existing jax.profiler trace "
                              "dir instead of running anything")
    p_fleet.add_argument("--steps", type=int, default=3,
                         help="traced steps for --smoke (default 3)")
    p_fleet.add_argument("--batch", type=int, default=16,
                         help="smoke-program batch size, rounded to a "
                              "multiple of the device count (default 16)")
    p_fleet.add_argument("--json", action="store_true",
                         help="print the full report as JSON")
    p_fleet.add_argument("--report", metavar="PATH",
                         help="also write the JSON report to PATH")
    p_fleet.add_argument("--no-probe", action="store_true",
                         help="skip the ICI/matmul/HBM probes")
    p_fleet.set_defaults(fn=cmd_fleet)

    p_an = sub.add_parser(
        "analyze", help="static program verification: shape/dtype/"
                        "dataflow checks + fast-path preflight, no "
                        "tracing or execution")
    p_an.add_argument("--config", default=None,
                      help="a train-style --config module; analyzes its "
                           "build() main program")
    p_an.add_argument("--example", default=None,
                      help="a shipped example: fit_a_line, criteo_dlrm, "
                           "transformer_long_context, or a path to any "
                           "module with build_programs()")
    p_an.add_argument("--smoke", nargs="?", const="fit_a_line",
                      default=None,
                      help="built-in smoke program(s), comma-separated "
                           "(fit_a_line, resnet; default fit_a_line)")
    p_an.add_argument("--json", action="store_true",
                      help="machine-readable report (counts + "
                           "diagnostics)")
    p_an.add_argument("--strict", action="store_true",
                      help="exit 1 when any error-severity diagnostic "
                           "is reported")
    p_an.add_argument("--no-info", action="store_true",
                      help="hide info-severity advisories")
    p_an.add_argument("--threads", action="store_true",
                      help="thread-safety lint over the paddle_tpu "
                           "source tree: lockset discipline, lock-order "
                           "cycles, blocking-under-lock, thread hygiene "
                           "+ census (exit 1 on any error)")
    p_an.set_defaults(fn=cmd_analyze)

    p_srv = sub.add_parser(
        "serve", help="serving benchmark: AOT bucket cache + dynamic "
                      "batcher + load shedding under concurrent clients "
                      "(normal phase, then 2x overload); JSON line per "
                      "phase with p50/p99/qps/shed/goodput")
    p_srv.add_argument("--smoke", action="store_true",
                       help="serve a tiny built-in fc scorer (default "
                            "when neither --example nor --model-dir)")
    p_srv.add_argument("--example", default=None,
                       help="a shipped example exporting a serving "
                            "surface: criteo_dlrm or "
                            "transformer_long_context")
    p_srv.add_argument("--model-dir", default=None,
                       help="a save_inference_model directory")
    p_srv.add_argument("--clients", type=int, default=4,
                       help="concurrent client threads in the normal "
                            "phase (overload runs 2x; default 4)")
    p_srv.add_argument("--requests", type=int, default=16,
                       help="requests per client per phase (default 16)")
    p_srv.add_argument("--max-batch", type=int, default=16,
                       help="top of the padded-bucket ladder (default 16)")
    p_srv.add_argument("--max-delay-ms", type=float, default=3.0,
                       help="batch-close deadline in ms (default 3)")
    p_srv.add_argument("--max-queue-depth", type=int, default=32,
                       help="bounded queue: requests beyond this shed "
                            "with ServingOverloadError (default 32)")
    p_srv.add_argument("--deadline-ms", type=float, default=None,
                       help="per-request deadline; expired requests are "
                            "shed instead of executed (default none)")
    p_srv.set_defaults(fn=cmd_serve)

    p_obs = sub.add_parser(
        "obs", help="live observability plane: scrapeable /metrics "
                    "/healthz /spans /report HTTP server + traced "
                    "training smoke; prints one JSON summary line")
    p_obs.add_argument("--port", type=int,
                       default=int(os.environ.get("PADDLE_TPU_OBS_PORT")
                                   or 0),
                       help="bind port (default $PADDLE_TPU_OBS_PORT "
                            "or 0 = ephemeral)")
    p_obs.add_argument("--smoke", default="fit_a_line",
                       help="smoke program driving the live data "
                            "(fit_a_line or resnet; default fit_a_line)")
    p_obs.add_argument("--steps", type=int, default=20,
                       help="smoke steps to run (default 20)")
    p_obs.add_argument("--batch", type=int, default=16,
                       help="smoke batch size (default 16)")
    p_obs.add_argument("--interval-ms", type=float, default=0.0,
                       help="sleep between smoke steps in ms (default 0)")
    p_obs.add_argument("--no-trace", action="store_true",
                       help="leave span tracing off (default: enabled "
                            "for the smoke)")
    p_obs.add_argument("--export-trace", default=None,
                       help="write the span ring as chrome-trace JSON "
                            "here before exiting")
    p_obs.add_argument("--hold", action="store_true",
                       help="keep serving after the smoke until Ctrl-C")
    p_obs.set_defaults(fn=cmd_obs)

    p_sent = sub.add_parser(
        "sentinel", help="run sentinel: statistical anomaly alerts + "
                         "hang watchdog; --smoke injects a stall and a "
                         "loss spike and prints the alert ledger")
    p_sent.add_argument("--smoke", action="store_true",
                        help="inject a planted regression, loss spike "
                             "and short hang, print the ledger, exit")
    p_sent.add_argument("--report", default=None,
                        help="hang report path (default "
                             "$PADDLE_TPU_SENTINEL_REPORT or "
                             "paddle_tpu_hang.json)")
    p_sent.add_argument("--interval", type=float, default=5.0,
                        help="live poll interval seconds (default 5)")
    p_sent.set_defaults(fn=cmd_sentinel)

    p_dyn = sub.add_parser(
        "dynamics", help="training-dynamics observatory: per-layer "
                         "weight/grad/update-ratio health; --smoke "
                         "plants a dead layer + update spike and "
                         "checks the verdicts, alerts and /dynamics")
    p_dyn.add_argument("--smoke", action="store_true",
                       help="train the planted-failure program, print "
                            "verdicts/alerts, exit 0 iff all fire")
    p_dyn.add_argument("--json", action="store_true",
                       help="print the observatory payload as JSON "
                            "(with --smoke: appended to the summary)")
    p_dyn.add_argument("--watch", action="store_true",
                       help="reprint the verdict table every --interval "
                            "seconds until Ctrl-C")
    p_dyn.add_argument("--steps", type=int, default=24,
                       help="smoke steps (default 24; the last 4 carry "
                            "the planted spike)")
    p_dyn.add_argument("--batch", type=int, default=16,
                       help="smoke batch size (default 16)")
    p_dyn.add_argument("--port", type=int, default=0,
                       help="obs-server port for /dynamics (default 0 = "
                            "ephemeral)")
    p_dyn.add_argument("--recent", type=int, default=16,
                       help="rows per series in --json output")
    p_dyn.add_argument("--interval", type=float, default=2.0,
                       help="--watch refresh seconds (default 2)")
    p_dyn.set_defaults(fn=cmd_dynamics)

    p_ver = sub.add_parser("version")
    p_ver.set_defaults(fn=cmd_version)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
