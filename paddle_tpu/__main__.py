"""`python -m paddle_tpu <job>` — the `paddle` CLI (see cli.py)."""
import sys

from .cli import main

sys.exit(main())
