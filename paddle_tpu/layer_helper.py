"""LayerHelper: parameter-creation glue shared by all layers
(reference: python/paddle/fluid/layer_helper.py)."""

from __future__ import annotations

from typing import Optional

from .framework import unique_name
from .framework.framework import (Parameter, Variable, default_main_program,
                                  default_startup_program)
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    @property
    def param_attr(self):
        return ParamAttr.to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr.to_attr(self.kwargs.get("bias_attr"))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [attr]
        if len(attr) != 1 and len(attr) != length:
            raise ValueError("parameter number mismatch")
        if len(attr) == 1 and length != 1:
            def clone(a):
                import copy
                c = copy.copy(a)
                c.name = None  # each replica gets its own generated name
                return c
            attr = [attr[0]] + [clone(attr[0]) for _ in range(length - 1)]
        return attr

    def iter_inputs_and_params(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        attrs = self.multiple_param_attr(len(inputs))
        yield from zip(inputs, attrs)

    def multiple_input(self, input_param_name="input"):
        ipt = self.kwargs[input_param_name]
        return list(ipt) if isinstance(ipt, (list, tuple)) else [ipt]

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError(f"{self.layer_type} expects one input")
        return inputs[0]

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for each in inputs:
            if dtype is None:
                dtype = each.dtype
            elif dtype != each.dtype:
                raise ValueError("all inputs must have the same dtype")
        return dtype

    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None) -> Parameter:
        attr = ParamAttr.to_attr(attr)
        if default_initializer is None:
            if is_bias:
                attr.set_default_bias_initializer()
            else:
                attr.set_default_param_initializer()
        else:
            attr.set_default_initializer(default_initializer)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w"]))
        init = attr.initializer
        # parameter in the main program …
        param = self.main_program.global_block().create_parameter(
            shape=shape, dtype=dtype, **attr.to_kwargs())
        # … and its twin + init op in the startup program
        startup_param = self.startup_program.global_block().create_parameter(
            shape=shape, dtype=dtype,
            **{k: v for k, v in attr.to_kwargs().items()})
        init(startup_param, self.startup_program.global_block())
        return param

    def create_tmp_variable(self, dtype, stop_gradient=False) -> Variable:
        return self.block.create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype, stop_gradient=stop_gradient)

    def create_variable(self, **kwargs) -> Variable:
        return self.block.create_var(**kwargs)

    def create_global_variable(self, persistable=False, **kwargs) -> Variable:
        return self.main_program.global_block().create_var(
            persistable=persistable, **kwargs)

    def set_variable_initializer(self, var, initializer):
        # mirror var into startup program and initialize it there
        sv = self.startup_program.global_block().create_var(
            name=var.name, shape=var.shape, dtype=var.dtype, persistable=True)
        initializer(sv, self.startup_program.global_block())
        var.persistable = True
        return var

    def append_op(self, **kwargs):
        return self.block.append_op(**kwargs)

    def get_parameter(self, name):
        """Look up an existing parameter by name (reference
        layer_helper.py; used e.g. to share the CRF transition between
        linear_chain_crf and crf_decoding)."""
        param = self.main_program.global_block().var(name)
        if not isinstance(param, Parameter):
            raise ValueError(f"no parameter named {name}")
        return param

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        """Add a bias over dims [dim_start, dim_end) of input
        (reference layer_helper.py append_bias_op)."""
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if not bias_attr:
            return input_var
        if bias_attr.name is None:
            bias_attr.name = unique_name.generate(".".join([self.name, "b"]))
        b = self.create_parameter(attr=bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        tmp = self.create_tmp_variable(dtype=input_var.dtype)
        self.append_op(type="elementwise_add",
                       inputs={"X": [input_var], "Y": [b]},
                       outputs={"Out": [tmp]},
                       attrs={"axis": dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_tmp_variable(dtype=input_var.dtype)
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [tmp]}, attrs=act)
        return tmp
