"""Tiled MXU Pallas kernels for conv2d forward / grad-input / grad-filter
(the kernel phase of the MFU campaign: the scheduling levers are landed
and the plateau is per kernel; /opt/skills/guides/pallas_guide.md
patterns, ops/pallas_attention.py and the fusion bn+act kernel as the
in-repo templates).

Tiling: NHWC operands, bf16 on the MXU datapath with f32 VMEM
accumulation (preferred_element_type), channels in 128-lane tiles. The
grid walks one output row per step with an H *input* block of size 1 —
at block size 1 the BlockSpec index map addresses *rows*, so
strided/dilated input-row selection (`oh*stride + kh*dilation`) happens
in the index map and no halo exchange or revisit is needed. Inside the
kernel the kw taps unroll as a Python loop of strided row slices feeding
[W-ish, Ci] x [Ci, Co] MXU dots into an f32 accumulator that carries
across the sequential (innermost) reduction dim of the grid:

  forward      grid (N, OH/BH, Co/128, KH*Ci/128 * BH), acc [BH, OW, 128]
  grad-filter  grid (KH, Ci/128, Co/128, N*OH), acc [KW, 128, 128]
  grad-input   = the forward kernel on the stride-dilated cotangent with
                 the spatially flipped filter and transposed-conv padding
                 (lo = (K-1)*d - p, hi = H - Hd + p), so one kernel body
                 serves both directions.

BH is the multi-row pipelining factor (BENCH_r06's headroom spend): the
filter tile is by far the heaviest HBM stream of the row-walk (for a
3x3 C=128 ResNet block each output row re-reads KH*KW*Ci*Co filter
bytes against one input row), so the reduction dim is extended by BH
output rows with the row index *innermost*. Consecutive grid steps then
keep the same filter block index and Pallas skips the copy — filter
traffic divides by BH while the f32 accumulator grows to [BH, OW, 128]
rows of VMEM, double-buffered input rows stream as before. BH is the
largest of {8, 4, 2, 1} that divides OH and fits the VMEM row budget.

`conv2d_q8` is the forward kernel on int8 operands (quant.py's O3
routing): int8 x/w tiles, int32 VMEM accumulation, and the per-channel
dequantization vector applied to the output row while it is still in
VMEM — the MXU runs int8 dots at twice the bf16 rate, which is where
the O3 images/sec over O2 comes from.

`conv2d_stats` is the forward kernel with the Co tile as the *outermost*
grid dim and per-channel sum/sum-of-squares carried in VMEM scratch: the
conv->bn->act training window (ops/fusion.py) gets batch statistics for
free while the output row is still in VMEM, then `bn_apply` normalizes
(+activation) in one more sweep — the window never re-reads the conv
output from HBM to compute statistics.

Eligibility is one shared predicate (`ineligible`) for forward AND
backward: the generated grad path vjp's the forward lowering
(registry.generic_grad_lower) and pallas_call is not differentiable, so
the forward may only take the Pallas route when the grad lowering will
too. Unsupported combinations fall back to lax.conv with a
reason-labelled `pallas_fallback_total{op,reason}` counter (mirroring
fusion_fallback_total), never an error. On CPU (the test mesh) the
kernels run under the Pallas interpreter — same code path, no Mosaic
compile — so parity gates run under JAX_PLATFORMS=cpu.
"""

from __future__ import annotations

import contextlib
import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from .pallas_attention import _compiler_params, _dot, _interpret, _scratch

__all__ = [
    "FALLBACK_REASONS", "KERNELS", "PALLAS_CONV", "bn_apply", "conv2d",
    "conv2d_grad_filter", "conv2d_grad_input", "conv2d_q8",
    "conv2d_stats", "count_fallback", "count_hit", "ineligible",
    "suppress_counters", "supports",
]

PALLAS_CONV = os.environ.get("PADDLE_TPU_PALLAS_CONV", "1") == "1"

_LANE = 128

# Every reason `ineligible` can return (pinned by check_pallas_table —
# a reason string produced but not listed here would ship an unlabelled
# fallback counter).
FALLBACK_REASONS = frozenset(
    {"disabled", "rank", "groups", "dtype", "channels", "attrs",
     "geometry"})

# VMEM width budget: each grid step keeps a [Wp, 128] bf16 input row, an
# [OW, 128] f32 accumulator and an [OW, 128] output row resident (double
# buffered by the pipeline), and grad-input re-pads the cotangent to
# W + KWe - 1 with OW' = W. 2048 lanes bounds that resident set around
# 3 MB — comfortably inside the ~16 MB/core VMEM of current TPUs — so
# wider shapes fall back to lax.conv instead of failing Mosaic
# compilation at run time.
_MAX_W = 2048


def ineligible(x, w, strides, paddings, dilations, groups=1):
    """None when the Pallas kernels apply, else the fallback reason.

    `x` is the NHWC operand *post mxu_cast* (AMP O1/O2 convs are bf16 by
    here; a plain f32 conv reads "dtype"), `w` the OIHW filter. The
    predicate is shared verbatim by forward and grad routing — see the
    module docstring for why they must agree — so it also encodes the
    grad-input geometry: transposed-conv padding stays non-negative iff
    p <= (K-1)*d per spatial dim.
    """
    if not PALLAS_CONV:
        return "disabled"
    if getattr(x, "ndim", 0) != 4 or getattr(w, "ndim", 0) != 4:
        return "rank"
    if (groups or 1) != 1:
        return "groups"   # depthwise/grouped convs keep the lax path
    if getattr(x, "dtype", None) != jnp.bfloat16 or \
            getattr(w, "dtype", None) != jnp.bfloat16:
        return "dtype"
    ci = x.shape[3]
    co, ci_w, kh, kw = w.shape
    if ci % _LANE or co % _LANE or ci_w != ci:
        return "channels"
    if len(strides) != 2 or len(paddings) != 2 or len(dilations) != 2:
        # e.g. Paddle's legal 4-element [top, bottom, left, right]
        # paddings — attrs the symmetric tiling doesn't model
        return "attrs"
    sh, sw = strides
    ph, pw = paddings
    dh, dw = dilations
    keh, kew = (kh - 1) * dh + 1, (kw - 1) * dw + 1
    oh = (x.shape[1] + 2 * ph - keh) // sh + 1
    ow = (x.shape[2] + 2 * pw - kew) // sw + 1
    if oh < 1 or ow < 1 or ph > keh - 1 or pw > kew - 1:
        return "geometry"
    if max(x.shape[2] + 2 * pw, x.shape[2] + kew - 1, ow) > _MAX_W:
        # padded width (forward/grad-filter), the grad-input re-pad, or
        # the accumulator row would overflow the VMEM row budget
        return "geometry"
    return None


def supports(x, w, strides, paddings, dilations, groups=1) -> bool:
    """Static eligibility, pallas_attention.supports-style."""
    return ineligible(x, w, strides, paddings, dilations, groups) is None


_SUPPRESS_COUNTERS = False


@contextlib.contextmanager
def suppress_counters():
    """Silence count_hit/count_fallback on this thread of lowering:
    generic_grad_lower's vjp re-traces the forward lowering, which would
    book a second pallas_fallback_total/pallas_kernel_total sample for a
    forward op that already counted itself when the forward graph was
    traced — inflating the coverage-trending series."""
    global _SUPPRESS_COUNTERS
    prev = _SUPPRESS_COUNTERS
    _SUPPRESS_COUNTERS = True
    try:
        yield
    finally:
        _SUPPRESS_COUNTERS = prev


def count_fallback(op: str, reason: str):
    if _SUPPRESS_COUNTERS:
        return
    from .. import telemetry
    telemetry.counter(
        "pallas_fallback_total",
        "conv lowerings that fell back from the Pallas kernel suite to "
        "the lax.conv path, by op and gating reason",
        labels=("op", "reason")).labels(op=op, reason=reason).inc()


def count_hit(op: str):
    if _SUPPRESS_COUNTERS:
        return
    from .. import telemetry
    telemetry.counter(
        "pallas_kernel_total",
        "conv lowerings served by the Pallas kernel suite, by op",
        labels=("op",)).labels(op=op).inc()


# --- kernel bodies ------------------------------------------------------

def _taps(x_row, kw_n, dw, sw, ow):
    """The kw tap slices of one padded input row: [OW, 128] each, strided
    by the conv stride. Slice bounds always fit the padded width — the
    widest tap ends at (KW-1)*dw + (OW-1)*sw + 1 = Wp by the output-dim
    equation."""
    for kw in range(kw_n):
        yield lax.slice(x_row, (kw * dw, 0),
                        (kw * dw + (ow - 1) * sw + 1, x_row.shape[1]),
                        (sw, 1))


def _dot_i32(a, b, dims):
    """int8 x int8 -> int32 MXU dot (the 2x-rate datapath)."""
    return lax.dot_general(a, b, (dims, ((), ())),
                           preferred_element_type=jnp.int32)


def _fwd_kernel(x_ref, w_ref, *refs, kw_n, dw, sw, ow, n_s, bh):
    """Grid (N, OH/BH, Co/128, KH*Ci/128 * BH): one output row [OW, 128]
    per (n, oh, co), reduction taps streamed innermost with the H-block
    row index `hb` cycling fastest — so the filter block index is
    unchanged for BH consecutive steps and its copy is skipped (module
    docstring). Quantized form (5 refs): int8 operands, int32
    accumulator, per-channel dequant vector applied on the way out."""
    import jax.experimental.pallas as pl
    if len(refs) == 3:
        dq_ref, o_ref, acc = refs
    else:
        (o_ref, acc), dq_ref = refs, None
    ss2 = pl.program_id(3)
    ss = ss2 // bh                 # reduction step: kh * n_ci + ci tile
    hb = ss2 % bh                  # output row within the H block

    @pl.when(ss == 0)
    def _zero():
        acc[pl.ds(hb, 1)] = jnp.zeros((1,) + acc.shape[1:], acc.dtype)

    dot = _dot if acc.dtype == jnp.float32 else _dot_i32
    x_row = x_ref[0, 0]            # [Wp, 128] one padded input row
    wt = w_ref[0]                  # [KW, 128, 128] one kh tap
    total = None
    for kw, xs in enumerate(_taps(x_row, kw_n, dw, sw, ow)):
        t = dot(xs, wt[kw], ((1,), (0,)))
        total = t if total is None else total + t
    acc[pl.ds(hb, 1)] += total[None]

    @pl.when(ss == n_s - 1)
    def _finish():
        row = acc[pl.ds(hb, 1)]
        if dq_ref is not None:
            row = row.astype(jnp.float32) * dq_ref[...]
        o_ref[0, pl.ds(hb, 1)] = row.astype(o_ref.dtype)


def _fwd_stats_kernel(x_ref, w_ref, o_ref, sum_ref, sq_ref, acc, ssum, ssq,
                      *, kw_n, dw, sw, ow, n_s, n_n, n_oh):
    """Forward + per-channel sum/sumsq of the rounded output. Grid
    (Co/128, N, OH, KH*Ci/128) — Co outermost so the [1, 128] statistics
    scratch carries across every output row of its channel tile. The
    statistics are of the *bf16-rounded* y, matching what the unfused bn
    would read back from HBM."""
    import jax.experimental.pallas as pl
    nn = pl.program_id(1)
    hh = pl.program_id(2)
    ss = pl.program_id(3)

    @pl.when(jnp.logical_and(nn == 0, jnp.logical_and(hh == 0, ss == 0)))
    def _zero_stats():
        ssum[...] = jnp.zeros_like(ssum)
        ssq[...] = jnp.zeros_like(ssq)

    @pl.when(ss == 0)
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    x_row = x_ref[0, 0]
    wt = w_ref[0]
    for kw, xs in enumerate(_taps(x_row, kw_n, dw, sw, ow)):
        acc[...] += _dot(xs, wt[kw], ((1,), (0,)))

    @pl.when(ss == n_s - 1)
    def _finish():
        y = acc[...].astype(o_ref.dtype)
        o_ref[0, 0] = y
        yf = y.astype(jnp.float32)
        ssum[...] += jnp.sum(yf, axis=0, keepdims=True)
        ssq[...] += jnp.sum(yf * yf, axis=0, keepdims=True)

    @pl.when(jnp.logical_and(nn == n_n - 1,
                             jnp.logical_and(hh == n_oh - 1, ss == n_s - 1)))
    def _write_stats():
        sum_ref[...] = ssum[...]
        sq_ref[...] = ssq[...]


def _wgrad_kernel(x_ref, do_ref, o_ref, acc, *, kw_n, dw, sw, ow, m_n):
    """Grid (KH, Ci/128, Co/128, N*OH): each step contracts one padded
    input row against one cotangent row over OW, accumulating all KW taps
    of a [128, 128] dW tile in one visit."""
    import jax.experimental.pallas as pl
    mm = pl.program_id(3)

    @pl.when(mm == 0)
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    x_row = x_ref[0, 0]            # [Wp, 128ci]
    do_row = do_ref[0, 0]          # [OW, 128co]
    for kw, xs in enumerate(_taps(x_row, kw_n, dw, sw, ow)):
        acc[kw] += _dot(xs, do_row, ((0,), (0,)))

    @pl.when(mm == m_n - 1)
    def _finish():
        o_ref[0] = acc[...].astype(o_ref.dtype)


def _bn_apply_kernel(x_ref, scale_ref, bias_ref, mean_ref, var_ref, *refs,
                     eps, act):
    """Normalize + activation given precomputed statistics — phase 1 of
    the fusion bn+act kernel with the statistics pass replaced by the
    conv2d_stats epilogue."""
    if act is None:
        (ybn_ref,) = refs
        yact_ref = None
    else:
        ybn_ref, yact_ref = refs
    inv = jax.lax.rsqrt(var_ref[...] + eps)
    xb = x_ref[...].astype(jnp.float32)
    y = (xb - mean_ref[...]) * (inv * scale_ref[...]) + bias_ref[...]
    y = y.astype(ybn_ref.dtype)
    ybn_ref[...] = y
    if yact_ref is not None:
        yact_ref[...] = act(y)


# --- pallas_call wrappers -----------------------------------------------

def _block_h(oh: int, ow: int) -> int:
    """Pipelining factor: the largest H block that divides OH and keeps
    the [BH, OW, 128] accumulator + the output block inside a ~3 MB
    VMEM slice of the row budget (4+2 bytes per element, x2 pipeline)."""
    return next(b for b in (8, 4, 2, 1) if oh % b == 0 and b * ow <= 4096)


def _conv_call(x, w_hwio, strides, dilations, pads, out_dtype=None,
               stats=False, dq=None):
    """Shared conv driver. `x` NHWC (unpadded), `w_hwio` [KH, KW, Ci, Co],
    `pads` explicit ((lo_h, hi_h), (lo_w, hi_w)) so the grad-input call
    can pass the asymmetric transposed-conv padding. `dq` (f32 [1, Co])
    selects the int8 form: int8 operands, int32 accumulation, dequant
    on the output row in VMEM."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    n, _, _, ci = x.shape
    kh, kw_n, _, co = w_hwio.shape
    sh, sw = strides
    dh, dw = dilations
    xp = jnp.pad(x, ((0, 0), tuple(pads[0]), tuple(pads[1]), (0, 0)))
    hp, wp = xp.shape[1], xp.shape[2]
    oh = (hp - ((kh - 1) * dh + 1)) // sh + 1
    ow = (wp - ((kw_n - 1) * dw + 1)) // sw + 1
    n_ci = ci // _LANE
    n_s = kh * n_ci
    out_dtype = out_dtype or x.dtype

    if not stats:
        bh = _block_h(oh, ow)
        grid = (n, oh // bh, co // _LANE, n_s * bh)
        x_spec = pl.BlockSpec(
            (1, 1, wp, _LANE),
            lambda nn, hh, cc, ss: (
                nn, (hh * bh + ss % bh) * sh + (ss // bh // n_ci) * dh, 0,
                (ss // bh) % n_ci))
        w_spec = pl.BlockSpec(
            (1, kw_n, _LANE, _LANE),
            lambda nn, hh, cc, ss: (ss // bh // n_ci, 0,
                                    (ss // bh) % n_ci, cc))
        o_spec = pl.BlockSpec((1, bh, ow, _LANE),
                              lambda nn, hh, cc, ss: (nn, hh, 0, cc))
        in_specs = [x_spec, w_spec]
        operands = [xp, w_hwio]
        acc_dtype = jnp.float32
        if dq is not None:
            in_specs.append(pl.BlockSpec((1, _LANE),
                                         lambda nn, hh, cc, ss: (0, cc)))
            operands.append(dq)
            acc_dtype = jnp.int32
        kernel = functools.partial(_fwd_kernel, kw_n=kw_n, dw=dw, sw=sw,
                                   ow=ow, n_s=n_s, bh=bh)
        return pl.pallas_call(
            kernel, grid=grid, in_specs=in_specs, out_specs=o_spec,
            out_shape=jax.ShapeDtypeStruct((n, oh, ow, co), out_dtype),
            scratch_shapes=[pltpu.VMEM((bh, ow, _LANE), acc_dtype)],
            interpret=_interpret(),
            compiler_params=_compiler_params(
                ("parallel", "parallel", "parallel", "arbitrary")),
        )(*operands)

    grid = (co // _LANE, n, oh, n_s)
    x_spec = pl.BlockSpec(
        (1, 1, wp, _LANE),
        lambda cc, nn, hh, ss: (nn, hh * sh + (ss // n_ci) * dh, 0,
                                ss % n_ci))
    w_spec = pl.BlockSpec(
        (1, kw_n, _LANE, _LANE),
        lambda cc, nn, hh, ss: (ss // n_ci, 0, ss % n_ci, cc))
    o_spec = pl.BlockSpec((1, 1, ow, _LANE),
                          lambda cc, nn, hh, ss: (nn, hh, 0, cc))
    vec_spec = pl.BlockSpec((1, _LANE), lambda cc, nn, hh, ss: (0, cc))
    kernel = functools.partial(_fwd_stats_kernel, kw_n=kw_n, dw=dw, sw=sw,
                               ow=ow, n_s=n_s, n_n=n, n_oh=oh)
    return pl.pallas_call(
        kernel, grid=grid, in_specs=[x_spec, w_spec],
        out_specs=[o_spec, vec_spec, vec_spec],
        out_shape=[jax.ShapeDtypeStruct((n, oh, ow, co), out_dtype),
                   jax.ShapeDtypeStruct((1, co), jnp.float32),
                   jax.ShapeDtypeStruct((1, co), jnp.float32)],
        scratch_shapes=[_scratch((ow, _LANE)), _scratch((1, _LANE)),
                        _scratch((1, _LANE))],
        interpret=_interpret(),
        compiler_params=_compiler_params(
            ("parallel", "arbitrary", "arbitrary", "arbitrary")),
    )(xp, w_hwio)


def conv2d(x, w, strides, paddings, dilations, out_dtype=None):
    """x [N, H, W, Ci] bf16, w [Co, Ci, KH, KW] bf16 -> y [N, OH, OW, Co].
    Caller must have passed the `ineligible` gate."""
    ph, pw = paddings
    return _conv_call(x, jnp.transpose(w, (2, 3, 1, 0)), strides, dilations,
                      ((ph, ph), (pw, pw)), out_dtype=out_dtype)


def conv2d_stats(x, w, strides, paddings, dilations, out_dtype=None):
    """conv2d plus per-channel (sum, sum-of-squares) of the rounded
    output: (y, csum [Co], csq [Co]) — the fused conv->bn->act window's
    statistics come for free from VMEM."""
    ph, pw = paddings
    y, csum, csq = _conv_call(
        x, jnp.transpose(w, (2, 3, 1, 0)), strides, dilations,
        ((ph, ph), (pw, pw)), out_dtype=out_dtype, stats=True)
    return y, csum.reshape(-1), csq.reshape(-1)


def conv2d_q8(x, w, strides, paddings, dilations, dq, out_dtype=None):
    """Quantized forward: x [N, H, W, Ci] int8, w [Co, Ci, KH, KW] int8,
    dq f32 [Co] the combined activation*weight dequant scales
    (quant.qconv2d builds them). int32 VMEM accumulation, dequantized to
    `out_dtype` (default bf16) on the output row. Caller must have
    passed quant.ineligible_conv — which requires the `ineligible` gate
    here, so the bf16 grad kernels keep agreeing with the route."""
    ph, pw = paddings
    return _conv_call(x, jnp.transpose(w, (2, 3, 1, 0)), strides,
                      dilations, ((ph, ph), (pw, pw)),
                      out_dtype=out_dtype or jnp.bfloat16,
                      dq=jnp.asarray(dq, jnp.float32).reshape(1, -1))


def conv2d_grad_input(dout, w, x_hw, strides, paddings, dilations,
                      out_dtype=None):
    """dL/dx as a transposed conv through the forward kernel: dilate the
    cotangent by the stride, flip the filter spatially and swap its
    channel axes, pad lo=(K-1)*d-p / hi=H-Hd+p (both non-negative by the
    shared gate), then run the stride-1 forward."""
    sh, sw = strides
    ph, pw = paddings
    dh, dw = dilations
    co, ci, kh, kw = w.shape
    h, wdim = x_hw
    n, oh, ow, _ = dout.shape
    hd, wd = (oh - 1) * sh + 1, (ow - 1) * sw + 1
    if sh > 1 or sw > 1:
        dd = jnp.zeros((n, hd, wd, co), dout.dtype)
        dd = dd.at[:, ::sh, ::sw, :].set(dout)
    else:
        dd = dout
    keh, kew = (kh - 1) * dh + 1, (kw - 1) * dw + 1
    w_t = jnp.transpose(jnp.flip(w, (2, 3)), (2, 3, 0, 1))  # [KH,KW,Co,Ci]
    return _conv_call(
        dd, w_t, (1, 1), dilations,
        ((keh - 1 - ph, h - hd + ph), (kew - 1 - pw, wdim - wd + pw)),
        out_dtype=out_dtype)


def conv2d_grad_filter(x, dout, kernel_hw, strides, paddings, dilations,
                       out_dtype=None):
    """dL/dw [Co, Ci, KH, KW]: per-(kh, ci, co) tiles accumulated over the
    N*OH row pairs in f32 scratch, rounded once at the end."""
    import jax.experimental.pallas as pl
    n, _, _, ci = x.shape
    _, oh, ow, co = dout.shape
    kh, kw_n = kernel_hw
    sh, sw = strides
    ph, pw = paddings
    dh, dw = dilations
    xp = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    wp = xp.shape[2]
    m_n = n * oh
    grid = (kh, ci // _LANE, co // _LANE, m_n)
    x_spec = pl.BlockSpec(
        (1, 1, wp, _LANE),
        lambda kk, ii, cc, mm: (mm // oh, (mm % oh) * sh + kk * dh, 0, ii))
    do_spec = pl.BlockSpec(
        (1, 1, ow, _LANE), lambda kk, ii, cc, mm: (mm // oh, mm % oh, 0, cc))
    o_spec = pl.BlockSpec((1, kw_n, _LANE, _LANE),
                          lambda kk, ii, cc, mm: (kk, 0, ii, cc))
    kernel = functools.partial(_wgrad_kernel, kw_n=kw_n, dw=dw, sw=sw,
                               ow=ow, m_n=m_n)
    g_hwio = pl.pallas_call(
        kernel, grid=grid, in_specs=[x_spec, do_spec], out_specs=o_spec,
        out_shape=jax.ShapeDtypeStruct((kh, kw_n, ci, co),
                                       out_dtype or x.dtype),
        scratch_shapes=[_scratch((kw_n, _LANE, _LANE))],
        interpret=_interpret(),
        compiler_params=_compiler_params(
            ("parallel", "parallel", "parallel", "arbitrary")),
    )(xp, dout)
    return jnp.transpose(g_hwio, (3, 2, 0, 1))


def bn_apply(x2, scale, bias, mean, var, eps, act_fn):
    """x2 [M, C] bf16 (C % 128 == 0, M % 8 == 0); scale/bias/mean/var f32
    [C]. Returns (ybn, yact) with yact None when act_fn is — the fusion
    bn+act kernel's normalize phase, statistics supplied by
    conv2d_stats."""
    import jax.experimental.pallas as pl
    m_total, c = x2.shape
    bc = _LANE
    bm = next(b for b in (512, 256, 128, 64, 32, 16, 8) if m_total % b == 0)
    grid = (c // bc, m_total // bm)
    x_spec = pl.BlockSpec((bm, bc), lambda cc, mm: (mm, cc))
    vec_spec = pl.BlockSpec((1, bc), lambda cc, mm: (0, cc))
    out_specs = [x_spec] + ([x_spec] if act_fn is not None else [])
    out_shape = [jax.ShapeDtypeStruct((m_total, c), x2.dtype)]
    if act_fn is not None:
        out_shape.append(jax.ShapeDtypeStruct((m_total, c), x2.dtype))
    kernel = functools.partial(_bn_apply_kernel, eps=eps, act=act_fn)
    outs = pl.pallas_call(
        kernel, grid=grid,
        in_specs=[x_spec, vec_spec, vec_spec, vec_spec, vec_spec],
        out_specs=out_specs, out_shape=out_shape,
        interpret=_interpret(),
        compiler_params=_compiler_params(("parallel", "parallel")),
    )(x2, scale.reshape(1, c), bias.reshape(1, c), mean.reshape(1, c),
      var.reshape(1, c))
    if act_fn is not None:
        return outs[0], outs[1]
    return outs[0], None


# Dispatch table: which registered op types route through this suite, and
# with which kernels. check_pallas_table pins it against ops/registry.py
# and fusion.CONV_OPS — an op listed here but not dispatched (or vice
# versa) silently loses the kernel, so the lint fails instead.
KERNELS = {
    "conv2d": (conv2d, conv2d_stats),
    "depthwise_conv2d": (conv2d,),        # groups gate: always falls back
    "conv2d_grad": (conv2d_grad_input, conv2d_grad_filter),
    "depthwise_conv2d_grad": (conv2d_grad_input, conv2d_grad_filter),
}
