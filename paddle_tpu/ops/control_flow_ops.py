"""Control-flow ops: while / conditional_block / rnn / tensor arrays.

TPU-native equivalents of the reference's scope-mutating control flow
(reference: paddle/fluid/operators/while_op.cc:35,96,
conditional_block_op.cc, recurrent_op.cc:222 + StepScopes :53,
tensor_array_read_write_op.cc, lod_rank_table.cc, shrink_rnn_memory_op.cc).
The reference interprets sub-blocks against child scopes; here each
sub-block lowers into the parent XLA computation as
`lax.while_loop` / `lax.cond` / `lax.scan` with explicit carries — the
functionalized form of the reference's step scopes.

LoDTensorArray: the reference grows arrays dynamically per step. XLA needs
static shapes, so a TensorArray is a fixed-capacity buffer + a length
scalar; writes are `dynamic_update_index` at traced indices. Capacity is
taken from the first pre-loop write or the `capacity` attr.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..framework.desc import BlockRef
from .common import in_var, set_out
from .registry import NO_GRAD, op


class TensorArrayVal:
    """Fixed-capacity tensor array: buffer [cap, ...] + length scalar."""

    def __init__(self, buffer, length):
        self.buffer = buffer
        self.length = length

    def __repr__(self):
        return f"TensorArrayVal(cap={self.buffer.shape[0]}, len={self.length})"


def _ta_flatten(ta):
    return (ta.buffer, ta.length), None


def _ta_unflatten(aux, children):
    return TensorArrayVal(*children)


jax.tree_util.register_pytree_node(TensorArrayVal, _ta_flatten, _ta_unflatten)

DEFAULT_ARRAY_CAPACITY = 128


def _scalar_i32(x):
    return jnp.asarray(x).reshape(()).astype(jnp.int32)


@op("write_to_array", grad=NO_GRAD)
def _write_to_array(ctx, op_, ins):
    """array[i] = x (reference tensor_array_read_write_op.cc WriteToArray).
    Out aliases the input array var; growing past the current buffer
    allocates capacity (only legal outside lax control flow)."""
    x = jnp.asarray(ins["X"][0])
    i = _scalar_i32(ins["I"][0])
    arr = ins.get("Out", [None])[0]
    if arr is None or not isinstance(arr, TensorArrayVal):
        cap = int(op_.attr("capacity", DEFAULT_ARRAY_CAPACITY))
        buf = jnp.zeros((cap,) + x.shape, x.dtype)
        arr = TensorArrayVal(buf, _scalar_i32(0))
    buf = lax.dynamic_update_index_in_dim(arr.buffer, x, i, axis=0)
    length = jnp.maximum(arr.length, i + 1)
    return {"Out": [TensorArrayVal(buf, length)]}


@op("read_from_array", grad=NO_GRAD)
def _read_from_array(ctx, op_, ins):
    arr = ins["X"][0]
    assert isinstance(arr, TensorArrayVal), "read_from_array needs an array"
    i = _scalar_i32(ins["I"][0])
    return {"Out": [lax.dynamic_index_in_dim(arr.buffer, i, axis=0,
                                             keepdims=False)]}


@op("lod_array_length", grad=NO_GRAD)
def _lod_array_length(ctx, op_, ins):
    arr = ins["X"][0]
    assert isinstance(arr, TensorArrayVal)
    return {"Out": [arr.length.reshape(1).astype(jnp.int64)]}


def _block_writes(program, block_idx) -> List[str]:
    """All var names written by a block (recursively through sub-blocks)."""
    writes: List[str] = []
    seen = set()
    block = program.block(block_idx)
    for o in block.ops:
        for name in o.output_arg_names:
            if name not in seen:
                seen.add(name)
                writes.append(name)
        for a in o.desc.attrs.values():
            if isinstance(a, BlockRef):
                for name in _block_writes(program, a.idx):
                    if name not in seen:
                        seen.add(name)
                        writes.append(name)
    return writes


@op("while", grad=NO_GRAD, no_kernel=True)
def _while(ctx, op_, ins):
    """while(Condition) { sub_block } (reference while_op.cc:35).

    Carries = every var the sub-block writes that already has a value in the
    outer env (loop state must be initialized before the loop), plus the
    condition var. Everything else the sub-block reads is closed over.
    """
    program = ctx.program
    sub = op_.attr("sub_block")
    assert isinstance(sub, BlockRef)
    cond_name = op_.desc.inputs["Condition"][0]

    writes = _block_writes(program, sub.idx)
    carry_names = [n for n in writes if n in ctx.env]
    if cond_name not in carry_names:
        carry_names.append(cond_name)
    outer_env = ctx.env
    base_env = dict(outer_env)

    def cond_fn(carry):
        return jnp.asarray(carry[cond_name]).reshape(()).astype(bool)

    def body_fn(carry):
        env2 = dict(base_env)
        env2.update(carry)
        ctx.run_block(sub.idx, env2)
        return {n: env2[n] for n in carry_names}

    init = {n: outer_env[n] for n in carry_names}
    final = lax.while_loop(cond_fn, body_fn, init)
    out_names = op_.desc.outputs.get("Out", [])
    return {"Out": [final.get(n) for n in out_names]}


@op("conditional_block", grad=NO_GRAD, no_kernel=True)
def _conditional_block(ctx, op_, ins):
    """if(cond) { sub_block } (reference conditional_block_op.cc). Vars the
    sub-block writes must either pre-exist in the outer env (else-branch
    keeps them) or they default to zeros shaped like the then-branch
    result."""
    program = ctx.program
    sub = op_.attr("sub_block")
    cond = ins["Cond"][0]
    is_scalar_condition = bool(op_.attr("is_scalar_condition", True))
    pred = jnp.asarray(cond).reshape(-1)[0].astype(bool) \
        if is_scalar_condition else jnp.all(jnp.asarray(cond))

    out_names = op_.desc.outputs.get("Out", [])
    outer_env = ctx.env
    base_env = dict(outer_env)

    def then_fn(carry):
        env2 = dict(base_env)
        env2.update(carry)
        ctx.run_block(sub.idx, env2)
        return [env2[n] for n in out_names]

    # seed carry with pre-existing values; for fresh vars, use zeros shaped
    # like the then-branch output (jax.eval_shape avoids running it)
    carry = {n: outer_env[n] for n in out_names if n in outer_env}
    missing = [n for n in out_names if n not in carry]
    if missing:
        shapes = jax.eval_shape(then_fn, carry)
        for n, sd in zip(out_names, shapes):
            if n in missing:
                carry[n] = jnp.zeros(sd.shape, sd.dtype)

    def else_fn(c):
        return [c[n] for n in out_names]

    outs = lax.cond(pred, then_fn, else_fn, carry)
    return {"Out": list(outs)}


@op("rnn", no_kernel=True)
def _rnn(ctx, op_, ins):
    """Step-scoped RNN over padded sequences (reference recurrent_op.cc:222;
    the TPU lowering is a single lax.scan over the time axis).

    inputs:  Inputs  — sequence vars [B, T, ...] sliced per step
             InitStates — initial state values (one per state var)
    attrs:   sub_block; step_input_vars / state_vars / state_out_vars /
             step_output_vars — block-local var names; with_mask
    outputs: Outputs — stacked per-step outputs [B, T, ...]
             FinalStates — state after the last valid step
    """
    program = ctx.program
    sub = op_.attr("sub_block")
    step_in_names = list(op_.attr("step_input_vars", []))
    state_names = list(op_.attr("state_vars", []))
    state_out_names = list(op_.attr("state_out_vars", []))
    out_names = list(op_.attr("step_output_vars", []))
    is_reverse = bool(op_.attr("is_reverse", False))

    seqs = [jnp.asarray(v) for v in ins.get("Inputs", [])]
    states = [jnp.asarray(v) for v in ins.get("InitStates", [])]
    assert seqs, "rnn op needs at least one sequence input"
    bsz, t = seqs[0].shape[0], seqs[0].shape[1]

    lengths = None
    for n in op_.desc.inputs.get("Inputs", []):
        lengths = ctx.seq_len(n)
        if lengths is not None:
            break
    if lengths is not None:
        steps = jnp.arange(t)[None, :]
        mask = (steps < jnp.asarray(lengths)[:, None]).astype(seqs[0].dtype)
    else:
        mask = jnp.ones((bsz, t), seqs[0].dtype)

    xs = [jnp.swapaxes(s, 0, 1) for s in seqs]          # [T, B, ...]
    ms = jnp.swapaxes(mask, 0, 1)                        # [T, B]
    if is_reverse:
        xs = [x[::-1] for x in xs]
        ms = ms[::-1]

    outer_env = ctx.env
    base_env = dict(outer_env)
    # outer reads as explicit inputs (differentiable; see DSL) override the
    # closure values so vjp sees them as primals
    extra_names = list(op_.attr("extra_in_vars", []))
    extra_vals = ins.get("ExtraIn", [])

    def step(carry, inp):
        xts, mt = inp
        env2 = dict(base_env)
        env2.update({n: v for n, v in zip(extra_names, extra_vals)
                     if v is not None})
        env2.update(dict(zip(step_in_names, xts)))
        env2.update(dict(zip(state_names, carry)))
        ctx.run_block(sub.idx, env2)
        new_states = [env2[n] for n in state_out_names]
        outs = [env2[n] for n in out_names]
        mexp = [mt.reshape((bsz,) + (1,) * (jnp.asarray(s).ndim - 1))
                for s in new_states]
        kept = [m * s + (1 - m) * c for m, s, c in
                zip(mexp, new_states, carry)]
        omask = [mt.reshape((bsz,) + (1,) * (jnp.asarray(o).ndim - 1)) * o
                 for o in outs]
        return kept, omask

    final_states, stacked = lax.scan(step, states, (xs, ms))
    if is_reverse:
        stacked = [s[::-1] for s in stacked]
    outputs = [jnp.swapaxes(s, 0, 1) for s in stacked]
    for name in op_.desc.outputs.get("Outputs", []):
        ctx.set_seq_len(name, lengths)
    for name in op_.desc.outputs.get("FinalStates", []):
        ctx.set_seq_len(name, None)
    return {"Outputs": outputs, "FinalStates": final_states}


@op("select_rows_by_cond", non_diff_inputs=("Cond",))
def _select_rows_by_cond(ctx, op_, ins):
    """Row-wise select for the dense IfElse lowering: out[i] = cond[i] ?
    x[i] : y[i] (the reference scatters rows into true/false sub-blocks,
    ifelse_op.cc; evaluating both branches and selecting is the
    branch-free TPU equivalent)."""
    cond = jnp.asarray(ins["Cond"][0]).reshape(-1).astype(bool)
    x = jnp.asarray(ins["X"][0])
    y = jnp.asarray(ins["Y"][0])
    c = cond.reshape((cond.shape[0],) + (1,) * (x.ndim - 1))
    return {"Out": [jnp.where(c, x, y)]}


@op("max_sequence_len", grad=NO_GRAD)
def _max_sequence_len(ctx, op_, ins):
    """Max length over a sequence batch (reference max_sequence_len_op.cc,
    fed from a rank table; here straight from the lengths channel)."""
    name = op_.desc.inputs["RankTable"][0]
    lengths = ctx.seq_len(name)
    if lengths is None:
        x = jnp.asarray(ins["RankTable"][0])
        return {"Out": [jnp.asarray(x.shape[1], jnp.int64).reshape(1)]}
    return {"Out": [jnp.max(jnp.asarray(lengths)).astype(jnp.int64).reshape(1)]}


@op("lod_rank_table", grad=NO_GRAD)
def _lod_rank_table(ctx, op_, ins):
    """The reference builds a (index, length) table sorted by length desc
    (lod_rank_table.cc) to drive batch-shrinking RNNs. The padded lowering
    keeps batches dense+masked, so the 'table' is just the lengths vector;
    ops that consume it (max_sequence_len) read the SEQLEN channel."""
    name = op_.desc.inputs["X"][0]
    lengths = ctx.seq_len(name)
    x = jnp.asarray(ins["X"][0])
    if lengths is None:
        lengths = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    out = jnp.asarray(lengths).astype(jnp.int32)
    for n in op_.desc.outputs.get("Out", []):
        ctx.set_seq_len(n, out)
    return {"Out": [out]}


@op("shrink_rnn_memory", grad=None)
def _shrink_rnn_memory(ctx, op_, ins):
    """The reference shrinks the RNN state batch to sequences still alive at
    step I (shrink_rnn_memory_op.cc). Dense+masked batches keep full size,
    so this passes the state through unchanged; masking in the rnn/scan
    lowering supplies the same semantics."""
    return {"Out": [jnp.asarray(ins["X"][0])]}
