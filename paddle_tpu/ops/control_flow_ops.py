"""Control-flow ops: while / conditional_block / rnn / tensor arrays.

TPU-native equivalents of the reference's scope-mutating control flow
(reference: paddle/fluid/operators/while_op.cc:35,96,
conditional_block_op.cc, recurrent_op.cc:222 + StepScopes :53,
tensor_array_read_write_op.cc, lod_rank_table.cc, shrink_rnn_memory_op.cc).
The reference interprets sub-blocks against child scopes; here each
sub-block lowers into the parent XLA computation as
`lax.while_loop` / `lax.cond` / `lax.scan` with explicit carries — the
functionalized form of the reference's step scopes.

LoDTensorArray: the reference grows arrays dynamically per step. XLA needs
static shapes, so a TensorArray is a fixed-capacity buffer + a length
scalar; writes are `dynamic_update_index` at traced indices. Capacity is
taken from the first pre-loop write or the `capacity` attr.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..framework.desc import BlockRef, OpDesc
from .common import in_var, set_out
from .registry import NO_GRAD, op


class TensorArrayVal:
    """Fixed-capacity tensor array: buffer [cap, ...] + length scalar."""

    def __init__(self, buffer, length):
        self.buffer = buffer
        self.length = length

    def __repr__(self):
        return f"TensorArrayVal(cap={self.buffer.shape[0]}, len={self.length})"


def _ta_flatten(ta):
    return (ta.buffer, ta.length), None


def _ta_unflatten(aux, children):
    return TensorArrayVal(*children)


jax.tree_util.register_pytree_node(TensorArrayVal, _ta_flatten, _ta_unflatten)

DEFAULT_ARRAY_CAPACITY = 128


def _scalar_i32(x):
    return jnp.asarray(x).reshape(()).astype(jnp.int32)


@op("write_to_array", grad=NO_GRAD)
def _write_to_array(ctx, op_, ins):
    """array[i] = x (reference tensor_array_read_write_op.cc WriteToArray).
    Out aliases the input array var; growing past the current buffer
    allocates capacity (only legal outside lax control flow)."""
    x = jnp.asarray(ins["X"][0])
    i = _scalar_i32(ins["I"][0])
    arr = ins.get("Out", [None])[0]
    if arr is None or not isinstance(arr, TensorArrayVal):
        cap = int(op_.attr("capacity", DEFAULT_ARRAY_CAPACITY))
        buf = jnp.zeros((cap,) + x.shape, x.dtype)
        arr = TensorArrayVal(buf, _scalar_i32(0))
    buf = lax.dynamic_update_index_in_dim(arr.buffer, x, i, axis=0)
    length = jnp.maximum(arr.length, i + 1)
    return {"Out": [TensorArrayVal(buf, length)]}


@op("read_from_array", grad=NO_GRAD)
def _read_from_array(ctx, op_, ins):
    arr = ins["X"][0]
    assert isinstance(arr, TensorArrayVal), "read_from_array needs an array"
    i = _scalar_i32(ins["I"][0])
    return {"Out": [lax.dynamic_index_in_dim(arr.buffer, i, axis=0,
                                             keepdims=False)]}


@op("lod_array_length", grad=NO_GRAD)
def _lod_array_length(ctx, op_, ins):
    arr = ins["X"][0]
    assert isinstance(arr, TensorArrayVal)
    return {"Out": [arr.length.reshape(1).astype(jnp.int64)]}


class StepScopesVal:
    """Recorded loop state for while_grad (reference while_op.cc keeps the
    per-iteration step scopes alive in its StepScopes output for the grad op
    to replay; here the record is a stacked pytree of pre-iteration carries
    plus the executed iteration count)."""

    def __init__(self, names, records, count):
        self.names = tuple(names)        # carry var names (static)
        self.records = records           # name -> pytree stacked [C, ...]
        self.count = count               # int32 iterations executed

    def __repr__(self):
        return f"StepScopesVal(names={self.names})"


def _ss_flatten(ss):
    return ((tuple(ss.records[n] for n in ss.names), ss.count), ss.names)


def _ss_unflatten(names, children):
    recs, count = children
    return StepScopesVal(names, dict(zip(names, recs)), count)


jax.tree_util.register_pytree_node(StepScopesVal, _ss_flatten, _ss_unflatten)


class ScopeRecordVal:
    """Pre-op values of outer vars a conditional_block overwrites (the
    conditional analogue of StepScopesVal: conditional_block_grad needs the
    else-branch passthrough values, which the forward op has clobbered in
    the env by the time the grad op runs)."""

    def __init__(self, names, values):
        self.names = tuple(names)
        self.values = values             # name -> pytree

    def __repr__(self):
        return f"ScopeRecordVal(names={self.names})"


def _sr_flatten(sr):
    return (tuple(sr.values[n] for n in sr.names), sr.names)


def _sr_unflatten(names, children):
    return ScopeRecordVal(names, dict(zip(names, children)))


jax.tree_util.register_pytree_node(ScopeRecordVal, _sr_flatten, _sr_unflatten)

# default while-loop step-scope recording capacity; per-loop override via
# While(max_iters=...), global override via PADDLE_TPU_MAX_LOOP_ITERS
import os as _os
DEFAULT_MAX_LOOP_ITERS = int(
    _os.environ.get("PADDLE_TPU_MAX_LOOP_ITERS") or 128)


def _block_writes(program, block_idx) -> List[str]:
    """All var names written by a block (recursively through sub-blocks)."""
    writes: List[str] = []
    seen = set()
    block = program.block(block_idx)
    for o in block.ops:
        for name in o.output_arg_names:
            if name not in seen:
                seen.add(name)
                writes.append(name)
        for a in o.desc.attrs.values():
            if isinstance(a, BlockRef):
                for name in _block_writes(program, a.idx):
                    if name not in seen:
                        seen.add(name)
                        writes.append(name)
    return writes


@op("while", grad=NO_GRAD, no_kernel=True)  # real maker assigned below
def _while(ctx, op_, ins):
    """while(Condition) { sub_block } (reference while_op.cc:35).

    Carries = every var the sub-block writes that already has a value in the
    outer env (loop state must be initialized before the loop), plus the
    condition var. Everything else the sub-block reads is closed over.

    When append_backward marks the op with `record_step_scopes`, the loop
    additionally records the pre-iteration carry of every step into
    fixed-capacity stacked buffers (attr `max_loop_iters`, default 128) —
    the functional analogue of the reference keeping step scopes alive for
    WhileGradOp (while_op.cc:96). while_grad replays them reversed.
    """
    program = ctx.program
    sub = op_.attr("sub_block")
    assert isinstance(sub, BlockRef)
    cond_name = op_.desc.inputs["Condition"][0]

    writes = _block_writes(program, sub.idx)
    carry_names = [n for n in writes if n in ctx.env]
    if cond_name not in carry_names:
        carry_names.append(cond_name)
    outer_env = ctx.env
    base_env = dict(outer_env)

    record = bool(op_.attr("record_step_scopes", False)) and \
        bool(op_.desc.outputs.get("StepScopes"))
    cap = int(op_.attr("max_loop_iters", 0) or DEFAULT_MAX_LOOP_ITERS)

    def body_env(carry):
        env2 = dict(base_env)
        env2.update(carry)
        ctx.run_block(sub.idx, env2)
        return {n: env2[n] for n in carry_names}

    init = {n: outer_env[n] for n in carry_names}

    if not record:
        def cond_fn(carry):
            return jnp.asarray(carry[cond_name]).reshape(()).astype(bool)

        final = lax.while_loop(cond_fn, body_env, init)
        out_names = op_.desc.outputs.get("Out", [])
        return {"Out": [final.get(n) for n in out_names]}

    rec0 = {n: jax.tree.map(
        lambda x: jnp.zeros((cap,) + jnp.asarray(x).shape,
                            jnp.asarray(x).dtype), init[n])
        for n in carry_names}

    def cond_fn(state):
        carry, i, _rec = state
        return jnp.asarray(carry[cond_name]).reshape(()).astype(bool)

    def body_fn(state):
        carry, i, rec = state
        j = jnp.minimum(i, cap - 1)
        rec = {n: jax.tree.map(
            lambda b, x: lax.dynamic_update_index_in_dim(
                b, jnp.asarray(x), j, axis=0), rec[n], carry[n])
            for n in carry_names}
        return body_env(carry), i + 1, rec

    final, count, rec = lax.while_loop(
        cond_fn, body_fn, (init, jnp.asarray(0, jnp.int32), rec0))
    ss = StepScopesVal(carry_names, rec, count)
    out_names = op_.desc.outputs.get("Out", [])
    return {"Out": [final.get(n) for n in out_names], "StepScopes": [ss]}


def _zeros_ct(primal):
    """Zero cotangent for a primal pytree: float leaves get jnp zeros,
    integer/bool leaves get int-dtype placeholders (swapped for float0 at
    the vjp boundary by _to_vjp_ct)."""
    return jax.tree.map(lambda x: jnp.zeros_like(jnp.asarray(x)), primal)


def _to_vjp_ct(ct, primal):
    """Convert carried cotangents to what jax.vjp accepts: float0 for
    non-inexact primal leaves."""
    def conv(c, p):
        p = jnp.asarray(p)
        if jnp.issubdtype(p.dtype, jnp.inexact):
            return jnp.asarray(c, p.dtype)
        return np.zeros(p.shape, dtype=jax.dtypes.float0)
    return jax.tree.map(conv, ct, primal)


def _from_vjp_ct(ct, primal):
    """Inverse of _to_vjp_ct: float0 leaves back to int placeholders so the
    structure can ride a lax.scan carry."""
    def conv(c, p):
        p = jnp.asarray(p)
        if jnp.issubdtype(p.dtype, jnp.inexact):
            return c
        return jnp.zeros_like(p)
    return jax.tree.map(conv, ct, primal)


def _while_grad_maker(fwd, no_grad_set):
    """Emit while_grad + mark the forward op to record step scopes
    (reference while_op.cc:96 WhileGradOp / while grad maker)."""
    from ..framework.framework import grad_var_name
    out_names = list(fwd.outputs.get("Out", []))
    x_names = list(fwd.inputs.get("X", []))
    gx = [n for n in x_names if n not in no_grad_set]
    if not gx:
        return []
    ss_name = (out_names[0] if out_names else x_names[0]) + "@STEP_SCOPES"
    fwd.outputs["StepScopes"] = [ss_name]
    fwd.attrs["record_step_scopes"] = True
    g = OpDesc(
        type="while_grad",
        inputs={"Condition": list(fwd.inputs["Condition"]),
                "X": x_names,
                "Out": out_names,
                "Out@GRAD": [grad_var_name(n) for n in out_names],
                "StepScopes": [ss_name]},
        outputs={"X@GRAD": [grad_var_name(n) for n in gx]},
        attrs=dict(fwd.attrs))
    return [g]


from . import registry as _registry_mod  # noqa: E402
_registry_mod.get("while").grad = _while_grad_maker


@op("while_grad", grad=NO_GRAD, no_kernel=True)
def _while_grad(ctx, op_, ins):
    """Reverse sweep of a recorded while loop: for j = n-1 .. 0, vjp of the
    loop body at the recorded carry, masked past the executed count
    (reference while_op.cc:96; the bounded-scan replay is the XLA-legal
    form of running the grad block once per retained step scope)."""
    program = ctx.program
    sub = op_.attr("sub_block")
    ss = ins["StepScopes"][0]
    assert isinstance(ss, StepScopesVal), "while_grad needs recorded scopes"
    carry_names = list(ss.names)
    rec, count = ss.records, ss.count
    cap = int(op_.attr("max_loop_iters", 0) or DEFAULT_MAX_LOOP_ITERS)

    x_names = op_.desc.inputs.get("X", [])
    x_vals = dict(zip(x_names, ins.get("X", [])))
    out_names = op_.desc.inputs.get("Out", [])
    out_cts = dict(zip(out_names, ins.get("Out@GRAD", [])))

    base_env = dict(ctx.env)
    base_env.update({n: v for n, v in x_vals.items() if v is not None})

    def _leafs_inexact(v):
        return all(jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)
                   for l in jax.tree.leaves(v))

    # differentiable non-carried reads (weights etc.); carried names get
    # their grads from the reverse-carry cotangent instead
    xd_names = [n for n in x_names
                if n not in carry_names and x_vals.get(n) is not None
                and _leafs_inexact(x_vals[n])]
    x_diff = {n: x_vals[n] for n in xd_names}

    def body_pure(carry, xd):
        env2 = dict(base_env)
        env2.update(xd)
        env2.update(carry)
        ctx.run_block(sub.idx, env2)
        return {n: env2[n] for n in carry_names}

    # initial cotangent of the loop state = grads of the while outputs
    g0 = {}
    carry_tmpl = {n: jax.tree.map(lambda r: r[0], rec[n])
                  for n in carry_names}
    for n in carry_names:
        ct = out_cts.get(n)
        tmpl = carry_tmpl[n]
        if ct is not None and jax.tree.structure(ct) == \
                jax.tree.structure(tmpl):
            g0[n] = jax.tree.map(
                lambda c, p: jnp.asarray(c, jnp.asarray(p).dtype), ct, tmpl)
        else:
            g0[n] = _zeros_ct(tmpl)
    xbar0 = _zeros_ct(x_diff)

    def rev_step(state, j):
        g, xbar = state
        active = j < count
        carry_j = {n: jax.tree.map(lambda r: r[j], rec[n])
                   for n in carry_names}
        out_primal, vjp_fn = jax.vjp(body_pure, carry_j, x_diff)
        ct = _to_vjp_ct(g, out_primal)
        dc, dx = vjp_fn(ct)
        dc = _from_vjp_ct(dc, carry_j)
        dx = _from_vjp_ct(dx, x_diff)
        g_new = jax.tree.map(lambda a, b: jnp.where(active, a, b), dc, g)
        xbar_new = jax.tree.map(
            lambda xb, d: xb + jnp.where(active, d, jnp.zeros_like(d)),
            xbar, dx)
        return (g_new, xbar_new), None

    js = jnp.arange(cap - 1, -1, -1)
    (g_fin, xbar_fin), _ = lax.scan(rev_step, (g0, xbar0), js)

    gx_names = op_.desc.outputs.get("X@GRAD", [])
    grads = []
    for gn in gx_names:
        base = gn.split("@RENAME@")[0]
        if base.endswith("@GRAD"):
            base = base[: -len("@GRAD")]
        if base in carry_names:
            v = g_fin[base]
            grads.append(v if _leafs_inexact(carry_tmpl[base]) else None)
        elif base in x_diff:
            grads.append(xbar_fin[base])
        else:
            grads.append(None)

    # If the loop ran past the recording capacity, the replay is truncated
    # and every gradient is undefined — poison with NaN so training fails
    # loudly instead of converging to a silently wrong optimum. Raise the
    # cap via While(cond, max_iters=N).
    overflow = count > cap

    def _poison(v):
        v = jnp.asarray(v)
        if jnp.issubdtype(v.dtype, jnp.inexact):
            return jnp.where(overflow, jnp.full_like(v, jnp.nan), v)
        return v

    grads = [jax.tree.map(_poison, g) if g is not None else None
             for g in grads]
    return {"X@GRAD": grads}


def _cond_apply(ctx, sub_idx, base_env, out_names, pred, carry, xd):
    """Pure form of conditional_block shared by forward + grad: lax.cond over
    {run sub-block, passthrough}, with explicit reads `xd` so vjp sees them
    as primals."""

    def then_fn(carry, xd):
        env2 = dict(base_env)
        env2.update(xd)
        env2.update(carry)
        ctx.run_block(sub_idx, env2)
        return [env2[n] for n in out_names]

    def else_fn(carry, xd):
        return [carry[n] for n in out_names]

    return lax.cond(pred, then_fn, else_fn, carry, xd)


@op("conditional_block", grad=NO_GRAD, no_kernel=True)  # maker set below
def _conditional_block(ctx, op_, ins):
    """if(cond) { sub_block } (reference conditional_block_op.cc). Vars the
    sub-block writes must either pre-exist in the outer env (else-branch
    keeps them) or they default to zeros shaped like the then-branch
    result. With `record_scope` set (by the grad maker), the pre-op carry
    is emitted through the Scope output for conditional_block_grad."""
    program = ctx.program
    sub = op_.attr("sub_block")
    cond = ins["Cond"][0]
    is_scalar_condition = bool(op_.attr("is_scalar_condition", True))
    pred = jnp.asarray(cond).reshape(-1)[0].astype(bool) \
        if is_scalar_condition else jnp.all(jnp.asarray(cond))

    out_names = op_.desc.outputs.get("Out", [])
    outer_env = ctx.env
    base_env = dict(outer_env)

    # seed carry with pre-existing values; for fresh vars, use zeros shaped
    # like the then-branch output (jax.eval_shape avoids running it)
    carry = {n: outer_env[n] for n in out_names if n in outer_env}
    missing = [n for n in out_names if n not in carry]
    if missing:
        def then_probe(c):
            env2 = dict(base_env)
            env2.update(c)
            ctx.run_block(sub.idx, env2)
            return [env2[n] for n in out_names]
        shapes = jax.eval_shape(then_probe, carry)
        for n, sd in zip(out_names, shapes):
            if n in missing:
                carry[n] = jnp.zeros(sd.shape, sd.dtype)

    outs = _cond_apply(ctx, sub.idx, base_env, out_names, pred, carry, {})
    result = {"Out": list(outs)}
    if bool(op_.attr("record_scope", False)) and \
            op_.desc.outputs.get("Scope"):
        result["Scope"] = [ScopeRecordVal(out_names,
                                          {n: carry[n] for n in out_names})]
    return result


def _conditional_block_grad_maker(fwd, no_grad_set):
    """Emit conditional_block_grad (reference conditional_block_op.cc
    ConditionalBlockGradOp) + mark the forward op to record its pre-op
    carry."""
    from ..framework.framework import grad_var_name
    out_names = list(fwd.outputs.get("Out", []))
    x_names = list(fwd.inputs.get("X", []))
    if not out_names:
        return []
    gx = [n for n in x_names if n not in no_grad_set]
    if not gx:
        return []
    scope_name = out_names[0] + "@COND_SCOPE"
    fwd.outputs["Scope"] = [scope_name]
    fwd.attrs["record_scope"] = True
    g = OpDesc(
        type="conditional_block_grad",
        inputs={"Cond": list(fwd.inputs["Cond"]),
                "X": x_names,
                "Out": out_names,
                "Out@GRAD": [grad_var_name(n) for n in out_names],
                "Scope": [scope_name]},
        outputs={"X@GRAD": [grad_var_name(n) for n in gx]},
        attrs=dict(fwd.attrs))
    return [g]


@op("conditional_block_grad", grad=NO_GRAD, no_kernel=True)
def _conditional_block_grad(ctx, op_, ins):
    """vjp of conditional_block: both branches replayed under lax.cond at the
    recorded pre-op carry; grads flow to explicit reads X and, for
    pre-existing outputs, through the else-branch passthrough."""
    sub = op_.attr("sub_block")
    cond = ins["Cond"][0]
    is_scalar_condition = bool(op_.attr("is_scalar_condition", True))
    pred = jnp.asarray(cond).reshape(-1)[0].astype(bool) \
        if is_scalar_condition else jnp.all(jnp.asarray(cond))

    sr = ins["Scope"][0]
    assert isinstance(sr, ScopeRecordVal), "cond grad needs recorded scope"
    out_names = list(sr.names)
    carry = dict(sr.values)
    out_cts = dict(zip(op_.desc.inputs.get("Out", []),
                       ins.get("Out@GRAD", [])))

    x_names = op_.desc.inputs.get("X", [])
    x_vals = dict(zip(x_names, ins.get("X", [])))
    base_env = dict(ctx.env)
    base_env.update({n: v for n, v in x_vals.items() if v is not None})

    def _leafs_inexact(v):
        return all(jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)
                   for l in jax.tree.leaves(v))

    xd_names = [n for n in x_names
                if n not in carry and x_vals.get(n) is not None
                and _leafs_inexact(x_vals[n])]
    x_diff = {n: x_vals[n] for n in xd_names}

    def pure(carry, xd):
        return _cond_apply(ctx, sub.idx, base_env, out_names, pred,
                           carry, xd)

    out_primal, vjp_fn = jax.vjp(pure, carry, x_diff)
    cts = []
    for n, p in zip(out_names, out_primal):
        p = jnp.asarray(p)
        g = out_cts.get(n)
        if not jnp.issubdtype(p.dtype, jnp.inexact):
            cts.append(np.zeros(p.shape, dtype=jax.dtypes.float0))
        elif g is not None:
            cts.append(jnp.asarray(g, p.dtype))
        else:
            cts.append(jnp.zeros_like(p))
    dc, dx = vjp_fn(cts)

    gx_names = op_.desc.outputs.get("X@GRAD", [])
    grads = []
    for gn in gx_names:
        base = gn.split("@RENAME@")[0]
        if base.endswith("@GRAD"):
            base = base[: -len("@GRAD")]
        g = None
        if base in x_diff:
            g = dx[base]
        if base in carry and _leafs_inexact(carry[base]):
            c = dc[base]
            if not (hasattr(c, "dtype") and c.dtype == jax.dtypes.float0):
                g = c if g is None else g + c
        grads.append(g)
    return {"X@GRAD": grads}


_registry_mod.get("conditional_block").grad = _conditional_block_grad_maker


@op("rnn", no_kernel=True)
def _rnn(ctx, op_, ins):
    """Step-scoped RNN over padded sequences (reference recurrent_op.cc:222;
    the TPU lowering is a single lax.scan over the time axis).

    inputs:  Inputs  — sequence vars [B, T, ...] sliced per step
             InitStates — initial state values (one per state var)
    attrs:   sub_block; step_input_vars / state_vars / state_out_vars /
             step_output_vars — block-local var names; with_mask
    outputs: Outputs — stacked per-step outputs [B, T, ...]
             FinalStates — state after the last valid step
    """
    program = ctx.program
    sub = op_.attr("sub_block")
    step_in_names = list(op_.attr("step_input_vars", []))
    state_names = list(op_.attr("state_vars", []))
    state_out_names = list(op_.attr("state_out_vars", []))
    out_names = list(op_.attr("step_output_vars", []))
    is_reverse = bool(op_.attr("is_reverse", False))

    seqs = [jnp.asarray(v) for v in ins.get("Inputs", [])]
    states = [jnp.asarray(v) for v in ins.get("InitStates", [])]
    assert seqs, "rnn op needs at least one sequence input"
    bsz, t = seqs[0].shape[0], seqs[0].shape[1]

    lengths = None
    for n in op_.desc.inputs.get("Inputs", []):
        lengths = ctx.seq_len(n)
        if lengths is not None:
            break
    if lengths is not None:
        steps = jnp.arange(t)[None, :]
        mask = (steps < jnp.asarray(lengths)[:, None]).astype(seqs[0].dtype)
    else:
        mask = jnp.ones((bsz, t), seqs[0].dtype)

    xs = [jnp.swapaxes(s, 0, 1) for s in seqs]          # [T, B, ...]
    ms = jnp.swapaxes(mask, 0, 1)                        # [T, B]
    if is_reverse:
        xs = [x[::-1] for x in xs]
        ms = ms[::-1]

    outer_env = ctx.env
    base_env = dict(outer_env)
    # outer reads as explicit inputs (differentiable; see DSL) override the
    # closure values so vjp sees them as primals
    extra_names = list(op_.attr("extra_in_vars", []))
    extra_vals = ins.get("ExtraIn", [])

    def step(carry, inp):
        xts, mt = inp
        env2 = dict(base_env)
        env2.update({n: v for n, v in zip(extra_names, extra_vals)
                     if v is not None})
        env2.update(dict(zip(step_in_names, xts)))
        env2.update(dict(zip(state_names, carry)))
        ctx.run_block(sub.idx, env2)
        new_states = [env2[n] for n in state_out_names]
        outs = [env2[n] for n in out_names]
        mexp = [mt.reshape((bsz,) + (1,) * (jnp.asarray(s).ndim - 1))
                for s in new_states]
        kept = [m * s + (1 - m) * c for m, s, c in
                zip(mexp, new_states, carry)]
        omask = [mt.reshape((bsz,) + (1,) * (jnp.asarray(o).ndim - 1)) * o
                 for o in outs]
        return kept, omask

    final_states, stacked = lax.scan(step, states, (xs, ms))
    if is_reverse:
        stacked = [s[::-1] for s in stacked]
    outputs = [jnp.swapaxes(s, 0, 1) for s in stacked]
    for name in op_.desc.outputs.get("Outputs", []):
        ctx.set_seq_len(name, lengths)
    for name in op_.desc.outputs.get("FinalStates", []):
        ctx.set_seq_len(name, None)
    return {"Outputs": outputs, "FinalStates": final_states}


@op("select_rows_by_cond", non_diff_inputs=("Cond",))
def _select_rows_by_cond(ctx, op_, ins):
    """Row-wise select for the dense IfElse lowering: out[i] = cond[i] ?
    x[i] : y[i] (the reference scatters rows into true/false sub-blocks,
    ifelse_op.cc; evaluating both branches and selecting is the
    branch-free TPU equivalent)."""
    cond = jnp.asarray(ins["Cond"][0]).reshape(-1).astype(bool)
    x = jnp.asarray(ins["X"][0])
    y = jnp.asarray(ins["Y"][0])
    c = cond.reshape((cond.shape[0],) + (1,) * (x.ndim - 1))
    return {"Out": [jnp.where(c, x, y)]}


@op("max_sequence_len", grad=NO_GRAD)
def _max_sequence_len(ctx, op_, ins):
    """Max length over a sequence batch (reference max_sequence_len_op.cc,
    fed from a rank table; here straight from the lengths channel)."""
    name = op_.desc.inputs["RankTable"][0]
    lengths = ctx.seq_len(name)
    if lengths is None:
        x = jnp.asarray(ins["RankTable"][0])
        return {"Out": [jnp.asarray(x.shape[1], jnp.int64).reshape(1)]}
    return {"Out": [jnp.max(jnp.asarray(lengths)).astype(jnp.int64).reshape(1)]}


@op("lod_rank_table", grad=NO_GRAD)
def _lod_rank_table(ctx, op_, ins):
    """The reference builds a (index, length) table sorted by length desc
    (lod_rank_table.cc) to drive batch-shrinking RNNs. The padded lowering
    keeps batches dense+masked, so the 'table' is just the lengths vector;
    ops that consume it (max_sequence_len) read the SEQLEN channel."""
    name = op_.desc.inputs["X"][0]
    lengths = ctx.seq_len(name)
    x = jnp.asarray(ins["X"][0])
    if lengths is None:
        lengths = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    out = jnp.asarray(lengths).astype(jnp.int32)
    for n in op_.desc.outputs.get("Out", []):
        ctx.set_seq_len(n, out)
    return {"Out": [out]}


@op("shrink_rnn_memory", grad=None)
def _shrink_rnn_memory(ctx, op_, ins):
    """The reference shrinks the RNN state batch to sequences still alive at
    step I (shrink_rnn_memory_op.cc). Dense+masked batches keep full size,
    so this passes the state through unchanged; masking in the rnn/scan
    lowering supplies the same semantics."""
    return {"Out": [jnp.asarray(ins["X"][0])]}
