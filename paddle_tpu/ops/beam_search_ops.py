"""Beam-search ops on dense [batch, beam] tensors.

TPU-native equivalents of the reference's LoD-based beam machinery
(reference: paddle/fluid/operators/beam_search_op.cc — per-step candidate
selection over LoD beams; beam_search_decode_op.cc — backtracking the
step arrays into final hypotheses). The reference encodes beams in LoD
levels with dynamic widths; XLA wants static shapes, so beams live in a
fixed [B, K] lane layout: finished beams (last id == end_id) are frozen
lanes that propagate end_id with unchanged score. Selection is one
jnp.top_k over the K*V flattened candidates per batch row — MXU/VPU
friendly, no host round-trips inside the decode loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import NO_GRAD, op


@op("beam_search", grad=NO_GRAD)
def _beam_search(ctx, op_, ins):
    """One decode step. pre_ids [B,K] int, pre_scores [B,K] float,
    scores [B,K,V] per-beam next-token log-probs. Returns selected ids,
    cumulative scores, and parent beam indices, all [B,K]."""
    pre_ids = jnp.asarray(ins["pre_ids"][0]).astype(jnp.int32)
    pre_scores = jnp.asarray(ins["pre_scores"][0])
    scores = jnp.asarray(ins["scores"][0])
    if pre_ids.ndim == 3:
        pre_ids = pre_ids[..., 0]
    bsz, k, v = scores.shape
    beam_size = int(op_.attr("beam_size", k))
    end_id = int(op_.attr("end_id", 1))
    assert beam_size == k, "beam lane count must equal beam_size"

    finished = pre_ids == end_id                                    # [B,K]
    # frozen lanes: only candidate is end_id with +0 score
    neg = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
    frozen_row = jnp.full((v,), neg, scores.dtype).at[end_id].set(0.0)
    step_scores = jnp.where(finished[..., None], frozen_row[None, None, :],
                            scores)
    cum = pre_scores[..., None] + step_scores                       # [B,K,V]
    flat = cum.reshape(bsz, k * v)
    top_scores, top_idx = lax.top_k(flat, beam_size)                # [B,K]
    parent = (top_idx // v).astype(jnp.int32)
    token = (top_idx % v).astype(jnp.int64)
    return {"selected_ids": [token], "selected_scores": [top_scores],
            "parent_idx": [parent]}


@op("beam_search_decode", grad=NO_GRAD)
def _beam_search_decode(ctx, op_, ins):
    """Backtrack step arrays into final hypotheses
    (reference beam_search_decode_op.cc). Ids/ParentIdx are TensorArrays of
    [B,K] steps; returns SentenceIds [B,K,T] (end_id-padded) and
    SentenceScores [B,K] (cumulative score of each lane at the last step)."""
    ids_arr = ins["Ids"][0]
    parents_arr = ins["ParentIdx"][0]
    scores_arr = ins["Scores"][0] if ins.get("Scores") and \
        ins["Scores"][0] is not None else None
    end_id = int(op_.attr("end_id", 1))

    ids_buf = ids_arr.buffer                                        # [C,B,K]
    par_buf = parents_arr.buffer
    n = ids_arr.length                                              # scalar
    cap, bsz, k = ids_buf.shape
    lane = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32)[None, :],
                            (bsz, k))

    def back(carry, i):
        lanes = carry                                               # [B,K]
        step = n - 1 - i                                            # traced
        valid = step >= 0
        sstep = jnp.maximum(step, 0)
        tok = jnp.take_along_axis(ids_buf[sstep], lanes, axis=1)
        par = jnp.take_along_axis(par_buf[sstep], lanes, axis=1)
        tok = jnp.where(valid, tok, end_id)
        new_lanes = jnp.where(valid, par, lanes)
        return new_lanes, tok

    _, toks_rev = lax.scan(back, lane, jnp.arange(cap))
    # toks_rev[i] is the token at step n-1-i, so plain reversal leaves the
    # (cap - n) invalid (end_id) entries at the FRONT of the time axis when
    # the TensorArray capacity exceeds the written steps; roll them to the
    # back so hypotheses start at t=0 and trailing slots are end_id padding.
    ordered = jnp.roll(toks_rev[::-1], -(cap - n), axis=0)
    sentences = jnp.swapaxes(jnp.swapaxes(ordered, 0, 1), 1, 2)
    # [B,K,C]; steps beyond length hold end_id
    if scores_arr is not None:
        last = jnp.maximum(n - 1, 0)
        final_scores = scores_arr.buffer[last]                      # [B,K]
    else:
        final_scores = jnp.zeros((bsz, k), jnp.float32)
    return {"SentenceIds": [sentences.astype(jnp.int64)],
            "SentenceScores": [final_scores]}
