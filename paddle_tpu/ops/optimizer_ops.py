"""Optimizer update ops (reference: sgd_op.cc, momentum_op.cc, adam_op.cc,
adamax_op.cc, adagrad_op.cc, decayed_adagrad_op.cc, adadelta_op.cc,
rmsprop_op.cc, ftrl_op.cc, proximal_gd_op.cc, proximal_adagrad_op.cc).

Like the reference, optimizer updates are ops in the program: outputs alias
the parameter/accumulator input names, so under the jitted whole-block
executor the updates fuse with the backward pass and parameters stay resident
in HBM (buffer donation in executor.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import NO_GRAD, op
from . import sparse_ops
from .common import SelectedRowsVal, maybe_dense, in_var, set_out


def _param_out_infer(*pairs):
    def infer(op_, block):
        for in_slot, out_slot in pairs:
            iv = in_var(op_, block, in_slot)
            if iv is not None:
                set_out(op_, block, out_slot, iv.shape, iv.dtype)
    return infer


def _lr(ins):
    return jnp.asarray(ins["LearningRate"][0]).reshape(())


def _param_grad(ins, op_type=None):
    """(param, grad) with the grad upcast to the param dtype: fp32
    master-weight updates under AMP O2 receive bf16 grads, which must be
    upcast before any arithmetic so lr*g and accumulators stay full
    precision. SelectedRows grads densify here, COUNTED: pass the op type
    so `sparse_densify_fallback_total{op,reason}` attributes the cliff —
    `no_sparse_kernel` for optimizers outside sparse_ops.SPARSE_APPLY_OPS
    (the reference registers SelectedRows kernels only for sgd/momentum/
    adam), `gated_off` when PADDLE_TPU_SPARSE_APPLY=0 disabled a capable
    one."""
    p = jnp.asarray(ins["Param"][0])
    g0 = ins["Grad"][0]
    if isinstance(g0, SelectedRowsVal) and op_type is not None:
        reason = ("gated_off" if op_type in sparse_ops.SPARSE_APPLY_OPS
                  else "no_sparse_kernel")
        sparse_ops.count_densify(op_type, reason)
    return p, jnp.asarray(maybe_dense(g0)).astype(p.dtype)


def _sparse_ready(ins):
    return (isinstance(ins["Grad"][0], SelectedRowsVal)
            and sparse_ops.sparse_apply_enabled())


def _pname(op_):
    names = op_.input("Param")
    return names[0] if names else None


# Dense update math, shared by the per-param lowerings below and the
# bucketed fused apply (ops/fusion.py). Purely elementwise over
# (param, grad, accumulators) with scalar hyperparameters, so applying
# one expression to a concatenation of flattened tensors is bitwise
# identical to applying it per tensor — the property the fused optimizer
# parity tests pin down.

def sgd_dense(p, g, lr):
    return p - lr * g


def momentum_dense(p, g, v, lr, mu, use_nesterov):
    v_out = mu * v + g
    if use_nesterov:
        p_out = p - lr * (g + mu * v_out)
    else:
        p_out = p - lr * v_out
    return p_out, v_out


def adam_dense(p, g, m1, m2, lr, b1, b2, eps, b1p, b2p):
    m1o = b1 * m1 + (1 - b1) * g
    m2o = b2 * m2 + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    po = p - lr_t * m1o / (jnp.sqrt(m2o) + eps)
    return po, m1o, m2o


@op("sgd", grad=NO_GRAD, infer_shape=_param_out_infer(("Param", "ParamOut")))
def _sgd(ctx, op_, ins):
    if _sparse_ready(ins):
        # scatter-apply kernel (reference sgd_op.h SelectedRows branch /
        # selected_rows_functor.cc), merge-first so duplicate ids sum
        # exactly like the dense accumulation
        p = jnp.asarray(ins["Param"][0])
        po = sparse_ops.sgd_apply(p, _lr(ins), ins["Grad"][0])
        po = sparse_ops.pin_table(ctx.program, _pname(op_), po)
        return {"ParamOut": [po]}
    p, g = _param_grad(ins, "sgd")
    return {"ParamOut": [sgd_dense(p, g, _lr(ins))]}


@op("momentum", grad=NO_GRAD,
    infer_shape=_param_out_infer(("Param", "ParamOut"),
                                 ("Velocity", "VelocityOut")))
def _momentum(ctx, op_, ins):
    mu = op_.attr("mu")
    if _sparse_ready(ins):
        # scatter-apply kernel: velocity decays + param moves only on
        # the gradient's rows (lazy semantics matching sparse adam below)
        p = jnp.asarray(ins["Param"][0])
        v = jnp.asarray(ins["Velocity"][0])
        po, vo = sparse_ops.momentum_apply(
            p, v, _lr(ins), mu, op_.attr("use_nesterov", False),
            ins["Grad"][0])
        po, vo = sparse_ops.pin_table(ctx.program, _pname(op_), po, vo)
        return {"ParamOut": [po], "VelocityOut": [vo]}
    p, g = _param_grad(ins, "momentum")
    v = jnp.asarray(ins["Velocity"][0])
    p_out, v_out = momentum_dense(p, g, v, _lr(ins), mu,
                                  op_.attr("use_nesterov", False))
    return {"ParamOut": [p_out], "VelocityOut": [v_out]}


@op("adam", grad=NO_GRAD,
    infer_shape=_param_out_infer(("Param", "ParamOut"), ("Moment1", "Moment1Out"),
                                 ("Moment2", "Moment2Out")))
def _adam(ctx, op_, ins):
    b1 = op_.attr("beta1", 0.9)
    b2 = op_.attr("beta2", 0.999)
    eps = op_.attr("epsilon", 1e-8)
    b1p = jnp.asarray(ins["Beta1Pow"][0]).reshape(())
    b2p = jnp.asarray(ins["Beta2Pow"][0]).reshape(())
    if _sparse_ready(ins):
        # scatter-apply kernel (reference adam_op.h SparseAdamFunctor):
        # moments/param update only the gradient's rows; untouched rows
        # keep stale moments, exactly like the reference. O(K*D) instead
        # of the O(V*D) densified update — the difference between an
        # embedding model training at batch cost vs vocab cost.
        p = jnp.asarray(ins["Param"][0])
        m1 = jnp.asarray(ins["Moment1"][0])
        m2 = jnp.asarray(ins["Moment2"][0])
        po, m1o, m2o = sparse_ops.adam_apply(
            p, m1, m2, _lr(ins), b1, b2, eps, b1p, b2p, ins["Grad"][0])
        po, m1o, m2o = sparse_ops.pin_table(
            ctx.program, _pname(op_), po, m1o, m2o)
        return {"ParamOut": [po], "Moment1Out": [m1o],
                "Moment2Out": [m2o]}
    p, g = _param_grad(ins, "adam")
    m1 = jnp.asarray(ins["Moment1"][0])
    m2 = jnp.asarray(ins["Moment2"][0])
    po, m1o, m2o = adam_dense(p, g, m1, m2, _lr(ins), b1, b2, eps,
                              b1p, b2p)
    return {"ParamOut": [po], "Moment1Out": [m1o], "Moment2Out": [m2o]}


@op("adamax", grad=NO_GRAD,
    infer_shape=_param_out_infer(("Param", "ParamOut"), ("Moment", "MomentOut"),
                                 ("InfNorm", "InfNormOut")))
def _adamax(ctx, op_, ins):
    p, g = _param_grad(ins, op_.type)
    m = jnp.asarray(ins["Moment"][0])
    u = jnp.asarray(ins["InfNorm"][0])
    b1p = jnp.asarray(ins["Beta1Pow"][0]).reshape(())
    b1 = op_.attr("beta1", 0.9)
    b2 = op_.attr("beta2", 0.999)
    eps = op_.attr("epsilon", 1e-8)
    mo = b1 * m + (1 - b1) * g
    uo = jnp.maximum(b2 * u, jnp.abs(g))
    po = p - (_lr(ins) / (1 - b1p)) * mo / (uo + eps)
    return {"ParamOut": [po], "MomentOut": [mo], "InfNormOut": [uo]}


@op("adagrad", grad=NO_GRAD,
    infer_shape=_param_out_infer(("Param", "ParamOut"), ("Moment", "MomentOut")))
def _adagrad(ctx, op_, ins):
    p, g = _param_grad(ins, op_.type)
    m = jnp.asarray(ins["Moment"][0])
    eps = op_.attr("epsilon", 1e-6)
    mo = m + g * g
    po = p - _lr(ins) * g / (jnp.sqrt(mo) + eps)
    return {"ParamOut": [po], "MomentOut": [mo]}


@op("decayed_adagrad", grad=NO_GRAD,
    infer_shape=_param_out_infer(("Param", "ParamOut"), ("Moment", "MomentOut")))
def _decayed_adagrad(ctx, op_, ins):
    p, g = _param_grad(ins, op_.type)
    m = jnp.asarray(ins["Moment"][0])
    decay = op_.attr("decay", 0.95)
    eps = op_.attr("epsilon", 1e-6)
    mo = decay * m + (1 - decay) * g * g
    po = p - _lr(ins) * g / (jnp.sqrt(mo) + eps)
    return {"ParamOut": [po], "MomentOut": [mo]}


@op("adadelta", grad=NO_GRAD,
    infer_shape=_param_out_infer(("Param", "ParamOut"),
                                 ("AvgSquaredGrad", "AvgSquaredGradOut"),
                                 ("AvgSquaredUpdate", "AvgSquaredUpdateOut")))
def _adadelta(ctx, op_, ins):
    p, g = _param_grad(ins, op_.type)
    ag = jnp.asarray(ins["AvgSquaredGrad"][0])
    au = jnp.asarray(ins["AvgSquaredUpdate"][0])
    rho = op_.attr("rho", 0.95)
    eps = op_.attr("epsilon", 1e-6)
    ago = rho * ag + (1 - rho) * g * g
    upd = -jnp.sqrt((au + eps) / (ago + eps)) * g
    auo = rho * au + (1 - rho) * upd * upd
    return {"ParamOut": [p + upd], "AvgSquaredGradOut": [ago],
            "AvgSquaredUpdateOut": [auo]}


@op("rmsprop", grad=NO_GRAD,
    infer_shape=_param_out_infer(("Param", "ParamOut"), ("Moment", "MomentOut"),
                                 ("MeanSquare", "MeanSquareOut")))
def _rmsprop(ctx, op_, ins):
    p, g = _param_grad(ins, op_.type)
    mom = jnp.asarray(ins["Moment"][0])
    ms = jnp.asarray(ins["MeanSquare"][0])
    rho = op_.attr("decay", 0.9)
    eps = op_.attr("epsilon", 1e-10)
    mu = op_.attr("momentum", 0.0)
    mso = rho * ms + (1 - rho) * g * g
    momo = mu * mom + _lr(ins) * g / jnp.sqrt(mso + eps)
    return {"ParamOut": [p - momo], "MomentOut": [momo], "MeanSquareOut": [mso]}


@op("ftrl", grad=NO_GRAD,
    infer_shape=_param_out_infer(("Param", "ParamOut"),
                                 ("SquaredAccumulator", "SquaredAccumOut"),
                                 ("LinearAccumulator", "LinearAccumOut")))
def _ftrl(ctx, op_, ins):
    p, g = _param_grad(ins, op_.type)
    sq = jnp.asarray(ins["SquaredAccumulator"][0])
    lin = jnp.asarray(ins["LinearAccumulator"][0])
    l1 = op_.attr("l1", 0.0)
    l2 = op_.attr("l2", 0.0)
    power = op_.attr("lr_power", -0.5)
    lr = _lr(ins)
    new_sq = sq + g * g
    if power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -power) - jnp.power(sq, -power)) / lr
    new_lin = lin + g - sigma * p
    if power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -power) / lr + 2 * l2
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    po = pre / denom
    return {"ParamOut": [po], "SquaredAccumOut": [new_sq],
            "LinearAccumOut": [new_lin]}


@op("proximal_gd", grad=NO_GRAD,
    infer_shape=_param_out_infer(("Param", "ParamOut")))
def _proximal_gd(ctx, op_, ins):
    p, g = _param_grad(ins, op_.type)
    l1 = op_.attr("l1", 0.0)
    l2 = op_.attr("l2", 0.0)
    lr = _lr(ins)
    prox = p - lr * g
    po = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) \
        / (1.0 + lr * l2)
    return {"ParamOut": [po]}


@op("proximal_adagrad", grad=NO_GRAD,
    infer_shape=_param_out_infer(("Param", "ParamOut"), ("Moment", "MomentOut")))
def _proximal_adagrad(ctx, op_, ins):
    p, g = _param_grad(ins, op_.type)
    m = jnp.asarray(ins["Moment"][0])
    l1 = op_.attr("l1", 0.0)
    l2 = op_.attr("l2", 0.0)
    mo = m + g * g
    lr = _lr(ins) / jnp.sqrt(mo + 1e-12)
    prox = p - lr * g
    po = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) \
        / (1.0 + lr * l2)
    return {"ParamOut": [po], "MomentOut": [mo]}
