"""NN ops: conv/pool/norm/softmax/losses/embedding/dropout/metrics.

TPU-native lowerings of the reference ops (conv_op.cc + conv_cudnn_op.cu.cc,
pool_op.cc, batch_norm_op.cc, layer_norm_op.cc, softmax_op.cc,
cross_entropy_op.cc, softmax_with_cross_entropy_op.cc, lookup_table_op.cc,
dropout_op.cc, lrn_op.cc, accuracy_op.cc, auc_op.cc, loss ops…). Layout is
NCHW to match the reference's user-visible semantics; XLA relayouts for the
MXU internally, so no data_layout_transform pass is needed (reference
framework/data_layout_transform.cc becomes a compiler concern).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.desc import OpDesc
from ..framework.framework import grad_var_name
from .registry import (NO_GRAD, generic_grad_lower, infer_grad_shapes, op,
                       register)
from .common import (SelectedRowsVal, in_var, mxu_cast, out_var,
                     same_as_input, set_out, to_np_dtype)


# --- softmax ----------------------------------------------------------------

@op("softmax", infer_shape=same_as_input())
def _softmax(ctx, op_, ins):
    return {"Out": [jax.nn.softmax(jnp.asarray(ins["X"][0]), axis=-1)]}


def _ce_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None and xv.shape is not None:
        set_out(op_, block, "Y", list(xv.shape[:-1]) + [1], xv.dtype)


@op("cross_entropy", infer_shape=_ce_infer, non_diff_inputs=("Label",))
def _cross_entropy(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    label = jnp.asarray(ins["Label"][0])
    if op_.attr("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.clip(x, 1e-12, None)),
                        axis=-1, keepdims=True)
    else:
        idx = label.reshape(label.shape[0], -1)[:, :1].astype(jnp.int32)
        picked = jnp.take_along_axis(x, idx, axis=-1)
        loss = -jnp.log(jnp.clip(picked, 1e-12, None))
    return {"Y": [loss]}


def _swce_infer(op_, block):
    xv = in_var(op_, block, "Logits")
    if xv is not None and xv.shape is not None:
        set_out(op_, block, "Softmax", xv.shape, xv.dtype)
        set_out(op_, block, "Loss", list(xv.shape[:-1]) + [1], xv.dtype)


@op("softmax_with_cross_entropy", infer_shape=_swce_infer,
    non_diff_inputs=("Label",))
def _softmax_with_cross_entropy(ctx, op_, ins):
    logits = jnp.asarray(ins["Logits"][0])
    label = jnp.asarray(ins["Label"][0])
    # logsumexp in f32 for stability with bf16 logits (AMP O2); the astype
    # is inside the trace so its vjp casts the cotangent back to bf16
    logits = logits.astype(jnp.float32) if logits.dtype != jnp.float32 \
        else logits
    logp = jax.nn.log_softmax(logits, axis=-1)
    if op_.attr("soft_label", False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        idx = label.astype(jnp.int32)
        if idx.ndim < logits.ndim:
            idx = idx[..., None]
        elif idx.shape[-1] != 1:
            idx = idx[..., :1]
        loss = -jnp.take_along_axis(logp, idx, axis=-1)
    # padded sequence logits [B,T,V]: zero the padded positions' losses
    lengths = ctx.seq_len(op_.desc.inputs["Logits"][0])
    if lengths is not None and logits.ndim >= 3:
        t = logits.shape[1]
        mask = (jnp.arange(t)[None, :] <
                jnp.asarray(lengths)[:, None]).astype(loss.dtype)
        loss = loss * mask.reshape(mask.shape + (1,) * (loss.ndim - 2))
    return {"Softmax": [jnp.exp(logp)], "Loss": [loss]}


@op("sigmoid_cross_entropy_with_logits", infer_shape=same_as_input(),
    non_diff_inputs=("Label",))
def _sigmoid_ce(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    label = jnp.asarray(ins["Label"][0])
    # max(x,0) - x*z + log(1+exp(-|x|)) — stable form
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": [loss]}


# --- simple losses ----------------------------------------------------------

@op("smooth_l1_loss", non_diff_inputs=("Y",))
def _smooth_l1(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    y = jnp.asarray(ins["Y"][0])
    sigma2 = op_.attr("sigma", 1.0) ** 2
    d = x - y
    if ins.get("InsideWeight") and ins["InsideWeight"][0] is not None:
        d = d * jnp.asarray(ins["InsideWeight"][0])
    ad = jnp.abs(d)
    diff = jnp.where(ad < 1.0 / sigma2, 0.5 * d * d * sigma2, ad - 0.5 / sigma2)
    if ins.get("OutsideWeight") and ins["OutsideWeight"][0] is not None:
        diff = diff * jnp.asarray(ins["OutsideWeight"][0])
    out = jnp.sum(diff.reshape(diff.shape[0], -1), axis=1, keepdims=True)
    return {"Out": [out], "Diff": [d]}


@op("log_loss", non_diff_inputs=("Labels",))
def _log_loss(ctx, op_, ins):
    p = jnp.asarray(ins["Predicted"][0])
    y = jnp.asarray(ins["Labels"][0])
    eps = op_.attr("epsilon", 1e-4)
    out = -y * jnp.log(p + eps) - (1 - y) * jnp.log(1 - p + eps)
    return {"Loss": [out]}


@op("hinge_loss", non_diff_inputs=("Labels",))
def _hinge_loss(ctx, op_, ins):
    pred = jnp.asarray(ins["Logits"][0])
    label = jnp.asarray(ins["Labels"][0])
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2 * label - 1) * pred)]}


@op("huber_loss", non_diff_inputs=("Y",))
def _huber_loss(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])  # predictions
    y = jnp.asarray(ins["Y"][0])
    delta = op_.attr("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return {"Out": [loss], "Residual": [r]}


@op("rank_loss", non_diff_inputs=("Label",))
def _rank_loss(ctx, op_, ins):
    label = jnp.asarray(ins["Label"][0])
    left = jnp.asarray(ins["Left"][0])
    right = jnp.asarray(ins["Right"][0])
    d = left - right
    return {"Out": [jnp.log1p(jnp.exp(d)) - label * d]}


@op("margin_rank_loss", non_diff_inputs=("Label",))
def _margin_rank_loss(ctx, op_, ins):
    label = jnp.asarray(ins["Label"][0])
    x1 = jnp.asarray(ins["X1"][0])
    x2 = jnp.asarray(ins["X2"][0])
    margin = op_.attr("margin", 0.0)
    act = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [act], "Activated": [(act > 0).astype(x1.dtype)]}


@op("squared_l2_norm")
def _squared_l2_norm(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    return {"Out": [jnp.sum(x * x).reshape(1)]}


@op("squared_l2_distance", non_diff_inputs=())
def _squared_l2_distance(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    y = jnp.asarray(ins["Y"][0])
    sub = x - y
    return {"Out": [jnp.sum(sub * sub, axis=1, keepdims=True)], "sub_result": [sub]}


# --- embedding --------------------------------------------------------------

def _lookup_infer(op_, block):
    wv, iv = in_var(op_, block, "W"), in_var(op_, block, "Ids")
    if wv is None or iv is None or wv.shape is None or iv.shape is None:
        return
    shape = list(iv.shape)
    if shape and shape[-1] == 1:
        shape = shape[:-1]
    set_out(op_, block, "Out", shape + [wv.shape[1]], wv.dtype)


@op("lookup_table", infer_shape=_lookup_infer, non_diff_inputs=("Ids",))
def _lookup_table(ctx, op_, ins):
    from . import sparse_ops
    w = jnp.asarray(ins["W"][0])
    ids = jnp.asarray(ins["Ids"][0])
    squeeze_last = ids.ndim > 1 and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids.reshape(ids.shape[:-1])
    pad = op_.attr("padding_idx", -1)
    ids32 = ids.astype(jnp.int32)
    wname = (op_.input("W") or [None])[0]
    if wname and sparse_ops.table_axes(ctx.program, wname) is not None:
        # row-sharded table: pin + gather under pd.coll.emb_lookup so
        # GSPMD mod-shard-routes the ids instead of all-gathering rows
        out = sparse_ops.sharded_lookup(ctx.program, wname, w, ids32)
    else:
        out = jnp.take(w, ids32, axis=0)
    if pad is not None and pad >= 0:
        out = jnp.where((ids == pad)[..., None], 0.0, out)
    return {"Out": [out]}


@op("lookup_table_grad", grad=NO_GRAD)
def _lookup_table_grad(ctx, op_, ins):
    """Embedding gradient (reference lookup_table_op.cc LookupTableGradKernel).
    is_sparse=True returns a SelectedRowsVal — ids + per-lookup cotangent
    rows, duplicates unmerged exactly like the reference — so the sgd
    update is a scatter-add touching only the looked-up rows instead of a
    dense table-sized gradient (reference selected_rows_functor.cc).
    Dense path scatter-adds into a full zeros table."""
    w = jnp.asarray(ins["W"][0])
    ids = jnp.asarray(ins["Ids"][0])
    if ids.ndim > 1 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    g = jnp.asarray(ins["Out@GRAD"][0])
    pad = op_.attr("padding_idx", -1)
    flat_ids = ids.reshape(-1).astype(jnp.int32)
    flat_g = g.reshape(-1, g.shape[-1]).astype(w.dtype)
    if pad is not None and pad >= 0:
        flat_g = jnp.where((flat_ids == pad)[:, None], 0.0, flat_g)
    from . import sparse_ops
    wname = (op_.input("W") or [None])[0]
    sharded = (wname is not None
               and sparse_ops.table_axes(ctx.program, wname) is not None)
    if op_.attr("is_sparse", False) or sharded:
        # sharded tables force the sparse grad even without is_sparse: a
        # dense [V, D] cotangent would materialize the whole table per
        # device before the optimizer ever saw it
        if sharded and not op_.attr("is_sparse", False):
            sparse_ops.note_once(
                f"forced_sparse:{wname}",
                f"lookup_table_grad for row-sharded table '{wname}' "
                f"emits a SelectedRows gradient (is_sparse forced on): "
                f"a dense gradient would materialize the full table.")
        return {"W@GRAD": [SelectedRowsVal(flat_ids, flat_g, w.shape[0])]}
    dense = jnp.zeros_like(w).at[flat_ids].add(flat_g)
    return {"W@GRAD": [dense]}


# --- conv / pool ------------------------------------------------------------

def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n

def _conv_out_dim(i, k, p, s, d=1):
    if i is None or i < 0:
        return None
    ke = d * (k - 1) + 1
    return (i + 2 * p - ke) // s + 1


def _conv2d_infer(op_, block):
    xv, fv = in_var(op_, block, "Input"), in_var(op_, block, "Filter")
    if xv is None or fv is None or xv.shape is None or fv.shape is None:
        return
    s, p, d = (_pair(op_.attr("strides", [1, 1])), _pair(op_.attr("paddings", [0, 0])),
               _pair(op_.attr("dilations", [1, 1])))
    n, _, h, w = xv.shape
    co, _, kh, kw = fv.shape
    set_out(op_, block, "Output",
            [n, co, _conv_out_dim(h, kh, p[0], s[0], d[0]),
             _conv_out_dim(w, kw, p[1], s[1], d[1])], xv.dtype)


@op("conv2d", infer_shape=_conv2d_infer)
def _conv2d(ctx, op_, ins):
    """Computes in NHWC — the TPU-preferred conv layout (channels on the
    minor axis feed the MXU directly; measured ~2x over NCHW on v5e).
    Under the trace-time layout convention (ops/layout.py) the NHWC
    result is kept and tagged so the whole conv/bn/pool stack runs NHWC
    with one transpose at each end; with the convention off, the
    user-visible NCHW layout is restored per conv.

    Eligible shapes (pallas_conv.ineligible is the shared gate) route to
    the hand-tiled Pallas MXU kernel; the rest keep lax.conv with a
    reason-labelled pallas_fallback_total counter."""
    from . import layout as layout_mod
    from . import pallas_conv
    from .. import quant
    x = jnp.asarray(ins["Input"][0])
    w = jnp.asarray(ins["Filter"][0])
    s = _pair(op_.attr("strides", [1, 1]))
    p = _pair(op_.attr("paddings", [0, 0]))
    d = _pair(op_.attr("dilations", [1, 1]))
    groups = op_.attr("groups", 1) or 1
    nhwc_in = ctx.layout_of(op_.desc.inputs["Input"][0]) == layout_mod.NHWC
    (x, w), restore = mxu_cast(ctx, x, w)
    if not nhwc_in:
        x = jnp.transpose(x, (0, 2, 3, 1))
    qmode = getattr(ctx, "quant_mode", None)
    reason = pallas_conv.ineligible(x, w, s, p, d, groups)
    if reason is None:
        pallas_conv.count_hit(op_.type)
        qreason = quant.ineligible_conv(x, w, s, p, d, groups, qmode) \
            if qmode else None
        if qmode and qreason is None:
            quant.count_hit(op_.type)
            fname = op_.desc.inputs["Filter"][0]
            out = quant.qconv2d(x, w, s, p, d, qmode,
                                pre=quant.prequantized(ctx, fname))
        else:
            if qmode:
                quant.count_fallback(op_.type, qreason)
            out = pallas_conv.conv2d(x, w, s, p, d)
    else:
        pallas_conv.count_fallback(op_.type, reason)
        if qmode:
            # the quant conv rides the Pallas kernel suite: no kernel,
            # no quantization (ineligible_conv's "kernel" prerequisite)
            quant.count_fallback(op_.type, "kernel")
        out = jax.lax.conv_general_dilated(
            x, jnp.transpose(w, (2, 3, 1, 0)),
            window_strides=s, padding=[(p[0], p[0]), (p[1], p[1])],
            rhs_dilation=d, feature_group_count=groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if restore is not None:
        out = out.astype(restore)
    if ctx.layout_opt:
        ctx.set_layout(op_.desc.outputs["Output"][0], layout_mod.NHWC)
    else:
        out = jnp.transpose(out, (0, 3, 1, 2))
    return {"Output": [out]}


@op("depthwise_conv2d", infer_shape=_conv2d_infer)
def _depthwise_conv2d(ctx, op_, ins):
    return _conv2d(ctx, op_, ins)


@op("conv2d_grad", infer_shape=infer_grad_shapes, grad=NO_GRAD)
def _conv2d_grad(ctx, op_, ins):
    """Explicit conv backward: eligible shapes take the Pallas grad-input
    and grad-filter kernels; the rest defer to generic_grad_lower (vjp of
    the forward lowering), which re-traces the forward under the SAME
    eligibility predicate — pallas_call is not differentiable, so the
    gate must agree in both directions (check_pallas_table pins this).

    Layout contract (matches the generic path's tag bookkeeping): the
    Output@GRAD cotangent arrives NHWC-tagged when the layout convention
    is on (layout.align_cotangents' prepass) and NCHW otherwise;
    Input@GRAD must be produced in Input's current layout because
    tag_outputs re-tags it from the forward var; Filter@GRAD is always
    canonical OIHW."""
    from . import layout as layout_mod
    from . import pallas_conv
    douts = ins.get("Output@GRAD")
    if not douts or douts[0] is None:
        # Zero cotangent (output unused by the loss): emit explicit
        # zeros. Deferring to generic_grad_lower would jax.vjp the
        # forward lowering, and for Pallas-eligible shapes that re-trace
        # hits pl.pallas_call — which has no transpose rule — and crashes
        # at trace time. zeros_like keeps each grad in its forward var's
        # current layout and dtype, satisfying the contract above.
        outs = {}
        for slot, names in op_.desc.outputs.items():
            base = slot[: -len("@GRAD")]
            srcs = ins.get(base, [])
            outs[slot] = [
                jnp.zeros_like(jnp.asarray(srcs[i]))
                if i < len(srcs) and srcs[i] is not None else None
                for i in range(len(names))]
        return outs
    x = jnp.asarray(ins["Input"][0])
    w = jnp.asarray(ins["Filter"][0])
    s = _pair(op_.attr("strides", [1, 1]))
    p = _pair(op_.attr("paddings", [0, 0]))
    d = _pair(op_.attr("dilations", [1, 1]))
    groups = op_.attr("groups", 1) or 1
    x_nhwc_in = ctx.layout_of(op_.desc.inputs["Input"][0]) == layout_mod.NHWC
    (xc, wc), _ = mxu_cast(ctx, x, w)
    x_nhwc = xc if x_nhwc_in else jnp.transpose(xc, (0, 2, 3, 1))
    reason = pallas_conv.ineligible(x_nhwc, wc, s, p, d, groups)
    if reason is not None:
        pallas_conv.count_fallback(op_.type, reason)
        # The forward lowering already counted itself when the forward
        # graph was traced; mute its counters while the vjp re-traces it,
        # or every grad fallback double-books the op=conv2d series.
        with pallas_conv.suppress_counters():
            return generic_grad_lower(ctx, op_, ins)
    pallas_conv.count_hit(op_.type)
    dout = jnp.asarray(ins["Output@GRAD"][0])
    gname = op_.desc.inputs["Output@GRAD"][0]
    if ctx.layout_of(gname) != layout_mod.NHWC:
        dout = jnp.transpose(dout, (0, 2, 3, 1))
    dout = dout.astype(jnp.bfloat16)
    outs = {}
    if "Input@GRAD" in op_.desc.outputs:
        dx = pallas_conv.conv2d_grad_input(
            dout, wc, (x_nhwc.shape[1], x_nhwc.shape[2]), s, p, d,
            out_dtype=x.dtype)
        if not x_nhwc_in:
            dx = jnp.transpose(dx, (0, 3, 1, 2))
        outs["Input@GRAD"] = [dx]
    if "Filter@GRAD" in op_.desc.outputs:
        dw = pallas_conv.conv2d_grad_filter(
            x_nhwc, dout, (wc.shape[2], wc.shape[3]), s, p, d,
            out_dtype=w.dtype)
        outs["Filter@GRAD"] = [dw]
    return outs


@op("depthwise_conv2d_grad", infer_shape=infer_grad_shapes, grad=NO_GRAD)
def _depthwise_conv2d_grad(ctx, op_, ins):
    return _conv2d_grad(ctx, op_, ins)


def _conv3d_infer(op_, block):
    xv, fv = in_var(op_, block, "Input"), in_var(op_, block, "Filter")
    if xv is None or fv is None or xv.shape is None or fv.shape is None:
        return
    s = _pair(op_.attr("strides", [1, 1, 1]), 3)
    p = _pair(op_.attr("paddings", [0, 0, 0]), 3)
    d = _pair(op_.attr("dilations", [1, 1, 1]), 3)
    n = xv.shape[0]
    co = fv.shape[0]
    dims = [_conv_out_dim(xv.shape[2 + i], fv.shape[2 + i], p[i], s[i], d[i])
            for i in range(3)]
    set_out(op_, block, "Output", [n, co] + dims, xv.dtype)


@op("conv3d", infer_shape=_conv3d_infer)
def _conv3d(ctx, op_, ins):
    """NDHWC compute for the MXU, same layout convention as conv2d."""
    from . import layout as layout_mod
    x = jnp.asarray(ins["Input"][0])
    w = jnp.asarray(ins["Filter"][0])
    s = _pair(op_.attr("strides", [1, 1, 1]), 3)
    p = _pair(op_.attr("paddings", [0, 0, 0]), 3)
    d = _pair(op_.attr("dilations", [1, 1, 1]), 3)
    groups = op_.attr("groups", 1) or 1
    ndhwc_in = ctx.layout_of(op_.desc.inputs["Input"][0]) == layout_mod.NDHWC
    (x, w), restore = mxu_cast(ctx, x, w)
    if not ndhwc_in:
        x = jnp.transpose(x, (0, 2, 3, 4, 1))
    out = jax.lax.conv_general_dilated(
        x, jnp.transpose(w, (2, 3, 4, 1, 0)),
        window_strides=s, padding=[(pi, pi) for pi in p],
        rhs_dilation=d, feature_group_count=groups,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    if restore is not None:
        out = out.astype(restore)
    if ctx.layout_opt:
        ctx.set_layout(op_.desc.outputs["Output"][0], layout_mod.NDHWC)
    else:
        out = jnp.transpose(out, (0, 4, 1, 2, 3))
    return {"Output": [out]}


def _convt2d_infer(op_, block):
    xv, fv = in_var(op_, block, "Input"), in_var(op_, block, "Filter")
    if xv is None or fv is None or xv.shape is None or fv.shape is None:
        return
    s = _pair(op_.attr("strides", [1, 1]))
    p = _pair(op_.attr("paddings", [0, 0]))
    d = _pair(op_.attr("dilations", [1, 1]))
    n, _, h, w = xv.shape
    _, co, kh, kw = fv.shape

    def odim(i, k, pp, ss, dd):
        if i is None or i < 0:
            return None
        return (i - 1) * ss - 2 * pp + dd * (k - 1) + 1
    set_out(op_, block, "Output",
            [n, co, odim(h, kh, p[0], s[0], d[0]), odim(w, kw, p[1], s[1], d[1])],
            xv.dtype)


@op("conv2d_transpose", infer_shape=_convt2d_infer)
def _conv2d_transpose(ctx, op_, ins):
    """Gradient-of-conv formulation (dilate the input by stride, pad by
    k-1-p), computed in NHWC for the MXU like conv2d."""
    from . import layout as layout_mod
    x = jnp.asarray(ins["Input"][0])
    w = jnp.asarray(ins["Filter"][0])   # (Cin, Cout, kh, kw) = IOHW
    s = _pair(op_.attr("strides", [1, 1]))
    p = _pair(op_.attr("paddings", [0, 0]))
    d = _pair(op_.attr("dilations", [1, 1]))
    kh = d[0] * (w.shape[2] - 1) + 1
    kw = d[1] * (w.shape[3] - 1) + 1
    nhwc_in = ctx.layout_of(op_.desc.inputs["Input"][0]) == layout_mod.NHWC
    (x, w), restore = mxu_cast(ctx, x, w)
    if not nhwc_in:
        x = jnp.transpose(x, (0, 2, 3, 1))
    # (Cin, Cout, kh, kw) flipped spatially -> HWIO with I=Cin, O=Cout
    out = jax.lax.conv_general_dilated(
        x, jnp.transpose(jnp.flip(w, (2, 3)), (2, 3, 0, 1)),
        window_strides=(1, 1),
        padding=[(kh - 1 - p[0], kh - 1 - p[0]), (kw - 1 - p[1], kw - 1 - p[1])],
        lhs_dilation=s, rhs_dilation=d,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if restore is not None:
        out = out.astype(restore)
    if ctx.layout_opt:
        ctx.set_layout(op_.desc.outputs["Output"][0], layout_mod.NHWC)
    else:
        out = jnp.transpose(out, (0, 3, 1, 2))
    return {"Output": [out]}


def _pool2d_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is None or xv.shape is None:
        return
    if op_.attr("global_pooling", False):
        set_out(op_, block, "Out", [xv.shape[0], xv.shape[1], 1, 1], xv.dtype)
        return
    k = _pair(op_.attr("ksize"))
    s = _pair(op_.attr("strides", [1, 1]))
    p = _pair(op_.attr("paddings", [0, 0]))
    n, c, h, w = xv.shape

    def odim(i, kk, pp, ss):
        if i is None or i < 0:
            return None
        if op_.attr("ceil_mode", False):
            return (i - kk + 2 * pp + ss - 1) // ss + 1
        return (i - kk + 2 * pp) // ss + 1
    set_out(op_, block, "Out",
            [n, c, odim(h, k[0], p[0], s[0]), odim(w, k[1], p[1], s[1])], xv.dtype)


@op("pool2d", infer_shape=_pool2d_infer)
def _pool2d(ctx, op_, ins):
    from . import layout as layout_mod
    x = jnp.asarray(ins["X"][0])
    nhwc = ctx.layout_of(op_.desc.inputs["X"][0]) == layout_mod.NHWC
    sp = (1, 2) if nhwc else (2, 3)   # spatial dims in the live layout
    ptype = op_.attr("pooling_type", "max")
    if op_.attr("global_pooling", False):
        k = [x.shape[sp[0]], x.shape[sp[1]]]
        s, p = k, [0, 0]
    else:
        k = _pair(op_.attr("ksize"))
        s = _pair(op_.attr("strides", [1, 1]))
        p = _pair(op_.attr("paddings", [0, 0]))
    if nhwc:
        window = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    else:
        window = (1, 1, k[0], k[1])
        strides = (1, 1, s[0], s[1])
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    if ptype == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides, pads)
    else:
        out = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
        if op_.attr("exclusive", True):
            ones = jnp.ones((x.shape[sp[0]], x.shape[sp[1]]), dtype=x.dtype)
            ones = ones[None, :, :, None] if nhwc else ones[None, None]
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides, pads)
            out = out / cnt
        else:
            out = out / (k[0] * k[1])
    if nhwc:
        ctx.set_layout(op_.desc.outputs["Out"][0], layout_mod.NHWC)
    return {"Out": [out]}


# --- normalization ----------------------------------------------------------

def _bn_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is None or xv.shape is None:
        return
    set_out(op_, block, "Y", xv.shape, xv.dtype)
    c = xv.shape[1] if len(xv.shape) > 1 else xv.shape[0]
    for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        set_out(op_, block, slot, [c], "float32")


@op("batch_norm", infer_shape=_bn_infer,
    non_diff_inputs=("Mean", "Variance"))
def _batch_norm(ctx, op_, ins):
    from . import layout as layout_mod
    x = jnp.asarray(ins["X"][0])
    scale = jnp.asarray(ins["Scale"][0])
    bias = jnp.asarray(ins["Bias"][0])
    mean = jnp.asarray(ins["Mean"][0])
    var = jnp.asarray(ins["Variance"][0])
    eps = op_.attr("epsilon", 1e-5)
    momentum = op_.attr("momentum", 0.9)
    is_test = op_.attr("is_test", False)
    tag = ctx.layout_of(op_.desc.inputs["X"][0])
    # channel axis: minor under the internal NHWC/NDHWC convention
    ch = (x.ndim - 1) if tag in (layout_mod.NHWC, layout_mod.NDHWC) else 1
    axes = tuple(i for i in range(x.ndim) if i != ch)
    shape = [1] * x.ndim
    shape[ch] = x.shape[ch]

    # statistics always in f32 — bf16 inputs (AMP O2) would lose too many
    # mantissa bits in the mean/var reductions; output returns to x's dtype
    # so bf16 activations stay bf16 downstream
    xf = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
    if is_test:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = mean
        saved_var = var
    else:
        use_mean = jnp.mean(xf, axis=axes)
        if x.dtype == jnp.bfloat16:
            # one-pass statistics: E[x] and E[x^2] are sibling reductions
            # over the same input, which XLA multi-output-fuses into a
            # single sweep of x — one fewer full HBM read per BN (+12%
            # ResNet-50 step throughput). Safe only for bf16 activations:
            # their 8-bit mantissa already bounds the relative error, so
            # the E[x^2]-E[x]^2 cancellation adds nothing beyond the
            # input quantization. f32 inputs with large mean/std ratio
            # would catastrophically cancel, so they take the centered
            # two-pass form below.
            use_var = jnp.maximum(
                jnp.mean(jnp.square(xf), axis=axes) - jnp.square(use_mean),
                0.0)
        else:
            use_var = jnp.mean(jnp.square(xf - use_mean.reshape(shape)),
                               axis=axes)
        mean_out = mean * momentum + use_mean * (1.0 - momentum)
        var_out = var * momentum + use_var * (1.0 - momentum)
        saved_mean = use_mean
        saved_var = use_var
    inv = jax.lax.rsqrt(use_var + eps)
    y = (xf - use_mean.reshape(shape)) * (inv * scale).reshape(shape) \
        + bias.reshape(shape)
    y = y.astype(x.dtype)
    if tag in (layout_mod.NHWC, layout_mod.NDHWC):
        ctx.set_layout(op_.desc.outputs["Y"][0], tag)
    return {"Y": [y], "MeanOut": [mean_out], "VarianceOut": [var_out],
            "SavedMean": [saved_mean], "SavedVariance": [saved_var]}


def _ln_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is None or xv.shape is None:
        return
    set_out(op_, block, "Y", xv.shape, xv.dtype)
    ax = op_.attr("begin_norm_axis", 1)
    left = int(np.prod([d for d in xv.shape[:ax]])) if all(
        d is not None and d > 0 for d in xv.shape[:ax]) else None
    set_out(op_, block, "Mean", [left] if left else None, "float32")
    set_out(op_, block, "Variance", [left] if left else None, "float32")


@op("layer_norm", infer_shape=_ln_infer)
def _layer_norm(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    ax = op_.attr("begin_norm_axis", 1)
    eps = op_.attr("epsilon", 1e-5)
    axes = tuple(range(ax, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    feat_shape = (1,) * ax + x.shape[ax:]
    if ins.get("Scale") and ins["Scale"][0] is not None:
        y = y * jnp.asarray(ins["Scale"][0]).reshape(feat_shape)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        y = y + jnp.asarray(ins["Bias"][0]).reshape(feat_shape)
    return {"Y": [y], "Mean": [mean.reshape(-1)], "Variance": [var.reshape(-1)]}


@op("lrn", infer_shape=same_as_input())
def _lrn(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    n = op_.attr("n", 5)
    k = op_.attr("k", 2.0)
    alpha = op_.attr("alpha", 1e-4)
    beta = op_.attr("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    acc = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add, (1, n, 1, 1), (1, 1, 1, 1),
        ((0, 0), (half, n - 1 - half), (0, 0), (0, 0)))
    mid = k + alpha * acc
    return {"Out": [x / jnp.power(mid, beta)], "MidOut": [mid]}


@op("label_smooth", non_diff_inputs=("PriorDist",))
def _label_smooth(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    eps = op_.attr("epsilon", 0.0)
    if ins.get("PriorDist") and ins["PriorDist"][0] is not None:
        prior = jnp.asarray(ins["PriorDist"][0])
        out = (1 - eps) * x + eps * prior
    else:
        out = (1 - eps) * x + eps / x.shape[-1]
    return {"Out": [out]}


# --- dropout ----------------------------------------------------------------

def _dropout_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None:
        set_out(op_, block, "Out", xv.shape, xv.dtype)
        set_out(op_, block, "Mask", xv.shape, "float32")


def _dropout_grad(fwd, no_grad_set):
    xname = fwd.input("X")[0]
    if xname in no_grad_set:
        return []
    return [OpDesc(
        type="dropout_grad",
        inputs={"Mask": fwd.output("Mask"),
                "Out@GRAD": [grad_var_name(fwd.output("Out")[0])]},
        outputs={"X@GRAD": [grad_var_name(xname)]},
        attrs=dict(fwd.attrs))]


@op("dropout", infer_shape=_dropout_infer, grad=_dropout_grad)
def _dropout(ctx, op_, ins):
    """Reference semantics (dropout_op.cc, 'downgrade_in_infer'): train
    multiplies by a bernoulli mask; inference scales by (1-p)."""
    x = jnp.asarray(ins["X"][0])
    p = op_.attr("dropout_prob", 0.5)
    if op_.attr("is_test", False):
        return {"Out": [x * (1.0 - p)], "Mask": [jnp.ones_like(x)]}
    key = ctx.next_rng(op_)
    mask = jax.random.bernoulli(key, 1.0 - p, x.shape).astype(x.dtype)
    return {"Out": [x * mask], "Mask": [mask]}


@op("dropout_grad", grad=NO_GRAD)
def _dropout_grad_kernel(ctx, op_, ins):
    dout = jnp.asarray(ins["Out@GRAD"][0])
    mask = jnp.asarray(ins["Mask"][0])
    return {"X@GRAD": [dout * mask]}


# --- metrics (no grad) ------------------------------------------------------

def _accuracy_infer(op_, block):
    set_out(op_, block, "Accuracy", [1], "float32")
    set_out(op_, block, "Correct", [1], "int32")
    set_out(op_, block, "Total", [1], "int32")


@op("accuracy", infer_shape=_accuracy_infer, grad=NO_GRAD)
def _accuracy(ctx, op_, ins):
    idx = jnp.asarray(ins["Indices"][0])
    label = jnp.asarray(ins["Label"][0]).reshape(-1, 1)
    hit = jnp.any(idx == label, axis=1)
    correct = jnp.sum(hit.astype(jnp.int32)).reshape(1)
    total = jnp.asarray([idx.shape[0]], dtype=jnp.int32)
    acc = correct.astype(jnp.float32) / idx.shape[0]
    return {"Accuracy": [acc], "Correct": [correct], "Total": [total]}


@op("auc", grad=NO_GRAD)
def _auc(ctx, op_, ins):
    """Streaming-free AUC over the batch via threshold buckets
    (reference auc_op.cc)."""
    pred = jnp.asarray(ins["Out"][0])
    label = jnp.asarray(ins["Label"][0]).reshape(-1)
    pos_score = pred[:, 1] if pred.ndim == 2 and pred.shape[1] >= 2 \
        else pred.reshape(-1)
    num_t = op_.attr("num_thresholds", 200)
    th = jnp.linspace(0.0, 1.0, num_t)
    is_pos = (label > 0)
    tp = jnp.sum((pos_score[None, :] >= th[:, None]) & is_pos[None, :], axis=1)
    fp = jnp.sum((pos_score[None, :] >= th[:, None]) & ~is_pos[None, :], axis=1)
    P = jnp.maximum(jnp.sum(is_pos), 1)
    N = jnp.maximum(jnp.sum(~is_pos), 1)
    tpr = tp / P
    fpr = fp / N
    auc = -jnp.trapezoid(tpr, fpr)
    return {"AUC": [auc.reshape(1)]}


# --- attention ---------------------------------------------------------------

def _sdpa_infer(op_, block):
    qv = in_var(op_, block, "Q")
    if qv is not None:
        set_out(op_, block, "Out", qv.shape, qv.dtype)
        if "LSE" in op_.desc.outputs:
            b, t, h = qv.shape[0], qv.shape[1], qv.shape[2]
            set_out(op_, block, "LSE", [b, h, t], "float32")


def _sdpa_grad(fwd, no_grad_set):
    """Explicit grad op consuming the forward's saved LSE (dropout-Mask
    pattern; reference batch_norm saves statistics the same way). The
    generic vjp maker would re-trace the forward INSIDE the grad op — for
    HLO einsums XLA CSEs the duplicate, but pallas custom calls are not
    CSE'd, so use_flash would pay the flash forward twice per step."""
    wanted = [s for s in ("Q", "K", "V")
              if fwd.input(s)[0] not in no_grad_set]
    if not wanted:
        return []
    return [OpDesc(
        type="scaled_dot_product_attention_grad",
        inputs={"Q": fwd.input("Q"), "K": fwd.input("K"),
                "V": fwd.input("V"), "Out": fwd.output("Out"),
                "LSE": fwd.output("LSE"),
                "Out@GRAD": [grad_var_name(fwd.output("Out")[0])]},
        outputs={s + "@GRAD": [grad_var_name(fwd.input(s)[0])]
                 for s in wanted},
        attrs=dict(fwd.attrs))]


def _flash_auto_threshold():
    """Sequence length at which auto-selection flips from the XLA einsum
    path to the Pallas flash kernel. Below it the einsum wins end-to-end
    (the custom call is a fusion barrier); at/above it flash WINS with
    the r5-tuned 512/1024 tiles — measured on v5e in the transformer
    bench: 1.13x at T=2048, 1.32x at 4096, 1.65x at 8192 over the einsum
    path (bench.py BENCH_MODE=transformer). Env-tunable for other
    chips."""
    import os
    return int(os.environ.get("PADDLE_TPU_FLASH_AUTO_T", "2048"))


def _ring_uses_flash(op_, q, mesh):
    """Whether the ring path runs Pallas flash blocks per shard: explicit
    use_flash=False forces the einsum ring; True or 'auto' takes flash
    whenever the shard shape tiles (long-context is exactly where flash
    pays). Static — the explicit grad op recomputes the same decision."""
    uf = op_.attr("use_flash", "auto")
    if uf is False:
        return False
    from ..parallel.ring_attention import flash_ring_eligible
    return flash_ring_eligible(q, mesh, "sp")


def _sdpa_paths(ctx, op_, q, k, v):
    """(mode, mesh): 'ring' under sequence_parallel with an sp mesh,
    'flash' when use_flash (True, or 'auto' at long T) and the shape
    tiles, else 'einsum'. Auto-selection (VERDICT r4 #2): the default
    config gets whichever path is faster for its shape, no user flag."""
    from . import pallas_attention
    mesh = getattr(ctx.program, "_mesh", None)
    if op_.attr("sequence_parallel", False) and mesh is not None and \
            "sp" in mesh.axis_names:
        return "ring", mesh
    uf = op_.attr("use_flash", "auto")
    if uf == "auto":
        uf = q.shape[1] >= _flash_auto_threshold()
    if uf and pallas_attention.supports(q, k, v):
        return "flash", None
    return "einsum", None


@op("scaled_dot_product_attention", infer_shape=_sdpa_infer,
    grad=_sdpa_grad)
def _scaled_dot_product_attention(ctx, op_, ins):
    """Fused softmax attention, Q/K/V [B, T, H, D] (no 2018-reference
    analogue — the capability the brief requires for long context). With
    sequence_parallel=True and a program mesh carrying an 'sp' axis, the
    computation runs as ring attention (parallel/ring_attention.py):
    sequence shards stay resident per device and K/V rotate over ICI via
    ppermute, so full-sequence scores never materialize.

    Also emits LSE, the per-row logsumexp of the scaled scores [B, H, T]
    (f32) — the residual the flash backward recomputes from. The einsum
    path derives it from the same logits XLA already CSEs; the ring path
    emits the real ring-merged LSE so its explicit backward can run the
    blockwise ring gradient directly, without re-executing the forward
    (Pallas custom calls are not CSE'd — ADVICE r4)."""
    q = jnp.asarray(ins["Q"][0])
    k = jnp.asarray(ins["K"][0])
    v = jnp.asarray(ins["V"][0])
    causal = op_.attr("causal", False)
    (q, k, v), restore = mxu_cast(ctx, q, k, v)
    from ..parallel.ring_attention import (attention_reference,
                                           attention_reference_lse,
                                           ring_attention_sharded)
    mode, mesh = _sdpa_paths(ctx, op_, q, k, v)
    if mode == "ring":
        out, lse = ring_attention_sharded(
            q, k, v, mesh, axis="sp", causal=causal,
            use_flash=_ring_uses_flash(op_, q, mesh), return_lse=True)
    elif mode == "flash":
        # Pallas flash attention (ops/pallas_attention.py): O(T) memory
        # online-softmax VMEM kernel
        from . import pallas_attention
        out, lse = pallas_attention._forward(q, k, v, causal,
                                             return_lse=True)
    else:
        out = attention_reference(q, k, v, causal=causal)
        lse = attention_reference_lse(q, k, causal=causal)
    if restore is not None:
        out = out.astype(restore)
    return {"Out": [out], "LSE": [lse]}


@op("scaled_dot_product_attention_grad", grad=NO_GRAD,
    non_diff_inputs=("LSE",))
def _sdpa_grad_kernel(ctx, op_, ins):
    """dQ/dK/dV from the saved (Out, LSE): the flash path runs the Pallas
    backward kernels directly (ops/pallas_attention.flash_attention_bwd_
    block) — no forward re-execution; einsum and ring paths differentiate
    their forward under jax.vjp (XLA CSEs the duplicated einsum HLO)."""
    q = jnp.asarray(ins["Q"][0])
    k = jnp.asarray(ins["K"][0])
    v = jnp.asarray(ins["V"][0])
    do = jnp.asarray(ins["Out@GRAD"][0])
    causal = op_.attr("causal", False)
    (q, k, v, do), restore = mxu_cast(ctx, q, k, v, do)
    from ..parallel.ring_attention import (attention_reference,
                                           ring_attention_sharded)
    mode, mesh = _sdpa_paths(ctx, op_, q, k, v)
    if mode == "flash":
        from . import pallas_attention
        o = jnp.asarray(ins["Out"][0]).astype(q.dtype)
        lse = jnp.asarray(ins["LSE"][0])
        scale = 1.0 / (q.shape[-1] ** 0.5)
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1).transpose(0, 2, 1)
        dq, dk, dv = pallas_attention.flash_attention_bwd_block(
            q, k, v, do, lse, delta, 0, 0, scale, causal)
    elif mode == "ring":
        if _ring_uses_flash(op_, q, mesh):
            # direct blockwise ring backward from the saved (Out, LSE):
            # no forward re-execution (ADVICE r4 — a vjp re-trace would
            # pay the un-CSE-able flash forward twice per step)
            from ..parallel.ring_attention import ring_attention_bwd_sharded
            o = jnp.asarray(ins["Out"][0]).astype(q.dtype)
            lse = jnp.asarray(ins["LSE"][0])
            dq, dk, dv = ring_attention_bwd_sharded(
                q, k, v, do.astype(q.dtype), o, lse, mesh, axis="sp",
                causal=causal)
        else:
            _, vjp_fn = jax.vjp(
                lambda a, b, c: ring_attention_sharded(
                    a, b, c, mesh, axis="sp", causal=causal,
                    use_flash=False), q, k, v)
            dq, dk, dv = vjp_fn(do.astype(q.dtype))
    else:
        _, vjp_fn = jax.vjp(
            lambda a, b, c: attention_reference(a, b, c, causal=causal),
            q, k, v)
        dq, dk, dv = vjp_fn(do.astype(q.dtype))
    if restore is not None:
        dq, dk, dv = (dq.astype(restore), dk.astype(restore),
                      dv.astype(restore))
    outs = {}
    for name, g in (("Q@GRAD", dq), ("K@GRAD", dk), ("V@GRAD", dv)):
        if name in op_.desc.outputs:
            outs[name] = [g]
    return outs


# --- mixture of experts ------------------------------------------------------

def _moe_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None:
        set_out(op_, block, "Out", xv.shape, xv.dtype)


@op("moe_ffn", infer_shape=_moe_infer)
def _moe_ffn(ctx, op_, ins):
    """Top-1 gated mixture-of-experts FFN in the GShard dispatch-einsum
    form (no 2018-reference analogue; the expert-parallel capability the
    brief requires). Tokens route to their top expert up to a fixed
    capacity C = ceil(N/E * capacity_factor); dispatch/combine are one-hot
    einsums, so when the expert weights W1 [E, D, F] / W2 [E, F, D] are
    sharded over an 'ep' mesh axis (parallel.shard_parameter), GSPMD
    partitions the expert matmuls and inserts the token all-to-all over
    ICI. Overflowed tokens pass through (residual), standard MoE practice.
    """
    x = jnp.asarray(ins["X"][0])              # [N, D]
    gw = jnp.asarray(ins["GateW"][0])         # [D, E]
    w1 = jnp.asarray(ins["W1"][0])            # [E, D, F]
    w2 = jnp.asarray(ins["W2"][0])            # [E, F, D]
    (x, gw, w1, w2), restore = mxu_cast(ctx, x, gw, w1, w2)
    n, d = x.shape
    e = w1.shape[0]
    cap_f = op_.attr("capacity_factor", 1.25)
    cap = max(int(np.ceil(n / e * cap_f)), 1)

    logits = x @ gw                            # [N, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top = jnp.argmax(probs, axis=-1)           # [N]
    top_p = jnp.max(probs, axis=-1)            # [N]
    onehot = jax.nn.one_hot(top, e, dtype=jnp.float32)   # [N, E]
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot    # position in expert
    keep = (pos < cap) & (onehot > 0)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap,
                            dtype=jnp.float32) * keep[..., None]
    dispatch = pos_oh                           # [N, E, C]

    expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), x)
    h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", expert_in, w1))
    expert_out = jnp.einsum("ecf,efd->ecd", h, w2)
    combine = dispatch * top_p[:, None, None].astype(jnp.float32)
    routed = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), expert_out)
    # overflowed / unrouted tokens pass through unchanged
    routed_mask = dispatch.sum(axis=(1, 2)).astype(x.dtype)[:, None]
    out = routed + x * (1.0 - routed_mask)
    if restore is not None:
        out = out.astype(restore)
    return {"Out": [out]}


def _hsigmoid_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None and xv.shape is not None:
        set_out(op_, block, "Cost", [xv.shape[0], 1], xv.dtype)


@op("hierarchical_sigmoid", infer_shape=_hsigmoid_infer,
    non_diff_inputs=("Label",))
def _hierarchical_sigmoid(ctx, op_, ins):
    """Hierarchical sigmoid over a complete binary code tree (reference
    gserver HierarchicalSigmoidLayer.cpp: codeLength = 1 + floor(log2(
    numClasses - 1)); per-class code bits walk the tree). Cost per sample =
    sum_j softplus(pre_j) - bit_j * pre_j over the label's path, which is
    -log P(label) under the tree factorization. Vectorized over a fixed
    max code length with a validity mask — no per-sample loops, MXU gemm
    for all path nodes at once."""
    x = jnp.asarray(ins["X"][0])                       # [B, F]
    w = jnp.asarray(ins["W"][0])                       # [C-1, F]
    label = jnp.asarray(ins["Label"][0]).reshape(-1)   # [B]
    bias = ins.get("Bias", [None])[0]
    num_classes = int(op_.attr("num_classes"))
    code_len = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))

    c = (label + num_classes).astype(jnp.int32)        # SimpleCode basis
    js = jnp.arange(code_len)
    shifted = c[:, None] >> (js[None, :] + 1)          # [B, J]
    valid = (shifted >= 1).astype(x.dtype)
    idx = jnp.maximum(shifted - 1, 0)                  # node ids [B, J]
    bits = ((c[:, None] >> js[None, :]) & 1).astype(x.dtype)

    wn = w[idx]                                        # [B, J, F]
    pre = jnp.einsum("bf,bjf->bj", x, wn)
    if bias is not None:
        b = jnp.asarray(bias).reshape(-1)              # [C-1]
        pre = pre + b[idx]
    cost = (jax.nn.softplus(pre) - bits * pre) * valid
    return {"Cost": [jnp.sum(cost, axis=1, keepdims=True)]}
