"""Misc + LoD-array ops: assign_value, fill, minus, modified_huber_loss,
l1_norm, average_accumulates, print, save/load(_combine),
lod_tensor_to_array / array_to_lod_tensor, split/merge_lod_tensor,
reorder_lod_tensor_by_rank.

TPU-native lowerings (reference: assign_value_op.cc, fill_op.cc,
minus_op.cc, modified_huber_loss_op.h, l1_norm_op.cc,
average_accumulates_op.h, print_op.cc, save_op.cc, load_op.cc,
save_combine_op.cc, load_combine_op.cc, lod_tensor_to_array_op.cc,
array_to_lod_tensor_op.cc, split_lod_tensor_op.cc, merge_lod_tensor_op.cc,
reorder_lod_tensor_by_rank_op.cc). The reference's row-routing LoD ops
become dense masked selects (rows keep their position; no dynamic shapes),
and the file-I/O ops run as host callbacks sequenced into the trace —
the XLA-compatible form of the reference's host-side kernels."""

from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp
import numpy as np

from .common import in_var, out_var, same_as_input, set_out, to_np_dtype
from .registry import NO_GRAD, op
from .control_flow_ops import TensorArrayVal


# --- small tensor ops ---------------------------------------------------------

def _assign_value_infer(op_, block):
    set_out(op_, block, "Out", list(op_.attr("shape")),
            op_.attr("dtype", "float32"))


@op("assign_value", infer_shape=_assign_value_infer, grad=NO_GRAD)
def _assign_value(ctx, op_, ins):
    """Materialize a compile-time constant (reference assign_value_op.cc)."""
    shape = list(op_.attr("shape"))
    dtype = op_.attr("dtype", "float32")
    vals = op_.attr("fp32_values", None)
    if not vals:
        vals = op_.attr("int32_values", None)
    arr = np.asarray(vals, dtype=to_np_dtype(dtype)).reshape(shape)
    return {"Out": [jnp.asarray(arr)]}


def _fill_infer(op_, block):
    set_out(op_, block, "Out", list(op_.attr("shape")),
            op_.attr("dtype", "float32"))


@op("fill", infer_shape=_fill_infer, grad=NO_GRAD)
def _fill(ctx, op_, ins):
    """Fill Out with the literal `value` list (reference fill_op.cc)."""
    shape = list(op_.attr("shape"))
    dtype = op_.attr("dtype", "float32")
    vals = np.asarray(op_.attr("value"), dtype=to_np_dtype(dtype))
    return {"Out": [jnp.asarray(vals.reshape(shape))]}


@op("minus", infer_shape=same_as_input())
def _minus(ctx, op_, ins):
    return {"Out": [jnp.asarray(ins["X"][0]) - jnp.asarray(ins["Y"][0])]}


def _mhl_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None and xv.shape is not None:
        set_out(op_, block, "IntermediateVal", xv.shape, xv.dtype)
        set_out(op_, block, "Out", [xv.shape[0], 1], xv.dtype)


@op("modified_huber_loss", infer_shape=_mhl_infer, non_diff_inputs=("Y",))
def _modified_huber_loss(ctx, op_, ins):
    """Modified Huber loss for binary classification, labels in {0, 1}
    (reference modified_huber_loss_op.h): with a = x * (2y - 1),
    loss = -4a if a < -1; (1 - a)^2 if -1 <= a < 1; 0 otherwise."""
    x = jnp.asarray(ins["X"][0])
    y = jnp.asarray(ins["Y"][0])
    a = x * (2.0 * y - 1.0)
    loss = jnp.where(a < -1.0, -4.0 * a,
                     jnp.where(a < 1.0, (1.0 - a) ** 2, 0.0))
    return {"IntermediateVal": [a], "Out": [loss.reshape(x.shape[0], 1)]}


def _l1_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None:
        set_out(op_, block, "Out", [1], xv.dtype)


@op("l1_norm", infer_shape=_l1_infer)
def _l1_norm(ctx, op_, ins):
    return {"Out": [jnp.sum(jnp.abs(jnp.asarray(ins["X"][0]))).reshape(1)]}


def _print_grad_maker(fwd, no_grad_set):
    """Identity pass-through grad: print only observes, so In@GRAD is
    Out@GRAD verbatim (reference print_op.cc registers its grad the same
    way; before this maker a Print on the loss path silently zeroed the
    gradients flowing through it — ADVICE r5)."""
    from ..framework.desc import OpDesc
    from ..framework.framework import grad_var_name
    in_name = fwd.inputs["In"][0]
    if in_name in no_grad_set:
        return []
    out_name = fwd.outputs["Out"][0]
    return [OpDesc(type="assign",
                   inputs={"X": [grad_var_name(out_name)]},
                   outputs={"Out": [grad_var_name(in_name)]})]


@op("print", grad=_print_grad_maker)
def _print(ctx, op_, ins):
    """Debug print-through (reference print_op.cc): logs the tensor each
    step via a host callback (jax.debug.print — fires at RUN time inside
    the compiled block) and forwards the input unchanged. Shows
    message + var name + shape/dtype; summarize > 0 truncates values."""
    x = jnp.asarray(ins["In"][0])
    msg = op_.attr("message", "") or ""
    name = op_.desc.inputs["In"][0]
    summarize = op_.attr("summarize", -1)
    shown = x.ravel()[:summarize] if summarize and summarize > 0 else x
    # user text goes through str.format: escape braces or a message like
    # "loss {step}" aborts tracing with a KeyError
    prefix = (f"{msg}{name} shape={tuple(x.shape)} dtype={x.dtype} "
              .replace("{", "{{").replace("}", "}}"))
    jax.debug.print(prefix + "{v}", v=shown)
    return {"Out": [x]}


# --- ModelAverage accumulators ------------------------------------------------

_K_MAX_ACC = 16384   # reference average_accumulates_op.h kMaxNumAccumulates


@op("average_accumulates", grad=NO_GRAD,
    non_diff_inputs=("param", "in_sum_1", "in_sum_2", "in_sum_3",
                     "in_num_accumulates", "in_old_num_accumulates",
                     "in_num_updates"))
def _average_accumulates(ctx, op_, ins):
    """ModelAverage accumulator update (reference average_accumulates_op.h):
    maintain staged parameter sums (sum_1 fine-grained, sum_2 coarse, sum_3
    snapshot) and window counters; when the window outgrows
    min(max_average_window, num_updates * average_window) the old sums roll
    into sum_3. The C++ if/else becomes jnp.where — same math, one fused
    XLA computation per step."""
    param = jnp.asarray(ins["param"][0])
    s1 = jnp.asarray(ins["in_sum_1"][0])
    s2 = jnp.asarray(ins["in_sum_2"][0])
    s3 = jnp.asarray(ins["in_sum_3"][0])
    num_acc = jnp.asarray(ins["in_num_accumulates"][0]).reshape(()).astype(jnp.int32)
    old_num_acc = jnp.asarray(ins["in_old_num_accumulates"][0]).reshape(()).astype(jnp.int32)
    num_upd = jnp.asarray(ins["in_num_updates"][0]).reshape(()).astype(jnp.int32)

    avg_win = op_.attr("average_window", 0.0)
    max_win = op_.attr("max_average_window", 2 ** 31 - 1)
    min_win = min(op_.attr("min_average_window", 10000), max_win)

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    s1 = s1 + param

    spill = (num_upd % _K_MAX_ACC) == 0
    s2 = jnp.where(spill, s2 + s1, s2)
    s1 = jnp.where(spill, jnp.zeros_like(s1), s1)

    window_full = (num_acc >= min_win) & \
        (num_acc >= jnp.minimum(
            jnp.asarray(max_win, jnp.float32),
            num_upd.astype(jnp.float32) * avg_win).astype(jnp.int32))
    s3 = jnp.where(window_full, s1 + s2, s3)
    s1 = jnp.where(window_full, jnp.zeros_like(s1), s1)
    s2 = jnp.where(window_full, jnp.zeros_like(s2), s2)
    old_num_acc = jnp.where(window_full, num_acc, old_num_acc)
    num_acc = jnp.where(window_full, 0, num_acc)

    return {"out_sum_1": [s1], "out_sum_2": [s2], "out_sum_3": [s3],
            "out_num_accumulates": [num_acc.reshape(1)],
            "out_old_num_accumulates": [old_num_acc.reshape(1)],
            "out_num_updates": [num_upd.reshape(1)]}


# --- save / load as ops ---------------------------------------------------------

def _save_payload(path, overwrite, payload):
    import os
    if not overwrite and os.path.exists(path):
        raise IOError(f"save op: '{path}' exists and overwrite is False")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(payload, f)


@op("save", grad=NO_GRAD)
def _save(ctx, op_, ins):
    """Persist one variable to file_path (reference save_op.cc). Runs as an
    ordered host callback inside the trace; the on-disk format matches
    io._save_one so load_vars/load ops interoperate."""
    from jax.experimental import io_callback
    x = jnp.asarray(ins["X"][0])
    path = op_.attr("file_path")
    overwrite = op_.attr("overwrite", True)

    def cb(val):
        _save_payload(path, overwrite,
                      {"tensor": np.asarray(val), "lod": None, "version": 0})
        return np.zeros((), np.int32)

    io_callback(cb, jax.ShapeDtypeStruct((), np.int32), x, ordered=True)
    return {}


@op("save_combine", grad=NO_GRAD)
def _save_combine(ctx, op_, ins):
    """Persist several variables into one file (reference
    save_combine_op.cc); format matches io.save_vars(filename=...)."""
    from jax.experimental import io_callback
    names = op_.desc.inputs["X"]
    vals = [jnp.asarray(v) for v in ins["X"]]
    path = op_.attr("file_path")
    overwrite = op_.attr("overwrite", True)

    def cb(*arrs):
        _save_payload(path, overwrite,
                      {n: (np.asarray(a), None) for n, a in zip(names, arrs)})
        return np.zeros((), np.int32)

    io_callback(cb, jax.ShapeDtypeStruct((), np.int32), *vals, ordered=True)
    return {}


def _out_shape_dtype(op_, slot, idx=0):
    block = getattr(op_, "block", None)
    name = op_.desc.outputs[slot][idx]
    b = block
    while b is not None:
        if b.desc.has_var(name):
            v = b.desc.var(name)
            if v.shape is not None and all(
                    s is not None and s >= 0 for s in v.shape):
                return tuple(v.shape), to_np_dtype(v.dtype or "float32")
        b = b.parent_block
    return None, None


@op("load", grad=NO_GRAD)
def _load(ctx, op_, ins):
    """Load a variable saved by the save op (reference load_op.cc). The
    output shape/dtype must be statically declared on the var desc (true
    for persistables) because XLA needs the callback's result shape."""
    path = op_.attr("file_path")
    shape, dtype = _out_shape_dtype(op_, "Out")
    assert shape is not None, (
        "load op: output var needs a static shape/dtype declaration")

    def cb():
        with open(path, "rb") as f:
            d = pickle.load(f)
        return np.asarray(d["tensor"], dtype=dtype).reshape(shape)

    out = jax.pure_callback(cb, jax.ShapeDtypeStruct(shape, dtype))
    return {"Out": [out]}


@op("load_combine", grad=NO_GRAD)
def _load_combine(ctx, op_, ins):
    path = op_.attr("file_path")
    names = op_.desc.outputs["Out"]
    specs = []
    for i, name in enumerate(names):
        shape, dtype = _out_shape_dtype(op_, "Out", i)
        assert shape is not None, (
            f"load_combine: var '{name}' needs a static shape/dtype")
        specs.append(jax.ShapeDtypeStruct(shape, dtype))

    def cb():
        # one read + unpickle for all outputs (reference load_combine_op.cc
        # reads the stream once)
        with open(path, "rb") as f:
            d = pickle.load(f)
        return tuple(
            np.asarray(d[name][0], dtype=spec.dtype).reshape(spec.shape)
            for name, spec in zip(names, specs))

    outs = jax.pure_callback(cb, tuple(specs))
    return {"Out": list(outs)}


# --- LoD-array ops --------------------------------------------------------------

def _table_lengths(ctx, op_, ins, slot="RankTable"):
    names = op_.desc.inputs.get(slot, [])
    lens = ctx.seq_len(names[0]) if names else None
    if lens is None and names and ins.get(slot) and ins[slot][0] is not None:
        v = jnp.asarray(ins[slot][0])
        if v.ndim == 1:   # the rank-table op outputs the lengths vector
            lens = v
    return None if lens is None else jnp.asarray(lens).astype(jnp.int32)


@op("lod_tensor_to_array", grad=None, non_diff_inputs=("RankTable",))
def _lod_tensor_to_array(ctx, op_, ins):
    """Split a padded sequence batch into a time-major TensorArray
    (reference lod_tensor_to_array_op.cc). The reference shrinks each
    timestep's batch to live sequences via the rank table; the dense
    lowering keeps the full batch per step (masking supplies the same
    semantics downstream), so array[t] = X[:, t]."""
    x = jnp.asarray(ins["X"][0])
    t = x.shape[1]
    buf = jnp.swapaxes(x, 0, 1)
    lens = _table_lengths(ctx, op_, ins)
    out_name = op_.desc.outputs["Out"][0]
    ctx.set_seq_len(out_name, lens)
    return {"Out": [TensorArrayVal(buf, jnp.asarray(t, jnp.int32))]}


@op("array_to_lod_tensor", grad=None, non_diff_inputs=("RankTable",))
def _array_to_lod_tensor(ctx, op_, ins):
    """Inverse of lod_tensor_to_array (reference array_to_lod_tensor_op.cc):
    stack the array back into [batch, T, ...] and restore the lengths."""
    arr = ins["X"][0]
    assert isinstance(arr, TensorArrayVal), "array_to_lod_tensor needs array"
    x = jnp.swapaxes(arr.buffer, 0, 1)
    lens = _table_lengths(ctx, op_, ins)
    if lens is None:
        lens = ctx.seq_len(op_.desc.inputs["X"][0])
    ctx.set_seq_len(op_.desc.outputs["Out"][0], lens)
    return {"Out": [x]}


@op("split_lod_tensor", non_diff_inputs=("Mask",))
def _split_lod_tensor(ctx, op_, ins):
    """Route rows by boolean mask (reference split_lod_tensor_op.cc, used by
    IfElse). The reference compacts selected rows; the dense lowering keeps
    row positions and zeroes the complement, which merge_lod_tensor inverts
    exactly."""
    x = jnp.asarray(ins["X"][0])
    mask = jnp.asarray(ins["Mask"][0]).reshape(-1).astype(bool)
    m = mask.reshape((mask.shape[0],) + (1,) * (x.ndim - 1))
    zero = jnp.zeros_like(x)
    return {"OutTrue": [jnp.where(m, x, zero)],
            "OutFalse": [jnp.where(m, zero, x)]}


@op("merge_lod_tensor", non_diff_inputs=("Mask",))
def _merge_lod_tensor(ctx, op_, ins):
    x_true = jnp.asarray(ins["InTrue"][0])
    x_false = jnp.asarray(ins["InFalse"][0])
    mask = jnp.asarray(ins["Mask"][0]).reshape(-1).astype(bool)
    m = mask.reshape((mask.shape[0],) + (1,) * (x_true.ndim - 1))
    return {"Out": [jnp.where(m, x_true, x_false)]}


@op("reorder_lod_tensor_by_rank", grad=None,
    non_diff_inputs=("RankTable",))
def _reorder_lod_tensor_by_rank(ctx, op_, ins):
    """Reorder sequences into rank-table order — descending length, stable
    (reference reorder_lod_tensor_by_rank_op.cc)."""
    x = jnp.asarray(ins["X"][0])
    lens = _table_lengths(ctx, op_, ins)
    if lens is None:
        lens = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    order = jnp.argsort(-lens, stable=True)
    out = jnp.take(x, order, axis=0)
    ctx.set_seq_len(op_.desc.outputs["Out"][0], jnp.take(lens, order))
    return {"Out": [out]}
