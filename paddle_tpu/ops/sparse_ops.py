"""Sparse-embedding kernels: SelectedRows end-to-end (ISSUE 10 tentpole).

The reference framework's SelectedRows exists for recommender-scale
embedding tables (reference: framework/selected_rows.h:19,
operators/math/selected_rows_functor.cc): a step's cost must scale with
*rows touched*, not table size. This module is the TPU-native kernel
layer for that contract:

  * `SPARSE_APPLY_OPS` — the sparse-capable optimizer table (the analogue
    of the reference's per-op SelectedRows kernel registrations; pinned
    against ops/optimizer_ops.py by tools/check_registry.py).
  * `sgd_apply` / `momentum_apply` / `adam_apply` — the scatter-apply
    kernels: merge duplicate rows (jax.ops.segment_sum with static
    num_segments, so dedup compiles into the step), gather the touched
    rows of param + accumulators, run the SAME `*_dense` update math
    from ops/optimizer_ops.py on the gathered [K, D] slab ("in-register"
    update), and scatter the results back with out-of-range drop. A
    1M x 64 table never materializes a dense gradient or a dense
    optimizer-state update.
  * `sharded_lookup` — `lookup_table` on a row-sharded table: the table
    is pinned to its `NamedSharding` under the `pd.coll.emb_lookup`
    scope (fleet.py attributes the routing collectives to it) and the
    static-shape gather lowers through GSPMD's indexed-dim partitioning:
    each shard gathers the ids it owns (div/mod routing against the
    shard's row range) and one cross-shard combine assembles the
    off-shard rows — communication O(ids * D), independent of table
    height.
  * Telemetry: `sparse_apply_rows_total{op}` (static rows per traced
    step per apply site) and `sparse_densify_fallback_total{op,reason}`
    — every place a SelectedRows gradient silently densified now counts
    and warns once, so the perf cliff is visible instead of invisible.

Env: `PADDLE_TPU_SPARSE_APPLY=0` disables the scatter-apply kernels
(gradients densify at the optimizer, counted under reason `gated_off`) —
the bisection baseline for parity debugging. Read at trace time.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .common import SelectedRowsVal, merge_selected_rows

__all__ = [
    "SPARSE_APPLY_OPS", "sparse_apply_enabled", "count_densify",
    "count_apply_rows", "table_axes", "table_sharding", "shard_factor",
    "sharded_lookup", "pin_table", "sgd_apply", "momentum_apply",
    "adam_apply",
]

# Optimizer ops with a scatter-apply (SelectedRows) kernel — the sparse-
# capable table. The reference registers SelectedRows kernels for exactly
# this family (sgd_op.h, momentum extension, adam_op.h SparseAdamFunctor);
# everything else densifies and is counted. tools/check_registry.py pins
# this tuple against the actual lowerings in ops/optimizer_ops.py and
# against executor._SPARSE_AWARE_OPS.
SPARSE_APPLY_OPS: Tuple[str, ...] = ("sgd", "momentum", "adam")


def sparse_apply_enabled() -> bool:
    """PADDLE_TPU_SPARSE_APPLY gate, read at trace time (default on)."""
    return os.environ.get("PADDLE_TPU_SPARSE_APPLY", "1") == "1"


_WARNED: set = set()


def count_densify(op: str, reason: str, amount: int = 1, *,
                  log: bool = True):
    """sparse_densify_fallback_total{op,reason} + a once-per-(op,reason)
    warning: a SelectedRows gradient just became a table-sized dense
    tensor, turning an O(rows-touched) update into an O(table-rows) one."""
    from .. import telemetry
    telemetry.counter(
        "sparse_densify_fallback_total",
        "SelectedRows gradients densified to a full table-sized tensor, "
        "by consuming op and reason (sparse-path perf cliffs made visible)",
        labels=("op", "reason")).labels(op=op, reason=reason).inc(amount)
    if log and (op, reason) not in _WARNED:
        _WARNED.add((op, reason))
        warnings.warn(
            f"SelectedRows gradient densified at '{op}' ({reason}): this "
            f"update now costs O(table rows), not O(rows touched). "
            f"sgd/momentum/adam keep sparse gradients sparse "
            f"(PADDLE_TPU_SPARSE_APPLY=1); other consumers densify.",
            stacklevel=3)


def note_once(key: str, msg: str):
    """One warning per process for a non-counter sparse-path note."""
    if key not in _WARNED:
        _WARNED.add(key)
        warnings.warn(msg, stacklevel=3)


def count_apply_rows(op: str, rows: int):
    """sparse_apply_rows_total{op}: rows scatter-applied per traced step
    at this apply site (K is a static shape, counted at trace time — one
    compiled step applies exactly this many scatter slots per run)."""
    from .. import telemetry
    telemetry.counter(
        "sparse_apply_rows_total",
        "rows scatter-applied per traced step by sparse optimizer "
        "kernels (static K per apply site, counted at trace time)",
        labels=("op",)).labels(op=op).inc(int(rows))


# --- sharded-table plumbing ------------------------------------------------

def table_axes(program, wname: str) -> Optional[Tuple[str, ...]]:
    """Mesh axis names sharding dim 0 (the row dim) of parameter `wname`,
    or None when the table is unsharded / the program has no mesh / the
    annotation names axes the mesh lacks. Dim-0 entries may be a single
    axis ("fsdp") or an axis tuple (("fsdp", "tp") — SNIPPETS.md [2]
    SpecLayout.embeddings)."""
    spec = (getattr(program, "_param_shardings", {}) or {}).get(wname)
    mesh = getattr(program, "_mesh", None)
    if not spec or mesh is None:
        return None
    first = spec[0]
    if not first:
        return None
    axes = tuple(first) if isinstance(first, (tuple, list)) else (first,)
    if not all(a in mesh.axis_names for a in axes):
        return None
    return axes


def table_sharding(program, wname: str):
    """NamedSharding for a row-sharded table, or None."""
    if table_axes(program, wname) is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec
    spec = (getattr(program, "_param_shardings", {}) or {})[wname]
    return NamedSharding(getattr(program, "_mesh"), PartitionSpec(*spec))


def shard_factor(program, wname: str) -> int:
    """How many ways the table's rows split (product of its dim-0 mesh
    axis sizes); 1 for unsharded tables."""
    axes = table_axes(program, wname) or ()
    mesh = getattr(program, "_mesh", None)
    sizes = dict(mesh.shape) if mesh is not None else {}
    f = 1
    for a in axes:
        f *= int(sizes.get(a, 1))
    return f


def sharded_lookup(program, wname: str, w, ids):
    """Embedding gather on a row-sharded table. The table is pinned to
    its NamedSharding inside the `pd.coll.emb_lookup` scope so (a) GSPMD
    partitions the gather on the indexed dim — each shard serves the ids
    in its own row range and one cross-shard combine assembles the
    off-shard rows, never an all-gather of the table — and (b) fleet.py's
    collective table attributes the routing traffic to this site."""
    from ..parallel._collectives import coll_scope
    sh = table_sharding(program, wname)
    with coll_scope("emb_lookup"):
        if sh is not None:
            try:
                w = jax.lax.with_sharding_constraint(w, sh)
            except (TypeError, ValueError):
                pass
        return jnp.take(w, ids, axis=0)


def pin_table(program, pname: str, *vals):
    """Re-pin table-shaped outputs (param + accumulators) to the table's
    row sharding after a scatter-apply, under the `pd.coll.emb_apply`
    scope. No-op (identity) for unsharded tables."""
    sh = table_sharding(program, pname)
    if sh is None:
        return vals if len(vals) != 1 else vals[0]
    from ..parallel._collectives import coll_scope
    out = []
    with coll_scope("emb_apply"):
        for v in vals:
            try:
                out.append(jax.lax.with_sharding_constraint(v, sh))
            except (TypeError, ValueError):
                out.append(v)
    return tuple(out) if len(out) != 1 else out[0]


# --- scatter-apply kernels -------------------------------------------------
#
# Shared shape: merge duplicate rows (segment_sum, static num_segments),
# gather the touched rows of param/accumulators (out-of-range padded
# slots clamp harmlessly), run the op family's *_dense math on the
# gathered [K, D] slab, scatter back with mode="drop" (padded slots
# carry row == height, out of range, so they vanish). Bitwise equal to
# the dense update on touched rows for sgd/momentum when ids are unique;
# duplicate ids differ from the dense scatter-add only by summation
# order inside the merge.

def _merged(p, sr: SelectedRowsVal):
    rows, gv = merge_selected_rows(sr)
    return rows, gv.astype(p.dtype)


def _rows(x, rows):
    return jnp.take(x, rows, axis=0, mode="clip")


def sgd_apply(p, lr, sr: SelectedRowsVal):
    """reference sgd_op.h SelectedRows branch, merge-first."""
    from . import optimizer_ops
    rows, gv = _merged(p, sr)
    count_apply_rows("sgd", rows.shape[0])
    po = optimizer_ops.sgd_dense(_rows(p, rows), gv, lr)
    return p.at[rows].set(po.astype(p.dtype), mode="drop")


def momentum_apply(p, v, lr, mu, use_nesterov, sr: SelectedRowsVal):
    """Lazy momentum: velocity decays + param moves only on the
    gradient's rows (matching sparse adam's lazy semantics)."""
    from . import optimizer_ops
    rows, gv = _merged(p, sr)
    count_apply_rows("momentum", rows.shape[0])
    po, vo = optimizer_ops.momentum_dense(
        _rows(p, rows), gv, _rows(v, rows), lr, mu, use_nesterov)
    return (p.at[rows].set(po.astype(p.dtype), mode="drop"),
            v.at[rows].set(vo.astype(v.dtype), mode="drop"))


def adam_apply(p, m1, m2, lr, b1, b2, eps, b1p, b2p, sr: SelectedRowsVal):
    """Lazy adam (reference adam_op.h SparseAdamFunctor): moments/param
    update only the gradient's rows; untouched rows keep stale moments.
    O(K*D) instead of the O(V*D) densified update."""
    from . import optimizer_ops
    rows, gv = _merged(p, sr)
    count_apply_rows("adam", rows.shape[0])
    po, m1o, m2o = optimizer_ops.adam_dense(
        _rows(p, rows), gv, _rows(m1, rows), _rows(m2, rows),
        lr, b1, b2, eps, b1p, b2p)
    return (p.at[rows].set(po.astype(p.dtype), mode="drop"),
            m1.at[rows].set(m1o.astype(m1.dtype), mode="drop"),
            m2.at[rows].set(m2o.astype(m2.dtype), mode="drop"))
