"""Operator registry: op type -> (jax lowering, shape inference, grad maker).

TPU-native replacement for the reference's op registry + kernel dispatch
(reference: paddle/fluid/framework/op_registry.h:62-195, op_info.h:68,
operator.cc:479 RunImpl). Where the reference dispatches each op to a
hand-written CPU/CUDA kernel at interpretation time, here every op carries a
*lowering* — a pure function from jax arrays to jax arrays — and the executor
traces a whole block of lowerings into a single jitted XLA computation.

Gradient ops: the reference registers a hand-written grad kernel per op
(grad_op_desc_maker.h). Here the default grad maker emits a `<type>_grad`
OpDesc whose kernel is generic: it re-applies the forward lowering under
`jax.vjp` and feeds in the output cotangents. Because the whole block (forward
+ grad ops) compiles into one XLA computation, XLA CSE merges the re-traced
forward with the original, so no redundant compute survives. Ops needing
structurally different grads (sparse embedding updates, control flow)
register custom grad makers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.desc import OpDesc
from ..framework.framework import grad_var_name

# sentinel: op has no gradient (metrics, int ops, assignment of constants…)
NO_GRAD = "no_grad"


@dataclass
class OpDef:
    type: str
    lower: Optional[Callable] = None          # (ctx, op, ins) -> {slot: [values]}
    infer_shape: Optional[Callable] = None    # (op, block) -> None
    grad: Any = None                          # None=generic vjp; NO_GRAD; or maker fn
    no_kernel: bool = False                   # executor-level op (feed/fetch/while…)
    # forward input slots the generic grad should NOT differentiate (indices etc.)
    non_diff_inputs: Sequence[str] = field(default_factory=tuple)


_REGISTRY: Dict[str, OpDef] = {}


def register(type: str, *, lower=None, infer_shape=None, grad=None,
             no_kernel=False, non_diff_inputs=()) -> OpDef:
    assert type not in _REGISTRY, f"op '{type}' registered twice"
    d = OpDef(type=type, lower=lower, infer_shape=infer_shape, grad=grad,
              no_kernel=no_kernel, non_diff_inputs=tuple(non_diff_inputs))
    _REGISTRY[type] = d
    return d


def op(type: str, *, infer_shape=None, grad=None, no_kernel=False,
       non_diff_inputs=()):
    """Decorator form: @op("relu") def _(ctx, op, ins): ..."""
    def deco(fn):
        register(type, lower=fn, infer_shape=infer_shape, grad=grad,
                 no_kernel=no_kernel, non_diff_inputs=non_diff_inputs)
        return fn
    return deco


def get(type: str) -> OpDef:
    d = try_get(type)
    if d is None:
        raise KeyError(f"op '{type}' is not registered")
    return d


def try_get(type: str) -> Optional[OpDef]:
    d = _REGISTRY.get(type)
    if d is None and type.endswith("_grad") and type[: -len("_grad")] in _REGISTRY:
        # Auto-generated grad op backed by the generic vjp kernel; registered
        # lazily so every differentiable forward op gets a grad op for free.
        d = OpDef(type=type, lower=generic_grad_lower,
                  infer_shape=infer_grad_shapes, grad=NO_GRAD)
        _REGISTRY[type] = d
    return d


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


def static_infer(type: str):
    """The shape-inference rule the static analyzer should use for `type`
    (analysis/infer.py keys its shapes pass off this): the registered
    infer_shape when there is one, the generic grad mirror for any
    `<base>_grad` of a registered base — including explicitly registered
    grad ops like dropout_grad whose build-time infer_shape is None — or
    None. Unlike try_get this never mutates the registry, so lints can
    probe coverage without materializing lazy grad entries."""
    d = _REGISTRY.get(type)
    if d is not None and d.infer_shape is not None:
        return d.infer_shape
    if type.endswith("_grad") and type[: -len("_grad")] in _REGISTRY:
        return infer_grad_shapes
    return None


# ---------------------------------------------------------------------------
# Generic gradient machinery
# ---------------------------------------------------------------------------

def make_grad_op_descs(fwd: OpDesc, no_grad_set: set) -> List[OpDesc]:
    """Build grad op desc(s) for a forward op (reference: GradOpDescMakerBase,
    framework/grad_op_desc_maker.h). Custom makers take precedence; the
    default emits one `<type>_grad` op wired by the @GRAD naming convention.
    """
    opdef = get(fwd.type)
    if opdef.grad is NO_GRAD:
        return []
    if callable(opdef.grad):
        return opdef.grad(fwd, no_grad_set)
    assert opdef.lower is not None, (
        f"op '{fwd.type}' has no lowering and no custom grad maker")
    inputs: Dict[str, List[str]] = {}
    for slot, names in fwd.inputs.items():
        inputs[slot] = list(names)
    for slot, names in fwd.outputs.items():
        inputs[slot] = list(names)
        inputs[slot + "@GRAD"] = [grad_var_name(n) for n in names]
    outputs = {
        slot + "@GRAD": [grad_var_name(n) for n in names]
        for slot, names in fwd.inputs.items()
        if slot not in opdef.non_diff_inputs
        and any(n not in no_grad_set for n in names)
    }
    if not outputs:
        return []
    g = OpDesc(type=fwd.type + "_grad", inputs=inputs, outputs=outputs,
               attrs=dict(fwd.attrs))
    g.attrs["__fwd_type__"] = fwd.type
    return [g]


def _is_diff(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)


def generic_grad_lower(ctx, op, ins):
    """Kernel for auto-generated `<type>_grad` ops: vjp of the forward lowering.

    Grad-op inputs hold the forward inputs (original slot names), forward
    outputs, and `<slot>@GRAD` cotangents; outputs are `<slot>@GRAD` input
    grads. Missing cotangents are treated as zeros (an output unused by the
    loss).
    """
    fwd_type = op.attr("__fwd_type__") or op.type[: -len("_grad")]
    fwd_def = get(fwd_type)

    # Reconstruct the forward op view.
    fwd_in_slots = [s for s in op.desc.inputs
                    if not s.endswith("@GRAD") and s not in op.desc.outputs
                    and s + "@GRAD" not in op.desc.inputs]
    # slots that are forward outputs: those with a matching @GRAD input slot
    fwd_out_slots = [s for s in op.desc.inputs
                     if not s.endswith("@GRAD") and s + "@GRAD" in op.desc.inputs]

    fwd_attrs = {k: v for k, v in op.desc.attrs.items() if k != "__fwd_type__"}
    fwd_desc = OpDesc(type=fwd_type,
                      inputs={s: op.desc.inputs[s] for s in fwd_in_slots + fwd_out_slots
                              if s in fwd_in_slots},
                      outputs={s: [n[: -len("@GRAD")] if n.endswith("@GRAD") else n
                                   for n in op.desc.inputs[s]] for s in fwd_out_slots},
                      attrs=fwd_attrs)
    from ..framework.framework import Operator
    fwd_op_view = Operator.__new__(Operator)
    fwd_op_view.block = getattr(op, "block", None)
    fwd_op_view.desc = fwd_desc

    fwd_ins = {s: ins[s] for s in fwd_in_slots if s in ins}

    # Differentiable leaves: float arrays in slots the op differentiates and
    # for which this grad op wants an output.
    want = set()
    for slot in fwd_in_slots:
        if slot + "@GRAD" in op.desc.outputs and slot not in fwd_def.non_diff_inputs:
            want.add(slot)

    diff_paths = []  # (slot, idx)
    for slot in sorted(want):
        for i, v in enumerate(fwd_ins.get(slot, [])):
            if _is_diff(v):
                diff_paths.append((slot, i))

    out_slots_order = sorted(fwd_out_slots)
    # (slot, idx) for each value fwd_fn actually returns — a lowering may
    # produce fewer outputs than the op declares (e.g. sequence_pool's
    # MaxIndex); populated during the eager vjp trace below
    out_spec: List = []

    def fwd_fn(diff_vals):
        local = {s: list(vs) for s, vs in fwd_ins.items()}
        for (slot, i), v in zip(diff_paths, diff_vals):
            local[slot][i] = v
        outs = fwd_def.lower(ctx, fwd_op_view, local)
        flat = []
        out_spec.clear()
        for s in out_slots_order:
            for j, v in enumerate(outs.get(s, [])):
                flat.append(v)
                out_spec.append((s, j))
        return flat

    primals = [fwd_ins[s][i] for s, i in diff_paths]
    # the vjp re-traces the forward lowering, which would book a second
    # quant hit/fallback sample for an op that already counted itself on
    # the forward trace (pallas_conv call sites suppress their own)
    from .. import quant
    with quant.suppress_counters():
        out_vals, vjp_fn = jax.vjp(fwd_fn, primals)

    # Cotangents matched to fwd_fn's actual flat output.
    cts = []
    for ov, (s, j) in zip(out_vals, out_spec):
        ov = jnp.asarray(ov)
        gvals = ins.get(s + "@GRAD", [])
        if not jnp.issubdtype(ov.dtype, jnp.inexact):
            # integer/bool outputs carry no gradient signal
            cts.append(np.zeros(ov.shape, dtype=jax.dtypes.float0))
        elif j < len(gvals) and gvals[j] is not None:
            cts.append(jnp.asarray(gvals[j], dtype=ov.dtype))
        else:
            cts.append(jnp.zeros_like(ov))
    (grads,) = vjp_fn(cts)

    outs: Dict[str, List[Any]] = {}
    by_slot: Dict[str, Dict[int, Any]] = {}
    for (slot, i), g in zip(diff_paths, grads):
        by_slot.setdefault(slot, {})[i] = g
    for slot in op.desc.outputs:
        base = slot[: -len("@GRAD")]
        n = len(op.desc.outputs[slot])
        vals = []
        for i in range(n):
            g = by_slot.get(base, {}).get(i)
            if g is None:
                # non-float input that still demanded a grad slot: zeros
                src = fwd_ins.get(base, [None] * (i + 1))[i]
                g = jnp.zeros_like(src) if src is not None else None
            vals.append(g)
        outs[slot] = vals
    return outs


def infer_grad_shapes(op, block):
    """Shape inference for generic grad ops: each input grad mirrors its
    forward var's shape/dtype."""
    for slot, gnames in op.desc.outputs.items():
        base = slot[: -len("@GRAD")]
        fnames = op.desc.inputs.get(base, [])
        for gname, fname in zip(gnames, fnames):
            if block.desc.has_var(gname) and block.desc.has_var(fname):
                f = block.desc.var(fname)
                g = block.desc.var(gname)
                g.shape = list(f.shape) if f.shape is not None else None
                g.dtype = f.dtype
