"""Vision op family: 3D pooling/conv-transpose, index pooling, unpool, SPP,
ROI pooling, crop, prelu, conv_shift.

TPU-native lowerings of the reference CUDA/CPU kernels (reference:
pool_op.cc [pool3d], pool_with_index_op.cc, unpool_op.cc, spp_op.cc,
roi_pool_op.cc, crop_op.cc, conv_transpose_op.cc [conv3d_transpose],
prelu_op.cc, conv_shift_op.cc). Everything is expressed as dense XLA ops —
windows become `lax.reduce_window` / stacked static slices, ROI bins become
broadcast masks (no data-dependent slicing, so XLA can tile for the MXU/VPU)
— and gradients come from the generic vjp kernel, which routes max-pool
cotangents through the argmax gather exactly like the reference's
hand-written backward kernels do with their saved masks.
"""

from __future__ import annotations

import itertools
import math

import jax
import jax.numpy as jnp
import numpy as np

from .registry import NO_GRAD, op
from .common import in_var, mxu_cast, out_var, same_as_input, set_out


def _triple(v):
    if isinstance(v, (list, tuple)):
        return list(v) if len(v) == 3 else list(v) * 3
    return [v, v, v]


def _pair(v):
    if isinstance(v, (list, tuple)):
        return list(v) if len(v) == 2 else list(v) * 2
    return [v, v]


# --- pool3d -----------------------------------------------------------------

def _pool3d_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is None or xv.shape is None:
        return
    if op_.attr("global_pooling", False):
        set_out(op_, block, "Out", list(xv.shape[:2]) + [1, 1, 1], xv.dtype)
        return
    k = _triple(op_.attr("ksize"))
    s = _triple(op_.attr("strides", [1, 1, 1]))
    p = _triple(op_.attr("paddings", [0, 0, 0]))

    def odim(i, kk, pp, ss):
        if i is None or i < 0:
            return None
        if op_.attr("ceil_mode", False):
            return (i - kk + 2 * pp + ss - 1) // ss + 1
        return (i - kk + 2 * pp) // ss + 1

    n, c, d, h, w = xv.shape
    set_out(op_, block, "Out",
            [n, c, odim(d, k[0], p[0], s[0]), odim(h, k[1], p[1], s[1]),
             odim(w, k[2], p[2], s[2])], xv.dtype)


@op("pool3d", infer_shape=_pool3d_infer)
def _pool3d(ctx, op_, ins):
    """NCDHW max/avg pooling (reference pool_op.cc pool3d registration)."""
    x = jnp.asarray(ins["X"][0])
    if op_.attr("global_pooling", False):
        k = list(x.shape[2:])
        s, p = k, [0, 0, 0]
    else:
        k = _triple(op_.attr("ksize"))
        s = _triple(op_.attr("strides", [1, 1, 1]))
        p = _triple(op_.attr("paddings", [0, 0, 0]))
    window = (1, 1, k[0], k[1], k[2])
    strides = (1, 1, s[0], s[1], s[2])
    pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]), (p[2], p[2]))
    if op_.attr("pooling_type", "max") == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                    strides, pads)
    else:
        out = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
        if op_.attr("exclusive", True):
            ones = jnp.ones(x.shape[2:], dtype=x.dtype)[None, None]
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides, pads)
            out = out / cnt
        else:
            out = out / (k[0] * k[1] * k[2])
    return {"Out": [out]}


# --- max pool with index ----------------------------------------------------

def _windows2d(x, k, s, p, fill):
    """(N,C,H,W) -> windows (N,C,OH,OW,kh*kw) plus flat input index of each
    window element ((OH,OW,kh*kw), -1 where padding)."""
    n, c, h, w = x.shape
    oh = (h - k[0] + 2 * p[0]) // s[0] + 1
    ow = (w - k[1] + 2 * p[1]) // s[1] + 1
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])),
                 constant_values=fill)
    cols, idxs = [], []
    hh = jnp.arange(oh) * s[0]
    ww = jnp.arange(ow) * s[1]
    for ki, kj in itertools.product(range(k[0]), range(k[1])):
        cols.append(jax.lax.slice(
            xp, (0, 0, ki, kj),
            (n, c, ki + (oh - 1) * s[0] + 1, kj + (ow - 1) * s[1] + 1),
            (1, 1, s[0], s[1])))
        hi = hh[:, None] + ki - p[0]
        wi = ww[None, :] + kj - p[1]
        valid = (hi >= 0) & (hi < h) & (wi >= 0) & (wi < w)
        idxs.append(jnp.where(valid, hi * w + wi, -1))
    return jnp.stack(cols, axis=-1), jnp.stack(idxs, axis=-1)


def _pool_index_infer():
    def infer(op_, block):
        xv = in_var(op_, block, "X")
        if xv is None or xv.shape is None:
            return
        nd = len(xv.shape) - 2
        if op_.attr("global_pooling", False):
            oshape = list(xv.shape[:2]) + [1] * nd
        else:
            k = op_.attr("ksize")
            s = op_.attr("strides", [1] * nd)
            p = op_.attr("paddings", [0] * nd)
            oshape = list(xv.shape[:2]) + [
                None if d is None else (d - k[i] + 2 * p[i]) // s[i] + 1
                for i, d in enumerate(xv.shape[2:])]
        set_out(op_, block, "Out", oshape, xv.dtype)
        set_out(op_, block, "Mask", oshape, "int32")
    return infer


@op("max_pool2d_with_index", infer_shape=_pool_index_infer())
def _max_pool2d_with_index(ctx, op_, ins):
    """Max pool that also emits the argmax position as a flat h*W+w index
    into the input plane (reference pool_with_index_op.cc). The forward is a
    gather at the argmax, so the generic vjp scatters the cotangent to the
    max element — identical math to the reference's mask-driven backward."""
    x = jnp.asarray(ins["X"][0])
    if op_.attr("global_pooling", False):
        k, s, p = list(x.shape[2:]), list(x.shape[2:]), [0, 0]
    else:
        k = _pair(op_.attr("ksize"))
        s = _pair(op_.attr("strides", [1, 1]))
        p = _pair(op_.attr("paddings", [0, 0]))
    wins, idx = _windows2d(x, k, s, p, -jnp.inf)
    am = jnp.argmax(wins, axis=-1)
    out = jnp.take_along_axis(wins, am[..., None], axis=-1)[..., 0]
    mask = jnp.take_along_axis(
        jnp.broadcast_to(idx, wins.shape), am[..., None], axis=-1)[..., 0]
    return {"Out": [out], "Mask": [mask.astype(jnp.int32)]}


@op("max_pool3d_with_index", infer_shape=_pool_index_infer())
def _max_pool3d_with_index(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    if op_.attr("global_pooling", False):
        k, s, p = list(x.shape[2:]), list(x.shape[2:]), [0, 0, 0]
    else:
        k = _triple(op_.attr("ksize"))
        s = _triple(op_.attr("strides", [1, 1, 1]))
        p = _triple(op_.attr("paddings", [0, 0, 0]))
    n, c, d, h, w = x.shape
    od = (d - k[0] + 2 * p[0]) // s[0] + 1
    oh = (h - k[1] + 2 * p[1]) // s[1] + 1
    ow = (w - k[2] + 2 * p[2]) // s[2] + 1
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]),
                     (p[2], p[2])), constant_values=-jnp.inf)
    cols, idxs = [], []
    dd = jnp.arange(od) * s[0]
    hh = jnp.arange(oh) * s[1]
    ww = jnp.arange(ow) * s[2]
    for kd, ki, kj in itertools.product(range(k[0]), range(k[1]), range(k[2])):
        cols.append(jax.lax.slice(
            xp, (0, 0, kd, ki, kj),
            (n, c, kd + (od - 1) * s[0] + 1, ki + (oh - 1) * s[1] + 1,
             kj + (ow - 1) * s[2] + 1),
            (1, 1, s[0], s[1], s[2])))
        di = dd[:, None, None] + kd - p[0]
        hi = hh[None, :, None] + ki - p[1]
        wi = ww[None, None, :] + kj - p[2]
        valid = (di >= 0) & (di < d) & (hi >= 0) & (hi < h) & \
            (wi >= 0) & (wi < w)
        idxs.append(jnp.where(valid, (di * h + hi) * w + wi, -1))
    wins = jnp.stack(cols, axis=-1)
    idx = jnp.stack(idxs, axis=-1)
    am = jnp.argmax(wins, axis=-1)
    out = jnp.take_along_axis(wins, am[..., None], axis=-1)[..., 0]
    mask = jnp.take_along_axis(
        jnp.broadcast_to(idx, wins.shape), am[..., None], axis=-1)[..., 0]
    return {"Out": [out], "Mask": [mask.astype(jnp.int32)]}


# --- unpool -----------------------------------------------------------------

def _unpool_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is None or xv.shape is None:
        return
    us = op_.attr("unpooled_size", None)
    if us:
        set_out(op_, block, "Out", list(xv.shape[:2]) + list(us), xv.dtype)


@op("unpool", infer_shape=_unpool_infer, non_diff_inputs=("Indices",))
def _unpool(ctx, op_, ins):
    """Max-unpool: scatter pooled values back to the argmax positions stored
    in Indices (reference unpool_op.cc; indices as produced by
    max_pool2d_with_index)."""
    x = jnp.asarray(ins["X"][0])
    idx = jnp.asarray(ins["Indices"][0]).astype(jnp.int32)
    oh, ow = op_.attr("unpooled_size")
    n, c = x.shape[:2]
    xf = x.reshape(n * c, -1)
    idf = idx.reshape(n * c, -1)
    out = jnp.zeros((n * c, oh * ow), dtype=x.dtype)
    out = out.at[jnp.arange(n * c)[:, None], idf].set(xf)
    return {"Out": [out.reshape(n, c, oh, ow)]}


# --- spatial pyramid pooling ------------------------------------------------

def _spp_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is None or xv.shape is None:
        return
    ph = op_.attr("pyramid_height")
    bins = sum(4 ** i for i in range(ph))
    n, c = xv.shape[:2]
    set_out(op_, block, "Out",
            [n, None if c is None else c * bins], xv.dtype)


@op("spp", infer_shape=_spp_infer)
def _spp(ctx, op_, ins):
    """Spatial pyramid pooling (reference spp_op.cc): pool at 1x1, 2x2, 4x4…
    grids and concatenate the flattened per-level outputs."""
    x = jnp.asarray(ins["X"][0])
    ptype = op_.attr("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for level in range(op_.attr("pyramid_height")):
        b = 2 ** level
        kh, kw = math.ceil(h / b), math.ceil(w / b)
        ph = (kh * b - h + 1) // 2
        pw = (kw * b - w + 1) // 2
        window = (1, 1, kh, kw)
        strides = (1, 1, kh, kw)
        pads = ((0, 0), (0, 0), (ph, kh * b - h - ph), (pw, kw * b - w - pw))
        if ptype == "max":
            o = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                      strides, pads)
        else:
            o = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                      pads) / (kh * kw)
        outs.append(o.reshape(n, -1))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


# --- ROI pooling ------------------------------------------------------------

def _roi_pool_infer(op_, block):
    xv = in_var(op_, block, "X")
    rv = in_var(op_, block, "ROIs")
    if xv is None or xv.shape is None or rv is None or rv.shape is None:
        return
    ph, pw = op_.attr("pooled_height"), op_.attr("pooled_width")
    set_out(op_, block, "Out", [rv.shape[0], xv.shape[1], ph, pw], xv.dtype)
    set_out(op_, block, "Argmax", [rv.shape[0], xv.shape[1], ph, pw], "int32")


@op("roi_pool", infer_shape=_roi_pool_infer,
    non_diff_inputs=("ROIs", "RoiBatchId"))
def _roi_pool(ctx, op_, ins):
    """ROI max pooling (reference roi_pool_op.cc). The reference quantizes
    each ROI into pooled_h x pooled_w bins and max-pools each bin with a
    data-dependent loop; here each bin is a broadcast membership mask over
    the (static) feature plane — masked max — which XLA vectorizes, and the
    vjp routes the cotangent to the argmax exactly like the reference's
    saved-argmax backward. ROIs are [x1, y1, x2, y2] rows; the owning batch
    index comes from the optional RoiBatchId input (LoD in the reference)."""
    x = jnp.asarray(ins["X"][0])                 # (N,C,H,W)
    rois = jnp.asarray(ins["ROIs"][0])           # (R,4)
    scale = op_.attr("spatial_scale", 1.0)
    ph, pw = op_.attr("pooled_height"), op_.attr("pooled_width")
    n, c, h, w = x.shape
    r = rois.shape[0]
    if ins.get("RoiBatchId") and ins["RoiBatchId"][0] is not None:
        bid = jnp.asarray(ins["RoiBatchId"][0]).reshape(-1).astype(jnp.int32)
    else:
        bid = jnp.zeros((r,), dtype=jnp.int32)

    # integer bin boundaries, matching the reference's round-then-clip
    x1 = jnp.round(rois[:, 0] * scale).astype(jnp.int32)
    y1 = jnp.round(rois[:, 1] * scale).astype(jnp.int32)
    x2 = jnp.round(rois[:, 2] * scale).astype(jnp.int32)
    y2 = jnp.round(rois[:, 3] * scale).astype(jnp.int32)
    rh = jnp.maximum(y2 - y1 + 1, 1)
    rw = jnp.maximum(x2 - x1 + 1, 1)

    pi = jnp.arange(ph)
    pj = jnp.arange(pw)
    # (R, ph): bin start/end rows, floor/ceil like the reference
    hstart = y1[:, None] + (pi[None, :] * rh[:, None]) // ph
    hend = y1[:, None] + ((pi[None, :] + 1) * rh[:, None] + ph - 1) // ph
    wstart = x1[:, None] + (pj[None, :] * rw[:, None]) // pw
    wend = x1[:, None] + ((pj[None, :] + 1) * rw[:, None] + pw - 1) // pw
    hstart = jnp.clip(hstart, 0, h)
    hend = jnp.clip(hend, 0, h)
    wstart = jnp.clip(wstart, 0, w)
    wend = jnp.clip(wend, 0, w)

    rows = jnp.arange(h)
    cols = jnp.arange(w)
    # (R, ph, H) / (R, pw, W) membership
    rmask = (rows[None, None, :] >= hstart[:, :, None]) & \
            (rows[None, None, :] < hend[:, :, None])
    cmask = (cols[None, None, :] >= wstart[:, :, None]) & \
            (cols[None, None, :] < wend[:, :, None])
    # (R, ph, pw, H, W)
    mask = rmask[:, :, None, :, None] & cmask[:, None, :, None, :]
    feat = x[bid]                                # (R,C,H,W)
    masked = jnp.where(mask[:, None], feat[:, :, None, None],
                       jnp.array(-jnp.inf, dtype=x.dtype))
    flat = masked.reshape(r, c, ph, pw, h * w)
    am = jnp.argmax(flat, axis=-1)
    out = jnp.take_along_axis(flat, am[..., None], axis=-1)[..., 0]
    empty = ~jnp.any(mask, axis=(-2, -1))        # (R,ph,pw)
    out = jnp.where(empty[:, None], jnp.zeros_like(out), out)
    return {"Out": [out], "Argmax": [am.astype(jnp.int32)]}


# --- crop -------------------------------------------------------------------

def _crop_infer(op_, block):
    xv = in_var(op_, block, "X")
    shp = op_.attr("shape", None)
    yv = in_var(op_, block, "Y")
    if shp:
        set_out(op_, block, "Out", list(shp),
                xv.dtype if xv is not None else None)
    elif yv is not None and yv.shape is not None and xv is not None:
        set_out(op_, block, "Out", yv.shape, xv.dtype)


@op("crop", infer_shape=_crop_infer, non_diff_inputs=("Y", "Offsets"))
def _crop(ctx, op_, ins):
    """Crop X to `shape` (attr, or Y's shape) at `offsets` (attr or input)
    (reference crop_op.cc)."""
    x = jnp.asarray(ins["X"][0])
    if ins.get("Y") and ins["Y"][0] is not None:
        shape = list(jnp.asarray(ins["Y"][0]).shape)
    else:
        shape = list(op_.attr("shape"))
    if ins.get("Offsets") and ins["Offsets"][0] is not None:
        off = jnp.asarray(ins["Offsets"][0]).astype(jnp.int32).reshape(-1)
        out = jax.lax.dynamic_slice(x, [off[i] for i in range(x.ndim)], shape)
    else:
        off = op_.attr("offsets", [0] * x.ndim)
        out = jax.lax.slice(x, off, [o + s for o, s in zip(off, shape)])
    return {"Out": [out]}


# --- conv3d_transpose -------------------------------------------------------

def _convt3d_infer(op_, block):
    xv = in_var(op_, block, "Input")
    wv = in_var(op_, block, "Filter")
    if xv is None or xv.shape is None or wv is None or wv.shape is None:
        return
    s = _triple(op_.attr("strides", [1, 1, 1]))
    p = _triple(op_.attr("paddings", [0, 0, 0]))
    d = _triple(op_.attr("dilations", [1, 1, 1]))
    n = xv.shape[0]
    cout = wv.shape[1]
    dims = []
    for i, sz in enumerate(xv.shape[2:]):
        if sz is None or wv.shape[2 + i] is None:
            dims.append(None)
        else:
            k = d[i] * (wv.shape[2 + i] - 1) + 1
            dims.append(s[i] * (sz - 1) + k - 2 * p[i])
    set_out(op_, block, "Output", [n, cout] + dims, xv.dtype)


@op("conv3d_transpose", infer_shape=_convt3d_infer)
def _conv3d_transpose(ctx, op_, ins):
    """Transposed 3D conv as gradient-of-conv: dilate input by stride, pad by
    k-1-p, convolve with the flipped filter (reference conv_transpose_op.cc
    conv3d_transpose; filter layout IODHW)."""
    x = jnp.asarray(ins["Input"][0])
    w = jnp.asarray(ins["Filter"][0])   # (Cin, Cout, kd, kh, kw)
    s = _triple(op_.attr("strides", [1, 1, 1]))
    p = _triple(op_.attr("paddings", [0, 0, 0]))
    d = _triple(op_.attr("dilations", [1, 1, 1]))
    ks = [d[i] * (w.shape[2 + i] - 1) + 1 for i in range(3)]
    (x, w), restore = mxu_cast(ctx, x, w)
    out = jax.lax.conv_general_dilated(
        x, jnp.flip(w, (2, 3, 4)).swapaxes(0, 1),
        window_strides=(1, 1, 1),
        padding=[(ks[i] - 1 - p[i], ks[i] - 1 - p[i]) for i in range(3)],
        lhs_dilation=s, rhs_dilation=d,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    if restore is not None:
        out = out.astype(restore)
    return {"Output": [out]}


# --- prelu ------------------------------------------------------------------

@op("prelu", infer_shape=same_as_input())
def _prelu(ctx, op_, ins):
    """Parametric ReLU (reference prelu_op.cc): modes all (one alpha),
    channel (per-C), element (per-element). Layout-aware: when X carries
    an NHWC/NDHWC tag the alpha broadcast targets the minor channel axis
    instead of forcing a canonicalization barrier mid-ResNet-block (alpha
    itself is stored in canonical [.., C, *spatial] order)."""
    from . import layout as layout_mod

    x = jnp.asarray(ins["X"][0])
    alpha = jnp.asarray(ins["Alpha"][0])
    mode = op_.attr("mode", "all")
    tag = ctx.layout_of(op_.desc.inputs["X"][0])
    tagged = (tag in (layout_mod.NHWC, layout_mod.NDHWC)
              and x.ndim == layout_mod.tag_rank(tag))
    if mode == "all":
        a = alpha.reshape(())
    elif mode == "channel":
        if tagged:
            a = alpha.reshape((1,) * (x.ndim - 1) + (-1,))
        else:
            a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:
        # element mode: alpha is [1, *canonical_feature_dims] (C first),
        # broadcast over batch
        if tagged:
            a = jnp.moveaxis(
                alpha.reshape((1, x.shape[-1]) + tuple(x.shape[1:-1])),
                1, -1)
        else:
            a = alpha.reshape((1,) + tuple(x.shape[1:]))
    if tagged and ctx.layout_opt:
        ctx.set_layout(op_.desc.outputs["Out"][0], tag)
    return {"Out": [jnp.where(x > 0, x, a * x)]}


# --- conv_shift -------------------------------------------------------------

def _conv_shift_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None:
        set_out(op_, block, "Out", xv.shape, xv.dtype)


@op("conv_shift", infer_shape=_conv_shift_infer)
def _conv_shift(ctx, op_, ins):
    """Circular convolution out[i] = sum_j x[(i + j - N/2) mod M] * y[j]
    (reference conv_shift_op.cc; N odd, N <= M). Lowered as N static rolls —
    N is small (attention shift kernels), so this stays fused elementwise
    work instead of a gather."""
    x = jnp.asarray(ins["X"][0])   # (B, M)
    y = jnp.asarray(ins["Y"][0])   # (B, N)
    n = y.shape[1]
    half = n // 2
    out = jnp.zeros_like(x)
    for j in range(n):
        out = out + jnp.roll(x, half - j, axis=1) * y[:, j:j + 1]
    return {"Out": [out]}


def _bilinear_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None and xv.shape is not None:
        set_out(op_, block, "Out",
                [xv.shape[0], xv.shape[1], op_.attr("out_h"),
                 op_.attr("out_w")], xv.dtype)


@op("bilinear_interp", infer_shape=_bilinear_infer)
def _bilinear_interp(ctx, op_, ins):
    """Bilinear upsampling NCHW (reference gserver BilinearInterpLayer.cpp /
    hl_cnn.h bilinear ops: ratio = (in-1)/(out-1), i.e. corners aligned).
    Pure gather + lerp so the vjp (downsampling grad) is a scatter XLA
    fuses with surrounding work."""
    x = jnp.asarray(ins["X"][0])                       # [B, C, H, W]
    out_h = int(op_.attr("out_h"))
    out_w = int(op_.attr("out_w"))
    b, ch, h, w = x.shape

    def grid(in_size, out_size):
        # grid math in f32 regardless of x's dtype: a bf16 arange already
        # misindexes past 256, duplicating/skipping source rows
        if out_size == 1 or in_size == 1:
            return (jnp.zeros((out_size,), jnp.float32),
                    jnp.zeros((out_size,), jnp.int32),
                    jnp.zeros((out_size,), jnp.int32))
        ratio = (in_size - 1.0) / (out_size - 1.0)
        pos = jnp.arange(out_size, dtype=jnp.float32) * ratio
        lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, in_size - 1)
        hi = jnp.minimum(lo + 1, in_size - 1)
        return pos - lo.astype(jnp.float32), lo, hi

    fh, h0, h1 = grid(h, out_h)
    fw, w0, w1 = grid(w, out_w)
    xh0 = x[:, :, h0]
    xh1 = x[:, :, h1]
    tl = xh0[:, :, :, w0]
    tr = xh0[:, :, :, w1]
    bl = xh1[:, :, :, w0]
    br = xh1[:, :, :, w1]
    fh = fh[None, None, :, None].astype(x.dtype)
    fw = fw[None, None, None, :].astype(x.dtype)
    top = tl * (1 - fw) + tr * fw
    bot = bl * (1 - fw) + br * fw
    return {"Out": [top * (1 - fh) + bot * fh]}
