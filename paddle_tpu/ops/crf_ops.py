"""Linear-chain CRF ops on padded sequences.

TPU-native equivalents of the reference's CRF kernels
(reference: paddle/fluid/operators/linear_chain_crf_op.cc — forward
algorithm + analytic gradients; crf_decoding_op.cc — Viterbi). Here the
forward recursion is a log-domain lax.scan and the gradient falls out of
jax.vjp through it (no hand-written backward); Viterbi is a scan with
backtracking via a second reverse scan.

Transition parameter layout matches the reference: [D+2, D] where row 0 is
the start transition, row 1 the stop transition, rows 2.. the pairwise
transition matrix.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .common import in_var, set_out
from .registry import NO_GRAD, op


def _crf_infer(op_, block):
    ev = in_var(op_, block, "Emission")
    if ev is None or ev.shape is None:
        return
    set_out(op_, block, "LogLikelihood", [ev.shape[0], 1], ev.dtype)


def _lengths_for(ctx, op_, slot):
    names = op_.desc.inputs.get(slot, [])
    return ctx.seq_len(names[0]) if names else None


@op("linear_chain_crf", infer_shape=_crf_infer, non_diff_inputs=("Label",))
def _linear_chain_crf(ctx, op_, ins):
    """Negative log-likelihood of label paths under a linear-chain CRF.

    Emission [B,T,D] padded; Transition [D+2,D]; Label [B,T,1] int.
    Returns LogLikelihood [B,1] = -log p(label path | emission) per
    sequence (the training cost, as in the reference book ch07)."""
    emission = jnp.asarray(ins["Emission"][0])
    transition = jnp.asarray(ins["Transition"][0])
    label = jnp.asarray(ins["Label"][0]).astype(jnp.int32)
    if label.ndim == 3:
        label = label[..., 0]
    bsz, t, d = emission.shape
    start, stop, trans = transition[0], transition[1], transition[2:]
    lengths = _lengths_for(ctx, op_, "Emission")
    if lengths is None:
        lengths = jnp.full((bsz,), t, jnp.int32)
    lengths = jnp.asarray(lengths)
    steps = jnp.arange(t)
    mask = (steps[None, :] < lengths[:, None]).astype(emission.dtype)

    # --- partition function: log-domain forward recursion -------------------
    alpha0 = start[None, :] + emission[:, 0]                       # [B, D]

    def fwd(alpha, inp):
        e_t, m_t = inp                                             # [B,D],[B]
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None], axis=1) + e_t
        alpha = m_t[:, None] * nxt + (1 - m_t)[:, None] * alpha
        return alpha, None

    es = jnp.swapaxes(emission, 0, 1)                              # [T,B,D]
    ms = jnp.swapaxes(mask, 0, 1)                                  # [T,B]
    alpha, _ = lax.scan(fwd, alpha0, (es[1:], ms[1:]))
    log_z = jax.nn.logsumexp(alpha + stop[None, :], axis=1)        # [B]

    # --- gold path score ----------------------------------------------------
    emit_scores = jnp.take_along_axis(emission, label[..., None],
                                      axis=2)[..., 0]              # [B,T]
    emit_sum = (emit_scores * mask).sum(axis=1)
    pair = trans[label[:, :-1], label[:, 1:]]                      # [B,T-1]
    pair_sum = (pair * mask[:, 1:]).sum(axis=1)
    last_idx = jnp.maximum(lengths - 1, 0)
    last_label = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    path = start[label[:, 0]] + emit_sum + pair_sum + stop[last_label]

    nll = (log_z - path)[:, None]
    for name in op_.desc.outputs.get("LogLikelihood", []):
        ctx.set_seq_len(name, None)
    return {"LogLikelihood": [nll]}


def _decode_infer(op_, block):
    ev = in_var(op_, block, "Emission")
    if ev is None or ev.shape is None:
        return
    set_out(op_, block, "ViterbiPath", list(ev.shape[:2]) + [1], "int64")


@op("crf_decoding", infer_shape=_decode_infer, grad=NO_GRAD)
def _crf_decoding(ctx, op_, ins):
    """Viterbi decode (reference crf_decoding_op.cc). Without Label: the
    best path [B,T,1]. With Label: per-token correctness indicator
    (1 where the Viterbi tag equals the gold tag), as the reference emits
    for evaluation."""
    emission = jnp.asarray(ins["Emission"][0])
    transition = jnp.asarray(ins["Transition"][0])
    bsz, t, d = emission.shape
    start, stop, trans = transition[0], transition[1], transition[2:]
    lengths = _lengths_for(ctx, op_, "Emission")
    if lengths is None:
        lengths = jnp.full((bsz,), t, jnp.int32)
    lengths = jnp.asarray(lengths)
    mask = (jnp.arange(t)[None, :] < lengths[:, None])

    delta0 = start[None, :] + emission[:, 0]

    def fwd(delta, inp):
        e_t, m_t = inp
        cand = delta[:, :, None] + trans[None]                      # [B,D,D]
        best = cand.max(axis=1) + e_t
        back = cand.argmax(axis=1).astype(jnp.int32)                # [B,D]
        delta = jnp.where(m_t[:, None], best, delta)
        return delta, back

    es = jnp.swapaxes(emission, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1)
    delta, backs = lax.scan(fwd, delta0, (es[1:], ms[1:]))  # backs [T-1,B,D]
    last = jnp.argmax(delta + stop[None, :], axis=1).astype(jnp.int32)  # [B]

    # backtrack from each sequence's last valid step; padded steps keep the
    # carried tag so the valid region reads out correctly
    def back_step(tag, inp):
        back_t, m_next = inp         # back at step t+1, mask of step t+1
        prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
        tag = jnp.where(m_next, prev, tag)
        return tag, tag

    ms_next = ms[1:]                 # mask for steps 1..T-1
    _, tags_rev = lax.scan(back_step, last, (backs[::-1], ms_next[::-1]))
    path = jnp.concatenate([tags_rev[::-1], last[None, :]], axis=0)  # [T,B]
    path = jnp.swapaxes(path, 0, 1)
    path = jnp.where(mask, path, 0).astype(jnp.int64)[..., None]     # [B,T,1]

    out_names = op_.desc.outputs.get("ViterbiPath", [])
    if ins.get("Label") and ins["Label"][0] is not None:
        label = jnp.asarray(ins["Label"][0]).astype(jnp.int64)
        if label.ndim == 2:
            label = label[..., None]
        correct = (path == label) & mask[..., None]
        result = correct.astype(jnp.int64)
    else:
        result = path
    for name in out_names:
        ctx.set_seq_len(name, lengths)
    return {"ViterbiPath": [result]}
