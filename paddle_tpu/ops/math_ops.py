"""Math ops: GEMM, elementwise+broadcast, activations, reductions.

TPU-native lowerings of the reference ops (mul_op.cc, matmul_op.cc,
elementwise_*_op.cc + elementwise_op_function.h, activation_op.cc — 20+
activations, reduce_op.cc, sum_op.cc, mean_op.cc, cumsum_op.cc, cos_sim_op.cc,
norm ops). Matmuls map straight onto the MXU via jnp.matmul/einsum; elementwise
ops fuse into neighbours under XLA, so there is no hand-written fusion layer
like the reference's math functors (operators/math/math_function.*).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .registry import NO_GRAD, op, register
from .common import (SelectedRowsVal, maybe_dense, broadcast_y_to_x, in_var, matmul_shape, mxu_cast, out_var,
                     same_as_input, set_out)


# --- GEMM family ------------------------------------------------------------

def _flat2(x, num_col_dims):
    """Flatten to 2-D the way mul_op does (reference mul_op.cc): leading
    num_col_dims dims become rows, the rest columns."""
    shape = x.shape
    rows = int(np.prod(shape[:num_col_dims])) if num_col_dims else 1
    cols = int(np.prod(shape[num_col_dims:])) if num_col_dims < len(shape) else 1
    return x.reshape(rows, cols)


def _mul_infer(op_, block):
    xv, yv = in_var(op_, block, "X"), in_var(op_, block, "Y")
    if xv is None or yv is None or xv.shape is None or yv.shape is None:
        return
    xn = op_.attr("x_num_col_dims", 1)
    yn = op_.attr("y_num_col_dims", 1)
    set_out(op_, block, "Out",
            list(xv.shape[:xn]) + list(yv.shape[yn:]), xv.dtype)


@op("mul", infer_shape=_mul_infer)
def _mul(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    y = jnp.asarray(ins["Y"][0])
    xn = op_.attr("x_num_col_dims", 1)
    yn = op_.attr("y_num_col_dims", 1)
    (xf, yf), restore = mxu_cast(ctx, _flat2(x, xn), _flat2(y, yn))
    qmode = getattr(ctx, "quant_mode", None)
    if qmode:
        from .. import quant
        reason = quant.ineligible_matmul(xf, yf, qmode)
        if reason is None:
            quant.count_hit(op_.type)
            pre = quant.prequantized(ctx, op_.desc.inputs["Y"][0])
            out2d = quant.qmatmul(xf, yf, qmode, pre=pre)
        else:
            quant.count_fallback(op_.type, reason)
            out2d = jnp.matmul(xf, yf)
    else:
        out2d = jnp.matmul(xf, yf)
    if restore is not None:
        out2d = out2d.astype(restore)
    out_shape = x.shape[:xn] + y.shape[yn:]
    return {"Out": [out2d.reshape(out_shape)]}


def _matmul_infer(op_, block):
    xv, yv = in_var(op_, block, "X"), in_var(op_, block, "Y")
    if xv is None or yv is None:
        return
    set_out(op_, block, "Out",
            matmul_shape(xv.shape and list(xv.shape), yv.shape and list(yv.shape),
                         op_.attr("transpose_X", False),
                         op_.attr("transpose_Y", False)),
            xv.dtype)


@op("matmul", infer_shape=_matmul_infer)
def _matmul(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    y = jnp.asarray(ins["Y"][0])
    if op_.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if op_.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    (x, y), restore = mxu_cast(ctx, x, y)
    qmode = getattr(ctx, "quant_mode", None)
    if qmode:
        from .. import quant
        reason = quant.ineligible_matmul(x, y, qmode)
        if reason is None:
            quant.count_hit(op_.type)
            # the admission cache stores Y in [K, N] orientation, so a
            # transposed Y quantizes dynamically (prequantize skips it)
            pre = None if op_.attr("transpose_Y", False) else \
                quant.prequantized(ctx, op_.desc.inputs["Y"][0])
            out = quant.qmatmul(x, y, qmode, pre=pre)
        else:
            quant.count_fallback(op_.type, reason)
            out = jnp.matmul(x, y)
    else:
        out = jnp.matmul(x, y)
    if restore is not None:
        out = out.astype(restore)
    alpha = op_.attr("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


def _bilinear_infer(op_, block):
    xv = in_var(op_, block, "X")
    wv = in_var(op_, block, "Weight")
    if xv is not None and xv.shape is not None and wv is not None \
            and wv.shape is not None:
        set_out(op_, block, "Out", [xv.shape[0], wv.shape[0]], xv.dtype)


@op("bilinear_tensor_product", infer_shape=_bilinear_infer)
def _bilinear_tensor_product(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])      # (B, M)
    y = jnp.asarray(ins["Y"][0])      # (B, N)
    w = jnp.asarray(ins["Weight"][0])  # (O, M, N)
    (x, y, w), restore = mxu_cast(ctx, x, y, w)
    out = jnp.einsum("bm,omn,bn->bo", x, w, y)
    if restore is not None:
        out = out.astype(restore)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        out = out + jnp.asarray(ins["Bias"][0]).astype(out.dtype)
    return {"Out": [out]}


# --- elementwise with axis broadcast ---------------------------------------

_elementwise_fns = {
    "elementwise_add": jnp.add,
    "elementwise_sub": jnp.subtract,
    "elementwise_mul": jnp.multiply,
    "elementwise_div": jnp.divide,
    "elementwise_max": jnp.maximum,
    "elementwise_min": jnp.minimum,
    "elementwise_pow": jnp.power,
}


def _ew_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None:
        set_out(op_, block, "Out", xv.shape, xv.dtype)


def _make_ew(fn):
    def lower(ctx, op_, ins):
        x = jnp.asarray(ins["X"][0])
        axis = op_.attr("axis", -1)
        # channel-bias form (axis==1, 1-D Y) under the internal NHWC
        # convention (ops/layout.py): the channel axis is minor, so the
        # broadcast target moves to the last dim
        if axis == 1 and getattr(ins["Y"][0], "ndim", 0) == 1 and \
                ctx.layout_of(op_.desc.inputs["X"][0]) is not None:
            axis = x.ndim - 1
        y = broadcast_y_to_x(x, ins["Y"][0], axis)
        # AMP O2: an f32 operand (e.g. a master-weight bias) must not
        # promote a bf16 activation back to f32 — that would silently
        # re-materialize f32 tensors at every fc/conv bias add and forfeit
        # the halved HBM traffic. The cast is in-trace, so the bias grad
        # flows back to the f32 master copy through the astype vjp.
        if getattr(ctx, "amp_level", "O1") in ("O2", "O3") and \
                x.dtype == jnp.bfloat16 and y.dtype == jnp.float32:
            y = y.astype(x.dtype)
        return {"Out": [fn(x, y)]}
    return lower


for _name, _fn in _elementwise_fns.items():
    register(_name, lower=_make_ew(_fn), infer_shape=_ew_infer)


# --- activations (reference activation_op.cc) -------------------------------

def _softshrink(x, lam=0.5):
    return jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam, 0.0))


_activations = {
    "sigmoid": lambda x, a: jax.nn.sigmoid(x),
    "logsigmoid": lambda x, a: jax.nn.log_sigmoid(x),
    "exp": lambda x, a: jnp.exp(x),
    "relu": lambda x, a: jax.nn.relu(x),
    "tanh": lambda x, a: jnp.tanh(x),
    "tanh_shrink": lambda x, a: x - jnp.tanh(x),
    "softshrink": lambda x, a: _softshrink(x, a.attr("lambda", 0.5)),
    "hard_shrink": lambda x, a: jnp.where(
        jnp.abs(x) > a.attr("threshold", 0.5), x, 0.0),
    "sqrt": lambda x, a: jnp.sqrt(x),
    "abs": lambda x, a: jnp.abs(x),
    "ceil": lambda x, a: jnp.ceil(x),
    "floor": lambda x, a: jnp.floor(x),
    "round": lambda x, a: jnp.round(x),
    "reciprocal": lambda x, a: 1.0 / x,
    "log": lambda x, a: jnp.log(x),
    "square": lambda x, a: jnp.square(x),
    "softplus": lambda x, a: jax.nn.softplus(x),
    "softsign": lambda x, a: x / (1.0 + jnp.abs(x)),
    "brelu": lambda x, a: jnp.clip(x, a.attr("t_min", 0.0), a.attr("t_max", 24.0)),
    "leaky_relu": lambda x, a: jnp.where(x >= 0, x, a.attr("alpha", 0.02) * x),
    "soft_relu": lambda x, a: jnp.log1p(jnp.exp(
        jnp.clip(x, -a.attr("threshold", 40.0), a.attr("threshold", 40.0)))),
    "elu": lambda x, a: jnp.where(x >= 0, x, a.attr("alpha", 1.0)
                                  * (jnp.exp(x) - 1.0)),
    "relu6": lambda x, a: jnp.clip(x, 0.0, a.attr("threshold", 6.0)),
    "pow": lambda x, a: jnp.power(x, a.attr("factor", 1.0)),
    "stanh": lambda x, a: a.attr("scale_b", 1.7159) * jnp.tanh(
        a.attr("scale_a", 2.0 / 3.0) * x),
    "hard_sigmoid": lambda x, a: jnp.clip(
        a.attr("slope", 0.2) * x + a.attr("offset", 0.5), 0.0, 1.0),
    "swish": lambda x, a: x * jax.nn.sigmoid(a.attr("beta", 1.0) * x),
    "thresholded_relu": lambda x, a: jnp.where(
        x > a.attr("threshold", 1.0), x, 0.0),
    "gelu": lambda x, a: jax.nn.gelu(x, approximate=False),
    "silu": lambda x, a: jax.nn.silu(x),
}


def _make_act(fn):
    def lower(ctx, op_, ins):
        x = jnp.asarray(ins["X"][0])
        return {"Out": [fn(x, op_)]}
    return lower


for _name, _fn in _activations.items():
    register(_name, lower=_make_act(_fn), infer_shape=same_as_input())


# --- reductions -------------------------------------------------------------

def _reduce_dims(op_, ndim):
    if op_.attr("reduce_all", False):
        return tuple(range(ndim))
    dim = op_.attr("dim", [0])
    if isinstance(dim, int):
        dim = [dim]
    return tuple(d % ndim for d in dim)


def _reduce_infer(op_, block):
    iv = in_var(op_, block, "X")
    if iv is None or iv.shape is None:
        return
    nd = len(iv.shape)
    dims = _reduce_dims(op_, nd)
    keep = op_.attr("keep_dim", False)
    if op_.attr("reduce_all", False):
        shape = [1] * nd if keep else [1]
    else:
        shape = [1 if i in dims else d for i, d in enumerate(iv.shape)] if keep \
            else [d for i, d in enumerate(iv.shape) if i not in dims]
        shape = shape or [1]
    set_out(op_, block, "Out", shape, iv.dtype)


_reduce_fns = {
    "reduce_sum": jnp.sum, "reduce_mean": jnp.mean, "reduce_max": jnp.max,
    "reduce_min": jnp.min, "reduce_prod": jnp.prod,
}


def _make_reduce(fn):
    def lower(ctx, op_, ins):
        x = jnp.asarray(ins["X"][0])
        dims = _reduce_dims(op_, x.ndim)
        keep = op_.attr("keep_dim", False)
        out = fn(x, axis=dims, keepdims=keep)
        if out.ndim == 0:
            out = out.reshape(1)
        return {"Out": [out]}
    return lower


for _name, _fn in _reduce_fns.items():
    register(_name, lower=_make_reduce(_fn), infer_shape=_reduce_infer)


def _mean_infer(op_, block):
    iv = in_var(op_, block, "X")
    set_out(op_, block, "Out", [1], iv.dtype if iv else "float32")


@op("mean", infer_shape=_mean_infer)
def _mean(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    lengths = ctx.seq_len(op_.desc.inputs["X"][0])
    if lengths is not None and x.ndim >= 2:
        # padded sequence: mean over valid positions only — matches the
        # reference's mean over packed [sum_len, ...] rows
        t = x.shape[1]
        mask = (jnp.arange(t)[None, :] <
                jnp.asarray(lengths)[:, None]).astype(x.dtype)
        m = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
        feat = 1
        for d in x.shape[2:]:
            feat *= d
        denom = jnp.maximum(mask.sum() * feat, 1.0)
        return {"Out": [(jnp.sum(x * m) / denom).reshape(1)]}
    return {"Out": [jnp.mean(x).reshape(1)]}


def _sum_infer(op_, block):
    iv = in_var(op_, block, "X", 0)
    if iv is not None:
        set_out(op_, block, "Out", iv.shape, iv.dtype)


@op("sum", infer_shape=_sum_infer)
def _sum(ctx, op_, ins):
    """Element sum with SelectedRows support (reference sum_op.cc handles
    dense+sparse mixes): all-sparse inputs concatenate rows/values (rows may
    repeat, like the reference's unmerged SelectedRows), a mix densifies."""
    raw = [x for x in ins["X"] if x is not None]
    if raw and all(isinstance(x, SelectedRowsVal) for x in raw):
        if len(raw) == 1:
            return {"Out": [raw[0]]}
        rows = jnp.concatenate([x.rows for x in raw])
        vals = jnp.concatenate([x.values for x in raw])
        return {"Out": [SelectedRowsVal(rows, vals, raw[0].height)]}
    xs = [jnp.asarray(maybe_dense(x)) for x in raw]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@op("cumsum", infer_shape=same_as_input())
def _cumsum(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    axis = op_.attr("axis", -1)
    if op_.attr("reverse", False):
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if op_.attr("exclusive", False):
        # shift by one along axis: out[i] = sum of x[:i]
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        sl = [slice(None)] * x.ndim
        sl[axis] = slice(0, x.shape[axis])
        out = jnp.pad(out, pad)[tuple(sl)]
    if op_.attr("reverse", False):
        out = jnp.flip(out, axis)
    return {"Out": [out]}


# --- similarity / norms -----------------------------------------------------

def _cos_sim_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None and xv.shape is not None:
        set_out(op_, block, "Out", [xv.shape[0], 1], xv.dtype)
        set_out(op_, block, "XNorm", [xv.shape[0], 1], xv.dtype)
    yv = in_var(op_, block, "Y")
    if yv is not None and yv.shape is not None:
        set_out(op_, block, "YNorm", [yv.shape[0], 1], yv.dtype)


@op("cos_sim", infer_shape=_cos_sim_infer)
def _cos_sim(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    y = jnp.asarray(ins["Y"][0])
    xn = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=1, keepdims=True))
    out = jnp.sum(x * y, axis=1, keepdims=True) / (xn * yn)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}


@op("norm", infer_shape=same_as_input())
def _norm(ctx, op_, ins):
    # l2-normalize along axis (reference norm_op.cc used by l2_normalize)
    x = jnp.asarray(ins["X"][0])
    axis = op_.attr("axis", -1)
    eps = op_.attr("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": [x / n], "Norm": [n]}
