"""Op corpus: importing this package registers every op lowering."""

from . import registry
from . import basic_ops      # noqa: F401
from . import math_ops       # noqa: F401
from . import nn_ops         # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import extra_ops      # noqa: F401
from . import sequence_ops   # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import crf_ops        # noqa: F401
from . import beam_search_ops  # noqa: F401
from . import vision_ops     # noqa: F401
from . import ctc_ops        # noqa: F401
from . import eval_ops       # noqa: F401
from . import misc_ops       # noqa: F401
from . import detection_ops  # noqa: F401
from . import fusion         # noqa: F401  (registers the fused op types)

from .registry import register, op, get, try_get, registered_ops, NO_GRAD
