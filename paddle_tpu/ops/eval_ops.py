"""Evaluation metric ops: chunk_eval, precision_recall, positive_negative_pair.

TPU-native lowerings of the reference CPU-only metric kernels (reference:
chunk_eval_op.h — sequential Segment extraction; precision_recall_op.h —
per-sample TP/FP/TN/FN loop; positive_negative_pair_op.h — per-query pair
loops over an unordered_map). All three are re-expressed as dense
vectorized computations (boundary flags + row-wise cummax for chunking,
one-hot scatter sums for the confusion states, an O(N^2) masked pairwise
grid for ranking pairs) so they run inside the same jitted XLA computation
as the model instead of forcing a host round-trip per batch."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .common import in_var, set_out
from .registry import NO_GRAD, op

# per-scheme tag ids, -1 = tag absent (reference chunk_eval_op.h:108-139)
_SCHEMES = {
    "IOB": dict(num_tags=2, begin=0, inside=1, end=-1, single=-1),
    "IOE": dict(num_tags=2, begin=-1, inside=0, end=1, single=-1),
    "IOBES": dict(num_tags=4, begin=0, inside=1, end=2, single=3),
    "plain": dict(num_tags=1, begin=-1, inside=-1, end=-1, single=-1),
}


def _chunk_flags(labels, valid, num_chunk_types, sc):
    """Per-position chunk begin/end flags + chunk type for padded [B, T]
    label rows. Vectorized form of the reference's GetSegments state machine
    (chunk_eval_op.h:38-77): a position is inside a chunk iff its type is
    not 'other', so begins/ends reduce to adjacent-pair predicates."""
    nt = sc["num_tags"]
    other = num_chunk_types
    tag = labels % nt
    typ = labels // nt
    typ = jnp.where(valid, typ, other)   # padding acts like 'O'

    prev_tag = jnp.concatenate(
        [jnp.full_like(tag[:, :1], -1), tag[:, :-1]], axis=1)
    prev_typ = jnp.concatenate(
        [jnp.full_like(typ[:, :1], other), typ[:, :-1]], axis=1)
    next_tag = jnp.concatenate(
        [tag[:, 1:], jnp.full_like(tag[:, :1], -1)], axis=1)
    next_typ = jnp.concatenate(
        [typ[:, 1:], jnp.full_like(typ[:, :1], other)], axis=1)

    nonother = typ != other

    def same_type_begin(ptag, ctag):
        # ChunkBegin for prev_type == type, both non-other
        return ((ctag == sc["begin"]) & (sc["begin"] >= 0)) | \
               ((ctag == sc["single"]) & (sc["single"] >= 0)) | \
               (((ctag == sc["inside"]) | (ctag == sc["end"])) &
                ((ptag == sc["end"]) | (ptag == sc["single"])) &
                (sc["end"] >= 0))

    def same_type_end(ptag, ctag):
        # ChunkEnd for prev_type == type, both non-other
        return (((ptag == sc["begin"]) | (ptag == sc["inside"])) &
                (((ctag == sc["begin"]) & (sc["begin"] >= 0)) |
                 ((ctag == sc["single"]) & (sc["single"] >= 0)))) | \
               (((ptag == sc["end"]) | (ptag == sc["single"])) &
                (sc["end"] >= 0))

    begin = nonother & ((prev_typ == other) | (prev_typ != typ) |
                        same_type_begin(prev_tag, tag))
    end = nonother & ((next_typ == other) | (next_typ != typ) |
                      same_type_end(tag, next_tag))
    return begin, end, typ


def _chunk_start_idx(begin):
    """start index of the chunk covering each position: running max of the
    positions where a chunk begins."""
    t = begin.shape[1]
    pos = jnp.arange(t)[None, :]
    marked = jnp.where(begin, pos, -1)
    return jax.lax.associative_scan(jnp.maximum, marked, axis=1)


def _chunk_eval_infer(op_, block):
    for slot in ("Precision", "Recall", "F1-Score"):
        set_out(op_, block, slot, [1], "float32")
    for slot in ("NumInferChunks", "NumLabelChunks", "NumCorrectChunks"):
        set_out(op_, block, slot, [1], "int32")


@op("chunk_eval", infer_shape=_chunk_eval_infer, grad=NO_GRAD)
def _chunk_eval(ctx, op_, ins):
    """Chunking (NER-style) precision/recall/F1 (reference chunk_eval_op.h).
    Inference and Label are padded [B, T] int rows + @SEQLEN. A correct
    chunk is an exactly matching (begin, end, type) span in both sequences;
    excluded_chunk_types drop from the correct count only, as in the
    reference (EvalOneSeq)."""
    inf = jnp.asarray(ins["Inference"][0])
    lab = jnp.asarray(ins["Label"][0])
    if inf.ndim == 3:
        inf = inf[..., 0]
    if lab.ndim == 3:
        lab = lab[..., 0]
    b, t = inf.shape
    names = op_.desc.inputs.get("Label", [])
    lens = ctx.seq_len(names[0]) if names else None
    if lens is None:
        valid = jnp.ones((b, t), dtype=bool)
    else:
        valid = jnp.arange(t)[None, :] < jnp.asarray(lens)[:, None]

    nct = op_.attr("num_chunk_types")
    sc = _SCHEMES[op_.attr("chunk_scheme", "IOB")]
    excluded = op_.attr("excluded_chunk_types", []) or []

    ib, ie, ityp = _chunk_flags(inf.astype(jnp.int32), valid, nct, sc)
    lb, le, ltyp = _chunk_flags(lab.astype(jnp.int32), valid, nct, sc)
    istart = _chunk_start_idx(ib)
    lstart = _chunk_start_idx(lb)

    correct = ie & le & (istart == lstart) & (ityp == ltyp)
    for ex in excluded:
        correct = correct & (ityp != ex)

    n_inf = ib.sum().astype(jnp.int32)
    n_lab = lb.sum().astype(jnp.int32)
    n_cor = correct.sum().astype(jnp.int32)
    prec = jnp.where(n_inf > 0, n_cor / jnp.maximum(n_inf, 1), 0.0)
    rec = jnp.where(n_lab > 0, n_cor / jnp.maximum(n_lab, 1), 0.0)
    f1 = jnp.where(n_cor > 0,
                   2 * prec * rec / jnp.maximum(prec + rec, 1e-12), 0.0)
    for slot in op_.desc.outputs:
        for name in op_.desc.outputs[slot]:
            ctx.set_seq_len(name, None)
    return {"Precision": [prec.astype(jnp.float32)[None]],
            "Recall": [rec.astype(jnp.float32)[None]],
            "F1-Score": [f1.astype(jnp.float32)[None]],
            "NumInferChunks": [n_inf[None]],
            "NumLabelChunks": [n_lab[None]],
            "NumCorrectChunks": [n_cor[None]]}


def _pr_infer(op_, block):
    c = op_.attr("class_number")
    set_out(op_, block, "BatchMetrics", [6], "float32")
    set_out(op_, block, "AccumMetrics", [6], "float32")
    set_out(op_, block, "AccumStatesInfo", [c, 4], "float32")


def _pr_metrics(states, cls_num):
    """states [C, 4] = per-class TP/FP/TN/FN -> the 6 macro/micro metrics
    (reference precision_recall_op.h ComputeMetrics)."""
    tp, fp, fn = states[:, 0], states[:, 1], states[:, 3]

    def prec(tp_, fp_):
        return jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1e-12),
                         1.0)

    def rec(tp_, fn_):
        return jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1e-12),
                         1.0)

    def f1(p, r):
        return jnp.where(p + r > 0, 2 * p * r / jnp.maximum(p + r, 1e-12),
                         0.0)

    macro_p = prec(tp, fp).mean()
    macro_r = rec(tp, fn).mean()
    micro_p = prec(tp.sum(), fp.sum())
    micro_r = rec(tp.sum(), fn.sum())
    return jnp.stack([macro_p, macro_r, f1(macro_p, macro_r),
                      micro_p, micro_r, f1(micro_p, micro_r)])


@op("precision_recall", infer_shape=_pr_infer, grad=NO_GRAD)
def _precision_recall(ctx, op_, ins):
    """Multi-class precision/recall/F1 with accumulation (reference
    precision_recall_op.h). Indices/Labels [N, 1] int; optional Weights
    [N, 1]; optional StatesInfo [C, 4] carries TP/FP/TN/FN across batches."""
    idx = jnp.asarray(ins["Indices"][0]).reshape(-1).astype(jnp.int32)
    lab = jnp.asarray(ins["Labels"][0]).reshape(-1).astype(jnp.int32)
    cls_num = op_.attr("class_number")
    n = idx.shape[0]
    if ins.get("Weights") and ins["Weights"][0] is not None:
        w = jnp.asarray(ins["Weights"][0]).reshape(-1).astype(jnp.float32)
    else:
        w = jnp.ones((n,), jnp.float32)

    oh_idx = jax.nn.one_hot(idx, cls_num, dtype=jnp.float32)
    oh_lab = jax.nn.one_hot(lab, cls_num, dtype=jnp.float32)
    hit = (idx == lab).astype(jnp.float32)
    tp = (oh_idx * hit[:, None] * w[:, None]).sum(0)
    fp = (oh_idx * (1 - hit)[:, None] * w[:, None]).sum(0)
    fn = (oh_lab * (1 - hit)[:, None] * w[:, None]).sum(0)
    # TN: every sample adds w to all classes except its idx (and its label
    # when mispredicted) — reference lines 66-81
    tn = w.sum() - tp - fp - fn
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)

    accum_states = batch_states
    if ins.get("StatesInfo") and ins["StatesInfo"][0] is not None:
        accum_states = accum_states + \
            jnp.asarray(ins["StatesInfo"][0]).astype(jnp.float32)
    return {"BatchMetrics": [_pr_metrics(batch_states, cls_num)],
            "AccumMetrics": [_pr_metrics(accum_states, cls_num)],
            "AccumStatesInfo": [accum_states]}


def _pnp_infer(op_, block):
    for slot in ("PositivePair", "NegativePair", "NeutralPair"):
        set_out(op_, block, slot, [1], "float32")


@op("positive_negative_pair", infer_shape=_pnp_infer, grad=NO_GRAD)
def _positive_negative_pair(ctx, op_, ins):
    """Ranking pair statistics per query (reference
    positive_negative_pair_op.h): for each same-query pair with different
    labels, count the pair as positive if score order matches label order,
    negative otherwise, neutral on score ties; weight = mean pair weight."""
    score = jnp.asarray(ins["Score"][0])
    label = jnp.asarray(ins["Label"][0]).reshape(-1)
    query = jnp.asarray(ins["QueryID"][0]).reshape(-1)
    col = op_.attr("column", -1)
    s = score.reshape(score.shape[0], -1)[:, col]
    n = s.shape[0]
    if ins.get("Weight") and ins["Weight"][0] is not None:
        w = jnp.asarray(ins["Weight"][0]).reshape(-1).astype(jnp.float32)
    else:
        w = jnp.ones((n,), jnp.float32)

    iu = jnp.triu(jnp.ones((n, n), bool), k=1)
    same_q = query[:, None] == query[None, :]
    diff_l = label[:, None] != label[None, :]
    pair = iu & same_q & diff_l
    pw = (w[:, None] + w[None, :]) * 0.5
    ds = s[:, None] - s[None, :]
    dl = (label[:, None] - label[None, :]).astype(s.dtype)
    tie = ds == 0
    pos = (pair & (ds * dl > 0)).astype(jnp.float32) * pw
    neg = (pair & ~tie & (ds * dl <= 0)).astype(jnp.float32) * pw
    # reference counts a tie as neutral AND as negative (the ternary falls
    # through to neg when ds == 0) — preserved for parity
    negt = (pair & tie).astype(jnp.float32) * pw
    neu = negt
    p = pos.sum()
    ng = neg.sum() + negt.sum()
    nu = neu.sum()
    if ins.get("AccumulatePositivePair") and \
            ins["AccumulatePositivePair"][0] is not None:
        p = p + jnp.asarray(ins["AccumulatePositivePair"][0]).reshape(())
        ng = ng + jnp.asarray(ins["AccumulateNegativePair"][0]).reshape(())
        nu = nu + jnp.asarray(ins["AccumulateNeutralPair"][0]).reshape(())
    return {"PositivePair": [p[None]], "NegativePair": [ng[None]],
            "NeutralPair": [nu[None]]}
