"""SSD detection op family: prior_box, iou_similarity, box_coder,
bipartite_match, mine_hard_examples, target_assign, multiclass_nms,
detection_map.

TPU-native lowerings of the reference CPU-only detection kernels
(reference: prior_box_op.h, iou_similarity_op.h, box_coder_op.h,
bipartite_match_op.cc, mine_hard_examples_op.cc, target_assign_op.h,
multiclass_nms_op.cc, detection_map_op.h). The reference routes these to
CPU with data-dependent loops and dynamic output shapes; here everything is
fixed-shape: batches are padded [B, G, ...] with @SEQLEN counts, greedy
matching/NMS run as bounded `lax.fori_loop`s over sorted candidates, and
selection results are compacted by stable sort on keep masks. detection_map
stays a host callback (like the reference's CPU-only kernel) because mAP is
a once-per-batch metric with inherently sequential per-class accumulation.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .common import in_var, out_var, seq_lengths as _lengths, set_out
from .registry import NO_GRAD, op

_EPS = 1e-6


# --- prior_box ----------------------------------------------------------------

def _expand_aspect_ratios(ars, flip):
    out = [1.0]
    for ar in ars:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(ar)
        if flip:
            out.append(1.0 / ar)
    return out


def _prior_box_infer(op_, block):
    iv = in_var(op_, block, "Input")
    if iv is None or iv.shape is None:
        return
    ars = _expand_aspect_ratios(op_.attr("aspect_ratios", [1.0]),
                                op_.attr("flip", False))
    num = len(ars) * len(op_.attr("min_sizes")) + \
        len(op_.attr("max_sizes", []) or [])
    h, w = iv.shape[2], iv.shape[3]
    set_out(op_, block, "Boxes", [h, w, num, 4], "float32")
    set_out(op_, block, "Variances", [h, w, num, 4], "float32")


@op("prior_box", infer_shape=_prior_box_infer, grad=NO_GRAD,
    non_diff_inputs=("Input", "Image"))
def _prior_box(ctx, op_, ins):
    """SSD prior (anchor) boxes for one feature map (reference
    prior_box_op.h). Pure function of static shapes and attrs, so the whole
    grid is computed in numpy at trace time and embedded as an XLA constant
    — zero runtime cost."""
    feat = ins["Input"][0]
    img = ins["Image"][0]
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    min_sizes = [float(v) for v in op_.attr("min_sizes")]
    max_sizes = [float(v) for v in (op_.attr("max_sizes", []) or [])]
    ars = _expand_aspect_ratios(
        [float(a) for a in op_.attr("aspect_ratios", [1.0])],
        op_.attr("flip", False))
    variances = [float(v) for v in op_.attr("variances",
                                            [0.1, 0.1, 0.2, 0.2])]
    step_w = float(op_.attr("step_w", 0.0)) or iw / fw
    step_h = float(op_.attr("step_h", 0.0)) or ih / fh
    offset = float(op_.attr("offset", 0.5))

    num_priors = len(ars) * len(min_sizes) + len(max_sizes)
    boxes = np.zeros((fh, fw, num_priors, 4), np.float32)
    for h in range(fh):
        for w in range(fw):
            cx = (w + offset) * step_w
            cy = (h + offset) * step_h
            idx = 0
            for s, ms in enumerate(min_sizes):
                bw = bh = ms / 2.0
                boxes[h, w, idx] = [(cx - bw) / iw, (cy - bh) / ih,
                                    (cx + bw) / iw, (cy + bh) / ih]
                idx += 1
                if max_sizes:
                    bw = bh = math.sqrt(ms * max_sizes[s]) / 2.0
                    boxes[h, w, idx] = [(cx - bw) / iw, (cy - bh) / ih,
                                        (cx + bw) / iw, (cy + bh) / ih]
                    idx += 1
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    bw = ms * math.sqrt(ar) / 2.0
                    bh = ms / math.sqrt(ar) / 2.0
                    boxes[h, w, idx] = [(cx - bw) / iw, (cy - bh) / ih,
                                        (cx + bw) / iw, (cy + bh) / ih]
                    idx += 1
    if op_.attr("clip", False):
        boxes = np.clip(boxes, 0.0, 1.0)
    vars_ = np.broadcast_to(
        np.asarray(variances, np.float32), boxes.shape).copy()
    return {"Boxes": [jnp.asarray(boxes)], "Variances": [jnp.asarray(vars_)]}


# --- iou_similarity -----------------------------------------------------------

def _iou_infer(op_, block):
    xv = in_var(op_, block, "X")
    yv = in_var(op_, block, "Y")
    if xv is not None and xv.shape is not None and yv is not None \
            and yv.shape is not None:
        set_out(op_, block, "Out", [xv.shape[0], yv.shape[0]], xv.dtype)


def pairwise_iou(x, y):
    """IoU between every row of x [..., N, 4] and y [M, 4] -> [..., N, M]."""
    x = x[..., :, None, :]
    y = y[None, :, :]
    ixmin = jnp.maximum(x[..., 0], y[..., 0])
    iymin = jnp.maximum(x[..., 1], y[..., 1])
    ixmax = jnp.minimum(x[..., 2], y[..., 2])
    iymax = jnp.minimum(x[..., 3], y[..., 3])
    iw = jnp.maximum(ixmax - ixmin, 0.0)
    ih = jnp.maximum(iymax - iymin, 0.0)
    inter = iw * ih
    a1 = (x[..., 2] - x[..., 0]) * (x[..., 3] - x[..., 1])
    a2 = (y[..., 2] - y[..., 0]) * (y[..., 3] - y[..., 1])
    union = a1 + a2 - inter
    return inter / jnp.maximum(union, _EPS)


@op("iou_similarity", infer_shape=_iou_infer, non_diff_inputs=("Y",))
def _iou_similarity(ctx, op_, ins):
    """Pairwise Jaccard overlap (reference iou_similarity_op.h). X may be a
    padded LoD batch [B, G, 4] (rows beyond the per-image count produce
    garbage rows that downstream consumers mask via @SEQLEN) or flat
    [N, 4]."""
    x = jnp.asarray(ins["X"][0])
    y = jnp.asarray(ins["Y"][0])
    if x.ndim == 3:
        out = jax.vmap(lambda xb: pairwise_iou(xb, y))(x)
    else:
        out = pairwise_iou(x, y)
    return {"Out": [out]}


# --- box_coder ----------------------------------------------------------------

def _box_coder_infer(op_, block):
    tv = in_var(op_, block, "TargetBox")
    pv = in_var(op_, block, "PriorBox")
    if tv is None or tv.shape is None or pv is None or pv.shape is None:
        return
    code_type = op_.attr("code_type", "encode_center_size")
    if code_type == "encode_center_size":
        set_out(op_, block, "OutputBox",
                [tv.shape[0], pv.shape[0], 4], tv.dtype)
    else:
        set_out(op_, block, "OutputBox", list(tv.shape), tv.dtype)


@op("box_coder", infer_shape=_box_coder_infer,
    non_diff_inputs=("PriorBox", "PriorBoxVar"))
def _box_coder(ctx, op_, ins):
    """Encode/decode boxes against priors in center-size form (reference
    box_coder_op.h). encode: targets [N, 4] x priors [M, 4] -> [N, M, 4];
    decode: codes [N, M, 4] (or [B, N, M, 4]) -> same shape boxes."""
    t = jnp.asarray(ins["TargetBox"][0])
    p = jnp.asarray(ins["PriorBox"][0])
    pv = jnp.asarray(ins["PriorBoxVar"][0]) if ins.get("PriorBoxVar") and \
        ins["PriorBoxVar"][0] is not None else jnp.ones_like(p)
    if pv.ndim > 2:
        pv = pv.reshape(-1, pv.shape[-1])
    if p.ndim > 2:
        p = p.reshape(-1, p.shape[-1])
    pw = p[:, 2] - p[:, 0]
    ph = p[:, 3] - p[:, 1]
    pcx = (p[:, 2] + p[:, 0]) / 2
    pcy = (p[:, 3] + p[:, 1]) / 2

    if op_.attr("code_type", "encode_center_size") == "encode_center_size":
        # targets [..., G, 4] x priors [P, 4] -> [..., G, P, 4]
        tcx = ((t[..., 2] + t[..., 0]) / 2)[..., None]
        tcy = ((t[..., 3] + t[..., 1]) / 2)[..., None]
        tw = (t[..., 2] - t[..., 0])[..., None]
        th = (t[..., 3] - t[..., 1])[..., None]
        out = jnp.stack([
            (tcx - pcx) / pw / pv[:, 0],
            (tcy - pcy) / ph / pv[:, 1],
            jnp.log(jnp.abs(tw / pw)) / pv[:, 2],
            jnp.log(jnp.abs(th / ph)) / pv[:, 3],
        ], axis=-1)
    else:
        # decode: t is [..., M, 4] codes aligned with priors
        tcx = pv[..., 0] * t[..., 0] * pw + pcx
        tcy = pv[..., 1] * t[..., 1] * ph + pcy
        tw = jnp.exp(pv[..., 2] * t[..., 2]) * pw
        th = jnp.exp(pv[..., 3] * t[..., 3]) * ph
        out = jnp.stack([tcx - tw / 2, tcy - th / 2,
                         tcx + tw / 2, tcy + th / 2], axis=-1)
    return {"OutputBox": [out]}


# --- bipartite_match ----------------------------------------------------------

def _bipartite_infer(op_, block):
    dv = in_var(op_, block, "DistMat")
    if dv is not None and dv.shape is not None:
        if len(dv.shape) == 3:
            shape = [dv.shape[0], dv.shape[2]]
        else:
            shape = [1, dv.shape[1]]
        set_out(op_, block, "ColToRowMatchIndices", shape, "int32")
        set_out(op_, block, "ColToRowMatchDist", shape, "float32")


def _bipartite_one(dist, row_len, match_type, overlap_threshold):
    """Greedy global-argmax bipartite matching for one image (reference
    bipartite_match_op.cc BipartiteMatch): repeatedly pick the largest
    remaining (row, col) entry, retire both. Sequential by nature — a
    bounded fori_loop with masked argmax, G iterations of O(G*P) work."""
    g, p = dist.shape
    row_valid = jnp.arange(g) < row_len
    dist = jnp.where(row_valid[:, None], dist, -1.0)

    def body(_, carry):
        match_idx, match_dist, row_used = carry
        masked = jnp.where(row_used[:, None] | (match_idx[None, :] >= 0)
                           | (dist < _EPS), -1.0, dist)
        flat = jnp.argmax(masked)
        r, c = flat // p, flat % p
        ok = masked[r, c] > 0.0
        match_idx = jnp.where(ok, match_idx.at[c].set(r.astype(jnp.int32)),
                              match_idx)
        match_dist = jnp.where(ok, match_dist.at[c].set(dist[r, c]),
                               match_dist)
        row_used = jnp.where(ok, row_used.at[r].set(True), row_used)
        return match_idx, match_dist, row_used

    init = (jnp.full((p,), -1, jnp.int32), jnp.zeros((p,), dist.dtype),
            jnp.zeros((g,), bool))
    match_idx, match_dist, _ = jax.lax.fori_loop(0, g, body, init)

    if match_type == "per_prediction":
        # additionally match any unmatched column to its argmax row when the
        # overlap clears the threshold (reference ArgMaxMatch)
        best = jnp.argmax(dist, axis=0).astype(jnp.int32)
        bestd = jnp.max(dist, axis=0)
        extra = (match_idx == -1) & (bestd >= overlap_threshold)
        match_idx = jnp.where(extra, best, match_idx)
        match_dist = jnp.where(extra, bestd, match_dist)
    return match_idx, match_dist


@op("bipartite_match", infer_shape=_bipartite_infer, grad=NO_GRAD)
def _bipartite_match(ctx, op_, ins):
    dist = jnp.asarray(ins["DistMat"][0])
    if dist.ndim == 2:
        dist = dist[None]
    b, g, p = dist.shape
    lens = _lengths(ctx, op_, "DistMat", b, g)
    mt = op_.attr("match_type", "bipartite")
    thr = op_.attr("dist_threshold", 0.5)
    idx, d = jax.vmap(_bipartite_one, in_axes=(0, 0, None, None))(
        dist, lens, mt, thr)
    for slot in ("ColToRowMatchIndices", "ColToRowMatchDist"):
        for n in op_.desc.outputs.get(slot, []):
            ctx.set_seq_len(n, None)
    return {"ColToRowMatchIndices": [idx], "ColToRowMatchDist": [d]}


# --- mine_hard_examples -------------------------------------------------------

@op("mine_hard_examples", grad=NO_GRAD)
def _mine_hard_examples(ctx, op_, ins):
    """Hard-negative mining (reference mine_hard_examples_op.cc). For
    max_negative: eligible negatives (unmatched, low overlap) are ranked by
    classification loss and the top num_pos*neg_pos_ratio kept. Selection
    is a rank test on the sorted losses instead of the reference's
    sort+set walk."""
    cls_loss = jnp.asarray(ins["ClsLoss"][0])
    match_idx = jnp.asarray(ins["MatchIndices"][0]).astype(jnp.int32)
    match_dist = jnp.asarray(ins["MatchDist"][0])
    if cls_loss.ndim == 3:
        cls_loss = cls_loss[..., 0]
    b, p = match_idx.shape
    mining_type = op_.attr("mining_type", "max_negative")
    neg_pos_ratio = op_.attr("neg_pos_ratio", 1.0)
    neg_dist_threshold = op_.attr("neg_dist_threshold", 0.5)
    sample_size = op_.attr("sample_size", 0)

    loss = cls_loss
    if mining_type == "hard_example" and ins.get("LocLoss") and \
            ins["LocLoss"][0] is not None:
        ll = jnp.asarray(ins["LocLoss"][0])
        loss = loss + (ll[..., 0] if ll.ndim == 3 else ll)

    if mining_type == "max_negative":
        eligible = (match_idx == -1) & (match_dist < neg_dist_threshold)
        num_pos = jnp.sum(match_idx != -1, axis=1)
        neg_sel = jnp.minimum(
            (num_pos.astype(jnp.float32) * neg_pos_ratio).astype(jnp.int32),
            eligible.sum(axis=1).astype(jnp.int32))
    else:
        eligible = jnp.ones_like(match_idx, dtype=bool)
        neg_sel = jnp.minimum(jnp.full((b,), sample_size, jnp.int32),
                              eligible.sum(axis=1).astype(jnp.int32))

    masked = jnp.where(eligible, loss, -jnp.inf)
    order = jnp.argsort(-masked, axis=1, stable=True)
    rank = jax.vmap(lambda o: jnp.zeros((p,), jnp.int32).at[o].set(
        jnp.arange(p, dtype=jnp.int32)))(order)
    selected = eligible & (rank < neg_sel[:, None])

    # compact selected prior indices to the front, ascending (reference
    # returns a LoD'd index list per image)
    key = jnp.where(selected, jnp.arange(p)[None, :], p + 1)
    sorted_idx = jnp.sort(key, axis=1)
    neg_count = selected.sum(axis=1).astype(jnp.int32)
    neg_indices = jnp.where(
        jnp.arange(p)[None, :] < neg_count[:, None], sorted_idx, 0
    ).astype(jnp.int32)

    updated = match_idx
    if mining_type == "hard_example":
        updated = jnp.where((match_idx > -1) & ~selected, -1, match_idx)

    out_name = op_.desc.outputs["NegIndices"][0]
    ctx.set_seq_len(out_name, neg_count)
    for n in op_.desc.outputs.get("UpdatedMatchIndices", []):
        ctx.set_seq_len(n, None)
    return {"NegIndices": [neg_indices[..., None]],
            "UpdatedMatchIndices": [updated]}


# --- target_assign ------------------------------------------------------------

@op("target_assign", grad=NO_GRAD,
    non_diff_inputs=("X", "MatchIndices", "NegIndices"))
def _target_assign(ctx, op_, ins):
    """Gather per-prior targets from per-image gt rows by match index
    (reference target_assign_op.h): out[b, m] = X[b, match[b, m]] where
    matched, else mismatch_value with weight 0; negative indices (from hard
    mining) force weight 1 at mismatch_value."""
    x = jnp.asarray(ins["X"][0])             # [B, G, K] or [B, G, M, K]
    match = jnp.asarray(ins["MatchIndices"][0]).astype(jnp.int32)  # [B, M]
    mismatch = op_.attr("mismatch_value", 0)
    b, m = match.shape
    k = x.shape[-1]
    safe = jnp.clip(match, 0, x.shape[1] - 1)
    if x.ndim == 4:
        # per-prior targets (the reference's P axis, target_assign_op.h
        # w_off = w % P): out[b, m] = X[b, match[b, m], m] — one fused
        # gather, no [M, M] intermediate
        gathered = x[jnp.arange(b)[:, None], safe, jnp.arange(m)[None, :], :]
    else:
        gathered = jnp.take_along_axis(x, safe[..., None], axis=1)
    matched = (match > -1)[..., None]
    out = jnp.where(matched, gathered,
                    jnp.full_like(gathered, float(mismatch)))
    wt = matched[..., 0].astype(jnp.float32)[..., None]

    if ins.get("NegIndices") and ins["NegIndices"][0] is not None:
        neg = jnp.asarray(ins["NegIndices"][0])
        if neg.ndim == 3:
            neg = neg[..., 0]
        names = op_.desc.inputs.get("NegIndices", [])
        ncount = ctx.seq_len(names[0]) if names else None
        if ncount is None:
            ncount = jnp.full((b,), neg.shape[1], jnp.int32)
        valid = jnp.arange(neg.shape[1])[None, :] < \
            jnp.asarray(ncount)[:, None]
        onehot = jax.nn.one_hot(
            jnp.where(valid, neg, m), m, dtype=jnp.float32)  # [B, N, M]
        is_neg = onehot.sum(axis=1) > 0
        wt = jnp.where(is_neg[..., None], 1.0, wt)
    for slot in ("Out", "OutWeight"):
        for n in op_.desc.outputs.get(slot, []):
            ctx.set_seq_len(n, None)
    return {"Out": [out], "OutWeight": [wt]}


# --- multiclass_nms -----------------------------------------------------------

def _nms_class(boxes, scores, score_threshold, nms_threshold, top_k):
    """Greedy NMS for one class (reference NMSFast): walk candidates in
    score order, keep a box iff it overlaps no already-kept box. The
    data-dependent erase loop becomes a fori_loop over the sorted list with
    a keep mask — O(P^2) IoU is precomputed once and tiles cleanly."""
    p = scores.shape[0]
    order = jnp.argsort(-scores, stable=True)
    sboxes = boxes[order]
    sscores = scores[order]
    valid = sscores > score_threshold
    if top_k > -1:
        valid = valid & (jnp.arange(p) < top_k)
    iou = pairwise_iou(sboxes, sboxes)

    def body(i, keep):
        over = (iou[:, i] > nms_threshold) & keep & (jnp.arange(p) < i)
        ki = valid[i] & ~jnp.any(over)
        return keep.at[i].set(ki)

    keep = jax.lax.fori_loop(0, p, body, jnp.zeros((p,), bool))
    return order, keep


def _nms_infer(op_, block):
    bv = in_var(op_, block, "BBoxes")
    sv = in_var(op_, block, "Scores")
    if bv is None or bv.shape is None or sv is None or sv.shape is None:
        return
    keep_top_k = op_.attr("keep_top_k", -1)
    cap = keep_top_k if keep_top_k > 0 else bv.shape[-2]
    batch = sv.shape[0] if len(sv.shape) == 3 else 1
    set_out(op_, block, "Out", [batch, cap, 6], bv.dtype)


@op("multiclass_nms", infer_shape=_nms_infer, grad=NO_GRAD)
def _multiclass_nms(ctx, op_, ins):
    """Multi-class NMS (reference multiclass_nms_op.cc). Scores [B, C, P],
    BBoxes [B, P, 4] (shared across classes) or [P, 4]. Output is padded
    [B, cap, 6] rows (label, score, x1, y1, x2, y2) + @SEQLEN per-image
    detection counts — the dense stand-in for the reference's LoD output."""
    scores = jnp.asarray(ins["Scores"][0])
    boxes = jnp.asarray(ins["BBoxes"][0])
    if scores.ndim == 2:
        scores = scores[None]
    if boxes.ndim == 2:
        boxes = boxes[None]
    b, c, p = scores.shape
    bg = op_.attr("background_label", 0)
    score_threshold = op_.attr("score_threshold", 0.0)
    nms_top_k = op_.attr("nms_top_k", -1)
    keep_top_k = op_.attr("keep_top_k", -1)
    nms_threshold = op_.attr("nms_threshold", 0.3)
    cap = keep_top_k if keep_top_k > 0 else p

    def one_image(sc, bx):
        # per-class NMS -> (C, P) keep grid in original index space
        def per_class(cs):
            order, keep = _nms_class(bx, cs, score_threshold, nms_threshold,
                                     nms_top_k)
            # scatter keep back to original indices
            return jnp.zeros((p,), bool).at[order].set(keep)

        keeps = jax.vmap(per_class)(sc)          # (C, P)
        if 0 <= bg < c:
            keeps = keeps.at[bg].set(False)
        flat_scores = jnp.where(keeps, sc, -jnp.inf).reshape(-1)
        total = keeps.sum()
        k = jnp.minimum(total, cap)
        order = jnp.argsort(-flat_scores, stable=True)[:cap]
        sel_class = (order // p).astype(jnp.float32)
        sel_idx = order % p
        sel_score = flat_scores.reshape(-1)[order]
        sel_box = bx[sel_idx]
        rows = jnp.concatenate(
            [sel_class[:, None], sel_score[:, None], sel_box], axis=1)
        rank_ok = jnp.arange(cap) < k
        rows = jnp.where(rank_ok[:, None], rows, jnp.zeros_like(rows))
        return rows, k.astype(jnp.int32)

    rows, counts = jax.vmap(one_image)(scores, boxes)
    out_name = op_.desc.outputs["Out"][0]
    ctx.set_seq_len(out_name, counts)
    return {"Out": [rows]}


# --- detection_map ------------------------------------------------------------

def _np_iou(a, b):
    ixmin = max(a[0], b[0]); iymin = max(a[1], b[1])
    ixmax = min(a[2], b[2]); iymax = min(a[3], b[3])
    if b[0] > a[2] or b[2] < a[0] or b[1] > a[3] or b[3] < a[1]:
        return 0.0
    inter = (ixmax - ixmin) * (iymax - iymin)
    a1 = (a[2] - a[0]) * (a[3] - a[1])
    a2 = (b[2] - b[0]) * (b[3] - b[1])
    return inter / max(a1 + a2 - inter, _EPS)


def detection_tp_fp(dets, det_counts, gts, gt_counts, overlap_threshold,
                    evaluate_difficult):
    """Per-class positives + (score, tp/fp) contributions of a batch
    (reference detection_map_op.h CalcTrueAndFalsePositive). Contributions
    are independent across images, so callers (the accumulative evaluator)
    can merge dicts across batches incrementally."""
    label_pos = {}
    tp, fp = {}, {}
    bsz = dets.shape[0]
    for n in range(bsz):
        g = gts[n][:int(gt_counts[n])]
        for row in g:
            lab = int(row[0])
            diff = bool(abs(row[1]) > 1e-6)
            if evaluate_difficult or not diff:
                label_pos[lab] = label_pos.get(lab, 0) + 1
    for n in range(bsz):
        g = gts[n][:int(gt_counts[n])]
        d = dets[n][:int(det_counts[n])]
        gt_by_label = {}
        for row in g:
            gt_by_label.setdefault(int(row[0]), []).append(row)
        det_by_label = {}
        for row in d:
            det_by_label.setdefault(int(row[0]), []).append(row)
        for lab, rows in det_by_label.items():
            if lab not in gt_by_label:
                for row in rows:
                    tp.setdefault(lab, []).append((float(row[1]), 0))
                    fp.setdefault(lab, []).append((float(row[1]), 1))
                continue
            matched = gt_by_label[lab]
            visited = [False] * len(matched)
            rows = sorted(rows, key=lambda r: -r[1])
            for row in rows:
                box = np.clip(row[2:6], 0.0, 1.0)
                score = float(row[1])
                overlaps = [_np_iou(box, m[2:6]) for m in matched]
                j = int(np.argmax(overlaps)) if overlaps else 0
                if overlaps and overlaps[j] > overlap_threshold:
                    mdiff = bool(abs(matched[j][1]) > 1e-6)
                    if evaluate_difficult or not mdiff:
                        if not visited[j]:
                            tp.setdefault(lab, []).append((score, 1))
                            fp.setdefault(lab, []).append((score, 0))
                            visited[j] = True
                        else:
                            tp.setdefault(lab, []).append((score, 0))
                            fp.setdefault(lab, []).append((score, 1))
                else:
                    tp.setdefault(lab, []).append((score, 0))
                    fp.setdefault(lab, []).append((score, 1))
    return label_pos, tp, fp


def map_from_tp_fp(label_pos, tp, fp, ap_type, background_label):
    """mAP from accumulated per-class contributions (reference
    detection_map_op.h CalcMAP)."""
    mAP, count = 0.0, 0
    for lab, num_pos in label_pos.items():
        if lab == background_label or lab not in tp or num_pos == 0:
            continue
        pairs_t = sorted(tp[lab], key=lambda x: -x[0])
        pairs_f = sorted(fp[lab], key=lambda x: -x[0])
        tps = np.cumsum([x[1] for x in pairs_t])
        fps = np.cumsum([x[1] for x in pairs_f])
        prec = tps / np.maximum(tps + fps, 1)
        rec = tps / num_pos
        if ap_type == "11point":
            maxp = np.zeros(11)
            for j in range(11):
                mask = rec >= j / 10.0
                maxp[j] = prec[mask].max() if mask.any() else 0.0
            ap = maxp.sum() / 11.0
        else:
            ap, prev = 0.0, 0.0
            for pr, rc in zip(prec, rec):
                if abs(rc - prev) > 1e-6:
                    ap += pr * abs(rc - prev)
                prev = rc
        mAP += ap
        count += 1
    return np.float32(mAP / count if count else 0.0)


def detection_map_np(dets, det_counts, gts, gt_counts, overlap_threshold,
                     evaluate_difficult, ap_type, background_label):
    """Host mAP over one batch (faithful port of reference
    detection_map_op.h). dets [B, D, 6] rows (label, score, box);
    gts [B, G, 6] rows (label, difficult, box)."""
    label_pos, tp, fp = detection_tp_fp(dets, det_counts, gts, gt_counts,
                                        overlap_threshold,
                                        evaluate_difficult)
    return map_from_tp_fp(label_pos, tp, fp, ap_type, background_label)


def _dmap_infer(op_, block):
    set_out(op_, block, "MAP", [1], "float32")


@op("detection_map", infer_shape=_dmap_infer, grad=NO_GRAD)
def _detection_map(ctx, op_, ins):
    """mAP metric (reference detection_map_op.h — a CPU-only kernel there
    too). Runs as a host callback: per-class AP accumulation is inherently
    sequential and once-per-batch, not MXU work. DetectRes/Label are padded
    [B, D, 6]/[B, G, 6] + @SEQLEN."""
    det = jnp.asarray(ins["DetectRes"][0])
    gt = jnp.asarray(ins["Label"][0])
    if det.ndim == 2:
        det = det[None]
    if gt.ndim == 2:
        gt = gt[None]
    dcount = _lengths(ctx, op_, "DetectRes", det.shape[0], det.shape[1])
    gcount = _lengths(ctx, op_, "Label", gt.shape[0], gt.shape[1])
    thr = op_.attr("overlap_threshold", 0.3)
    ed = op_.attr("evaluate_difficult", True)
    ap_type = op_.attr("ap_type", "integral")
    bg = op_.attr("background_label", 0)

    def cb(d, dc, g, gc):
        return detection_map_np(np.asarray(d), np.asarray(dc), np.asarray(g),
                                np.asarray(gc), thr, ed, ap_type, bg
                                ).reshape(1)

    out = jax.pure_callback(cb, jax.ShapeDtypeStruct((1,), np.float32),
                            det, dcount, gt, gcount)
    for n in op_.desc.outputs.get("MAP", []):
        ctx.set_seq_len(n, None)
    return {"MAP": [out]}
