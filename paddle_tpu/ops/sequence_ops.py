"""Sequence (LoD) ops on padded dense tensors + length masks.

TPU-native equivalents of the reference's packed-LoD sequence ops
(reference: paddle/fluid/operators/sequence_*_op.cc, lstm_op.cc, gru_op.cc,
operators/math/lstm_compute.*, gru_compute.*, sequence2batch.h,
sequence_pooling.cc). The reference stores variable-length batches packed
([sum_len, D] + LoD offsets) and reorders them per-timestep
(sequence2batch); XLA wants static shapes, so here sequences are padded
dense [batch, T, D] with an int32 lengths vector riding along the trace
(executor.SEQLEN_SUFFIX), and every op masks by length. RNNs lower to
`lax.scan` over the time axis — one XLA while-loop with a fused cell body
instead of the reference's per-timestep kernel launches.

Gate layouts (documented, tested for self-consistency via OpTest numeric
gradients rather than weight-level parity with CUDA kernels):
  LSTM: gates order [input, forget, cell-candidate, output] along 4H.
  GRU:  weight [H, 3H] = [update, reset | candidate]; h = u*h_prev + (1-u)*c.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .common import in_var, out_var, set_out
from .registry import NO_GRAD, op


def _lengths(ctx, op_, slot="X", idx=0):
    names = op_.desc.inputs.get(slot, [])
    if idx < len(names):
        return ctx.seq_len(names[idx])
    return None


def _time_mask(lengths, t, batch):
    """[B, T] float mask from lengths; all-ones if lengths is None."""
    if lengths is None:
        return jnp.ones((batch, t), dtype=jnp.float32)
    steps = jnp.arange(t)[None, :]
    return (steps < jnp.asarray(lengths)[:, None]).astype(jnp.float32)


_ACTS = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
    "": lambda x: x,
}


# ---------------------------------------------------------------------------
# Fused RNNs
# ---------------------------------------------------------------------------

def _lstm_infer(op_, block):
    xv = in_var(op_, block, "Input")
    if xv is None or xv.shape is None:
        return
    b, t, h4 = xv.shape[0], xv.shape[1], xv.shape[2]
    h = h4 // 4 if h4 and h4 > 0 else None
    set_out(op_, block, "Hidden", [b, t, h], xv.dtype)
    set_out(op_, block, "Cell", [b, t, h], xv.dtype)


@op("lstm", infer_shape=_lstm_infer, non_diff_inputs=())
def _lstm(ctx, op_, ins):
    """Fused LSTM over a padded sequence (reference lstm_op.cc,
    math/lstm_compute.*). Input [B,T,4H] is the precomputed x-projection
    (the reference also takes it pre-projected); Weight [H,4H] is the
    recurrent projection; Bias [1,4H] or [1,7H] with peepholes."""
    x = jnp.asarray(ins["Input"][0])          # [B, T, 4H]
    w = jnp.asarray(ins["Weight"][0])         # [H, 4H]
    bias = jnp.asarray(ins["Bias"][0]).reshape(-1) if ins.get("Bias") and \
        ins["Bias"][0] is not None else None
    h_dim = w.shape[0]
    bsz, t = x.shape[0], x.shape[1]
    lengths = _lengths(ctx, op_, "Input")
    use_peepholes = bool(op_.attr("use_peepholes", False))
    is_reverse = bool(op_.attr("is_reverse", False))
    gate_act = _ACTS[op_.attr("gate_activation", "sigmoid")]
    cell_act = _ACTS[op_.attr("cell_activation", "tanh")]
    cand_act = _ACTS[op_.attr("candidate_activation", "tanh")]

    b_gate = bias[: 4 * h_dim] if bias is not None else 0.0
    if use_peepholes:
        assert bias is not None and bias.shape[0] >= 7 * h_dim, (
            "use_peepholes=True requires a Bias of width 7*H "
            "(gate bias + W_ic|W_fc|W_oc peephole weights)")
        w_ic = bias[4 * h_dim: 5 * h_dim]
        w_fc = bias[5 * h_dim: 6 * h_dim]
        w_oc = bias[6 * h_dim: 7 * h_dim]

    h0 = jnp.asarray(ins["H0"][0]) if ins.get("H0") and ins["H0"][0] is not None \
        else jnp.zeros((bsz, h_dim), x.dtype)
    c0 = jnp.asarray(ins["C0"][0]) if ins.get("C0") and ins["C0"][0] is not None \
        else jnp.zeros((bsz, h_dim), x.dtype)

    xs = jnp.swapaxes(x, 0, 1)                      # [T, B, 4H]
    mask = jnp.swapaxes(_time_mask(lengths, t, bsz), 0, 1)[..., None]  # [T,B,1]
    mask = mask.astype(x.dtype)
    if is_reverse:
        xs, mask = xs[::-1], mask[::-1]

    def step(carry, inp):
        h_prev, c_prev = carry
        xt, mt = inp
        gates = xt + h_prev @ w + b_gate            # [B, 4H]
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i, f = gate_act(gi), gate_act(gf)
        c_tilde = cand_act(gc)
        c = f * c_prev + i * c_tilde
        if use_peepholes:
            go = go + c * w_oc
        o = gate_act(go)
        h = o * cell_act(c)
        # masked (padded) steps: carries hold, emitted frames are zero
        c = mt * c + (1.0 - mt) * c_prev
        h_keep = mt * h + (1.0 - mt) * h_prev
        return (h_keep, c), (mt * h, mt * c)

    (_, _), (hs, cs) = lax.scan(step, (h0, c0), (xs, mask))
    if is_reverse:
        hs, cs = hs[::-1], cs[::-1]
    hidden = jnp.swapaxes(hs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    outs = op_.desc.outputs
    if "Hidden" in outs:
        for name in outs["Hidden"]:
            ctx.set_seq_len(name, lengths)
    if "Cell" in outs:
        for name in outs["Cell"]:
            ctx.set_seq_len(name, lengths)
    return {"Hidden": [hidden], "Cell": [cell]}


def _lstmp_infer(op_, block):
    xv = in_var(op_, block, "Input")
    pv = in_var(op_, block, "ProjWeight")
    if xv is None or xv.shape is None:
        return
    b, t, h4 = xv.shape[0], xv.shape[1], xv.shape[2]
    h = h4 // 4 if h4 and h4 > 0 else None
    p = pv.shape[1] if pv is not None and pv.shape is not None else None
    set_out(op_, block, "Projection", [b, t, p], xv.dtype)
    set_out(op_, block, "Cell", [b, t, h], xv.dtype)


@op("lstmp", infer_shape=_lstmp_infer)
def _lstmp(ctx, op_, ins):
    """LSTM with recurrent projection (reference lstmp_op.cc): the recurrent
    state is r = proj_act(h @ ProjWeight) [B,P]; gates read r, not h."""
    x = jnp.asarray(ins["Input"][0])          # [B, T, 4H]
    w = jnp.asarray(ins["Weight"][0])         # [P, 4H]
    pw = jnp.asarray(ins["ProjWeight"][0])    # [H, P]
    bias = jnp.asarray(ins["Bias"][0]).reshape(-1) if ins.get("Bias") and \
        ins["Bias"][0] is not None else None
    h_dim, p_dim = pw.shape
    bsz, t = x.shape[0], x.shape[1]
    lengths = _lengths(ctx, op_, "Input")
    use_peepholes = bool(op_.attr("use_peepholes", False))
    gate_act = _ACTS[op_.attr("gate_activation", "sigmoid")]
    cell_act = _ACTS[op_.attr("cell_activation", "tanh")]
    cand_act = _ACTS[op_.attr("candidate_activation", "tanh")]
    proj_act = _ACTS[op_.attr("proj_activation", "tanh")]
    is_reverse = bool(op_.attr("is_reverse", False))

    b_gate = bias[: 4 * h_dim] if bias is not None else 0.0
    if use_peepholes:
        assert bias is not None and bias.shape[0] >= 7 * h_dim
        w_ic = bias[4 * h_dim: 5 * h_dim]
        w_fc = bias[5 * h_dim: 6 * h_dim]
        w_oc = bias[6 * h_dim: 7 * h_dim]

    xs = jnp.swapaxes(x, 0, 1)
    mask = jnp.swapaxes(_time_mask(lengths, t, bsz), 0, 1)[..., None]
    mask = mask.astype(x.dtype)
    if is_reverse:
        xs, mask = xs[::-1], mask[::-1]
    r0 = jnp.zeros((bsz, p_dim), x.dtype)
    c0 = jnp.zeros((bsz, h_dim), x.dtype)

    def step(carry, inp):
        r_prev, c_prev = carry
        xt, mt = inp
        gates = xt + r_prev @ w + b_gate
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i, f = gate_act(gi), gate_act(gf)
        c = f * c_prev + i * cand_act(gc)
        if use_peepholes:
            go = go + c * w_oc
        h = gate_act(go) * cell_act(c)
        r = proj_act(h @ pw)
        c = mt * c + (1.0 - mt) * c_prev
        r_keep = mt * r + (1.0 - mt) * r_prev
        return (r_keep, c), (mt * r, mt * c)

    (_, _), (rs, cs) = lax.scan(step, (r0, c0), (xs, mask))
    if is_reverse:
        rs, cs = rs[::-1], cs[::-1]
    proj = jnp.swapaxes(rs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    for name in op_.desc.outputs.get("Projection", []):
        ctx.set_seq_len(name, lengths)
    for name in op_.desc.outputs.get("Cell", []):
        ctx.set_seq_len(name, lengths)
    return {"Projection": [proj], "Cell": [cell]}


def _gru_infer(op_, block):
    xv = in_var(op_, block, "Input")
    if xv is None or xv.shape is None:
        return
    b, t, h3 = xv.shape[0], xv.shape[1], xv.shape[2]
    h = h3 // 3 if h3 and h3 > 0 else None
    set_out(op_, block, "Hidden", [b, t, h], xv.dtype)


@op("gru", infer_shape=_gru_infer)
def _gru(ctx, op_, ins):
    """Fused GRU over a padded sequence (reference gru_op.cc,
    math/gru_compute.*). Input [B,T,3H] pre-projected; Weight [H,3H]:
    first [H,2H] update|reset, last [H,H] candidate."""
    x = jnp.asarray(ins["Input"][0])
    w = jnp.asarray(ins["Weight"][0])
    h_dim = w.shape[0]
    bias = jnp.asarray(ins["Bias"][0]).reshape(-1) if ins.get("Bias") and \
        ins["Bias"][0] is not None else jnp.zeros((3 * h_dim,), x.dtype)
    bsz, t = x.shape[0], x.shape[1]
    lengths = _lengths(ctx, op_, "Input")
    is_reverse = bool(op_.attr("is_reverse", False))
    gate_act = _ACTS[op_.attr("gate_activation", "sigmoid")]
    cand_act = _ACTS[op_.attr("activation", "tanh")]

    w_ur = w[:, : 2 * h_dim]
    w_c = w[:, 2 * h_dim:]
    h0 = jnp.asarray(ins["H0"][0]) if ins.get("H0") and ins["H0"][0] is not None \
        else jnp.zeros((bsz, h_dim), x.dtype)

    xs = jnp.swapaxes(x, 0, 1)
    mask = jnp.swapaxes(_time_mask(lengths, t, bsz), 0, 1)[..., None]
    mask = mask.astype(x.dtype)
    if is_reverse:
        xs, mask = xs[::-1], mask[::-1]

    def step(h_prev, inp):
        xt, mt = inp
        x_ur, x_c = xt[:, : 2 * h_dim], xt[:, 2 * h_dim:]
        ur = gate_act(x_ur + h_prev @ w_ur + bias[: 2 * h_dim])
        u, r = jnp.split(ur, 2, axis=-1)
        c = cand_act(x_c + (r * h_prev) @ w_c + bias[2 * h_dim:])
        h = u * h_prev + (1.0 - u) * c
        h_keep = mt * h + (1.0 - mt) * h_prev
        return h_keep, mt * h

    _, hs = lax.scan(step, h0, (xs, mask))
    if is_reverse:
        hs = hs[::-1]
    hidden = jnp.swapaxes(hs, 0, 1)
    for name in op_.desc.outputs.get("Hidden", []):
        ctx.set_seq_len(name, lengths)
    return {"Hidden": [hidden]}


def _lstm_unit_infer(op_, block):
    cv = in_var(op_, block, "C_prev")
    if cv is not None and cv.shape is not None:
        set_out(op_, block, "C", cv.shape, cv.dtype)
        set_out(op_, block, "H", cv.shape, cv.dtype)


@op("lstm_unit", infer_shape=_lstm_unit_infer)
def _lstm_unit(ctx, op_, ins):
    """Single LSTM step (reference lstm_unit_op.cc): inputs X=[B,4H] gates
    (already x@W_x + h@W_h + b), C_prev=[B,H]; outputs C, H."""
    gates = jnp.asarray(ins["X"][0])
    c_prev = jnp.asarray(ins["C_prev"][0])
    forget_bias = float(op_.attr("forget_bias", 0.0))
    gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * c_prev + i * jnp.tanh(gc)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    return {"C": [c], "H": [h]}


def _gru_unit_infer(op_, block):
    hv = in_var(op_, block, "HiddenPrev")
    iv = in_var(op_, block, "Input")
    if hv is not None and hv.shape is not None:
        set_out(op_, block, "Hidden", hv.shape, hv.dtype)
        set_out(op_, block, "ResetHiddenPrev", hv.shape, hv.dtype)
    if iv is not None and iv.shape is not None:
        set_out(op_, block, "Gate", iv.shape, iv.dtype)


@op("gru_unit", infer_shape=_gru_unit_infer)
def _gru_unit(ctx, op_, ins):
    """Single GRU step (reference gru_unit_op.cc): Input=[B,3H] x-projection,
    HiddenPrev=[B,H], Weight=[H,3H], Bias=[1,3H]."""
    x = jnp.asarray(ins["Input"][0])
    h_prev = jnp.asarray(ins["HiddenPrev"][0])
    w = jnp.asarray(ins["Weight"][0])
    h_dim = h_prev.shape[-1]
    bias = jnp.asarray(ins["Bias"][0]).reshape(-1) if ins.get("Bias") and \
        ins["Bias"][0] is not None else jnp.zeros((3 * h_dim,), x.dtype)
    gate_act = _ACTS[op_.attr("gate_activation", "sigmoid")]
    cand_act = _ACTS[op_.attr("activation", "tanh")]
    ur = gate_act(x[:, : 2 * h_dim] + h_prev @ w[:, : 2 * h_dim]
                  + bias[: 2 * h_dim])
    u, r = jnp.split(ur, 2, axis=-1)
    c = cand_act(x[:, 2 * h_dim:] + (r * h_prev) @ w[:, 2 * h_dim:]
                 + bias[2 * h_dim:])
    h = u * h_prev + (1.0 - u) * c
    return {"Hidden": [h], "Gate": [jnp.concatenate([u, r, c], -1)],
            "ResetHiddenPrev": [r * h_prev]}


# ---------------------------------------------------------------------------
# Sequence reductions / transforms
# ---------------------------------------------------------------------------

def _seq_pool_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is None or xv.shape is None:
        return
    set_out(op_, block, "Out", [xv.shape[0]] + list(xv.shape[2:]), xv.dtype)


@op("sequence_pool", infer_shape=_seq_pool_infer)
def _sequence_pool(ctx, op_, ins):
    """Pool over the time axis by length mask (reference
    sequence_pool_op.cc, math/sequence_pooling.cc): SUM/AVERAGE/SQRT/MAX/
    LAST/FIRST; [B,T,D] -> [B,D]."""
    x = jnp.asarray(ins["X"][0])
    pooltype = str(op_.attr("pooltype", "AVERAGE")).upper()
    lengths = _lengths(ctx, op_, "X")
    bsz, t = x.shape[0], x.shape[1]
    mask = _time_mask(lengths, t, bsz).astype(x.dtype)
    mshape = mask.shape + (1,) * (x.ndim - 2)
    m = mask.reshape(mshape)
    n = jnp.maximum(mask.sum(axis=1), 1.0).reshape((bsz,) + (1,) * (x.ndim - 2))
    if pooltype == "SUM":
        out = (x * m).sum(axis=1)
    elif pooltype == "AVERAGE":
        out = (x * m).sum(axis=1) / n
    elif pooltype == "SQRT":
        out = (x * m).sum(axis=1) / jnp.sqrt(n)
    elif pooltype == "MAX":
        neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
        out = jnp.where(m > 0, x, neg).max(axis=1)
    elif pooltype == "LAST":
        idx = (jnp.asarray(lengths) - 1).astype(jnp.int32) if lengths is not None \
            else jnp.full((bsz,), t - 1, jnp.int32)
        out = jnp.take_along_axis(
            x, idx.reshape((bsz, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    elif pooltype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {pooltype}")
    for name in op_.desc.outputs.get("Out", []):
        ctx.set_seq_len(name, None)
    return {"Out": [out]}


@op("sequence_softmax", infer_shape=None)
def _sequence_softmax(ctx, op_, ins):
    """Per-sequence softmax over time with length mask (reference
    sequence_softmax_op.cc). x: [B,T] or [B,T,1]."""
    x = jnp.asarray(ins["X"][0])
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    v = x.reshape(x.shape[:2]) if squeeze else x
    lengths = _lengths(ctx, op_, "X")
    mask = _time_mask(lengths, v.shape[1], v.shape[0]).astype(bool)
    neg = jnp.asarray(jnp.finfo(v.dtype).min, v.dtype)
    logits = jnp.where(mask, v, neg)
    out = jax.nn.softmax(logits, axis=1)
    out = jnp.where(mask, out, 0.0)
    if squeeze:
        out = out[..., None]
    return {"Out": [out]}


def _seq_expand_infer(op_, block):
    xv, yv = in_var(op_, block, "X"), in_var(op_, block, "Y")
    if xv is None or yv is None or xv.shape is None or yv.shape is None:
        return
    feat = list(xv.shape[1:]) if len(xv.shape) == 2 else list(xv.shape[2:])
    set_out(op_, block, "Out",
            [xv.shape[0], yv.shape[1] if len(yv.shape) > 1 else None]
            + feat, xv.dtype)


@op("sequence_expand", infer_shape=_seq_expand_infer, non_diff_inputs=("Y",))
def _sequence_expand(ctx, op_, ins):
    """Broadcast each batch row of x across y's time steps (reference
    sequence_expand_op.cc). Padded-case supported: x [B,D] (one row per
    sequence) -> out [B,Ty,D] masked to y's lengths. This covers the
    encoder-state-to-decoder-steps pattern (machine_translation)."""
    x = jnp.asarray(ins["X"][0])
    y = jnp.asarray(ins["Y"][0])
    ylen = _lengths(ctx, op_, "Y")
    t = y.shape[1]
    if x.ndim == 2:
        out = jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[1]))
    else:
        assert x.shape[1] == 1, (
            "padded sequence_expand supports one row per sequence in X")
        out = jnp.broadcast_to(x, (x.shape[0], t) + x.shape[2:])
    mask = _time_mask(ylen, t, x.shape[0]).astype(x.dtype)
    out = out * mask.reshape(mask.shape + (1,) * (out.ndim - 2))
    for name in op_.desc.outputs.get("Out", []):
        ctx.set_seq_len(name, ylen)
    return {"Out": [out]}


def _seq_conv_infer(op_, block):
    xv = in_var(op_, block, "X")
    fv = in_var(op_, block, "Filter")
    if xv is None or xv.shape is None or fv is None or fv.shape is None:
        return
    set_out(op_, block, "Out", list(xv.shape[:2]) + [fv.shape[1]], xv.dtype)


@op("sequence_conv", infer_shape=_seq_conv_infer)
def _sequence_conv(ctx, op_, ins):
    """Context-window convolution over time (reference sequence_conv_op.cc,
    math/context_project.h): for each t, concat rows
    [t+start, t+start+len) (zero beyond bounds/length) then project by
    Filter [len*D, M]. Lowered as k shifted copies + one MXU matmul."""
    x = jnp.asarray(ins["X"][0])              # [B, T, D]
    filt = jnp.asarray(ins["Filter"][0])      # [k*D, M]
    k = int(op_.attr("contextLength", 3))
    start = int(op_.attr("contextStart", -((k - 1) // 2)))
    lengths = _lengths(ctx, op_, "X")
    bsz, t, d = x.shape
    mask = _time_mask(lengths, t, bsz).astype(x.dtype)[..., None]
    xm = x * mask
    cols = []
    for j in range(k):
        shift = start + j
        if shift < 0:
            shifted = jnp.pad(xm, ((0, 0), (-shift, 0), (0, 0)))[:, :t]
        elif shift > 0:
            shifted = jnp.pad(xm, ((0, 0), (0, shift), (0, 0)))[:, shift:]
        else:
            shifted = xm
        cols.append(shifted)
    ctxmat = jnp.concatenate(cols, axis=-1)     # [B, T, k*D]
    out = (ctxmat @ filt) * mask
    return {"Out": [out]}


@op("sequence_concat", infer_shape=None)
def _sequence_concat(ctx, op_, ins):
    """Concatenate sequences instance-wise along time (reference
    sequence_concat_op.cc). Padded lowering: shift each input to start at
    the running length offset and sum."""
    xs = [jnp.asarray(v) for v in ins["X"]]
    names = op_.desc.inputs["X"]
    lens = [ctx.seq_len(n) for n in names]
    bsz = xs[0].shape[0]
    total_t = sum(v.shape[1] for v in xs)
    # zero each input's padded region first: upstream ops (e.g. bias add)
    # may have written non-zeros there, and the shift-and-sum below lands
    # later sequences exactly where earlier inputs' padding sits
    xs = [v if l is None else
          v * _time_mask(l, v.shape[1], bsz).astype(v.dtype).reshape(
              (bsz, v.shape[1]) + (1,) * (v.ndim - 2))
          for v, l in zip(xs, lens)]
    full = [jnp.pad(v, ((0, 0), (0, total_t - v.shape[1]))
                    + ((0, 0),) * (v.ndim - 2)) for v in xs]
    out = full[0]
    offset = lens[0] if lens[0] is not None else jnp.full(
        (bsz,), xs[0].shape[1], jnp.int32)
    for v, l, orig in zip(full[1:], lens[1:], xs[1:]):
        t = v.shape[1]
        idx = jnp.arange(t)[None, :] - offset[:, None]     # gather source pos
        valid = idx >= 0
        idx = jnp.clip(idx, 0, t - 1)
        shifted = jnp.take_along_axis(
            v, idx.reshape((bsz, t) + (1,) * (v.ndim - 2)), axis=1)
        shifted = jnp.where(
            valid.reshape((bsz, t) + (1,) * (v.ndim - 2)), shifted, 0)
        out = out + shifted
        li = l if l is not None else jnp.full((bsz,), orig.shape[1], jnp.int32)
        offset = offset + li
    for name in op_.desc.outputs.get("Out", []):
        ctx.set_seq_len(name, offset)
    return {"Out": [out]}


@op("sequence_reshape", infer_shape=None)
def _sequence_reshape(ctx, op_, ins):
    """Change the feature dim, scaling lengths (reference
    sequence_reshape_op.cc): [B,T,D] -> [B,T*D/new_dim, new_dim]."""
    x = jnp.asarray(ins["X"][0])
    new_dim = int(op_.attr("new_dim"))
    bsz, t, d = x.shape
    assert (t * d) % new_dim == 0
    out = x.reshape(bsz, t * d // new_dim, new_dim)
    lengths = _lengths(ctx, op_, "X")
    for name in op_.desc.outputs.get("Out", []):
        ctx.set_seq_len(
            name, None if lengths is None
            else (jnp.asarray(lengths) * d) // new_dim)
    return {"Out": [out]}


@op("sequence_slice", infer_shape=None, non_diff_inputs=("Offset", "Length"))
def _sequence_slice(ctx, op_, ins):
    """Per-sequence slice (reference sequence_slice_op.cc): take
    [offset_i, offset_i+length_i) from each sequence."""
    x = jnp.asarray(ins["X"][0])
    offset = jnp.asarray(ins["Offset"][0]).reshape(-1).astype(jnp.int32)
    length = jnp.asarray(ins["Length"][0]).reshape(-1).astype(jnp.int32)
    bsz, t = x.shape[0], x.shape[1]
    in_len = ctx.seq_len(op_.desc.inputs["X"][0])
    avail = (jnp.asarray(in_len).astype(jnp.int32) if in_len is not None
             else jnp.full((bsz,), t, jnp.int32))
    # the reference errors on offset+length beyond the sequence; inside a
    # traced computation we clamp the effective length instead of fabricating
    # rows from clamped gather indices
    eff_len = jnp.clip(jnp.minimum(length, avail - offset), 0, t)
    idx = jnp.arange(t)[None, :] + offset[:, None]
    idx = jnp.clip(idx, 0, t - 1)
    out = jnp.take_along_axis(
        x, idx.reshape((bsz, t) + (1,) * (x.ndim - 2)), axis=1)
    mask = (jnp.arange(t)[None, :] < eff_len[:, None])
    out = jnp.where(mask.reshape((bsz, t) + (1,) * (x.ndim - 2)), out, 0)
    for name in op_.desc.outputs.get("Out", []):
        ctx.set_seq_len(name, eff_len)
    return {"Out": [out]}


@op("sequence_erase", infer_shape=None, grad=NO_GRAD)
def _sequence_erase(ctx, op_, ins):
    """Remove tokens in `tokens` from each int sequence (reference
    sequence_erase_op.cc). Padded lowering keeps shape: kept tokens are
    left-compacted via a stable sort on removal flags."""
    x = jnp.asarray(ins["X"][0])
    tokens = jnp.asarray(op_.attr("tokens", []) or [], dtype=x.dtype)
    v = x.reshape(x.shape[0], x.shape[1])    # [B, T] int ids
    lengths = _lengths(ctx, op_, "X")
    bsz, t = v.shape
    inlen_mask = _time_mask(lengths, t, bsz).astype(bool)
    erase = jnp.isin(v, tokens) | ~inlen_mask
    keys = jnp.where(erase, 1, 0)
    order = jnp.argsort(keys, axis=1, stable=True)
    out = jnp.take_along_axis(v, order, axis=1)
    new_len = (~erase).sum(axis=1).astype(jnp.int32)
    pos_mask = jnp.arange(t)[None, :] < new_len[:, None]
    out = jnp.where(pos_mask, out, 0)
    if x.ndim == 3:
        out = out[..., None]
    for name in op_.desc.outputs.get("Out", []):
        ctx.set_seq_len(name, new_len)
    return {"Out": [out]}


@op("lod_reset", infer_shape=None, non_diff_inputs=("Y",))
def _lod_reset(ctx, op_, ins):
    """Re-partition a sequence batch under a new LoD (reference
    lod_reset_op.cc). With a static attr target_lod the padded rows are
    physically regrouped: valid rows compact to the front (stable sort on
    the padding mask) and re-split by the new offsets — static output
    shape, traced old lengths. With a traced Y offsets input only the
    lengths channel changes (partitions must then be compatible with the
    existing padding)."""
    x = jnp.asarray(ins["X"][0])
    if ins.get("Y") and ins["Y"][0] is not None:
        y = jnp.asarray(ins["Y"][0]).reshape(-1).astype(jnp.int32)
        lengths = y[1:] - y[:-1]   # offsets -> lengths
        for name in op_.desc.outputs.get("Out", []):
            ctx.set_seq_len(name, lengths)
        return {"Out": [x]}
    import numpy as _np
    offs = _np.asarray(op_.attr("target_lod", []), dtype=_np.int32)
    new_lens = offs[1:] - offs[:-1]
    name_x = op_.desc.inputs["X"][0]
    old = ctx.seq_len(name_x)
    if x.ndim >= 2 and old is not None:
        b, t = x.shape[0], x.shape[1]
        valid = jnp.arange(t)[None, :] < jnp.asarray(old)[:, None]
        flat_valid = valid.reshape(-1)
        order = jnp.argsort(jnp.where(flat_valid, 0, 1), stable=True)
        flat_rows = x.reshape((b * t,) + tuple(x.shape[2:]))[order]
        b2, t2 = len(new_lens), int(new_lens.max()) if len(new_lens) else 1
        idx = _np.zeros((b2, t2), dtype=_np.int32)
        for i in range(b2):
            for j in range(t2):
                idx[i, j] = offs[i] + min(j, max(int(new_lens[i]) - 1, 0))
        out = flat_rows[jnp.asarray(idx.reshape(-1))].reshape(
            (b2, t2) + tuple(x.shape[2:]))
        mask = (_np.arange(t2)[None, :] <
                new_lens[:, None]).reshape((b2, t2) + (1,) * (x.ndim - 2))
        out = out * jnp.asarray(mask, dtype=x.dtype)
    else:
        out = x
    lengths = jnp.asarray(new_lens)
    for name in op_.desc.outputs.get("Out", []):
        ctx.set_seq_len(name, lengths)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# Nested (level-2) LoD plumbing
# ---------------------------------------------------------------------------

def _unfold_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None and xv.shape is not None and len(xv.shape) >= 2:
        b, sdim = xv.shape[0], xv.shape[1]
        lead = None if (b is None or b < 0 or sdim is None or sdim < 0) \
            else b * sdim
        set_out(op_, block, "Out", [lead if lead is not None else -1]
                + list(xv.shape[2:]), xv.dtype)


@op("sequence_unfold", grad=None, infer_shape=_unfold_infer)
def _sequence_unfold(ctx, op_, ins):
    """Nested batch [B, S, T, ...] -> flat sub-sequence batch [B*S, T, ...]
    whose @SEQLEN is the flattened inner lengths (0 for padded sub-slots).
    The TPU-native entry to level-2 LoD (reference lod_tensor.h:55 nested
    offsets, RecurrentGradientMachine.h:32 nested step semantics): inner
    sequence ops then run masked over the flattened batch, and
    sequence_fold restores the outer grouping."""
    x = jnp.asarray(ins["X"][0])
    b, s = x.shape[0], x.shape[1]
    name = op_.desc.inputs["X"][0]
    inner = ctx.seq_len2(name)
    outer = ctx.seq_len(name)
    if inner is None:
        inner = jnp.full((b, s), x.shape[2], jnp.int32)
        if outer is not None:
            inner = jnp.where(
                jnp.arange(s)[None, :] < jnp.asarray(outer)[:, None],
                inner, 0)
    out = x.reshape((b * s,) + tuple(x.shape[2:]))
    out_name = op_.desc.outputs["Out"][0]
    ctx.set_seq_len(out_name, jnp.asarray(inner).reshape(-1))
    ctx.set_seq_len2(out_name, None)
    return {"Out": [out]}


def _fold_infer(op_, block):
    xv = in_var(op_, block, "X")
    lv = in_var(op_, block, "OuterLike")
    if xv is not None and xv.shape is not None and lv is not None \
            and lv.shape is not None and len(lv.shape) >= 2:
        set_out(op_, block, "Out",
                [lv.shape[0], lv.shape[1]] + list(xv.shape[1:]), xv.dtype)


@op("sequence_fold", grad=None, non_diff_inputs=("OuterLike",),
    infer_shape=_fold_infer)
def _sequence_fold(ctx, op_, ins):
    """Inverse of sequence_unfold: [B*S, ...] -> [B, S, ...], restoring the
    outer lengths channel from OuterLike (the original nested var)."""
    x = jnp.asarray(ins["X"][0])
    like_name = op_.desc.inputs["OuterLike"][0]
    like = jnp.asarray(ins["OuterLike"][0])
    b, s = like.shape[0], like.shape[1]
    out = x.reshape((b, s) + tuple(x.shape[1:]))
    out_name = op_.desc.outputs["Out"][0]
    ctx.set_seq_len(out_name, ctx.seq_len(like_name))
    inner = None
    # inner lengths only survive if the folded payload still has a time axis
    if out.ndim >= 3 and ctx.seq_len2(like_name) is not None:
        il = jnp.asarray(ctx.seq_len2(like_name))
        if out.shape[2] == jnp.asarray(ins["OuterLike"][0]).shape[2]:
            inner = il
    ctx.set_seq_len2(out_name, inner)
    return {"Out": [out]}


def _context_project_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None and xv.shape is not None:
        shape = list(xv.shape)
        shape[-1] = shape[-1] * op_.attr("context_length", 1)
        set_out(op_, block, "Out", shape, xv.dtype)


@op("context_project", infer_shape=_context_project_infer)
def _context_project(ctx, op_, ins):
    """Concatenate a window of neighboring timesteps onto the feature
    axis (reference gserver ContextProjection / trainer_config_helpers
    context_projection): out[:, t] = [x[:, t+s], ..., x[:, t+s+L-1]] with
    s = context_start, zero-padded outside each sequence. Linear in x, so
    the generic vjp gives the exact gradient; per-sequence boundaries
    come from the lengths side channel (padded-LoD convention)."""
    x = jnp.asarray(ins["X"][0])                 # [B, T, D]
    start = op_.attr("context_start", 0)
    length = op_.attr("context_length", 1)
    b, t, d = x.shape
    lengths = _lengths(ctx, op_, "X")
    steps = jnp.arange(t)[None, :]               # [1, T]
    if lengths is None:
        valid = jnp.ones((b, t), bool)
    else:
        valid = steps < jnp.asarray(lengths)[:, None]
    # zero out padding rows first so shifts can never leak garbage
    x = jnp.where(valid[..., None], x, 0.0)
    pieces = []
    for k in range(length):
        shift = start + k                        # source offset per step
        if shift < 0:
            shifted = jnp.pad(x, ((0, 0), (-shift, 0), (0, 0)))[:, :t]
        elif shift > 0:
            shifted = jnp.pad(x, ((0, 0), (0, shift), (0, 0)))[:, shift:]
        else:
            shifted = x
        # window positions past a sequence's end contribute zeros
        src_ok = valid if lengths is None else \
            ((steps + shift >= 0)
             & (steps + shift < jnp.asarray(lengths)[:, None]))
        pieces.append(jnp.where(src_ok[..., None], shifted, 0.0))
    return {"Out": [jnp.concatenate(pieces, axis=-1)]}


@op("sequence_mask", grad=NO_GRAD)
def _sequence_mask(ctx, op_, ins):
    """Dense [B, T] validity mask from a padded sequence var's lengths
    channel (the padded-LoD equivalent of reading the LoD offset table,
    reference lod_tensor.h:55; the mask is what sequence_softmax/rnn
    lowerings use internally — this op exposes it to user programs, e.g.
    attention over encoder states in a beam-search decoder)."""
    x = jnp.asarray(ins["X"][0])
    name = op_.desc.inputs["X"][0]
    t = x.shape[1]
    lengths = ctx.seq_len(name)
    if lengths is None:
        mask = jnp.ones(x.shape[:2], jnp.float32)
    else:
        steps = jnp.arange(t)[None, :]
        mask = (steps < jnp.asarray(lengths)[:, None]).astype(jnp.float32)
    return {"Y": [mask]}
