"""Internal activation-layout convention for the image path.

The user-visible layout is NCHW (reference conv_op.cc semantics), but the
TPU MXU wants channels on the minor axis. Instead of transposing around
every conv (which batch_norm/pool2d running NCHW in between kept XLA from
cancelling), the executor tracks a per-variable layout *tag* during the
trace: convs produce NHWC-tagged values, layout-aware ops (batch_norm,
pool2d) consume and propagate them, layout-agnostic elementwise ops pass
tags through, and any other consumer forces the value back to canonical
NCHW first (the "barrier"). Net effect: one NCHW->NHWC transpose where an
image enters the conv stack and one back where it leaves (usually the
global-pool -> fc boundary) — the TPU-native equivalent of the reference's
data_layout_transform pass (framework/data_layout_transform.cc), applied
at trace time instead of graph-rewrite time.

Gradient consistency falls out of the name-keyed tags: the generic vjp
grad kernel (ops/registry.py) re-traces the forward lowering against the
same tag state, cotangents are aligned to the layout of their forward
value before the vjp, and produced grads inherit the forward var's tag.
"""

from __future__ import annotations

import os

import jax.numpy as jnp

# default ON; PADDLE_TPU_NHWC=0 restores per-conv transposes
LAYOUT_OPT = os.environ.get("PADDLE_TPU_NHWC", "1") == "1"

NHWC = "NHWC"      # 4-D image activations
NDHWC = "NDHWC"    # 5-D volumetric activations

# ops whose lowerings read/write layout tags themselves
AWARE_OPS = {
    "conv2d", "depthwise_conv2d", "conv2d_transpose", "conv3d",
    "batch_norm", "pool2d", "prelu",
}

# elementwise ops that preserve layout: values pass through untouched and
# the tag propagates to same-rank outputs (their generic vjp grads are
# consistent because the cotangent is aligned to the forward value)
AGNOSTIC_OPS = {
    "relu", "relu6", "leaky_relu", "elu", "sigmoid", "tanh", "abs",
    "square", "sqrt", "exp", "log", "clip", "scale", "cast", "dropout",
    "dropout_grad", "pow", "softsign", "softplus", "round", "floor",
    "ceil", "hard_sigmoid", "brelu", "soft_relu", "swish",
    "sum", "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
}

_TO_CANON = {NHWC: (0, 3, 1, 2), NDHWC: (0, 4, 1, 2, 3)}
_FROM_CANON = {NHWC: (0, 2, 3, 1), NDHWC: (0, 2, 3, 4, 1)}
_RANK = {NHWC: 4, NDHWC: 5}


def to_canonical(val, tag):
    """Tagged-layout value -> canonical NCHW/NCDHW."""
    return jnp.transpose(jnp.asarray(val), _TO_CANON[tag])


def from_canonical(val, tag):
    """Canonical NCHW/NCDHW value -> tagged layout."""
    return jnp.transpose(jnp.asarray(val), _FROM_CANON[tag])


def tag_rank(tag) -> int:
    return _RANK[tag]


def _grad_base(name: str):
    """'x@GRAD' / 'x@GRAD@RENAME@b0@0' -> 'x'; None for non-grad names."""
    i = name.find("@GRAD")
    return name[:i] if i >= 0 else None


def _aware_retrace_tag(base, op, layouts):
    """Layout an aware op's forward lowering emits for its primary output
    when re-traced against the CURRENT tag state (the vjp re-trace in the
    generic grad kernel). Convs always emit the TPU layout; pool/bn follow
    their input's tag. Returns (output_slot, tag)."""
    if base in ("conv2d", "depthwise_conv2d", "conv2d_transpose"):
        return "Output", NHWC
    if base == "conv3d":
        return "Output", NDHWC
    if base == "pool2d":
        t = layouts.get(op.desc.inputs.get("X", [""])[0])
        return "Out", t if t == NHWC else None
    if base == "batch_norm":
        t = layouts.get(op.desc.inputs.get("X", [""])[0])
        return "Y", t if t in (NHWC, NDHWC) else None
    if base == "prelu":
        t = layouts.get(op.desc.inputs.get("X", [""])[0])
        return "Out", t if t in (NHWC, NDHWC) else None
    return None, None


def align_cotangents(layouts, op, env, want_overrides=None):
    """Before a grad op runs, bring each `<slot>@GRAD` input to the layout
    the vjp's forward re-trace will produce for that output — by default
    the forward value's current tag; aware ops pass explicit overrides
    (their re-trace layout is a function of the op, not the possibly
    barrier-cleared output tag)."""
    for slot, names in op.desc.inputs.items():
        if not slot.endswith("@GRAD"):
            continue
        base_slot = slot[: -len("@GRAD")]
        fwd_names = op.desc.inputs.get(base_slot, [])
        for gname, fname in zip(names, fwd_names):
            if want_overrides and base_slot in want_overrides:
                want = want_overrides[base_slot]
            else:
                want = layouts.get(fname)
            have = layouts.get(gname)
            if want == have:
                continue
            val = env.get(gname)
            if val is None or getattr(val, "ndim", 0) != _RANK[want or have]:
                continue
            if have is not None:
                val = to_canonical(val, have)
                layouts.pop(gname, None)
            if want is not None:
                val = from_canonical(val, want)
                layouts[gname] = want
            env[gname] = val


def _elementwise_tag_ok(op, env, tag):
    """Layout-tag pass-through is safe for an elementwise op iff broadcast
    semantics are unaffected: equal shapes, scalar Y, or the channel-bias
    form (axis==1, 1-D Y) which the lowering remaps to the minor axis."""
    if op.type == "sum" or not op.type.startswith("elementwise_"):
        return True
    ynames = op.desc.inputs.get("Y", [])
    y = env.get(ynames[0]) if ynames else None
    if y is None:
        return True
    xnames = op.desc.inputs.get("X", [])
    x = env.get(xnames[0]) if xnames else None
    if x is None:
        return False
    if getattr(y, "ndim", 0) == 0 or getattr(y, "shape", None) == x.shape:
        return True
    axis = op.attr("axis", -1)
    return axis == 1 and getattr(y, "ndim", 0) == 1


def prepass(layouts, op, op_type, env):
    """Called by the executor before lowering `op`. Enforces the invariant
    that every env value's layout matches its tag: unaware consumers get
    tagged inputs canonicalized in place (the barrier); agnostic consumers
    pass through when all same-rank inputs share one tag. Returns the tag
    to propagate to the op's outputs (None = no propagation)."""
    base = op_type[: -len("_grad")] if op_type.endswith("_grad") \
        else op_type
    if base in AWARE_OPS:
        # runs even with no live tags: conv lowerings emit the TPU layout
        # unconditionally, so their cotangents always need aligning
        if op_type.endswith("_grad"):
            out_slot, tag = _aware_retrace_tag(base, op, layouts)
            align_cotangents(layouts, op, env,
                             want_overrides={out_slot: tag}
                             if out_slot else None)
        return None    # aware lowerings manage tags themselves
    if not layouts:
        return None
    in_names = [n for names in op.desc.inputs.values() for n in names]
    tags = {layouts[n] for n in in_names if n in layouts}
    if not tags:
        return None
    if base in AGNOSTIC_OPS and len(tags) == 1:
        tag = next(iter(tags))
        rank = _RANK[tag]
        # every input of the tag's rank must carry the tag — an untagged
        # same-rank operand would be in a different layout
        uniform = all(
            layouts.get(n) == tag
            for n in in_names
            if getattr(env.get(n), "ndim", None) == rank)
        if uniform and _elementwise_tag_ok(op, env, tag):
            if op_type.endswith("_grad"):
                align_cotangents(layouts, op, env)
            return tag
    # barrier: canonicalize tagged inputs in place
    for n in in_names:
        tag = layouts.pop(n, None)
        if tag is not None and env.get(n) is not None:
            env[n] = to_canonical(env[n], tag)
    if op_type.endswith("_grad"):
        align_cotangents(layouts, op, env)
    return None


def tag_outputs(layouts, op, env, propagate_tag, overrides):
    """After an op runs: aware-lowering overrides (ctx.set_layout) win;
    agnostic outputs inherit the propagated tag; a true grad op's
    `<base>@GRAD*` outputs inherit the forward var's current tag (the
    aligned vjp produced them in that layout — this does NOT hold for
    plain forward ops a custom grad maker re-emits in the backward pass,
    e.g. cast-grad-as-cast, which follow normal propagation); everything
    else clears any stale tag (names can be rewritten)."""
    is_grad_op = op.type.endswith("_grad")
    in_names = {n for ns in op.desc.inputs.values() for n in ns} \
        if is_grad_op else ()
    for names in op.desc.outputs.values():
        for name in names:
            val = env.get(name)
            if val is None:
                continue
            if name in overrides:
                tag = overrides[name]
                if tag is None:
                    layouts.pop(name, None)
                else:
                    layouts[name] = tag
                continue
            # a vjp-produced grad matches the layout of the forward value
            # the vjp consumed — which requires that forward var to BE an
            # input of this grad op (custom grad lowerings that never see
            # the forward var, e.g. dropout_grad, compute in their own
            # inputs' layout and follow normal propagation instead)
            gb = _grad_base(name) if is_grad_op else None
            if gb is not None and gb not in in_names:
                gb = None
            if gb is not None:
                gt = layouts.get(gb)
                if gt is not None and getattr(val, "ndim", 0) == _RANK[gt]:
                    layouts[name] = gt
                else:
                    layouts.pop(name, None)
            elif propagate_tag is not None and \
                    getattr(val, "ndim", 0) == _RANK[propagate_tag]:
                layouts[name] = propagate_tag
            else:
                layouts.pop(name, None)


def canonicalize(layouts, env, names):
    """Force the given env entries back to canonical layout (fetch /
    persistable-state boundary)."""
    for n in names:
        tag = layouts.get(n)
        if tag is not None and env.get(n) is not None:
            env[n] = to_canonical(env[n], tag)
            layouts.pop(n, None)
