"""Shared helpers for op lowerings and shape inference."""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np


def to_np_dtype(name: str):
    if name == "bfloat16":
        return jnp.bfloat16
    return np.dtype(name)


def broadcast_y_to_x(x, y, axis: int):
    """Paddle elementwise broadcast: align y's dims to x starting at `axis`
    (reference: operators/elementwise_op_function.h). axis==-1 means align to
    the trailing dims."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if y.ndim == 0 or x.shape == y.shape:
        return y
    # Paddle allows a trailing run of size-1 dims in y beyond the aligned
    # region (e.g. x:(N,C), y:(N,1) with axis=0); squeeze them so the
    # alignment fits.
    if axis == -1:
        axis = x.ndim - y.ndim
        while axis < 0 and y.shape[-1] == 1:
            y = y.reshape(y.shape[:-1])
            axis += 1
    else:
        while axis + y.ndim > x.ndim and y.shape[-1] == 1:
            y = y.reshape(y.shape[:-1])
    assert axis >= 0 and axis + y.ndim <= x.ndim, (
        f"cannot broadcast y{tuple(y.shape)} to x{tuple(x.shape)} at axis {axis}")
    new_shape = (1,) * axis + tuple(y.shape) + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


# --- shape inference helpers ------------------------------------------------

def out_var(op, block, slot="Out", idx=0):
    names = op.desc.outputs.get(slot, [])
    if idx >= len(names):
        return None
    name = names[idx]
    return block.desc.vars.get(name) or _find_up(block, name)


def in_var(op, block, slot="X", idx=0):
    names = op.desc.inputs.get(slot, [])
    if idx >= len(names):
        return None
    return _find_up(block, names[idx])


def _find_up(block, name):
    b = block
    while b is not None:
        if b.desc.has_var(name):
            return b.desc.var(name)
        b = b.parent_block
    return None


def set_out(op, block, slot, shape, dtype):
    v = out_var(op, block, slot)
    if v is not None:
        v.shape = list(shape) if shape is not None else None
        if dtype is not None:
            v.dtype = dtype


def same_as_input(in_slot="X", out_slot="Out"):
    def infer(op, block):
        iv = in_var(op, block, in_slot)
        if iv is not None:
            set_out(op, block, out_slot, iv.shape, iv.dtype)
    return infer


def elementwise_infer(op, block):
    xv = in_var(op, block, "X")
    if xv is not None:
        set_out(op, block, "Out", xv.shape, xv.dtype)


def matmul_shape(xs: Optional[List[int]], ys: Optional[List[int]],
                 tx: bool, ty: bool) -> Optional[List[int]]:
    if xs is None or ys is None:
        return None
    xs, ys = list(xs), list(ys)
    if len(xs) == 1:
        xs = [1, xs[0]]
    if len(ys) == 1:
        ys = [ys[0], 1]
    if tx:
        xs[-2], xs[-1] = xs[-1], xs[-2]
    if ty:
        ys[-2], ys[-1] = ys[-1], ys[-2]
    batch = xs[:-2] or ys[:-2]
    return batch + [xs[-2], ys[-1]]
