"""Shared helpers for op lowerings and shape inference."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class SelectedRowsVal:
    """Sparse-rows gradient value: the TPU-native SelectedRows
    (reference: framework/selected_rows.h:19). `rows` may repeat (like the
    reference's unmerged SelectedRows); consumers either scatter-add
    (sparse optimizer update touching only K rows of the table) or
    densify. Static `height` is the dense row count of the full table."""
    rows: Any          # int32 [K]
    values: Any        # [K, D...]
    height: int

    def to_dense(self):
        dense = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                          self.values.dtype)
        return dense.at[self.rows].add(self.values)


jax.tree_util.register_pytree_node(
    SelectedRowsVal,
    lambda v: ((v.rows, v.values), v.height),
    lambda h, ch: SelectedRowsVal(ch[0], ch[1], h))


def maybe_dense(v, count_as: Optional[str] = None):
    """Densify a SelectedRowsVal (identity otherwise). Pass `count_as`
    (a site label like "fetch") to record the densification in
    sparse_densify_fallback_total — silent call sites are the perf
    cliffs ISSUE 10's counters exist to surface."""
    if isinstance(v, SelectedRowsVal):
        if count_as is not None:
            from . import sparse_ops
            sparse_ops.count_densify(count_as, "densified_at_" + count_as)
        return v.to_dense()
    return v


def merge_selected_rows(sr: "SelectedRowsVal"):
    """Merge duplicate rows by summation (reference
    operators/math/selected_rows_functor.cc MergeAdd), keeping shapes
    static: returns (rows [K], values [K, D...]) where duplicates are
    summed into their first slot and freed slots carry row index =
    height (out of range, so scatters drop them and gathers clamp
    harmlessly). Cost O(K log K + K*D) — never materializes the dense
    table, which is the point of the sparse optimizer path."""
    rows = jnp.asarray(sr.rows)
    vals = jnp.asarray(sr.values)
    k = rows.shape[0]
    order = jnp.argsort(rows)
    r_s = rows[order]
    v_s = vals[order]
    is_new = jnp.concatenate(
        [jnp.ones((1,), bool), r_s[1:] != r_s[:-1]])
    seg = jnp.cumsum(is_new) - 1                       # [K] in [0, K)
    merged_vals = jax.ops.segment_sum(v_s, seg, num_segments=k)
    merged_rows = jnp.full((k,), sr.height, rows.dtype).at[seg].set(r_s)
    return merged_rows, merged_vals


def to_np_dtype(name: str):
    if name == "bfloat16":
        return jnp.bfloat16
    return np.dtype(name)


def mxu_cast(ctx, *xs):
    """Mixed-precision policy hook for MXU-bound ops (matmul/conv).

    Under AMP (program._amp_dtype, see paddle_tpu/amp.py) float32 operands
    are cast to the compute dtype (bfloat16 → the MXU's native input type);
    the call site casts the op result back via the returned restore dtype,
    so everything downstream (BN statistics, losses, optimizer updates on
    fp32 master weights) stays float32. On TPU the MXU accumulates bf16
    products in fp32 internally, but the op's *stored* output is bf16 and
    is then upcast — each output element is rounded to bf16 once (the same
    rounding the operands already took; `preferred_element_type=f32` is NOT
    used because this jax version's conv transpose rule rejects mixed
    bf16-operand/f32-cotangent convs). The generic vjp-backed grad ops
    re-trace this lowering, so backward matmuls/convs run bf16 too (the
    astype vjp casts cotangents bf16-ward on entry and back to fp32 toward
    the weights).

    TPU-native replacement for the reference's fp16 story
    (reference: paddle/fluid/platform/float16.h:64) — on TPU the low-precision
    type is bf16 and no loss scaling is needed (bf16 keeps f32's exponent).

    Returns (cast_operands_tuple, restore_dtype_or_None); call sites do
    `out = out.astype(restore) if restore is not None else out`.

    Under level O2 the restore dtype is None even after casting: activations
    stay bf16 end-to-end (halving HBM traffic — the dominant cost on
    bandwidth-bound chips); norm/loss lowerings locally upcast where
    statistics need f32. O3 is O2 on this axis (bf16 activations; the
    quantized routing happens downstream of this cast in the matmul/conv
    lowerings), so gating quantization off restores O2 numerics exactly.
    """
    amp = getattr(ctx, "amp_dtype", None)
    if not amp:
        return xs, None
    cd = jnp.dtype(amp)
    casted = tuple(x.astype(cd) if x.dtype == jnp.float32 else x for x in xs)
    if getattr(ctx, "amp_level", "O1") in ("O2", "O3"):
        return casted, None
    any_cast = any(c is not x for c, x in zip(casted, xs))
    return casted, (jnp.float32 if any_cast else None)


def broadcast_y_to_x(x, y, axis: int):
    """Paddle elementwise broadcast: align y's dims to x starting at `axis`
    (reference: operators/elementwise_op_function.h). axis==-1 means align to
    the trailing dims."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if y.ndim == 0 or x.shape == y.shape:
        return y
    # Paddle allows a trailing run of size-1 dims in y beyond the aligned
    # region (e.g. x:(N,C), y:(N,1) with axis=0); squeeze them so the
    # alignment fits.
    if axis == -1:
        axis = x.ndim - y.ndim
        while axis < 0 and y.shape[-1] == 1:
            y = y.reshape(y.shape[:-1])
            axis += 1
    else:
        while axis + y.ndim > x.ndim and y.shape[-1] == 1:
            y = y.reshape(y.shape[:-1])
    assert axis >= 0 and axis + y.ndim <= x.ndim, (
        f"cannot broadcast y{tuple(y.shape)} to x{tuple(x.shape)} at axis {axis}")
    new_shape = (1,) * axis + tuple(y.shape) + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


def seq_lengths(ctx, op_, slot, batch, cap):
    """Valid per-sequence lengths for a padded input slot: the @SEQLEN side
    channel when the var is a LoD feed, else the full padded extent."""
    names = op_.desc.inputs.get(slot, [])
    lens = ctx.seq_len(names[0]) if names else None
    if lens is None:
        return jnp.full((batch,), cap, dtype=jnp.int32)
    return jnp.asarray(lens).astype(jnp.int32)


# --- shape inference helpers ------------------------------------------------

def out_var(op, block, slot="Out", idx=0):
    names = op.desc.outputs.get(slot, [])
    if idx >= len(names):
        return None
    name = names[idx]
    return block.desc.vars.get(name) or _find_up(block, name)


def in_var(op, block, slot="X", idx=0):
    names = op.desc.inputs.get(slot, [])
    if idx >= len(names):
        return None
    return _find_up(block, names[idx])


def _find_up(block, name):
    b = block
    while b is not None:
        if b.desc.has_var(name):
            return b.desc.var(name)
        b = b.parent_block
    return None


def set_out(op, block, slot, shape, dtype):
    v = out_var(op, block, slot)
    if v is not None:
        v.shape = list(shape) if shape is not None else None
        if dtype is not None:
            v.dtype = dtype


def same_as_input(in_slot="X", out_slot="Out"):
    def infer(op, block):
        iv = in_var(op, block, in_slot)
        if iv is not None:
            set_out(op, block, out_slot, iv.shape, iv.dtype)
    return infer


def elementwise_infer(op, block):
    xv = in_var(op, block, "X")
    if xv is not None:
        set_out(op, block, "Out", xv.shape, xv.dtype)


def matmul_shape(xs: Optional[List[int]], ys: Optional[List[int]],
                 tx: bool, ty: bool) -> Optional[List[int]]:
    if xs is None or ys is None:
        return None
    xs, ys = list(xs), list(ys)
    if len(xs) == 1:
        xs = [1, xs[0]]
    if len(ys) == 1:
        ys = [ys[0], 1]
    if tx:
        xs[-2], xs[-1] = xs[-1], xs[-2]
    if ty:
        ys[-2], ys[-1] = ys[-1], ys[-2]
    batch = xs[:-2] or ys[:-2]
    return batch + [xs[-2], ys[-1]]
