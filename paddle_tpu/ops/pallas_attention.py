"""Flash attention as a Pallas TPU kernel (the "pallas for the hot ops"
tier of the compute path; /opt/skills/guides/pallas_guide.md patterns).

Forward: online-softmax blocks — Q tiles stay resident in VMEM while K/V
tiles stream through as the innermost (sequential) grid dim, carrying the
running max/denominator in VMEM scratch, so the [T, T] score matrix never
materializes in HBM and VMEM use is O(tile) — T is unbounded (memory
O(T) end to end, same contract as parallel/ring_attention.py across chips
but within one core's VMEM).

Backward: the standard flash backward (FlashAttention-2 style) — the
forward saves only the per-row logsumexp (m + log l); the backward
recomputes score blocks in VMEM from (Q, K, LSE) and accumulates
dQ (one kernel, Q tiles resident, K/V streaming) and dK/dV (a second
kernel, K/V tiles resident, Q/dO streaming). Both kernels take global
(q_off, k_off) position offsets so the same code serves the single-device
path (offsets 0) and the per-shard blocks of the ring composition
(parallel/ring_attention.py flash_ring backward).

Layout: operands stay in the model's [B, T, H, D] — tiles span the FULL
(H, D) trailing dims (Mosaic-legal: equal to the array dims) and the
kernels loop heads in an unrolled Python loop, so no head-major transpose
copies bracket the kernels (they dominated wall time in transformer
training, where T is moderate and attention is called per layer).
Precision: dots take the input dtype (bf16 rides the MXU's half-precision
datapath) with f32 ACCUMULATION via preferred_element_type; softmax
statistics and scaling run in f32; P/dS are cast back to the input dtype
for their matmuls — the FlashAttention-2 recipe.

On CPU (the test mesh) the kernels run under the Pallas interpreter
(interpret=True) — same code path, no Mosaic compile. Shapes must tile:
T divisible by the block (128, or T itself when smaller; sublane-aligned
T % 8 == 0); callers fall back to attention_reference otherwise
(ops/nn_ops.py wiring).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "supports"]

_NEG = -1e30


def supports(q, k, v) -> bool:
    """Static-shape eligibility: [B, T, H, D] with T tileable and
    sublane-aligned (T % 8 == 0 — Mosaic tiles (8, 128) for f32)."""
    if q.ndim != 4 or q.shape != k.shape or q.shape != v.shape:
        return False
    t = q.shape[1]
    return t >= 8 and t % 8 == 0 and (t <= 128 or t % 128 == 0)


def _block(t: int) -> int:
    """Resident-side (Q in fwd/dq, K in dkv) tile rows. Default 128; the
    env knob grows it (power-of-two, must divide t) — larger resident
    tiles amortize per-block softmax-state updates and halve grid steps,
    at the cost of more VMEM per tile."""
    import os
    if t % 128 != 0:
        return t
    b = 128
    # 512 measured optimal on v5e at long T (r5 in-model sweep at
    # T=2048-8192: 128->512 took the transformer from 0.83x to 1.1-1.6x
    # OVER the XLA einsum path; 1024 exceeds the VMEM budget and fails to
    # compile). Below T=2048 the large tiles buy nothing and the embedded
    # compile has been observed to fail — keep the proven 128 there.
    default = "512" if t >= 2048 else "128"
    want = int(os.environ.get("PADDLE_TPU_FLASH_BLOCK_Q", default))
    while b * 2 <= want and t % (b * 2) == 0:
        b *= 2
    return b


def _block_k(t: int) -> int:
    """Streamed-side (K or Q) tile rows: larger tiles amortize MXU matmul
    setup — the per-block dots contract over D (= 64 typically), so the
    streamed dimension is the only one free to grow. Capped by an env
    knob for tuning; must divide t. 1024 measured optimal on v5e at long
    T (r5; 2048 fails the VMEM budget; below T=2048 keep the proven
    512)."""
    import os
    default = "1024" if t >= 2048 else "512"
    cap = int(os.environ.get("PADDLE_TPU_FLASH_BLOCK_K", default))
    b = _block(t)
    while b * 2 <= cap and t % (b * 2) == 0:
        b *= 2
    return b


def _interpret() -> bool:
    """Mosaic-compile only when actually lowering for TPU. The executor
    targets its place's device via jax.default_device — which
    jax.default_backend() ignores — so a CPUPlace run in a TPU-default
    process (the axon terminal) must still take the interpreter."""
    dev = jax.config.jax_default_device
    if dev is not None:
        platform = getattr(dev, "platform", None)
        if platform is not None:
            return platform != "tpu"
    return jax.default_backend() != "tpu"


def _compiler_params(semantics):
    """Declare grid-dimension semantics so Mosaic can overlap tile DMA
    with compute: "parallel" dims carry nothing across iterations;
    "arbitrary" marks the streamed innermost dim whose scratch
    accumulators DO carry. vmem_limit raised past the 16 MB default: the
    unrolled head loop keeps H tiles' intermediates live (v5e has 128 MB
    physical VMEM; 64 MB leaves headroom for double-buffered DMA)."""
    if _interpret():
        return None
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.CompilerParams(dimension_semantics=tuple(semantics),
                                vmem_limit_bytes=64 * 1024 * 1024)


def _scratch(shape):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, jnp.float32)


_SEM3 = ("parallel", "parallel", "arbitrary")


def _dot(a, b, dims):
    from jax import lax
    return lax.dot_general(a, b, (dims, ((), ())),
                           preferred_element_type=jnp.float32)


def _causal_mask(s, q_first, k_first, bq, bk):
    from jax import lax
    qpos = q_first + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = k_first + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(qpos >= kpos, s, _NEG)


def _fwd_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_sc, m_sc, l_sc, *, bq: int, bk: int, n_h: int,
                n_k: int, scale: float, causal: bool, normalize: bool):
    """Grid (B, n_q, n_k): Q tile [bq, H, D] resident, K/V tiles
    [bk, H, D] streamed innermost; unrolled head loop; (acc, m, l) carry
    in scratch with a leading head axis. normalize=True emits
    (softmax(S)V, LSE) — the single-device forward; normalize=False emits
    the raw (acc, m, l) — the per-shard block the ring merge consumes."""
    import jax.experimental.pallas as pl

    i = pl.program_id(1)
    j = pl.program_id(2)
    q_off = off_ref[0]
    k_off = off_ref[1]

    @pl.when(j == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        l_sc[...] = jnp.zeros_like(l_sc)

    def compute():
        # full-tile loads + value-level head slices: Mosaic's bf16 layout
        # inference rejects (1, rows, 1, d) ref-slice reshapes, and whole
        # tiles give it freedom to keep the packed layout
        qt = q_ref[0]                                 # [bq, H, D]
        kt = k_ref[0]
        vt = v_ref[0]
        for hh in range(n_h):
            q = qt[:, hh, :]                          # [bq, D]
            s = _dot(q, kt[:, hh, :], ((1,), (1,))) * scale
            if causal:
                s = _causal_mask(s, q_off + i * bq, k_off + j * bk, bq, bk)
            m_prev = m_sc[hh, :, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            corr = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, None])
            l_sc[hh, :, 0] = l_sc[hh, :, 0] * corr + jnp.sum(p, axis=-1)
            acc_sc[hh] = acc_sc[hh] * corr[:, None] + _dot(
                p.astype(q.dtype), vt[:, hh, :], ((1,), (0,)))
            m_sc[hh, :, 0] = m_new

    if causal:
        # K tiles strictly past this Q tile's last row are dead: skip the
        # MXU work (the tile DMA still streams — grids are static)
        pl.when(q_off + i * bq + (bq - 1) >= k_off + j * bk)(compute)
    else:
        compute()

    @pl.when(j == n_k - 1)
    def _finalize():
        outs, stats = [], []
        for hh in range(n_h):
            if normalize:
                l = l_sc[hh, :, 0]
                outs.append((acc_sc[hh] /
                             jnp.maximum(l, 1e-30)[:, None]))
                # per-row logsumexp of the scaled scores — the only
                # residual the flash backward needs beyond (q, k, v, o)
                stats.append((m_sc[hh, :, 0] +
                              jnp.log(jnp.maximum(l, 1e-30)))[:, None])
            else:
                outs.append(acc_sc[hh])
                stats.append(jnp.stack([m_sc[hh, :, 0], l_sc[hh, :, 0]],
                                       axis=1))
        o_ref[0] = jnp.stack(outs, axis=1).astype(o_ref.dtype)
        lse_ref[0] = jnp.stack(stats, axis=1)


def _vma_struct(like):
    vma = getattr(like, "aval", None)
    vma = getattr(vma, "vma", frozenset()) or frozenset()

    def out_struct(shape, dtype):
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:            # older jax: no vma kwarg
            return jax.ShapeDtypeStruct(shape, dtype)

    return out_struct


def _fwd_call(q, k, v, q_off, k_off, scale, causal, normalize):
    import jax.experimental.pallas as pl

    b, tq, h, d = q.shape
    tk = k.shape[1]
    bq = _block(min(tq, tk))
    bk = _block_k(tk)
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    out_struct = _vma_struct(q)
    stat_last = 1 if normalize else 2

    out, stats = pl.pallas_call(
        functools.partial(_fwd_kernel, bq=bq, bk=bk, n_h=h, n_k=tk // bk,
                          scale=float(scale), causal=causal,
                          normalize=normalize),
        grid=(b, tq // bq, tk // bk),
        in_specs=[
            pl.BlockSpec((2,), lambda bb, j, kk: (0,)),
            pl.BlockSpec((1, bq, h, d), lambda bb, j, kk: (bb, j, 0, 0)),
            pl.BlockSpec((1, bk, h, d), lambda bb, j, kk: (bb, kk, 0, 0)),
            pl.BlockSpec((1, bk, h, d), lambda bb, j, kk: (bb, kk, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, h, d), lambda bb, j, kk: (bb, j, 0, 0)),
            pl.BlockSpec((1, bq, h, stat_last),
                         lambda bb, j, kk: (bb, j, 0, 0)),
        ],
        out_shape=[
            out_struct((b, tq, h, d),
                       q.dtype if normalize else jnp.float32),
            out_struct((b, tq, h, stat_last), jnp.float32),
        ],
        scratch_shapes=[_scratch((h, bq, d)), _scratch((h, bq, 1)),
                        _scratch((h, bq, 1))],
        interpret=_interpret(),
        compiler_params=_compiler_params(_SEM3),
    )(offs, q, k, v)
    return out, stats


def _forward(q, k, v, causal, return_lse=False):
    d = q.shape[-1]
    scale = 1.0 / (d ** 0.5)
    out, lse = _fwd_call(q, k, v, 0, 0, scale, causal, normalize=True)
    if return_lse:
        # [B, T, H, 1] -> [B, H, T]: tiny (no D axis) transpose
        return out, lse[..., 0].transpose(0, 2, 1)
    return out


def flash_attention_block(q, k, v, q_off, k_off, scale, causal):
    """Per-shard flash block for ring attention: q [B,Tq,H,D] resident,
    k/v [B,Tk,H,D] visiting, global offsets as traced scalars. Returns
    (acc [B,Tq,H,D] unnormalized, l [B,H,Tq], m [B,H,Tq]) in f32 carries,
    matching parallel.ring_attention._block_attn's online-softmax form."""
    acc, stats = _fwd_call(q, k, v, q_off, k_off, scale, causal,
                           normalize=False)
    m = stats[..., 0].transpose(0, 2, 1)
    l = stats[..., 1].transpose(0, 2, 1)
    return acc, l, m


def block_supports(q, k) -> bool:
    tq, tk = q.shape[1], k.shape[1]
    blk = _block(min(tq, tk))
    return (q.ndim == 4 and tq % blk == 0 and tk % blk == 0
            and min(tq, tk) >= 8 and tq % 8 == 0 and tk % 8 == 0)


def _dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
               dq_ref, dq_sc, *, bq: int, bk: int, n_h: int, n_k: int,
               scale: float, causal: bool):
    """Grid (B, n_q, n_k), K/V STREAMED innermost (wide bk tiles) with a
    per-head dQ scratch carry. Recomputes P = exp(S - LSE) per block;
    dS = P*(dO V^T - delta); dQ = (sum_k dS K) * scale. Causal: K blocks
    fully past the Q tile's last row skip their MXU work."""
    import jax.experimental.pallas as pl

    i = pl.program_id(1)
    j = pl.program_id(2)
    q_off = off_ref[0]
    k_off = off_ref[1]

    @pl.when(j == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    def compute():
        qt = q_ref[0]
        kt = k_ref[0]
        vt = v_ref[0]
        dot_ = do_ref[0]
        lset = lse_ref[0].astype(jnp.float32)
        dlt = dl_ref[0].astype(jnp.float32)
        for hh in range(n_h):
            q = qt[:, hh, :]
            kb = kt[:, hh, :]
            s = _dot(q, kb, ((1,), (1,))) * scale
            if causal:
                s = _causal_mask(s, q_off + i * bq, k_off + j * bk, bq, bk)
            p = jnp.exp(s - lset[:, hh, :])
            dp = _dot(dot_[:, hh, :], vt[:, hh, :], ((1,), (1,)))
            ds = (p * (dp - dlt[:, hh, :])).astype(q.dtype)
            dq_sc[hh] = dq_sc[hh] + _dot(ds, kb, ((1,), (0,)))

    if causal:
        pl.when(q_off + i * bq + (bq - 1) >= k_off + j * bk)(compute)
    else:
        compute()

    @pl.when(j == n_k - 1)
    def _finalize():
        dq_ref[0] = jnp.stack([dq_sc[hh] * scale for hh in range(n_h)],
                              axis=1).astype(dq_ref.dtype)


def _dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, bq: int, bk: int,
                n_h: int, n_q: int, scale: float, causal: bool):
    """Grid (B, n_k, n_q), Q/dO/LSE/delta STREAMED innermost (wide bq
    tiles) with per-head dK/dV scratch carries. dV = sum_q P^T dO;
    dK = (sum_q dS^T Q) * scale. Causal: Q blocks fully before the K
    tile's first column skip their MXU work."""
    import jax.experimental.pallas as pl

    i = pl.program_id(1)   # k tile
    j = pl.program_id(2)   # q tile (streamed)
    q_off = off_ref[0]
    k_off = off_ref[1]

    @pl.when(j == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    def compute():
        kt = k_ref[0]
        vt = v_ref[0]
        qt = q_ref[0]
        dot_ = do_ref[0]
        lset = lse_ref[0].astype(jnp.float32)
        dlt = dl_ref[0].astype(jnp.float32)
        for hh in range(n_h):
            kb = kt[:, hh, :]
            qb = qt[:, hh, :]
            dob = dot_[:, hh, :]
            s = _dot(qb, kb, ((1,), (1,))) * scale
            if causal:
                s = _causal_mask(s, q_off + j * bq, k_off + i * bk, bq, bk)
            p = jnp.exp(s - lset[:, hh, :])
            dv_sc[hh] = dv_sc[hh] + _dot(p.astype(kb.dtype), dob,
                                         ((0,), (0,)))
            dp = _dot(dob, vt[:, hh, :], ((1,), (1,)))
            ds = (p * (dp - dlt[:, hh, :])).astype(kb.dtype)
            dk_sc[hh] = dk_sc[hh] + _dot(ds, qb, ((0,), (0,)))

    if causal:
        pl.when(q_off + j * bq + (bq - 1) >= k_off + i * bk)(compute)
    else:
        compute()

    @pl.when(j == n_q - 1)
    def _finalize():
        dk_ref[0] = jnp.stack([dk_sc[hh] * scale for hh in range(n_h)],
                              axis=1).astype(dk_ref.dtype)
        dv_ref[0] = jnp.stack([dv_sc[hh] for hh in range(n_h)],
                              axis=1).astype(dv_ref.dtype)


def flash_attention_bwd_block(q, k, v, do, lse, delta, q_off, k_off, scale,
                              causal):
    """Flash backward for one (Q shard, K/V shard) pair with global position
    offsets: q/do [B,Tq,H,D], k/v [B,Tk,H,D], lse/delta [B,H,Tq] (scaled-
    score logsumexp from the forward; delta = rowsum(dO*O)). Returns
    (dq, dk, dv) in the inputs' dtypes. Offsets (0, 0) with Tq == Tk == T
    is exactly the single-device flash backward; the ring backward calls it
    per visiting shard (parallel/ring_attention.py)."""
    import jax.experimental.pallas as pl

    b, tq, h, d = q.shape
    tk = k.shape[1]
    block = _block(min(tq, tk))
    assert tq % block == 0 and tk % block == 0, (
        f"flash_attention_bwd_block needs tileable shapes (tq={tq}, "
        f"tk={tk}, block={block}); gate callers with block_supports()")
    # resident tiles stay at `block`; the STREAMED side gets wide tiles
    # (dq streams K, dkv streams Q — see _block_k)
    bq_w = _block_k(tq)
    bk_w = _block_k(tk)
    # rows no shard ever validated carry lse = -inf (possible only for
    # non-causal corner cases); push them to +big so exp(s - lse) == 0 and
    # they contribute nothing to any gradient. Operands stay [B,T,H,D];
    # the row stats become [B,T,H,1] (tiny transposes — no D axis).
    lseh = jnp.where(jnp.isfinite(lse), lse, 1e30).astype(
        jnp.float32).transpose(0, 2, 1)[..., None]
    dlh = delta.astype(jnp.float32).transpose(0, 2, 1)[..., None]
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])

    interpret = _interpret()
    out_struct = _vma_struct(q)

    off_spec = pl.BlockSpec((2,), lambda bb, j, kk: (0,))

    def res_spec(rows, d_):
        return pl.BlockSpec((1, rows, h, d_),
                            lambda bb, j, kk: (bb, j, 0, 0))

    def stream_spec(rows, d_):
        return pl.BlockSpec((1, rows, h, d_),
                            lambda bb, j, kk: (bb, kk, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bq=block, bk=bk_w, n_h=h,
                          n_k=tk // bk_w, scale=float(scale),
                          causal=causal),
        grid=(b, tq // block, tk // bk_w),
        in_specs=[off_spec, res_spec(block, d), stream_spec(bk_w, d),
                  stream_spec(bk_w, d), res_spec(block, d),
                  res_spec(block, 1), res_spec(block, 1)],
        out_specs=res_spec(block, d),
        out_shape=out_struct((b, tq, h, d), q.dtype),
        scratch_shapes=[_scratch((h, block, d))],
        interpret=interpret,
        compiler_params=_compiler_params(_SEM3),
    )(offs, q, k, v, do, lseh, dlh)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bq=bq_w, bk=block, n_h=h,
                          n_q=tq // bq_w, scale=float(scale),
                          causal=causal),
        grid=(b, tk // block, tq // bq_w),
        in_specs=[off_spec, stream_spec(bq_w, d), res_spec(block, d),
                  res_spec(block, d), stream_spec(bq_w, d),
                  stream_spec(bq_w, 1), stream_spec(bq_w, 1)],
        out_specs=[res_spec(block, d), res_spec(block, d)],
        out_shape=[out_struct((b, tk, h, d), k.dtype),
                   out_struct((b, tk, h, d), v.dtype)],
        scratch_shapes=[_scratch((h, block, d)), _scratch((h, block, d))],
        interpret=interpret,
        compiler_params=_compiler_params(_SEM3),
    )(offs, q, k, v, do, lseh, dlh)

    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal=False):
    """softmax(QK^T/sqrt(D) [+causal mask]) V over [B, T, H, D]."""
    return _forward(q, k, v, causal)


def _fwd(q, k, v, causal):
    o, lse = _forward(q, k, v, causal, return_lse=True)
    return o, (q, k, v, o, lse)


def _bwd(causal, res, g):
    q, k, v, o, lse = res
    scale = 1.0 / (q.shape[-1] ** 0.5)
    # delta_i = dO_i . O_i  — the softmax-jacobian row correction
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).transpose(0, 2, 1)       # [B, H, T]
    return flash_attention_bwd_block(q, k, v, g, lse, delta, 0, 0, scale,
                                     causal)


flash_attention.defvjp(_fwd, _bwd)
