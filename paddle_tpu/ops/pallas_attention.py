"""Flash attention as a Pallas TPU kernel (the "pallas for the hot ops"
tier of the compute path; /opt/skills/guides/pallas_guide.md patterns).

Forward: online-softmax blocks — Q tiles stay resident in VMEM while K/V
tiles stream through, carrying the running max/denominator, so the [T, T]
score matrix never materializes in HBM (memory O(T) instead of O(T^2),
same contract as parallel/ring_attention.py across chips but within one
core's VMEM).

Backward: jax.custom_vjp recomputes through the reference attention —
the standard recompute tradeoff; gradients are bitwise those of
attention_reference, which the ring-attention tests already validate.

On CPU (the test mesh) the kernel runs under the Pallas interpreter
(interpret=True) — same code path, no Mosaic compile. Shapes must tile:
T divisible by the block (128, or T itself when smaller); callers
fall back to attention_reference otherwise (ops/nn_ops.py wiring).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "supports"]

_NEG = -1e30


def supports(q, k, v) -> bool:
    """Static-shape eligibility: [B, T, H, D] with T tileable."""
    if q.ndim != 4 or q.shape != k.shape or q.shape != v.shape:
        return False
    t = q.shape[1]
    return t >= 8 and (t <= 128 or t % 128 == 0)


def _block(t: int) -> int:
    return 128 if t % 128 == 0 else t


def _flash_loop(q, k_ref, v_ref, block, n_live, causal, q_base, k_base):
    """Shared online-softmax inner loop over K tiles: q [BQ, D] pre-scaled,
    k/v read from VMEM refs, global positions q_base + row / k_base +
    i*block + col for causal masking. Returns unnormalized (acc, m, l)."""
    from jax import lax
    import jax.experimental.pallas as pl

    bq, d = q.shape

    def body(i, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.dslice(i * block, block), :].astype(jnp.float32)
        vb = v_ref[0, pl.dslice(i * block, block), :].astype(jnp.float32)
        s = lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            qpos = q_base + lax.broadcasted_iota(jnp.int32, (bq, block), 0)
            kpos = k_base + i * block + lax.broadcasted_iota(
                jnp.int32, (bq, block), 1)
            s = jnp.where(qpos >= kpos, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    return lax.fori_loop(0, n_live, body, (acc0, m0, l0))


def _kernel(q_ref, k_ref, v_ref, o_ref, *, block: int, t: int, scale: float,
            causal: bool):
    import jax.experimental.pallas as pl

    pid_q = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [BQ, D]
    n_k = t // block
    # blocks strictly past the diagonal contribute nothing; with BQ == BK
    # the diagonal block is index pid_q
    n_live = (pid_q + 1) if causal else n_k
    acc, m, l = _flash_loop(q, k_ref, v_ref, block, n_live, causal,
                            pid_q * block, 0)
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _forward(q, k, v, causal):
    import jax.experimental.pallas as pl

    b, t, h, d = q.shape
    block = _block(t)
    scale = 1.0 / (d ** 0.5)
    # [B, T, H, D] -> [B*H, T, D]: heads become independent grid rows
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    interpret = jax.default_backend() != "tpu"
    grid = (b * h, t // block)
    out = pl.pallas_call(
        functools.partial(_kernel, block=block, t=t, scale=scale,
                          causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qh, kh, vh)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _block_kernel(off_ref, q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, *,
                  block: int, tk: int, scale: float, causal: bool):
    """Unnormalized flash block for the ring composition: one Q tile vs the
    whole visiting K/V shard, global positions offset by (q_off, k_off)
    from the scalar operand. Emits (acc, m, l) so the caller's online-
    softmax merge can combine shards."""
    import jax.experimental.pallas as pl

    pid_q = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    q_off = off_ref[0]
    k_off = off_ref[1]
    n_k = tk // block
    if causal:
        # prune K blocks entirely past this Q tile's last row: a visiting
        # shard fully in the future costs zero MXU work (n_live = 0)
        q_last = q_off + pid_q * block + (block - 1)
        n_live = jnp.clip((q_last - k_off) // block + 1, 0, n_k)
    else:
        n_live = n_k
    acc, m, l = _flash_loop(q, k_ref, v_ref, block, n_live, causal,
                            q_off + pid_q * block, k_off)
    acc_ref[0] = acc.astype(acc_ref.dtype)
    m_ref[0] = m[:, None]
    l_ref[0] = l[:, None]


def flash_attention_block(q, k, v, q_off, k_off, scale, causal):
    """Per-shard flash block for ring attention: q [B,Tq,H,D] resident,
    k/v [B,Tk,H,D] visiting, global offsets as traced scalars. Returns
    (acc [B,Tq,H,D] unnormalized, l [B,H,Tq], m [B,H,Tq]) in f32 carries,
    matching parallel.ring_attention._block_attn's online-softmax form."""
    import jax.experimental.pallas as pl

    b, tq, h, d = q.shape
    tk = k.shape[1]
    block = _block(min(tq, tk))
    assert tq % block == 0 and tk % block == 0, (
        f"flash_attention_block needs tileable shapes (tq={tq}, tk={tk}, "
        f"block={block}); gate callers with block_supports()")
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])

    interpret = jax.default_backend() != "tpu"
    vma = getattr(q, "aval", None)
    vma = getattr(vma, "vma", frozenset()) or frozenset()

    def out_struct(shape, dtype):
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:            # older jax: no vma kwarg
            return jax.ShapeDtypeStruct(shape, dtype)

    acc, m, l = pl.pallas_call(
        functools.partial(_block_kernel, block=block, tk=tk,
                          scale=float(scale), causal=causal),
        grid=(b * h, tq // block),
        in_specs=[
            pl.BlockSpec((2,), lambda i, j: (0,)),
            pl.BlockSpec((1, block, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), lambda i, j: (i, j, 0)),
            # trailing singleton keeps the (sublane, lane) tiling legal
            pl.BlockSpec((1, block, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            out_struct((b * h, tq, d), jnp.float32),
            out_struct((b * h, tq, 1), jnp.float32),
            out_struct((b * h, tq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(offs, qh, kh, vh)
    acc = acc.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    m = m.reshape(b, h, tq)
    l = l.reshape(b, h, tq)
    return acc, l, m


def block_supports(q, k) -> bool:
    tq, tk = q.shape[1], k.shape[1]
    blk = _block(min(tq, tk))
    return (q.ndim == 4 and tq % blk == 0 and tk % blk == 0
            and min(tq, tk) >= 8)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal=False):
    """softmax(QK^T/sqrt(D) [+causal mask]) V over [B, T, H, D]."""
    return _forward(q, k, v, causal)


def _fwd(q, k, v, causal):
    return _forward(q, k, v, causal), (q, k, v)


def _bwd(causal, res, g):
    from ..parallel.ring_attention import attention_reference
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: attention_reference(a, b, c,
                                                         causal=causal),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
