"""Extra NN ops: pad, maxout, row_conv, im2sequence, nce, pool variants
(reference: pad_op.cc, maxout_op.cc, row_conv_op.cc, im2sequence_op.cc,
nce_op.cc, spp_op.cc, unpool_op.cc, roi_pool_op.cc)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import NO_GRAD, op
from .common import in_var, same_as_input, set_out


def _pad_infer(op_, block):
    iv = in_var(op_, block, "X")
    p = op_.attr("paddings")
    if iv is not None and iv.shape is not None:
        shape = [None if d is None else d + p[2 * i] + p[2 * i + 1]
                 for i, d in enumerate(iv.shape)]
        set_out(op_, block, "Out", shape, iv.dtype)


@op("pad", infer_shape=_pad_infer)
def _pad(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    p = op_.attr("paddings")
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, pads, constant_values=op_.attr("pad_value", 0.0))]}


def _maxout_infer(op_, block):
    iv = in_var(op_, block, "X")
    g = op_.attr("groups")
    if iv is not None and iv.shape is not None:
        n, c, h, w = iv.shape
        set_out(op_, block, "Out", [n, None if c is None else c // g, h, w],
                iv.dtype)


@op("maxout", infer_shape=_maxout_infer)
def _maxout(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    g = op_.attr("groups")
    n, c, h, w = x.shape
    return {"Out": [jnp.max(x.reshape(n, c // g, g, h, w), axis=2)]}


@op("row_conv")
def _row_conv(ctx, op_, ins):
    """Lookahead row convolution (reference row_conv_op.cc): for each t,
    out[t] = sum_{i=0..k} x[t+i] * filter[i]. Accepts (T, D) or (N, T, D)."""
    x = jnp.asarray(ins["X"][0])
    w = jnp.asarray(ins["Filter"][0])   # (k+1, D)
    squeeze = x.ndim == 2
    if squeeze:
        x = x[None]
    k = w.shape[0]
    T = x.shape[1]
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for i in range(k):
        out = out + xp[:, i:i + T, :] * w[i]
    if squeeze:
        out = out[0]
    return {"Out": [out]}


@op("im2sequence", grad=None)
def _im2sequence(ctx, op_, ins):
    """Image patches -> sequence rows (reference im2sequence_op.cc): output
    (N*OH*OW, kh*kw*C)."""
    x = jnp.asarray(ins["X"][0])
    kh, kw = op_.attr("kernels")
    sh, sw = op_.attr("strides", [1, 1])
    p = op_.attr("paddings", [0, 0, 0, 0])
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw),
        padding=((p[0], p[2]), (p[1], p[3])),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: (N, C*kh*kw, OH, OW)
    np_, ckk, oh, ow = patches.shape
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, ckk)
    return {"Out": [out]}


def _nce_infer(op_, block):
    xv = in_var(op_, block, "Input")
    if xv is not None and xv.shape is not None:
        set_out(op_, block, "Cost", [xv.shape[0], 1], xv.dtype)


@op("nce", infer_shape=_nce_infer, non_diff_inputs=("Label", "SampleWeight"))
def _nce(ctx, op_, ins):
    """Noise-contrastive estimation (reference nce_op.cc): binary logistic
    loss on the true class vs uniformly sampled negatives."""
    x = jnp.asarray(ins["Input"][0])          # (N, D)
    label = jnp.asarray(ins["Label"][0]).reshape(-1)  # (N,)
    w = jnp.asarray(ins["Weight"][0])         # (C, D)
    b = jnp.asarray(ins["Bias"][0]).reshape(-1) if ins.get("Bias") and \
        ins["Bias"][0] is not None else None
    num_classes = op_.attr("num_total_classes")
    num_neg = op_.attr("num_neg_samples", 10)
    key = ctx.next_rng(op_)
    n = x.shape[0]
    neg = jax.random.randint(key, (n, num_neg), 0, num_classes)

    def logit(ids):
        l = jnp.einsum("nd,nkd->nk", x, w[ids])
        if b is not None:
            l = l + b[ids]
        return l

    pos_logit = logit(label[:, None])          # (N, 1)
    neg_logit = logit(neg)                     # (N, K)
    pos_loss = jnp.log1p(jnp.exp(-pos_logit))
    neg_loss = jnp.log1p(jnp.exp(neg_logit))
    cost = pos_loss.sum(axis=1, keepdims=True) + \
        neg_loss.sum(axis=1, keepdims=True)
    sample_logits = jnp.concatenate([pos_logit, neg_logit], axis=1)
    sample_labels = jnp.concatenate(
        [label[:, None], neg], axis=1).astype(jnp.int64)
    return {"Cost": [cost], "SampleLogits": [sample_logits],
            "SampleLabels": [sample_labels]}
