"""CTC family: warpctc loss, ctc_align greedy decode, edit_distance.

TPU-native replacements for the reference's warp-ctc dynload + CPU kernels
(reference: warpctc_op.cc/.h — dynloaded Baidu warp-ctc library;
ctc_align_op.h; edit_distance_op.h). Instead of a vendored CUDA library the
CTC forward algorithm runs in-graph as a `lax.scan` over time in log space
— differentiable by construction, so the gradient comes from the generic
vjp kernel instead of warp-ctc's hand-written backward, and the whole loss
fuses into the model's single XLA computation. Sequences follow the
padded-dense + @SEQLEN convention (the LoD emulation)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .common import in_var, seq_lengths as _lengths, set_out
from .registry import NO_GRAD, op

_NEG_INF = -1e30


def _ctc_loss_one(logp, labels, t_len, l_len, blank):
    """CTC forward (alpha) recursion for one sequence in log space.

    logp: [T, C] log-softmax scores; labels: [L] int32 (padded);
    t_len/l_len: valid lengths. Returns -log p(labels | logp)."""
    t_max, _ = logp.shape
    l_max = labels.shape[0]
    s_max = 2 * l_max + 1
    # extended label sequence: blank, l1, blank, l2, ..., blank
    z = jnp.full((s_max,), blank, dtype=jnp.int32).at[1::2].set(labels)
    pos = jnp.arange(s_max)
    # skip transition s-2 -> s allowed where z[s] != blank and z[s] != z[s-2]
    z_m2 = jnp.roll(z, 2)
    allow_skip = (z != blank) & (z != z_m2) & (pos >= 2)

    alpha0 = jnp.full((s_max,), _NEG_INF)
    alpha0 = alpha0.at[0].set(logp[0, blank])
    alpha0 = alpha0.at[1].set(jnp.where(l_len > 0, logp[0, z[1]], _NEG_INF))

    def step(alpha, xs):
        logp_t, t = xs
        a1 = alpha
        a2 = jnp.concatenate([jnp.array([_NEG_INF]), alpha[:-1]])
        a3 = jnp.where(allow_skip,
                       jnp.concatenate([jnp.full((2,), _NEG_INF), alpha[:-2]]),
                       _NEG_INF)
        m = jnp.maximum(jnp.maximum(a1, a2), a3)
        tot = m + jnp.log(jnp.exp(a1 - m) + jnp.exp(a2 - m) + jnp.exp(a3 - m))
        new = tot + logp_t[z]
        # freeze alpha once past this sequence's end so the final carry is
        # alpha at t = t_len-1 (the LoD emulation of per-sequence T)
        new = jnp.where(t < t_len, new, alpha)
        return new, None

    alpha, _ = jax.lax.scan(step, alpha0,
                            (logp[1:], jnp.arange(1, t_max)))
    end1 = alpha[2 * l_len]
    end2 = jnp.where(l_len > 0, alpha[jnp.maximum(2 * l_len - 1, 0)], _NEG_INF)
    m = jnp.maximum(end1, end2)
    return -(m + jnp.log(jnp.exp(end1 - m) + jnp.exp(end2 - m)))


def _warpctc_infer(op_, block):
    xv = in_var(op_, block, "Logits")
    if xv is not None and xv.shape is not None:
        set_out(op_, block, "Loss", [xv.shape[0], 1], xv.dtype)


@op("warpctc", infer_shape=_warpctc_infer, non_diff_inputs=("Label",))
def _warpctc(ctx, op_, ins):
    """CTC loss (reference warpctc_op.cc, via the dynloaded warp-ctc lib).
    Logits are padded [B, T, C] (+ @SEQLEN), Label padded [B, L] int
    (+ @SEQLEN). Softmax is applied internally, like warp-ctc. With
    norm_by_times the *gradient* is scaled by 1/T_b (forward loss unchanged),
    matching the reference's ScaleLoDTensorFunctor on the logits grad."""
    logits = jnp.asarray(ins["Logits"][0])
    labels = jnp.asarray(ins["Label"][0]).astype(jnp.int32)
    if labels.ndim == 3:
        labels = labels[..., 0]
    b, t, _ = logits.shape
    t_lens = _lengths(ctx, op_, "Logits", b, t)
    l_lens = _lengths(ctx, op_, "Label", b, labels.shape[1])
    blank = op_.attr("blank", 0)

    if op_.attr("norm_by_times", False):
        s = (1.0 / jnp.maximum(t_lens, 1).astype(logits.dtype))
        s = s[:, None, None]
        logits = logits * s + jax.lax.stop_gradient(logits * (1.0 - s))

    logits32 = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits32, axis=-1)
    loss = jax.vmap(_ctc_loss_one, in_axes=(0, 0, 0, 0, None))(
        logp, labels, t_lens, l_lens, blank)
    loss = loss.astype(logits.dtype)[:, None]
    name = op_.desc.outputs["Loss"][0]
    ctx.set_seq_len(name, None)   # Loss is [num_seq, 1], not a sequence
    return {"Loss": [loss]}


def _ctc_align_infer(op_, block):
    xv = in_var(op_, block, "Input")
    if xv is not None:
        set_out(op_, block, "Output", xv.shape, xv.dtype)


@op("ctc_align", infer_shape=_ctc_align_infer, grad=NO_GRAD)
def _ctc_align(ctx, op_, ins):
    """Greedy CTC decode: merge repeats, drop blanks (reference
    ctc_align_op.h). Input padded [B, T] int (+ @SEQLEN); output padded
    [B, T] with new per-sequence lengths — compaction is a stable sort on
    the keep mask, the XLA-friendly form of the reference's sequential
    copy loop."""
    x = jnp.asarray(ins["Input"][0])
    squeeze = x.ndim == 3
    if squeeze:
        x = x[..., 0]
    b, t = x.shape
    lens = _lengths(ctx, op_, "Input", b, t)
    blank = op_.attr("blank", 0)
    merge = op_.attr("merge_repeated", True)

    steps = jnp.arange(t)[None, :]
    valid = steps < lens[:, None]
    prev = jnp.concatenate([jnp.full((b, 1), -1, x.dtype), x[:, :-1]], axis=1)
    keep = (x != blank) & valid
    if merge:
        keep = keep & (x != prev)
    order = jnp.argsort(jnp.where(keep, 0, 1), axis=1, stable=True)
    out = jnp.take_along_axis(x, order, axis=1)
    new_lens = keep.sum(axis=1).astype(jnp.int32)
    out = jnp.where(steps < new_lens[:, None], out, jnp.zeros_like(out))
    if squeeze:
        out = out[..., None]
    ctx.set_seq_len(op_.desc.outputs["Output"][0], new_lens)
    return {"Output": [out]}


def _edit_distance_one(hyp, ref, m, n):
    """Levenshtein DP for one (hyp, ref) pair over padded buffers; only the
    dp[m, n] cell is read, which depends solely on real tokens."""
    l2 = ref.shape[0]
    row0 = jnp.arange(l2 + 1, dtype=jnp.float32)

    def outer(prev_row, xs):
        h_tok, i = xs   # i is 1-based hyp position

        def inner(left, xs2):
            r_tok, j, up, upleft = xs2
            cost = jnp.where(h_tok == r_tok, 0.0, 1.0)
            val = jnp.minimum(jnp.minimum(up + 1.0, left + 1.0),
                              upleft + cost)
            return val, val

        _, rest = jax.lax.scan(
            inner, i.astype(jnp.float32),
            (ref, jnp.arange(1, l2 + 1), prev_row[1:], prev_row[:-1]))
        new_row = jnp.concatenate([i.astype(jnp.float32)[None], rest])
        return new_row, new_row

    _, rows = jax.lax.scan(outer, row0,
                           (hyp, jnp.arange(1, hyp.shape[0] + 1)))
    all_rows = jnp.concatenate([row0[None], rows], axis=0)
    return all_rows[m, n]


def _edit_distance_infer(op_, block):
    hv = in_var(op_, block, "Hyps")
    if hv is not None and hv.shape is not None:
        set_out(op_, block, "Out", [hv.shape[0], 1], "float32")
        set_out(op_, block, "SequenceNum", [1], "int32")


@op("edit_distance", infer_shape=_edit_distance_infer, grad=NO_GRAD)
def _edit_distance(ctx, op_, ins):
    """Levenshtein distance between hypothesis and reference id sequences
    (reference edit_distance_op.h). Padded [B, L] ints + @SEQLEN each side;
    Out is [B, 1] float, optionally normalized by the reference length."""
    hyp = jnp.asarray(ins["Hyps"][0])
    ref = jnp.asarray(ins["Refs"][0])
    if hyp.ndim == 3:
        hyp = hyp[..., 0]
    if ref.ndim == 3:
        ref = ref[..., 0]
    b = hyp.shape[0]
    m = _lengths(ctx, op_, "Hyps", b, hyp.shape[1])
    n = _lengths(ctx, op_, "Refs", b, ref.shape[1])
    dist = jax.vmap(_edit_distance_one)(hyp, ref, m, n)
    dist = jnp.where(m == 0, n.astype(jnp.float32), dist)
    dist = jnp.where((n == 0) & (m != 0), m.astype(jnp.float32), dist)
    if op_.attr("normalized", False):
        dist = dist / jnp.maximum(n, 1).astype(jnp.float32)
    for name in op_.desc.outputs.get("Out", []):
        ctx.set_seq_len(name, None)
    return {"Out": [dist[:, None]],
            "SequenceNum": [jnp.array([b], dtype=jnp.int32)]}
