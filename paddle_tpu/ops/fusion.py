"""Trace-time kernel fusion pass over the ProgramDesc.

The reference treats Fluid programs as compiler IR (PAPER.md: the
transpilers rewrite ProgramDesc graphs; data_layout_transform rewrites
layouts) — this module is the fusion-pass instance of that idea, applied
at trace time like the NHWC tag pass (ops/layout.py). `plan()` pattern-
matches CONTIGUOUS op windows in the global block:

    conv2d/depthwise_conv2d -> batch_norm [-> activation]
    mul -> elementwise_add(1-D bias) [-> activation]
    elementwise/activation chains (incl. their _grad variants)
    runs of same-type sgd/momentum/adam updates sharing one LR

and the executor lowers each match as ONE fused op instead of N separate
lowerings. Because matches are contiguous windows, executing a group at
its anchor preserves the original op order exactly — no dependency
analysis is needed, and the compose paths below run each member through
the executor's own `_exec_op` (prepass, registry lowering, SEQLEN and
layout-tag bookkeeping), so they are bitwise identical to the unfused
trace. The only value-rewriting paths are:

  * inference-mode conv+bn: BN folds into the conv filter/bias
    (w' = w * scale/sqrt(var+eps), b' = bias - mean * that) and the
    conv's own output is elided from the trace when nothing else
    consumes it;
  * training-mode bn[+act] on bf16 NHWC activations: a single Pallas
    TPU kernel (one-pass E[x^2]-E[x]^2 statistics, matching the unfused
    bf16 path) normalizes and activates in one VMEM sweep;
  * optimizer buckets: dense param/grad/moment tensors concatenate into
    one flat same-dtype buffer per bucket and apply the identical
    elementwise update once (bitwise equal per element; SelectedRows
    grads keep their per-param sparse fast path).

Gradients stay consistent for free: fused windows only ever cover
forward ops whose `<type>_grad` ops re-trace the UNFUSED forward
lowering (ops/registry.py generic vjp), member-level layout tags are
kept live during compose execution, and backward elementwise chains
fuse through the same compose machinery.

Env-gated by PADDLE_TPU_FUSION=1 (default on); per-reason fallback
counters (`fusion_fallback_total`) mirror executor_window_fallback_total.
Applies to the traced global block only — eager mode and control-flow
sub-blocks run per-op as before.
"""

from __future__ import annotations

import functools
import os
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.desc import OpDesc
from . import layout as layout_mod
from . import optimizer_ops
from .common import SelectedRowsVal, maybe_dense
from .math_ops import _activations
from .registry import NO_GRAD, register

# default ON; PADDLE_TPU_FUSION=0 restores the per-op trace
FUSION_OPT = os.environ.get("PADDLE_TPU_FUSION", "1") == "1"

# --- pattern tables (tools/check_registry.py lints these against the
# --- registry so a typo can't silently disable an optimization) ---------

CONV_OPS = frozenset({"conv2d", "depthwise_conv2d"})

# activations fusable as a window tail: unary X->Out, layout-agnostic,
# and expressible inside the Pallas bn+act kernel (static attrs only)
ACT_OPS = frozenset({
    "relu", "relu6", "leaky_relu", "sigmoid", "tanh", "elu", "swish",
    "brelu", "hard_sigmoid", "soft_relu",
})

# elementwise chain members (matched by base type, so their _grad
# variants ride along): the layout-agnostic pass-through set
CHAIN_OPS = frozenset(
    n for n in layout_mod.AGNOSTIC_OPS if not n.endswith("_grad"))

OPTIMIZER_BUCKET_OPS = frozenset({"sgd", "momentum", "adam"})

FUSED_OP_TYPES = (
    "fused_conv_bn_act", "fused_bn_act", "fused_fc_act", "fused_chain",
    "fused_sgd", "fused_momentum", "fused_adam",
    "fused_sparse_sgd", "fused_sparse_momentum", "fused_sparse_adam",
)

# per-param input slots / shared input slots / per-param output slots
_OPT_SLOTS = {
    "sgd": (("Param", "Grad"), ("LearningRate",), ("ParamOut",)),
    "momentum": (("Param", "Grad", "Velocity"), ("LearningRate",),
                 ("ParamOut", "VelocityOut")),
    "adam": (("Param", "Grad", "Moment1", "Moment2"),
             ("LearningRate", "Beta1Pow", "Beta2Pow"),
             ("ParamOut", "Moment1Out", "Moment2Out")),
}


@dataclass
class Group:
    """One fused window: ops[start:end] of the global block, executed as
    a unit at the anchor index (start)."""
    kind: str                    # conv_bn_act | bn_act | fc_act | chain | opt_bucket
    start: int
    end: int                     # exclusive
    members: Tuple[Any, ...]     # Operators in block order
    op: Any = None               # synthetic fused Operator (non-bucket kinds)
    conv: Any = None
    bn: Any = None
    act: Any = None
    fold: bool = False           # inference-mode BN fold into conv weights
    elide: Tuple[str, ...] = ()  # names the fold path never materializes
    cache: Dict[Any, Any] = field(default_factory=dict)


# --- plan ---------------------------------------------------------------

_PLANS: Dict[Tuple[int, int], Tuple[Any, Optional[Dict[int, Group]]]] = {}


def plan(program) -> Optional[Dict[int, Group]]:
    """anchor index -> Group for the program's global block, or None when
    fusion is off / nothing matches. Cached per (id, version) like the
    executor's jit cache."""
    if not FUSION_OPT:
        return None
    key = (id(program), getattr(program, "_version", 0))
    hit = _PLANS.get(key)
    if hit is not None and hit[0] is program:
        return hit[1]
    if len(_PLANS) > 64:
        _PLANS.clear()
    groups = _build(program.global_block())
    _PLANS[key] = (program, groups)
    return groups


def _build(block) -> Optional[Dict[int, Group]]:
    ops = block.ops
    groups: Dict[int, Group] = {}
    i, n = 0, len(ops)
    while i < n:
        g = (_match_opt_bucket(ops, i) or _match_conv_bn_act(ops, i)
             or _match_fc_act(ops, i) or _match_chain(ops, i))
        if g is not None:
            groups[i] = g
            i = g.end
        else:
            i += 1
    return groups or None


def _first(names: List[str]) -> Optional[str]:
    return names[0] if names else None


def _match_conv_bn_act(ops, i) -> Optional[Group]:
    n = len(ops)
    conv = None
    j = i
    if ops[j].type in CONV_OPS:
        conv = ops[j]
        j += 1
        if j >= n or ops[j].type != "batch_norm":
            return None
    elif ops[j].type != "batch_norm":
        return None
    bn = ops[j]
    if conv is not None and \
            _first(bn.desc.input("X")) != _first(conv.desc.output("Output")):
        return None
    j += 1
    act = None
    if j < n and ops[j].type in ACT_OPS and \
            _first(ops[j].desc.input("X")) == _first(bn.desc.output("Y")):
        act = ops[j]
        j += 1
    if conv is None and act is None:
        return None   # a bare batch_norm is not a window
    members = tuple(m for m in (conv, bn, act) if m is not None)
    fold, elide = False, ()
    if conv is not None and bn.attr("is_test", False):
        out_name = _first(conv.desc.output("Output"))
        fold = _foldable(ops, conv, bn, out_name)
        if fold:
            elide = (out_name,)
    kind = "conv_bn_act" if conv is not None else "bn_act"
    g = Group(kind=kind, start=i, end=j, members=members,
              conv=conv, bn=bn, act=act, fold=fold, elide=elide)
    g.op = _window_synth(
        members, "fused_conv_bn_act" if conv is not None else "fused_bn_act",
        g, elide=elide)
    return g


def _foldable(ops, conv, bn, out_name) -> bool:
    """The conv output can be elided iff bn is its only consumer, no
    later op rewrites the name, and it isn't persistable state."""
    if out_name is None:
        return False
    for o in ops:
        if o is bn or o is conv:
            continue
        if out_name in o.desc.input_arg_names():
            return False
        if out_name in o.desc.output_arg_names():
            return False
    block = getattr(conv, "block", None)
    if block is not None and block.desc.has_var(out_name) and \
            block.desc.var(out_name).persistable:
        return False
    return True


def _match_fc_act(ops, i) -> Optional[Group]:
    n = len(ops)
    if ops[i].type != "mul" or i + 1 >= n or \
            ops[i + 1].type != "elementwise_add":
        return None
    mul, add = ops[i], ops[i + 1]
    if _first(add.desc.input("X")) != _first(mul.desc.output("Out")):
        return None
    # channel-bias form only: a 1-D Y (plan-time shape from the block)
    yname = _first(add.desc.input("Y"))
    block = getattr(mul, "block", None)
    if yname is None or block is None or not block.desc.has_var(yname):
        return None
    yshape = block.desc.var(yname).shape
    if yshape is None or len(yshape) != 1:
        return None
    j = i + 2
    act = None
    if j < n and ops[j].type in ACT_OPS and \
            _first(ops[j].desc.input("X")) == _first(add.desc.output("Out")):
        act = ops[j]
        j += 1
    members = tuple(m for m in (mul, add, act) if m is not None)
    g = Group(kind="fc_act", start=i, end=j, members=members)
    g.op = _window_synth(members, "fused_fc_act", g)
    return g


def _chain_ok(op) -> bool:
    t = op.type
    base = t[: -len("_grad")] if t.endswith("_grad") else t
    return base in CHAIN_OPS


def _match_chain(ops, i) -> Optional[Group]:
    n = len(ops)
    j = i
    while j < n and _chain_ok(ops[j]):
        j += 1
    if j - i < 2:
        return None
    members = tuple(ops[i:j])
    g = Group(kind="chain", start=i, end=j, members=members)
    g.op = _window_synth(members, "fused_chain", g)
    return g


def _opt_key(op):
    lr = tuple(op.desc.input("LearningRate"))
    if op.type == "sgd":
        return (lr,)
    if op.type == "momentum":
        return (lr, op.attr("mu"), bool(op.attr("use_nesterov", False)))
    return (lr, op.attr("beta1", 0.9), op.attr("beta2", 0.999),
            op.attr("epsilon", 1e-8), tuple(op.desc.input("Beta1Pow")),
            tuple(op.desc.input("Beta2Pow")))


def _match_opt_bucket(ops, i) -> Optional[Group]:
    t = ops[i].type
    if t not in OPTIMIZER_BUCKET_OPS:
        return None
    key0 = _opt_key(ops[i])
    n = len(ops)
    j = i + 1
    while j < n and ops[j].type == t and _opt_key(ops[j]) == key0:
        j += 1
    if j - i < 2:
        return None
    return Group(kind="opt_bucket", start=i, end=j, members=tuple(ops[i:j]))


# --- synthetic fused Operators ------------------------------------------

def _synth_operator(block, desc, site):
    from ..framework.framework import Operator
    o = Operator.__new__(Operator)   # view pattern, as registry's grad re-trace
    o.block = block
    o.desc = desc
    o.creation_site = site
    return o


def _window_synth(members, type_, group, elide=()):
    """One fused op spanning the window. Member slots merge under
    per-member prefixes ("<k>:<slot>") so colliding slot names (bn "X" vs
    act "X") stay distinct; only EXTERNAL inputs (not produced inside the
    window) are declared. The compose lowerings read ctx.env directly and
    ignore the gathered ins."""
    produced = set()
    inputs: Dict[str, List[str]] = {}
    outputs: Dict[str, List[str]] = {}
    attrs: Dict[str, Any] = {}
    for k, m in enumerate(members):
        for slot, names in m.desc.inputs.items():
            ext = [x for x in names if x not in produced]
            if ext:
                inputs[f"{k}:{slot}"] = ext
        for slot, names in m.desc.outputs.items():
            keep = [x for x in names if x not in elide]
            if keep:
                outputs[f"{k}:{slot}"] = keep
            produced.update(names)
        for a, v in m.desc.attrs.items():
            attrs[f"{k}:{a}"] = v
    attrs["__fusion_group__"] = group
    desc = OpDesc(type=type_, inputs=inputs, outputs=outputs, attrs=attrs)
    return _synth_operator(getattr(members[0], "block", None), desc,
                           getattr(members[0], "creation_site", None))


def _bucket_synth(group, members, t, prefix="fused_"):
    """Fused optimizer op over a same-dtype sub-bucket: slots keep their
    natural names with one entry per member (uniform across members),
    shared slots (LR, beta pows) collapse to one. prefix="fused_sparse_"
    builds the scatter-apply bucket (members re-executed by
    _sparse_bucket_lower under one scope); the prefix is part of the
    cache key because a member set can flip dense<->sparse across traces
    (PADDLE_TPU_SPARSE_APPLY toggles between compiles)."""
    key = (prefix,) + tuple(id(m) for m in members)
    hit = group.cache.get(key)
    if hit is not None:
        return hit
    per_param, shared, outs = _OPT_SLOTS[t]
    inputs = {s: [_first(m.desc.input(s)) for m in members]
              for s in per_param}
    for s in shared:
        inputs[s] = list(members[0].desc.input(s))
    outputs = {s: [_first(m.desc.output(s)) for m in members] for s in outs}
    attrs = dict(members[0].desc.attrs)
    attrs["__fusion_group__"] = group
    if prefix == "fused_sparse_":
        attrs["__sparse_members__"] = tuple(members)
    desc = OpDesc(type=prefix + t, inputs=inputs, outputs=outputs,
                  attrs=attrs)
    op = _synth_operator(getattr(members[0], "block", None), desc,
                         getattr(members[0], "creation_site", None))
    group.cache[key] = op
    return op


# --- execution ----------------------------------------------------------

def _count(ctx, reason: str, amount: int = 1):
    from .. import telemetry
    telemetry.counter(
        "fusion_fallback_total",
        "ops lowered unfused (or without the fused kernel) by the "
        "trace-time fusion pass, by reason",
        labels=("program", "reason")).labels(
        program=telemetry.program_label(ctx.program), reason=reason).inc(
        amount)


@contextmanager
def _muted_observers():
    """Member ops run through the executor's full _exec_op for bitwise
    parity, but only the FUSED op should reach the cost observers — the
    device-side HLO attribution keys on the outermost pd.* named scope
    (xplane.hlo_op_names), so the analytic table must match it."""
    from .. import executor as executor_mod
    saved = executor_mod._op_observers
    executor_mod._op_observers = []
    try:
        yield
    finally:
        executor_mod._op_observers = saved


def execute_group(executor, ctx, group: Group, env, protected=()):
    """Lower one planned group at its anchor. `protected` (fetch names +
    persistable outputs) blocks fold-mode elision at trace time — the
    plan is fetch-agnostic and cached."""
    if group.kind == "opt_bucket":
        _execute_opt_bucket(executor, ctx, group, env)
        return
    if group.elide and (set(group.elide) & set(protected)):
        _count(ctx, "fetched_intermediate", len(group.members))
        for m in group.members:
            executor._exec_op(ctx, m, env)
        return
    executor._exec_op(ctx, group.op, env)


def _execute_opt_bucket(executor, ctx, group: Group, env):
    from . import sparse_ops
    t = group.members[0].type
    specs = getattr(ctx.program, "_param_shardings", None) or {}
    tables = getattr(ctx.program, "_sharded_tables", None) or {}
    dense: List[Any] = []
    sparse: List[Any] = []
    for m in group.members:
        gname = _first(m.desc.input("Grad"))
        pname = _first(m.desc.input("Param"))
        if isinstance(env.get(gname), SelectedRowsVal):
            # sparse grads never join the dense concat (densifying would
            # be O(vocab)); when the op has a scatter-apply kernel they
            # get their own per-dtype fused_sparse bucket below. The
            # reasons distinguish "kept sparse on purpose" (dashboards
            # should not read the sparse path as a perf cliff) from a
            # genuinely unsupported combination.
            if sparse_ops.sparse_apply_enabled() \
                    and t in sparse_ops.SPARSE_APPLY_OPS:
                _count(ctx, "sharded_table_sparse_path" if pname in tables
                       else "sparse_grad_handled")
                sparse.append(m)
            else:
                _count(ctx, "sparse_grad_unsupported")
                executor._exec_op(ctx, m, env)
        elif pname in specs:
            # explicitly sharded params stay per-param: concatenating
            # differently-sharded buffers would force GSPMD gathers
            _count(ctx, "sharded_param")
            executor._exec_op(ctx, m, env)
        else:
            dense.append(m)
    # sub-bucket by the trace-time dtypes of every per-param tensor so the
    # flat concat never promotes (bitwise parity holds per element)
    per_param = _OPT_SLOTS[t][0]
    buckets: Dict[Tuple[str, ...], List[Any]] = {}
    for m in dense:
        sig = []
        for s in per_param:
            if s == "Grad":
                continue   # grads upcast per-tensor to the param dtype
            v = env.get(_first(m.desc.input(s)))
            sig.append(str(getattr(v, "dtype", None)))
        buckets.setdefault(tuple(sig), []).append(m)
    for sig in sorted(buckets):
        ms = buckets[sig]
        if len(ms) < 2:
            for m in ms:
                executor._exec_op(ctx, m, env)
            continue
        executor._exec_op(ctx, _bucket_synth(group, ms, t), env)
    # scatter-apply members bucket per param dtype, mirroring the dense
    # buckets: one fused_sparse_<t> unit per dtype (the scatters stay
    # per-table — tables differ in height — but share one scope/observer
    # entry so attribution sees one apply unit, not N stragglers)
    sbuckets: Dict[str, List[Any]] = {}
    for m in sparse:
        p = env.get(_first(m.desc.input("Param")))
        sbuckets.setdefault(str(getattr(p, "dtype", None)), []).append(m)
    for sig in sorted(sbuckets):
        ms = sbuckets[sig]
        if len(ms) < 2:
            for m in ms:
                executor._exec_op(ctx, m, env)
            continue
        executor._exec_op(
            ctx, _bucket_synth(group, ms, t, prefix="fused_sparse_"), env)


# --- compose machinery --------------------------------------------------

def _out_names(op_) -> List[str]:
    return [n for ns in op_.desc.outputs.values() for n in ns]


def _freeze(ctx, env, names):
    """After members ran inside a fused lowering, freeze their layout
    tags and SEQLEN side channels into the OUTER op's override dicts —
    otherwise the executor's post-op tag_outputs/SEQLEN pass (which only
    understands the fused op's merged desc) would clobber member-exact
    state. A None override pops, same as absent."""
    from .. import executor as executor_mod
    ctx.layout_overrides = {n: ctx.layouts.get(n) for n in names}
    seq: Dict[str, Any] = {}
    for n in names:
        seq[n] = env.get(n + executor_mod.SEQLEN_SUFFIX)
        seq[n + executor_mod.SEQLEN2_SUFFIX] = \
            env.get(n + executor_mod.SEQLEN2_SUFFIX)
    ctx.seq_overrides = seq


def _collect(op_, env):
    return {slot: [env.get(n) for n in names]
            for slot, names in op_.desc.outputs.items()}


def _compose_lower(ctx, op_, ins):
    """Generic fused lowering: run every member through the executor's
    own _exec_op (prepass -> registry lowering -> tag/SEQLEN bookkeeping)
    under the fused op's named scope — bitwise identical values to the
    unfused trace, one scope/observer entry for attribution."""
    g: Group = op_.attr("__fusion_group__")
    env = ctx.env
    with _muted_observers():
        for m in g.members:
            ctx.executor._exec_op(ctx, m, env)
    _freeze(ctx, env, _out_names(op_))
    return _collect(op_, env)


# --- conv/bn/act window -------------------------------------------------

def _conv_bn_act_lower(ctx, op_, ins):
    g: Group = op_.attr("__fusion_group__")
    env = ctx.env
    if g.fold:
        return _fold_lower(ctx, op_, g, env)
    with _muted_observers():
        if g.conv is not None and _conv_stats_pallas(ctx, g, env):
            # whole window went through the Pallas conv+stats kernel with
            # the bn-apply(+act) kernel as epilogue — nothing left to run
            _freeze(ctx, env, _out_names(op_))
            return _collect(op_, env)
        if g.conv is not None:
            ctx.executor._exec_op(ctx, g.conv, env)
        reason = _kernel_ineligible(ctx, g, env)
        if reason is None:
            _bn_act_pallas(ctx, g, env)
        else:
            # compose fallback: still one fused unit for attribution,
            # but the plain jnp batch_norm (+act) lowerings — bitwise
            # identical to the unfused trace
            _count(ctx, reason)
            ctx.executor._exec_op(ctx, g.bn, env)
            if g.act is not None:
                ctx.executor._exec_op(ctx, g.act, env)
    _freeze(ctx, env, _out_names(op_))
    return _collect(op_, env)


def _kernel_ineligible(ctx, g: Group, env) -> Optional[str]:
    """None when the Pallas bn+act kernel applies, else a fallback-counter
    reason. The kernel computes one-pass f32 statistics — exactly the
    unfused bf16 path — so it is gated to bf16 inputs; f32 inputs keep the
    two-pass centered variance via the compose fallback."""
    if g.bn.attr("is_test", False):
        return "kernel_is_test"
    xname = _first(g.bn.desc.input("X"))
    x = env.get(xname)
    if getattr(x, "ndim", 0) != 4 or \
            ctx.layouts.get(xname) != layout_mod.NHWC:
        return "kernel_layout"
    if getattr(x, "dtype", None) != jnp.bfloat16:
        return "kernel_dtype"
    c = x.shape[-1]
    m = int(np.prod(x.shape[:-1]))
    if c % 128 != 0 or m < 8 or m % 8 != 0:
        return "kernel_shape"
    return None


def _bn_act_pallas(ctx, g: Group, env):
    """Training-mode BN[+act] as one Pallas TPU kernel over the [M, C]
    view of the NHWC activation (M = N*H*W): a two-phase grid reads each
    x block twice — phase 0 accumulates per-channel sum/sum-of-squares in
    VMEM scratch, phase 1 normalizes, applies the activation, and writes
    the bf16 outputs — so statistics + normalize + activation take two
    HBM sweeps of x and never materialize f32 intermediates."""
    bn, act = g.bn, g.act
    xname = _first(bn.desc.input("X"))
    x = jnp.asarray(env[xname])
    scale = jnp.asarray(env[_first(bn.desc.input("Scale"))])
    bias = jnp.asarray(env[_first(bn.desc.input("Bias"))])
    mean = jnp.asarray(env[_first(bn.desc.input("Mean"))])
    var = jnp.asarray(env[_first(bn.desc.input("Variance"))])
    eps = float(bn.attr("epsilon", 1e-5))
    momentum = bn.attr("momentum", 0.9)
    c = x.shape[-1]
    x2 = x.reshape(-1, c)

    act_fn = None
    if act is not None:
        base = _activations[act.type]
        act_fn = functools.partial(base, a=act)
    ybn2, yact2, saved_mean, saved_var = _pallas_bn_act(
        x2, scale.astype(jnp.float32), bias.astype(jnp.float32), eps,
        act_fn)

    y = ybn2.reshape(x.shape)
    env[_first(bn.desc.output("Y"))] = y
    ctx.layouts[_first(bn.desc.output("Y"))] = layout_mod.NHWC
    # running stats on tiny [C] vectors stay outside the kernel
    env[_first(bn.desc.output("MeanOut"))] = \
        mean * momentum + saved_mean * (1.0 - momentum)
    env[_first(bn.desc.output("VarianceOut"))] = \
        var * momentum + saved_var * (1.0 - momentum)
    env[_first(bn.desc.output("SavedMean"))] = saved_mean
    env[_first(bn.desc.output("SavedVariance"))] = saved_var
    if act is not None:
        out = _first(act.desc.output("Out"))
        env[out] = yact2.reshape(x.shape)
        ctx.layouts[out] = layout_mod.NHWC


def _conv_stats_pallas(ctx, g: Group, env) -> bool:
    """Whole-window Pallas path: the conv2d_stats kernel emits the conv
    output AND its per-channel sum/sum-of-squares while each output row
    is still in VMEM, then bn_apply normalizes (+act) — the window never
    re-reads the conv output from HBM to compute batch statistics.

    Returns False with NO side effects when ineligible: the caller's
    member-by-member ladder takes over (its conv member still picks up
    the Pallas conv kernel through the ordinary lowering, and its
    fallback reasons keep counting), so this gate needs no counter of
    its own. Gated to the same predicate as the conv routing plus the
    bn-apply blocking (M % 8), training-mode bn, the layout convention
    on (consumers expect the NHWC tags this writes), and no AMP restore
    (O1 would hand the bn an f32 conv output — the compose ladder's
    kernel_dtype case)."""
    from . import pallas_conv
    from .common import mxu_cast
    from .nn_ops import _conv_out_dim, _pair
    conv, bn, act = g.conv, g.bn, g.act
    if bn.attr("is_test", False) or not ctx.layout_opt:
        return False
    if getattr(ctx, "quant_mode", None):
        # O3: the member-by-member ladder runs instead, so the conv
        # member reaches its quantized routing (the bf16 conv+stats
        # kernel would silently skip quantization for fused convs); the
        # bn+act epilogue still fuses through _bn_act_pallas
        return False
    xname = _first(conv.desc.input("Input"))
    wname = _first(conv.desc.input("Filter"))
    if env.get(xname) is None or env.get(wname) is None:
        return False
    x = jnp.asarray(env[xname])
    w = jnp.asarray(env[wname])
    s = _pair(conv.attr("strides", [1, 1]))
    p = _pair(conv.attr("paddings", [0, 0]))
    d = _pair(conv.attr("dilations", [1, 1]))
    groups = conv.attr("groups", 1) or 1
    (xc, wc), restore = mxu_cast(ctx, x, w)
    if restore is not None:
        return False
    nhwc_in = ctx.layouts.get(xname) == layout_mod.NHWC
    x_nhwc = xc if nhwc_in else jnp.transpose(xc, (0, 2, 3, 1))
    if pallas_conv.ineligible(x_nhwc, wc, s, p, d, groups) is not None:
        return False
    n = x_nhwc.shape[0]
    co, _, kh, kw = wc.shape
    oh = _conv_out_dim(x_nhwc.shape[1], kh, p[0], s[0], d[0])
    ow = _conv_out_dim(x_nhwc.shape[2], kw, p[1], s[1], d[1])
    m = n * oh * ow
    if m < 8 or m % 8 != 0:
        return False

    pallas_conv.count_hit("fused_conv_bn_act")
    y, csum, csq = pallas_conv.conv2d_stats(x_nhwc, wc, s, p, d)
    out_name = _first(conv.desc.output("Output"))
    env[out_name] = y
    ctx.layouts[out_name] = layout_mod.NHWC
    # one-pass variance, clamped like the unfused bf16 batch_norm
    saved_mean = csum / float(m)
    saved_var = jnp.maximum(csq / float(m) - saved_mean * saved_mean, 0.0)

    scale = jnp.asarray(env[_first(bn.desc.input("Scale"))])
    bias = jnp.asarray(env[_first(bn.desc.input("Bias"))])
    mean = jnp.asarray(env[_first(bn.desc.input("Mean"))])
    var = jnp.asarray(env[_first(bn.desc.input("Variance"))])
    eps = float(bn.attr("epsilon", 1e-5))
    momentum = bn.attr("momentum", 0.9)
    act_fn = None
    if act is not None:
        act_fn = functools.partial(_activations[act.type], a=act)
    ybn2, yact2 = pallas_conv.bn_apply(
        y.reshape(-1, co), scale.astype(jnp.float32),
        bias.astype(jnp.float32), saved_mean, saved_var, eps, act_fn)

    env[_first(bn.desc.output("Y"))] = ybn2.reshape(y.shape)
    ctx.layouts[_first(bn.desc.output("Y"))] = layout_mod.NHWC
    env[_first(bn.desc.output("MeanOut"))] = \
        mean * momentum + saved_mean * (1.0 - momentum)
    env[_first(bn.desc.output("VarianceOut"))] = \
        var * momentum + saved_var * (1.0 - momentum)
    env[_first(bn.desc.output("SavedMean"))] = saved_mean
    env[_first(bn.desc.output("SavedVariance"))] = saved_var
    if act is not None:
        aout = _first(act.desc.output("Out"))
        env[aout] = yact2.reshape(y.shape)
        ctx.layouts[aout] = layout_mod.NHWC
    return True


def _bn_act_kernel(x_ref, scale_ref, bias_ref, *refs, eps, act, m_total):
    if act is None:
        ybn_ref, mean_ref, var_ref, sum_ref, sq_ref = refs
        yact_ref = None
    else:
        ybn_ref, yact_ref, mean_ref, var_ref, sum_ref, sq_ref = refs
    from jax.experimental import pallas as pl
    p = pl.program_id(1)
    m = pl.program_id(2)

    @pl.when(jnp.logical_and(p == 0, m == 0))
    def _zero():
        sum_ref[...] = jnp.zeros(sum_ref.shape, jnp.float32)
        sq_ref[...] = jnp.zeros(sq_ref.shape, jnp.float32)

    @pl.when(p == 0)
    def _accumulate():
        xb = x_ref[...].astype(jnp.float32)
        sum_ref[...] += jnp.sum(xb, axis=0, keepdims=True)
        sq_ref[...] += jnp.sum(xb * xb, axis=0, keepdims=True)

    @pl.when(p == 1)
    def _apply():
        mean = sum_ref[...] / m_total
        # one-pass variance, clamped like the unfused bf16 batch_norm
        varv = jnp.maximum(sq_ref[...] / m_total - mean * mean, 0.0)

        @pl.when(m == 0)
        def _stats():
            mean_ref[...] = mean
            var_ref[...] = varv

        inv = jax.lax.rsqrt(varv + eps)
        xb = x_ref[...].astype(jnp.float32)
        y = (xb - mean) * (inv * scale_ref[...]) + bias_ref[...]
        y = y.astype(ybn_ref.dtype)
        ybn_ref[...] = y
        if yact_ref is not None:
            yact_ref[...] = act(y)


def _pallas_bn_act(x2, scale, bias, eps, act_fn):
    """x2: [M, C] bf16 (C % 128 == 0, M % 8 == 0). Returns (ybn, yact,
    mean, var) with yact None-shaped out when act_fn is None."""
    from jax.experimental import pallas as pl
    from .pallas_attention import _compiler_params, _interpret, _scratch
    m_total, c = x2.shape
    bc = 128
    bm = next(b for b in (512, 256, 128, 64, 32, 16, 8) if m_total % b == 0)
    grid = (c // bc, 2, m_total // bm)

    x_spec = pl.BlockSpec((bm, bc), lambda cc, p, mm: (mm, cc))
    vec_spec = pl.BlockSpec((1, bc), lambda cc, p, mm: (0, cc))
    out_specs = [x_spec] + ([x_spec] if act_fn is not None else []) + \
        [vec_spec, vec_spec]
    out_shape = [jax.ShapeDtypeStruct((m_total, c), x2.dtype)]
    if act_fn is not None:
        out_shape.append(jax.ShapeDtypeStruct((m_total, c), x2.dtype))
    out_shape += [jax.ShapeDtypeStruct((1, c), jnp.float32),
                  jax.ShapeDtypeStruct((1, c), jnp.float32)]

    kernel = functools.partial(_bn_act_kernel, eps=eps, act=act_fn,
                               m_total=float(m_total))
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[x_spec, vec_spec, vec_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[_scratch((1, bc)), _scratch((1, bc))],
        interpret=_interpret(),
        compiler_params=_compiler_params(
            ("parallel", "arbitrary", "arbitrary")),
    )(x2, scale.reshape(1, c), bias.reshape(1, c))
    if act_fn is not None:
        ybn, yact, mean, var = outs
    else:
        ybn, mean, var = outs
        yact = None
    return ybn, yact, mean.reshape(c), var.reshape(c)


def _fold_lower(ctx, op_, g: Group, env):
    """Inference-mode conv+bn[+act]: BN folds into the conv filter and a
    channel bias — y = conv(x, w * s) + (bias - mean * s) with
    s = scale/sqrt(var+eps) — and the conv's own output is never
    materialized (the plan guaranteed bn is its only consumer). The
    folded filter goes through the REGISTERED conv lowering (a view of
    the conv op whose Output name is bn's Y), so NHWC layout handling and
    AMP casts stay identical."""
    from .registry import get as reg_get
    conv, bn, act = g.conv, g.bn, g.act
    w = jnp.asarray(env[_first(conv.desc.input("Filter"))])
    scale = jnp.asarray(env[_first(bn.desc.input("Scale"))]).astype(
        jnp.float32)
    bias = jnp.asarray(env[_first(bn.desc.input("Bias"))]).astype(
        jnp.float32)
    mean = jnp.asarray(env[_first(bn.desc.input("Mean"))]).astype(
        jnp.float32)
    var = jnp.asarray(env[_first(bn.desc.input("Variance"))]).astype(
        jnp.float32)
    eps = bn.attr("epsilon", 1e-5)
    s = scale * jax.lax.rsqrt(var + eps)
    # OIHW filter: fold scales the output-channel dim (groups included)
    wf = (w.astype(jnp.float32) * s.reshape((-1,) + (1,) * (w.ndim - 1))
          ).astype(w.dtype)
    bf = bias - mean * s

    y_name = _first(bn.desc.output("Y"))
    view_desc = OpDesc(type=conv.type, inputs=dict(conv.desc.inputs),
                       outputs={"Output": [y_name]},
                       attrs=dict(conv.desc.attrs))
    conv_view = _synth_operator(getattr(conv, "block", None), view_desc,
                                getattr(conv, "creation_site", None))
    conv_ins = {slot: [env.get(n) for n in names]
                for slot, names in conv.desc.inputs.items()}
    conv_ins["Filter"] = [wf]
    y = reg_get(conv.type).lower(ctx, conv_view, conv_ins)["Output"][0]
    tag = ctx.layout_overrides.get(y_name)
    bfc = bf.astype(y.dtype)   # AMP O2: keep bf16 activations bf16
    if tag is not None:
        y = y + bfc.reshape((1,) * (y.ndim - 1) + (-1,))
    else:
        y = y + bfc.reshape((1, -1) + (1,) * (y.ndim - 2))
    env[y_name] = y
    if tag is not None:
        ctx.layouts[y_name] = tag
    # is_test BN passes running stats through all four stat outputs
    env[_first(bn.desc.output("MeanOut"))] = env[_first(bn.desc.input("Mean"))]
    env[_first(bn.desc.output("VarianceOut"))] = \
        env[_first(bn.desc.input("Variance"))]
    env[_first(bn.desc.output("SavedMean"))] = \
        env[_first(bn.desc.input("Mean"))]
    env[_first(bn.desc.output("SavedVariance"))] = \
        env[_first(bn.desc.input("Variance"))]
    if act is not None:
        out = _first(act.desc.output("Out"))
        env[out] = _activations[act.type](y, act)
        if tag is not None:
            ctx.layouts[out] = tag
    _freeze(ctx, env, _out_names(op_))
    return _collect(op_, env)


# --- bucketed optimizer lowerings ---------------------------------------

def _flat_params_grads(ins):
    ps = [jnp.asarray(v) for v in ins["Param"]]
    shapes = [p.shape for p in ps]
    # per-tensor upcast BEFORE the concat — exactly _param_grad per member
    gs = [jnp.asarray(maybe_dense(gv)).astype(p.dtype)
          for p, gv in zip(ps, ins["Grad"])]
    return _cat(ps), _cat(gs), shapes


def _cat(vals):
    flats = [jnp.asarray(v).ravel() for v in vals]
    return flats[0] if len(flats) == 1 else jnp.concatenate(flats)


def _split(flat, shapes):
    out = []
    off = 0
    for s in shapes:
        n = int(np.prod(s)) if len(s) else 1
        out.append(flat[off:off + n].reshape(s))
        off += n
    return out


def _lower_fused_sgd(ctx, op_, ins):
    p, grad, shapes = _flat_params_grads(ins)
    po = optimizer_ops.sgd_dense(p, grad, optimizer_ops._lr(ins))
    return {"ParamOut": _split(po, shapes)}


def _lower_fused_momentum(ctx, op_, ins):
    p, grad, shapes = _flat_params_grads(ins)
    v = _cat(ins["Velocity"])
    po, vo = optimizer_ops.momentum_dense(
        p, grad, v, optimizer_ops._lr(ins), op_.attr("mu"),
        op_.attr("use_nesterov", False))
    return {"ParamOut": _split(po, shapes),
            "VelocityOut": _split(vo, shapes)}


def _lower_fused_adam(ctx, op_, ins):
    p, grad, shapes = _flat_params_grads(ins)
    m1 = _cat(ins["Moment1"])
    m2 = _cat(ins["Moment2"])
    b1p = jnp.asarray(ins["Beta1Pow"][0]).reshape(())
    b2p = jnp.asarray(ins["Beta2Pow"][0]).reshape(())
    po, m1o, m2o = optimizer_ops.adam_dense(
        p, grad, m1, m2, optimizer_ops._lr(ins), op_.attr("beta1", 0.9),
        op_.attr("beta2", 0.999), op_.attr("epsilon", 1e-8), b1p, b2p)
    return {"ParamOut": _split(po, shapes),
            "Moment1Out": _split(m1o, shapes),
            "Moment2Out": _split(m2o, shapes)}


def _sparse_bucket_lower(ctx, op_, ins):
    """Fused scatter-apply bucket: run each member optimizer op (whose
    lowering hits the sparse_ops scatter-apply kernel, including the
    sharded-table pin-back) under ONE fused scope/observer entry — the
    values are bitwise identical to the per-param sparse path, only the
    attribution unit changes, mirroring _compose_lower for dense windows."""
    env = ctx.env
    with _muted_observers():
        for m in op_.attr("__sparse_members__"):
            ctx.executor._exec_op(ctx, m, env)
    _freeze(ctx, env, _out_names(op_))
    return _collect(op_, env)


# --- registration -------------------------------------------------------

register("fused_conv_bn_act", lower=_conv_bn_act_lower, grad=NO_GRAD)
register("fused_bn_act", lower=_conv_bn_act_lower, grad=NO_GRAD)
register("fused_fc_act", lower=_compose_lower, grad=NO_GRAD)
register("fused_chain", lower=_compose_lower, grad=NO_GRAD)
register("fused_sgd", lower=_lower_fused_sgd, grad=NO_GRAD)
register("fused_momentum", lower=_lower_fused_momentum, grad=NO_GRAD)
register("fused_adam", lower=_lower_fused_adam, grad=NO_GRAD)
register("fused_sparse_sgd", lower=_sparse_bucket_lower, grad=NO_GRAD)
register("fused_sparse_momentum", lower=_sparse_bucket_lower, grad=NO_GRAD)
register("fused_sparse_adam", lower=_sparse_bucket_lower, grad=NO_GRAD)

# fused ops manage layout tags themselves (member-level prepass/
# tag_outputs run inside the lowerings); without this the executor's
# prepass would barrier-canonicalize every tagged input of the window
layout_mod.AWARE_OPS.update(FUSED_OP_TYPES)
