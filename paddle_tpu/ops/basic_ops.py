"""Basic ops: fills, randoms, casts, shape manipulation, indexing.

TPU-native lowerings of the reference ops in paddle/fluid/operators/
(fill_constant_op.cc, uniform_random_op.cc, gaussian_random_op.cc, cast_op.cc,
scale_op.cc, reshape_op.cc, transpose_op.cc, concat_op.cc, split_op.cc,
expand_op.cc, gather_op.cc, scatter_op.cc, one_hot_op.cc, top_k_op.cc,
clip_op.cc, assign_op.cc, increment_op.cc, sign_op.cc …).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .registry import NO_GRAD, op, register
from .common import (in_var, out_var, same_as_input, set_out, to_np_dtype)


# --- feed / fetch are executor-level (reference feed_op.cc/fetch_op.cc) -----
register("feed", no_kernel=True, grad=NO_GRAD)
register("fetch", no_kernel=True, grad=NO_GRAD)


# --- fills ------------------------------------------------------------------

def _fill_constant_infer(op_, block):
    set_out(op_, block, "Out", op_.attr("shape"), op_.attr("dtype", "float32"))


@op("fill_constant", infer_shape=_fill_constant_infer, grad=NO_GRAD)
def _fill_constant(ctx, op_, ins):
    dtype = to_np_dtype(op_.attr("dtype", "float32"))
    return {"Out": [jnp.full(tuple(op_.attr("shape")),
                             op_.attr("value", 0.0), dtype=dtype)]}


def _fill_like_infer(op_, block):
    iv = in_var(op_, block, "X")
    if iv is not None:
        set_out(op_, block, "Out", iv.shape, op_.attr("dtype") or iv.dtype)


@op("fill_zeros_like", infer_shape=_fill_like_infer, grad=NO_GRAD)
def _fill_zeros_like(ctx, op_, ins):
    x = ins["X"][0]
    return {"Out": [jnp.zeros_like(x)]}


def _fill_bsl_infer(op_, block):
    shape = list(op_.attr("shape"))
    iv = in_var(op_, block, "Input")
    in_idx = op_.attr("input_dim_idx", 0)
    out_idx = op_.attr("output_dim_idx", 0)
    if iv is not None and iv.shape is not None:
        shape[out_idx] = iv.shape[in_idx]
    set_out(op_, block, "Out", shape, op_.attr("dtype", "float32"))


@op("fill_constant_batch_size_like", infer_shape=_fill_bsl_infer, grad=NO_GRAD)
def _fill_constant_bsl(ctx, op_, ins):
    x = ins["Input"][0]
    shape = list(op_.attr("shape"))
    shape[op_.attr("output_dim_idx", 0)] = x.shape[op_.attr("input_dim_idx", 0)]
    dtype = to_np_dtype(op_.attr("dtype", "float32"))
    return {"Out": [jnp.full(tuple(shape), op_.attr("value", 0.0), dtype=dtype)]}


def _fill_tensor_infer(op_, block):
    set_out(op_, block, "Out", op_.attr("shape"), op_.attr("dtype", "float32"))


@op("fill_constant_tensor", infer_shape=_fill_tensor_infer, grad=NO_GRAD)
def _fill_constant_tensor(ctx, op_, ins):
    """Materialize a literal ndarray (layers.assign of numpy data)."""
    vals = np.asarray(op_.attr("values"),
                      dtype=to_np_dtype(op_.attr("dtype", "float32")))
    return {"Out": [jnp.asarray(vals.reshape(tuple(op_.attr("shape"))))]}


def _arg_infer(op_, block):
    iv = in_var(op_, block, "X")
    axis = op_.attr("axis", 0)
    if iv is not None and iv.shape is not None:
        shape = [d for i, d in enumerate(iv.shape) if i != axis % len(iv.shape)]
        set_out(op_, block, "Out", shape or [1], "int64")


@op("arg_max", infer_shape=_arg_infer, grad=NO_GRAD)
def _arg_max(ctx, op_, ins):
    return {"Out": [jnp.argmax(jnp.asarray(ins["X"][0]),
                               axis=op_.attr("axis", 0)).astype(jnp.int64)]}


@op("arg_min", infer_shape=_arg_infer, grad=NO_GRAD)
def _arg_min(ctx, op_, ins):
    return {"Out": [jnp.argmin(jnp.asarray(ins["X"][0]),
                               axis=op_.attr("axis", 0)).astype(jnp.int64)]}


# --- randoms ----------------------------------------------------------------

@op("uniform_random", infer_shape=_fill_constant_infer, grad=NO_GRAD)
def _uniform_random(ctx, op_, ins):
    dtype = to_np_dtype(op_.attr("dtype", "float32"))
    key = ctx.next_rng(op_)
    return {"Out": [jax.random.uniform(
        key, tuple(op_.attr("shape")), dtype=jnp.float32,
        minval=op_.attr("min", -1.0), maxval=op_.attr("max", 1.0)
    ).astype(dtype)]}


@op("gaussian_random", infer_shape=_fill_constant_infer, grad=NO_GRAD)
def _gaussian_random(ctx, op_, ins):
    dtype = to_np_dtype(op_.attr("dtype", "float32"))
    key = ctx.next_rng(op_)
    out = op_.attr("mean", 0.0) + op_.attr("std", 1.0) * jax.random.normal(
        key, tuple(op_.attr("shape")), dtype=jnp.float32)
    return {"Out": [out.astype(dtype)]}


@op("uniform_random_batch_size_like", infer_shape=_fill_bsl_infer, grad=NO_GRAD)
def _uniform_random_bsl(ctx, op_, ins):
    x = ins["Input"][0]
    shape = list(op_.attr("shape"))
    shape[op_.attr("output_dim_idx", 0)] = x.shape[op_.attr("input_dim_idx", 0)]
    dtype = to_np_dtype(op_.attr("dtype", "float32"))
    key = ctx.next_rng(op_)
    return {"Out": [jax.random.uniform(
        key, tuple(shape), dtype=jnp.float32,
        minval=op_.attr("min", -1.0), maxval=op_.attr("max", 1.0)).astype(dtype)]}


@op("gaussian_random_batch_size_like", infer_shape=_fill_bsl_infer, grad=NO_GRAD)
def _gaussian_random_bsl(ctx, op_, ins):
    x = ins["Input"][0]
    shape = list(op_.attr("shape"))
    shape[op_.attr("output_dim_idx", 0)] = x.shape[op_.attr("input_dim_idx", 0)]
    dtype = to_np_dtype(op_.attr("dtype", "float32"))
    key = ctx.next_rng(op_)
    out = op_.attr("mean", 0.0) + op_.attr("std", 1.0) * jax.random.normal(
        key, tuple(shape), dtype=jnp.float32)
    return {"Out": [out.astype(dtype)]}


# --- assign / cast / scale --------------------------------------------------

@op("assign", infer_shape=same_as_input("X", "Out"))
def _assign(ctx, op_, ins):
    return {"Out": [jnp.asarray(ins["X"][0])]}


def _cast_infer(op_, block):
    iv = in_var(op_, block, "X")
    set_out(op_, block, "Out", iv.shape if iv else None,
            op_.attr("out_dtype", "float32"))


def _cast_grad(fwd, no_grad_set):
    from ..framework.desc import OpDesc
    from ..framework.framework import grad_var_name
    xname = fwd.input("X")[0]
    if xname in no_grad_set:
        return []
    return [OpDesc(type="cast",
                   inputs={"X": [grad_var_name(fwd.output("Out")[0])]},
                   outputs={"Out": [grad_var_name(xname)]},
                   attrs={"in_dtype": fwd.attr("out_dtype", "float32"),
                          "out_dtype": fwd.attr("in_dtype", "float32")})]


@op("cast", infer_shape=_cast_infer, grad=_cast_grad)
def _cast(ctx, op_, ins):
    return {"Out": [jnp.asarray(ins["X"][0]).astype(
        to_np_dtype(op_.attr("out_dtype", "float32")))]}


@op("scale", infer_shape=same_as_input())
def _scale(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    s = op_.attr("scale", 1.0)
    b = op_.attr("bias", 0.0)
    if op_.attr("bias_after_scale", True):
        return {"Out": [x * s + b]}
    return {"Out": [(x + b) * s]}


@op("increment", infer_shape=same_as_input())  # d(x+c)/dx = 1: generic vjp
def _increment(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    return {"Out": [x + jnp.asarray(op_.attr("step", 1.0), dtype=x.dtype)]}


@op("sign", infer_shape=same_as_input(), grad=NO_GRAD)
def _sign(ctx, op_, ins):
    return {"Out": [jnp.sign(jnp.asarray(ins["X"][0]))]}


@op("clip", infer_shape=same_as_input())
def _clip(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    return {"Out": [jnp.clip(x, op_.attr("min"), op_.attr("max"))]}


@op("clip_by_norm", infer_shape=same_as_input())
def _clip_by_norm(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    max_norm = op_.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(x * x))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": [x * scale.astype(x.dtype)]}


# --- shape manipulation -----------------------------------------------------

def _reshape_infer(op_, block):
    iv = in_var(op_, block, "X")
    shape = list(op_.attr("shape"))
    if iv is not None and iv.shape is not None and all(
            s is not None for s in iv.shape):
        src = list(iv.shape)
        # resolve 0 (copy dim) then -1 (infer)
        shape = [src[i] if s == 0 else s for i, s in enumerate(shape)]
        if -1 in shape and all(s > 0 for s in src):
            total = int(np.prod(src))
            known = int(np.prod([s for s in shape if s != -1]))
            shape[shape.index(-1)] = total // known
    set_out(op_, block, "Out", shape, iv.dtype if iv else None)


@op("reshape", infer_shape=_reshape_infer)
def _reshape(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    shape = list(op_.attr("shape"))
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return {"Out": [x.reshape(tuple(shape))]}


def _transpose_infer(op_, block):
    iv = in_var(op_, block, "X")
    axis = op_.attr("axis")
    if iv is not None and iv.shape is not None:
        set_out(op_, block, "Out", [iv.shape[a] for a in axis], iv.dtype)


@op("transpose", infer_shape=_transpose_infer)
def _transpose(ctx, op_, ins):
    return {"Out": [jnp.transpose(jnp.asarray(ins["X"][0]), op_.attr("axis"))]}


def _same_shape_infer(op_, block):
    iv = in_var(op_, block, "X")
    if iv is not None and iv.shape is not None:
        set_out(op_, block, "Out", list(iv.shape), iv.dtype)


@op("reverse", infer_shape=_same_shape_infer)
def _reverse(ctx, op_, ins):
    """Flip along the given axes (serves the v2 rotate layer, the gserver
    RotateLayer capability — reference gserver/layers/RotateLayer.cpp;
    linear, so the generic vjp gives the exact gradient)."""
    return {"Out": [jnp.flip(jnp.asarray(ins["X"][0]),
                             tuple(op_.attr("axis")))]}


def _concat_infer(op_, block):
    axis = op_.attr("axis", 0)
    shapes = []
    for i in range(len(op_.desc.inputs.get("X", []))):
        v = in_var(op_, block, "X", i)
        if v is None or v.shape is None:
            return
        shapes.append(list(v.shape))
    if any(len(s) <= axis for s in shapes):
        # rank not statically known for some input (e.g. a var produced by
        # an op whose infer bailed); leave the shape to runtime
        return
    out = list(shapes[0])
    if any(s[axis] is None or s[axis] < 0 for s in shapes):
        out[axis] = -1
    else:
        out[axis] = sum(s[axis] for s in shapes)
    set_out(op_, block, "Out", out, in_var(op_, block, "X").dtype)


@op("concat", infer_shape=_concat_infer)
def _concat(ctx, op_, ins):
    return {"Out": [jnp.concatenate([jnp.asarray(x) for x in ins["X"]],
                                    axis=op_.attr("axis", 0))]}


def _split_infer(op_, block):
    iv = in_var(op_, block, "X")
    axis = op_.attr("axis", 0)
    n = len(op_.desc.outputs.get("Out", []))
    sections = op_.attr("sections") or None
    if iv is None or iv.shape is None:
        return
    for i in range(n):
        s = list(iv.shape)
        if sections:
            s[axis] = sections[i]
        elif s[axis] is not None and s[axis] > 0:
            s[axis] = s[axis] // n
        set_out_i(op_, block, "Out", i, s, iv.dtype)


def set_out_i(op_, block, slot, i, shape, dtype):
    v = out_var(op_, block, slot, i)
    if v is not None:
        v.shape = list(shape) if shape is not None else None
        v.dtype = dtype


@op("split", infer_shape=_split_infer)
def _split(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    axis = op_.attr("axis", 0)
    sections = op_.attr("sections") or None
    n = op_.attr("num", 0) or len(op_.desc.outputs["Out"])
    if sections:
        idxs = np.cumsum(sections)[:-1].tolist()
        parts = jnp.split(x, idxs, axis=axis)
    else:
        parts = jnp.split(x, n, axis=axis)
    return {"Out": list(parts)}


def _expand_infer(op_, block):
    iv = in_var(op_, block, "X")
    times = op_.attr("expand_times")
    if iv is not None and iv.shape is not None:
        set_out(op_, block, "Out",
                [None if d is None else d * t
                 for d, t in zip(iv.shape, times)], iv.dtype)


@op("expand", infer_shape=_expand_infer)
def _expand(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    return {"Out": [jnp.tile(x, tuple(op_.attr("expand_times")))]}


def _squeeze_axes(shape, axes):
    if axes:
        axes = [a % len(shape) for a in axes]
        return [d for i, d in enumerate(shape) if i not in axes]
    return [d for d in shape if d != 1]


def _squeeze_infer(op_, block):
    iv = in_var(op_, block, "X")
    if iv is not None and iv.shape is not None:
        set_out(op_, block, "Out",
                _squeeze_axes(list(iv.shape), op_.attr("axes") or []), iv.dtype)


@op("squeeze", infer_shape=_squeeze_infer)
def _squeeze(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    axes = op_.attr("axes") or []
    return {"Out": [x.reshape(tuple(_squeeze_axes(list(x.shape), axes)))]}


def _unsqueeze_infer(op_, block):
    iv = in_var(op_, block, "X")
    if iv is not None and iv.shape is not None:
        shape = list(iv.shape)
        for a in sorted(op_.attr("axes")):
            shape.insert(a, 1)
        set_out(op_, block, "Out", shape, iv.dtype)


@op("unsqueeze", infer_shape=_unsqueeze_infer)
def _unsqueeze(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    for a in sorted(op_.attr("axes")):
        x = jnp.expand_dims(x, a)
    return {"Out": [x]}


# --- indexing ---------------------------------------------------------------

def _gather_infer(op_, block):
    xv, iv = in_var(op_, block, "X"), in_var(op_, block, "Index")
    if xv is not None and xv.shape is not None and iv is not None \
            and iv.shape is not None:
        set_out(op_, block, "Out", list(iv.shape[:1]) + list(xv.shape[1:]),
                xv.dtype)


@op("gather", infer_shape=_gather_infer, non_diff_inputs=("Index",))
def _gather(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    idx = jnp.asarray(ins["Index"][0]).reshape(-1)
    return {"Out": [jnp.take(x, idx, axis=0)]}


@op("scatter", infer_shape=same_as_input("X", "Out"),
    non_diff_inputs=("Ids",))
def _scatter(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    ids = jnp.asarray(ins["Ids"][0]).reshape(-1)
    upd = jnp.asarray(ins["Updates"][0])
    return {"Out": [x.at[ids].set(upd)]}


def _one_hot_infer(op_, block):
    iv = in_var(op_, block, "X")
    if iv is not None and iv.shape is not None:
        shape = list(iv.shape)
        if shape and shape[-1] == 1:
            shape = shape[:-1]
        set_out(op_, block, "Out", shape + [op_.attr("depth")], "float32")


@op("one_hot", infer_shape=_one_hot_infer, grad=NO_GRAD)
def _one_hot(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    if x.ndim and x.shape[-1] == 1:
        x = x.reshape(x.shape[:-1])
    return {"Out": [jax.nn.one_hot(x, op_.attr("depth"), dtype=jnp.float32)]}


def _top_k_infer(op_, block):
    iv = in_var(op_, block, "X")
    k = op_.attr("k", 1)
    if iv is not None and iv.shape is not None:
        shape = list(iv.shape[:-1]) + [k]
        set_out(op_, block, "Out", shape, iv.dtype)
        set_out(op_, block, "Indices", shape, "int64")


@op("top_k", infer_shape=_top_k_infer, grad=NO_GRAD)
def _top_k(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    vals, idxs = jax.lax.top_k(x, op_.attr("k", 1))
    return {"Out": [vals], "Indices": [idxs.astype(jnp.int64)]}


@op("multiplex", non_diff_inputs=("Ids",))
def _multiplex(ctx, op_, ins):
    ids = jnp.asarray(ins["Ids"][0]).reshape(-1)
    stack = jnp.stack([jnp.asarray(x) for x in ins["X"]], axis=0)
    rows = jnp.arange(stack.shape[1])
    return {"Out": [stack[ids, rows]]}


# --- compare / logical (reference compare_op.cc, logical_op.cc) -------------

def _cmp_infer(op_, block):
    xv = in_var(op_, block, "X")
    if xv is not None:
        set_out(op_, block, "Out", xv.shape, "bool")


_cmp_fns = {"less_than": jnp.less, "less_equal": jnp.less_equal,
            "greater_than": jnp.greater, "greater_equal": jnp.greater_equal,
            "equal": jnp.equal, "not_equal": jnp.not_equal}


def _make_cmp(fn):
    def lower(ctx, op_, ins):
        return {"Out": [fn(jnp.asarray(ins["X"][0]), jnp.asarray(ins["Y"][0]))]}
    return lower


for _n, _f in _cmp_fns.items():
    register(_n, lower=_make_cmp(_f), infer_shape=_cmp_infer, grad=NO_GRAD)

_logical_fns = {"logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
                "logical_xor": jnp.logical_xor}


def _make_logical(fn):
    def lower(ctx, op_, ins):
        return {"Out": [fn(jnp.asarray(ins["X"][0]), jnp.asarray(ins["Y"][0]))]}
    return lower


for _n, _f in _logical_fns.items():
    register(_n, lower=_make_logical(_f), infer_shape=_cmp_infer, grad=NO_GRAD)


@op("logical_not", infer_shape=_cmp_infer, grad=NO_GRAD)
def _logical_not(ctx, op_, ins):
    return {"Out": [jnp.logical_not(jnp.asarray(ins["X"][0]))]}


@op("is_empty", grad=NO_GRAD)
def _is_empty(ctx, op_, ins):
    x = jnp.asarray(ins["X"][0])
    return {"Out": [jnp.asarray(x.size == 0)]}


# --- shape/metadata queries -------------------------------------------------

@op("shape", grad=NO_GRAD)
def _shape(ctx, op_, ins):
    x = ins["Input"][0] if "Input" in op_.desc.inputs else ins["X"][0]
    return {"Out": [jnp.asarray(np.asarray(jnp.asarray(x).shape,
                                           dtype=np.int64))]}
