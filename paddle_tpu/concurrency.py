"""Go-style channels + select (reference: paddle/fluid/framework/
channel.h:25-86 buffered/unbuffered semantics, python/paddle/fluid/
concurrency.py:27-429 make_channel/channel_send/channel_recv/
channel_close/Go/Select).

DESIGN (closes the F15 gap the TPU way): the reference's channels are IR
ops executed by its interpreted C++ executor — concurrency INSIDE the
graph. Under a whole-block XLA compile there is no interpreter to
schedule against, and every in-graph use of channels (double buffering,
reader pipelines, parameter prefetch) is subsumed by compiled dataflow +
the reader machinery. What survives is the HOST-side role: orchestrating
Python producers/consumers around the compiled step (exactly where the
reference demos used them — feeding queues from IO threads). So this
module implements the same user surface with the same semantics at the
host level, over threads:

  ch = make_channel(capacity=0)     # 0 = unbuffered rendezvous
  go(producer, ch)                  # goroutine = daemon thread
  channel_send(ch, x)               # blocks per Go semantics
  val, ok = channel_recv(ch)        # ok=False once closed AND drained
  channel_close(ch)
  Select().case(...).default(...).run()

Semantics match framework/channel.h: unbuffered sends rendezvous with a
receiver; buffered sends block only when full; close wakes all blockers,
pending buffered items still drain, receives on a drained closed channel
return (None, False), and sending on a closed channel raises
ChannelClosedError (the reference PADDLE_ENFORCEs)."""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

__all__ = ["Channel", "ChannelClosedError", "make_channel", "channel_send",
           "channel_recv", "channel_close", "go", "Go", "Select"]


class ChannelClosedError(RuntimeError):
    """Send attempted on a closed channel (channel.h: enforced error)."""


class _Offer:
    """One unbuffered send in flight: the value plus its handoff flag."""

    __slots__ = ("value", "taken")

    def __init__(self, value):
        self.value = value
        self.taken = False


class Channel:
    """Blocking FIFO channel; capacity 0 means rendezvous (channel.h:25:
    an unbuffered send completes only when a receiver takes the value)."""

    def __init__(self, capacity: int = 0, dtype=None):
        if capacity < 0:
            raise ValueError("channel capacity must be >= 0")
        self.capacity = capacity
        self.dtype = dtype           # kept for reference API parity
        self._buf: List[Any] = []
        self._offers: List[_Offer] = []
        self._closed = False
        self._cond = threading.Condition()

    # --- core ops -----------------------------------------------------------
    def send(self, value, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            if self._closed:
                raise ChannelClosedError("send on closed channel")
            if self.capacity > 0:
                while len(self._buf) >= self.capacity and not self._closed:
                    if not self._wait(deadline):
                        raise TimeoutError("channel send timed out")
                if self._closed:
                    raise ChannelClosedError("send on closed channel")
                self._buf.append(value)
                self._cond.notify_all()
                return
            offer = _Offer(value)
            self._offers.append(offer)
            self._cond.notify_all()
            while not offer.taken and not self._closed:
                if not self._wait(deadline):
                    if offer.taken:  # taken exactly at the deadline:
                        return       # the value WAS delivered
                    if offer in self._offers:
                        self._offers.remove(offer)
                    raise TimeoutError("channel send timed out")
            if not offer.taken:      # closed under us (Go: send panics)
                if offer in self._offers:
                    self._offers.remove(offer)
                raise ChannelClosedError("channel closed during send")

    def recv(self, timeout: Optional[float] = None) -> Tuple[Any, bool]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                got = self._try_recv_locked()
                if got is not None:
                    return got
                if self._closed:
                    return None, False
                if not self._wait(deadline):
                    raise TimeoutError("channel recv timed out")

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # --- non-blocking forms (Select) ---------------------------------------
    def try_send(self, value, wait: float = 0.002) -> bool:
        """Non-blocking-in-spirit send: buffered channels commit or fail
        instantly; an unbuffered channel posts the offer, gives any
        receiver — blocked in recv() OR polling via another Select —
        `wait` seconds (2 ms, a few Select poll periods) to take it, then
        withdraws. The brief window is what lets two Selects rendezvous
        on a capacity-0 channel instead of livelocking."""
        with self._cond:
            if self._closed:
                raise ChannelClosedError("send on closed channel")
            if self.capacity > 0:
                if len(self._buf) >= self.capacity:
                    return False
                self._buf.append(value)
                self._cond.notify_all()
                return True
            offer = _Offer(value)
            self._offers.append(offer)
            self._cond.notify_all()
            deadline = time.monotonic() + wait
            while not offer.taken and not self._closed:
                if not self._wait(deadline):
                    break
            if not offer.taken:
                if offer in self._offers:
                    self._offers.remove(offer)
                return False
            return True

    def try_recv(self) -> Optional[Tuple[Any, bool]]:
        """(value, True) if a value was available, (None, False) if closed
        and drained, None if nothing is ready yet."""
        with self._cond:
            got = self._try_recv_locked()
            if got is not None:
                return got
            if self._closed:
                return None, False
            return None

    # --- helpers ------------------------------------------------------------
    def _try_recv_locked(self):
        if self._buf:
            value = self._buf.pop(0)
            self._cond.notify_all()
            return value, True
        while self._offers:
            offer = self._offers.pop(0)
            if not offer.taken:
                offer.taken = True
                self._cond.notify_all()
                return offer.value, True
        return None

    def _wait(self, deadline) -> bool:
        if deadline is None:
            self._cond.wait()
            return True
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        self._cond.wait(remaining)
        return time.monotonic() < deadline or self._buf or self._offers \
            or self._closed

    def __len__(self):
        """Buffered backlog only — Go semantics: len() of an unbuffered
        channel is always 0, even with senders blocked in rendezvous."""
        with self._cond:
            return len(self._buf)


def make_channel(dtype=None, capacity: int = 0) -> Channel:
    """(reference concurrency.py:279) dtype is recorded, not enforced —
    the host-level channel carries arbitrary Python/numpy values."""
    return Channel(capacity=capacity, dtype=dtype)


def channel_send(channel: Channel, value, is_copy: bool = False,
                 timeout: Optional[float] = None) -> None:
    """(reference concurrency.py:335) is_copy mirrors the reference
    signature: True snapshots numpy values so later in-place mutation by
    the producer can't race the consumer."""
    if is_copy:
        import copy as _copy
        value = _copy.deepcopy(value)
    channel.send(value, timeout=timeout)


def channel_recv(channel: Channel, return_value=None,
                 timeout: Optional[float] = None) -> Tuple[Any, bool]:
    """(reference concurrency.py:385) -> (value, ok). `return_value` is
    accepted for signature parity (the reference used it as the output
    var holder)."""
    return channel.recv(timeout=timeout)


def channel_close(channel: Channel) -> None:
    """(reference concurrency.py:426)"""
    channel.close()


def go(fn: Callable, *args, name: Optional[str] = None,
       **kwargs) -> threading.Thread:
    """Launch fn concurrently — the goroutine (reference Go block,
    concurrency.py:27). The reference's `with Go():` captured an IR
    sub-block to run on executor threads; Python executes a with-body
    eagerly, so the honest host-level surface is a function launcher.
    Returns the (daemon) thread for joining. Threads are named
    ``pd-go-<fn name>`` (override with ``name=``) so sentinel hang
    reports and the thread census render readable identities."""
    t = threading.Thread(
        target=fn, args=args, kwargs=kwargs, daemon=True,
        name=name or f"pd-go-{getattr(fn, '__name__', 'fn')}")
    t.start()
    return t


Go = go   # reference-name alias


class Select:
    """Go-style select over channel operations (reference Select,
    concurrency.py:193): blocks until one registered case can run, picks
    uniformly among ready cases, runs its callback, returns the case
    index. .default() makes it non-blocking."""

    _POLL = 0.0005

    def __init__(self):
        self._cases = []             # (kind, channel, value, callback)
        self._default = None

    def case(self, action: str, channel: Channel, value=None,
             callback: Optional[Callable] = None) -> "Select":
        if action not in ("send", "recv"):
            raise ValueError("Select.case action must be 'send' or 'recv'")
        self._cases.append((action, channel, value, callback))
        return self

    def default(self, callback: Optional[Callable] = None) -> "Select":
        self._default = callback if callback is not None else (lambda: None)
        return self

    def run(self, timeout: Optional[float] = None) -> int:
        """Returns the index of the executed case (-1 for default)."""
        if not self._cases and self._default is None:
            raise ValueError("empty select would block forever")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            order = list(range(len(self._cases)))
            random.shuffle(order)    # Go: uniform choice among ready cases
            for i in order:
                action, ch, value, cb = self._cases[i]
                if action == "recv":
                    got = ch.try_recv()
                    if got is not None:
                        if cb is not None:
                            cb(*got)
                        return i
                else:
                    if ch.try_send(value):
                        if cb is not None:
                            cb()
                        return i
            if self._default is not None:
                self._default()
                return -1
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError("select timed out")
            time.sleep(self._POLL)
