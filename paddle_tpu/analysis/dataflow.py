"""Dataflow checks over the root block (the `dataflow` pass).

Single forward walk for def/use ordering and write-after-write, one
backward liveness sweep from the fetch set for dead code, then the
program-attribute cross-checks: param<->grad pairing recorded by
append_backward (`program._grad_param_pairs`), the donated-state fetch
hazard, and sparse-gradient reachability (`program._sparse_grad_names`
consumers vs executor._SPARSE_AWARE_OPS — the densify cliff the runtime
counter `sparse_densify_fallback_total` only reports after the fact).

Severity policy: use-before-def where a later op DOES produce the var
is an error (the trace will read garbage or throw); an input nothing
ever produces is only a warning when the caller pinned the feed list
(without it, any producer-less var is presumed feedable). Dead ops,
WAW, donated-fetch and densify boundaries are warnings — programs run
fine with them, they just waste memory or FLOPs.
"""

from __future__ import annotations

# ops whose value is their side effect — never dead, and their outputs
# (save paths, print passthroughs) don't need to reach the fetch set
_SIDE_EFFECT_OPS = frozenset({
    "save", "save_combine", "print", "feed", "fetch", "while",
    "conditional_block", "write_to_array", "beam_search_decode",
})


def _reads(op):
    return set(op.input_arg_names)


def _writes(op):
    return set(op.output_arg_names)


def run(pctx):
    block = pctx.block
    ops = pctx.ops
    declared = set(block.desc.vars)
    persistable = {n for n, v in block.desc.vars.items() if v.persistable}

    producers = {}  # var -> [op indices that write it]
    for i, op in enumerate(ops):
        for n in _writes(op):
            producers.setdefault(n, []).append(i)

    feeds = pctx.feeds
    if feeds is None:
        # presume any declared producer-less var is a feed
        feeds = {n for n in declared if n not in producers}

    # --- use-before-def + write-after-write (one forward walk) ---
    defined = set(feeds) | persistable
    last_write = {}  # var -> (op index, read since?)
    for i, op in enumerate(ops):
        for n in sorted(_reads(op)):
            if n in last_write:
                last_write[n] = (last_write[n][0], True)
            if n in defined or n not in declared:
                continue
            later = [j for j in producers.get(n, []) if j >= i]
            if later and later[0] == i and n in _writes(op):
                # in-place accumulator (write_to_array appending to the
                # array it reads, increment counters): the op is its own
                # only producer — the value starts implicitly empty/zero,
                # not garbage, so this is not a use-before-def
                pass
            elif later:
                pctx.emit(
                    "error", "use-before-def",
                    f"reads '{n}' which is only produced later, by op "
                    f"{later[0]} '{ops[later[0]].type}'",
                    op_index=i, var=n,
                    hint="reorder the ops: the producer must be appended "
                         "before this consumer")
            elif pctx.feeds is not None and not n.endswith("@GRAD"):
                # @GRAD names are optional cotangents: zero when absent
                pctx.emit(
                    "warning", "undefined-input",
                    f"reads '{n}' which no op produces and the feed list "
                    f"does not include", op_index=i, var=n)
            defined.add(n)  # one diagnostic per var, not per consumer
        for n in sorted(_writes(op)):
            prev = last_write.get(n)
            if prev is not None and not prev[1] and n not in _reads(op):
                pctx.emit(
                    "warning", "write-after-write",
                    f"overwrites '{n}' before anyone read the value op "
                    f"{prev[0]} '{ops[prev[0]].type}' stored there",
                    op_index=i, var=n,
                    hint="dead store: drop the first writer or give the "
                         "second a fresh output var")
            last_write[n] = (i, False)
            defined.add(n)

    # --- dead code relative to the fetch set (backward liveness) ---
    fetches = set(pctx.fetches)
    if fetches:
        needed = set(fetches)
        live = [False] * len(ops)
        for i in range(len(ops) - 1, -1, -1):
            op = ops[i]
            outs = _writes(op)
            if (outs & needed or op.type in _SIDE_EFFECT_OPS
                    or outs & persistable):  # state updates are the point
                live[i] = True
                needed |= _reads(op)
        for i, op in enumerate(ops):
            if not live[i]:
                pctx.emit(
                    "warning", "dead-op",
                    f"no path from any output {sorted(_writes(op))} to "
                    f"the fetch set", op_index=i,
                    hint="Program.prune(fetches) drops it, or fetch one "
                         "of its results")
        read_anywhere = set()
        for op in ops:
            read_anywhere |= _reads(op)
        for n in sorted(declared - read_anywhere - fetches - persistable):
            if n in producers and all(not live[j] for j in producers[n]):
                continue  # already covered by the dead-op diagnostic
            if n in producers:
                pctx.emit("info", "dead-var",
                          f"'{n}' is computed but never read or fetched",
                          var=n)

    # --- donated persistable state vs fetch ---
    written = set()
    for op in ops:
        written |= _writes(op)
    for n in sorted(fetches):
        if n in persistable and n in written:
            pctx.emit(
                "warning", "donated-fetch",
                f"fetches persistable '{n}', which is also updated "
                f"in-program: its pre-update buffer is donated to XLA, so "
                f"the fetch costs an extra device copy and under "
                f"PADDLE_TPU_STEPS_PER_CALL>1 only the last window value "
                f"is visible", var=n,
                hint="fetch a non-persistable snapshot (assign the value "
                     "to a fresh var) or read the param from the scope "
                     "after run()")

    # --- param<->grad pairing (append_backward's record) ---
    sparse = set(getattr(pctx.program, "_sparse_grad_names", None) or ())
    pairs = getattr(pctx.program, "_grad_param_pairs", None) or []
    from ..framework.desc import VarType
    for pname, gname in pairs:
        pv = block.desc.vars.get(pname)
        gv = block.desc.vars.get(gname)
        if pv is None or gv is None:
            pctx.emit("error", "param-grad-pairing",
                      f"recorded pair ('{pname}', '{gname}') names a var "
                      f"missing from the block", var=pname)
            continue
        if (gname in sparse or gv.type == VarType.SELECTED_ROWS
                or pv.shape is None or gv.shape is None):
            continue
        from .infer import shapes_agree
        if not shapes_agree(pv.shape, gv.shape):
            pctx.emit(
                "error", "param-grad-shape",
                f"param '{pname}' {list(pv.shape)} vs grad '{gname}' "
                f"{list(gv.shape)}", var=gname,
                hint="a desc edit between append_backward and the "
                     "optimizer broke the pairing")
        if gname not in {n for op in ops for n in _reads(op)}:
            pctx.emit("warning", "unused-grad",
                      f"gradient '{gname}' of param '{pname}' is computed "
                      f"but no optimizer op consumes it", var=gname,
                      hint="pass the param to minimize()'s parameter_list "
                           "or drop it from the backward")

    # --- sparse-gradient reachability ---
    if sparse:
        from ..executor import _SPARSE_AWARE_OPS
        for i, op in enumerate(ops):
            hit = sorted(_reads(op) & sparse)
            if hit and op.type not in _SPARSE_AWARE_OPS:
                pctx.emit(
                    "warning", "sparse-densify",
                    f"consumes SelectedRows gradient '{hit[0]}' but has "
                    f"no sparse kernel: the rows densify to the full "
                    f"table at this boundary (O(rows) -> O(table))",
                    op_index=i, var=hit[0],
                    hint="keep the sparse grad chain inside "
                         "{sum, sgd/momentum/adam, fused_sparse_*} or "
                         "accept the densify (counted at runtime by "
                         "sparse_densify_fallback_total)")
