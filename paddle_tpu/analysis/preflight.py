"""Fast-path preflight advisors (the `preflight` pass).

The runtime already measures every missed fast path after the fact
(pallas_fallback_total, fusion_fallback_total, overlap_fallback_total,
sparse_densify_fallback_total). This pass answers the question those
counters can't: *before compile*, which ops will miss, and what
one-line change fixes it. It dry-runs the real gates — never parallel
re-implementations:

  pallas   — pallas_conv.ineligible over abstract NHWC/OIHW avals built
             from the desc shapes (bf16 when the program is
             amp.decorate'd, since mxu_cast runs before the gate); when
             the first answer is "dtype" we probe again in bf16 so an
             AMP suggestion doesn't mask a channels problem behind it.
  quant    — quant.gate_for_op over the quantizable ops' desc avals,
             only when the program is decorated O3 (_quant_mode set):
             which matmul/conv ops will count into quant_fallback_total
             at trace time, per reason, with the one-line fix.
  sharding — `_param_shardings` specs against the mesh axis sizes; GSPMD
             requires every annotated dim divisible by the product of
             its axes, and an axis name the mesh lacks silently means
             "replicated", which is never what the annotation intended.
             These two are the only *errors* this pass emits.
  layout   — NHWC tag propagation walk (layout.AWARE_OPS/AGNOSTIC_OPS):
             ops that force a transpose barrier, as advisory info.
  plans    — fusion.plan / overlap.plan summaries, as advisory info.

Missed fast paths are warnings (the program runs, slower); plan
summaries and layout barriers are info.
"""

from __future__ import annotations

_PROBE_BATCH = 8  # stand-in for symbolic -1 dims; gates never read it


def _conv_hint(reason, ci, co):
    return {
        "disabled": "set PADDLE_TPU_PALLAS_CONV=1 to enable the kernels",
        "rank": "the Pallas kernels only tile 4-D NCHW convs",
        "groups": "grouped/depthwise convs keep the lax.conv path; use "
                  "groups=1 for the MXU kernels",
        "dtype": "run the program under amp.decorate (bf16 on the MXU "
                 "datapath) — f32 convs never take the Pallas route",
        "channels": f"pad channels to a multiple of 128 (Ci={ci}, "
                    f"Co={co}): the MXU tiles lanes in 128s, so e.g. "
                    f"Ci={-(-max(ci, 1) // 128) * 128} keeps the kernel "
                    f"eligible",
        "attrs": "use symmetric 2-element strides/paddings/dilations "
                 "(the [top, bottom, left, right] padding form is not "
                 "tiled)",
        "geometry": "output must stay >= 1x1, padding < effective "
                    "kernel, and padded width <= 2048 (the VMEM row "
                    "budget)",
    }.get(reason, reason)


class _Aval:
    def __init__(self, shape, dtype):
        self.shape = tuple(shape)
        self.ndim = len(shape)
        self.dtype = dtype


def _check_pallas_convs(pctx):
    import jax.numpy as jnp

    from ..ops import pallas_conv

    amp = getattr(pctx.program, "_amp_dtype", None)
    block = pctx.block
    per_reason = {}  # reason -> detailed diagnostics emitted so far
    rollup = {}      # reason -> suppressed count
    for i, op in enumerate(pctx.ops):
        if op.type != "conv2d":
            continue
        xn = (op.desc.input("Input") or [None])[0]
        wn = (op.desc.input("Filter") or [None])[0]
        if not (xn and wn and block.desc.has_var(xn)
                and block.desc.has_var(wn)):
            continue
        xv, wv = block.desc.var(xn), block.desc.var(wn)
        if (xv.shape is None or wv.shape is None
                or len(xv.shape) != 4 or len(wv.shape) != 4):
            continue  # the shapes pass already diagnosed rank problems
        n, c, h, w = (_PROBE_BATCH if d == -1 else d for d in xv.shape)
        # mxu_cast has run by the time the gate sees the operands
        dt = jnp.bfloat16 if amp is not None else jnp.float32
        x = _Aval((n, h, w, c), dt)
        wt = _Aval(wv.shape, dt)
        args = (list(op.attr("strides", [1, 1])),
                list(op.attr("paddings", [0, 0])),
                list(op.attr("dilations", [1, 1])),
                int(op.attr("groups", 1) or 1))
        reason = pallas_conv.ineligible(x, wt, *args)
        if reason is None:
            continue
        ci, co = wv.shape[1], wv.shape[0]
        hint = _conv_hint(reason, ci, co)
        if reason == "dtype":
            # would bf16 alone fix it, or is a deeper miss hiding behind
            # the AMP suggestion?
            deeper = pallas_conv.ineligible(
                _Aval((n, h, w, c), jnp.bfloat16),
                _Aval(wv.shape, jnp.bfloat16), *args)
            if deeper is not None:
                reason = f"dtype, then {deeper}"
                hint = (f"{_conv_hint('dtype', ci, co)}; even then: "
                        f"{_conv_hint(deeper, ci, co)}")
        seen = per_reason.get(reason, 0)
        if seen >= 4:
            # a resnet emits one identical miss per conv — summarize the
            # tail so the first few carry the detail
            rollup[reason] = rollup.get(reason, 0) + 1
            continue
        per_reason[reason] = seen + 1
        pctx.emit(
            "warning", "pallas-conv-fallback",
            f"will take the lax.conv fallback (reason: {reason}) "
            f"instead of the tiled MXU Pallas kernels — forward and "
            f"both grad convs all miss, since they share the gate",
            op_index=i, var=xn, hint=hint)
    for reason, n in sorted(rollup.items()):
        pctx.emit("warning", "pallas-conv-fallback",
                  f"{n} more conv2d op(s) fall back for the same reason "
                  f"({reason}) — details suppressed after the first "
                  f"{per_reason[reason]}")


def _quant_hint(reason, op_type, k):
    return {
        "disabled": "PADDLE_TPU_QUANT=0 is set — unset it (or =1) to "
                    "re-enable the quantized path",
        "mode": "this mode/op pair has no quantized kernel (fp8 needs "
                "backend support and the quant conv is int8-only); use "
                "PADDLE_TPU_QUANT_MODE=int8",
        "rank": "the quantized matmul tiles 2-D operands only",
        "dtype": "operands must reach the gate as bf16/f32 — integer or "
                 "f64 matmuls never quantize",
        "shape": f"contraction depth K={k} must be >= 32 and a multiple "
                 f"of 8 to amortize the scale sweeps on the int8 MXU "
                 f"tile; pad the feature dim",
        "kernel": "the quantized conv rides the Pallas kernel suite — "
                  "fix the pallas-conv-fallback diagnosis first and this "
                  "clears too",
        "error_bound": "the trace-time error estimate exceeds "
                       "PADDLE_TPU_QUANT_TOL; raise the tolerance to "
                       "accept the quantization noise",
    }.get(reason, reason)


def _check_quant(pctx):
    """Dry-run quant.gate_for_op — the REAL eligibility gate, not a
    re-implementation — over every quantizable op's desc avals, so an O3
    program learns before compile which ops will count into
    quant_fallback_total and why. Only runs when the program is actually
    decorated O3 (program._quant_mode set): an O1/O2 program falling
    back everywhere is the configured behavior, not a diagnosis."""
    import jax.numpy as jnp

    from .. import quant

    qmode = getattr(pctx.program, "_quant_mode", None)
    if not qmode:
        return
    block = pctx.block
    slots = {"conv2d": ("Input", "Filter"),
             "depthwise_conv2d": ("Input", "Filter")}
    per_reason = {}
    rollup = {}
    for i, op in enumerate(pctx.ops):
        if op.type not in quant.QUANT_OPS:
            continue
        xslot, yslot = slots.get(op.type, ("X", "Y"))
        xn = (op.desc.input(xslot) or [None])[0]
        yn = (op.desc.input(yslot) or [None])[0]
        if not (xn and yn and block.desc.has_var(xn)
                and block.desc.has_var(yn)):
            continue
        xv, yv = block.desc.var(xn), block.desc.var(yn)
        if xv.shape is None or yv.shape is None:
            continue
        # mxu_cast runs before the gate: O3 operands arrive bf16
        x = _Aval([_PROBE_BATCH if d == -1 else d for d in xv.shape],
                  jnp.bfloat16)
        y = _Aval(yv.shape, jnp.bfloat16)
        try:
            reason = quant.gate_for_op(
                op.type, {xslot: [x], yslot: [y]},
                dict(op.desc.attrs), qmode, nhwc=False)
        except Exception:  # noqa: BLE001 - odd desc shapes: shapes pass
            continue       # already diagnosed those
        if reason is None:
            continue
        k = x.shape[-1] if op.type in ("mul", "matmul") else None
        seen = per_reason.get(reason, 0)
        if seen >= 4:
            rollup[reason] = rollup.get(reason, 0) + 1
            continue
        per_reason[reason] = seen + 1
        pctx.emit(
            "warning", "quant-fallback",
            f"{op.type} will keep the bf16 path under O3 (reason: "
            f"{reason}) and count into quant_fallback_total",
            op_index=i, var=xn, hint=_quant_hint(reason, op.type, k))
    for reason, n in sorted(rollup.items()):
        pctx.emit("warning", "quant-fallback",
                  f"{n} more quantizable op(s) fall back for the same "
                  f"reason ({reason}) — details suppressed after the "
                  f"first {per_reason[reason]}")


def _axis_factor(entry, axis_sizes):
    """(divisor, missing axis names) for one PartitionSpec entry."""
    if entry is None:
        return 1, []
    axes = entry if isinstance(entry, (tuple, list)) else (entry,)
    factor, missing = 1, []
    for a in axes:
        if a in axis_sizes:
            factor *= int(axis_sizes[a])
        else:
            missing.append(a)
    return factor, missing


def _check_shardings(pctx):
    specs = getattr(pctx.program, "_param_shardings", None) or {}
    if not specs:
        return
    mesh = getattr(pctx.program, "_mesh", None)
    if mesh is None:
        pctx.emit("warning", "sharding-no-mesh",
                  f"{len(specs)} parameter(s) carry sharding specs but "
                  f"the program has no mesh — the annotations are dead",
                  hint="tag the program with parallel.make_mesh before "
                       "sharding parameters")
        return
    axis_sizes = dict(getattr(mesh, "shape", None) or {})
    block = pctx.block
    for pname in sorted(specs):
        spec = specs[pname]
        v = block.desc.vars.get(pname)
        if v is None or v.shape is None:
            pctx.emit("error", "sharding-unknown-param",
                      f"sharding spec {spec} names '{pname}', which is "
                      f"not a var of the global block", var=pname)
            continue
        shape = list(v.shape)
        if len(spec) > len(shape):
            pctx.emit("error", "sharding-rank",
                      f"spec {spec} has {len(spec)} entries but "
                      f"'{pname}' is rank {len(shape)} ({shape})",
                      var=pname)
            continue
        for d, entry in enumerate(spec):
            factor, missing = _axis_factor(entry, axis_sizes)
            if missing:
                pctx.emit(
                    "error", "sharding-unknown-axis",
                    f"spec {spec} for '{pname}' names mesh axis "
                    f"'{missing[0]}' but the mesh only has "
                    f"{sorted(axis_sizes) or 'no axes'}", var=pname,
                    hint="GSPMD treats an unknown axis as replicated — "
                         "fix the axis name or rebuild the mesh with it")
                continue
            if factor > 1 and shape[d] != -1 and shape[d] % factor:
                pctx.emit(
                    "error", "sharding-indivisible",
                    f"'{pname}' dim {d} has size {shape[d]}, not "
                    f"divisible by the {factor}-way split of spec entry "
                    f"{entry!r}", var=pname,
                    hint=f"pad the dim to "
                         f"{-(-shape[d] // factor) * factor} or shard a "
                         f"different dim")


def _check_layout(pctx):
    from ..ops import layout as layout_mod

    tagged = set()  # var names carrying an NHWC-family tag
    flagged = set()  # one advisory per op type
    for i, op in enumerate(pctx.ops):
        t = op.type
        base = t[: -len("_grad")] if t.endswith("_grad") else t
        ins = set(op.input_arg_names)
        if base in layout_mod.AWARE_OPS:
            tagged.update(op.output_arg_names)
            continue
        hit = sorted(ins & tagged)
        if not hit:
            continue
        if base in layout_mod.AGNOSTIC_OPS:
            tagged.update(op.output_arg_names)
            continue
        if base not in flagged:
            flagged.add(base)
            pctx.emit(
                "info", "layout-barrier",
                f"consumes NHWC-tagged '{hit[0]}' but is neither "
                f"layout-aware nor layout-agnostic: under "
                f"PADDLE_TPU_LAYOUT_OPT the value transposes back to "
                f"NCHW here", op_index=i, var=hit[0])


def _check_plans(pctx):
    from ..ops import fusion
    from ..parallel import overlap

    program = pctx.program
    if not fusion.FUSION_OPT:
        pctx.emit("info", "fusion-plan",
                  "fusion is disabled (PADDLE_TPU_FUSION=0): every op "
                  "traces individually")
    else:
        groups = fusion.plan(program)
        if groups:
            kinds = {}
            for g in groups.values():
                kinds[g.kind] = kinds.get(g.kind, 0) + 1
            desc = ", ".join(f"{k} x{n}" for k, n in sorted(kinds.items()))
            pctx.emit("info", "fusion-plan",
                      f"{len(groups)} fused window(s): {desc}")

    mesh = getattr(program, "_mesh", None)
    if mesh is None or "dp" not in getattr(mesh, "axis_names", ()):
        return  # overlap only applies to dp-tagged programs
    plan = overlap.plan(program)
    if plan is None:
        pctx.emit("info", "overlap-plan",
                  "dp mesh but no overlap buckets (overlap disabled or "
                  "no dense replicated parameter gradients)")
    else:
        pctx.emit("info", "overlap-plan",
                  f"{len(plan.buckets)} eager all-reduce bucket(s) over "
                  f"{sum(len(b.grads) for b in plan.buckets)} gradient(s)")


def _check_emb_cache(pctx):
    """Beyond-HBM hot-row cache sizing: the per-step touched-row bound
    for a cached table is the total id count its lookups can feed in one
    step (every id distinct in the worst case). When that bound exceeds
    cache_rows, steady-state steps evict rows they staged moments ago —
    and a fused run_steps window, whose whole-id union must be resident
    at once, can fail outright. Static shapes only; -1 dims probe as
    _PROBE_BATCH, so the bound scales with the real batch at runtime."""
    program = pctx.program
    from ..parallel import emb_cache as emb_cache_mod

    sized = {}  # table -> cache_rows (active cache wins over requests)
    cache = emb_cache_mod.active_cache(program)
    if cache is not None:
        for t in cache.tables().values():
            sized[t.name] = t.cache_rows
    for name, rows in emb_cache_mod.requested_rows(program).items():
        sized.setdefault(name, int(rows))
    if not sized:
        return

    block = pctx.block
    bound = {}     # table -> summed worst-case ids per step
    first_op = {}  # table -> op index of its first lookup
    for i, op in enumerate(pctx.ops):
        if op.type != "lookup_table":
            continue
        wname = (op.input("W") or [None])[0]
        ids = (op.input("Ids") or [None])[0]
        if wname not in sized or not ids or not block.has_var(ids):
            continue
        shape = tuple(block.var(ids).shape or ())
        n = 1
        for d in shape:
            n *= _PROBE_BATCH if int(d) == -1 else int(d)
        bound[wname] = bound.get(wname, 0) + n
        first_op.setdefault(wname, i)
    for wname, n in sorted(bound.items()):
        if n <= sized[wname]:
            continue
        pctx.emit(
            "warning", "emb-cache-thrash",
            f"cached table '{wname}' can touch up to {n} unique rows "
            f"per step (batch probed as {_PROBE_BATCH} for -1 dims) but "
            f"cache_rows={sized[wname]}: steady-state steps will evict "
            f"rows staged the same step, and a fused window's id union "
            f"may not fit the slab at all",
            op_index=first_op[wname], var=wname,
            hint="raise cache_rows (or the enable() budget) above the "
                 "per-step touched-row bound, or lower the batch size")


def _check_planner(pctx):
    """Planner-output diagnostics (ISSUE 15), on top of the per-spec
    checks in _check_shardings:

      * sharding-batch-indivisible — a feed's dim-0 batch does not
        divide by its data-axis split, so GSPMD pads every step's input;
      * sharding-overcommit — one tensor dim sharded by an axis product
        larger than the dim itself (shards would be empty/padded);
      * norm-sharded — a role the planner keeps replicated on purpose
        (norm scale/bias, layer bias) carries a spec anyway: legal, but
        almost always a hand-annotation mistake since the bytes saved
        are trivial and every use pays a gather.
    """
    program = pctx.program
    mesh = getattr(program, "_mesh", None)
    if mesh is None:
        return
    axis_sizes = dict(getattr(mesh, "shape", None) or {})
    block = pctx.block

    # feeds: explicit _feed_shardings dim-0 entries vs static batch dims
    for name, spec in sorted(
            (getattr(program, "_feed_shardings", None) or {}).items()):
        if not spec or not block.has_var(name):
            continue
        shape = tuple(block.var(name).shape or ())
        if not shape or int(shape[0]) == -1:
            continue  # symbolic batch: runtime-sized, nothing to check
        factor, _missing = _axis_factor(spec[0], axis_sizes)
        if factor > 1 and int(shape[0]) % factor:
            pctx.emit(
                "error", "sharding-batch-indivisible",
                f"feed '{name}' has batch dim {shape[0]}, not divisible "
                f"by the {factor}-way data split of spec entry "
                f"{spec[0]!r}", var=name,
                hint=f"feed a global batch that is a multiple of "
                     f"{factor}, or re-plan on a smaller data axis")

    specs = getattr(program, "_param_shardings", None) or {}
    if not specs:
        return

    # axis overcommit: one dim split by more ways than it has elements
    for pname in sorted(specs):
        v = block.desc.vars.get(pname)
        if v is None or v.shape is None:
            continue  # _check_shardings already errors unknown params
        shape = list(v.shape)
        for d, entry in enumerate(specs[pname]):
            if d >= len(shape):
                break
            factor, _missing = _axis_factor(entry, axis_sizes)
            if factor > 1 and 0 < int(shape[d]) < factor:
                pctx.emit(
                    "error", "sharding-overcommit",
                    f"'{pname}' dim {d} has size {shape[d]} but spec "
                    f"entry {entry!r} splits it {factor} ways — "
                    f"{factor - int(shape[d])} shard(s) would be empty",
                    var=pname,
                    hint="drop one axis from the entry or shard a "
                         "larger dim")

    # norm/bias roles carrying a spec: replicated-by-design params
    from ..parallel import planner as planner_mod
    try:
        roles = planner_mod.classify_params(program)
    except Exception:
        return
    for pname in sorted(specs):
        if roles.get(pname) not in ("norm", "bias"):
            continue
        if not any(e for e in specs[pname]):
            continue
        pctx.emit(
            "warning", "norm-sharded",
            f"'{pname}' is a {roles[pname]} parameter (planner keeps "
            f"these replicated) but carries spec {specs[pname]} — the "
            f"bytes saved are trivial and every use pays a gather",
            var=pname,
            hint="let planner.plan assign this spec, or drop the "
                 "hand annotation")


def run(pctx):
    _check_pallas_convs(pctx)
    _check_quant(pctx)
    _check_shardings(pctx)
    _check_layout(pctx)
    _check_plans(pctx)
    _check_emb_cache(pctx)
    _check_planner(pctx)
