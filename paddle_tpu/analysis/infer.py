"""IR-level shape/dtype inference pass (the `shapes` pass).

Strategy: clone the program (ProgramDesc JSON round-trip — cheap, and
keeps the user's desc untouched), then walk the root block re-deriving
every op's output shapes from the feed/parameter leaves. Four rule
sources, in precedence order per op type:

  CHECKERS            hand-written validating rules in this module: they
                      check input ranks/dtypes/broadcast compatibility
                      (which the build-time registry rules never do) and
                      may compute outputs themselves.
  registry rule       ops/registry.py's build-time infer_shape (via
                      registry.static_infer), re-run on the clone; an
                      exception here is itself a diagnostic. `<t>_grad`
                      ops use the generic grad mirror the same way.
  EVAL_SHAPE_OPS      long-tail ops whose lowering is abstractly traced
                      with jax.eval_shape over ShapeDtypeStructs (zero
                      FLOPs) — the lowering is the ground truth for ops
                      with no closed-form rule.
  DYNAMIC_SHAPE_OPS   the explicit allowlist of ops whose output shapes
                      are genuinely value/LoD-dependent (control flow,
                      tensor arrays, beam search, save/load); their
                      outputs are marked unknown and downstream checks
                      go lenient.

tools/check_registry.py's check_infer_rules lint pins every registered
op to exactly one of these sources, so a newly registered op must be
placed here deliberately (and orphan table entries are flagged in the
converse direction).

Symbolic -1 batch dims flow through every rule: two dims are compatible
when equal or either is -1. Once an op errors, its outputs are marked
unknown so one planted defect doesn't cascade into a diagnostic per
downstream op. After the walk, any var whose re-derived shape disagrees
with the declared desc shape gets a `shape-drift` warning — the
signature of a desc edited behind the registry's back.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..framework.desc import VarType

# --- coverage tables (pinned by tools/check_registry.py) -------------------

# Output shapes depend on runtime values / LoD structure / sub-block
# control flow: static inference is not attempted, outputs are unknown.
DYNAMIC_SHAPE_OPS = frozenset({
    "array_to_lod_tensor", "beam_search", "beam_search_decode",
    "conditional_block", "conditional_block_grad", "feed", "fetch",
    "is_empty", "load", "load_combine", "lod_array_length",
    "lod_rank_table", "lod_tensor_to_array", "max_sequence_len",
    "merge_lod_tensor", "print", "read_from_array",
    "reorder_lod_tensor_by_rank", "rnn", "save", "save_combine",
    "select_rows_by_cond", "sequence_concat", "sequence_erase",
    "sequence_reshape", "sequence_slice", "shrink_rnn_memory",
    "split_lod_tensor", "while", "while_grad", "write_to_array",
})

# No closed-form rule, but the lowering itself abstractly evaluates:
# jax.eval_shape of the registered lowering over ShapeDtypeStructs.
EVAL_SHAPE_OPS = frozenset({
    "auc", "average_accumulates", "fused_adam", "fused_bn_act",
    "fused_chain", "fused_conv_bn_act", "fused_fc_act", "fused_momentum",
    "fused_sgd", "fused_sparse_adam", "fused_sparse_momentum",
    "fused_sparse_sgd", "hinge_loss", "huber_loss", "im2sequence",
    "log_loss", "margin_rank_loss", "mine_hard_examples", "multiplex",
    "rank_loss", "sequence_mask", "smooth_l1_loss",
    "squared_l2_distance", "target_assign",
})

# -1 → this probe value when the eval_shape fallback needs a concrete
# batch; output dims equal to it are mapped back to -1. Prime and
# larger than any static dim these programs use, so collisions with a
# real dim are implausible.
_PROBE_BATCH = 8191


# --- small shape algebra ---------------------------------------------------

def dim_ok(a: int, b: int) -> bool:
    return a == b or a == -1 or b == -1


def shapes_agree(x, y) -> bool:
    return len(x) == len(y) and all(dim_ok(a, b) for a, b in zip(x, y))


def _prod(dims) -> Optional[int]:
    """Product of dims, None when any is symbolic."""
    if any(d == -1 for d in dims):
        return None
    return math.prod(dims) if dims else 1


def _is_int(dtype: Optional[str]) -> bool:
    return bool(dtype) and ("int" in dtype or "bool" in dtype)


def _fmt(shape) -> str:
    return "[" + ", ".join(str(d) for d in shape) + "]"


# --- per-op checker context ------------------------------------------------

class OpCtx:
    """What a CHECKERS rule sees: slot-indexed input/output shapes read
    from the re-inference clone, emit helpers bound to this op's index,
    and set_out to publish re-derived output shapes."""

    def __init__(self, pctx, index, op, cblock, unknown):
        self._pctx = pctx
        self.index = index
        self.op = op
        self._cblock = cblock
        self._unknown = unknown
        self.errored = False
        self.outputs_set = False

    # -- reads --
    def _var(self, name):
        b = self._cblock
        while b is not None:
            if b.desc.has_var(name):
                return b.desc.var(name)
            b = b.parent_block
        return None

    def name(self, slot: str, idx: int = 0) -> Optional[str]:
        names = self.op.desc.inputs.get(slot, [])
        return names[idx] if idx < len(names) else None

    def shape(self, slot: str, idx: int = 0) -> Optional[Tuple[int, ...]]:
        n = self.name(slot, idx)
        if n is None or n in self._unknown:
            return None
        v = self._var(n)
        return tuple(v.shape) if v is not None and v.shape is not None \
            else None

    def dtype(self, slot: str, idx: int = 0) -> Optional[str]:
        n = self.name(slot, idx)
        v = self._var(n) if n else None
        return v.dtype if v is not None else None

    def var_type(self, slot: str, idx: int = 0) -> Optional[VarType]:
        n = self.name(slot, idx)
        v = self._var(n) if n else None
        return v.type if v is not None else None

    def n_inputs(self, slot: str) -> int:
        return len(self.op.desc.inputs.get(slot, ()))

    def attr(self, name, default=None):
        return self.op.attr(name, default)

    # -- writes --
    def set_out(self, slot: str, shape, dtype: Optional[str] = None,
                idx: int = 0):
        names = self.op.desc.outputs.get(slot, [])
        if idx >= len(names):
            return
        v = self._var(names[idx])
        if v is None:
            return
        v.shape = list(shape) if shape is not None else None
        if dtype is not None:
            v.dtype = dtype
        self.outputs_set = True

    # -- diagnostics --
    def error(self, code, msg, *, var=None, hint=None):
        self.errored = True
        self._pctx.emit("error", code, msg, op_index=self.index, var=var,
                        hint=hint)

    def warning(self, code, msg, *, var=None, hint=None):
        self._pctx.emit("warning", code, msg, op_index=self.index, var=var,
                        hint=hint)


# --- hand-written rules ----------------------------------------------------

def _no_int_float_mix(c: OpCtx, slots):
    """Arithmetic between an integer and a float operand is a class
    error in this IR (lowerings don't insert implicit casts); float-vs-
    float width mixes are fine — AMP legitimately mixes f32/bf16."""
    dts = [(s, c.dtype(s)) for s in slots if c.dtype(s)]
    ints = [s for s, d in dts if _is_int(d)]
    floats = [s for s, d in dts if not _is_int(d)]
    if ints and floats:
        c.error("dtype-mismatch",
                f"mixes integer input '{ints[0]}' "
                f"({c.dtype(ints[0])}) with float input '{floats[0]}' "
                f"({c.dtype(floats[0])})",
                var=c.name(ints[0]),
                hint="insert an explicit cast op; lowerings do not "
                     "implicitly promote int<->float")


def _chk_mul(c: OpCtx):
    x, y = c.shape("X"), c.shape("Y")
    if x is None or y is None:
        return
    xn = int(c.attr("x_num_col_dims", 1))
    yn = int(c.attr("y_num_col_dims", 1))
    if len(x) < xn + 1 or len(y) < yn + 1:
        c.error("rank-mismatch",
                f"X{_fmt(x)} / Y{_fmt(y)} too low-rank for "
                f"x_num_col_dims={xn}, y_num_col_dims={yn}",
                var=c.name("X"))
        return
    kx, ky = _prod(x[xn:]), _prod(y[:yn])
    if kx is not None and ky is not None and kx != ky:
        c.error("shape-mismatch",
                f"contraction dims disagree: X{_fmt(x)} flattens to "
                f"[*, {kx}] but Y{_fmt(y)} flattens to [{ky}, *]",
                var=c.name("X"),
                hint=f"X's trailing dims (from axis {xn}) must multiply "
                     f"out to Y's leading dims (through axis {yn})")
    _no_int_float_mix(c, ("X", "Y"))


def _chk_matmul(c: OpCtx):
    x, y = c.shape("X"), c.shape("Y")
    if x is None or y is None or len(x) < 2 or len(y) < 2:
        return
    kx = x[-2] if c.attr("transpose_X", False) else x[-1]
    ky = y[-1] if c.attr("transpose_Y", False) else y[-2]
    if not dim_ok(kx, ky):
        c.error("shape-mismatch",
                f"contraction dims disagree: X{_fmt(x)} x Y{_fmt(y)} "
                f"(transpose_X={bool(c.attr('transpose_X', False))}, "
                f"transpose_Y={bool(c.attr('transpose_Y', False))}) "
                f"contracts {kx} against {ky}", var=c.name("X"))
    _no_int_float_mix(c, ("X", "Y"))


def _chk_elementwise(c: OpCtx):
    x, y = c.shape("X"), c.shape("Y")
    if x is None or y is None:
        return
    axis = int(c.attr("axis", -1))
    if len(y) > len(x):
        c.error("broadcast-mismatch",
                f"Y{_fmt(y)} has higher rank than X{_fmt(x)} — Y "
                f"broadcasts into X, not the reverse", var=c.name("Y"))
        return
    off = len(x) - len(y) if axis == -1 else axis
    if off < 0 or off + len(y) > len(x):
        c.error("broadcast-mismatch",
                f"axis={axis} places Y{_fmt(y)} outside X{_fmt(x)}",
                var=c.name("Y"))
        return
    for j, yd in enumerate(y):
        xd = x[off + j]
        if yd != 1 and not dim_ok(xd, yd):
            c.error("broadcast-mismatch",
                    f"X{_fmt(x)} and Y{_fmt(y)} (axis={axis}) disagree "
                    f"at X dim {off + j}: {xd} vs {yd}", var=c.name("Y"),
                    hint="elementwise ops broadcast Y into X: each Y dim "
                         "must equal the aligned X dim or be 1")
            return
    _no_int_float_mix(c, ("X", "Y"))


def _conv_out(i, k, s, p, d):
    if i == -1:
        return -1
    ke = (k - 1) * d + 1
    return (i + 2 * p - ke) // s + 1


def _chk_conv2d(c: OpCtx):
    x, w = c.shape("Input"), c.shape("Filter")
    if x is None or w is None:
        return
    if len(x) != 4 or len(w) != 4:
        c.error("rank-mismatch",
                f"conv2d needs NCHW Input and OIHW Filter, got "
                f"Input{_fmt(x)} Filter{_fmt(w)}", var=c.name("Input"))
        return
    groups = int(c.attr("groups", 1) or 1)
    if x[1] != -1 and w[1] != -1 and w[1] * groups != x[1]:
        c.error("channel-mismatch",
                f"Input has {x[1]} channels but Filter{_fmt(w)} with "
                f"groups={groups} consumes {w[1] * groups}",
                var=c.name("Filter"))
        return
    strides = list(c.attr("strides", [1, 1]))
    paddings = list(c.attr("paddings", [0, 0]))
    dilations = list(c.attr("dilations", [1, 1]))
    for i, s, p, d, k in zip(x[2:], strides, paddings, dilations, w[2:]):
        o = _conv_out(i, k, s, p, d)
        if o != -1 and o < 1:
            c.error("conv-geometry",
                    f"spatial output collapses to {o}: input dim {i}, "
                    f"kernel {k}, stride {s}, padding {p}, dilation {d}",
                    var=c.name("Input"),
                    hint="pad the input or shrink the kernel/stride so "
                         "(i + 2p - ((k-1)d + 1)) // s + 1 >= 1")
            return


def _chk_pool2d(c: OpCtx):
    x = c.shape("X")
    if x is not None and len(x) != 4:
        c.error("rank-mismatch", f"pool2d needs NCHW input, got {_fmt(x)}",
                var=c.name("X"))


def _chk_batch_norm(c: OpCtx):
    x = c.shape("X")
    if x is None or len(x) < 2:
        return
    ch = x[-1] if c.attr("data_layout", "NCHW") == "NHWC" else x[1]
    for slot in ("Scale", "Bias", "Mean", "Variance"):
        s = c.shape(slot)
        if s is not None and ch != -1 and (len(s) != 1
                                           or not dim_ok(s[0], ch)):
            c.error("shape-mismatch",
                    f"{slot}{_fmt(s)} does not match X{_fmt(x)}'s "
                    f"channel dim {ch}", var=c.name(slot))
            return


def _chk_xent(c: OpCtx):
    logits = c.shape("Logits") or c.shape("X")
    lslot = "Logits" if c.shape("Logits") is not None else "X"
    label = c.shape("Label")
    if logits is None or label is None:
        return
    soft = bool(c.attr("soft_label", False))
    ldt = c.dtype("Label")
    if not soft and ldt and not _is_int(ldt):
        c.error("dtype-mismatch",
                f"hard-label cross entropy needs integer class ids, "
                f"Label is {ldt}", var=c.name("Label"),
                hint="feed int64 class indices, or set soft_label=True "
                     "for float distributions")
        return
    if len(label) != len(logits):
        c.error("rank-mismatch",
                f"Label{_fmt(label)} rank must match "
                f"{lslot}{_fmt(logits)}", var=c.name("Label"))
        return
    want_last = logits[-1] if soft else 1
    if not dim_ok(label[-1], want_last) or not all(
            dim_ok(a, b) for a, b in zip(label[:-1], logits[:-1])):
        c.error("shape-mismatch",
                f"Label{_fmt(label)} does not match {lslot}"
                f"{_fmt(logits)} (expected trailing dim {want_last})",
                var=c.name("Label"))


def _chk_lookup_table(c: OpCtx):
    ids, w = c.shape("Ids"), c.shape("W")
    dt = c.dtype("Ids")
    if dt and not _is_int(dt):
        c.error("dtype-mismatch", f"Ids must be integer, got {dt}",
                var=c.name("Ids"))
    if w is not None and len(w) != 2:
        c.error("rank-mismatch",
                f"embedding table W must be [rows, dim], got {_fmt(w)}",
                var=c.name("W"))
    del ids


def _chk_concat(c: OpCtx):
    shapes = [c.shape("X", i) for i in range(c.n_inputs("X"))]
    shapes = [s for s in shapes if s is not None]
    if len(shapes) < 2:
        return
    axis = int(c.attr("axis", 0))
    r = len(shapes[0])
    for s in shapes[1:]:
        if len(s) != r:
            c.error("rank-mismatch",
                    f"concat inputs mix ranks: {_fmt(shapes[0])} vs "
                    f"{_fmt(s)}", var=c.name("X"))
            return
        for d in range(r):
            if d != axis % r and not dim_ok(s[d], shapes[0][d]):
                c.error("shape-mismatch",
                        f"concat(axis={axis}) inputs disagree on dim "
                        f"{d}: {_fmt(shapes[0])} vs {_fmt(s)}",
                        var=c.name("X"))
                return


def _chk_reshape(c: OpCtx):
    x = c.shape("X")
    target = c.attr("shape")
    if x is None or not target:
        return
    target = list(target)
    if sum(1 for d in target if d == -1) > 1:
        c.error("shape-mismatch",
                f"reshape target {target} has more than one -1",
                var=c.name("X"))
        return
    # 0 copies the input dim (reference reshape semantics)
    resolved = [x[i] if d == 0 and i < len(x) else d
                for i, d in enumerate(target)]
    px, pt = _prod(x), _prod(resolved)
    if px is not None and pt is not None and px != pt:
        c.error("shape-mismatch",
                f"cannot reshape X{_fmt(x)} ({px} elements) to "
                f"{resolved} ({pt} elements)", var=c.name("X"))


def _chk_sum(c: OpCtx):
    shapes = [c.shape("X", i) for i in range(c.n_inputs("X"))]
    shapes = [s for s in shapes if s is not None]
    for s in shapes[1:]:
        if not shapes_agree(shapes[0], s):
            c.error("shape-mismatch",
                    f"sum inputs disagree: {_fmt(shapes[0])} vs "
                    f"{_fmt(s)}", var=c.name("X"))
            return


def _chk_optimizer(c: OpCtx):
    p, g = c.shape("Param"), c.shape("Grad")
    if c.var_type("Grad") == VarType.SELECTED_ROWS:
        return  # sparse rows: grad is [rows_touched, dim], checked at apply
    if p is not None and g is not None and not shapes_agree(p, g):
        c.error("optimizer-shape",
                f"Param{_fmt(p)} and Grad{_fmt(g)} disagree",
                var=c.name("Param"),
                hint="the param<->grad pairing is positional — a desc "
                     "edit between backward and the optimizer broke it")
        return
    for slot in ("Moment", "Moment1", "Moment2", "Velocity"):
        m = c.shape(slot)
        if p is not None and m is not None and not shapes_agree(p, m):
            c.error("optimizer-shape",
                    f"{slot}{_fmt(m)} does not match Param{_fmt(p)}",
                    var=c.name(slot))
            return


def _mirror(in_slot="X", out_slot="Out"):
    def chk(c: OpCtx):
        s = c.shape(in_slot)
        if s is not None:
            c.set_out(out_slot, s, c.dtype(in_slot))
    return chk


def _chk_squared_l2_norm(c: OpCtx):
    c.set_out("Out", [1], c.dtype("X"))


def _chk_shape_op(c: OpCtx):
    s = c.shape("Input") or c.shape("X")
    if s is not None:
        c.set_out("Out", [len(s)], "int32")


CHECKERS = {
    "mul": _chk_mul,
    "matmul": _chk_matmul,
    "elementwise_add": _chk_elementwise,
    "elementwise_sub": _chk_elementwise,
    "elementwise_mul": _chk_elementwise,
    "elementwise_div": _chk_elementwise,
    "elementwise_max": _chk_elementwise,
    "elementwise_min": _chk_elementwise,
    "elementwise_pow": _chk_elementwise,
    "conv2d": _chk_conv2d,
    "depthwise_conv2d": _chk_conv2d,
    "pool2d": _chk_pool2d,
    "batch_norm": _chk_batch_norm,
    "softmax_with_cross_entropy": _chk_xent,
    "cross_entropy": _chk_xent,
    "lookup_table": _chk_lookup_table,
    "concat": _chk_concat,
    "reshape": _chk_reshape,
    "sum": _chk_sum,
    "sgd": _chk_optimizer,
    "momentum": _chk_optimizer,
    "adam": _chk_optimizer,
    # no-registry-rule ops with a closed form
    "label_smooth": _mirror(),
    "sequence_softmax": _mirror(),
    "lod_reset": _mirror(),
    "row_conv": _mirror(),
    "squared_l2_norm": _chk_squared_l2_norm,
    "shape": _chk_shape_op,
}


def rule_kind(op_type: str) -> Optional[str]:
    """Which rule source covers `op_type`: 'checker' | 'dynamic' | 'eval'
    | 'registry' | 'grad' | None. The check_infer_rules lint requires a
    non-None answer for every registered op."""
    from ..ops import registry
    if op_type in CHECKERS:
        return "checker"
    if op_type in DYNAMIC_SHAPE_OPS:
        return "dynamic"
    if op_type in EVAL_SHAPE_OPS:
        return "eval"
    rule = registry.static_infer(op_type)
    if rule is registry.infer_grad_shapes:
        return "grad"
    if rule is not None:
        return "registry"
    return None


# --- eval_shape fallback ---------------------------------------------------

class _AbstractCtx:
    """Lowering context stub for jax.eval_shape: enough surface for
    data-path lowerings (AMP policy, rng, no sequence side channels, no
    layout tags). Control-flow lowerings need run_block/executor and are
    DYNAMIC_SHAPE_OPS instead; anything else missing raises and the op
    degrades to unknown outputs."""

    def __init__(self, program):
        self.program = program
        self.place = None
        self.amp_dtype = getattr(program, "_amp_dtype", None)
        self.amp_level = getattr(program, "_amp_level", "O1")
        self.env: Dict = {}
        self.lod_map: Dict = {}
        self.layout_opt = False
        self.layouts: Dict = {}
        self.layout_overrides: Dict = {}
        self.seq_overrides: Dict = {}

    def layout_of(self, name):
        return None

    def set_layout(self, name, tag):
        self.layout_overrides[name] = tag

    def seq_len(self, name):
        return None

    def seq_len2(self, name):
        return None

    def set_seq_len(self, name, lengths):
        self.seq_overrides[name] = lengths

    def set_seq_len2(self, name, lengths):
        pass

    def next_rng(self, op=None):
        import jax
        return jax.random.key(0)


def _eval_shape_op(pctx, c: OpCtx, clone, cop, unknown) -> bool:
    """Abstractly trace the op's lowering; write output shapes into the
    clone desc. True when outputs were derived. A ValueError/TypeError
    with fully known inputs is a real shape error; any other failure
    (stub ctx limitation) degrades to unknown outputs."""
    import jax
    import numpy as np

    from ..ops import registry
    opdef = registry.try_get(cop.type)
    if opdef is None or opdef.lower is None:
        return False
    ins, known = {}, True
    for slot, names in cop.desc.inputs.items():
        vals = []
        for n in names:
            v = c._var(n)
            if v is None or v.shape is None or n in unknown:
                vals.append(None)
                known = False
                continue
            shape = tuple(_PROBE_BATCH if d == -1 else d for d in v.shape)
            vals.append(jax.ShapeDtypeStruct(shape, np.dtype(v.dtype)))
        ins[slot] = vals
    if not known:
        return False
    actx = _AbstractCtx(clone)
    try:
        out = jax.eval_shape(lambda kw: opdef.lower(actx, cop, kw), ins)
    except (ValueError, TypeError) as e:
        c.error("infer-failed",
                f"lowering rejects the input shapes: {e}",
                var=(cop.desc.input_arg_names() or [None])[0])
        return False
    except Exception:  # noqa: BLE001 - stub-context limitation, not a bug
        return False
    for slot, vals in (out or {}).items():
        names = cop.desc.outputs.get(slot, [])
        for n, aval in zip(names, vals):
            v = c._var(n)
            if v is None or not hasattr(aval, "shape"):
                continue
            v.shape = [-1 if d == _PROBE_BATCH else int(d)
                       for d in aval.shape]
            v.dtype = str(np.dtype(aval.dtype)) if hasattr(aval, "dtype") \
                else v.dtype
    return True


# --- the pass --------------------------------------------------------------

def run(pctx):
    from ..ops import registry
    program = pctx.program
    clone = program.clone()
    cblock = clone.global_block()
    orig_block = pctx.block
    if len(cblock.ops) != len(orig_block.ops):
        pctx.emit("warning", "analyzer-internal",
                  "clone op count differs from source; skipping shapes")
        return
    declared = {n: (list(v.shape) if v.shape is not None else None)
                for n, v in orig_block.desc.vars.items()}
    unknown: set = set()

    for i, cop in enumerate(cblock.ops):
        t = cop.type
        opdef = registry.try_get(t)
        if opdef is None:
            pctx.emit("error", "unregistered-op",
                      f"op type '{t}' is not registered in "
                      f"ops/registry.py", op_index=i)
            unknown.update(cop.output_arg_names)
            continue
        kind = rule_kind(t)
        c = OpCtx(pctx, i, cop, cblock, unknown)
        if kind == "dynamic":
            unknown.update(cop.output_arg_names)
            continue
        inputs_unknown = any(n in unknown for n in cop.input_arg_names)
        if kind == "checker" and not inputs_unknown:
            CHECKERS[t](c)
        if c.errored:
            unknown.update(cop.output_arg_names)
            continue
        if not c.outputs_set:
            rule = registry.static_infer(t)
            if inputs_unknown:
                unknown.update(cop.output_arg_names)
            elif rule is not None:
                try:
                    rule(cop, cblock)
                except Exception as e:  # noqa: BLE001 - rule = validator
                    pctx.emit("error", "infer-failed",
                              f"shape inference rule for '{t}' raised: "
                              f"{e!r}", op_index=i)
                    unknown.update(cop.output_arg_names)
            elif kind == "eval":
                if not _eval_shape_op(pctx, c, clone, cop, unknown):
                    unknown.update(cop.output_arg_names)
            else:
                unknown.update(cop.output_arg_names)

    # declared-vs-rederived drift: a desc whose recorded shapes can't be
    # reproduced from its own leaves was edited behind the registry's
    # back (or deserialized from a corrupt JSON)
    for name, v in cblock.desc.vars.items():
        if name in unknown or v.shape is None:
            continue
        decl = declared.get(name)
        if decl is not None and not shapes_agree(decl, v.shape):
            pctx.emit("warning", "shape-drift",
                      f"declared shape {decl} disagrees with the shape "
                      f"re-derived from the program's own leaves "
                      f"{list(v.shape)}", var=name)
