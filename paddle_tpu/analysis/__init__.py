"""Whole-program static verifier over the ProgramDesc IR (ISSUE 12).

The reference front-loads correctness into compile time — every op runs
InferShape/InferVarType against the ProgramDesc before a kernel executes
— while our executor discovers mistakes only when JAX tracing throws
deep inside _trace_block. This package restores the compile-time story
as a pass pipeline over the IR, and extends it with the preflight the
MFU campaign needs: which ops will miss the Pallas/fusion/overlap fast
paths and what one-line change would fix it.

Passes (each a pure function over the program; see the sibling modules):

  shapes    (infer.py)    — re-derives every var's shape/dtype from the
                            feed/parameter leaves through per-op rules
                            keyed off ops/registry.py, with symbolic -1
                            batch dims, a jax.eval_shape fallback for
                            long-tail ops and an explicit
                            DYNAMIC_SHAPE_OPS allowlist.
  dataflow  (dataflow.py) — use-before-def, dead ops/vars relative to
                            the fetch set, write-after-write, donated
                            persistable fetch hazards, param<->grad
                            pairing, sparse-path reachability.
  preflight (preflight.py)— dry-runs the fusion/overlap plans, the
                            Pallas conv eligibility gate and the
                            sharding specs; emits fix-it hints.

Severity semantics: "error" = the program will fail (or silently
compute garbage) at trace/run time — PADDLE_TPU_VERIFY=1 turns these
into errors.ProgramVerifyError at first compile and `analyze --strict`
fails on them; "warning" = suspicious dataflow or a missed fast path
worth a look (never raises); "info" = advisory context (plan summaries,
layout notes).

Every Diagnostic carries the op index, op type, the offending var, the
Python source line the op was built at (framework._user_frame via
Operator.creation_site) and, where we know one, a concrete fix-it hint.

Entry points: analyze_program() here, `python -m paddle_tpu analyze`
(cli.py), the executor's PADDLE_TPU_VERIFY hook, and the inspector
crash report's "analysis" section.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

__all__ = [
    "Diagnostic", "Report", "SEVERITIES", "analyze_program", "pass_names",
]

SEVERITIES = ("error", "warning", "info")


@dataclass
class Diagnostic:
    """One finding. `site` is the user source line ("file:lineno") the op
    was built at; `hint` is an actionable one-liner when we know one."""

    severity: str
    code: str
    message: str
    pass_name: str = ""
    op_index: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None
    site: Optional[str] = None
    hint: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if v is not None}

    def format(self) -> str:
        where = ""
        if self.op_index is not None:
            where = f" [op {self.op_index} '{self.op_type}']"
        elif self.var:
            where = f" [var '{self.var}']"
        site = f" ({self.site})" if self.site else ""
        hint = f"\n      hint: {self.hint}" if self.hint else ""
        return (f"{self.severity}: {self.code}{where}{site}: "
                f"{self.message}{hint}")


@dataclass
class Report:
    diagnostics: List[Diagnostic] = field(default_factory=list)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def infos(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "info"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def counts(self) -> Dict[str, int]:
        c = {s: 0 for s in SEVERITIES}
        for d in self.diagnostics:
            c[d.severity] = c.get(d.severity, 0) + 1
        return c

    def to_dict(self) -> Dict[str, Any]:
        return {"counts": self.counts(),
                "diagnostics": [d.to_dict() for d in self.diagnostics]}

    def format(self, *, show_info: bool = True) -> str:
        c = self.counts()
        lines = [d.format() for d in self.diagnostics
                 if show_info or d.severity != "info"]
        lines.append(f"{c['error']} error(s), {c['warning']} warning(s), "
                     f"{c['info']} info")
        return "\n".join(lines)


class PassContext:
    """Shared state handed to each pass: the (read-only) program, the
    feed/fetch sets when the caller knows them, and the diagnostic sink.
    `ops` are the ORIGINAL Operator wrappers — their creation_site points
    at the user's model code, which cloned/re-synced wrappers lose."""

    def __init__(self, program, feeds: Optional[Sequence[str]],
                 fetches: Optional[Sequence[str]]):
        self.program = program
        self.block = program.global_block()
        self.ops = list(self.block.ops)
        self.feeds = set(feeds) if feeds is not None else None
        if fetches is None:
            fetches = list(getattr(program, "_loss_names", None) or [])
            self.fetches_explicit = False
        else:
            self.fetches_explicit = True
        self.fetches = [f if isinstance(f, str) else getattr(f, "name", str(f))
                        for f in fetches]
        self.diagnostics: List[Diagnostic] = []
        self._pass_name = ""

    def site_of(self, op_index: Optional[int]) -> Optional[str]:
        if op_index is None or not (0 <= op_index < len(self.ops)):
            return None
        return getattr(self.ops[op_index], "creation_site", None)

    def emit(self, severity: str, code: str, message: str, *,
             op_index: Optional[int] = None, var: Optional[str] = None,
             hint: Optional[str] = None) -> Diagnostic:
        assert severity in SEVERITIES, severity
        op_type = (self.ops[op_index].type
                   if op_index is not None and 0 <= op_index < len(self.ops)
                   else None)
        d = Diagnostic(severity=severity, code=code, message=message,
                       pass_name=self._pass_name, op_index=op_index,
                       op_type=op_type, var=var,
                       site=self.site_of(op_index), hint=hint)
        self.diagnostics.append(d)
        return d


def _passes():
    from . import dataflow, infer, preflight
    return [("shapes", infer.run), ("dataflow", dataflow.run),
            ("preflight", preflight.run)]


def pass_names() -> List[str]:
    return [n for n, _ in _passes()]


def analyze_program(program, feeds: Optional[Sequence[str]] = None,
                    fetches: Optional[Sequence[str]] = None) -> Report:
    """Run every pass over `program`'s global block and return the Report.

    `feeds`/`fetches` sharpen the dataflow checks when the caller knows
    them (the executor and CLI do); without them, no-producer vars are
    presumed feedable and the fetch set falls back to the loss names
    recorded by append_backward. Never raises: a pass that dies on an
    analyzer bug degrades to a single `analyzer-internal` warning so the
    crash-report and bench integrations stay harmless.
    """
    ctx = PassContext(program, feeds, fetches)
    for name, fn in _passes():
        ctx._pass_name = name
        try:
            fn(ctx)
        except Exception as e:  # noqa: BLE001 - analyzer must not crash
            ctx.emit("warning", "analyzer-internal",
                     f"'{name}' pass failed internally: {e!r}")
    ctx._pass_name = ""
    return Report(ctx.diagnostics)
