"""Thread-safety lint over the paddle_tpu source tree (ISSUE 18).

The repo runs ~14 modules' worth of background threads (feeder window
builders, DynamicBatcher, emb-cache prefetch, obs server, sentinel
poll/watchdog, Go-style channels) guarded by ad-hoc locks — and PR 17
fixed a builder/consumer data race that was found by accident, not by
tooling. This module is the static leg of the correctness tooling
(after PR 12's program verifier and PR 17's runtime sentinel): a
ThreadSanitizer-flavoured *lockset* analysis specialized to this
codebase's concurrency idioms, pure AST, no imports of the linted
modules, safe to run in CI.

What it computes, per module of ``paddle_tpu/``:

  * a **thread census**: every ``threading.Thread(...)`` and ``go(...)``
    creation site, its static name (or f-string prefix), daemon flag,
    target, where the handle is stored and whether it is ever joined,
    plus the self-attributes/globals the target's body reaches. The
    census is pinned against the declared `THREAD_CATALOG` in both
    directions (``tools/check_registry.py check_thread_catalog``).
  * a **lockset model**: which lock guards each shared field, inferred
    from ``with self._lock:`` / ``with _LOCK:`` scopes (including the
    repo's ``*_locked``-suffix convention for methods that run with the
    class lock already held).
  * a **lock-order graph** across modules via a call-graph fixpoint of
    "locks this function may acquire" (depth-unbounded within the
    resolvable call graph: self-methods, same-module functions and
    closures, uniquely-named same-module methods, and
    ``mod.fn(...)`` calls into other paddle_tpu modules).

Diagnostics (PR 12 vocabulary — `analysis.Diagnostic` with stable codes,
``file:line`` sites and fix-it hints):

  lockset-mixed-guard   (error)   field guarded by a lock in one method
                                  but accessed bare in another
  lock-order-cycle      (error)   cycle in the lock-order graph
                                  (deadlock potential)
  blocking-under-lock   (error)   blocking call (``.join()``,
                                  ``time.sleep``, ``open()``, HTTP,
                                  unbounded ``queue.get``/``.wait()``/
                                  ``.result()``, ``np.asarray``/
                                  ``jax.device_put`` device syncs) while
                                  holding a lock
  thread-unnamed        (error)   Thread(...) without ``name=`` — hang
                                  reports and the census need identities
  thread-non-daemon     (warning) background thread that can wedge
                                  interpreter shutdown
  thread-never-joined   (warning) catalog says joined=True but no join
                                  site exists in the module
  thread-uncataloged    (error)   creation site missing from
                                  THREAD_CATALOG
  thread-catalog-stale  (error)   THREAD_CATALOG entry with no matching
                                  creation site
  thread-census         (info)    one advisory line per creation site

Intentional violations are waived in place with a trailing comment
``# thread-lint: ok <code>[, <code>...]`` on the flagged line — the
waiver is part of the diff, reviewable, and scoped to one line+code.

Entry points: ``analyze_threads()`` -> `analysis.Report`,
``python -m paddle_tpu analyze --threads`` (cli.py), and
``catalog_problems()`` consumed by ``tools/check_registry.py``.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from . import Diagnostic, Report

__all__ = ["THREAD_CATALOG", "ThreadSite", "analyze_threads",
           "thread_census", "catalog_problems"]

PASS_NAME = "threads"

# --- the declared thread catalog ---------------------------------------------
# Every Thread/go creation site in paddle_tpu/ must map to exactly one
# entry here (by module + static name/prefix), and every entry must have
# at least one site — check_registry.py pins both directions. `joined`
# declares whether the OWNING module joins the thread ("detached" threads
# hand the handle to their caller or run for the process lifetime).

THREAD_CATALOG: Dict[str, Dict[str, Any]] = {
    "pd-feeder-batch": dict(
        module="paddle_tpu/reader/pipeline.py", daemon=True, joined=True,
        help="per-batch producer: converts + device_puts batches into "
             "the bounded double-buffer queue"),
    "pd-feeder-window": dict(
        module="paddle_tpu/reader/pipeline.py", daemon=True, joined=True,
        help="window builder: stacks k batches + device_puts whole "
             "windows ahead of the fused multi-step loop"),
    "pd-reader-buffered": dict(
        module="paddle_tpu/reader/__init__.py", daemon=True, joined=False,
        help="buffered() fill thread; ends with the pass, surfaced "
             "errors ride the queue"),
    "pd-emb-prefetch": dict(
        module="paddle_tpu/parallel/emb_cache.py", daemon=True,
        joined=True,
        help="one background hot-row prefetch; joined by "
             "_PrefetchHandle.wait()"),
    "pd-go-": dict(
        module="paddle_tpu/concurrency.py", prefix=True, daemon=True,
        joined=False,
        help="go()-launched goroutine; the handle is returned for the "
             "caller to join"),
    "pd-serving-client-": dict(
        module="paddle_tpu/serving/harness.py", prefix=True, daemon=True,
        joined=True,
        help="load-harness client threads, joined under the sentinel "
             "dispatch watchdog"),
    "serving-batcher": dict(
        module="paddle_tpu/serving/batcher.py", daemon=True, joined=True,
        help="DynamicBatcher worker: collects + executes batches; "
             "joined by close()"),
    "paddle-tpu-obs": dict(
        module="paddle_tpu/obs_server.py", daemon=True, joined=True,
        help="observability HTTP server loop; joined by stop()"),
    "paddle-tpu-sentinel-poll": dict(
        module="paddle_tpu/sentinel.py", daemon=True, joined=True,
        help="sentinel metric poll loop; joined by Sentinel.stop()"),
    "paddle-tpu-sentinel-watch": dict(
        module="paddle_tpu/sentinel.py", daemon=True, joined=True,
        help="sentinel hang watchdog loop; joined by Sentinel.stop()"),
    "sentinel-stall-drill": dict(
        module="paddle_tpu/sentinel.py", daemon=True, joined=False,
        help="inject_stall() drill dispatch; handle returned for the "
             "caller (cli --smoke) to join"),
    "paddle_tpu_pool_": dict(
        module="paddle_tpu/threadpool.py", prefix=True, daemon=True,
        joined=False,
        help="ThreadPool workers; daemon lifetime, shutdown drains via "
             "sentinel tasks"),
    "ilv-": dict(
        module="paddle_tpu/testing/interleave.py", prefix=True,
        daemon=True, joined=True,
        help="interleave-harness worker threads, scheduled "
             "cooperatively under a seeded schedule"),
}

# --- classification tables ---------------------------------------------------

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
# internally-synchronized containers: exempt from mixed-guard (their
# methods are atomic; a lock around them is belt-and-braces, not a
# guard discipline)
_SYNC_FACTORIES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
                   "Event", "local"}
_LOCKISH_ATTRS = {"_lock", "_cond", "_LOCK", "_mu", "_mutex"}
# methods that mutate their receiver — an `self.x.append(...)` is a
# write to x for lockset purposes
_MUTATORS = {"append", "appendleft", "extend", "insert", "pop", "popleft",
             "remove", "clear", "update", "add", "discard", "setdefault",
             "popitem"}
# calls that block (or synchronize with the device) — flagged when made
# while holding a lock. `transitive=False` ops are only flagged when
# they appear directly in the locked body: np.asarray on a small host
# array is instant, so propagating it through the call graph would
# drown the signal; a *direct* device sync inside a critical section is
# the reviewable pattern.
_BLOCKING_NAME_CALLS = {
    # dotted-name call -> (description, transitive)
    "time.sleep": ("time.sleep()", True),
    "np.asarray": ("np.asarray() device sync", False),
    "numpy.asarray": ("np.asarray() device sync", False),
    "jax.device_put": ("jax.device_put()", False),
    "urllib.request.urlopen": ("urlopen()", True),
    "urlopen": ("urlopen()", True),
    "requests.get": ("HTTP request", True),
    "requests.post": ("HTTP request", True),
    "open": ("file open()", True),
}
# method calls (by attribute name, 0 positional args) that block unless
# bounded by a timeout= keyword
_BLOCKING_METHODS_TIMEOUT_OK = {
    "get": "unbounded queue.get()",
    "wait": "unbounded .wait()",
    "result": "future .result()",
}
# method calls that block regardless of timeout (joining a thread that
# may itself need the held lock is a deadlock in one hop; a bounded
# join still parks the lock for the full timeout)
_BLOCKING_METHODS_ALWAYS = {
    "join": ".join() on a thread",
    "shutdown": ".shutdown()",
    "serve_forever": ".serve_forever()",
    "block_until_ready": ".block_until_ready() device sync",
}

_WAIVER_RE = re.compile(r"#\s*thread-lint:\s*ok\s+([a-z\-,\s]+)")


# --- data model --------------------------------------------------------------

@dataclass
class ThreadSite:
    """One Thread(...)/go(...) creation site discovered in the census."""

    module: str                       # repo-relative path
    lineno: int
    kind: str                         # "thread" | "go"
    name: Optional[str] = None        # static name or f-string prefix
    name_is_prefix: bool = False
    daemon: Optional[bool] = None
    target: Optional[str] = None
    stored_in: Optional[str] = None   # receiver the handle lands in
    joined: bool = False
    reaches: Tuple[str, ...] = ()     # attrs/globals the target touches

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if v not in
                (None, (), False)}


@dataclass
class _Fn:
    qualname: str
    name: str
    klass: Optional[str]
    lineno: int
    locals_: Set[str] = field(default_factory=set)
    globals_decl: Set[str] = field(default_factory=set)
    local_locks: Set[str] = field(default_factory=set)
    # (scope "attr"|"global", name, is_write, held lock keys, lineno)
    accesses: List[Tuple[str, str, bool, Tuple[str, ...], int]] = \
        field(default_factory=list)
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    # (outer lock, inner lock, lineno of inner acquire)
    nested: List[Tuple[str, str, int]] = field(default_factory=list)
    # (held lock keys, callee descriptor, lineno)
    calls: List[Tuple[Tuple[str, ...], Tuple, int]] = \
        field(default_factory=list)
    # (held lock keys, description, lineno, transitive?)
    blocking: List[Tuple[Tuple[str, ...], str, int, bool]] = \
        field(default_factory=list)
    # class-own condition locks this function wait()s/notify()s on —
    # Python requires the caller to hold a Condition to wait on it, so
    # the whole body implicitly runs with these held
    waits_on: Set[str] = field(default_factory=set)


@dataclass
class _Class:
    name: str
    lock_attrs: Set[str] = field(default_factory=set)
    sync_attrs: Set[str] = field(default_factory=set)
    method_names: Set[str] = field(default_factory=set)


@dataclass
class _Module:
    relpath: str
    modname: str
    functions: Dict[str, _Fn] = field(default_factory=dict)
    classes: Dict[str, _Class] = field(default_factory=dict)
    global_locks: Set[str] = field(default_factory=set)
    global_names: Set[str] = field(default_factory=set)
    imports: Dict[str, str] = field(default_factory=dict)
    thread_sites: List[ThreadSite] = field(default_factory=list)
    join_receivers: Set[str] = field(default_factory=set)
    # loop alias -> iterated name (for `for t in threads: t.join()`)
    for_aliases: Dict[str, str] = field(default_factory=dict)
    # local name -> collection it is append()ed into (resolves
    # `threads.append(t)` ... `for t in threads: t.join()` chains)
    append_into: Dict[str, str] = field(default_factory=dict)
    waivers: Dict[int, Set[str]] = field(default_factory=dict)


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 - display only
        return "<expr>"


def _dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> "a.b.c", Name -> "a"; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_factory(node: ast.AST, factories: Set[str]) -> bool:
    """True when `node` is a call of threading.X()/queue.X()/deque()...
    whose terminal name is in `factories`."""
    if not isinstance(node, ast.Call):
        return False
    dn = _dotted(node.func)
    if dn is None:
        return False
    return dn.split(".")[-1] in factories


def _static_name(expr: ast.AST) -> Tuple[Optional[str], bool]:
    """Extract a Thread name= value: (literal, False) for a constant,
    (leading static prefix, True) for an f-string, (None, False)
    otherwise."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value, False
    if isinstance(expr, ast.JoinedStr):
        prefix = ""
        for part in expr.values:
            if isinstance(part, ast.Constant) and isinstance(part.value,
                                                             str):
                prefix += part.value
            else:
                break
        return (prefix or None), True
    if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.Or) \
            and expr.values:
        # `name=name or f"pd-go-{...}"` — the fallback is the statically
        # known floor; a caller override only makes it MORE specific
        sub, _ = _static_name(expr.values[-1])
        if sub is not None:
            return sub, True
    return None, False


# --- per-function walker -----------------------------------------------------

class _FnWalker(ast.NodeVisitor):
    """Walk one function body tracking the held-lock stack, recording
    field/global accesses, lock acquisitions, nesting pairs, call sites
    and direct blocking calls."""

    def __init__(self, mod: _Module, fn: _Fn, models: "Dict[str, _Module]"):
        self.mod = mod
        self.fn = fn
        self.models = models
        self.held: List[str] = []

    # -- lock resolution --
    def _resolve_lock(self, expr: ast.AST) -> Optional[str]:
        dn = _dotted(expr)
        if dn is None:
            return None
        parts = dn.split(".")
        if len(parts) == 1:
            nm = parts[0]
            if nm in self.mod.global_locks:
                return f"{self.mod.relpath}:{nm}"
            if nm in self.fn.local_locks:
                return f"{self.mod.relpath}:{self.fn.qualname}.{nm}"
            return None
        if parts[0] == "self" and self.fn.klass:
            kl = self.mod.classes.get(self.fn.klass)
            if len(parts) == 2 and kl and parts[1] in kl.lock_attrs:
                return f"{self.mod.relpath}:{self.fn.klass}.{parts[1]}"
            # self.a.b..._lock: opaque foreign lock reached through an
            # attribute chain — keyed by the chain so nesting is still
            # visible, without claiming an identity we can't prove
            if parts[-1] in _LOCKISH_ATTRS:
                return f"{self.mod.relpath}:{self.fn.klass}" \
                       f".<{'.'.join(parts[1:])}>"
            return None
        if parts[0] in self.mod.imports and len(parts) == 2:
            other = self.models.get(self.mod.imports[parts[0]])
            if other and parts[1] in other.global_locks:
                return f"{other.relpath}:{parts[1]}"
        if parts[-1] in _LOCKISH_ATTRS:
            return f"{self.mod.relpath}:<{dn}>"
        return None

    # -- structure --
    def visit_FunctionDef(self, node: ast.FunctionDef):
        pass            # nested defs are walked as their own _Fn

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        pass

    def visit_With(self, node: ast.With):
        locks: List[str] = []
        for item in node.items:
            key = self._resolve_lock(item.context_expr)
            if key is not None:
                self.fn.acquires.append((key, item.context_expr.lineno))
                for outer in self.held:
                    if outer != key:
                        self.fn.nested.append(
                            (outer, key, item.context_expr.lineno))
                locks.append(key)
            else:
                self.visit(item.context_expr)
        self.held.extend(locks)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(locks):]

    # -- accesses --
    def _record(self, scope: str, name: str, write: bool, lineno: int):
        self.fn.accesses.append(
            (scope, name, write, tuple(self.held), lineno))

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self" \
                and self.fn.klass:
            self._record("attr", node.attr,
                         isinstance(node.ctx, (ast.Store, ast.Del)),
                         node.lineno)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        nm = node.id
        if nm in self.mod.global_names and nm not in self.fn.locals_:
            write = isinstance(node.ctx, (ast.Store, ast.Del)) \
                and nm in self.fn.globals_decl
            if write or isinstance(node.ctx, ast.Load):
                self._record("global", nm, write, node.lineno)

    def visit_Subscript(self, node: ast.Subscript):
        # self.x[k] = v / _g[0] += 1: a write to the container
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            base = node.value
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and self.fn.klass:
                self._record("attr", base.attr, True, node.lineno)
            elif isinstance(base, ast.Name) and \
                    base.id in self.mod.global_names and \
                    base.id not in self.fn.locals_:
                self._record("global", base.id, True, node.lineno)
        self.generic_visit(node)

    # -- calls --
    def visit_Call(self, node: ast.Call):
        func = node.func
        dn = _dotted(func)
        held = tuple(self.held)
        npos = len(node.args)
        kwnames = {kw.arg for kw in node.keywords}

        # mutator method on a tracked receiver is a write
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            base = func.value
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and self.fn.klass:
                self._record("attr", base.attr, True, node.lineno)
            elif isinstance(base, ast.Name) and \
                    base.id in self.mod.global_names and \
                    base.id not in self.fn.locals_:
                self._record("global", base.id, True, node.lineno)

        # wait()/notify() on a class-own condition: the caller must
        # already hold it (Condition semantics), and wait() RELEASES it
        # — record the implied-held lock, never a blocking hazard
        own_cond = None
        if isinstance(func, ast.Attribute) and \
                func.attr in ("wait", "notify", "notify_all"):
            rk = self._resolve_lock(func.value)
            if rk is not None and self.fn.klass and \
                    rk.startswith(f"{self.mod.relpath}:{self.fn.klass}."):
                self.fn.waits_on.add(rk)
                own_cond = rk

        # direct blocking calls
        desc = None
        if dn is not None and dn in _BLOCKING_NAME_CALLS:
            what, transitive = _BLOCKING_NAME_CALLS[dn]
            desc = (what, transitive)
        elif isinstance(func, ast.Attribute) and npos == 0:
            attr = func.attr
            if attr in _BLOCKING_METHODS_ALWAYS:
                desc = (_BLOCKING_METHODS_ALWAYS[attr], True)
            elif attr in _BLOCKING_METHODS_TIMEOUT_OK \
                    and "timeout" not in kwnames:
                # `self._cond.wait()` releases the condition it waits
                # on — not a blocking hazard for that lock itself
                if not (attr == "wait" and
                        (own_cond is not None or
                         self._resolve_lock(func.value) in self.held)):
                    desc = (_BLOCKING_METHODS_TIMEOUT_OK[attr], True)
        if desc is not None:
            self.fn.blocking.append(
                (held, desc[0], node.lineno, desc[1]))

        # call-graph edge for the interprocedural fixpoints
        callee = None
        if isinstance(func, ast.Name):
            callee = ("name", func.id)
        elif isinstance(func, ast.Attribute):
            base = _dotted(func.value)
            if base == "self":
                callee = ("self", func.attr)
            elif base in self.mod.imports:
                callee = ("mod", self.mod.imports[base], func.attr)
            else:
                callee = ("method", func.attr)
        if callee is not None:
            self.fn.calls.append((held, callee, node.lineno))
        self.generic_visit(node)


# --- module model builder ----------------------------------------------------

def _collect_locals(fn_node) -> Tuple[Set[str], Set[str]]:
    """(assigned-or-bound names, declared globals) of one function,
    nested defs excluded."""
    locals_: Set[str] = set()
    globals_decl: Set[str] = set()
    for a in list(fn_node.args.args) + list(fn_node.args.kwonlyargs) \
            + list(fn_node.args.posonlyargs):
        locals_.add(a.arg)
    if fn_node.args.vararg:
        locals_.add(fn_node.args.vararg.arg)
    if fn_node.args.kwarg:
        locals_.add(fn_node.args.kwarg.arg)

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                if hasattr(child, "name"):
                    locals_.add(child.name)
                continue
            if isinstance(child, ast.Global):
                globals_decl.update(child.names)
            elif isinstance(child, ast.Name) and \
                    isinstance(child.ctx, ast.Store):
                locals_.add(child.id)
            walk(child)

    walk(fn_node)
    locals_ -= globals_decl
    return locals_, globals_decl


class _ModuleBuilder:
    def __init__(self, relpath: str, modname: str, source: str):
        self.mod = _Module(relpath=relpath, modname=modname)
        self.is_pkg = relpath.endswith("__init__.py")
        self.source = source
        self.tree = ast.parse(source)
        for i, line in enumerate(source.splitlines(), 1):
            m = _WAIVER_RE.search(line)
            if m:
                codes = {c.strip() for c in m.group(1).split(",")
                         if c.strip()}
                self.mod.waivers.setdefault(i, set()).update(codes)

    # pass 1: module-level names, imports, classes + lock/sync attrs
    def scan_toplevel(self, package: str):
        mod = self.mod
        for node in self.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._scan_import(node, package)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                value = node.value
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if value is not None and \
                            _call_factory(value, _LOCK_FACTORIES):
                        mod.global_locks.add(t.id)
                    else:
                        mod.global_names.add(t.id)
            elif isinstance(node, ast.ClassDef):
                kl = _Class(name=node.name)
                mod.classes[node.name] = kl
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        kl.method_names.add(item.name)
                        for sub in ast.walk(item):
                            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                                self._scan_attr_types(kl, sub)

    def _scan_import(self, node, package: str):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith(package + "."):
                    self.mod.imports[alias.asname or
                                     alias.name.split(".")[-1]] = alias.name
            return
        # from . import telemetry / from ..parallel import emb_cache /
        # from paddle_tpu import x
        base: Optional[str] = None
        if node.level:
            parts = self.mod.modname.split(".")
            # a module's level-1 base is its package; a package
            # __init__'s level-1 base is itself
            drop = node.level - (1 if self.is_pkg else 0)
            keep = len(parts) - drop
            if keep >= 1:
                base = ".".join(parts[:keep])
                if node.module:
                    base = f"{base}.{node.module}"
        elif node.module and (node.module == package or
                              node.module.startswith(package + ".")):
            base = node.module
        if base is None or not base.startswith(package):
            return
        for alias in node.names:
            self.mod.imports[alias.asname or alias.name] = \
                f"{base}.{alias.name}"

    @staticmethod
    def _scan_attr_types(kl: _Class, node):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        value = node.value
        if value is None:
            return
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                if _call_factory(value, _LOCK_FACTORIES):
                    kl.lock_attrs.add(t.attr)
                elif _call_factory(value, _SYNC_FACTORIES) or \
                        _call_factory(value, {"deque"}):
                    kl.sync_attrs.add(t.attr)

    # pass 2: register every function/method/closure
    def register_functions(self):
        def reg(node, qualprefix: str, klass: Optional[str]):
            for child in node.body if hasattr(node, "body") else []:
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = f"{qualprefix}{child.name}"
                    locals_, gdecl = _collect_locals(child)
                    fn = _Fn(qualname=q, name=child.name, klass=klass,
                             lineno=child.lineno, locals_=locals_,
                             globals_decl=gdecl)
                    for sub in ast.walk(child):
                        if isinstance(sub, ast.Assign) and \
                                _call_factory(sub.value, _LOCK_FACTORIES):
                            for t in sub.targets:
                                if isinstance(t, ast.Name):
                                    fn.local_locks.add(t.id)
                    self.mod.functions[q] = fn
                    fn._node = child          # type: ignore[attr-defined]
                    reg(child, q + ".", klass)
                elif isinstance(child, ast.ClassDef):
                    reg(child, f"{child.name}.", child.name)

        reg(self.tree, "", None)

    # pass 3: walk each function with the lockset walker, then census
    def walk(self, models: Dict[str, _Module]):
        for fn in self.mod.functions.values():
            node = fn._node                   # type: ignore[attr-defined]
            walker = _FnWalker(self.mod, fn, models)
            for stmt in node.body:
                walker.visit(stmt)
        self._census()

    # -- thread census --
    def _census(self):
        mod = self.mod
        alias_elems: Dict[str, Set[str]] = {}
        # join receivers + for-aliases (to resolve `for t in threads`)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join" and not node.args:
                # 0 positional args excludes str.join(iterable)
                recv = _dotted(node.func.value)
                if recv:
                    mod.join_receivers.add(recv)
            if isinstance(node, ast.For) and \
                    isinstance(node.target, ast.Name):
                it = _dotted(node.iter)
                if it:
                    mod.for_aliases[node.target.id] = it.split(".")[-1]
                elif isinstance(node.iter, (ast.Tuple, ast.List)):
                    # `for t in (poll_t, watch_t):` — the alias covers
                    # every literal element
                    for el in node.iter.elts:
                        en = _dotted(el)
                        if en:
                            alias_elems.setdefault(
                                node.target.id, set()).add(
                                    en.split(".")[-1])
        # second walk: `threads.append(t)` edges — `t` may itself be a
        # literal-tuple alias collected above, in which case every
        # element it covers lands in the collection
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "append" and len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Name):
                recv = _dotted(node.func.value)
                if recv:
                    arg = node.args[0].id
                    for nm in alias_elems.get(arg, {arg}):
                        mod.append_into[nm] = recv.split(".")[-1]

        class _SiteFinder(ast.NodeVisitor):
            def __init__(self, builder):
                self.b = builder
                self.assign_stack: List[ast.AST] = []

            def visit_Assign(self, node):
                self.assign_stack.append(node)
                self.generic_visit(node)
                self.assign_stack.pop()

            def visit_Call(self, node):
                dn = _dotted(node.func)
                last = dn.split(".")[-1] if dn else None
                if last == "Thread" and dn in ("threading.Thread",
                                               "Thread"):
                    self.b._thread_site(node, self.assign_stack)
                elif last in ("go", "Go") and \
                        self.b.mod.modname != "paddle_tpu.concurrency":
                    self.b._go_site(node)
                self.generic_visit(node)

        _SiteFinder(self).visit(self.tree)

    def _thread_site(self, node: ast.Call, assign_stack: List[ast.AST]):
        kwargs = {kw.arg: kw.value for kw in node.keywords}
        name, is_prefix = (None, False)
        if "name" in kwargs:
            name, is_prefix = _static_name(kwargs["name"])
        daemon: Optional[bool] = None
        if "daemon" in kwargs and isinstance(kwargs["daemon"],
                                             ast.Constant):
            daemon = bool(kwargs["daemon"].value)
        target = _dotted(kwargs["target"]) if "target" in kwargs else None
        stored = None
        for a in reversed(assign_stack):
            if isinstance(a, ast.Assign) and a.targets:
                stored = _dotted(a.targets[0])
                break
        base = stored.split(".")[-1] if stored else None
        joined = self._is_joined(base)
        self.mod.thread_sites.append(ThreadSite(
            module=self.mod.relpath, lineno=node.lineno, kind="thread",
            name=name, name_is_prefix=is_prefix, daemon=daemon,
            target=target, stored_in=stored, joined=joined,
            reaches=self._reaches(target)))

    def _go_site(self, node: ast.Call):
        target = _dotted(node.args[0]) if node.args else None
        self.mod.thread_sites.append(ThreadSite(
            module=self.mod.relpath, lineno=node.lineno, kind="go",
            name="pd-go-", name_is_prefix=True, daemon=True,
            target=target, joined=False, reaches=self._reaches(target)))

    def _is_joined(self, base: Optional[str]) -> bool:
        if base is None:
            return False
        targets = {base}
        coll = self.mod.append_into.get(base)
        if coll:
            targets.add(coll)     # joined via the collection it lives in
        for recv in self.mod.join_receivers:
            rb = recv.split(".")[-1]
            if rb in targets:
                return True
            if self.mod.for_aliases.get(rb) in targets:
                return True
        return False

    def _reaches(self, target: Optional[str]) -> Tuple[str, ...]:
        """attrs/globals the thread target's body touches (depth 1)."""
        if target is None:
            return ()
        base = target.split(".")[-1]
        for q, fn in self.mod.functions.items():
            if q == base or q.endswith("." + base):
                names = sorted({("self." + n if sc == "attr" else n)
                                for sc, n, _w, _h, _l in fn.accesses})
                return tuple(names[:12])
        return ()


# --- model construction ------------------------------------------------------

def _package_root() -> Tuple[str, str]:
    """(repo root dir, package dir name)."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir), os.path.basename(pkg_dir)


def build_models(root: Optional[str] = None) -> Dict[str, _Module]:
    """Parse every .py under the paddle_tpu package into module models,
    keyed by dotted module name."""
    repo, package = _package_root()
    if root is None:
        root = os.path.join(repo, package)
    base = os.path.dirname(os.path.abspath(root))
    builders: List[_ModuleBuilder] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if not f.endswith(".py"):
                continue
            path = os.path.join(dirpath, f)
            rel = os.path.relpath(path, base).replace(os.sep, "/")
            modname = rel[:-3].replace("/", ".")
            if modname.endswith(".__init__"):
                modname = modname[:-len(".__init__")]
            try:
                with open(path, encoding="utf-8") as fh:
                    src = fh.read()
                builders.append(_ModuleBuilder(rel, modname, src))
            except (OSError, SyntaxError):
                continue
    models: Dict[str, _Module] = {}
    pkg = os.path.basename(os.path.abspath(root))
    for b in builders:
        b.scan_toplevel(pkg)
        b.register_functions()
        models[b.mod.modname] = b.mod
    for b in builders:
        b.walk(models)
    return models


def thread_census(root: Optional[str] = None,
                  models: Optional[Dict[str, _Module]] = None
                  ) -> List[ThreadSite]:
    models = models if models is not None else build_models(root)
    sites: List[ThreadSite] = []
    for mod in models.values():
        sites.extend(mod.thread_sites)
    return sorted(sites, key=lambda s: (s.module, s.lineno))


# --- interprocedural fixpoints -----------------------------------------------

def _resolve_callee(mod: _Module, fn: _Fn, callee: Tuple,
                    models: Dict[str, _Module]) -> List[Tuple[_Module, _Fn]]:
    kind = callee[0]
    out: List[Tuple[_Module, _Fn]] = []
    if kind == "name":
        nm = callee[1]
        # closure sibling/child first, then module function
        pref = fn.qualname + "."
        cand = mod.functions.get(pref + nm)
        if cand is None and "." in fn.qualname:
            parent = fn.qualname.rsplit(".", 1)[0]
            cand = mod.functions.get(parent + "." + nm)
        if cand is None:
            cand = mod.functions.get(nm)
        if cand is not None:
            out.append((mod, cand))
    elif kind == "self":
        if fn.klass:
            cand = mod.functions.get(f"{fn.klass}.{callee[1]}")
            if cand is not None:
                out.append((mod, cand))
    elif kind == "method":
        for kl in mod.classes.values():
            if callee[1] in kl.method_names:
                cand = mod.functions.get(f"{kl.name}.{callee[1]}")
                if cand is not None:
                    out.append((mod, cand))
    elif kind == "mod":
        other = models.get(callee[1])
        if other is not None:
            cand = other.functions.get(callee[2])
            if cand is not None and cand.klass is None:
                out.append((other, cand))
    return out


def _fixpoints(models: Dict[str, _Module]):
    """Per-function transitive acquired-lock sets and may-block reasons.
    Returns ({qual: set(lockkeys)}, {qual: (description, via)})
    keyed by "relpath:qualname"."""
    acq: Dict[str, Set[str]] = {}
    blk: Dict[str, Tuple[str, str]] = {}
    key = lambda m, f: f"{m.relpath}:{f.qualname}"  # noqa: E731
    for mod in models.values():
        for fn in mod.functions.values():
            acq[key(mod, fn)] = {k for k, _l in fn.acquires}
            for _held, what, _line, transitive in fn.blocking:
                if transitive and key(mod, fn) not in blk:
                    blk[key(mod, fn)] = (what, fn.qualname)
    for _ in range(6):             # call chains in this repo are shallow
        changed = False
        for mod in models.values():
            for fn in mod.functions.values():
                k = key(mod, fn)
                for _held, callee, _line in fn.calls:
                    for om, ofn in _resolve_callee(mod, fn, callee,
                                                   models):
                        ok = key(om, ofn)
                        extra = acq.get(ok, set()) - acq[k]
                        if extra:
                            acq[k] |= extra
                            changed = True
                        if ok in blk and k not in blk:
                            blk[k] = (blk[ok][0],
                                      f"{ofn.qualname} "
                                      f"({om.relpath})")
                            changed = True
        if not changed:
            break
    return acq, blk


# --- rules -------------------------------------------------------------------

def _waived(mod: _Module, lineno: int, code: str) -> bool:
    return code in mod.waivers.get(lineno, ())


def _emit(diags: List[Diagnostic], mod: _Module, lineno: int,
          severity: str, code: str, message: str,
          hint: Optional[str] = None, var: Optional[str] = None):
    if _waived(mod, lineno, code):
        return
    diags.append(Diagnostic(
        severity=severity, code=code, message=message,
        pass_name=PASS_NAME, var=var,
        site=f"{mod.relpath}:{lineno}", hint=hint))


def _module_lock_keys(mod: _Module) -> Set[str]:
    keys = {f"{mod.relpath}:{g}" for g in mod.global_locks}
    for kl in mod.classes.values():
        keys |= {f"{mod.relpath}:{kl.name}.{a}" for a in kl.lock_attrs}
    return keys


def _primary_lock(mod: _Module, klass: str) -> Optional[str]:
    kl = mod.classes.get(klass)
    if kl and len(kl.lock_attrs) == 1:
        return f"{mod.relpath}:{klass}.{next(iter(kl.lock_attrs))}"
    return None


def _rule_mixed_guard(models: Dict[str, _Module],
                      diags: List[Diagnostic]):
    for mod in models.values():
        own = _module_lock_keys(mod)
        # class fields
        by_field: Dict[Tuple[str, str],
                       List[Tuple[bool, Tuple[str, ...], int, str]]] = {}
        for fn in mod.functions.values():
            if fn.klass is None or fn.name == "__init__":
                continue
            implied = tuple(sorted(fn.waits_on))
            if fn.name.endswith("_locked"):
                pl = _primary_lock(mod, fn.klass)
                if pl:
                    implied = implied + (pl,)
            for scope, name, write, held, lineno in fn.accesses:
                if scope != "attr":
                    continue
                kl = mod.classes.get(fn.klass)
                if kl is None or name in kl.lock_attrs or \
                        name in kl.sync_attrs or name in kl.method_names:
                    continue
                h = tuple(held) + implied
                by_field.setdefault((fn.klass, name), []).append(
                    (write, h, lineno, fn.qualname))
        for (klass, name), accs in sorted(by_field.items()):
            _judge_field(mod, own, f"{klass}.{name}", name, accs, diags)
        # module globals
        by_glob: Dict[str, List[Tuple[bool, Tuple[str, ...], int, str]]] = {}
        for fn in mod.functions.values():
            for scope, name, write, held, lineno in fn.accesses:
                if scope == "global":
                    by_glob.setdefault(name, []).append(
                        (write, tuple(held), lineno, fn.qualname))
        for name, accs in sorted(by_glob.items()):
            _judge_field(mod, own, name, name, accs, diags)


def _judge_field(mod: _Module, own_locks: Set[str], label: str,
                 var: str, accs, diags: List[Diagnostic]):
    guarded = [(w, h, l, q) for w, h, l, q in accs
               if any(k in own_locks for k in h)]
    bare = [(w, h, l, q) for w, h, l, q in accs
            if not any(k in own_locks for k in h)]
    writes = [a for a in accs if a[0]]
    if not guarded or not bare or not writes:
        return
    locks = sorted({k for _w, h, _l, _q in guarded for k in h
                    if k in own_locks})
    lock_names = ", ".join(k.split(":", 1)[1] for k in locks)
    for _w, _h, lineno, qual in sorted(bare, key=lambda a: a[2]):
        _emit(diags, mod, lineno, "error", "lockset-mixed-guard",
              f"'{label}' is guarded by {lock_names} elsewhere in this "
              f"module but accessed bare in {qual}()",
              hint=f"hold {lock_names} here too, or waive with "
                   f"'# thread-lint: ok lockset-mixed-guard' if this "
                   f"access provably happens-before/after all "
                   f"concurrent use", var=var)


def _rule_lock_order(models: Dict[str, _Module], acq: Dict[str, Set[str]],
                     diags: List[Diagnostic]):
    edges: Dict[Tuple[str, str], Tuple[_Module, int]] = {}

    def add(outer, inner, mod, lineno):
        if outer != inner:
            edges.setdefault((outer, inner), (mod, lineno))

    for mod in models.values():
        for fn in mod.functions.values():
            for outer, inner, lineno in fn.nested:
                add(outer, inner, mod, lineno)
            for held, callee, lineno in fn.calls:
                if not held:
                    continue
                for om, ofn in _resolve_callee(mod, fn, callee, models):
                    for inner in acq.get(f"{om.relpath}:{ofn.qualname}",
                                         ()):
                        for outer in held:
                            add(outer, inner, mod, lineno)
    # DFS cycle detection over the lock graph
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    seen: Set[str] = set()
    reported: Set[frozenset] = set()

    def dfs(node, stack, onstack):
        seen.add(node)
        onstack.add(node)
        stack.append(node)
        for nxt in graph.get(node, ()):
            if nxt in onstack:
                cycle = stack[stack.index(nxt):] + [nxt]
                fs = frozenset(cycle)
                if fs not in reported:
                    reported.add(fs)
                    mod, lineno = edges[(node, nxt)]
                    _emit(diags, mod, lineno, "error",
                          "lock-order-cycle",
                          "lock-order cycle (deadlock potential): "
                          + " -> ".join(c.split(":", 1)[1]
                                        for c in cycle),
                          hint="acquire these locks in one global "
                               "order everywhere, or drop to a "
                               "single lock")
            elif nxt not in seen:
                dfs(nxt, stack, onstack)
        stack.pop()
        onstack.discard(node)

    for node in sorted(graph):
        if node not in seen:
            dfs(node, [], set())


def _rule_blocking(models: Dict[str, _Module],
                   blk: Dict[str, Tuple[str, str]],
                   diags: List[Diagnostic]):
    for mod in models.values():
        for fn in mod.functions.values():
            for held, what, lineno, _tr in fn.blocking:
                if not held:
                    continue
                lock = held[-1].split(":", 1)[1]
                _emit(diags, mod, lineno, "error", "blocking-under-lock",
                      f"{what} while holding {lock}",
                      hint="move the blocking call outside the critical "
                           "section (snapshot state under the lock, "
                           "block after releasing), or waive with "
                           "'# thread-lint: ok blocking-under-lock' "
                           "when the wait is the point")
            for held, callee, lineno in fn.calls:
                if not held:
                    continue
                for om, ofn in _resolve_callee(mod, fn, callee, models):
                    k = f"{om.relpath}:{ofn.qualname}"
                    if k in blk:
                        what, via = blk[k]
                        lock = held[-1].split(":", 1)[1]
                        _emit(diags, mod, lineno, "error",
                              "blocking-under-lock",
                              f"call to {ofn.qualname}() may block "
                              f"({what} via {via}) while holding {lock}",
                              hint="release the lock before the call, "
                                   "or waive with '# thread-lint: ok "
                                   "blocking-under-lock'")


def _catalog_match(site: ThreadSite) -> Optional[str]:
    for cname, entry in THREAD_CATALOG.items():
        if entry["module"] != site.module:
            continue
        if entry.get("prefix"):
            if site.name is not None and site.name == cname:
                return cname
        elif site.name == cname:
            return cname
    return None


def _rule_threads(models: Dict[str, _Module], diags: List[Diagnostic]):
    sites = thread_census(models=models)
    seen_entries: Set[str] = set()
    for s in sites:
        mod = next((m for m in models.values()
                    if m.relpath == s.module), None)
        if mod is None:
            continue
        if s.kind == "thread" and s.name is None:
            _emit(diags, mod, s.lineno, "error", "thread-unnamed",
                  f"thread created without name= (target="
                  f"{s.target or '?'})",
                  hint="name every background thread (pd-<subsystem>-"
                       "<role>) so sentinel hang reports and the "
                       "census render readable identities")
        if s.kind == "thread" and s.daemon is not True:
            _emit(diags, mod, s.lineno, "warning", "thread-non-daemon",
                  f"thread '{s.name or s.target}' is not daemon=True; "
                  f"a wedged worker would hang interpreter exit",
                  hint="pass daemon=True unless a clean join on "
                       "shutdown is guaranteed")
        entry = _catalog_match(s)
        if entry is None:
            _emit(diags, mod, s.lineno, "error", "thread-uncataloged",
                  f"thread creation site (name={s.name!r}) has no "
                  f"THREAD_CATALOG entry",
                  hint="declare it in paddle_tpu/analysis/threads.py "
                       "THREAD_CATALOG (module, daemon, joined, help)")
        else:
            seen_entries.add(entry)
            decl = THREAD_CATALOG[entry]
            if decl.get("joined") and not s.joined and s.kind == "thread":
                _emit(diags, mod, s.lineno, "warning",
                      "thread-never-joined",
                      f"catalog declares '{entry}' joined=True but no "
                      f"join site for {s.stored_in or '?'} exists in "
                      f"{s.module}",
                      hint="join the handle on the shutdown path or "
                           "declare joined=False in THREAD_CATALOG")
        _emit(diags, mod, s.lineno, "info", "thread-census",
              f"{s.kind} name={s.name or '<unnamed>'}"
              f"{'*' if s.name_is_prefix else ''} "
              f"daemon={s.daemon} target={s.target or '?'} "
              f"joined={s.joined}"
              + (f" reaches={','.join(s.reaches)}" if s.reaches else ""))
    for cname, entry in THREAD_CATALOG.items():
        if cname in seen_entries:
            continue
        mod = next((m for m in models.values()
                    if m.relpath == entry["module"]), None)
        if mod is None:
            continue
        _emit(diags, mod, 1, "error", "thread-catalog-stale",
              f"THREAD_CATALOG entry '{cname}' has no matching "
              f"Thread/go creation site in {entry['module']}",
              hint="remove the stale entry or restore the thread name")


# --- entry points ------------------------------------------------------------

def analyze_threads(root: Optional[str] = None) -> Report:
    """Run the full lint over the paddle_tpu tree (or `root`) and return
    an `analysis.Report`. Never raises: an analyzer-internal failure
    degrades to a single warning, same contract as analyze_program."""
    diags: List[Diagnostic] = []
    try:
        models = build_models(root)
        _rule_threads(models, diags)
        _rule_mixed_guard(models, diags)
        acq, blk = _fixpoints(models)
        _rule_lock_order(models, acq, diags)
        _rule_blocking(models, blk, diags)
    except Exception as e:  # noqa: BLE001 - analyzer must not crash
        diags.append(Diagnostic(
            severity="warning", code="analyzer-internal",
            message=f"thread lint failed internally: {e!r}",
            pass_name=PASS_NAME))
    order = {"error": 0, "warning": 1, "info": 2}
    diags.sort(key=lambda d: (order.get(d.severity, 3), d.site or ""))
    return Report(diags)


def catalog_problems(root: Optional[str] = None) -> List[Tuple[str, str]]:
    """check_registry.py surface: both-direction THREAD_CATALOG pinning
    as (where, message) pairs."""
    problems: List[Tuple[str, str]] = []
    sites = thread_census(root)
    seen: Set[str] = set()
    for s in sites:
        entry = _catalog_match(s)
        if entry is None:
            problems.append((
                f"{s.module}:{s.lineno}",
                f"thread creation site (kind={s.kind}, name={s.name!r}) "
                f"not declared in THREAD_CATALOG"))
            continue
        seen.add(entry)
        decl = THREAD_CATALOG[entry]
        if s.kind == "thread" and decl.get("daemon") is not None and \
                s.daemon is not None and bool(decl["daemon"]) != s.daemon:
            problems.append((
                f"{s.module}:{s.lineno}",
                f"THREAD_CATALOG['{entry}'] declares daemon="
                f"{decl['daemon']} but the site passes daemon={s.daemon}"))
        if s.kind == "thread" and decl.get("joined") and not s.joined:
            problems.append((
                f"{s.module}:{s.lineno}",
                f"THREAD_CATALOG['{entry}'] declares joined=True but "
                f"no join site exists in {s.module}"))
    for cname, entry in THREAD_CATALOG.items():
        if cname not in seen:
            problems.append((
                f"analysis/threads.py THREAD_CATALOG['{cname}']",
                f"no matching Thread/go creation site in "
                f"{entry['module']}"))
    return problems
