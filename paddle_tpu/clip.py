"""Error clip + gradient clipping pipeline
(reference: python/paddle/fluid/clip.py:32-215)."""

from __future__ import annotations

__all__ = ["ErrorClipByValue", "GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "set_gradient_clip",
           "append_gradient_clip_ops", "error_clip_callback"]


class BaseErrorClipAttr:
    def append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def append_clip_op(self, block, grad_name):
        block.append_op(type="clip", inputs={"X": [grad_name]},
                        outputs={"Out": [grad_name]},
                        attrs={"min": self.min, "max": self.max})


def error_clip_callback(block, op):
    # placeholder hook for per-op error clipping; attrs-driven clipping is
    # attached via Variable error_clip attrs (reference clip.py:66)
    pass


class BaseGradientClipAttr:
    def process_context(self, context, param, grad):
        pass

    def create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def create_operators(self, param, grad):
        from .layers.nn import clip as clip_layer
        return param, clip_layer(grad, self.min, self.max)


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def create_operators(self, param, grad):
        from .layers.nn import clip_by_norm
        return param, clip_by_norm(grad, self.clip_norm)


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        from .layers.nn import reduce_sum
        from .layers.ops import square
        context[self.group_name].append(reduce_sum(square(grad)))

    def create_operators(self, param, grad):
        from .layers import nn, ops, tensor
        context = getattr(self, "_context")
        # compute the global norm + scale once per group, reuse for every param
        scale_key = self.group_name + "_scale_var"
        if scale_key not in context:
            group = context[self.group_name]
            total = group[0] if len(group) == 1 else tensor.sums(group)
            global_norm = ops.sqrt(total)
            clip_value = tensor.fill_constant([1], "float32", self.clip_norm)
            context[scale_key] = nn.elementwise_div(
                clip_value, nn.elementwise_max(clip_value, global_norm))
            # mark the norm var for the executor's telemetry side-fetch:
            # Executor.run publishes it as the optimizer_global_norm gauge
            # (ISSUE: "global-norm gauge when clipping is active"); the
            # mark rides the program so clones/pruned programs drop it
            prog = global_norm.block.program
            marks = getattr(prog, "_telemetry_fetch_extra", None)
            if marks is None:
                marks = prog._telemetry_fetch_extra = {}
            marks["optimizer_global_norm"] = global_norm.name
        return param, nn.elementwise_mul(grad, context[scale_key])


def set_gradient_clip(clip, param_list=None, program=None):
    from .framework.framework import default_main_program
    program = program or default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    param_list = [program.global_block().var(p) if isinstance(p, str) else p
                  for p in param_list]
    for param in param_list:
        param.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grad):
    context = {}
    for p, g in param_grad:
        clip_attr = getattr(p, "gradient_clip_attr", None)
        if clip_attr is None:
            clip_attr = NullGradientClipAttr()
        clip_attr.process_context(context=context, param=p, grad=g)
    res = []
    for p, g in param_grad:
        clip_attr = getattr(p, "gradient_clip_attr", None)
        if clip_attr is None:
            clip_attr = NullGradientClipAttr()
        clip_attr._context = context
        res.append(clip_attr.create_operators(param=p, grad=g))
    return res
