"""Program inspector: on-device tensor-stat probes, NaN/Inf origin
attribution, gradient-flow audit, and a crash flight recorder.

The reference framework's numeric-health story is all-or-nothing:
FLAGS_check_nan_inf scans every op output on the host after each kernel
(reference executor.cc:325-333 CheckTensorNANOrInf), and jax_debug_nans
de-optimizes the whole program to op-by-op execution. This module keeps the
whole-block jit while still localizing *which op and which step* went
non-finite:

1. Probe pass — `instrument(program, ...)` clones the program and inserts
   `tensor_stats` ops after selected ops. Each probe reduces one tensor to an
   8-float vector (min/max/mean/abs-mean/l2/nan-count/inf-count/size) *inside
   the jitted computation*; the executor fetches the vectors alongside the
   user's fetch list, so a probed step costs one device round-trip, not an
   op-by-op fallback. Selection is by output name, op type, regex, explicit
   indices, `every=True`, or `auto=True` (role boundaries + loss/grad vars).

2. Origin attribution — `attribute_nonfinite(...)` replays a failing step
   against a scratch copy of the scope, bisecting over program position:
   each round probes one checkpoint op and halves the window, then a dense
   pass over the final window names the first offending op; one more run
   collects its inputs' stats. O(log n) replays, reported as a structured
   `errors.NonFiniteError` + `nonfinite_detections_total` counter.

3. Gradient-flow audit — `GradientAudit(program)` walks backward.py's
   grad-var mapping and probes every trainable parameter's final gradient;
   `report()` classifies each as zero / vanishing / exploding / nonfinite /
   ok and feeds the telemetry gauges `grad_l2` / `grad_abs_mean`.

4. Flight recorder — `enable_flight_recorder(path)` (or the
   PADDLE_TPU_FLIGHT_RECORDER flag) keeps a bounded ring of recent step
   records and dumps a JSON crash report (steps, probe stats, telemetry
   events, flags/env, pprint_program text) on executor exception or fatal
   signal. `read_crash_report` / `python -m paddle_tpu inspect <dump>`
   read it back.
"""

from __future__ import annotations

import collections
import json
import os
import re
import signal as signal_mod
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from . import flags, telemetry
from .errors import NonFiniteError
from .framework.desc import VarType
from .framework.framework import Program, grad_var_name
from .ops import registry

__all__ = [
    "STAT_FIELDS", "TensorStats", "ProbeSite", "Attribution", "GradientAudit",
    "instrument", "select_ops", "probe_compatible", "attribute_nonfinite",
    "enable_flight_recorder", "disable_flight_recorder", "flight_enabled",
    "dump_crash_report", "read_crash_report", "format_crash_report",
    "probe_report", "feed_signature",
]

STAT_FIELDS = ("min", "max", "mean", "abs_mean", "l2",
               "nan_count", "inf_count", "size")

_FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")

# op types whose outputs are not plain tensors a stats reduction can consume:
# step scopes, rank tables, tensor arrays, or nothing at all (side-effect
# ops). tensor_stats itself is excluded so `every=True` never probes probes.
_NON_TENSOR_OUTPUT_OPS = frozenset({
    "feed", "fetch", "while", "while_grad", "conditional_block",
    "conditional_block_grad", "rnn", "write_to_array", "lod_rank_table",
    "lod_tensor_to_array", "save", "save_combine", "tensor_stats",
})


# ---------------------------------------------------------------------------
# The tensor_stats op
# ---------------------------------------------------------------------------

def _tensor_stats_infer(op, block):
    for name in op.desc.outputs.get("Out", []):
        if block.desc.has_var(name):
            v = block.desc.var(name)
            v.shape = [len(STAT_FIELDS)]
            v.dtype = "float32"


def _tensor_stats_lower(ctx, op_, ins):
    """[min, max, mean, abs_mean, l2, nan_count, inf_count, size] of X as a
    float32 vector. min/max/mean/l2 are computed over the *finite* elements
    (masked), so the summary stays informative even while NaNs are present;
    the counts carry the contamination. A 1-D [8] output stays below the
    executor's ndim>=2 SEQLEN-inheritance rule, so probing a sequence tensor
    never tags the stats vector as a sequence."""
    k = len(STAT_FIELDS)
    x = ins["X"][0] if ins.get("X") else None
    if x is None:
        return {"Out": [jnp.zeros((k,), jnp.float32)]}
    x = jnp.asarray(x)
    if x.size == 0:
        return {"Out": [jnp.zeros((k,), jnp.float32)]}
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        nan_mask = jnp.isnan(x.real) | jnp.isnan(x.imag)
        inf_mask = jnp.isinf(x.real) | jnp.isinf(x.imag)
        xf = jnp.abs(x).astype(jnp.float32)
    elif jnp.issubdtype(x.dtype, jnp.inexact):
        # masks on the original dtype: a float64 value that overflows the
        # float32 display cast must not be miscounted as Inf
        nan_mask = jnp.isnan(x)
        inf_mask = jnp.isinf(x)
        xf = x.astype(jnp.float32)
    else:
        nan_mask = jnp.zeros(x.shape, bool)
        inf_mask = jnp.zeros(x.shape, bool)
        xf = x.astype(jnp.float32)
    finite = ~(nan_mask | inf_mask)
    n_finite = finite.sum().astype(jnp.float32)
    denom = jnp.maximum(n_finite, 1.0)
    safe = jnp.where(finite, xf, 0.0)
    mn = jnp.where(n_finite > 0, jnp.where(finite, xf, jnp.inf).min(), 0.0)
    mx = jnp.where(n_finite > 0, jnp.where(finite, xf, -jnp.inf).max(), 0.0)
    out = jnp.stack([
        mn, mx, safe.sum() / denom, jnp.abs(safe).sum() / denom,
        jnp.sqrt(jnp.square(safe).sum()),
        nan_mask.sum().astype(jnp.float32),
        inf_mask.sum().astype(jnp.float32),
        jnp.asarray(x.size, jnp.float32)])
    return {"Out": [out]}


if registry.try_get("tensor_stats") is None:
    registry.register("tensor_stats", lower=_tensor_stats_lower,
                      infer_shape=_tensor_stats_infer, grad=registry.NO_GRAD,
                      non_diff_inputs=("X",))


class TensorStats:
    """Wrapper over one fetched stats vector."""

    __slots__ = ("vec",)

    def __init__(self, vec):
        self.vec = np.asarray(vec, np.float64).ravel()

    def _f(self, name):
        return float(self.vec[STAT_FIELDS.index(name)])

    min = property(lambda s: s._f("min"))
    max = property(lambda s: s._f("max"))
    mean = property(lambda s: s._f("mean"))
    abs_mean = property(lambda s: s._f("abs_mean"))
    l2 = property(lambda s: s._f("l2"))
    nan_count = property(lambda s: s._f("nan_count"))
    inf_count = property(lambda s: s._f("inf_count"))
    size = property(lambda s: s._f("size"))

    @property
    def nonfinite(self) -> bool:
        return (self.nan_count + self.inf_count) > 0

    def to_dict(self) -> Dict[str, float]:
        return {f: float(self.vec[i]) for i, f in enumerate(STAT_FIELDS)}

    def __repr__(self):
        return (f"TensorStats(min={self.min:.4g}, max={self.max:.4g}, "
                f"mean={self.mean:.4g}, l2={self.l2:.4g}, "
                f"nan={self.nan_count:.0f}, inf={self.inf_count:.0f}, "
                f"size={self.size:.0f})")


class ProbeSite:
    """One inserted probe: which op (pristine-program index) and which var it
    watches, and the stat var carrying its vector. kind: 'probe' (output
    probe), 'input' (attribution input probe), 'grad' (GradientAudit)."""

    __slots__ = ("op_index", "op_type", "var", "stat_var", "kind", "param")

    def __init__(self, op_index, op_type, var, stat_var, kind="probe",
                 param=None):
        self.op_index = op_index
        self.op_type = op_type
        self.var = var
        self.stat_var = stat_var
        self.kind = kind
        self.param = param

    def to_dict(self):
        return {"op_index": self.op_index, "op_type": self.op_type,
                "var": self.var, "kind": self.kind, "param": self.param}

    def __repr__(self):
        return (f"ProbeSite(op {self.op_index} '{self.op_type}' "
                f"-> '{self.var}', kind={self.kind})")


class _Plan:
    __slots__ = ("insert_at", "var", "site")

    def __init__(self, insert_at, var, site):
        self.insert_at = insert_at
        self.var = var
        self.site = site


# ---------------------------------------------------------------------------
# Probe pass
# ---------------------------------------------------------------------------

def probe_compatible(op_type: str) -> bool:
    """Type-level predicate: can tensor_stats consume this op's output?
    True when the op has a kernel lowering and pure-tensor outputs (see
    tools/op_coverage.py --probe-compat for the registry-wide report)."""
    if op_type in _NON_TENSOR_OUTPUT_OPS:
        return False
    opdef = registry.try_get(op_type)
    return (opdef is not None and not opdef.no_kernel
            and opdef.lower is not None)


def _probeable_var(block, name: str) -> bool:
    if not name or not block.desc.has_var(name):
        return False
    v = block.desc.var(name)
    if v.type not in (VarType.LOD_TENSOR, VarType.SELECTED_ROWS):
        return False
    return (v.dtype or "float32") in _FLOAT_DTYPES


def _probe_target(block, op) -> Optional[str]:
    """First float-tensor output of `op`, or None when the op is not
    probe-able (structural op, int outputs, no declared tensor output)."""
    if not probe_compatible(op.type):
        return None
    for name in op.output_arg_names:
        if _probeable_var(block, name):
            return name
    return None


def _auto_indices(program: Program) -> List[int]:
    """`auto` selection: block boundaries (first/last op + the last op of
    each op_role segment: forward->backward->optimize transitions) plus the
    ops producing the loss (backward.py records program._loss_names) and
    every parameter gradient."""
    block = program.global_block()
    n = len(block.ops)
    if not n:
        return []
    sel = {0, n - 1}
    roles = [op.desc.attrs.get("op_role") for op in block.ops]
    for i in range(n - 1):
        if roles[i] != roles[i + 1]:
            sel.add(i)
    interesting = set(getattr(program, "_loss_names", ()))
    interesting.update(grad_var_name(p.name)
                       for p in block.all_parameters())
    for i, op in enumerate(block.ops):
        if interesting & set(op.output_arg_names):
            sel.add(i)
    return sorted(sel)


def select_ops(program: Program, *, names=None, types=None, regex=None,
               indices=None, auto: bool = False,
               every: bool = False) -> List[int]:
    """Root-block op indices matched by any of the selectors: output var
    `names`, op `types`, a `regex` over op type and output names, explicit
    `indices`, `auto` boundaries, or `every` op."""
    block = program.global_block()
    sel = set(int(i) for i in (indices or ()))
    name_set = set(names or ())
    type_set = set(types or ())
    pat = re.compile(regex) if regex else None
    for i, op in enumerate(block.ops):
        if op.type in type_set:
            sel.add(i)
        if name_set and name_set & set(op.output_arg_names):
            sel.add(i)
        if pat is not None and (pat.search(op.type) or
                                any(pat.search(n)
                                    for n in op.output_arg_names)):
            sel.add(i)
    if every:
        sel.update(range(len(block.ops)))
    if auto:
        sel.update(_auto_indices(program))
    return sorted(i for i in sel if 0 <= i < len(block.ops))


def _apply_plans(base: Program, plans: List[_Plan]) -> Program:
    """Clone `base` and insert one tensor_stats op per plan. Insertions run
    highest-position-first so earlier insert positions stay valid; sites keep
    their *pristine* op indices for attribution windows."""
    inst = base.clone()
    block = inst.global_block()
    for plan in sorted(plans, key=lambda p: p.insert_at, reverse=True):
        block.create_var(name=plan.site.stat_var,
                         shape=[len(STAT_FIELDS)], dtype="float32")
        block.insert_op(plan.insert_at, type="tensor_stats",
                        inputs={"X": [plan.var]},
                        outputs={"Out": [plan.site.stat_var]},
                        attrs={"op_role": "probe"})
    inst._probe_sites = sorted((p.site for p in plans),
                               key=lambda s: (s.op_index, s.kind, s.var))
    inst._probe_parent = base
    return inst


def instrument(program: Program, *, names=None, types=None, regex=None,
               indices=None, auto: bool = False,
               every: bool = False) -> Program:
    """Probe pass: return a clone of `program` with tensor_stats probes on
    the first float output of every selected op. The executor fetches the
    probe vectors with the user fetch list (one round-trip), records them on
    the program as `_last_probe_stats` (see probe_report), and raises a
    structured NonFiniteError — with bisection attribution — when any probe
    reports NaN/Inf."""
    base = getattr(program, "_probe_parent", None) or program
    selected = select_ops(base, names=names, types=types, regex=regex,
                          indices=indices, auto=auto, every=every)
    block = base.global_block()
    plans = []
    for i in selected:
        var = _probe_target(block, block.ops[i])
        if var is None:
            continue
        plans.append(_Plan(i + 1, var, ProbeSite(
            i, block.ops[i].type, var, f"{var}@STATS@{i}", kind="probe")))
    if not plans:
        raise ValueError(
            "no probe-compatible ops matched the selection (see "
            "inspector.probe_compatible / tools/op_coverage.py "
            "--probe-compat)")
    return _apply_plans(base, plans)


def probe_report(program: Program) -> List[Dict[str, Any]]:
    """Last run's probe stats of an instrumented program, as dicts sorted by
    op position (empty before the first run)."""
    stats = getattr(program, "_last_probe_stats", None) or {}
    return [dict(site.to_dict(), stats=st.to_dict())
            for site, st in sorted(stats.items(),
                                   key=lambda it: it[0].op_index)]


def feed_signature(feed) -> Optional[Tuple]:
    """telemetry.signature_of over a user feed dict (tolerates LoDTensor and
    plain-list values)."""
    try:
        from .executor import LoDTensor
        vals = {}
        for k, v in (feed or {}).items():
            if isinstance(v, LoDTensor):
                v = v.array()
            vals[k] = np.asarray(v)
        return telemetry.signature_of(vals)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# NaN/Inf origin attribution
# ---------------------------------------------------------------------------

class Attribution:
    """Result of a bisection replay: the first op whose output went
    non-finite, with its stats, its inputs' stats, and the replay cost."""

    def __init__(self, op_index, op_type, var, stats, input_stats, inputs,
                 outputs, creation_site, runs, feed_signature):
        self.op_index = op_index
        self.op_type = op_type
        self.var = var
        self.stats = stats
        self.input_stats = input_stats      # {input var: TensorStats}
        self.inputs = inputs
        self.outputs = outputs
        self.creation_site = creation_site
        self.runs = runs                    # replay executor runs used
        self.feed_signature = feed_signature

    def summary(self) -> str:
        parts = [f"origin: op {self.op_index} '{self.op_type}' -> "
                 f"'{self.var}' ({self.stats.nan_count:.0f} NaN, "
                 f"{self.stats.inf_count:.0f} Inf) "
                 f"[{self.runs} replay run(s)]"]
        if self.creation_site:
            parts.append(f"built at {self.creation_site}")
        for n, st in self.input_stats.items():
            parts.append(f"input '{n}': {st!r}")
        return "; ".join(parts)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "op_index": self.op_index, "op_type": self.op_type,
            "var": self.var, "stats": self.stats.to_dict(),
            "input_stats": {n: st.to_dict()
                            for n, st in self.input_stats.items()},
            "inputs": list(self.inputs), "outputs": list(self.outputs),
            "creation_site": self.creation_site, "runs": self.runs,
            "feed_signature": ([list(s) for s in self.feed_signature]
                               if self.feed_signature else None),
        }

    def __repr__(self):
        return f"Attribution({self.summary()})"


def _copy_value(v, fallback):
    """Host copy of one scope value for the attribution scratch scope.
    Copies decouple the replay from jit buffer donation two ways: the
    original scope's buffers may already be donated (deleted) — then the
    post-step `fallback` from new_state stands in — and each replay run
    re-uploads from numpy, so replay N's donation never invalidates
    replay N+1's input."""
    from .executor import LoDTensor
    if v is None:
        return None
    if isinstance(v, LoDTensor):
        try:
            return LoDTensor(np.array(v.array()),
                             [list(l) for l in (v.lod or [])])
        except Exception:
            v = fallback
            if v is None:
                return None
    try:
        return np.array(v)
    except Exception:
        if fallback is not None:
            try:
                return np.array(fallback)
            except Exception:
                return fallback
        return v


def _scratch_scope(scope, state):
    from .executor import Scope
    s = Scope()
    seen = set()
    sc = scope
    while sc is not None:
        for n, v in sc.vars.items():
            if n in seen or n == "__rng_counter__":
                continue
            seen.add(n)
            s.set_var(n, _copy_value(v, (state or {}).get(n)))
        sc = sc.parent
    return s


def attribute_nonfinite(exe, program: Program, feed, *, scope=None,
                        state=None, rng_counter=0, use_jit=None,
                        window: Optional[Tuple[int, int]] = None,
                        max_dense: int = 8,
                        max_runs: int = 40) -> Optional[Attribution]:
    """Name the first op whose output goes non-finite when `program` is
    re-run with `feed`. Replays happen against a scratch copy of `scope`
    (post-step persistable values from `state` stand in for donated
    buffers), with the same rng_counter so dropout masks etc. reproduce.

    Bisection over program position: each round instruments ONE checkpoint
    op (midpoint of the window) and runs once; a finite checkpoint moves the
    window past it, a non-finite one pulls the window in. Once the window is
    <= max_dense candidate ops, one dense pass probes all of them, and a
    final run collects the offender's input stats. Cost: ceil(log2(n /
    max_dense)) + 2 replay runs — the acceptance bound is O(log n). Should
    the non-finite value *not* propagate to a probed checkpoint (masked
    downstream), a full dense fallback pass recovers correctness at the
    price of one more run.

    Returns None when attribution is inconclusive (nothing non-finite on
    replay — e.g. nondeterministic corruption — or no probe-able ops)."""
    if scope is None:
        from .executor import global_scope
        scope = global_scope()
    base = getattr(program, "_probe_parent", None)
    if base is None:
        base = None if getattr(program, "_probe_sites", None) else program
    if base is None:
        return None
    block = base.global_block()
    cands = [i for i in range(len(block.ops))
             if _probe_target(block, block.ops[i]) is not None]
    if window is not None:
        lo_op, hi_op = window
        in_window = [i for i in cands if lo_op <= i <= hi_op]
        cands = in_window or cands
    if not cands:
        return None

    scratch = _scratch_scope(scope, state)
    runs = 0

    def probe_run(plans):
        nonlocal runs
        inst = _apply_plans(base, plans)
        inst._inspector_internal = True
        scratch.set_var("__rng_counter__", int(rng_counter))
        vals = exe.run(inst, feed=dict(feed or {}),
                       fetch_list=[s.stat_var for s in inst._probe_sites],
                       scope=scratch, use_program_cache=False,
                       use_jit=use_jit)
        runs += 1
        return [(site, TensorStats(v))
                for site, v in zip(inst._probe_sites, vals)]

    def out_plan(i):
        var = _probe_target(block, block.ops[i])
        return _Plan(i + 1, var, ProbeSite(
            i, block.ops[i].type, var, f"{var}@STATS@{i}", kind="probe"))

    try:
        lo, hi = 0, len(cands) - 1
        while (hi - lo + 1) > max_dense and runs < max_runs:
            mid = (lo + hi) // 2
            res = probe_run([out_plan(cands[mid])])
            if any(st.nonfinite for _, st in res):
                hi = mid
            else:
                lo = mid + 1
        offender = offender_stats = None
        res = probe_run([out_plan(cands[j]) for j in range(lo, hi + 1)])
        for site, st in res:
            if st.nonfinite:
                offender, offender_stats = site, st
                break
        if offender is None and (lo > 0 or hi < len(cands) - 1) \
                and runs < max_runs:
            # the monotonic-propagation assumption failed: dense fallback
            res = probe_run([out_plan(j) for j in cands])
            for site, st in res:
                if st.nonfinite:
                    offender, offender_stats = site, st
                    break
        if offender is None:
            return None

        op = block.ops[offender.op_index]
        input_stats: Dict[str, TensorStats] = {}
        in_plans = [
            _Plan(offender.op_index, n, ProbeSite(
                offender.op_index, op.type, n,
                f"{n}@STATS@in{offender.op_index}", kind="input"))
            for n in dict.fromkeys(op.input_arg_names)
            if _probeable_var(block, n)]
        if in_plans and runs < max_runs:
            try:
                for site, st in probe_run(in_plans):
                    input_stats[site.var] = st
            except Exception:
                input_stats = {}
    except Exception:
        return None

    return Attribution(
        op_index=offender.op_index, op_type=op.type, var=offender.var,
        stats=offender_stats, input_stats=input_stats,
        inputs=list(op.input_arg_names), outputs=list(op.output_arg_names),
        creation_site=getattr(op, "creation_site", None), runs=runs,
        feed_signature=feed_signature(feed))


# ---------------------------------------------------------------------------
# Executor hooks (probe recording + NonFiniteError raising)
# ---------------------------------------------------------------------------

def record_probes(exe, program, scope, sites, stat_vals, *, feed, new_state,
                  rng_counter, prog_label):
    """Called by the executor after a probed run, before state writeback:
    stores stats on the program, feeds the gradient-audit gauges, and — when
    any output probe reports NaN/Inf — counts the detection, runs bisection
    attribution inside the window the probes already narrowed, and raises a
    structured NonFiniteError (so the diverged state is never committed)."""
    stats: Dict[ProbeSite, TensorStats] = {}
    for site, val in zip(sites, stat_vals):
        try:
            stats[site] = TensorStats(val)
        except Exception:
            continue
    program._last_probe_stats = stats
    audit = getattr(program, "_grad_audit", None)
    if audit is not None:
        audit._observe(stats, prog_label)
    try:
        # activation probes feed the observatory's `saturating` verdicts
        from . import dynamics as dynamics_mod
        dynamics_mod.observe_probes(prog_label, stats)
    except Exception:
        pass
    bad = sorted(((s, st) for s, st in stats.items()
                  if s.kind == "probe" and st.nonfinite),
                 key=lambda it: it[0].op_index)
    if not bad:
        return
    telemetry.counter(
        "nonfinite_detections_total",
        "NaN/Inf values caught by check_nan_inf or inspector probes",
        labels=("program", "source")).labels(
            program=prog_label, source="probe").inc()
    site, st = bad[0]
    # the probes already bracket the origin: start the bisection window at
    # the last finite probed op before the first bad one
    window_lo = 0
    for s2, st2 in stats.items():
        if s2.kind == "probe" and s2.op_index < site.op_index \
                and not st2.nonfinite:
            window_lo = max(window_lo, s2.op_index + 1)
    attribution = None
    if flags.get("nonfinite_attribution"):
        try:
            attribution = attribute_nonfinite(
                exe, program, feed, scope=scope, state=new_state,
                rng_counter=rng_counter, window=(window_lo, site.op_index))
        except Exception:
            attribution = None
    msg = (f"NaN/Inf detected by probe: op {site.op_index} "
           f"'{site.op_type}' output '{site.var}' has "
           f"{st.nan_count:.0f} NaN / {st.inf_count:.0f} Inf values")
    if attribution is not None:
        msg += "\n  " + attribution.summary()
    raise NonFiniteError(msg, var_name=site.var, op_type=site.op_type,
                         op_index=site.op_index, stats=st,
                         attribution=attribution,
                         feed_signature=feed_signature(feed))


# ---------------------------------------------------------------------------
# Gradient-flow audit
# ---------------------------------------------------------------------------

class GradientAudit:
    """Per-step gradient health for every trainable parameter.

    Walks the program for the last op writing each parameter's grad var
    (backward.py's grad_var_name mapping, after fan-in accumulation) and
    probes it; `self.program` is the instrumented clone to run instead of
    the original. A parameter with NO grad-producing op (detached from the
    loss) is reported as status 'zero' without needing a probe. Each run
    feeds telemetry: gauges grad_l2{program,param} / grad_abs_mean and
    counter grad_audit_flags_total{program,param,status} for every non-ok
    status. Non-finite gradients are *reported*, not raised — combine with
    instrument()/check_nan_inf when divergence should abort the step."""

    def __init__(self, program: Program, parameters=None,
                 vanishing_threshold: Optional[float] = None,
                 exploding_threshold: Optional[float] = None):
        # thresholds default from the dynamics constants table so the
        # audit and the observatory can never disagree on "vanishing"
        from . import dynamics as dynamics_mod
        if vanishing_threshold is None:
            vanishing_threshold = \
                dynamics_mod.THRESHOLDS["grad_vanishing_abs_mean"]
        if exploding_threshold is None:
            exploding_threshold = \
                dynamics_mod.THRESHOLDS["grad_exploding_max_abs"]
        base = getattr(program, "_probe_parent", None) or program
        block = base.global_block()
        if parameters is None:
            params = [p.name for p in block.all_parameters()
                      if getattr(p, "trainable", True)]
        else:
            params = [p if isinstance(p, str) else p.name
                      for p in parameters]
        self.params = params
        self.vanishing_threshold = float(vanishing_threshold)
        self.exploding_threshold = float(exploding_threshold)
        self.missing: List[str] = []
        plans = []
        for pname in params:
            g = grad_var_name(pname)
            last = None
            for i, op in enumerate(block.ops):
                if g in op.output_arg_names:
                    last = i
            if last is None or not _probeable_var(block, g):
                self.missing.append(pname)
                continue
            plans.append(_Plan(last + 1, g, ProbeSite(
                last, block.ops[last].type, g, f"{g}@STATS@{last}",
                kind="grad", param=pname)))
        self.program = _apply_plans(base, plans) if plans else base.clone()
        self.program._grad_audit = self
        self._last: Dict[str, Dict[str, Any]] = {}

    def classify(self, st: TensorStats) -> str:
        from . import dynamics as dynamics_mod
        return dynamics_mod.classify_grad(
            st.nonfinite, st.l2, st.abs_mean,
            max(abs(st.min), abs(st.max)),
            vanishing_threshold=self.vanishing_threshold,
            exploding_threshold=self.exploding_threshold)

    def _observe(self, stats: Dict[ProbeSite, TensorStats], prog_label: str):
        for site, st in stats.items():
            if site.kind != "grad" or site.param is None:
                continue
            status = self.classify(st)
            self._last[site.param] = dict(st.to_dict(), status=status,
                                          grad_var=site.var)
            telemetry.gauge(
                "grad_l2", "per-parameter gradient L2 norm (GradientAudit)",
                labels=("program", "param")).labels(
                    program=prog_label, param=site.param).set(st.l2)
            telemetry.gauge(
                "grad_abs_mean",
                "per-parameter gradient mean |g| (GradientAudit)",
                labels=("program", "param")).labels(
                    program=prog_label, param=site.param).set(st.abs_mean)
            if status != "ok":
                telemetry.counter(
                    "grad_audit_flags_total",
                    "gradient health flags (zero/vanishing/exploding/"
                    "nonfinite) per parameter",
                    labels=("program", "param", "status")).labels(
                        program=prog_label, param=site.param,
                        status=status).inc()

    def report(self) -> Dict[str, Dict[str, Any]]:
        out = {}
        for p in self.params:
            if p in self._last:
                out[p] = dict(self._last[p])
            elif p in self.missing:
                out[p] = {"status": "zero",
                          "reason": "no op writes this parameter's grad "
                                    "var (detached from the loss)"}
            else:
                out[p] = {"status": "unknown",
                          "reason": "audit program not run yet"}
        return out


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    def __init__(self, capacity: int = 256, path: Optional[str] = None):
        self.capacity = capacity
        self.path = path
        self.enabled = False
        self.explicitly_disabled = False
        self.records: collections.deque = collections.deque(maxlen=capacity)
        self._signals_installed: List[Tuple[int, Any]] = []


_RECORDER = FlightRecorder()
_IN_CRASH = False


def enable_flight_recorder(path: Optional[str] = None, capacity: int = 256,
                           signals: bool = False) -> FlightRecorder:
    """Start recording step records into a bounded ring buffer; a JSON crash
    report lands at `path` on executor exception (and, with signals=True, on
    SIGTERM/SIGABRT — plus a non-fatal diagnostic dump on SIGUSR1). Also
    reachable without code changes via PADDLE_TPU_FLIGHT_RECORDER=<path>."""
    _RECORDER.capacity = int(capacity)
    _RECORDER.records = collections.deque(maxlen=_RECORDER.capacity)
    _RECORDER.path = path or _RECORDER.path or "paddle_tpu_crash.json"
    _RECORDER.enabled = True
    _RECORDER.explicitly_disabled = False
    if signals:
        _install_signal_handlers()
    return _RECORDER


def disable_flight_recorder():
    _RECORDER.enabled = False
    _RECORDER.explicitly_disabled = True
    _remove_signal_handlers()


def flight_enabled() -> bool:
    """Live check consulted by the executor each run; lazily honors the
    PADDLE_TPU_FLIGHT_RECORDER flag (so flags.set enables it at runtime)."""
    if not _RECORDER.enabled and not _RECORDER.explicitly_disabled:
        p = flags.get("flight_recorder")
        if p:
            enable_flight_recorder(p, signals=True)
    return _RECORDER.enabled


def record_step(program, prog_label: str, info: Dict[str, Any]):
    """Append one step record to the ring (executor hook)."""
    if not _RECORDER.enabled:
        return
    rec = {"ts": time.time(), "program": prog_label}
    rec.update(info)
    stats = getattr(program, "_last_probe_stats", None)
    if stats:
        rec["probes"] = len(stats)
        nonfinite = [dict(s.to_dict(), nan=st.nan_count, inf=st.inf_count)
                     for s, st in stats.items() if st.nonfinite]
        if nonfinite:
            rec["nonfinite_probes"] = nonfinite
    _RECORDER.records.append(rec)


def notify_crash(exe, program, exc) -> Optional[str]:
    """Executor crash hook: write the crash report (when the recorder is
    enabled) and return its path. EOFException is the reader drain-loop's
    normal end-of-pass signal, not a crash."""
    global _IN_CRASH
    if _IN_CRASH:
        return None
    if getattr(program, "_inspector_internal", False):
        return None
    try:
        from .layers.io import EOFException
        if isinstance(exc, EOFException):
            return None
    except Exception:
        pass
    try:
        # step-event record regardless of the flight recorder so
        # /healthz (obs_server) can report a last-error verdict even in
        # processes that never enabled crash dumps
        telemetry.log_event(
            "crash", error=f"{type(exc).__name__}: {exc}",
            program=telemetry.program_label(program))
    except Exception:
        pass
    if not flight_enabled():
        return None
    _IN_CRASH = True
    try:
        telemetry.counter(
            "inspector_crash_reports_total",
            "crash reports written by the flight recorder").inc()
        path = dump_crash_report(_RECORDER.path, error=exc, program=program,
                                 kind="exception")
        print(f"paddle_tpu inspector: crash report written to {path} "
              f"(read with `python -m paddle_tpu inspect {path}`)",
              file=sys.stderr)
        return path
    except Exception:
        return None
    finally:
        _IN_CRASH = False


def dump_crash_report(path: Optional[str] = None, *, error=None,
                      program=None, kind: str = "crash",
                      extra: Optional[Dict[str, Any]] = None) -> str:
    """Write the flight-recorder JSON crash report. Format (version 1):
    {format, version, kind, ts, host, error{type,message,...}, env (the
    PADDLE_TPU_*/JAX_*/XLA_* vars), flags (full registry dump), steps (the
    ring), events (telemetry ring incl. retrace causes), metrics (local
    snapshot), program (pprint_program text), probe_stats, grad_audit}.
    `extra` merges caller sections into the report before it is written —
    the sentinel's hang reports add {threads, spans, hang} this way."""
    report: Dict[str, Any] = {
        "format": "paddle_tpu-crash-report", "version": 1, "kind": kind,
        "ts": time.time(),
        "host": int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0),
        "error": None,
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith(("PADDLE_TPU_", "PADDLE_TRAINER", "JAX_",
                                 "XLA_"))},
        "flags": {n: v for n, (v, _h) in flags.dump().items()},
        "steps": list(_RECORDER.records),
        "events": telemetry.recent_events(200),
        "metrics": telemetry.registry().local_snapshot(),
        "program": None, "probe_stats": None, "grad_audit": None,
        "memory": None, "dynamics": None,
    }
    try:
        from . import memory as memory_mod
        report["memory"] = memory_mod.crash_section()
    except Exception:
        pass
    try:
        # last training-dynamics snapshot: per-series verdicts + final
        # sample, so a crash report names the layer that died first
        from . import dynamics as dynamics_mod
        report["dynamics"] = dynamics_mod.crash_section()
    except Exception:
        pass
    if error is not None:
        if hasattr(error, "to_dict"):
            # structured errors (NonFiniteError, OOMError) serialize their
            # own forensic fields
            report["error"] = error.to_dict()
        else:
            report["error"] = {"type": type(error).__name__,
                               "message": str(error)}
    if program is not None:
        from . import debugger
        lines: List[str] = []
        try:
            debugger.pprint_program(program, print_fn=lines.append)
        except Exception:
            lines.append("<program dump failed>")
        report["program"] = "\n".join(lines)
        report["program_label"] = telemetry.program_label(program)
        stats = getattr(program, "_last_probe_stats", None)
        if stats:
            report["probe_stats"] = [
                dict(s.to_dict(), stats=st.to_dict())
                for s, st in sorted(stats.items(),
                                    key=lambda it: it[0].op_index)]
        audit = getattr(program, "_grad_audit", None)
        if audit is not None:
            report["grad_audit"] = audit.report()
        try:
            # static analyzer findings: when a trace/run crashed, the
            # verifier's view of the same program is often the fastest
            # pointer to the root cause (and it never executes anything)
            from .analysis import analyze_program
            areport = analyze_program(program)
            report["analysis"] = {
                "counts": areport.counts(),
                "diagnostics": [d.to_dict()
                                for d in areport.diagnostics[:50]],
            }
        except Exception:
            pass
    if extra:
        report.update(extra)
    path = path or _RECORDER.path or "paddle_tpu_crash.json"
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(report, f, indent=2, default=str)
    return path


def read_crash_report(path: str) -> Dict[str, Any]:
    with open(path) as f:
        report = json.load(f)
    if report.get("format") != "paddle_tpu-crash-report":
        raise ValueError(f"{path} is not a paddle_tpu crash report")
    return report


def _signal_handler(signum, frame):
    try:
        name = signal_mod.Signals(signum).name
    except ValueError:
        name = str(signum)
    try:
        dump_crash_report(kind=f"signal:{name}")
    except Exception:
        pass
    if signum == getattr(signal_mod, "SIGUSR1", None):
        return  # diagnostic dump only; keep running
    _remove_signal_handlers()
    signal_mod.signal(signum, signal_mod.SIG_DFL)
    os.kill(os.getpid(), signum)


def _install_signal_handlers():
    if _RECORDER._signals_installed:
        return
    for signum in (signal_mod.SIGTERM, signal_mod.SIGABRT,
                   getattr(signal_mod, "SIGUSR1", None)):
        if signum is None:
            continue
        try:
            prev = signal_mod.signal(signum, _signal_handler)
        except (ValueError, OSError):
            continue  # not the main thread / unsupported platform
        _RECORDER._signals_installed.append((signum, prev))


def _remove_signal_handlers():
    for signum, prev in _RECORDER._signals_installed:
        try:
            signal_mod.signal(signum, prev)
        except (ValueError, OSError):
            pass
    _RECORDER._signals_installed = []


# ---------------------------------------------------------------------------
# Crash-report pretty printer (the `inspect` CLI)
# ---------------------------------------------------------------------------

def _fmt_hbm(n) -> str:
    from . import memory as memory_mod
    return memory_mod._fmt_bytes(n)


def _fmt_stats_dict(d: Dict[str, Any]) -> str:
    try:
        return (f"min={d['min']:.4g} max={d['max']:.4g} "
                f"mean={d['mean']:.4g} l2={d['l2']:.4g} "
                f"nan={d['nan_count']:.0f} inf={d['inf_count']:.0f}")
    except Exception:
        return str(d)


def format_crash_report(report: Dict[str, Any], *,
                        show_program: bool = False) -> str:
    lines: List[str] = []
    ts = report.get("ts")
    when = (time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))
            if ts else "?")
    lines.append(f"paddle_tpu crash report  kind={report.get('kind')}  "
                 f"host={report.get('host')}  {when}")
    if report.get("program_label"):
        lines.append(f"program: {report['program_label']}")
    err = report.get("error")
    if err:
        lines.append(f"error: {err.get('type')}: {err.get('message')}")
        if err.get("var_name"):
            lines.append(f"  variable: '{err['var_name']}'"
                         + (f" (dtype {err['dtype']})"
                            if err.get("dtype") else ""))
        attr = err.get("attribution")
        if attr:
            lines.append(
                f"  origin: op {attr.get('op_index')} "
                f"'{attr.get('op_type')}' -> '{attr.get('var')}' "
                f"[{attr.get('runs')} replay run(s)]"
                + (f", built at {attr['creation_site']}"
                   if attr.get("creation_site") else ""))
            for n, st in (attr.get("input_stats") or {}).items():
                lines.append(f"    input '{n}': {_fmt_stats_dict(st)}")
        if err.get("breakdown"):
            lines.append("  memory breakdown: " + ", ".join(
                f"{k}={_fmt_hbm(v)}"
                for k, v in sorted(err["breakdown"].items())))
        for b in (err.get("top_buffers") or [])[:5]:
            nm = f" '{b['name']}'" if b.get("name") else ""
            lines.append(f"  live buffer{nm}: {_fmt_hbm(b.get('nbytes'))} "
                         f"{b.get('dtype')}{b.get('shape')}")
    hang = report.get("hang") or {}
    if hang:
        lines.append(
            f"hang: program={hang.get('program')} "
            f"budget={hang.get('budget_s', 0.0):.3g}s "
            f"waited={hang.get('waited_s', 0.0):.3g}s "
            f"thread={hang.get('thread')}")
    threads = report.get("threads") or []
    if threads:
        stalled = sum(1 for t in threads if t.get("stalled"))
        lines.append(f"threads: {len(threads)} captured"
                     + (f", {stalled} stalled" if stalled else ""))
        for t in threads:
            mark = "  ** STALLED **" if t.get("stalled") else ""
            lines.append(f"  thread '{t.get('name')}' "
                         f"ident={t.get('ident')}"
                         f"{' daemon' if t.get('daemon') else ''}{mark}")
            # stacks are multi-line strings from traceback.format_stack
            tail = t.get("stack") or []
            for frame in (tail if t.get("stalled") else tail[-2:]):
                for ln in frame.splitlines():
                    lines.append("    " + ln)
    mem = report.get("memory") or {}
    if mem.get("tracker") or mem.get("programs"):
        tr = mem.get("tracker") or {}
        if tr:
            lines.append(f"memory: in_use={_fmt_hbm(tr.get('bytes_in_use'))} "
                         f"peak={_fmt_hbm(mem.get('peak_bytes'))} "
                         f"source={tr.get('source')}")
        for p in (mem.get("programs") or [])[-3:]:
            lines.append(
                f"  {p.get('program')}: "
                f"total={_fmt_hbm(p.get('total_bytes'))} "
                f"(args={_fmt_hbm(p.get('argument_bytes'))} "
                f"temp={_fmt_hbm(p.get('temp_bytes'))} "
                f"out={_fmt_hbm(p.get('output_bytes'))})")
    steps = report.get("steps") or []
    lines.append(f"steps recorded: {len(steps)}"
                 + (" (most recent last)" if steps else ""))
    for rec in steps[-10:]:
        extra = ""
        if rec.get("global_norm") is not None:
            extra += f" |g|={rec['global_norm']:.4g}"
        if rec.get("nonfinite_probes"):
            extra += f" NONFINITE x{len(rec['nonfinite_probes'])}"
        lines.append(
            f"  {rec.get('program')} mode={rec.get('mode')} "
            f"cache={rec.get('cache')} "
            f"t={rec.get('seconds', 0.0):.4f}s{extra}")
    probes = report.get("probe_stats") or []
    if probes:
        lines.append(f"probe stats (last step, {len(probes)} sites):")
        for p in probes:
            lines.append(f"  op {p.get('op_index')} '{p.get('op_type')}' "
                         f"{p.get('var')}: "
                         f"{_fmt_stats_dict(p.get('stats') or {})}")
    audit = report.get("grad_audit") or {}
    if audit:
        lines.append("gradient audit:")
        for param, info in sorted(audit.items()):
            detail = (f" l2={info['l2']:.4g}" if "l2" in info else
                      f" ({info.get('reason', '')})")
            lines.append(f"  {param}: {info.get('status')}{detail}")
    dyn = report.get("dynamics") or {}
    if dyn:
        verd = dyn.get("verdicts") or []
        lines.append(f"training dynamics: "
                     f"{dyn.get('samples_recorded', 0)} samples, "
                     f"{len(verd)} non-ok verdict(s)")
        for v in verd[:10]:
            since = (f" since step {v['since_step']}"
                     if v.get("since_step") is not None else "")
            lines.append(f"  {v.get('program')}/{v.get('series')} "
                         f"[{v.get('role')}]: {v.get('code')}{since}")
    analysis = report.get("analysis") or {}
    if analysis:
        c = analysis.get("counts") or {}
        lines.append(f"static analysis: {c.get('error', 0)} error(s), "
                     f"{c.get('warning', 0)} warning(s), "
                     f"{c.get('info', 0)} info")
        for d in (analysis.get("diagnostics") or []):
            if d.get("severity") == "info":
                continue
            where = (f" [op {d['op_index']} '{d.get('op_type')}']"
                     if d.get("op_index") is not None else
                     f" [var '{d['var']}']" if d.get("var") else "")
            site = f" ({d['site']})" if d.get("site") else ""
            lines.append(f"  {d.get('severity')}: {d.get('code')}"
                         f"{where}{site}: {d.get('message')}")
    events = report.get("events") or []
    if events:
        counts: Dict[str, int] = {}
        for e in events:
            counts[e.get("kind", "?")] = counts.get(e.get("kind", "?"), 0) + 1
        lines.append("telemetry events: "
                     + ", ".join(f"{k}={v}"
                                 for k, v in sorted(counts.items())))
    fl = report.get("flags") or {}
    interesting = {k: v for k, v in fl.items()
                   if k in ("eager", "check_nan_inf", "trap_fp", "vlog",
                            "nonfinite_attribution", "flight_recorder")
                   and v not in ("", 0, False)}
    if interesting:
        lines.append("flags: " + ", ".join(f"{k}={v}"
                                           for k, v in sorted(
                                               interesting.items())))
    if show_program and report.get("program"):
        lines.append("")
        lines.append(report["program"])
    return "\n".join(lines)
