"""Unified telemetry: process-wide metrics registry + structured step log.

The reference framework's observability is split across platform/profiler.cc
(host event table), device_tracer.cc (CUPTI kernels) and tools/timeline.py
(post-hoc trace merge). This module is the TPU-native consolidation: one
process-wide registry of counters / gauges / log-scale histograms (labeled,
Prometheus-exportable, fleet-reducible over hosts) plus a structured
step-event log — one JSONL record per Executor.run with the
compile-vs-execute split, donated-buffer stats and the shape/dtype
signature that caused any jit retrace. `profiler.py` (host wall times) and
`xplane.py` (device HLO attribution) keep their APIs but publish into this
registry, so a single `snapshot()` answers both "which op eats the step"
and "which step ate the minute".

Hot-path cost: one lock + dict update per metric op; event logging is a
dict build + deque append (and one JSON line when a sink is enabled).
Everything is import-light — jax is only touched for the cross-host
reduce and the compile-time listener, both lazily/guarded.
"""

from __future__ import annotations

import collections
import contextlib
import json
import math
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "counter", "gauge", "histogram", "registry", "MetricsRegistry",
    "snapshot", "prometheus_text", "log_event", "recent_events",
    "enable_step_log", "disable_step_log", "step_log_path", "read_step_log",
    "export_chrome_trace", "default_buckets", "reset", "program_label",
    "jax_compile_seconds", "signature_of", "read_gauge", "read_series",
    "read_histogram", "histogram_quantile",
]


# ---------------------------------------------------------------------------
# Metric primitives
# ---------------------------------------------------------------------------

def default_buckets() -> Tuple[float, ...]:
    """Fixed log-scale histogram buckets: powers of 4 from 1 microsecond to
    ~67 seconds. Fixed (not adaptive) so bucket counts from different hosts
    and different runs add cell-wise — the property the cross-host reduce
    and Prometheus rate() queries rely on."""
    return tuple(1e-6 * (4.0 ** i) for i in range(14))


def _label_key(labels: Dict[str, str]) -> str:
    """Canonical serialized label set — doubles as the cross-host merge key."""
    return ",".join(f"{k}={labels[k]}" for k in sorted(labels))


# one lock for all series mutations: += on an attribute is a
# read-modify-write and reader/feeder threads update concurrently with the
# training loop; contention is negligible at per-step granularity
_VALUES_LOCK = threading.Lock()


class _Child:
    """One (metric, label-values) time series."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0):
        with _VALUES_LOCK:
            self.value += amount

    def set(self, value: float):
        with _VALUES_LOCK:
            self.value = float(value)


class _HistChild:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float]):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)   # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float):
        value = float(value)
        with _VALUES_LOCK:
            self.sum += value
            self.count += 1
            for i, le in enumerate(self.buckets):
                if value <= le:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1


class _Family:
    """A named metric with a fixed label-name schema; `.labels(**kw)`
    resolves (and lazily creates) one child series per label-value tuple.
    Label-free families proxy inc/set/observe to their single () child."""

    kind = "counter"

    def __init__(self, reg: "MetricsRegistry", name: str, help: str,
                 labelnames: Sequence[str], buckets=None):
        self._reg = reg
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._buckets = tuple(buckets) if buckets else None
        self._children: Dict[Tuple[str, ...], Any] = {}

    def _make_child(self):
        if self.kind == "histogram":
            return _HistChild(self._buckets or default_buckets())
        return _Child()

    def labels(self, **kw):
        if set(kw) != set(self.labelnames):
            raise ValueError(
                f"metric '{self.name}' takes labels {self.labelnames}, "
                f"got {sorted(kw)}")
        key = tuple(str(kw[k]) for k in self.labelnames)
        with self._reg._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def _default_child(self):
        if self.labelnames:
            raise ValueError(
                f"metric '{self.name}' is labeled {self.labelnames}; "
                f"use .labels(...)")
        return self.labels()

    # label-free conveniences
    def inc(self, amount: float = 1.0):
        self._default_child().inc(amount)

    def set(self, value: float):
        self._default_child().set(value)

    def observe(self, value: float):
        self._default_child().observe(value)

    def series(self) -> Dict[str, Any]:
        """{serialized-labels: child} snapshot view."""
        with self._reg._lock:
            return {_label_key(dict(zip(self.labelnames, k))): c
                    for k, c in self._children.items()}


class _Counter(_Family):
    kind = "counter"


class _Gauge(_Family):
    kind = "gauge"


class _Histogram(_Family):
    kind = "histogram"


class MetricsRegistry:
    """Thread-safe name -> metric family registry. Re-registering the same
    name with the same kind returns the existing family (idempotent, so
    instrumented modules can declare metrics at call sites)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}
        self._generation = 0

    def _get_or_make(self, cls, name, help, labels, buckets=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != cls.kind:
                    raise ValueError(
                        f"metric '{name}' already registered as {fam.kind}")
                return fam
            fam = cls(self, name, help, labels, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> _Counter:
        return self._get_or_make(_Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> _Gauge:
        return self._get_or_make(_Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> _Histogram:
        return self._get_or_make(_Histogram, name, help, labels, buckets)

    def families(self) -> List[_Family]:
        with self._lock:
            return list(self._families.values())

    def clear(self):
        with self._lock:
            self._families.clear()
            self._generation += 1

    def generation(self) -> int:
        """Bumped by clear()/reset(); lets hot paths that cache a family or
        child handle (profiler.record_event) self-invalidate with one int
        compare instead of re-resolving through the registry lock.
        Deliberately lock-free: int loads are atomic under the GIL and a
        stale read only costs one redundant re-resolve."""
        return self._generation  # thread-lint: ok lockset-mixed-guard

    # --- snapshots ----------------------------------------------------------
    def local_snapshot(self) -> Dict[str, Any]:
        """JSON-serializable view of every series in this process."""
        snap = {"host": _host_index(), "counters": {}, "gauges": {},
                "histograms": {}}
        for fam in self.families():
            if fam.kind == "histogram":
                dst = snap["histograms"].setdefault(fam.name, {})
                for lk, ch in fam.series().items():
                    with _VALUES_LOCK:   # counts/sum/count read consistently
                        dst[lk] = {"buckets": list(ch.buckets),
                                   "counts": list(ch.counts),
                                   "sum": ch.sum, "count": ch.count}
            else:
                dst = snap[fam.kind + "s"].setdefault(fam.name, {})
                for lk, ch in fam.series().items():
                    dst[lk] = ch.value
        return snap


_REG = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REG


def counter(name: str, help: str = "", labels: Sequence[str] = ()):
    return _REG.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()):
    return _REG.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None):
    return _REG.histogram(name, help, labels, buckets)


def read_gauge(name: str, **labels) -> Optional[float]:
    """Last value of one gauge series, or None when the family or the exact
    label set does not exist yet. A read-only peek: unlike `.labels(...)` it
    never creates the series, so observers (the inspector flight recorder
    reading optimizer_global_norm) cannot pollute the registry with empty
    children."""
    with _REG._lock:
        fam = _REG._families.get(name)
        if fam is None or fam.kind != "gauge":
            return None
        if set(labels) != set(fam.labelnames):
            return None
        child = fam._children.get(
            tuple(str(labels[k]) for k in fam.labelnames))
        return None if child is None else child.value


def read_series(name: str) -> Dict[str, float]:
    """All series of one counter/gauge family as {label_key: value}
    (label_key is the registry's serialized 'k=v,k=v' form; the unlabeled
    series maps from ''). Same read-only contract as read_gauge: never
    creates the family or any child. Empty when the family is absent or a
    histogram. Used by the memory CLI/bench to fold per-device hbm_*
    gauges without knowing the device labels in advance."""
    with _REG._lock:
        fam = _REG._families.get(name)
        if fam is None or fam.kind == "histogram":
            return {}
        return {
            ",".join(f"{k}={v}" for k, v in zip(fam.labelnames, key)):
                child.value
            for key, child in fam._children.items()}


def read_histogram(name: str, **labels) -> Optional[Dict[str, float]]:
    """{'sum', 'count'} of one histogram series, or None when the family or
    the exact label set does not exist. Same read-only contract as
    read_gauge — never creates the family or a child. Used by fleet.py to
    price input stall (input_stall_seconds) and checkpoint badput without
    registering the histograms from an observer."""
    with _REG._lock:
        fam = _REG._families.get(name)
        if fam is None or fam.kind != "histogram":
            return None
        if set(labels) != set(fam.labelnames):
            return None
        child = fam._children.get(
            tuple(str(labels[k]) for k in fam.labelnames))
        if child is None:
            return None
        with _VALUES_LOCK:
            return {"sum": child.sum, "count": child.count}


def histogram_quantile(name: str, q: float, **labels) -> Optional[float]:
    """Quantile estimate of one histogram series from its cumulative bucket
    counts (Prometheus histogram_quantile semantics: find the bucket whose
    cumulative count crosses rank q*total, interpolate linearly inside it).
    Accuracy is bounded by the bucket geometry — with default_buckets()'s
    powers-of-4 ladder an estimate is within 4x of the true value, which is
    enough to rank p50 against p99 and track trends. None when the series
    does not exist or has no observations; same read-only contract as
    read_histogram. The serving harness reads request-latency p50/p99 here."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    with _REG._lock:
        fam = _REG._families.get(name)
        if fam is None or fam.kind != "histogram":
            return None
        if set(labels) != set(fam.labelnames):
            return None
        child = fam._children.get(
            tuple(str(labels[k]) for k in fam.labelnames))
        if child is None:
            return None
        with _VALUES_LOCK:
            counts = list(child.counts)
            edges = list(child.buckets)
            total = child.count
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts[:-1]):
        prev = cum
        cum += c
        if cum >= rank and c > 0:
            lo = edges[i - 1] if i > 0 else 0.0
            hi = edges[i]
            frac = min(max((rank - prev) / c, 0.0), 1.0)
            return lo + (hi - lo) * frac
    # rank fell in the +Inf tail: the true quantile is beyond the last
    # finite edge, so the returned value is a floor, not an estimate.
    # Signal that once per (name, scrape interval) via a counter so
    # dashboards can annotate the clamped p99 instead of trusting it.
    counter("telemetry_quantile_tail_clamped_total",
            "histogram_quantile ranks that fell in the +Inf bucket and "
            "were clamped to the last finite edge (the returned quantile "
            "is a floor)", labels=("name",)).labels(name=name).inc()
    return edges[-1]


def _host_index() -> int:
    # env-derived (reference PADDLE_TRAINER_ID): reading jax.process_index()
    # here would force backend init from a metrics call
    return int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)


# ---------------------------------------------------------------------------
# Cross-host reduce
# ---------------------------------------------------------------------------

def _merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Sum-merge per-host snapshots into fleet totals. Counters, histogram
    cells and gauges all add — a reduced gauge is the fleet total (e.g.
    per-host queue depths sum to fleet backlog); per-host values stay
    available in the unreduced snapshot."""
    out = {"hosts": len(snaps), "counters": {}, "gauges": {},
           "histograms": {}}
    for snap in snaps:
        for kind in ("counters", "gauges"):
            for name, series in snap.get(kind, {}).items():
                dst = out[kind].setdefault(name, {})
                for lk, v in series.items():
                    dst[lk] = dst.get(lk, 0.0) + v
        for name, series in snap.get("histograms", {}).items():
            dst = out["histograms"].setdefault(name, {})
            for lk, h in series.items():
                acc = dst.get(lk)
                if acc is None or list(acc["buckets"]) != list(h["buckets"]):
                    if acc is None:
                        dst[lk] = {"buckets": list(h["buckets"]),
                                   "counts": list(h["counts"]),
                                   "sum": h["sum"], "count": h["count"]}
                    else:   # bucket-schema skew: keep first host's layout,
                        acc["sum"] += h["sum"]         # fold scalars only
                        acc["count"] += h["count"]
                    continue
                acc["counts"] = [a + b for a, b in
                                 zip(acc["counts"], h["counts"])]
                acc["sum"] += h["sum"]
                acc["count"] += h["count"]
    return out


def snapshot(reduce: bool = False) -> Dict[str, Any]:
    """Registry snapshot. reduce=True returns FLEET-WIDE totals: every
    host's snapshot rides an allgather (parallel/_collectives.py) and the
    series sum-merge by (metric, labels) — the multi-controller equivalent
    of scraping every pserver and adding (single-process: identical to the
    local snapshot)."""
    local = _REG.local_snapshot()
    if not reduce:
        return local
    from .parallel import multihost
    payloads = multihost.allgather_bytes(
        json.dumps(local, sort_keys=True).encode("utf-8"))
    return _merge_snapshots([json.loads(p.decode("utf-8"))
                             for p in payloads])


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _prom_labels(label_key: str, extra: str = "") -> str:
    if not label_key and not extra:
        return ""
    parts = []
    if label_key:
        for pair in label_key.split(","):
            k, _, v = pair.partition("=")
            v = v.replace("\\", "\\\\").replace('"', '\\"')
            parts.append(f'{k}="{v}"')
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}"


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def prometheus_text(snap: Optional[Dict[str, Any]] = None) -> str:
    """Render a snapshot (default: local) in the Prometheus text exposition
    format — counters/gauges as single samples, histograms as cumulative
    `_bucket{le=...}` + `_sum` + `_count` (the scrape surface a serving
    fleet sidecar exposes)."""
    snap = snap if snap is not None else _REG.local_snapshot()
    helps = {f.name: (f.help, f.kind) for f in _REG.families()}
    lines: List[str] = []
    for kind_key, prom_kind in (("counters", "counter"), ("gauges", "gauge")):
        for name in sorted(snap.get(kind_key, {})):
            help_, _ = helps.get(name, ("", prom_kind))
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {prom_kind}")
            for lk in sorted(snap[kind_key][name]):
                lines.append(f"{name}{_prom_labels(lk)} "
                             f"{_fmt(snap[kind_key][name][lk])}")
    for name in sorted(snap.get("histograms", {})):
        help_, _ = helps.get(name, ("", "histogram"))
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} histogram")
        for lk in sorted(snap["histograms"][name]):
            h = snap["histograms"][name][lk]
            cum = 0
            for le, c in zip(list(h["buckets"]) + [math.inf], h["counts"]):
                cum += c
                le_label = 'le="%s"' % _fmt(le)
                lines.append(
                    f"{name}_bucket{_prom_labels(lk, le_label)} {cum}")
            lines.append(f"{name}_sum{_prom_labels(lk)} {_fmt(h['sum'])}")
            lines.append(f"{name}_count{_prom_labels(lk)} {h['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# Structured step-event log (JSONL)
# ---------------------------------------------------------------------------

_EVENTS_MAX = 4096
_events: "collections.deque" = collections.deque(maxlen=_EVENTS_MAX)
_events_lock = threading.Lock()
_log_path: Optional[str] = None
_log_file = None


def enable_step_log(path: str):
    """Mirror every event to `path` as one JSON line per event (in addition
    to the in-memory ring buffer). Also settable via PADDLE_TPU_STEP_LOG."""
    global _log_path, _log_file
    # open() hits the filesystem — do it before taking the lock so a
    # slow/hung open can't stall every concurrent log_event(); only the
    # reference swap happens under _events_lock
    f = open(path, "a", buffering=1)   # line-buffered
    with _events_lock:
        old, _log_file = _log_file, f
        _log_path = path
    if old is not None:
        old.close()


def disable_step_log():
    global _log_path, _log_file
    with _events_lock:
        old, _log_file = _log_file, None
        _log_path = None
    if old is not None:
        old.close()


def step_log_path() -> Optional[str]:
    with _events_lock:
        return _log_path


def log_event(kind: str, **fields) -> Dict[str, Any]:
    """Record a structured event: wall timestamp + monotonic timestamp
    (perf_counter, merge key for the chrome-trace export) + host + kind +
    caller fields. Returns the record."""
    rec = {"ts": time.time(), "mono": time.perf_counter(),
           "host": _host_index(), "kind": kind}
    rec.update(fields)
    with _events_lock:
        _events.append(rec)
        if _log_file is not None:
            try:
                _log_file.write(json.dumps(rec, default=str) + "\n")
            except (OSError, ValueError):
                pass    # a torn sink must never kill the training step
    return rec


def recent_events(n: Optional[int] = None,
                  kind: Optional[str] = None) -> List[Dict[str, Any]]:
    with _events_lock:
        evs = list(_events)
    if kind is not None:
        evs = [e for e in evs if e.get("kind") == kind]
    return evs[-n:] if n else evs


def read_step_log(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL step log; tolerates a torn final line (crash mid-write)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


if os.environ.get("PADDLE_TPU_STEP_LOG"):
    enable_step_log(os.environ["PADDLE_TPU_STEP_LOG"])


# ---------------------------------------------------------------------------
# Merged chrome-trace export
# ---------------------------------------------------------------------------

def export_chrome_trace(path: str, events: Optional[Iterable[Dict]] = None):
    """One Perfetto-loadable timeline with BOTH telemetry step events (run /
    compile / cache_miss rows, tid 1) and the profiler's host events (tid 0)
    — the merged view the reference's tools/timeline.py produced from
    separate host+device dumps. Both sources share the perf_counter
    timebase ('mono' here, profiler._epoch there)."""
    from . import profiler as profiler_mod
    epoch = profiler_mod._epoch
    trace = [{"name": name, "ph": "X", "pid": 0, "tid": 0,
              "ts": start * 1e6, "dur": dur * 1e6, "cat": "host"}
             for name, start, dur in profiler_mod._timeline]
    for e in (events if events is not None else recent_events()):
        dur = float(e.get("seconds", 0.0) or 0.0)
        start = float(e.get("mono", 0.0)) - epoch - dur
        args = {k: v for k, v in e.items()
                if k not in ("mono", "kind") and _json_ok(v)}
        trace.append({"name": e.get("kind", "event"), "ph": "X",
                      "pid": 0, "tid": 1, "ts": start * 1e6,
                      "dur": dur * 1e6, "cat": "step", "args": args})
    with open(path, "w") as f:
        json.dump({"traceEvents": trace, "displayTimeUnit": "ms"}, f)
    return path


def _json_ok(v) -> bool:
    return isinstance(v, (str, int, float, bool, list, tuple, type(None)))


# ---------------------------------------------------------------------------
# Executor-facing helpers
# ---------------------------------------------------------------------------

_prog_labels: Dict[int, str] = {}
_prog_seq = [0]
_prog_lock = threading.Lock()


def program_label(program) -> str:
    """Stable short label for a Program within this process ("p0", "p1"…)
    — id() is unreadable and Programs carry no user-facing name."""
    lbl = getattr(program, "_telemetry_label", None)
    if lbl is None:
        # the seq bump is a read-modify-write; two threads labelling
        # concurrently must not mint the same "pN"
        with _prog_lock:
            lbl = getattr(program, "_telemetry_label", None)
            if lbl is None:
                lbl = f"p{_prog_seq[0]}"
                _prog_seq[0] += 1
                try:
                    program._telemetry_label = lbl
                except AttributeError:
                    pass
    return lbl


def signature_of(feed_vals: Dict[str, Any]) -> Tuple[Tuple[str, str, str], ...]:
    """(name, shape, dtype) triples for a feed dict — the retrace identity:
    jax.jit keys its trace cache on exactly these avals."""
    sig = []
    for name in sorted(feed_vals):
        v = feed_vals[name]
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        sig.append((name, str(tuple(shape) if shape is not None else ()),
                    str(dtype)))
    return tuple(sig)


# Accumulated backend-compile seconds, fed by jax.monitoring: XLA fires
# '/jax/core/compile/backend_compile_duration' for every real compilation
# (including jit retraces the executor-level cache can't see). Reading the
# accumulator before/after a run call splits that run's wall time into
# compile vs execute without AOT-lowering anything.
_compile_secs = [0.0]
_compile_listener_installed = [False]


def _install_compile_listener():
    if _compile_listener_installed[0]:
        return
    _compile_listener_installed[0] = True
    try:
        import jax.monitoring

        def _on_duration(name, secs, **kw):
            if name.endswith("backend_compile_duration"):
                _compile_secs[0] += float(secs)
                counter("jax_backend_compile_seconds_total",
                        "XLA backend compile wall seconds").inc(float(secs))
                counter("jax_backend_compiles_total",
                        "XLA backend compilations").inc()

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:   # jax absent/too old: compile split degrades to 0
        pass


def jax_compile_seconds() -> float:
    """Monotone accumulator of XLA backend-compile seconds in this process."""
    _install_compile_listener()
    return _compile_secs[0]


_install_compile_listener()


def reset():
    """Clear every metric series and the in-memory event buffer (tests).
    The JSONL sink, program labels and the compile accumulator survive —
    they are process-lifetime state."""
    _REG.clear()
    with _events_lock:
        _events.clear()


# --- declared metric catalog -------------------------------------------------
# The single source of truth for every metric family this codebase may
# create: name -> (kind, label set, help). `tools/check_registry.py
# check_metric_names` lints both directions against it — every
# counter()/gauge()/histogram() call site in paddle_tpu/ must declare a
# cataloged name with the cataloged label set, and every catalog entry
# must have at least one emitter — so label-set drift between emitters
# and readers (read_gauge/fleet.py/obs dashboards) is caught at lint
# time, not on a dashboard. Entries marked dynamic=True are created with
# a computed name (a loop or a program-attached mark) that the AST
# scanner cannot see; the lint exempts them from the needs-an-emitter
# direction but still checks any readers.

def _m(kind, labels=(), help="", dynamic=False):
    return {"kind": kind, "labels": tuple(labels), "help": help,
            "dynamic": dynamic}


METRIC_CATALOG = {
    # executor
    "executor_runs_total": _m("counter", ("program", "place", "mode"),
                              "Executor.run calls"),
    "executor_steps_total": _m("counter", ("program", "place"),
                               "training/eval steps executed"),
    "executor_run_seconds": _m("histogram", ("program", "mode"),
                               "Executor.run wall seconds"),
    "executor_last_step_seconds": _m("gauge", (),
                                     "wall seconds of the latest step"),
    "executor_compiles_total": _m("counter", ("program", "place"),
                                  "block traces/compiles"),
    "executor_compile_seconds_total": _m(
        "counter", ("program", "place"),
        "XLA compile wall seconds inside Executor.run"),
    "executor_cache_hits_total": _m(
        "counter", ("program", "place"),
        "runs served by an already-traced signature"),
    "executor_cache_misses_total": _m(
        "counter", ("program", "place"), "signature-cache misses"),
    "executor_window_fallback_total": _m(
        "counter", ("program", "reason"),
        "run_steps windows that fell back to per-step execution"),
    "optimizer_steps_total": _m("counter", ("program",),
                                "runs of optimizer-carrying programs"),
    "optimizer_minimize_total": _m("counter", ("optimizer",),
                                   "Optimizer.minimize calls"),
    "optimizer_global_norm": _m(
        "gauge", ("program",),
        "pre-clip gradient global norm (telemetry side-fetch)",
        dynamic=True),
    "jax_backend_compiles_total": _m("counter", (),
                                     "XLA backend compiles observed"),
    "jax_backend_compile_seconds_total": _m(
        "counter", (), "XLA backend compile wall seconds"),
    "donation_fallback_total": _m("counter", ("program",),
                                  "buffer-donation fallbacks"),
    "oom_errors_total": _m("counter", ("program",),
                           "device OOMs classified by the executor"),
    "nonfinite_detections_total": _m(
        "counter", ("program", "source"),
        "non-finite values caught by checks/probes"),
    "feed_conversion_seconds": _m("histogram", (),
                                  "host feed conversion wall seconds"),
    "feed_conversion_seconds_total": _m(
        "counter", (), "cumulative host feed conversion seconds"),
    # fusion / lowering / kernels
    "fusion_fallback_total": _m("counter", ("program", "reason"),
                                "fusion pattern bail-outs"),
    "pallas_kernel_total": _m("counter", ("op",),
                              "pallas kernel launches"),
    "pallas_fallback_total": _m("counter", ("op", "reason"),
                                "pallas kernels that fell back to XLA"),
    "quant_kernel_total": _m("counter", ("op",),
                             "ops routed through int8/fp8 quantization"),
    "quant_fallback_total": _m("counter", ("op", "reason"),
                               "quantizable ops kept at full precision"),
    "pallas_kernel_coverage": _m("gauge", (),
                                 "fraction of eligible ops on pallas"),
    "kernel_efficiency": _m("gauge", ("op", "shape"),
                            "measured/roofline kernel efficiency"),
    "device_op_seconds_total": _m("counter", ("op",),
                                  "per-op device seconds (profiled)"),
    # sparse / embedding
    "sparse_apply_rows_total": _m("counter", ("op",),
                                  "rows touched by sparse applies"),
    "sparse_densify_fallback_total": _m(
        "counter", ("op", "reason"), "sparse paths densified"),
    "emb_cache_hits_total": _m("counter", ("table",),
                               "embedding hot-row cache hits"),
    "emb_cache_misses_total": _m("counter", ("table",),
                                 "embedding hot-row cache misses"),
    "emb_cache_hit_rate": _m("gauge", ("table",),
                             "embedding cache rolling hit rate"),
    "emb_cache_evictions_total": _m("counter", ("policy",),
                                    "embedding cache evictions"),
    "emb_cache_flush_bytes_total": _m(
        "counter", (), "dirty embedding bytes flushed to host"),
    "emb_cache_prefetch_total": _m("counter", (),
                                   "embedding prefetch batches staged"),
    "emb_cache_prefetch_overlap_fraction": _m(
        "gauge", (), "prefetch time hidden under compute"),
    # memory
    "hbm_bytes_in_use": _m("gauge", ("device",),
                           "live HBM bytes (tracker)"),
    "hbm_peak_bytes": _m("gauge", ("device",), "peak HBM bytes"),
    "hbm_limit_bytes": _m("gauge", ("device",), "HBM capacity"),
    "hbm_class_bytes": _m("gauge", ("device", "kind"),
                          "HBM bytes by allocation class"),
    # input pipeline
    "input_batches_total": _m("counter", (), "reader batches produced"),
    "input_windows_total": _m("counter", (), "reader windows produced"),
    "input_window_dropped_batches_total": _m(
        "counter", (), "tail batches dropped at window close"),
    "input_stall_seconds": _m("histogram", (),
                              "executor wait on the input pipeline"),
    # checkpoint io
    "checkpoint_bytes": _m("gauge", ("op",),
                           "payload bytes of the last save/load"),
    "checkpoint_saves_total": _m("counter", (),
                                 "checkpoints written by this process"),
    "checkpoint_last_step": _m("gauge", (),
                               "step of the newest checkpoint"),
    "checkpoint_save_seconds": _m("histogram", (),
                                  "wall seconds per checkpoint save",
                                  dynamic=True),
    "checkpoint_load_seconds": _m("histogram", (),
                                  "wall seconds per checkpoint load",
                                  dynamic=True),
    # multihost / fleet
    "multihost_initialize_total": _m("counter", (),
                                     "distributed init calls"),
    "multihost_processes": _m("gauge", (), "process count at init"),
    "fleet_step_skew": _m("gauge", (), "max-min step skew across hosts"),
    "fleet_straggler_host": _m("gauge", (),
                               "host index of the slowest step"),
    "goodput_fraction": _m("gauge", (), "goodput fraction of wall time"),
    "goodput_seconds": _m("gauge", ("bucket",),
                          "wall seconds by goodput bucket"),
    "collective_time_seconds": _m("gauge", (),
                                  "total collective device seconds"),
    "collective_exposed_seconds": _m(
        "gauge", (), "collective seconds not hidden by compute"),
    # planner / parallel
    "planner_fallback_total": _m("counter", ("program", "reason"),
                                 "sharding planner bail-outs"),
    "overlap_buckets_total": _m("counter", ("program",),
                                "gradient overlap buckets built"),
    "overlap_fallback_total": _m("counter", ("program", "reason"),
                                 "overlap scheduling bail-outs"),
    # grad audit
    "grad_l2": _m("gauge", ("program", "param"), "per-param grad L2"),
    "grad_abs_mean": _m("gauge", ("program", "param"),
                        "per-param grad |mean|"),
    "grad_audit_flags_total": _m("counter",
                                 ("program", "param", "status"),
                                 "grad audit anomaly flags"),
    # profiler / roofline
    "profiler_sessions_total": _m("counter", ("traced",),
                                  "profiler sessions"),
    "profiler_event_seconds": _m("histogram", ("event",),
                                 "profiler event wall seconds"),
    "mfu_nominal": _m("gauge", (), "MFU vs nominal peak", dynamic=True),
    "mfu_vs_sustained": _m("gauge", (), "MFU vs sustained peak",
                           dynamic=True),
    "device_duty_cycle": _m("gauge", (), "device busy fraction",
                            dynamic=True),
    # inspector
    "inspector_crash_reports_total": _m(
        "counter", (), "crash reports written"),
    # serving
    "serving_request_seconds": _m("histogram", ("program", "phase"),
                                  "per-request latency by phase"),
    "serving_batches_total": _m("counter", ("program", "close"),
                                "batches closed, by close cause"),
    "serving_shed_total": _m("counter", ("program", "reason"),
                             "requests shed by overload control"),
    "serving_queue_depth": _m("gauge", ("program",),
                              "requests waiting in the batcher"),
    "serving_bucket_runs_total": _m("counter", ("program", "bucket"),
                                    "batches executed per bucket"),
    "serving_cache_hit_total": _m("counter", ("program", "bucket"),
                                  "AOT executable cache hits"),
    "serving_cache_miss_total": _m("counter", ("program", "bucket"),
                                   "AOT executable cache misses"),
    "serving_cache_evictions_total": _m(
        "counter", ("program",), "bucket executables LRU-evicted"),
    "serving_compile_seconds": _m("histogram", ("program", "bucket"),
                                  "AOT lower+compile seconds"),
    "serving_fallback_total": _m("counter", ("program", "reason"),
                                 "requests on the non-AOT path"),
    # observability plane (this PR)
    "slo_burn_rate": _m("gauge", ("model", "window"),
                        "error-budget burn rate by window"),
    "telemetry_quantile_tail_clamped_total": _m(
        "counter", ("name",),
        "quantiles clamped to the last finite bucket edge"),
    "trace_spans_total": _m("counter", ("name",),
                            "finished (sampled) trace spans"),
    "trace_spans_dropped_total": _m(
        "counter", (), "spans evicted from the trace ring buffer"),
    "obs_requests_total": _m("counter", ("endpoint",),
                             "observability endpoint scrapes"),
    # run sentinel
    "sentinel_alerts_total": _m(
        "counter", ("rule", "severity"),
        "deduplicated sentinel alerts, by rule and severity"),
    "sentinel_hangs_total": _m("counter", (),
                               "hang-watchdog deadline expiries"),
    "train_loss": _m("gauge", ("program",),
                     "training loss observed by the run sentinel"),
    # training-dynamics observatory (dynamics.py)
    "dynamics_update_ratio": _m(
        "gauge", ("program", "series"),
        "per-series |dW|/(|W|+eps) from the fused on-device reduction"),
    "dynamics_grad_rms": _m("gauge", ("program", "series"),
                            "per-series gradient RMS"),
    "dynamics_weight_rms": _m("gauge", ("program", "series"),
                              "per-series parameter RMS"),
    "dynamics_dead_layers": _m(
        "gauge", ("program",), "series currently classified dead-layer"),
    "dynamics_frozen_params": _m(
        "gauge", ("program",), "series currently classified frozen-param"),
    "dynamics_unhealthy_series": _m(
        "gauge", ("program",), "series with any non-ok dynamics verdict"),
    "dynamics_samples_total": _m(
        "counter", ("program",), "dynamics samples recorded"),
}
