"""Parameter initializers emitting init ops onto the startup program
(reference: python/paddle/fluid/initializer.py:27-338)."""

from __future__ import annotations

import math

import numpy as np

__all__ = [
    "Constant", "Uniform", "Normal", "Xavier", "MSRA",
    "ConstantInitializer", "UniformInitializer", "NormalInitializer",
    "XavierInitializer", "MSRAInitializer", "force_init_on_cpu",
    "init_on_cpu",
]

import contextlib

_force_init_on_cpu = False


def force_init_on_cpu() -> bool:
    return _force_init_on_cpu


@contextlib.contextmanager
def init_on_cpu():
    """Kept for API parity: on TPU the executor places init where the program
    runs, so this is a no-op marker (reference initializer.py init_on_cpu)."""
    global _force_init_on_cpu
    old, _force_init_on_cpu = _force_init_on_cpu, True
    try:
        yield
    finally:
        _force_init_on_cpu = old


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "value": float(self.value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type="uniform_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "min": self.low, "max": self.high, "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self.loc, "std": self.scale, "seed": self.seed})


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class XavierInitializer(Initializer):
    """Glorot init (reference initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = (
            uniform, fan_in, fan_out, seed)

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / (fi + fo))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He init (reference initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / fi)
        return NormalInitializer(0.0, std, self.seed)(var, block)


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
