"""Profiler (reference: python/paddle/fluid/profiler.py:33 cuda_profiler,
:76 profiler; platform/profiler.cc, device_tracer.cc).

On TPU the device tracer is jax.profiler (XLA/TensorBoard trace). The host
event profiler records per-run wall times of the compiled block, mirroring
the reference's RecordEvent aggregation table."""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, List, Optional

import jax

__all__ = ["cuda_profiler", "profiler", "start_profiler", "stop_profiler",
           "reset_profiler", "export_chrome_trace"]

_events: Dict[str, List[float]] = defaultdict(list)
# (name, start_ts, duration) triples for the chrome-trace export
# (reference tools/timeline.py:31 merges host+device events the same way)
_timeline: List = []
_active = False
_epoch = time.perf_counter()


# Cached telemetry handles for the host hot path: [module, family,
# registry-generation, {event_name: child}]. telemetry.reset() clears the
# registry, which would leave a bare cached child orphaned (observing into
# a family no exporter sees) — the generation int-compare catches that and
# re-resolves once instead of on every event.
_event_hist = [None, None, -1, {}]


def _event_child(name: str):
    tel = _event_hist[0]
    if tel is None:
        from . import telemetry as tel
        _event_hist[0] = tel
    gen = tel.registry().generation()
    if _event_hist[1] is None or _event_hist[2] != gen:
        _event_hist[1] = tel.histogram(
            "profiler_event_seconds", "host profiler event durations",
            labels=("event",))
        _event_hist[2] = gen
        _event_hist[3] = {}
    children = _event_hist[3]
    child = children.get(name)
    if child is None:
        child = children[name] = _event_hist[1].labels(event=name)
    return child


def record_event(name: str, seconds: float, start: Optional[float] = None):
    if _active:
        _events[name].append(seconds)
        if start is not None:
            _timeline.append((name, start - _epoch, seconds))
        # publish into the shared registry too, so one telemetry snapshot
        # answers both "which op eats the step" and "which step ate the
        # minute" (ISSUE tentpole: profiler keeps its API, feeds telemetry)
        _event_child(name).observe(seconds)


@contextlib.contextmanager
def record(name: str):
    if not _active:      # keep the interpreter hot path overhead-free
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_event(name, time.perf_counter() - t0, start=t0)


def is_active() -> bool:
    return _active


def reset_profiler():
    _events.clear()
    _timeline.clear()


def export_chrome_trace(path: str):
    """Write recorded host events as a Chrome tracing JSON (chrome://tracing
    / Perfetto), the host half of the reference's timeline.py:31 output.
    Device-side kernels live in the TensorBoard trace captured by
    profiler(trace_dir=...) — point Perfetto at both for the merged view."""
    import json
    events = [{"name": name, "ph": "X", "pid": 0, "tid": 0,
               "ts": start * 1e6, "dur": dur * 1e6,
               "cat": "host"}
              for name, start, dur in _timeline]
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return path


def start_profiler(state="All", trace_dir: Optional[str] = None):
    global _active
    _active = True
    from . import telemetry
    telemetry.counter(
        "profiler_sessions_total", "profiling sessions started",
        labels=("traced",)).labels(
            traced=str(bool(trace_dir)).lower()).inc()
    _hlo_suppliers.clear()
    _steps_at_start[0] = sum(
        telemetry.read_series("executor_steps_total").values())
    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    _start_trace_dir[0] = trace_dir


_start_trace_dir = [None]
_steps_at_start = [0.0]
# id(compiled_fn) -> (supplier, cost_fn): supplier is a zero-arg callable
# returning the AOT-compiled block (or raw optimized-HLO text), cost_fn an
# optional zero-arg callable returning the analytic per-op cost table
# (roofline.program_cost). Registered by the executor while a traced
# profile is active, consumed by the device report at stop.
_hlo_suppliers: Dict[int, tuple] = {}


def wants_device_table() -> bool:
    """True while a traced (trace_dir) profiling session is active — the
    executor then registers its compiled blocks for HLO attribution."""
    return _active and _start_trace_dir[0] is not None


_MAX_HLO_SUPPLIERS = 4  # each supply() is a full AOT recompile at stop


def has_hlo_supplier(key: int) -> bool:
    # saturated registry counts as "has": with program caching off every
    # step builds a fresh compiled fn, and an unbounded registry would
    # both pin them all alive and recompile each one at stop_profiler
    return key in _hlo_suppliers or \
        len(_hlo_suppliers) >= _MAX_HLO_SUPPLIERS


def register_hlo_supplier(key: int, supplier, cost_fn=None):
    if len(_hlo_suppliers) < _MAX_HLO_SUPPLIERS:
        _hlo_suppliers.setdefault(key, (supplier, cost_fn))


def consume_suppliers() -> list:
    """Drain the registered (supplier, cost_fn) pairs — the device report
    is built at most once per traced session."""
    pairs = list(_hlo_suppliers.values())
    _hlo_suppliers.clear()
    return pairs


def _traced_steps() -> Optional[int]:
    """Executor steps run during the current traced session (delta of the
    executor_steps_total counter since start_profiler); None when no step
    ran — the report then skips flops-rate columns rather than divide by
    a guessed step count."""
    from . import telemetry
    delta = sum(telemetry.read_series(
        "executor_steps_total").values()) - _steps_at_start[0]
    return int(delta) if delta > 0 else None


def _end_trace():
    trace_dir = _start_trace_dir[0]
    if trace_dir:
        jax.profiler.stop_trace()
        _start_trace_dir[0] = None
    return trace_dir


def stop_profiler(sorted_key=None, profile_path=None):
    global _active
    _active = False
    trace_dir = _end_trace()
    _print_table(sorted_key)
    if trace_dir:
        _print_device_table(trace_dir, sorted_key)
    try:
        if jax.process_count() > 1:
            # multi-process runs get the fleet line: step skew, slowest
            # host and goodput — the cross-host view no single-host table
            # above can show
            from . import fleet
            print(fleet.format_fleet(fleet.fleet_snapshot()))
            gp = fleet.goodput_report()
            if gp:
                print("[fleet] goodput {:.1%} over {:.2f}s wall".format(
                    gp["goodput_fraction"], gp["span_s"]))
    except Exception:  # noqa: BLE001 - summary line is best-effort
        pass


def finish_trace_report(steps: Optional[int] = None, probe: bool = True):
    """Silent counterpart of stop_profiler for programmatic capture
    (bench.py, roofline.capture): stop the traced session and return the
    roofline report dict without printing anything — bench stdout must
    stay one-JSON-line-per-config. Returns None when no trace was active."""
    global _active
    _active = False
    trace_dir = _end_trace()
    if not trace_dir:
        return None
    from . import roofline
    return roofline.collect_report(
        trace_dir, consume_suppliers(),
        steps=steps if steps is not None else _traced_steps(), probe=probe)


def _print_device_table(trace_dir, sorted_key=None):
    """Per-IR-op device-time attribution for the whole-block jit (VERDICT
    r4 #8; reference ParseEvents, platform/profiler.h:137-166): xplane
    per-instruction timings joined with each compiled module's
    metadata op_name (which carries the executor's pd.<op_type> named
    scope), enriched by roofline.py with analytic FLOPs/bytes, achieved
    TF/s and a compute/memory/unattributed verdict. Unmapped device time
    is pooled under "(unattributed)" so fractions sum to the true device
    total. Re-lowers each registered block from avals — served from jax's
    compilation cache when warm."""
    from . import roofline

    pairs = consume_suppliers()
    try:
        report = roofline.collect_report(trace_dir, pairs,
                                         steps=_traced_steps())
    except Exception as e:  # noqa: BLE001 - truncated/foreign .xplane.pb
        print(f"[device] (trace unreadable: {type(e).__name__}: {e})")
        return
    if report is None or not report.get("rows"):
        return
    if not report.get("mapped") and not pairs:
        # nothing was registered (eager run, foreign trace): keep the old
        # silent behaviour instead of printing an all-unattributed table
        return
    for line in roofline.format_report(report):
        print(line)


def _print_table(sorted_key=None):
    if not _events:
        return
    rows = []
    for name, times in _events.items():
        total = sum(times)
        rows.append((name, len(times), total, total / len(times),
                     min(times), max(times)))
    if sorted_key in ("total", None):
        rows.sort(key=lambda r: -r[2])
    elif sorted_key == "calls":
        rows.sort(key=lambda r: -r[1])
    elif sorted_key == "ave":
        rows.sort(key=lambda r: -r[3])
    print(f"{'Event':40s} {'Calls':>8s} {'Total(s)':>10s} {'Ave(s)':>10s} "
          f"{'Min(s)':>10s} {'Max(s)':>10s}")
    for name, calls, total, ave, mn, mx in rows:
        print(f"{name:40s} {calls:8d} {total:10.4f} {ave:10.4f} "
              f"{mn:10.4f} {mx:10.4f}")


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Source-compat alias: wraps an XLA trace around the block
    (reference profiler.py:33 drove nvprof)."""
    with profiler("All", trace_dir=output_file):
        yield


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             trace_dir: Optional[str] = None):
    start_profiler(state, trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
