"""Profiler (reference: python/paddle/fluid/profiler.py:33 cuda_profiler,
:76 profiler; platform/profiler.cc, device_tracer.cc).

On TPU the device tracer is jax.profiler (XLA/TensorBoard trace). The host
event profiler records per-run wall times of the compiled block, mirroring
the reference's RecordEvent aggregation table."""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, List, Optional

import jax

__all__ = ["cuda_profiler", "profiler", "start_profiler", "stop_profiler",
           "reset_profiler", "export_chrome_trace"]

_events: Dict[str, List[float]] = defaultdict(list)
# (name, start_ts, duration) triples for the chrome-trace export
# (reference tools/timeline.py:31 merges host+device events the same way)
_timeline: List = []
_active = False
_epoch = time.perf_counter()


def record_event(name: str, seconds: float, start: Optional[float] = None):
    if _active:
        _events[name].append(seconds)
        if start is not None:
            _timeline.append((name, start - _epoch, seconds))
        # publish into the shared registry too, so one telemetry snapshot
        # answers both "which op eats the step" and "which step ate the
        # minute" (ISSUE tentpole: profiler keeps its API, feeds telemetry)
        from . import telemetry
        telemetry.histogram(
            "profiler_event_seconds", "host profiler event durations",
            labels=("event",)).labels(event=name).observe(seconds)


@contextlib.contextmanager
def record(name: str):
    if not _active:      # keep the interpreter hot path overhead-free
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_event(name, time.perf_counter() - t0, start=t0)


def is_active() -> bool:
    return _active


def reset_profiler():
    _events.clear()
    _timeline.clear()


def export_chrome_trace(path: str):
    """Write recorded host events as a Chrome tracing JSON (chrome://tracing
    / Perfetto), the host half of the reference's timeline.py:31 output.
    Device-side kernels live in the TensorBoard trace captured by
    profiler(trace_dir=...) — point Perfetto at both for the merged view."""
    import json
    events = [{"name": name, "ph": "X", "pid": 0, "tid": 0,
               "ts": start * 1e6, "dur": dur * 1e6,
               "cat": "host"}
              for name, start, dur in _timeline]
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return path


def start_profiler(state="All", trace_dir: Optional[str] = None):
    global _active
    _active = True
    from . import telemetry
    telemetry.counter(
        "profiler_sessions_total", "profiling sessions started",
        labels=("traced",)).labels(
            traced=str(bool(trace_dir)).lower()).inc()
    _hlo_suppliers.clear()
    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    _start_trace_dir[0] = trace_dir


_start_trace_dir = [None]
# id(compiled_fn) -> zero-arg callable returning the optimized HLO text;
# registered by the executor while a traced profile is active, consumed by
# the per-op device table at stop (paddle_tpu/xplane.py)
_hlo_suppliers: Dict[int, object] = {}


def wants_device_table() -> bool:
    """True while a traced (trace_dir) profiling session is active — the
    executor then registers its compiled blocks for HLO attribution."""
    return _active and _start_trace_dir[0] is not None


_MAX_HLO_SUPPLIERS = 4  # each supply() is a full AOT recompile at stop


def has_hlo_supplier(key: int) -> bool:
    # saturated registry counts as "has": with program caching off every
    # step builds a fresh compiled fn, and an unbounded registry would
    # both pin them all alive and recompile each one at stop_profiler
    return key in _hlo_suppliers or \
        len(_hlo_suppliers) >= _MAX_HLO_SUPPLIERS


def register_hlo_supplier(key: int, supplier):
    if len(_hlo_suppliers) < _MAX_HLO_SUPPLIERS:
        _hlo_suppliers.setdefault(key, supplier)


def stop_profiler(sorted_key=None, profile_path=None):
    global _active
    _active = False
    trace_dir = _start_trace_dir[0]
    if trace_dir:
        jax.profiler.stop_trace()
        _start_trace_dir[0] = None
    _print_table(sorted_key)
    if trace_dir:
        _print_device_table(trace_dir, sorted_key)


def _print_device_table(trace_dir, sorted_key=None):
    """Per-IR-op device-time attribution for the whole-block jit (VERDICT
    r4 #8; reference ParseEvents, platform/profiler.h:137-166): xplane
    per-instruction timings joined with each compiled module's
    metadata op_name (which carries the executor's pd.<op_type> named
    scope). Re-lowers each registered block from avals to read its
    optimized HLO — served from jax's compilation cache when warm."""
    from . import xplane

    mapping = {}
    for supplier in _hlo_suppliers.values():
        try:
            mapping.update(xplane.hlo_op_names(supplier()))
        except Exception as e:  # noqa: BLE001 - table is best-effort
            print(f"[device] (hlo attribution unavailable: {e})")
    _hlo_suppliers.clear()
    if not mapping:
        return
    try:
        instr_ps = xplane.aggregate_dir(trace_dir)
        agg = xplane.attribute(instr_ps, mapping)
    except Exception as e:  # noqa: BLE001 - truncated/foreign .xplane.pb
        print(f"[device] (trace unreadable: {type(e).__name__}: {e})")
        return
    if not agg:
        return
    rows = sorted(agg.items(), key=lambda kv: -kv[1])
    total = sum(agg.values())
    from . import telemetry
    for name, ps in rows:
        telemetry.counter(
            "device_op_seconds_total",
            "device time attributed to IR ops across traced sessions",
            labels=("op",)).labels(op=name).inc(ps / 1e12)
    print(f"{'Device op (jit)':40s} {'Total(ms)':>12s} {'Frac':>8s}")
    for name, ps in rows:
        print(f"[device] {name:31s} {ps / 1e9:12.4f} "
              f"{ps / total:8.1%}")


def _print_table(sorted_key=None):
    if not _events:
        return
    rows = []
    for name, times in _events.items():
        total = sum(times)
        rows.append((name, len(times), total, total / len(times),
                     min(times), max(times)))
    if sorted_key in ("total", None):
        rows.sort(key=lambda r: -r[2])
    elif sorted_key == "calls":
        rows.sort(key=lambda r: -r[1])
    elif sorted_key == "ave":
        rows.sort(key=lambda r: -r[3])
    print(f"{'Event':40s} {'Calls':>8s} {'Total(s)':>10s} {'Ave(s)':>10s} "
          f"{'Min(s)':>10s} {'Max(s)':>10s}")
    for name, calls, total, ave, mn, mx in rows:
        print(f"{name:40s} {calls:8d} {total:10.4f} {ave:10.4f} "
              f"{mn:10.4f} {mx:10.4f}")


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Source-compat alias: wraps an XLA trace around the block
    (reference profiler.py:33 drove nvprof)."""
    with profiler("All", trace_dir=output_file):
        yield


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             trace_dir: Optional[str] = None):
    start_profiler(state, trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
