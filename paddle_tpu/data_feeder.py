"""DataFeeder: minibatch rows -> feed dict of arrays/LoDTensors
(reference: python/paddle/fluid/data_feeder.py:25 DataToLoDTensorConverter,
:69 DataFeeder)."""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .executor import LoDTensor
from .framework.framework import Variable, default_main_program


class DataToLoDTensorConverter:
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = [d for d in shape]
        self.dtype = dtype
        self.data: List = []
        self.lod = [[0] for _ in range(lod_level)]

    def feed(self, data):
        """Accumulate one sample: level i of a nested sequence contributes
        its length to self.lod[i] as a cumulative offset (the reference's
        LoD convention), and the leaves land flat in self.data. Iterative
        level-order walk — each pass over `frontier` stamps one offset row
        and descends one nesting level."""
        if self.lod_level == 0:
            self.data.append(data)
            return
        frontier = [data]
        for offsets in self.lod:
            for seq in frontier:
                offsets.append(offsets[-1] + len(seq))
            frontier = [item for seq in frontier for item in seq]
        self.data.extend(frontier)

    def done(self):
        if self.lod_level == 0:
            shape = [len(self.data)] + [abs(d) for d in self.shape if d != -1] \
                if -1 in self.shape else [len(self.data)] + list(self.shape)
            arr = np.array(self.data, dtype=self.dtype)
            want = [len(self.data)] + [d for d in self.shape if d > 0]
            if list(arr.shape) != want and int(np.prod(arr.shape)) == int(
                    np.prod(want)):
                arr = arr.reshape(want)
            return arr
        flat = np.array(self.data, dtype=self.dtype)
        if flat.ndim == 1:
            flat = flat.reshape(-1, 1)
        t = LoDTensor(flat, self.lod)
        return t


class DataFeeder:
    def __init__(self, feed_list: Sequence, place, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        program = program or default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("feed_list entries must be Variables or names")
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            shape = list(each_var.shape or [])
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(shape)
        self.place = place

    def feed(self, iterable):
        import time

        from . import telemetry
        t0 = time.perf_counter()
        converters = []
        for lod_level, shape, dtype in zip(self.feed_lod_level,
                                           self.feed_shapes, self.feed_dtypes):
            batch_free = [d for d in shape if d != -1] if shape and \
                shape[0] == -1 else shape
            converters.append(DataToLoDTensorConverter(
                place=self.place, lod_level=lod_level, shape=batch_free,
                dtype=dtype))
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                f"sample has {len(each_sample)} fields, expected "
                f"{len(converters)}")
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        ret_dict = {}
        for each_name, each_converter in zip(self.feed_names, converters):
            ret_dict[each_name] = each_converter.done()
        dt = time.perf_counter() - t0
        telemetry.counter(
            "feed_conversion_seconds_total",
            "host seconds spent converting minibatches to feed arrays").inc(dt)
        telemetry.histogram(
            "feed_conversion_seconds",
            "per-batch feed conversion latency").observe(dt)
        return ret_dict

    def feed_window(self, minibatches):
        """Convert K minibatches and stack each feed name into ONE [K, ...]
        array — the host-side shape Executor.run_steps scans over. Dense
        feeds only: LoD feeds pad per-batch (pack_to_padded) and would need
        a per-step host repack, which is exactly what the fused window
        avoids — feed those per-step via run_steps(feed_window=[...])
        so the executor can fall back."""
        dicts = [self.feed(mb) for mb in minibatches]
        if not dicts:
            raise ValueError("feed_window needs at least one minibatch")
        window = {}
        for name in self.feed_names:
            vals = [d[name] for d in dicts]
            if any(isinstance(v, LoDTensor) and v.lod for v in vals):
                raise ValueError(
                    f"feed '{name}' carries LoD; window stacking requires "
                    f"dense batches (use per-step feeds instead)")
            window[name] = np.stack([np.asarray(v) for v in vals])
        return window
