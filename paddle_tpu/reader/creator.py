"""Reader creators (reference: python/paddle/v2/reader/creator.py —
np_array, text_file, recordio): factories that turn a data source into a
sample reader, composing with the decorator chain (shuffle/batch/...)."""

from __future__ import annotations

__all__ = ["np_array", "text_file", "recordio"]


def np_array(x):
    """Reader over the first axis of an ndarray (reference creator.py:22)."""
    import numpy as np

    arr = np.asarray(x)

    def reader():
        for row in arr:
            yield row

    return reader


def text_file(path):
    """Reader yielding stripped lines of a text file (reference
    creator.py:42)."""

    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100, decode=False):
    """Reader over recordio file(s): RAW record bytes, the reference
    contract (reference creator.py:60 yields f.read(), prefetching
    buf_size records); here the native chunk reader serves the stream
    through the buffered decorator. Files written by
    paddle_tpu.recordio.write_samples hold pickled samples — pass
    decode=True to get the original objects back."""
    if isinstance(paths, str):
        paths = paths.split(",")

    def raw():
        import pickle
        from ..recordio import RecordIOScanner
        for p in paths:
            with RecordIOScanner(p) as scanner:
                for rec in scanner:
                    yield pickle.loads(rec) if decode else rec

    from . import buffered
    return buffered(raw, buf_size)
