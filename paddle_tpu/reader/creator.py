"""Reader creators (reference: python/paddle/v2/reader/creator.py —
np_array, text_file, recordio): factories that turn a data source into a
sample reader, composing with the decorator chain (shuffle/batch/...)."""

from __future__ import annotations

__all__ = ["np_array", "text_file", "recordio"]


def np_array(x):
    """Reader over the first axis of an ndarray (reference creator.py:22)."""
    import numpy as np

    arr = np.asarray(x)

    def reader():
        for row in arr:
            yield row

    return reader


def text_file(path):
    """Reader yielding stripped lines of a text file (reference
    creator.py:42)."""

    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100, decode=False):
    """Reader over recordio file(s): RAW record bytes, the reference
    contract (reference creator.py:60 yields f.read()). Files written by
    paddle_tpu.recordio.write_samples hold pickled samples — pass
    decode=True to get the original objects back (delegates to
    recordio.read_samples, the one scan-and-unpickle implementation).

    buf_size is accepted for reference source compatibility but not
    applied here: the buffered decorator's prefetch thread would leak
    (parked on a full queue, scanner handle open) whenever a consumer
    abandons the stream early — compose `reader.buffered(r, n)`
    explicitly when prefetch is wanted and the stream is fully drained.
    A generator here means abandonment closes the scanner promptly
    (GeneratorExit unwinds the with-block)."""
    if isinstance(paths, str):
        paths = paths.split(",")

    def reader():
        from .. import recordio as recordio_mod
        for p in paths:
            if decode:
                yield from recordio_mod.read_samples(p)
            else:
                with recordio_mod.RecordIOScanner(p) as scanner:
                    yield from scanner

    return reader
