"""Device-side input pipeline: double-buffered host->HBM prefetch.

TPU-native replacement for the reference's decorated-reader chain
(reference: framework/reader.h:28-68 ReaderBase/DecoratedReader,
operators/reader/create_double_buffer_reader_op.cc — a background thread
that stages the next batch on the device while the current one computes;
operators/reader/create_batch_reader_op.cc, create_shuffle_reader_op.cc).

The reference implements each decorator as a C++ reader op chained inside
the program; here the chain is a host-side pipeline object the executor
pulls from. The part that matters for TPU throughput — overlapping the
host->HBM copy of batch N+1 with the compute of batch N — is kept: a
producer thread converts each batch and `jax.device_put`s it into HBM
ahead of consumption, bounded by a small queue (capacity 2 = classic
double buffering)."""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import numpy as np

__all__ = ["DoubleBufferedFeeder"]

_STOP = object()


class DoubleBufferedFeeder:
    """Wrap a batch reader into an iterator of device-resident feed dicts.

    reader: callable returning an iterable of batches (paddle reader
    convention). to_feed: batch -> {name: ndarray/LoDTensor} (e.g.
    DataFeeder.feed, or identity for dict readers). device: target
    jax.Device for the prefetch copies. capacity: queue depth (2 =
    double buffering, the reference's default). window_prefetch: how many
    STACKED next_window windows to build ahead (1 = the classic
    synchronous stack: only the per-batch producer overlaps; >1 moves
    the stack + device_put of up to that many windows onto a background
    thread, so window N+1's host work fully overlaps window N's
    compute)."""

    def __init__(self, reader: Callable[[], Iterable], to_feed=None,
                 device=None, capacity: int = 2, window_prefetch: int = 1):
        self.reader = reader
        self.to_feed = to_feed or (lambda b: b)
        self.device = device
        self.capacity = capacity
        self.window_prefetch = max(1, int(window_prefetch))
        self._thread: Optional[threading.Thread] = None
        self._queue: Optional[queue.Queue] = None
        self._stop = threading.Event()
        # persistent consumer generator for next_window: windows pull from
        # ONE pass rather than restarting the reader per window
        self._consumer = None
        # window-builder thread state (window_prefetch > 1)
        self._wthread: Optional[threading.Thread] = None
        self._wqueue: Optional[queue.Queue] = None
        self._wstop = threading.Event()
        self._wkey = None
        # consumer-side trace context the builder thread adopts
        # (tracing.capture_context handle; None when no span is live)
        self._wctx = None

    def _produce(self):
        try:
            for batch in self.reader():
                if self._stop.is_set():
                    return
                feed = self.to_feed(batch)
                if self.device is not None:
                    feed = {
                        k: (jax.device_put(v, self.device)
                            if isinstance(v, (np.ndarray, np.generic))
                            else v)
                        for k, v in feed.items()}
                self._queue.put(feed)
        except BaseException as e:          # surface in the consumer
            self._queue.put(e)
            return
        self._queue.put(_STOP)

    def __iter__(self):
        import time

        from .. import telemetry
        stall = telemetry.histogram(
            "input_stall_seconds",
            "consumer wait on the prefetch queue (0 when the producer "
            "keeps ahead — the pipeline's headroom signal)")
        batches = telemetry.counter(
            "input_batches_total", "batches delivered by prefetch feeders")
        self.reset()
        while True:
            t0 = time.perf_counter()
            item = self._queue.get()
            stall.observe(time.perf_counter() - t0)
            if item is _STOP:
                self._thread.join()
                self._thread = None
                return
            if isinstance(item, BaseException):
                self._thread.join()
                self._thread = None
                raise item
            batches.inc()
            yield item

    def next_window(self, k: int, device=None, sparse_slots=None
                    ) -> Dict[str, Any]:
        """Pull the next k batches and stack each feed name into ONE
        [k, ...] array, `jax.device_put` to `device` — the input half of the
        fused multi-step loop (Executor.run_steps). The producer thread
        keeps staging batch k+1, k+2, ... into the bounded queue while the
        device computes the PREVIOUS window, so the host-side stack +
        host->HBM copy of window N+1 overlaps window N's compute.

        For window mode construct the feeder with device=None and pass the
        target device here: one stacked transfer beats k small ones, and
        per-batch device_put in the producer would force the stack back
        through the host. Raises StopIteration at end of pass; a short
        remainder (< k batches, XLA would need a fresh window shape) is
        dropped and counted in input_window_dropped_batches_total.

        With window_prefetch > 1 the stack + device_put happens on a
        background window-builder thread holding up to window_prefetch
        ready windows in a bounded queue — this call just dequeues.

        sparse_slots=[names]: the emb_cache prefetch hook. The return
        becomes `(window, {name: unique-id union over the window})` for
        each listed feed name present, the named slots stay host-side
        numpy (the cache remaps them to slot indices before they ever
        reach the device), and the dedup runs on the builder thread
        under window_prefetch > 1. Batch accounting (dedup, dropped
        remainder) is identical either way — test-pinned."""
        from .. import telemetry
        sparse = tuple(sparse_slots) if sparse_slots else None
        if self.window_prefetch > 1:
            return self._next_window_prefetched(k, device, sparse)
        if self._consumer is None:
            self._consumer = iter(self)
        feeds: List[Dict[str, Any]] = []
        try:
            while len(feeds) < k:
                feeds.append(next(self._consumer))
        except StopIteration:
            self._consumer = None
            self._count_dropped(len(feeds))
            raise StopIteration from None
        from .. import tracing
        with tracing.span("input_window_build", batches=k):
            window = self._stack_window(feeds, device, sparse)
        telemetry.counter(
            "input_windows_total",
            "stacked k-step windows delivered by prefetch feeders").inc()
        return window

    @staticmethod
    def _stack_window(feeds: List[Dict[str, Any]], device,
                      sparse_slots=None):
        names = set(feeds[0])
        if any(set(f) != names for f in feeds[1:]):
            raise ValueError("window batches must share the same feed names")
        window = {n: np.stack([np.asarray(f[n]) for f in feeds])
                  for n in sorted(names)}
        uniq = None
        if sparse_slots is not None:
            uniq = {n: np.unique(window[n]) for n in sparse_slots
                    if n in window}
        if device is not None:
            skip = set(uniq or ())
            window = {n: (v if n in skip else jax.device_put(v, device))
                      for n, v in window.items()}
        return (window, uniq) if sparse_slots is not None else window

    @staticmethod
    def _count_dropped(n: int):
        if n:
            from .. import telemetry
            telemetry.counter(
                "input_window_dropped_batches_total",
                "end-of-pass remainder batches shorter than the "
                "window").inc(n)

    def _produce_windows(self, k: int, device, wq, wstop,
                         sparse_slots=None):
        """Window-builder thread body: pull k batches at a time from the
        batch pipeline, stack + device_put (+ sparse-slot dedup), enqueue
        the ready window. `wq`/`wstop` are locals (not self attributes)
        so a builder abandoned by a (k, device) change can neither
        pollute its replacement's queue nor block forever on its own."""
        def _put(item):
            while not wstop.is_set():
                try:
                    wq.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        from .. import tracing
        try:
            it = iter(self)
            while not wstop.is_set():
                feeds: List[Dict[str, Any]] = []
                try:
                    while len(feeds) < k:
                        feeds.append(next(it))
                except StopIteration:
                    # the drop count rides the stop marker so the CONSUMER
                    # books it at the pull that raises StopIteration —
                    # counting here would race the caller's reads of the
                    # dropped-batches counter (the builder runs ahead)
                    _put((_STOP, len(feeds)))
                    return
                # adopt the consumer thread's captured trace context so
                # the build span is a child of the owning step trace, not
                # an orphan root minted on this thread
                with tracing.adopt(self._wctx):
                    with tracing.span("input_window_build", batches=k):
                        window = self._stack_window(feeds, device,
                                                    sparse_slots)
                if not _put(window):
                    return
        except BaseException as e:        # surface in the consumer
            _put(e)

    def _next_window_prefetched(self, k: int, device, sparse_slots=None):
        from .. import telemetry
        from .. import tracing
        # refreshed every pull: the builder parents its next build span
        # under whatever step trace is live on the consumer right now
        self._wctx = tracing.capture_context()
        key = (k, device, sparse_slots)
        if self._wthread is None or self._wkey != key:
            self._stop_windows()
            self._wkey = key
            self._wstop = threading.Event()
            self._wqueue = queue.Queue(maxsize=self.window_prefetch)
            self._wthread = threading.Thread(
                target=self._produce_windows,
                args=(k, device, self._wqueue, self._wstop, sparse_slots),
                daemon=True, name="pd-feeder-window")
            self._wthread.start()
        item = self._wqueue.get()
        if type(item) is tuple and len(item) == 2 and item[0] is _STOP:
            self._count_dropped(item[1])
            self._wthread.join()
            self._wthread = None
            self._wkey = None
            raise StopIteration
        if isinstance(item, BaseException):
            self._wthread.join()
            self._wthread = None
            self._wkey = None
            raise item
        telemetry.counter(
            "input_windows_total",
            "stacked k-step windows delivered by prefetch feeders").inc()
        return item

    def _stop_windows(self):
        # the builder itself resets the nested batch pipeline through
        # iter(self) -> reset() -> stop(); never self-join from there
        if self._wthread is None or \
                self._wthread is threading.current_thread():
            return
        self._wstop.set()
        try:                      # unblock a builder stuck on batch get()
            self._queue.put_nowait(_STOP)
        except (queue.Full, AttributeError):
            pass
        try:                      # unblock a builder stuck on window put()
            while True:
                self._wqueue.get_nowait()
        except queue.Empty:
            pass
        self._wthread.join(timeout=5)
        self._wthread = None
        self._wkey = None

    def reset(self):
        # NOTE: does not touch _consumer — __iter__'s generator body calls
        # reset() on its first next(), which runs AFTER next_window stored
        # the generator; next_window clears it itself at end of pass
        self.stop()
        self._stop.clear()
        self._queue = queue.Queue(maxsize=self.capacity)
        self._thread = threading.Thread(target=self._produce, daemon=True,
                                        name="pd-feeder-batch")
        self._thread.start()

    def stop(self):
        self._stop_windows()
        if self._thread is not None:
            self._stop.set()
            try:                      # unblock a producer stuck on put()
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5)
            self._thread = None
