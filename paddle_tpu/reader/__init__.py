"""Reader decorators (reference: python/paddle/v2/reader/decorator.py:29-337
map_readers/shuffle/chain/compose/buffered/firstn/xmap_readers).

A reader is a zero-arg callable returning an iterable of samples. Decorators
wrap readers into new readers — the host-side input pipeline that feeds the
device double-buffer."""

from __future__ import annotations

import itertools
import queue
import random
import threading
from typing import Callable, Iterable

from . import creator  # noqa: E402  (reference v2/reader/creator.py)

__all__ = [
    "map_readers", "buffered", "compose", "chain", "shuffle", "firstn",
    "xmap_readers", "ComposeNotAligned", "creator",
]


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    """Apply func to the items of each reader, zipped (reference :29)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer (reference :59)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers (reference :90)."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, check_alignment=True):
    """Zip readers into tuple samples (reference :121)."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            # reference semantics: raise if readers end at different times
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            # discard trailing unaligned outputs
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Prefetch up to `size` samples on a worker thread (reference :165) —
    overlaps host data prep with device steps."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q: queue.Queue = queue.Queue(maxsize=size)
        err = []

        def fill():
            try:
                for d in r:
                    q.put(d)
            except BaseException as e:  # surfaced in the consumer
                err.append(e)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True,
                             name="pd-reader-buffered")
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e
        if err:
            raise err[0]

    return data_reader


def firstn(reader, n):
    """First n samples (reference :213)."""

    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return data_reader


class _XmapError:
    """Mapper exception forwarded to the consuming thread."""

    def __init__(self, exc):
        self.exc = exc


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads (reference :237),
    scheduled on a framework ThreadPool (reference threadpool.h — the
    host-side F16 role) sized for this reader's feed + workers so a
    shared global pool can never deadlock the bounded queues. Mapper
    exceptions RE-RAISE in the consumer (never a silent stall), and
    closing/abandoning the returned reader tears the pool down — every
    queue op is abort-aware, so no thread outlives its reader."""

    end = object()

    def data_reader():
        from ..threadpool import ThreadPool

        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        abort = threading.Event()

        def _put(q, item):
            while not abort.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def _get(q):
            while not abort.is_set():
                try:
                    return q.get(timeout=0.1)
                except queue.Empty:
                    continue
            return end

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    if not _put(in_q, (i, sample)):
                        return
            except Exception as e:  # noqa: BLE001 - forwarded like mapper
                # a dying SOURCE must also fail loudly in the consumer:
                # the error item re-raises there, whose teardown (abort)
                # then releases the workers still blocked on in_q
                _put(out_q, (-1, _XmapError(e)))
                return
            for _ in range(process_num):
                if not _put(in_q, end):
                    return

        def work():
            while True:
                item = _get(in_q)
                if item is end:
                    _put(out_q, end)
                    return
                i, sample = item
                try:
                    mapped = mapper(sample)
                except Exception as e:  # noqa: BLE001 - forwarded
                    _put(out_q, (i, _XmapError(e)))
                    _put(out_q, end)
                    return
                if not _put(out_q, (i, mapped)):
                    return

        pool = ThreadPool(num_threads=process_num + 1)
        pool.run(feed)
        for _ in range(process_num):
            pool.run(work)

        def _unwrap(mapped):
            if isinstance(mapped, _XmapError):
                raise mapped.exc
            return mapped

        finished = 0
        try:
            if order:
                pending = {}
                want = 0
                while finished < process_num:
                    item = out_q.get()
                    if item is end:
                        finished += 1
                        continue
                    i, mapped = item
                    pending[i] = _unwrap(mapped)
                    while want in pending:
                        yield pending.pop(want)
                        want += 1
                for i in sorted(pending):
                    yield pending[i]
            else:
                while finished < process_num:
                    item = out_q.get()
                    if item is end:
                        finished += 1
                        continue
                    yield _unwrap(item[1])
        finally:
            # normal exhaustion, consumer error, or abandoned generator
            # (GeneratorExit): stop feed/workers and release the pool
            abort.set()
            pool.shutdown()

    return data_reader
